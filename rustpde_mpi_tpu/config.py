"""Global configuration for rustpde_mpi_tpu.

The reference framework (rustpde-mpi, /root/reference/src/lib.rs) computes in
f64 everywhere.  On TPU, f64 is emulated and slow, so precision is a run-time
choice here:

* ``RUSTPDE_X64=1`` (default) enables ``jax_enable_x64`` at import time and all
  operators/states default to float64 — required for the 1e-6 Nusselt-parity
  gate against the CPU reference.
* ``RUSTPDE_X64=0`` leaves JAX in f32 mode for maximum TPU throughput; solver
  setup (eigendecompositions, LU factorizations) still happens on the host in
  numpy f64 and is rounded once at the end.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field


# -- Environment knob registry -------------------------------------------------
#
# Every ``RUSTPDE_*`` environment knob in the repo is declared HERE, once,
# with its default and one line of documentation.  Library modules read
# knobs through :func:`env_get` (which refuses unregistered names), the
# README "Environment knobs" table mirrors this registry, and
# tests/test_lint.py diffs all three against a grep of the source tree —
# so a new knob cannot ship unregistered or undocumented, and a typo'd
# read dies loudly instead of silently returning the default forever.
# Driver-side code (bench.py, scripts/, tests/, examples/) may keep raw
# ``os.environ`` reads, but its knob NAMES must still be registered
# (scope "bench"/"test"); tools/lint rule RPD006 enforces the read-path
# rule inside the package (utils/faults.py stays raw by design: it must
# not import this jax-loading module from inside the two-phase commit
# window).


@dataclass(frozen=True)
class EnvKnob:
    """One registered environment knob: ``default`` is documentation of the
    effective default (None = unset means off/auto), ``scope`` names the
    consuming layer (``lib`` | ``bench`` | ``test``)."""

    name: str
    default: str | None
    doc: str
    scope: str = "lib"


_ENV_KNOBS: dict[str, EnvKnob] = {}


class UnregisteredKnobError(KeyError):
    """A ``RUSTPDE_*`` environment variable was read through
    :func:`env_get` without being declared in the knob registry."""


def register_knob(name: str, default: str | None, doc: str, scope: str = "lib") -> None:
    _ENV_KNOBS[name] = EnvKnob(name=name, default=default, doc=doc, scope=scope)


def env_knobs() -> dict[str, EnvKnob]:
    """The full knob registry (name -> :class:`EnvKnob`), a copy."""
    return dict(_ENV_KNOBS)


def env_get(name: str, default: str | None = None) -> str | None:
    """``os.environ.get`` with a registration gate: reading an unregistered
    ``RUSTPDE_*`` name raises :class:`UnregisteredKnobError` (a typo'd knob
    must die loudly, not silently read its default forever).  The
    ``default`` argument keeps call-site semantics — the registry default
    is documentation, not a fallback."""
    if name.startswith("RUSTPDE_") and name not in _ENV_KNOBS:
        raise UnregisteredKnobError(
            f"environment knob {name!r} is not registered in "
            "config.env_knobs() — declare it with config.register_knob"
        )
    return os.environ.get(name, default)


# precision / numerics
register_knob("RUSTPDE_X64", "1", "f64 master switch (0 = f32 throughput mode)")
register_knob("RUSTPDE_MATMUL_PRECISION", "highest",
              "global jax matmul precision (high = 3-pass bf16 on TPU)")
register_knob("RUSTPDE_FWD_PRECISION", "highest",
              "dealiased convection forward-transform matmul precision")
register_knob("RUSTPDE_SYNTH_PRECISION", "high",
              "synthesis (spectral->physical) matmul precision")
register_knob("RUSTPDE_SOLVE_PRECISION", None,
              "scoped matmul precision around the four implicit solves")
register_knob("RUSTPDE_F64_HYBRID", None,
              "1 = f32 convection transforms feeding f64 solves under X64")
# operator / kernel selection
register_knob("RUSTPDE_FORCE_TPU_PATH", None,
              "1 = exercise the TPU execution paths on CPU CI")
register_knob("RUSTPDE_SEP", "auto", "separable y-operator application mode")
register_knob("RUSTPDE_FOLDED", "1", "folded (kept-row) operator storage")
register_knob("RUSTPDE_FOURSTEP", "auto", "four-step factored transform mode")
register_knob("RUSTPDE_FOURSTEP_MIN", "2048", "four-step min size (dft)")
register_knob("RUSTPDE_FOURSTEP_MIN_C2C", "1024", "four-step min size (c2c)")
register_knob("RUSTPDE_FOURSTEP_MIN_DCT", "8192", "four-step min size (dct)")
register_knob("RUSTPDE_FOURSTEP_N1", None, "forced four-step N1 split factor")
register_knob("RUSTPDE_FAST_DERIV", "auto", "banded fast-derivative mode")
register_knob("RUSTPDE_FAST_DERIV_MIN", "2048", "fast-derivative min size")
register_knob("RUSTPDE_CONV_KERNEL", "dense",
              "convection chain: dense per-GEMM chain | pallas fused kernel")
register_knob("RUSTPDE_STEP_KERNEL", "dense",
              "implicit-solve stages: dense solver chain | pallas fused megakernel")
register_knob("RUSTPDE_PALLAS_CONV_BLOCK", "256",
              "pallas conv kernel physical-x tile")
register_knob("RUSTPDE_PALLAS_CONV_BLOCK_K", "512",
              "pallas conv kernel spectral-y contraction tile")
register_knob("RUSTPDE_TRANSPOSE", "alltoall",
              "pencil transpose collective: alltoall | ring")
register_knob("RUSTPDE_RING_IMPL", "pallas",
              "ring transpose implementation: pallas remote-copy | ppermute")
register_knob("RUSTPDE_SPLIT_SEP_FALLBACK", "manual",
              "split-sep periodic under a mesh: manual shard_map | eager triage")
register_knob("RUSTPDE_FORCE_FUSED_GSPMD", None,
              "1 = pin the known-miscompiling fused GSPMD split-sep path")
# physics observability (models/stats.py in-scan statistics engine)
register_knob("RUSTPDE_STATS", None,
              "1 = arm the in-scan physics-stats engine on from_config DNS models")
register_knob("RUSTPDE_STATS_STRIDE", "16",
              "in-scan stats sampling stride (steps between samples)")
register_knob("RUSTPDE_STATS_TAIL_WARN", "1e-3",
              "spectral-tail energy fraction above which resolution_warning fires")
register_knob("RUSTPDE_STATS_BUDGET_WARN", "0.5",
              "Nu budget-closure residual above which budget_drift fires")
# end-to-end integrity (integrity/: on-device state digests, shadow
# re-execution audits, device quarantine)
register_knob("RUSTPDE_INTEGRITY", None,
              "1 = arm on-device state digests + shadow re-execution audits "
              "on from_config DNS models")
register_knob("RUSTPDE_INTEGRITY_CADENCE", "8",
              "committed chunks between shadow re-execution audits (digests "
              "stream every chunk; 0 = digests only, never audit)")
register_knob("RUSTPDE_VOTE_RATE", "0",
              "fleet proxy cross-replica voting: fraction of requests "
              "double-assigned and digest-compared at completion (0..1)")
# telemetry
register_knob("RUSTPDE_TELEMETRY", "1", "telemetry master switch")
register_knob("RUSTPDE_TRACE", "1", "flight-recorder span tracing switch")
register_knob("RUSTPDE_TRACE_EVENTS", "4096", "flight-recorder ring capacity")
register_knob("RUSTPDE_METRICS_DUMP_S", "60", "metrics.jsonl dump cadence")
register_knob("RUSTPDE_REQTRACE", "1",
              "per-request distributed tracing switch (trace ids still mint)")
register_knob("RUSTPDE_REQTRACE_EVENTS", "16384",
              "request-trace per-process event capacity per campaign")
register_knob("RUSTPDE_PROFILE_MAX_S", "60",
              "cap on one POST /profile (or perf_degraded auto) capture")
register_knob("RUSTPDE_TREND_BAND", "0.3",
              "bench_trend noise band: regression when below (1-band)*best",
              "bench")
# resilience / watchdogs / fault injection
register_knob("RUSTPDE_DISPATCH_TIMEOUT_S", None, "device-dispatch hang watchdog")
register_knob("RUSTPDE_SYNC_TIMEOUT_S", "0",
              "barrier/broadcast watchdog (0 = off): peer death -> DispatchHang")
register_knob("RUSTPDE_IO_TIMEOUT_S", None, "async checkpoint writer watchdog")
register_knob("RUSTPDE_FAULT", None,
              "fault injection <nan|spike|kill|slow|bitflip>@<step>"
              "[:host<p>|:member<k>|:gang<g>[member<m>]]")
register_knob("RUSTPDE_GANG_SYNC_TIMEOUT_S", "0",
              "gang-barrier watchdog (0 = off): a dead gang member trips "
              "this deadline and surfaces as typed GangMemberLost instead "
              "of a wedged collective")
register_knob("RUSTPDE_SHARD_CRASH", None,
              "two-phase commit window kill <after_shard|before_manifest>@<step>[:host<p>]")
register_knob("RUSTPDE_SPIKE_FACTOR", None, "spike fault velocity scale override")
# fleet layer (serve/fleet/: replicated front door + queue-level leases)
register_knob("RUSTPDE_LEASE_TTL_S", "15",
              "bucket-lease heartbeat TTL: a replica silent past this is "
              "broken by survivors and its requests re-claimed")
register_knob("RUSTPDE_FLEET_REPLICA_ID", None,
              "stable replica identity for lease/heartbeat files "
              "(unset = <hostname>-<pid>)")
register_knob("RUSTPDE_FLEET_HEARTBEAT_S", None,
              "lease/replica heartbeat cadence (unset = lease_ttl/3)")
register_knob("RUSTPDE_FLEET_QUOTA", None,
              "default per-tenant admission quota (queued+running; "
              "unset = unlimited)")
register_knob("RUSTPDE_PREEMPT_NOTICE_S", None,
              "preemption-notice window: SIGTERM on a fleet replica parks "
              "every running slot durably + releases leases within this "
              "many seconds (unset = full graceful drain)")
register_knob("RUSTPDE_PROXY_TOKENS", None,
              "comma-separated bearer-token allowlist for proxy mutating "
              "endpoints (unset = open admission)")
# collective-sequence sanitizer (parallel/sanitizer.py)
register_knob("RUSTPDE_SANITIZE", "0",
              "1 = record every multihost collective + cadenced cross-host "
              "sequence verification (CollectiveDesyncError on divergence)")
register_knob("RUSTPDE_SANITIZE_CADENCE", "32",
              "collectives between cross-host sequence verifications")
register_knob("RUSTPDE_SANITIZE_RING", "256",
              "sanitizer per-host ring capacity (records kept for diagnosis)")
register_knob("RUSTPDE_SANITIZE_INJECT", None,
              "desync injection skip_broadcast@<n>[:host<p>] (tests only)")
# persistent compile cache (cold-start elimination: serialized XLA
# executables survive process death, so restarts / incarnations / elastic
# re-plans reload instead of recompiling)
register_knob("RUSTPDE_COMPILE_CACHE", "1",
              "0 = do NOT arm the persistent JAX compilation cache in "
              "long-lived entry points (serve/replica/resilient sessions)")
register_knob("RUSTPDE_COMPILE_CACHE_DIR", None,
              "persistent compile cache root (default <repo>/.jax_cache; "
              "exported as JAX_COMPILATION_CACHE_DIR so children inherit)")
# bench drivers (bench.py — raw reads allowed, names registered)
register_knob("RUSTPDE_BENCH_CONFIGS", None, "comma list of bench configs", "bench")
register_knob("RUSTPDE_BENCH_STEPS", None, "bench step-count override", "bench")
register_knob("RUSTPDE_BENCH_BUDGET_S", None, "bench wall budget", "bench")
register_knob("RUSTPDE_BENCH_SLACK_S", None, "bench budget slack", "bench")
register_knob("RUSTPDE_BENCH_CHILD", None, "internal: marks a bench child", "bench")
register_knob("RUSTPDE_BENCH_STARVE_LIMIT", "3",
              "consecutive budget-starved skips before a config FAILS", "bench")
register_knob("RUSTPDE_BENCH_PROBE_TIMEOUT_S", None, "device probe timeout", "bench")
register_knob("RUSTPDE_BENCH_ALLOW_CPU", None, "1 = let bench run on CPU", "bench")
register_knob("RUSTPDE_BENCH_SHARDED_N", "130",
              "shardedio129 grid size override", "bench")
register_knob("RUSTPDE_SERVE_BENCH_REQUESTS", None,
              "serve129 soak request count", "bench")
register_knob("RUSTPDE_SERVE_MP_REQUESTS", "4",
              "serve129 2-proc leg request count", "bench")
register_knob("RUSTPDE_FLEET_BENCH_REQUESTS", "10",
              "serve129 fleet leg request count (proxy + 2 replicas)", "bench")
register_knob("RUSTPDE_AUTOSCALE_BENCH_REQUESTS", "6",
              "autoscale129 chaos leg request count (autoscaled fleet under "
              "Poisson preemptions)", "bench")
register_knob("RUSTPDE_GANG_BENCH_REQUESTS", "2",
              "serve_submesh129 gang-sharded request count (the co-resident "
              "vmapped count rides along, min 2)", "bench")
# test harness (tests/ — raw reads allowed, names registered)
register_knob("RUSTPDE_SLOW", None, "1 = run the slow test tier", "test")
register_knob("RUSTPDE_TEST_BUDGET_S", "45", "per-test wall budget (fast tier)", "test")
register_knob("RUSTPDE_TEST_TRACEBACK_S", None,
              "faulthandler dump_traceback_later arming", "test")
register_knob("RUSTPDE_MP_BLOCKING_IO", None,
              "1 = pin synchronous shard writes in mp workers", "test")
register_knob("RUSTPDE_MP_SERVE_REQUESTS", "5",
              "mp_worker serve_campaign request count", "test")
register_knob("RUSTPDE_MP_SERVE_SLOTS", "2",
              "mp_worker serve_campaign slot count", "test")
register_knob("RUSTPDE_MP_GANG_REQUESTS", "2",
              "mp_worker gang_serve sharded (gang-scheduled) request count", "test")
register_knob("RUSTPDE_MP_VMAP_REQUESTS", "3",
              "mp_worker gang_serve vmapped co-resident request count", "test")
register_knob("RUSTPDE_SERVE_SOAK_REQUESTS", None,
              "serve chaos soak request count", "test")


import jax
import numpy as np

X64: bool = env_get("RUSTPDE_X64", "1") != "0"

if X64:
    jax.config.update("jax_enable_x64", True)

# Spectral transforms/solves are precision-critical: TPU f32 matmuls default
# to bf16 MXU passes (~1e-2 relative error), which destroys spectral accuracy.
# "highest" (default) keeps true f32 (or f64 under x64) accumulation via
# 6-pass bf16; RUSTPDE_MATMUL_PRECISION=high selects the 3-pass variant —
# ~1.6x faster steps on the MXU-bound path, measured Nu drift at the 129^2
# parity config within the f32 noise floor (see BASELINE.md).
MATMUL_PRECISION = env_get("RUSTPDE_MATMUL_PRECISION", "highest")
jax.config.update("jax_default_matmul_precision", MATMUL_PRECISION)


def enable_compilation_cache(path: str | None = None) -> str:
    """Enable JAX's persistent compilation cache (works through the axon
    relay: measured 39 s -> 9 s for the 1025^2 step compile, 67 s -> 10 s for
    model build).  Call before the first jit dispatch; idempotent.

    The env vars are also set so child processes (the f64 bench subprocess)
    inherit the cache."""
    if path is None:
        path = os.environ.get(
            "JAX_COMPILATION_CACHE_DIR",
            os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".jax_cache"),
        )
    os.environ["JAX_COMPILATION_CACHE_DIR"] = path
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update(
        "jax_persistent_cache_min_entry_size_bytes",
        int(os.environ["JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES"]),
    )
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs",
        float(os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"]),
    )
    return path


_cache_armed: str | None = None


def ensure_compile_cache() -> str | None:
    """Idempotently arm the persistent compile cache in a long-lived entry
    point (``SimServer.serve``, ``replica_main``, ``ResilientRunner.session``,
    the examples drivers).  Honors the registered knobs:

    * ``RUSTPDE_COMPILE_CACHE=0`` disables arming entirely (returns None —
      byte-identical to the pre-cache behavior),
    * ``RUSTPDE_COMPILE_CACHE_DIR`` overrides the cache root (else
      ``JAX_COMPILATION_CACHE_DIR`` / ``<repo>/.jax_cache`` as
      :func:`enable_compilation_cache` resolves it).

    Returns the cache path when armed.  The env vars are exported, so any
    child a launcher spawns after this call boots warm against the same
    serialized executables."""
    global _cache_armed
    if env_get("RUSTPDE_COMPILE_CACHE", "1") == "0":
        return None
    if _cache_armed is not None:
        return _cache_armed
    _cache_armed = enable_compilation_cache(env_get("RUSTPDE_COMPILE_CACHE_DIR"))
    return _cache_armed


def compile_cache_env() -> dict:
    """Env-var seed for spawned replicas: the cache arming vars a child needs
    to boot against the same persistent cache (empty when the cache is off or
    not yet armed — the child then decides for itself)."""
    out = {}
    for name in (
        "JAX_COMPILATION_CACHE_DIR",
        "JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES",
        "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
        "RUSTPDE_COMPILE_CACHE",
        "RUSTPDE_COMPILE_CACHE_DIR",
    ):
        val = os.environ.get(name)
        if val is not None:
            out[name] = val
    return out


def host_cache_dir() -> str:
    """Root for host-side factorization caches (modal eigs, dense inverses):
    a ``host/`` subdir of the XLA compilation cache root, honoring
    JAX_COMPILATION_CACHE_DIR like enable_compilation_cache does."""
    root = os.environ.get(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".jax_cache"),
    )
    return os.path.join(root, "host")


def host_cache_store(path: str, save_fn) -> None:
    """Best-effort atomic publish of a host cache entry: ``save_fn(tmp)``
    writes the temp file (suffix chosen by the caller), then os.replace."""
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.{os.getpid()}.tmp{os.path.splitext(path)[1]}"
        save_fn(tmp)
        os.replace(tmp, path)
    except OSError:
        pass


def real_dtype():
    """Default real dtype for device arrays."""
    return np.float64 if X64 else np.float32


def complex_dtype():
    """Default complex dtype for device arrays."""
    return np.complex128 if X64 else np.complex64


def default_device_kind() -> str:
    return jax.devices()[0].platform


def is_tpu_like() -> bool:
    """True on TPU (including the 'axon' tunnel platform).

    ``RUSTPDE_FORCE_TPU_PATH=1`` forces True so CI (which runs on CPU,
    tests/conftest.py) can exercise the execution paths the real TPU uses:
    matmul transforms, dense ADI solves, fast-diagonalisation Poisson."""
    if env_get("RUSTPDE_FORCE_TPU_PATH") == "1":
        return True
    return default_device_kind() not in ("cpu", "gpu", "cuda", "rocm")


def supports_complex() -> bool:
    """The axon TPU backend implements no complex dtypes (and therefore no
    FFT); spectral pipelines there must run real-valued matmul transforms,
    with Fourier axes in a split re/im representation."""
    return not is_tpu_like()


@dataclass
class StabilityConfig:
    """Knobs for the proactive stability governor
    (:class:`~rustpde_mpi_tpu.utils.governor.StabilityGovernor` + the
    on-device sentinels compiled into the scanned step when a model's
    ``set_stability`` is called).

    * ``target_cfl`` — the Courant number the dt controller drives toward,
    * ``max_cfl`` — the hard on-device ceiling: a chunk whose per-step CFL
      exceeds it early-exits the scan with a ``pre_divergence`` status
      *before* NaNs propagate (recovered by a cheap in-memory rollback of
      just that chunk),
    * ``ladder_ratio`` — geometric spacing of the dt ladder the controller
      quantizes to (the dt-baked solver factorizations are cached per rung,
      so the re-jit/refactorization count over a long run is bounded by the
      ladder size),
    * ``dt_min``/``dt_max`` — ladder bounds (None: ``dt_max`` anchors at the
      run's initial dt, ``dt_min`` at ``dt_max * ladder_ratio**-10``),
    * ``grow_after`` — healthy chunks at a rung before the governor climbs
      back up the ladder (the regrowth the reactive backoff lacks),
    * ``shrink_cfl`` — proactive shrink threshold (None:
      ``0.85 * max_cfl``): a chunk whose max CFL exceeds it steps the ladder
      down *without* any rollback,
    * ``member_pin_patience`` — consecutive pre-divergence catches pinned on
      the same ensemble member before that member is declared dead and
      handed to the ``respawn_dead`` machinery."""

    target_cfl: float = 0.5
    max_cfl: float = 1.0
    ladder_ratio: float = 2.0
    dt_min: float | None = None
    dt_max: float | None = None
    grow_after: int = 4
    shrink_cfl: float | None = None
    member_pin_patience: int = 3


@dataclass
class StatsConfig:
    """Knobs for the in-scan physics-statistics engine
    (:class:`~rustpde_mpi_tpu.models.stats.StatsEngine`, armed via a DNS
    model's ``set_stats``): running spectral/profile/budget accumulators
    updated ON DEVICE inside the scanned step chunk, vmapped per ensemble
    member, carried through checkpoints bit-exactly.

    * ``stride`` — steps between samples (None: ``RUSTPDE_STATS_STRIDE``,
      default 16).  The sample cost is a handful of extra syntheses, so the
      amortized overhead scales as ~1/stride (the bench gate holds it ≤5%),
    * ``tail_warn`` — spectral-tail energy fraction (top third of the
      ortho spectrum, per field/axis) above which the runner journals a
      typed ``resolution_warning`` (None: ``RUSTPDE_STATS_TAIL_WARN``),
    * ``budget_warn`` — Nu budget-closure residual (plate-flux Nu vs the
      exact-relation ``1 + <uy*T> * 2*sy/ka``) above which the runner
      journals a typed ``budget_drift`` (None:
      ``RUSTPDE_STATS_BUDGET_WARN``).

    The hard contract (CI- and bench-gated like the sentinel/telemetry
    layers): the accumulators READ the state and never feed back — the
    state trajectory is bit-identical stats-on vs stats-off."""

    stride: int | None = None
    tail_warn: float | None = None
    budget_warn: float | None = None


@dataclass
class IntegrityConfig:
    """Knobs for the end-to-end integrity layer (``integrity/``, armed via
    a DNS model's ``set_integrity``): an on-device state digest (bitcast
    XOR/add fold, see :func:`~rustpde_mpi_tpu.integrity.digest_tree`)
    streamed with the observables futures after every committed chunk, plus
    sampled shadow re-execution audits in the resilient runner.

    * ``cadence`` — committed chunks between audits (None:
      ``RUSTPDE_INTEGRITY_CADENCE``, default 8; 0 = stream digests but
      never audit).  An audit replays the just-completed chunk from the
      retained chunk-start copy and compares digests — deterministic XLA
      means bit-equal or corrupted,
    * ``strikes`` — audit mismatches charged to one device before the
      quarantine ledger journals ``device_quarantined`` and the serve
      scheduler re-carves sub-meshes around it,
    * ``strike_ttl_s`` — ledger strike expiry window: strikes older than
      this no longer count toward the threshold (transient upsets decay,
      sticky-bad silicon accumulates).

    The hard contract (bench-gated like the stats engine): the digest READS
    the state and never feeds back — the trajectory is bit-identical
    integrity-on vs integrity-off, overhead ≤2%."""

    cadence: int | None = None
    strikes: int = 2
    strike_ttl_s: float = 3600.0

    def resolved_cadence(self) -> int:
        if self.cadence is not None:
            return int(self.cadence)
        return int(env_get("RUSTPDE_INTEGRITY_CADENCE", "8") or 8)


@dataclass
class IOConfig:
    """Knobs for the overlapped I/O pipeline (utils/io_pipeline.py).

    * ``async_checkpoints`` — cadence checkpoints are fetched to host on the
      main thread (:func:`~rustpde_mpi_tpu.utils.checkpoint.snapshot_to_host`,
      the one device sync a checkpoint inherently needs) and serialized +
      digest-stamped + fsynced on a background worker while the device steps
      the next chunks.  Edge checkpoints (anchor/final/preempt) stay
      effectively synchronous — the runner drains right after submitting
      them.  On multihost meshes the WRITE side runs through per-host
      background shard writers (each host overlaps its own shard
      serialization; the two-phase manifest commit happens collectively at
      the next chunk boundary, after every host drained its writer —
      drain-before-barrier), while ``overlap_dispatch`` stays disabled:
      a lagged break check resolving on per-host device timing would
      desynchronize the collective dispatch sequence, so break decisions
      remain un-lagged and root-broadcast.
      Durability is unchanged: writes are still atomic and verified, the
      writer drains before any rollback/resume read, and a write failure
      re-raises at the next submit/drain (collectively, on the sharded
      path: no manifest is committed when any host failed).
    * ``overlap_dispatch`` — dispatch double-buffering in the chunked
      driver: break checks + callback observables ride futures (one-chunk
      lag, see ``integrate(overlap=...)``) instead of fencing the device
      queue every boundary.  Single-process only (see above).
    * ``sharded_checkpoints`` — the distributed two-phase checkpoint format
      (utils/checkpoint.write_sharded_snapshot: per-host shard files +
      root manifest commit marker).  ``None`` (default) = auto: sharded on
      multi-process runtimes, gathered single-file otherwise; ``True``
      forces the sharded format (CI exercises it on the single-process
      virtual mesh); ``False`` pins the legacy gathered writer (which
      REQUIRES fully-addressable state — it cannot checkpoint a real
      multi-controller mesh).
    * ``queue_depth`` — bounded in-flight background writes: a submission
      past the depth blocks (back-pressure), so host memory holds at most
      ``queue_depth`` pending snapshots and cadence can never outrun disk.
    * ``diag_lag`` — boundaries a diagnostics emission may trail the device
      before the callback blocks for it (0 = synchronous printing).
    """

    async_checkpoints: bool = True
    overlap_dispatch: bool = True
    sharded_checkpoints: bool | None = None
    queue_depth: int = 1
    diag_lag: int = 1

    @classmethod
    def blocking(cls) -> "IOConfig":
        """Fully synchronous IO (the pre-pipeline behavior)."""
        return cls(async_checkpoints=False, overlap_dispatch=False, diag_lag=0)


@dataclass
class ResilienceConfig:
    """Knobs for :class:`~rustpde_mpi_tpu.utils.resilience.ResilientRunner`
    (field names match the runner's keyword arguments; build one via
    ``ResilientRunner.from_config(pde, cfg.resilience, max_time)``).

    ``checkpoint_every_s``/``checkpoint_every_t`` are the wall-clock and
    sim-time checkpoint cadences (either may be None); ``keep`` is the
    rolling retention window; ``dt_backoff`` is the divergence-retry step
    shrink factor with ``dt_min`` as its hard floor (so compounding backoff
    cannot drive dt toward denormals); ``respawn_seed`` carries the PRNG
    seed for ``respawn_dead`` donor perturbations (recovery runs are
    reproducible when set); ``dispatch_timeout_s`` arms the device-dispatch
    hang watchdog (None = RUSTPDE_DISPATCH_TIMEOUT_S env, unset = off);
    ``stability`` enables the proactive governor
    (:class:`StabilityConfig`)."""

    run_dir: str = "data/resilient"
    checkpoint_every_s: float | None = 300.0
    checkpoint_every_t: float | None = None
    keep: int = 3
    max_retries: int = 3
    dt_backoff: float = 0.5
    dt_min: float = 0.0
    respawn_members: bool = False
    respawn_amp: float = 1e-3
    respawn_seed: int | None = None
    dispatch_timeout_s: float | None = None
    resume: bool = True
    stability: StabilityConfig | None = None
    # overlapped-IO pipeline knobs (None = IOConfig() defaults: async
    # cadence checkpoints + dispatch double-buffering ON)
    io: IOConfig | None = None


@dataclass
class FleetConfig:
    """Knobs for the fleet layer (serve/fleet/): N stateless proxy
    processes and M ``SimServer`` replicas over ONE shared durable queue,
    coordinated by queue-level lease files — no consensus service, the
    fsynced atomic-rename lifecycle is the substrate.

    * ``replica_id`` — stable identity for lease/heartbeat files (empty:
      ``RUSTPDE_FLEET_REPLICA_ID`` env, else ``<hostname>-<pid>``),
    * ``lease_ttl_s`` — a lease whose heartbeat has not advanced for this
      long (observer-monotonic, clock-skew tolerant) is STALE: survivors
      break it and re-claim its requests (None: ``RUSTPDE_LEASE_TTL_S``,
      default 15),
    * ``heartbeat_s`` — lease + replica-status heartbeat cadence (None:
      ``RUSTPDE_FLEET_HEARTBEAT_S``, else ``lease_ttl_s / 3``),
    * ``default_quota`` — per-tenant admission bound over queued+running
      requests (None: ``RUSTPDE_FLEET_QUOTA``, unset = unlimited); the
      429 carries ``Retry-After`` + the live queue depth,
    * ``quotas`` — per-tenant overrides of ``default_quota``,
    * ``preempt`` — let an at-risk deadline request park a running
      best-effort lane (requeue-with-state through the durable
      continuation dir, loss-free),
    * ``preempt_slack_s`` — remaining deadline slack below which a queued
      interactive request is AT RISK and triggers preemption,
    * ``durable_park`` — persist parked member states into
      ``parked/<id>/`` continuation dirs (two-phase: state shard +
      manifest commit marker) so requeue-with-state survives replica
      SIGKILL.  Off only for A/B debugging — fleet HA rides on it."""

    replica_id: str = ""
    lease_ttl_s: float | None = None
    heartbeat_s: float | None = None
    default_quota: int | None = None
    quotas: dict = field(default_factory=dict)
    preempt: bool = True
    preempt_slack_s: float = 30.0
    durable_park: bool = True

    def resolved_replica_id(self) -> str:
        if self.replica_id:
            return str(self.replica_id)
        rid = env_get("RUSTPDE_FLEET_REPLICA_ID")
        if rid:
            return rid
        import socket

        return f"{socket.gethostname()}-{os.getpid()}"

    def resolved_ttl(self) -> float:
        if self.lease_ttl_s is not None:
            return float(self.lease_ttl_s)
        return float(env_get("RUSTPDE_LEASE_TTL_S", "15"))

    def resolved_heartbeat(self) -> float:
        if self.heartbeat_s is not None:
            return float(self.heartbeat_s)
        hb = env_get("RUSTPDE_FLEET_HEARTBEAT_S")
        return float(hb) if hb else self.resolved_ttl() / 3.0

    def resolved_quota(self, tenant: str) -> int | None:
        if tenant in self.quotas:
            q = self.quotas[tenant]
            return None if q is None else int(q)
        if self.default_quota is not None:
            return int(self.default_quota)
        q = env_get("RUSTPDE_FLEET_QUOTA")
        return int(q) if q else None


@dataclass
class AutoscaleConfig:
    """Control law for the fleet autoscaler (serve/fleet/autoscaler.py): a
    controller that reads the signals the fleet already exports (queue
    depth + per-tenant census, deadline slack from the QoS ordering,
    replica heartbeats) and drives a pluggable ``ReplicaLauncher``.

    Scale-OUT (one replica per decision, bounded by ``max_replicas``):

    * deadline pressure — a queued deadline-bearing request's slack fell
      below ``slack_low_s`` (immediate: waiting out a sustain window is
      exactly how the deadline is missed),
    * queue pressure — queued depth above ``queue_high`` continuously for
      ``sustain_s``,
    * capacity repair — live replicas below ``min_replicas`` (immediate
      and exempt from the cooldown: replacing preempted capacity must not
      wait out the window that throttles elective growth).

    Scale-IN (one replica per decision, bounded by ``min_replicas``): the
    fleet fully idle — nothing queued, nothing running — continuously for
    ``idle_sustain_s``.  The victim is retired by SIGTERM through the
    existing park machinery (running slots persist as durable
    continuations, leases release, exit clean), never killed.

    Hysteresis = the separate sustain windows; ``cooldown_s`` additionally
    spaces consecutive elective actions.  A spawned replica counts toward
    the fleet for ``spawn_grace_s`` before its first heartbeat lands, so a
    slow JAX import cannot read as missing capacity and storm spawns.

    ``notice_s`` seeds ``RUSTPDE_PREEMPT_NOTICE_S`` in launched replicas
    (None: inherit the environment): preemptible capacity should drain
    urgently when its platform says the clock is running.

    ``gang_size`` makes capacity GANG-SHAPED (two-level serving): every
    scale decision moves ``gang_size`` replicas as one fate-shared unit —
    spawns go through the launcher's all-or-nothing ``spawn_gang`` and
    scale-in retires a whole gang or nothing, so the fleet never holds a
    lone gang member that could wedge a sharded campaign's collectives.
    The default 1 is exactly the pre-gang control law."""

    min_replicas: int = 1
    max_replicas: int = 4
    queue_high: int = 8
    sustain_s: float = 5.0
    idle_sustain_s: float = 15.0
    slack_low_s: float = 30.0
    cooldown_s: float = 30.0
    decide_s: float = 2.0
    spawn_grace_s: float = 60.0
    notice_s: float | None = None
    replica_prefix: str = "auto"
    gang_size: int = 1


@dataclass
class SubmeshConfig:
    """Two-level serving (parallel/submesh.py + serve/fleet/gang.py): the
    device fleet is carved into SUB-MESHES so one pencil-sharded flagship
    bucket runs as a gang on a slice while vmapped small-grid buckets
    keep the remainder — with the gang as the failure domain.

    * ``shapes`` — sub-mesh sizes (device counts) to carve, e.g.
      ``(2,)`` on the 2-proc CPU harness or ``(8, 4)`` on a pod slice.
      Shapes the current fleet cannot hold are dropped from the carve
      (the elastic re-planner re-maps stamped buckets, journaled
      ``gang_replanned``); on a multi-process runtime a shape must be a
      multiple of the process count so every process participates in
      every sub-mesh's collectives,
    * ``shard_min_nx`` — grids at/above this extent are SHARDED traffic:
      admission stamps them with the smallest fitting configured shape
      (the stamp joins the compat key, so equal grids bucket together);
      below it requests stay vmapped default traffic with today's keys,
    * ``max_pending`` — admission bound on QUEUED sharded requests per
      stamped shape: past it the POST gets a 429 ``reason="capacity"``
      with queue-depth-derived Retry-After (a fitting sub-mesh exists
      but is busy); a grid that fits NO configured shape is a typed 400
      ``reason="no_submesh"`` at POST time — never a durable poison
      pill."""

    shapes: tuple = (2,)
    shard_min_nx: int = 257
    max_pending: int = 32


@dataclass
class CanonicalConfig:
    """Admission canonicalization (serve/scheduler.py ``submit``): quantize
    the request onto a small, warmable compat-key space so the warm pool's
    AOT executables actually cover traffic.

    What admission may change about a request: its ``dt`` (snapped to the
    nearest rung of a service-wide geometric :class:`DtLadder` anchored at
    ``dt_anchor``, only when the relative shift stays within
    ``max_rel_dt_shift``) and the campaign slot count K (rounded UP to the
    nearest entry of ``slot_sizes`` so a prebuilt ensemble fits — extra
    lanes start dead and are refilled from the queue like any other slot).
    What it may NOT change: the simulated horizon (``SimRequest.steps``
    derives from horizon/dt, so a dt snap re-derives the step count at the
    same physical end time), the grid/Ra/Pr/BC physics of the key, seeds,
    priority, or deadlines.  Every snap is journaled
    (``request_canonicalized``) and the result is guaranteed within
    ``rtol`` of the un-canonicalized run (tests/bench gate it).

    * ``dt_anchor`` / ``ladder_ratio`` — the service-wide rung grid
      (``dt = anchor * ratio**rung``); anchor defaults to the request
      default dt so default traffic is already on-rung,
    * ``dt_min`` / ``dt_max`` — ladder bounds (requests outside snap to the
      edge rung only if within ``max_rel_dt_shift``),
    * ``max_rel_dt_shift`` — admission refuses to move dt further than
      this relative fraction (the request then keeps its exact dt and pays
      its own compile),
    * ``slot_sizes`` — ascending pool sizes K is rounded up to (empty =
      keep the configured ``ServeConfig.slots``),
    * ``rtol`` — the documented parity tolerance between a canonicalized
      run and the same request served at its exact dt."""

    dt_anchor: float = 2e-3
    ladder_ratio: float = 2.0
    dt_min: float = 1e-6
    dt_max: float = 1e-1
    max_rel_dt_shift: float = 0.5
    slot_sizes: tuple = ()
    rtol: float = 5e-2


@dataclass
class ServeConfig:
    """Knobs for the fault-isolated simulation service
    (:class:`~rustpde_mpi_tpu.serve.SimServer`): a persistent driver that
    accepts simulation requests through a durable on-disk queue (plus an
    optional thin HTTP front), bucket-batches compatible requests into
    :class:`~rustpde_mpi_tpu.models.ensemble.NavierEnsemble` slots
    LLM-style, and streams per-request observables back as each resolves.

    * ``run_dir`` — service state root: the durable queue lives under
      ``<run_dir>/queue``, campaign checkpoints under
      ``<run_dir>/campaigns/<key>``, and every runner + ``request_*`` event
      rides ONE ``<run_dir>/journal.jsonl``,
    * ``slots`` — ensemble members per campaign batch (the K of the vmapped
      dispatch); a finished/failed/cancelled member's slot is refilled from
      the queue mid-campaign without recompiling,
    * ``max_queue`` — admission-control bound: a submit past this depth is
      rejected with a typed reason (bounded memory + latency instead of an
      unbounded backlog),
    * ``chunk_steps`` — upper bound on steps per dispatch between schedule
      points (slot completions land exactly on chunk boundaries because the
      chunk is also capped by the minimum remaining steps of any running
      slot),
    * ``checkpoint_every_s`` — wall-clock cadence for slot-table
      checkpoints (None: only drain/edge checkpoints); serve checkpoints
      always use the sharded two-phase writer, carrying the slot table as
      digest-covered manifest data so restarts rebuild it from the
      checkpoint alone,
    * ``request_max_retries`` / ``request_dt_backoff`` — per-request
      divergence policy: a diverged request is re-queued at
      ``dt * backoff`` (a new compatibility bucket) up to the retry budget,
      then lands in the ``failed/`` terminal state with a typed
      :class:`~rustpde_mpi_tpu.serve.RequestFailed` record,
    * ``default_amp`` — initial-condition amplitude for requests that do
      not specify one,
    * ``idle_exit`` — return from :meth:`serve` once the queue is empty and
      every slot resolved (the batch/soak mode); False keeps the service
      waiting for new work (the daemon mode),
    * ``poll_s`` — idle-queue poll interval in daemon mode,
    * ``http_host``/``http_port`` — thin HTTP front (``http_port=None``
      disables it; 0 binds an ephemeral port, reported by ``http_address``),
    * ``resilience`` — runner knobs for the embedded
      :class:`~rustpde_mpi_tpu.utils.resilience.ResilientRunner` (fault
      injection, watchdogs, governor); ``run_dir``/``resume`` fields are
      overridden per campaign by the scheduler."""

    run_dir: str = "data/serve"
    slots: int = 8
    max_queue: int = 256
    chunk_steps: int = 256
    # bucket fairness: max requests one campaign visit may claim while
    # OTHER buckets hold queued work (0 = unlimited); with round-robin
    # bucket selection this bounds any bucket's wait to one quantum per
    # competitor instead of a hot bucket's whole backlog
    bucket_quantum: int = 32
    checkpoint_every_s: float | None = 60.0
    request_max_retries: int = 2
    request_dt_backoff: float = 0.5
    default_amp: float = 0.1
    idle_exit: bool = True
    poll_s: float = 0.2
    http_host: str = "127.0.0.1"
    http_port: int | None = None
    resilience: ResilienceConfig | None = None
    # in-scan physics statistics (None = off): arms the stats engine on
    # every DNS campaign ensemble — per-member running averages updated on
    # device, reset when a lane is refilled by a new request, summarized
    # into each done record ("stats": samples, Nu estimators, budget
    # residuals, spectral-tail fractions).  Lane moves across a drain/
    # re-plan restart the per-request averages (documented limitation);
    # the bit-exact durability contract lives on the runner/campaign path.
    stats: StatsConfig | None = None
    # governed campaign dt (None = reactive-only): arms the on-device
    # stability sentinels on every campaign ensemble and gives each bucket
    # a per-bucket DtLadder — a CFL-ceiling catch re-buckets the pinned
    # requests at a lower rung (requeue-WITH-state, journaled
    # `bucket_dt_adjust`) instead of waiting for NaN + reactive retry.
    # The batch-wide StabilityGovernor stays OFF in campaigns: per-request
    # dt is part of the request contract and the bucket key, so the only
    # legal dt response is re-bucketing, never an in-place set_dt.
    stability: StabilityConfig | None = None
    # fleet mode (None = off, the single-replica behavior unchanged —
    # zero extra journal rows or collectives): this SimServer becomes one
    # replica of a fleet over the shared run_dir — it claims buckets via
    # queue-level leases, heartbeats them, persists parked continuations
    # durably, writes its journal/campaigns under replicas/<id>/, and
    # enforces the QoS traffic contract (quotas, priority classes,
    # deadlines, preemption).  Pair with serve/fleet/proxy.py fronts.
    fleet: FleetConfig | None = None
    # embedded fleet autoscaler (None = off, the default: byte-identical
    # serve behavior — zero extra journal rows, zero extra collectives, no
    # controller threads, CI-asserted).  Set (fleet mode, root only) it
    # starts an Autoscaler daemon thread next to the heartbeat thread:
    # pure host-side file IO + subprocess spawns through a local
    # ReplicaLauncher — never a collective.  The controller can equally
    # run standalone (examples/navier_rbc_autoscale.py).
    autoscale: AutoscaleConfig | None = None
    # two-level serving (None = off, the default: byte-identical serve
    # behavior — 10-tuple compat keys everywhere, zero gang journal rows,
    # CI-asserted): carve the device fleet into sub-meshes and serve
    # pencil-sharded flagship buckets as fate-shared GANGS on slices
    # while vmapped buckets keep the remainder.  See SubmeshConfig.
    submesh: SubmeshConfig | None = None
    # warm campaign pool (None = off, the default: byte-identical serve
    # behavior, zero warm-pool journal rows, CI-asserted): a traffic
    # profile — a path to a durable JSON learned from the journal's
    # historical compile_build rows (serve/warmpool.py learn_profile), or
    # an inline list of {"key": [...], "k": int} entries — whose
    # (model kind × grid × K × dt-rung) matrix is AOT-compiled in a
    # background thread at service start and handed to the scheduler warm
    # at bucket-open, so admission-to-first-chunk skips the jit entirely.
    warm_profile: object | None = None
    # admission canonicalization (None = off, the default: requests keep
    # their exact dt and the configured slot count).  See CanonicalConfig.
    canonicalize: CanonicalConfig | None = None
    # end-to-end integrity (None = off): arms the on-device state digest +
    # shadow-audit layer (integrity/) on every campaign ensemble — silent
    # bit flips are caught by the runner's digest audits, contained by
    # in-memory rollback, charged to the quarantine ledger
    # (<run_dir>/quarantine.json), and a quarantined device is excluded
    # from the next campaign's sub-mesh carve.  Done records carry each
    # member's final state digest so the fleet proxy's cross-replica
    # voting can compare double-assigned requests bit-for-bit.
    integrity: IntegrityConfig | None = None


@dataclass
class NavierConfig:
    """Configuration dataclass for the Navier models (SURVEY.md S5: the
    reference passes bare constructor arguments and mutates public fields,
    navier.rs:229-233; this names the same vocabulary in one object).

    Use with ``Navier2D.from_config(cfg)`` / ``Navier2DAdjoint.from_config``.
    """

    nx: int = 129
    ny: int = 129
    ra: float = 1e7
    pr: float = 1.0
    dt: float = 2e-3
    aspect: float = 1.0
    bc: str = "rbc"  # "rbc" | "hc"
    periodic: bool = False
    # post-construction knobs (public-field mutation in the reference)
    write_intervall: float | None = None
    init_random_amp: float | None = 0.1
    params: dict = field(default_factory=dict)  # extra params recorded to h5
    # member count for NavierEnsemble.from_config (1 = plain single run);
    # members share the operator constants and differ by IC seed
    ensemble: int = 1
    # resilience-harness knobs (None = run without the harness; see
    # ResilienceConfig / utils/resilience.ResilientRunner)
    resilience: ResilienceConfig | None = None
    # stability-sentinel knobs (None = plain stepping; see StabilityConfig /
    # utils/governor.py) — from_config calls model.set_stability(stability)
    stability: StabilityConfig | None = None
    # in-scan physics-statistics knobs (None = off unless RUSTPDE_STATS=1;
    # see StatsConfig / models/stats.py) — from_config calls
    # model.set_stats(stats)
    stats: StatsConfig | None = None
    # scenario step modifiers (None = plain physics; a
    # workloads.modifiers.ScenarioConfig or equivalent dict: rotating-frame
    # coriolis rate, passive_scalar, scalar_kappa) — baked into the step
    # and signed into compat_key
    scenario: object | None = None

    def ctor_args(self) -> tuple:
        return (self.nx, self.ny, self.ra, self.pr, self.dt, self.aspect, self.bc)
