"""Global configuration for rustpde_mpi_tpu.

The reference framework (rustpde-mpi, /root/reference/src/lib.rs) computes in
f64 everywhere.  On TPU, f64 is emulated and slow, so precision is a run-time
choice here:

* ``RUSTPDE_X64=1`` (default) enables ``jax_enable_x64`` at import time and all
  operators/states default to float64 — required for the 1e-6 Nusselt-parity
  gate against the CPU reference.
* ``RUSTPDE_X64=0`` leaves JAX in f32 mode for maximum TPU throughput; solver
  setup (eigendecompositions, LU factorizations) still happens on the host in
  numpy f64 and is rounded once at the end.
"""

from __future__ import annotations

import os

import jax
import numpy as np

X64: bool = os.environ.get("RUSTPDE_X64", "1") != "0"

if X64:
    jax.config.update("jax_enable_x64", True)

# Spectral transforms/solves are precision-critical: TPU f32 matmuls default
# to bf16 MXU passes (~1e-2 relative error), which destroys spectral accuracy.
# "highest" (default) keeps true f32 (or f64 under x64) accumulation via
# 6-pass bf16; RUSTPDE_MATMUL_PRECISION=high selects the 3-pass variant —
# ~1.6x faster steps on the MXU-bound path, measured Nu drift at the 129^2
# parity config within the f32 noise floor (see BASELINE.md).
MATMUL_PRECISION = os.environ.get("RUSTPDE_MATMUL_PRECISION", "highest")
jax.config.update("jax_default_matmul_precision", MATMUL_PRECISION)


def enable_compilation_cache(path: str | None = None) -> str:
    """Enable JAX's persistent compilation cache (works through the axon
    relay: measured 39 s -> 9 s for the 1025^2 step compile, 67 s -> 10 s for
    model build).  Call before the first jit dispatch; idempotent.

    The env vars are also set so child processes (the f64 bench subprocess)
    inherit the cache."""
    if path is None:
        path = os.environ.get(
            "JAX_COMPILATION_CACHE_DIR",
            os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".jax_cache"),
        )
    os.environ["JAX_COMPILATION_CACHE_DIR"] = path
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update(
        "jax_persistent_cache_min_entry_size_bytes",
        int(os.environ["JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES"]),
    )
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs",
        float(os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"]),
    )
    return path


def host_cache_dir() -> str:
    """Root for host-side factorization caches (modal eigs, dense inverses):
    a ``host/`` subdir of the XLA compilation cache root, honoring
    JAX_COMPILATION_CACHE_DIR like enable_compilation_cache does."""
    root = os.environ.get(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".jax_cache"),
    )
    return os.path.join(root, "host")


def host_cache_store(path: str, save_fn) -> None:
    """Best-effort atomic publish of a host cache entry: ``save_fn(tmp)``
    writes the temp file (suffix chosen by the caller), then os.replace."""
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.{os.getpid()}.tmp{os.path.splitext(path)[1]}"
        save_fn(tmp)
        os.replace(tmp, path)
    except OSError:
        pass


def real_dtype():
    """Default real dtype for device arrays."""
    return np.float64 if X64 else np.float32


def complex_dtype():
    """Default complex dtype for device arrays."""
    return np.complex128 if X64 else np.complex64


def default_device_kind() -> str:
    return jax.devices()[0].platform


def is_tpu_like() -> bool:
    """True on TPU (including the 'axon' tunnel platform).

    ``RUSTPDE_FORCE_TPU_PATH=1`` forces True so CI (which runs on CPU,
    tests/conftest.py) can exercise the execution paths the real TPU uses:
    matmul transforms, dense ADI solves, fast-diagonalisation Poisson."""
    if os.environ.get("RUSTPDE_FORCE_TPU_PATH") == "1":
        return True
    return default_device_kind() not in ("cpu", "gpu", "cuda", "rocm")


def supports_complex() -> bool:
    """The axon TPU backend implements no complex dtypes (and therefore no
    FFT); spectral pipelines there must run real-valued matmul transforms,
    with Fourier axes in a split re/im representation."""
    return not is_tpu_like()


from dataclasses import dataclass, field


@dataclass
class StabilityConfig:
    """Knobs for the proactive stability governor
    (:class:`~rustpde_mpi_tpu.utils.governor.StabilityGovernor` + the
    on-device sentinels compiled into the scanned step when a model's
    ``set_stability`` is called).

    * ``target_cfl`` — the Courant number the dt controller drives toward,
    * ``max_cfl`` — the hard on-device ceiling: a chunk whose per-step CFL
      exceeds it early-exits the scan with a ``pre_divergence`` status
      *before* NaNs propagate (recovered by a cheap in-memory rollback of
      just that chunk),
    * ``ladder_ratio`` — geometric spacing of the dt ladder the controller
      quantizes to (the dt-baked solver factorizations are cached per rung,
      so the re-jit/refactorization count over a long run is bounded by the
      ladder size),
    * ``dt_min``/``dt_max`` — ladder bounds (None: ``dt_max`` anchors at the
      run's initial dt, ``dt_min`` at ``dt_max * ladder_ratio**-10``),
    * ``grow_after`` — healthy chunks at a rung before the governor climbs
      back up the ladder (the regrowth the reactive backoff lacks),
    * ``shrink_cfl`` — proactive shrink threshold (None:
      ``0.85 * max_cfl``): a chunk whose max CFL exceeds it steps the ladder
      down *without* any rollback,
    * ``member_pin_patience`` — consecutive pre-divergence catches pinned on
      the same ensemble member before that member is declared dead and
      handed to the ``respawn_dead`` machinery."""

    target_cfl: float = 0.5
    max_cfl: float = 1.0
    ladder_ratio: float = 2.0
    dt_min: float | None = None
    dt_max: float | None = None
    grow_after: int = 4
    shrink_cfl: float | None = None
    member_pin_patience: int = 3


@dataclass
class IOConfig:
    """Knobs for the overlapped I/O pipeline (utils/io_pipeline.py).

    * ``async_checkpoints`` — cadence checkpoints are fetched to host on the
      main thread (:func:`~rustpde_mpi_tpu.utils.checkpoint.snapshot_to_host`,
      the one device sync a checkpoint inherently needs) and serialized +
      digest-stamped + fsynced on a background worker while the device steps
      the next chunks.  Edge checkpoints (anchor/final/preempt) stay
      effectively synchronous — the runner drains right after submitting
      them.  On multihost meshes the WRITE side runs through per-host
      background shard writers (each host overlaps its own shard
      serialization; the two-phase manifest commit happens collectively at
      the next chunk boundary, after every host drained its writer —
      drain-before-barrier), while ``overlap_dispatch`` stays disabled:
      a lagged break check resolving on per-host device timing would
      desynchronize the collective dispatch sequence, so break decisions
      remain un-lagged and root-broadcast.
      Durability is unchanged: writes are still atomic and verified, the
      writer drains before any rollback/resume read, and a write failure
      re-raises at the next submit/drain (collectively, on the sharded
      path: no manifest is committed when any host failed).
    * ``overlap_dispatch`` — dispatch double-buffering in the chunked
      driver: break checks + callback observables ride futures (one-chunk
      lag, see ``integrate(overlap=...)``) instead of fencing the device
      queue every boundary.  Single-process only (see above).
    * ``sharded_checkpoints`` — the distributed two-phase checkpoint format
      (utils/checkpoint.write_sharded_snapshot: per-host shard files +
      root manifest commit marker).  ``None`` (default) = auto: sharded on
      multi-process runtimes, gathered single-file otherwise; ``True``
      forces the sharded format (CI exercises it on the single-process
      virtual mesh); ``False`` pins the legacy gathered writer (which
      REQUIRES fully-addressable state — it cannot checkpoint a real
      multi-controller mesh).
    * ``queue_depth`` — bounded in-flight background writes: a submission
      past the depth blocks (back-pressure), so host memory holds at most
      ``queue_depth`` pending snapshots and cadence can never outrun disk.
    * ``diag_lag`` — boundaries a diagnostics emission may trail the device
      before the callback blocks for it (0 = synchronous printing).
    """

    async_checkpoints: bool = True
    overlap_dispatch: bool = True
    sharded_checkpoints: bool | None = None
    queue_depth: int = 1
    diag_lag: int = 1

    @classmethod
    def blocking(cls) -> "IOConfig":
        """Fully synchronous IO (the pre-pipeline behavior)."""
        return cls(async_checkpoints=False, overlap_dispatch=False, diag_lag=0)


@dataclass
class ResilienceConfig:
    """Knobs for :class:`~rustpde_mpi_tpu.utils.resilience.ResilientRunner`
    (field names match the runner's keyword arguments; build one via
    ``ResilientRunner.from_config(pde, cfg.resilience, max_time)``).

    ``checkpoint_every_s``/``checkpoint_every_t`` are the wall-clock and
    sim-time checkpoint cadences (either may be None); ``keep`` is the
    rolling retention window; ``dt_backoff`` is the divergence-retry step
    shrink factor with ``dt_min`` as its hard floor (so compounding backoff
    cannot drive dt toward denormals); ``respawn_seed`` carries the PRNG
    seed for ``respawn_dead`` donor perturbations (recovery runs are
    reproducible when set); ``dispatch_timeout_s`` arms the device-dispatch
    hang watchdog (None = RUSTPDE_DISPATCH_TIMEOUT_S env, unset = off);
    ``stability`` enables the proactive governor
    (:class:`StabilityConfig`)."""

    run_dir: str = "data/resilient"
    checkpoint_every_s: float | None = 300.0
    checkpoint_every_t: float | None = None
    keep: int = 3
    max_retries: int = 3
    dt_backoff: float = 0.5
    dt_min: float = 0.0
    respawn_members: bool = False
    respawn_amp: float = 1e-3
    respawn_seed: int | None = None
    dispatch_timeout_s: float | None = None
    resume: bool = True
    stability: StabilityConfig | None = None
    # overlapped-IO pipeline knobs (None = IOConfig() defaults: async
    # cadence checkpoints + dispatch double-buffering ON)
    io: IOConfig | None = None


@dataclass
class ServeConfig:
    """Knobs for the fault-isolated simulation service
    (:class:`~rustpde_mpi_tpu.serve.SimServer`): a persistent driver that
    accepts simulation requests through a durable on-disk queue (plus an
    optional thin HTTP front), bucket-batches compatible requests into
    :class:`~rustpde_mpi_tpu.models.ensemble.NavierEnsemble` slots
    LLM-style, and streams per-request observables back as each resolves.

    * ``run_dir`` — service state root: the durable queue lives under
      ``<run_dir>/queue``, campaign checkpoints under
      ``<run_dir>/campaigns/<key>``, and every runner + ``request_*`` event
      rides ONE ``<run_dir>/journal.jsonl``,
    * ``slots`` — ensemble members per campaign batch (the K of the vmapped
      dispatch); a finished/failed/cancelled member's slot is refilled from
      the queue mid-campaign without recompiling,
    * ``max_queue`` — admission-control bound: a submit past this depth is
      rejected with a typed reason (bounded memory + latency instead of an
      unbounded backlog),
    * ``chunk_steps`` — upper bound on steps per dispatch between schedule
      points (slot completions land exactly on chunk boundaries because the
      chunk is also capped by the minimum remaining steps of any running
      slot),
    * ``checkpoint_every_s`` — wall-clock cadence for slot-table
      checkpoints (None: only drain/edge checkpoints); serve checkpoints
      always use the sharded two-phase writer, carrying the slot table as
      digest-covered manifest data so restarts rebuild it from the
      checkpoint alone,
    * ``request_max_retries`` / ``request_dt_backoff`` — per-request
      divergence policy: a diverged request is re-queued at
      ``dt * backoff`` (a new compatibility bucket) up to the retry budget,
      then lands in the ``failed/`` terminal state with a typed
      :class:`~rustpde_mpi_tpu.serve.RequestFailed` record,
    * ``default_amp`` — initial-condition amplitude for requests that do
      not specify one,
    * ``idle_exit`` — return from :meth:`serve` once the queue is empty and
      every slot resolved (the batch/soak mode); False keeps the service
      waiting for new work (the daemon mode),
    * ``poll_s`` — idle-queue poll interval in daemon mode,
    * ``http_host``/``http_port`` — thin HTTP front (``http_port=None``
      disables it; 0 binds an ephemeral port, reported by ``http_address``),
    * ``resilience`` — runner knobs for the embedded
      :class:`~rustpde_mpi_tpu.utils.resilience.ResilientRunner` (fault
      injection, watchdogs, governor); ``run_dir``/``resume`` fields are
      overridden per campaign by the scheduler."""

    run_dir: str = "data/serve"
    slots: int = 8
    max_queue: int = 256
    chunk_steps: int = 256
    # bucket fairness: max requests one campaign visit may claim while
    # OTHER buckets hold queued work (0 = unlimited); with round-robin
    # bucket selection this bounds any bucket's wait to one quantum per
    # competitor instead of a hot bucket's whole backlog
    bucket_quantum: int = 32
    checkpoint_every_s: float | None = 60.0
    request_max_retries: int = 2
    request_dt_backoff: float = 0.5
    default_amp: float = 0.1
    idle_exit: bool = True
    poll_s: float = 0.2
    http_host: str = "127.0.0.1"
    http_port: int | None = None
    resilience: ResilienceConfig | None = None
    # governed campaign dt (None = reactive-only): arms the on-device
    # stability sentinels on every campaign ensemble and gives each bucket
    # a per-bucket DtLadder — a CFL-ceiling catch re-buckets the pinned
    # requests at a lower rung (requeue-WITH-state, journaled
    # `bucket_dt_adjust`) instead of waiting for NaN + reactive retry.
    # The batch-wide StabilityGovernor stays OFF in campaigns: per-request
    # dt is part of the request contract and the bucket key, so the only
    # legal dt response is re-bucketing, never an in-place set_dt.
    stability: StabilityConfig | None = None


@dataclass
class NavierConfig:
    """Configuration dataclass for the Navier models (SURVEY.md S5: the
    reference passes bare constructor arguments and mutates public fields,
    navier.rs:229-233; this names the same vocabulary in one object).

    Use with ``Navier2D.from_config(cfg)`` / ``Navier2DAdjoint.from_config``.
    """

    nx: int = 129
    ny: int = 129
    ra: float = 1e7
    pr: float = 1.0
    dt: float = 2e-3
    aspect: float = 1.0
    bc: str = "rbc"  # "rbc" | "hc"
    periodic: bool = False
    # post-construction knobs (public-field mutation in the reference)
    write_intervall: float | None = None
    init_random_amp: float | None = 0.1
    params: dict = field(default_factory=dict)  # extra params recorded to h5
    # member count for NavierEnsemble.from_config (1 = plain single run);
    # members share the operator constants and differ by IC seed
    ensemble: int = 1
    # resilience-harness knobs (None = run without the harness; see
    # ResilienceConfig / utils/resilience.ResilientRunner)
    resilience: ResilienceConfig | None = None
    # stability-sentinel knobs (None = plain stepping; see StabilityConfig /
    # utils/governor.py) — from_config calls model.set_stability(stability)
    stability: StabilityConfig | None = None
    # scenario step modifiers (None = plain physics; a
    # workloads.modifiers.ScenarioConfig or equivalent dict: rotating-frame
    # coriolis rate, passive_scalar, scalar_kappa) — baked into the step
    # and signed into compat_key
    scenario: object | None = None

    def ctor_args(self) -> tuple:
        return (self.nx, self.ny, self.ra, self.pr, self.dt, self.aspect, self.bc)
