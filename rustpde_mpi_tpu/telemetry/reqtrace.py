"""Per-request distributed tracing: one trace_id from HTTP admission to the
last chunk, across drains, restarts, re-buckets and fleet incarnations.

The flight recorder (telemetry/tracing.py) answers "what was THIS PROCESS
doing just before the incident"; this module answers the orthogonal serving
question — "what happened to THIS REQUEST", whose lifecycle spans several
campaigns, possibly several process incarnations, and (multihost) several
hosts.  Three pieces:

* **trace context** — :func:`mint` creates ``{"trace_id", "span"}`` at
  admission (:meth:`SimRequest.__post_init__` calls it, so EVERY request
  carries one); the context is a plain dict riding the durable request
  file, so it survives drain/requeue/re-bucket/restart by the same rename
  atomicity the request itself does,
* **request trace log** — a bounded per-process event list
  (``RUSTPDE_REQTRACE_EVENTS``) the serve scheduler feeds per-slot chunk
  spans into; :func:`write_campaign_trace` drains it at campaign close,
  gathers every host's events over ``multihost.allgather_bytes`` (root-only
  file write, like the journal) and drops one Perfetto ``traceEvents`` file
  per campaign next to its checkpoints,
* **assembly** — :func:`assemble_request_trace` reconstructs one request's
  full timeline (admission → queued → scheduled → N chunks → re-bucket →
  done) from the journal's lifecycle rows (absolute ``t`` stamps) plus the
  per-campaign trace files, keyed by the single trace_id — the
  ``GET /requests/<id>/trace`` endpoint serves exactly this payload.

The binding surface (:func:`bind_slots` / :func:`active_ids`) tells the
rest of the telemetry layer which requests are on the device RIGHT NOW:
flight-recorder spans are annotated with the active trace ids (see
``tracing.set_span_annotator``) and incident dumps carry them, so a chaos
soak's dump pile is attributable to requests.

Overhead contract: same as the rest of telemetry — host-side bookkeeping
only, nothing traced changes, ``RUSTPDE_REQTRACE=0`` (or the
``RUSTPDE_TELEMETRY=0`` master) turns recording off while trace ids keep
being minted (ids are durability metadata, not instrumentation).
"""

from __future__ import annotations

import json
import os
import threading
import time as _time
import uuid

from .. import config as _config

_ENABLED = (
    _config.env_get("RUSTPDE_REQTRACE", "1") != "0"
    and _config.env_get("RUSTPDE_TELEMETRY", "1") != "0"
)


def enabled() -> bool:
    return _ENABLED


def set_enabled(flag: bool) -> None:
    """Toggle request-trace recording (the bench overhead gate's OFF leg
    rides ``telemetry.set_enabled``, which calls this too)."""
    global _ENABLED
    _ENABLED = bool(flag)


def mint(request_id: str | None = None) -> dict:
    """A fresh trace context: ``trace_id`` names the request's whole
    lifecycle (all incarnations), ``span`` the admission root span.  The
    request id seeds nothing — ids must stay unique across re-submits of
    the same request payload."""
    del request_id
    return {"trace_id": uuid.uuid4().hex[:16], "span": uuid.uuid4().hex[:8]}


class RequestTraceLog:
    """Bounded, thread-safe event list (host-side).  Events are Chrome
    ``traceEvents`` dicts with ABSOLUTE wall-clock microsecond ``ts`` so
    events recorded by different processes/incarnations align on one
    timeline without any clock exchange beyond NTP."""

    def __init__(self, capacity: int | None = None):
        if capacity is None:
            capacity = int(
                _config.env_get("RUSTPDE_REQTRACE_EVENTS", "16384") or 16384
            )
        self.capacity = max(64, int(capacity))
        self._events: list[dict] = []
        self._lock = threading.Lock()
        self.dropped = 0

    def record(
        self,
        trace_id: str,
        name: str,
        t0_wall: float,
        dur_s: float | None = None,
        args: dict | None = None,
    ) -> None:
        event = {
            "name": name,
            "ph": "X" if dur_s is not None else "i",
            "ts": round(t0_wall * 1e6, 1),
            "pid": _host_index(),
            "tid": 0,
            "args": {"trace_id": trace_id, **(args or {})},
        }
        if dur_s is not None:
            event["dur"] = round(dur_s * 1e6, 1)
        else:
            event["s"] = "g"
        with self._lock:
            if len(self._events) >= self.capacity:
                self.dropped += 1
                return
            self._events.append(event)

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def drain(self) -> list[dict]:
        with self._lock:
            out, self._events = self._events, []
            return out


#: process-wide log the serve scheduler records chunk spans into
LOG = RequestTraceLog()


def _host_index() -> int:
    try:
        import jax

        return int(jax.process_index())
    except Exception:
        return 0


# -- active-request binding (annotates spans + flight dumps) ------------------

_active: dict[int, str] = {}  # slot index -> trace_id
_active_lock = threading.Lock()


def bind_slots(mapping: dict) -> None:
    """Declare which trace ids are on the device right now (the scheduler
    rebinds at every chunk boundary); installs the span annotator so the
    flight recorder's dispatch/resolve/checkpoint spans carry them."""
    from . import tracing as _tr

    with _active_lock:
        _active.clear()
        _active.update({int(k): str(v) for k, v in mapping.items()})
        have = bool(_active)
    _tr.set_span_annotator(_annotate if (have and _ENABLED) else None)


def clear_active() -> None:
    bind_slots({})


def active_ids() -> list[str]:
    """The distinct active trace ids, sorted (stable for journal rows)."""
    with _active_lock:
        return sorted(set(_active.values()))


def _annotate() -> dict | None:
    ids = active_ids()
    return {"trace_ids": ids} if ids else None


def chunk_span(trace_id: str, t0_wall: float, dur_s: float, **args) -> None:
    """One slot's share of a campaign chunk (the scheduler's per-boundary
    record): a complete span on the request's own timeline."""
    if _ENABLED:
        LOG.record(trace_id, "chunk", t0_wall, dur_s, args or None)


def instant(trace_id: str, name: str, **args) -> None:
    if _ENABLED:
        LOG.record(trace_id, name, _time.time(), None, args or None)


# -- per-campaign gather + root write -----------------------------------------


def write_campaign_trace(run_dir: str, tag: str) -> str | None:
    """Drain every host's request-trace events for the closing campaign and
    (root only) write one Perfetto file under ``run_dir``.

    COLLECTIVE when recording is enabled: every host drains + allgathers
    together (the call sites are the campaign-close and drain paths, where
    the fleet is already aligned); the env-pinned :func:`enabled` flag is
    identical on every host, so the skip is aligned too.  Returns the
    written path on root, None elsewhere / when nothing was recorded."""
    if not _ENABLED:
        return None
    local = LOG.drain()
    from ..parallel import multihost

    blobs = multihost.allgather_bytes(json.dumps(local).encode("utf-8"))
    if not multihost.is_root():
        return None
    events: list[dict] = []
    for blob in blobs:
        try:
            events.extend(json.loads(blob.decode("utf-8")))
        except ValueError:
            continue
    if not events:
        return None
    # monotonic per-campaign-dir sequence: incarnations append, never clobber
    n = len(
        [f for f in _listdir(run_dir) if f.startswith("trace_") and f.endswith(".json")]
    )
    path = os.path.join(run_dir, f"trace_{n:04d}.json")
    payload = {
        "traceEvents": sorted(events, key=lambda e: e.get("ts", 0.0)),
        "displayTimeUnit": "ms",
        "otherData": {"campaign": tag, "hosts": len(blobs)},
    }
    try:
        os.makedirs(run_dir, exist_ok=True)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(payload, fh)
        os.replace(tmp, path)
    except OSError:
        return None  # trace IO must never kill the campaign
    return path


def _listdir(path: str) -> list[str]:
    try:
        return os.listdir(path)
    except OSError:
        return []


# -- request-timeline assembly (GET /requests/<id>/trace) ---------------------

#: journal lifecycle rows that belong on a request's timeline
_LIFECYCLE_EVENTS = (
    "request_admitted",
    "request_scheduled",
    "request_requeued",
    "request_retry",
    "request_failed",
    "request_done",
    "bucket_dt_adjust",
)

#: rows that OPEN a queued wait / a running phase (for derived "X" spans)
_QUEUE_OPENERS = ("request_admitted", "request_requeued", "bucket_dt_adjust")
_RUN_CLOSERS = (
    "request_done",
    "request_requeued",
    "request_retry",
    "request_failed",
    "bucket_dt_adjust",
)


def _journal_trace_id(journal: list, request_id: str) -> str | None:
    """The trace_id a request's journal rows carry (None: not journaled —
    the queue's lifecycle files are the fallback source)."""
    for rec in journal:
        if rec.get("id") == request_id and rec.get("trace_id"):
            return rec["trace_id"]
    return None


def _queue_trace_id(run_dir: str, request_id: str) -> str | None:
    qroot = os.path.join(run_dir, "queue")
    for state in ("running", "done", "failed", "queued"):
        sdir = os.path.join(qroot, state)
        for name in _listdir(sdir):
            if request_id not in name or not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(sdir, name), encoding="utf-8") as fh:
                    data = json.load(fh)
            except (OSError, ValueError):
                continue
            req = data.get("request", data)
            trace = req.get("trace") or {}
            if trace.get("trace_id"):
                return trace["trace_id"]
    return None


def assemble_request_trace(run_dir: str, request_id: str) -> dict | None:
    """One request's full lifecycle as a Perfetto ``traceEvents`` payload,
    reconstructed from durable state alone (journal + per-campaign trace
    files) — so it works across any number of process incarnations and
    after every in-memory recorder is gone.  None for an unknown request."""
    from ..utils.journal import read_journal

    # ONE journal parse serves both the trace-id lookup and the lifecycle
    # rows — the file is O(whole run) and this backs a per-request endpoint
    journal = read_journal(
        os.path.join(run_dir, "journal.jsonl"), on_error="skip"
    )
    tid = _journal_trace_id(journal, request_id) or _queue_trace_id(
        run_dir, request_id
    )
    if tid is None:
        return None
    rows = [
        r
        for r in journal
        if r.get("id") == request_id
        and r.get("event") in _LIFECYCLE_EVENTS
        and isinstance(r.get("t"), (int, float))
    ]
    rows.sort(key=lambda r: r["t"])
    events: list[dict] = []
    for r in rows:
        args = {
            k: v
            for k, v in r.items()
            if k not in ("event", "t", "wall_s") and _jsonable_scalar(v)
        }
        args["trace_id"] = tid
        events.append(
            {
                "name": r["event"],
                "ph": "i",
                "s": "g",
                "ts": round(r["t"] * 1e6, 1),
                "pid": 0,
                "tid": 0,
                "args": args,
            }
        )
    # derived phases: queued waits (admission/requeue -> next scheduled) and
    # running windows (scheduled -> next terminal/requeue row)
    for i, r in enumerate(rows):
        if r["event"] in _QUEUE_OPENERS:
            nxt = _next_of(rows, i, ("request_scheduled",))
            if nxt is not None:
                events.append(_phase("queued", tid, r["t"], nxt["t"]))
        elif r["event"] == "request_scheduled":
            nxt = _next_of(rows, i, _RUN_CLOSERS)
            if nxt is not None:
                events.append(_phase("running", tid, r["t"], nxt["t"]))
    # per-campaign chunk spans carrying this trace id
    campaigns = os.path.join(run_dir, "campaigns")
    for cdir in sorted(_listdir(campaigns)):
        full = os.path.join(campaigns, cdir)
        for name in sorted(_listdir(full)):
            if not (name.startswith("trace_") and name.endswith(".json")):
                continue
            try:
                with open(os.path.join(full, name), encoding="utf-8") as fh:
                    payload = json.load(fh)
            except (OSError, ValueError):
                continue
            for ev in payload.get("traceEvents", ()):
                if (ev.get("args") or {}).get("trace_id") == tid:
                    events.append(ev)
    if not events:
        return None
    t0 = min(e["ts"] for e in events)
    for e in events:
        e["ts"] = round(e["ts"] - t0, 1)
    events.sort(key=lambda e: e["ts"])
    incarnations = sum(1 for r in journal if r.get("event") == "server_start")
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "request_id": request_id,
            "trace_id": tid,
            "t0_unix": round(t0 / 1e6, 6),
            "incarnations": incarnations,
        },
    }


def _fleet_sources(run_dir: str) -> list[tuple[str, str, str]]:
    """Every journal-bearing lane of a fleet run as ``(lane, journal,
    campaigns_dir)`` triples: the root journal (single-replica runs /
    pre-fleet rows) plus one lane per ``replicas/<id>/`` subtree —
    replicas AND proxies, whoever journaled the request's rows."""
    sources = [
        (
            "root",
            os.path.join(run_dir, "journal.jsonl"),
            os.path.join(run_dir, "campaigns"),
        )
    ]
    rroot = os.path.join(run_dir, "replicas")
    for name in sorted(_listdir(rroot)):
        sub = os.path.join(rroot, name)
        if not os.path.isdir(sub):
            continue  # heartbeat files (<id>.json) live beside the dirs
        sources.append(
            (
                name,
                os.path.join(sub, "journal.jsonl"),
                os.path.join(sub, "campaigns"),
            )
        )
    return sources


def assemble_fleet_request_trace(run_dir: str, request_id: str) -> dict | None:
    """Cross-replica request timeline: one Perfetto payload stitching the
    rows every fleet process journaled about ``request_id`` — proxy
    admission, each replica's scheduled/requeued/done lifecycle, and the
    per-campaign chunk spans from whichever ``replicas/<rid>/campaigns``
    subtree ran it.  Each journal source gets its own Perfetto process
    lane (``pid``) named via metadata rows, so a request that migrated
    across replicas (lease break, preemption, autoscale retire) renders
    as a handoff between lanes.  None for an unknown request."""
    from ..utils.journal import read_journal

    sources = _fleet_sources(run_dir)
    journals = [
        (lane, read_journal(jpath, on_error="skip"), cdir)
        for lane, jpath, cdir in sources
    ]
    tid = None
    for _, journal, _ in journals:
        tid = _journal_trace_id(journal, request_id)
        if tid is not None:
            break
    tid = tid or _queue_trace_id(run_dir, request_id)
    if tid is None:
        return None
    events: list[dict] = []
    lanes: dict[int, str] = {}
    merged: list[tuple[int, dict]] = []  # (lane_pid, row) across sources
    for pid, (lane, journal, cdir) in enumerate(journals):
        rows = [
            r
            for r in journal
            if r.get("id") == request_id
            and r.get("event") in _LIFECYCLE_EVENTS
            and isinstance(r.get("t"), (int, float))
        ]
        chunk_events = []
        for sub in sorted(_listdir(cdir)):
            full = os.path.join(cdir, sub)
            for name in sorted(_listdir(full)):
                if not (name.startswith("trace_") and name.endswith(".json")):
                    continue
                try:
                    with open(
                        os.path.join(full, name), encoding="utf-8"
                    ) as fh:
                        payload = json.load(fh)
                except (OSError, ValueError):
                    continue
                for ev in payload.get("traceEvents", ()):
                    if (ev.get("args") or {}).get("trace_id") == tid:
                        chunk_events.append({**ev, "pid": pid})
        if not rows and not chunk_events:
            continue  # lane never touched this request: no empty track
        lanes[pid] = lane
        events.extend(chunk_events)
        for r in rows:
            merged.append((pid, r))
            args = {
                k: v
                for k, v in r.items()
                if k not in ("event", "t", "wall_s") and _jsonable_scalar(v)
            }
            args["trace_id"] = tid
            args["lane"] = lane
            events.append(
                {
                    "name": r["event"],
                    "ph": "i",
                    "s": "g",
                    "ts": round(r["t"] * 1e6, 1),
                    "pid": pid,
                    "tid": 0,
                    "args": args,
                }
            )
    # derived queued/running phases span lanes (admitted on a proxy,
    # scheduled on a replica): derive over the time-merged row sequence,
    # pin each phase to the lane of the row that OPENED it
    merged.sort(key=lambda pr: pr[1]["t"])
    mrows = [r for _, r in merged]
    for i, (pid, r) in enumerate(merged):
        if r["event"] in _QUEUE_OPENERS:
            nxt = _next_of(mrows, i, ("request_scheduled",))
            if nxt is not None:
                events.append({**_phase("queued", tid, r["t"], nxt["t"]), "pid": pid})
        elif r["event"] == "request_scheduled":
            nxt = _next_of(mrows, i, _RUN_CLOSERS)
            if nxt is not None:
                events.append({**_phase("running", tid, r["t"], nxt["t"]), "pid": pid})
    if not events:
        return None
    t0 = min(e["ts"] for e in events)
    for e in events:
        e["ts"] = round(e["ts"] - t0, 1)
    events.sort(key=lambda e: e["ts"])
    for pid, lane in sorted(lanes.items()):
        events.insert(
            0,
            {
                "name": "process_name",
                "ph": "M",
                "ts": 0.0,
                "pid": pid,
                "tid": 0,
                "args": {"name": lane},
            },
        )
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "request_id": request_id,
            "trace_id": tid,
            "t0_unix": round(t0 / 1e6, 6),
            "lanes": {str(p): n for p, n in sorted(lanes.items())},
        },
    }


def _phase(name: str, tid: str, t0: float, t1: float) -> dict:
    return {
        "name": name,
        "ph": "X",
        "ts": round(t0 * 1e6, 1),
        "dur": round(max(0.0, t1 - t0) * 1e6, 1),
        "pid": 0,
        "tid": 0,
        "args": {"trace_id": tid},
    }


def _next_of(rows: list, start: int, names: tuple) -> dict | None:
    for r in rows[start + 1 :]:
        if r["event"] in names:
            return r
    return None


def _jsonable_scalar(v) -> bool:
    return isinstance(v, (str, int, float, bool)) or v is None
