"""Compile & device attribution: where the fleet's non-stepping time goes.

The cold-start ROADMAP item needs numbers nobody records today: which
compat key paid how much build/jit wall, how often a key RE-compiled
(restart, elastic re-plan, dt re-bucket), and how long a request waits
between campaign open and the first committed chunk.  This module is the
recording half — the seams call in, the metrics registry carries the
labeled series, the journal gets one row per observation:

* :func:`observe_build` — wraps the model-build seam
  (``workloads.registry.build_model_for_key``): per-compat-key build wall
  time histogram + recompile counter (first build of a key in a process is
  a compile, every later one a RE-compile),
* :func:`observe_entry_compile` — wraps the jit-entry-point seam
  (``models.campaign._compile_entry_points``): per-model-kind lowering/jit
  wall, counted separately because dt-ladder re-jits re-enter it without a
  model rebuild,
* :func:`observe_first_chunk` — time-to-first-chunk per compat key (the
  scheduler stamps campaign open and the first committed chunk),
* :func:`update_device_memory_gauges` — live per-device memory watermarks
  from ``jax.local_devices()[i].memory_stats()`` where the backend exposes
  them (None-safe: CPU and the axon relay report nothing, the gauges just
  stay unset),
* :class:`ProfilerCapture` — the on-demand ``jax.profiler`` hook behind
  ``POST /profile?seconds=N`` (capped by ``RUSTPDE_PROFILE_MAX_S``), also
  fired as a ONE-SHOT when the ThroughputMonitor reports ``perf_degraded``
  (observability closing the loop on robustness: the capture of the slow
  window lands next to the journal row that flagged it).

Everything here is host-side bookkeeping around seams that already exist;
the bit-identical / ≤2% overhead telemetry contract is unchanged.
"""

from __future__ import annotations

import os
import threading
import time as _time

from .. import config as _config
from . import metrics as _tm

_builds: dict[str, int] = {}  # compat-key tag -> in-process build count
_last_walls: dict[str, float] = {}  # compat-key tag -> last build wall (phase="build")
_warm_pool: dict[str, int] = {"hit": 0, "miss": 0, "evict": 0, "aot": 0}
_lock = threading.Lock()


def key_tag(key) -> str:
    """The short stable label for a compat key — the same sha1-12 tag the
    scheduler's campaign directories use, so metrics, journal rows and
    on-disk campaign state all name a bucket identically."""
    import hashlib

    return hashlib.sha1(repr(tuple(key)).encode()).hexdigest()[:12]


def observe_build(key, wall_s: float, kind: str = "", phase: str = "build") -> dict:
    """Record one model build for a compat key; returns the journal-ready
    payload (the caller owns the journal, root-ness and all).

    ``phase`` disambiguates the layered observers around one campaign open —
    ``build`` (the registry's model construction, the only phase that bumps
    the per-key build/recompile accounting), ``entry_points`` (the
    scheduler's campaign-level remainder: ensemble wrap + arming, journaled
    so TTFC attribution SUMS across rows instead of double-counting the
    build wall ~2x), and ``aot`` (warm-pool ahead-of-time builds)."""
    tag = key_tag(key)
    if phase == "build":
        with _lock:
            _builds[tag] = _builds.get(tag, 0) + 1
            count = _builds[tag]
            _last_walls[tag] = wall_s
    else:
        with _lock:
            count = _builds.get(tag, 1)
    _tm.histogram(
        "compile_build_seconds",
        "model build + jit wall per compat key",
        key=tag,
        phase=phase,
    ).observe(wall_s)
    if phase == "build" and count > 1:
        _tm.counter(
            "compile_recompiles_total",
            "model rebuilds of an already-built compat key",
            key=tag,
        ).inc()
    return {
        "event": "compile_build",
        "key_tag": tag,
        "kind": kind,
        "phase": phase,
        "wall_s": round(wall_s, 4),
        "builds": count,
        "recompile": phase == "build" and count > 1,
    }


def build_counts() -> dict:
    """Per-key in-process build counts (tests + the bench payload)."""
    with _lock:
        return dict(_builds)


def last_build_wall(key) -> float:
    """The most recent phase="build" wall for a compat key (0.0 when the
    key never built in this process) — the scheduler subtracts it from its
    campaign-open window so the ``entry_points`` row carries only the
    remainder and the per-key rows sum to the true TTFC."""
    with _lock:
        return _last_walls.get(key_tag(key), 0.0)


def observe_warm_pool(event: str, key=None, k: int | None = None, **extra) -> dict:
    """Warm-pool accounting (serve/warmpool.py): ``event`` is one of
    ``hit`` / ``miss`` / ``evict`` / ``aot``; returns the journal-ready
    payload.  Counters ride the shared metrics registry so the bench and
    the hit-rate gates read one source of truth."""
    with _lock:
        _warm_pool[event] = _warm_pool.get(event, 0) + 1
    _tm.counter(
        "serve_warm_pool_events_total",
        "warm campaign pool events (hit/miss/evict/aot)",
        event=event,
    ).inc()
    payload = {
        "event": {
            "hit": "warm_pool_hit",
            "miss": "warm_pool_miss",
            "evict": "warm_pool_evict",
            "aot": "aot_build",
        }.get(event, f"warm_pool_{event}"),
    }
    if key is not None:
        payload["key_tag"] = key_tag(key)
    if k is not None:
        payload["k"] = int(k)
    payload.update(extra)
    return payload


def warm_pool_counts() -> dict:
    """Warm-pool event counts (tests + the bench payload), a copy."""
    with _lock:
        return dict(_warm_pool)


def observe_entry_compile(model_kind: str, wall_s: float) -> None:
    """One jit-entry-point compile (step/observables hoist+jit): re-entered
    by dt-ladder re-jits without a model rebuild, so counted separately."""
    _tm.histogram(
        "model_entry_compile_seconds",
        "entry-point hoist+jit wall per model kind",
        model=model_kind,
    ).observe(wall_s)
    _tm.counter(
        "model_entry_compiles_total",
        "entry-point compile passes per model kind",
        model=model_kind,
    ).inc()


def observe_first_chunk(key, wall_s: float) -> dict:
    """Time-to-first-chunk: campaign open (model build start) to the first
    committed chunk — the cold-start item's gate metric."""
    tag = key_tag(key)
    _tm.histogram(
        "serve_time_to_first_chunk_seconds",
        "campaign open to first committed chunk per compat key",
        key=tag,
    ).observe(wall_s)
    return {
        "event": "first_chunk",
        "key_tag": tag,
        "wall_s": round(wall_s, 4),
    }


# -- device memory watermarks --------------------------------------------------


def update_device_memory_gauges() -> int:
    """Refresh ``device_memory_bytes_in_use`` / ``device_memory_peak_bytes``
    per local device from the backend's memory stats; returns how many
    devices reported (0 on CPU / relay backends — None-safe by contract)."""
    from ..utils.profiling import device_memory_stats

    reported = 0
    for dev, stats in device_memory_stats().items():
        if not stats:
            continue
        reported += 1
        if "bytes_in_use" in stats:
            _tm.gauge(
                "device_memory_bytes_in_use",
                "live backend memory per device",
                device=dev,
            ).set(float(stats["bytes_in_use"]))
        peak = stats.get("peak_bytes_in_use")
        if peak is not None:
            _tm.gauge(
                "device_memory_peak_bytes",
                "peak backend memory watermark per device",
                device=dev,
            ).set(float(peak))
    return reported


# -- on-demand / auto jax.profiler capture ------------------------------------


class ProfilerCapture:
    """Bounded, single-flight ``jax.profiler`` capture.

    ``start(logdir, seconds)`` spawns a daemon thread that runs
    ``start_trace``/``stop_trace`` around a sleep; a second start while one
    is in flight is refused (409 shape at the HTTP layer).  Seconds are
    capped by ``RUSTPDE_PROFILE_MAX_S`` — a typo'd ``?seconds=86400`` must
    not pin the profiler for a day.  Injectable trace functions keep the
    unit tests off the real profiler."""

    def __init__(self, start_fn=None, stop_fn=None):
        self._lock = threading.Lock()
        self._busy = False
        self._start_fn = start_fn
        self._stop_fn = stop_fn
        self.captures = 0
        self.last: dict | None = None

    @property
    def busy(self) -> bool:
        return self._busy

    def max_seconds(self) -> float:
        return float(_config.env_get("RUSTPDE_PROFILE_MAX_S", "60") or 60.0)

    def start(self, logdir: str, seconds: float, reason: str = "manual") -> dict:
        """Begin a capture; returns the status payload (``started`` False
        carries the refusal reason)."""
        try:
            seconds = float(seconds)
        except (TypeError, ValueError):
            return {"started": False, "error": f"bad seconds {seconds!r}"}
        if seconds <= 0:
            return {"started": False, "error": "seconds must be positive"}
        seconds = min(seconds, self.max_seconds())
        with self._lock:
            if self._busy:
                return {"started": False, "error": "capture already running"}
            self._busy = True
        status = {
            "started": True,
            "dir": logdir,
            "seconds": seconds,
            "reason": reason,
        }
        self.last = status
        thread = threading.Thread(
            target=self._run,
            args=(logdir, seconds, status),
            name="profile-capture",
            daemon=True,
        )
        thread.start()
        return dict(status)

    def _run(self, logdir: str, seconds: float, status: dict) -> None:
        start = self._start_fn
        stop = self._stop_fn
        if start is None or stop is None:
            import jax

            start = start or jax.profiler.start_trace
            stop = stop or jax.profiler.stop_trace
        try:
            os.makedirs(logdir, exist_ok=True)
            start(logdir)
            try:
                _time.sleep(seconds)
            finally:
                stop()
            status["done"] = True
            self.captures += 1
            _tm.counter(
                "profiler_captures_total", "completed jax.profiler captures"
            ).inc()
        except Exception as exc:  # backend may refuse: recorded, never raised
            status["done"] = False
            status["error"] = f"{type(exc).__name__}: {exc}"
        finally:
            with self._lock:
                self._busy = False


#: process-wide capture the HTTP front and the perf_degraded hook share
CAPTURE = ProfilerCapture()

_degrade_fired = False


def capture_on_perf_degraded(run_dir: str) -> dict | None:
    """ONE-SHOT automatic capture when the SLO monitor reports a
    ``perf_degraded`` regression: the first event per process captures a
    short window into ``<run_dir>/profiles/degraded``; later events only
    count.  Returns the status payload on the firing call, else None."""
    global _degrade_fired
    if _degrade_fired or not _tm.enabled():
        return None
    try:
        import jax

        host = int(jax.process_index())
    except Exception:
        host = 0
    # per-host capture dir: the run_dir is shared across a multihost fleet
    logdir = os.path.join(run_dir, "profiles", f"degraded_h{host}")
    status = CAPTURE.start(
        logdir, min(2.0, CAPTURE.max_seconds()), reason="perf_degraded"
    )
    # the one-shot is spent only by a capture that actually STARTED — a
    # refusal (manual capture in flight) must leave the shot for the next
    # perf_degraded event, or the auto-profile is silently lost forever
    if status.get("started"):
        _degrade_fired = True
        return status
    return None
