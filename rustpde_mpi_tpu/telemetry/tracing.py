"""Flight-recorder tracing: Chrome/Perfetto trace events in a bounded ring.

The runner/scheduler hot seams are wrapped in :func:`span` context managers
(chunk dispatch/resolve, checkpoint stage/commit, serve settle/refill).
Every span lands in a process-wide ring buffer — the **flight recorder** —
whose contents are dumped as a ``traceEvents`` JSON file (loadable directly
in Perfetto / ``chrome://tracing``) when something goes wrong:

* a :class:`~rustpde_mpi_tpu.utils.resilience.DispatchHang` or
  :class:`~rustpde_mpi_tpu.utils.resilience.DivergenceError`,
* a SIGTERM/preemption drain,
* any other exception escaping a runner session, and unclean process exit
  while a session is armed (an ``atexit`` dump armed/disarmed per session),

so every incident ships with the timeline of its last few thousand events
instead of a bare traceback.  The ring bounds memory (default 4096 events,
``RUSTPDE_TRACE_EVENTS``); dumping never clears it.

Overhead contract: with tracing disabled (:func:`set_enabled` or
``RUSTPDE_TRACE=0``) :func:`span` returns a shared no-op context manager —
one function call and one branch (~ns, no allocation); enabled spans cost
two ``perf_counter`` reads and one deque append.  Spans wrap HOST-side
seams only and never add device work, so traced runs stay bit-identical
(CI-asserted together with the metrics layer)."""

from __future__ import annotations

import json
import os
import threading
import time as _time
from collections import deque
from .. import config as _config

# RUSTPDE_TELEMETRY=0 is the master kill switch; RUSTPDE_TRACE=0 turns off
# just the tracing half (metrics keep recording)
_ENABLED = (
    _config.env_get("RUSTPDE_TRACE", "1") != "0"
    and _config.env_get("RUSTPDE_TELEMETRY", "1") != "0"
)


def enabled() -> bool:
    return _ENABLED


def set_enabled(flag: bool) -> None:
    """Turn span recording on/off globally (``RUSTPDE_TRACE`` env default;
    the bench overhead gate toggles this together with the metrics flag)."""
    global _ENABLED
    _ENABLED = bool(flag)


class FlightRecorder:
    """Bounded ring of Chrome trace events (host-side, thread-safe).

    Events use the ``traceEvents`` JSON schema: complete spans (``ph=X``,
    microsecond ``ts``/``dur`` relative to recorder start) and instant
    markers (``ph=i``).  ``tid`` is a stable small integer per thread."""

    def __init__(self, capacity: int | None = None):
        if capacity is None:
            capacity = int(_config.env_get("RUSTPDE_TRACE_EVENTS", "4096") or 4096)
        self.capacity = max(16, int(capacity))
        self._events: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._t0 = _time.perf_counter()
        self._tids: dict[int, int] = {}
        self._pid = os.getpid()
        self.dumped = 0  # dump() calls (tests/ops counters)
        self._dump_seq = 0  # monotonic dump ids (alloc_seq, lock-held)

    def alloc_seq(self) -> int:
        """Allocate the next dump sequence number (lock-held: concurrent
        incident dumps — watchdog thread vs signal/atexit path — must not
        collide on one seq and overwrite each other's file)."""
        with self._lock:
            self._dump_seq += 1
            return self._dump_seq

    def _tid(self) -> int:
        ident = threading.get_ident()
        with self._lock:
            tid = self._tids.get(ident)
            if tid is None:
                tid = len(self._tids)
                self._tids[ident] = tid
            return tid

    def now_us(self) -> float:
        return (_time.perf_counter() - self._t0) * 1e6

    def add_complete(self, name: str, t0_us: float, dur_us: float, args=None) -> None:
        event = {
            "name": name,
            "ph": "X",
            "ts": round(t0_us, 3),
            "dur": round(dur_us, 3),
            "pid": self._pid,
            "tid": self._tid(),
        }
        if args:
            event["args"] = args
        with self._lock:
            self._events.append(event)

    def add_instant(self, name: str, args=None) -> None:
        event = {
            "name": name,
            "ph": "i",
            "s": "g",  # global-scope instant marker
            "ts": round(self.now_us(), 3),
            "pid": self._pid,
            "tid": self._tid(),
        }
        if args:
            event["args"] = args
        with self._lock:
            self._events.append(event)

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def dump(self, path: str, reason: str = "", extra: dict | None = None) -> str:
        """Write the ring as a Perfetto-loadable trace file (atomic tmp +
        replace; the ring is NOT cleared — later incidents still carry the
        shared history)."""
        payload = {
            "traceEvents": self.events(),
            "displayTimeUnit": "ms",
            "otherData": {
                "reason": reason,
                "pid": self._pid,
                "capacity": self.capacity,
                **(extra or {}),
            },
        }
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.{self._pid}.tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(payload, fh)
        os.replace(tmp, path)
        self.dumped += 1
        return path


#: process-wide recorder every span records into
RECORDER = FlightRecorder()

#: optional span-args annotator (telemetry/reqtrace.py installs one while
#: requests are bound to device slots): called once per completed span,
#: its dict — the active request trace ids — is merged into the span args,
#: so flight-recorder timelines and incident dumps are request-attributable
_ANNOTATOR = None


def set_span_annotator(fn) -> None:
    """Install/clear the span annotator (``fn() -> dict | None``); one
    global so the disabled path stays a single branch."""
    global _ANNOTATOR
    _ANNOTATOR = fn


class _Span:
    __slots__ = ("name", "args", "_t0")

    def __init__(self, name: str, args: dict | None):
        self.name = name
        self.args = args or None

    def __enter__(self):
        self._t0 = RECORDER.now_us()
        return self

    def __exit__(self, exc_type, exc, tb):
        args = self.args
        if exc_type is not None:
            args = dict(args or {})
            args["error"] = exc_type.__name__
        if _ANNOTATOR is not None:
            extra = _ANNOTATOR()
            if extra:
                args = dict(args or {})
                args.update(extra)
        RECORDER.add_complete(self.name, self._t0, RECORDER.now_us() - self._t0, args)
        return False


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NULL_SPAN = _NullSpan()


def span(name: str, **args):
    """Context manager recording one complete trace event; the shared
    no-op object when tracing is disabled (one branch, no allocation)."""
    if not _ENABLED:
        return _NULL_SPAN
    return _Span(name, args or None)


def instant(name: str, **args) -> None:
    """Record an instant marker (fault injected, rollback, drain)."""
    if _ENABLED:
        RECORDER.add_instant(name, args or None)


def dump_flight_record(
    run_dir: str, reason: str, step: int | None = None, extra: dict | None = None
) -> str | None:
    """Dump the flight recorder into ``run_dir`` as
    ``flight_<reason>[_stepN]_nSEQ.json``; best-effort (an incident dump
    must never mask the incident), returns the path or None.

    ``SEQ`` is a process-monotonic dump sequence number and the payload
    carries the active request trace ids (telemetry/reqtrace.py), so a
    chaos soak's pile of dumps sorts chronologically and each one names
    the requests that were on the device — attributable, not anonymous."""
    if not _ENABLED:
        return None
    from . import reqtrace as _reqtrace

    seq = RECORDER.alloc_seq()
    trace_ids = _reqtrace.active_ids()
    tag = reason.replace(" ", "_").replace("/", "_")
    name = (
        f"flight_{tag}"
        + (f"_step{step}" if step is not None else "")
        + f"_n{seq:04d}.json"
    )
    path = os.path.join(run_dir, name)
    try:
        info = dict(extra or {})
        if step is not None:
            info["step"] = step
        info["seq"] = seq
        if trace_ids:
            info["trace_ids"] = trace_ids
        return RECORDER.dump(path, reason=reason, extra=info)
    except OSError:
        return None


# -- unclean-exit arming -------------------------------------------------------

_exit_hooks: dict[int, tuple] = {}
_exit_lock = threading.Lock()
_exit_registered = False
_hook_seq = 0


def _run_exit_hooks() -> None:
    with _exit_lock:
        hooks = list(_exit_hooks.values())
        _exit_hooks.clear()
    for run_dir, step_fn in hooks:
        try:
            dump_flight_record(
                run_dir, "unclean_exit", step=step_fn() if step_fn else None
            )
        except Exception:
            pass


def arm_exit_dump(run_dir: str, step_fn=None):
    """Arm an ``atexit`` flight-record dump for an in-flight session: if the
    process exits while armed (sys.exit, un-handled exception past the
    session, interpreter teardown after SIGTERM default handling), the ring
    is dumped into ``run_dir`` with reason ``unclean_exit``.  Returns a
    disarm callable — the session's CLEAN exit path calls it, so normal
    completions leave no incident file."""
    global _exit_registered, _hook_seq
    with _exit_lock:
        if not _exit_registered:
            import atexit

            atexit.register(_run_exit_hooks)
            _exit_registered = True
        _hook_seq += 1
        token = _hook_seq
        _exit_hooks[token] = (run_dir, step_fn)

    def disarm() -> None:
        with _exit_lock:
            _exit_hooks.pop(token, None)

    return disarm
