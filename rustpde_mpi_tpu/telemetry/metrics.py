"""Live in-process metrics: labeled counters, gauges and log-bucketed
histograms behind a thread-safe registry.

The reference stack's only runtime visibility was printf-style interval
dumps (rustpde-mpi's per-interval info lines); this repo grew the same gap
at scale — the runner journals, the bench JSON and the serve ``/stats``
endpoint are all *post-hoc*.  This module is the live half: every layer
(runner, governor, io pipeline, serve scheduler) records into ONE default
registry, and the exporters (telemetry/exporters.py: Prometheus ``/metrics``
text + cadenced ``metrics.jsonl``) read it without touching the writers.

Design constraints, carried as CI gates (tests/test_telemetry.py and the
``governor129`` bench leg):

* **never touch traced programs** — metrics record host-side scalars the
  run already fetched (chunk statuses, journal fields, queue counts);
  instrumented runs are BIT-identical to ``RUSTPDE_TELEMETRY=0`` runs,
* **no sample retention** — histograms are log-bucketed (geometric bucket
  edges, ~10 buckets/decade by default), so percentiles are derivable from
  O(buckets) counters at any time while memory stays bounded regardless of
  observation count,
* **cheap when off** — :func:`set_enabled` (or ``RUSTPDE_TELEMETRY=0``)
  routes every handle lookup to a shared no-op metric; the overhead budget
  (metrics+tracing ON vs OFF within 2% wall) is bench-gated,
* **multihost** — each host owns a local registry;
  :func:`gather_global_snapshot` exchanges JSON-encoded snapshots over the
  existing ``multihost.allgather_host`` and merges them (counters and
  histograms sum; gauges keep per-host values), so root can export a
  fleet-wide view without a second collective transport.

The :class:`ThroughputMonitor` closes the loop from observability back to
robustness: a rolling steps/s baseline that reports a typed
``perf_degraded`` record when throughput regresses (the resilient runner
journals it — see README "Telemetry").
"""

from __future__ import annotations

import math
import threading
import time as _time
from .. import config as _config

_ENABLED = _config.env_get("RUSTPDE_TELEMETRY", "1") != "0"


def enabled() -> bool:
    """Is telemetry recording active (``RUSTPDE_TELEMETRY``, default on)?"""
    return _ENABLED


def set_enabled(flag: bool) -> None:
    """Turn metric recording on/off globally (the bench overhead gate's
    OFF leg and a kill switch for pathological environments).  Off routes
    every registry lookup to one shared no-op metric — existing handles
    held by callers keep working, they just came from an earlier lookup."""
    global _ENABLED
    _ENABLED = bool(flag)


class _NullMetric:
    """Shared do-nothing stand-in handed out while telemetry is disabled."""

    def inc(self, amount=1.0):
        pass

    def dec(self, amount=1.0):
        pass

    def set(self, value):
        pass

    def observe(self, value):
        pass

    @property
    def value(self):
        return 0.0


_NULL = _NullMetric()


class Counter:
    """Monotonically increasing float counter (Prometheus semantics)."""

    kind = "counter"

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counters only go up (inc {amount})")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value

    def to_dict(self) -> dict:
        return {"value": self._value}


class Gauge:
    """Point-in-time value (queue depth, current dt, slot utilization)."""

    kind = "gauge"

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        return self._value

    def to_dict(self) -> dict:
        return {"value": self._value}


class Histogram:
    """Log-bucketed histogram: percentiles without sample retention.

    Observations land in geometric buckets with edge ratio ``base`` (the
    default ``10**0.1`` ≈ 1.26 gives 10 buckets per decade, so any derived
    quantile carries at most ~26% relative error — plenty for latency/
    seconds telemetry while the storage stays a handful of integers however
    many observations arrive).  Non-positive observations land in a
    dedicated zero-bucket.  ``quantile(q)`` interpolates on the cumulative
    bucket counts and returns the (geometric) midpoint of the target
    bucket; ``buckets()`` yields Prometheus-style cumulative ``(le, n)``
    pairs."""

    kind = "histogram"

    def __init__(self, base: float = 10.0 ** 0.1):
        if base <= 1.0:
            raise ValueError(f"bucket ratio must exceed 1 (got {base})")
        self._lock = threading.Lock()
        self._base = float(base)
        self._log_base = math.log(self._base)
        self._counts: dict[int, int] = {}  # bucket index -> count
        self._zero = 0  # observations <= 0
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    def _index(self, value: float) -> int:
        # bucket i covers (base**(i-1), base**i]
        return int(math.ceil(math.log(value) / self._log_base - 1e-12))

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self.count += 1
            if not math.isfinite(value):
                # counted (the event happened) but kept OUT of sum/min/max:
                # one NaN/inf observation must not poison _sum — and every
                # rate()/avg query over it — for the process lifetime
                self._zero += 1
                return
            self.sum += value
            self.min = min(self.min, value)
            self.max = max(self.max, value)
            if value <= 0.0:
                self._zero += 1
            else:
                idx = self._index(value)
                self._counts[idx] = self._counts.get(idx, 0) + 1

    def buckets(self) -> list[tuple[float, int]]:
        """Cumulative ``(upper_edge, count)`` pairs, ascending (the
        Prometheus ``le`` series, +Inf omitted — it equals ``count``)."""
        with self._lock:
            items = sorted(self._counts.items())
            zero = self._zero
        out = []
        cum = zero
        if zero:
            out.append((0.0, zero))
        for idx, n in items:
            cum += n
            out.append((self._base ** idx, cum))
        return out

    def quantile(self, q: float) -> float:
        """Approximate q-quantile (0..1) from the bucket counts: the
        geometric midpoint of the bucket holding the target rank."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        with self._lock:
            total = self.count
            if total == 0:
                return float("nan")
            rank = q * total
            cum = self._zero
            if cum >= rank and self._zero:
                return 0.0
            for idx, n in sorted(self._counts.items()):
                cum += n
                if cum >= rank:
                    lo, hi = self._base ** (idx - 1), self._base ** idx
                    return math.sqrt(lo * hi)
            return self.max

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else float("nan")

    def to_dict(self) -> dict:
        with self._lock:
            counts = dict(self._counts)
            zero = self._zero
            count, total = self.count, self.sum
            mn = self.min if count else None
            mx = self.max if count else None
        d = {
            "count": count,
            "sum": total,
            "min": mn,
            "max": mx,
            "zero": zero,
            "base": self._base,
            "counts": {str(k): v for k, v in counts.items()},
        }
        if count:
            d.update(
                p50=self.quantile(0.5), p90=self.quantile(0.9),
                p99=self.quantile(0.99),
            )
        return d

    def merge_dict(self, other: dict) -> None:
        """Fold another histogram's ``to_dict`` payload in (multihost
        aggregation; bases must match — every host runs the same code)."""
        with self._lock:
            if abs(float(other.get("base", self._base)) - self._base) > 1e-12:
                raise ValueError("cannot merge histograms with different bases")
            for key, n in other.get("counts", {}).items():
                idx = int(key)
                self._counts[idx] = self._counts.get(idx, 0) + int(n)
            self._zero += int(other.get("zero", 0))
            self.count += int(other.get("count", 0))
            self.sum += float(other.get("sum", 0.0))
            if other.get("min") is not None:
                self.min = min(self.min, float(other["min"]))
            if other.get("max") is not None:
                self.max = max(self.max, float(other["max"]))


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class MetricsRegistry:
    """Thread-safe collection of named, labeled metrics.

    ``counter/gauge/histogram`` are get-or-create (idempotent: the same
    (name, labels) always returns the same handle, so callers need no
    module-level globals); a name registered as one kind cannot be reused
    as another.  ``snapshot()`` is a plain-JSON view of everything;
    ``delta(prev)`` subtracts a previous snapshot's counters/histogram
    counts — the cadenced jsonl exporter's rate view."""

    def __init__(self):
        self._lock = threading.Lock()
        # name -> (kind, {label_key: metric, ...}, help)
        self._families: dict[str, tuple[str, dict, str]] = {}

    def _get(self, cls, name: str, help: str, labels: dict):
        if not _ENABLED:
            return _NULL
        key = _label_key(labels)
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = (cls.kind, {}, help)
                self._families[name] = fam
            kind, series, _ = fam
            if kind != cls.kind:
                raise ValueError(
                    f"metric {name!r} already registered as a {kind}, "
                    f"requested {cls.kind}"
                )
            metric = series.get(key)
            if metric is None:
                metric = cls()
                series[key] = metric
            return metric

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        return self._get(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        return self._get(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "", **labels) -> Histogram:
        return self._get(Histogram, name, help, labels)

    def clear(self) -> None:
        """Drop every registered metric (tests; a fresh-process analogue)."""
        with self._lock:
            self._families.clear()

    def families(self) -> list[tuple[str, str, str, list]]:
        """``(name, kind, help, [(labels_dict, metric), ...])`` rows, name
        order — the exporters' iteration surface."""
        with self._lock:
            fams = {
                name: (kind, dict(series), help)
                for name, (kind, series, help) in self._families.items()
            }
        out = []
        for name in sorted(fams):
            kind, series, help = fams[name]
            rows = [
                (dict(key), metric) for key, metric in sorted(series.items())
            ]
            out.append((name, kind, help, rows))
        return out

    def snapshot(self) -> dict:
        """Plain-JSON view: ``{name: {"kind", "help", "series": [
        {"labels": {...}, ...metric fields...}]}}``."""
        snap = {}
        for name, kind, help, rows in self.families():
            snap[name] = {
                "kind": kind,
                "help": help,
                "series": [
                    {"labels": labels, **metric.to_dict()}
                    for labels, metric in rows
                ],
            }
        return snap

    def delta(self, prev: dict) -> dict:
        """Current snapshot minus ``prev`` for the cumulative kinds
        (counter values and histogram count/sum); gauges pass through as
        point-in-time values.  Series absent from ``prev`` report their
        full value."""
        cur = self.snapshot()
        out = {}
        for name, fam in cur.items():
            pseries = {}
            if name in prev and prev[name].get("kind") == fam["kind"]:
                for s in prev[name].get("series", []):
                    pseries[_label_key(s.get("labels", {}))] = s
            rows = []
            for s in fam["series"]:
                p = pseries.get(_label_key(s.get("labels", {})))
                row = dict(s)
                if p is not None:
                    if fam["kind"] == "counter":
                        row["value"] = s["value"] - p.get("value", 0.0)
                    elif fam["kind"] == "histogram":
                        row["count"] = s["count"] - p.get("count", 0)
                        row["sum"] = s["sum"] - p.get("sum", 0.0)
                rows.append(row)
            out[name] = {**fam, "series": rows}
        return out


#: the process-wide default registry every instrumented layer records into
REGISTRY = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return REGISTRY


def counter(name: str, help: str = "", **labels) -> Counter:
    return REGISTRY.counter(name, help, **labels)


def gauge(name: str, help: str = "", **labels) -> Gauge:
    return REGISTRY.gauge(name, help, **labels)


def histogram(name: str, help: str = "", **labels) -> Histogram:
    return REGISTRY.histogram(name, help, **labels)


def snapshot() -> dict:
    return REGISTRY.snapshot()


# -- multihost aggregation ----------------------------------------------------


def merge_snapshots(snaps: list[dict]) -> dict:
    """Merge per-host snapshots into one fleet view: counters sum,
    histograms merge bucket-wise, gauges keep per-host values (labeled
    ``host=<i>`` when hosts disagree; a single shared value stays plain).
    Used by :func:`gather_global_snapshot`; host order is rank order."""
    if not snaps:
        return {}
    if len(snaps) == 1:
        return snaps[0]
    out: dict = {}
    for host, snap in enumerate(snaps):
        for name, fam in snap.items():
            tgt = out.setdefault(
                name, {"kind": fam["kind"], "help": fam.get("help", ""),
                       "series": []}
            )
            index = {
                _label_key(s.get("labels", {})): s for s in tgt["series"]
            }
            for s in fam.get("series", []):
                labels = dict(s.get("labels", {}))
                if fam["kind"] == "gauge" and len(snaps) > 1:
                    labels["host"] = str(host)
                key = _label_key(labels)
                cur = index.get(key)
                if cur is None:
                    row = dict(s)
                    row["labels"] = labels
                    tgt["series"].append(row)
                    index[key] = row
                elif fam["kind"] == "counter":
                    cur["value"] = cur.get("value", 0.0) + s.get("value", 0.0)
                elif fam["kind"] == "histogram":
                    h = Histogram(base=float(cur.get("base", 10.0 ** 0.1)))
                    h.merge_dict(cur)
                    h.merge_dict(s)
                    merged = h.to_dict()
                    merged["labels"] = cur["labels"]
                    cur.clear()
                    cur.update(merged)
    return out


def gather_global_snapshot(registry: MetricsRegistry | None = None) -> dict:
    """Root-aggregated fleet snapshot: each host JSON-encodes its local
    registry snapshot, the blobs ride ``multihost.allgather_bytes`` (the
    shared variable-length-blob primitive the request-trace gather uses
    too), and every host merges the stack identically.  On a single
    process this is exactly the local snapshot."""
    import json

    reg = registry if registry is not None else REGISTRY
    local = reg.snapshot()
    try:
        import jax

        multi = jax.process_count() > 1
    except Exception:
        multi = False
    if not multi:
        return local
    from ..parallel import multihost

    blobs = multihost.allgather_bytes(json.dumps(local).encode("utf-8"))
    snaps = [json.loads(blob.decode("utf-8")) for blob in blobs]
    return merge_snapshots(snaps)


# -- the SLO loop-closer ------------------------------------------------------


class ThroughputMonitor:
    """Rolling steps/s baseline with a typed degradation verdict — the
    piece that turns the observability layer back into a robustness
    signal: the resilient runner feeds it the committed step count at each
    chunk boundary and journals a ``perf_degraded`` event whenever the
    boundary-to-boundary rate falls below ``tolerance`` of the rolling
    median baseline.

    * ``window`` — boundaries in the rolling baseline (median of the last
      N rates, so one slow boundary cannot poison the baseline),
    * ``warmup`` — boundaries ignored before any verdict (compile /
      cache-warm boundaries are legitimately slow),
    * ``tolerance`` — degraded when ``rate < tolerance * baseline``,
    * ``min_interval_s`` — report at most one event per interval (a
      sustained regression journals a heartbeat, not a line per chunk),
    * ``clock`` — injectable time source (tests).
    """

    def __init__(
        self,
        window: int = 16,
        warmup: int = 3,
        tolerance: float = 0.5,
        min_interval_s: float = 30.0,
        clock=_time.monotonic,
    ):
        from collections import deque

        self.window = int(window)
        self.warmup = int(warmup)
        self.tolerance = float(tolerance)
        self.min_interval_s = float(min_interval_s)
        self._clock = clock
        self._rates = deque(maxlen=self.window)
        self._seen = 0
        self._last_t: float | None = None
        self._last_report: float = -math.inf
        self.baseline: float | None = None
        self.events = 0

    def record(self, steps: int) -> dict | None:
        """One chunk boundary: ``steps`` committed since the previous call.
        Returns a ``perf_degraded`` payload (rate, baseline, ratio) when
        the regression fires, else None."""
        now = self._clock()
        last, self._last_t = self._last_t, now
        if last is None or steps <= 0:
            return None
        elapsed = now - last
        if elapsed <= 0:
            return None
        rate = steps / elapsed
        self._seen += 1
        verdict = None
        if (
            self._seen > self.warmup
            and self.baseline
            and rate < self.tolerance * self.baseline
            and now - self._last_report >= self.min_interval_s
        ):
            self._last_report = now
            self.events += 1
            verdict = {
                "steps_per_sec": round(rate, 3),
                "baseline_steps_per_sec": round(self.baseline, 3),
                "ratio": round(rate / self.baseline, 4),
                "tolerance": self.tolerance,
            }
        self._rates.append(rate)
        if self._seen >= self.warmup:
            ordered = sorted(self._rates)
            self.baseline = ordered[len(ordered) // 2]
        gauge("runner_steps_per_sec", "committed steps/s at chunk boundaries").set(rate)
        return verdict
