"""Telemetry subsystem: live metrics, Prometheus/jsonl export, flight-recorder
tracing.

The observability layer wired through every other subsystem (runner,
governor, io pipeline, serve scheduler — see README "Telemetry"):

* :mod:`.metrics` — thread-safe registry of labeled counters / gauges /
  log-bucketed histograms (percentiles without sample retention), snapshot/
  delta views, multihost root aggregation, and the :class:`ThroughputMonitor`
  SLO baseline behind the journal's ``perf_degraded`` event,
* :mod:`.exporters` — Prometheus text exposition (served from
  ``GET /metrics`` on the HTTP front) + the cadenced ``metrics.jsonl``
  run-dir dump for headless runs,
* :mod:`.tracing` — ~ns-overhead-when-disabled ``span()`` API feeding a
  bounded flight recorder, auto-dumped as Perfetto ``traceEvents`` JSON on
  DispatchHang / DivergenceError / SIGTERM drain / unclean exit.

Hard contract (CI + bench gated): telemetry records host-side values the
run already computed — it never touches traced programs, instrumented runs
are bit-identical to ``RUSTPDE_TELEMETRY=0`` runs, and the combined
metrics+tracing overhead stays within the ``governor129`` 2% wall gate.
"""

from .exporters import (  # noqa: F401
    PROMETHEUS_CONTENT_TYPE,
    MetricsDumper,
    prometheus_text,
    read_metrics_jsonl,
)
from .metrics import (  # noqa: F401
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    ThroughputMonitor,
    counter,
    default_registry,
    gather_global_snapshot,
    gauge,
    histogram,
    merge_snapshots,
    snapshot,
)
from .metrics import enabled as metrics_enabled  # noqa: F401
from .metrics import set_enabled as set_metrics_enabled  # noqa: F401
from .tracing import (  # noqa: F401
    RECORDER,
    FlightRecorder,
    arm_exit_dump,
    dump_flight_record,
    instant,
    span,
)
from .tracing import enabled as tracing_enabled  # noqa: F401
from .tracing import set_enabled as set_tracing_enabled  # noqa: F401
from . import compile_log  # noqa: F401
from . import reqtrace  # noqa: F401
from .reqtrace import assemble_request_trace  # noqa: F401
from .reqtrace import mint as mint_trace_context  # noqa: F401
from .reqtrace import enabled as reqtrace_enabled  # noqa: F401
from .reqtrace import set_enabled as set_reqtrace_enabled  # noqa: F401


def set_enabled(flag: bool) -> None:
    """Master switch: metrics AND tracing AND request tracing together
    (the bench gate's OFF leg; ``RUSTPDE_TELEMETRY=0`` / ``RUSTPDE_TRACE=0``
    / ``RUSTPDE_REQTRACE=0`` set the per-layer defaults at import)."""
    set_metrics_enabled(flag)
    set_tracing_enabled(flag)
    set_reqtrace_enabled(flag)


def enabled() -> bool:
    """True when any layer records."""
    return metrics_enabled() or tracing_enabled() or reqtrace_enabled()
