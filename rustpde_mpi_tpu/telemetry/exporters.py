"""Metric exporters: Prometheus text exposition + cadenced ``metrics.jsonl``.

Two read paths over the live registry (telemetry/metrics.py), chosen by how
the run is operated:

* **served** — ``GET /metrics`` on the serve layer's
  :class:`~rustpde_mpi_tpu.serve.http_front.HttpFront` renders
  :func:`prometheus_text` (exposition format 0.0.4: ``# HELP``/``# TYPE``
  comments, labeled samples, cumulative histogram ``le`` buckets with
  ``+Inf``/``_sum``/``_count``) — point any Prometheus scraper at it,
* **headless** — the resilient runner drops a :class:`MetricsDumper` into
  its ``run_dir``: one JSON line per cadence tick (default 60 s,
  ``RUSTPDE_METRICS_DUMP_S``) carrying the full registry snapshot plus the
  delta since the previous line, force-flushed at run end — so a batch
  campaign's live metrics land next to its journal without any server.
"""

from __future__ import annotations

import json
import os
import time as _time

from . import metrics as _metrics
from .. import config as _config

#: Content-Type of the Prometheus text exposition format
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _fmt_value(v: float) -> str:
    if v != v:  # NaN
        return "NaN"
    if v in (float("inf"), float("-inf")):
        return "+Inf" if v > 0 else "-Inf"
    if float(v) == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape(v) -> str:
    return (
        str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _fmt_labels(labels: dict) -> str:
    if not labels:
        return ""
    body = ",".join(f'{k}="{_escape(v)}"' for k, v in sorted(labels.items()))
    return "{" + body + "}"


def prometheus_text(registry=None) -> str:
    """Render a registry in the Prometheus text exposition format (0.0.4).

    Counters/gauges emit one sample per label set; histograms emit the
    cumulative ``<name>_bucket{le=...}`` series (log-bucket upper edges +
    ``+Inf``) plus ``<name>_sum`` / ``<name>_count`` — exactly what
    ``histogram_quantile()`` consumes server-side."""
    reg = registry if registry is not None else _metrics.default_registry()
    lines: list[str] = []
    for name, kind, help, rows in reg.families():
        if help:
            lines.append(f"# HELP {name} {help}")
        lines.append(f"# TYPE {name} {kind}")
        for labels, metric in rows:
            if kind in ("counter", "gauge"):
                lines.append(f"{name}{_fmt_labels(labels)} {_fmt_value(metric.value)}")
            elif kind == "histogram":
                for le, count in metric.buckets():
                    bl = dict(labels, le=_fmt_value(le))
                    lines.append(f"{name}_bucket{_fmt_labels(bl)} {count}")
                bl = dict(labels, le="+Inf")
                lines.append(f"{name}_bucket{_fmt_labels(bl)} {metric.count}")
                lines.append(f"{name}_sum{_fmt_labels(labels)} {_fmt_value(metric.sum)}")
                lines.append(f"{name}_count{_fmt_labels(labels)} {metric.count}")
    return "\n".join(lines) + ("\n" if lines else "")


def _host_suffixed(path: str) -> str:
    """Non-root processes of a multihost run get a ``.p<rank>`` suffix
    before the extension (``metrics.jsonl`` -> ``metrics.p1.jsonl``): the
    run_dir is SHARED across hosts, so every host appending the same path
    would interleave torn lines into one file.  Root and single-process
    runs keep the plain name — every existing reader is unchanged."""
    try:
        import jax

        if jax.process_count() > 1 and jax.process_index() != 0:
            root, ext = os.path.splitext(path)
            return f"{root}.p{jax.process_index()}{ext}"
    except Exception:
        pass
    return path


class MetricsDumper:
    """Cadenced ``metrics.jsonl`` writer for headless runs.

    ``maybe_dump()`` is called from chunk boundaries (already host-side
    control flow) and appends one line at most every ``every_s`` seconds:
    ``{"t", "wall_s", "step", "snapshot", "delta"}`` where ``delta`` is
    the registry delta since this dumper's previous line (rates without a
    scrape server).  ``dump(force=True)`` flushes unconditionally (run
    end, drain).  Append-only + line-buffered: a SIGKILL tears at most the
    line in flight, like the journal."""

    def __init__(
        self,
        path: str,
        every_s: float | None = None,
        registry=None,
    ):
        if every_s is None:
            env = _config.env_get("RUSTPDE_METRICS_DUMP_S", "")
            every_s = float(env) if env else 60.0
        self.path = _host_suffixed(path)
        self.every_s = float(every_s)
        self.registry = registry if registry is not None else _metrics.default_registry()
        self._t0 = _time.monotonic()
        self._last_dump: float | None = None
        self._prev_snapshot: dict = {}
        self.dumps = 0

    def maybe_dump(self, step: int | None = None) -> bool:
        """Dump when the cadence elapsed (the first call only arms the
        clock — an empty registry line at t=0 is noise)."""
        now = _time.monotonic()
        if self._last_dump is None:
            self._last_dump = now
            return False
        if now - self._last_dump < self.every_s:
            return False
        return self.dump(step=step)

    def dump(self, step: int | None = None, force: bool = True) -> bool:
        del force  # signature symmetry with maybe_dump
        if not _metrics.enabled():
            return False
        snap = self.registry.snapshot()
        record = {
            "t": _time.time(),
            "wall_s": round(_time.monotonic() - self._t0, 3),
            "step": step,
            "snapshot": snap,
            "delta": self.registry.delta(self._prev_snapshot),
        }
        try:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            with open(self.path, "a", encoding="utf-8") as fh:
                fh.write(json.dumps(record) + "\n")
        except OSError:
            return False  # metrics IO must never kill the run
        self._prev_snapshot = snap
        self._last_dump = _time.monotonic()
        self.dumps += 1
        return True


def read_metrics_jsonl(path: str) -> list[dict]:
    """Best-effort reader for ``metrics.jsonl`` (torn trailing line from a
    SIGKILL mid-append is skipped, like the journal reader)."""
    records = []
    try:
        with open(path, encoding="utf-8") as fh:
            lines = fh.read().splitlines()
    except OSError:
        return []
    for i, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            records.append(json.loads(line))
        except ValueError:
            if i == len(lines) - 1:
                continue  # torn tail
            raise
    return records
