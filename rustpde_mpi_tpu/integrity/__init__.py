"""End-to-end integrity: silent-data-corruption defense.

Every failure mode the stack survives elsewhere is *loud* — NaNs, CFL
blowups, crashes, collective desync.  The failure mode that corrupts
science quietly is silent data corruption from marginal cores and HBM bit
flips: finite-but-wrong state that sails past every sentinel and gets
journaled as a healthy done-record ("Cores that don't count", Hochschild
et al., HotOS '21).  This package is the detection + containment layer:

* :func:`digest_tree` / :func:`make_digest` — a cheap deterministic
  on-device fold over the spectral state (bitcast-to-uint32 XOR/add tree
  with positional mixing), compiled into the model's entry points like
  the stats engine and streamed with the observables futures.  The
  digest READS the state and never feeds back: trajectories are
  bit-identical integrity-on vs integrity-off.
* shadow re-execution audits (driven by the resilient runner): at a
  sampled cadence the just-completed chunk is re-dispatched from the
  retained chunk-start copy and the digests compared — deterministic XLA
  means bit-equal or corrupted.
* :class:`IntegrityError` — the typed containment raise, naming
  chunk/member/device.
* :class:`QuarantineLedger` — durable per-device strike ledger; repeated
  strikes journal ``device_quarantined`` and the serve scheduler
  re-carves sub-meshes around the device.
* :func:`flip_one_bit` — the deterministic bitflip fault injector's
  on-device mutation (``RUSTPDE_FAULT=bitflip@<step>``): finite,
  CFL-sane, invisible to every loud sentinel — caught only here.
"""

from .digest import digest_tree, flip_one_bit, flip_state_bit
from .errors import IntegrityError
from .ledger import QuarantineLedger

__all__ = [
    "digest_tree",
    "flip_one_bit",
    "flip_state_bit",
    "IntegrityError",
    "QuarantineLedger",
]
