"""Typed integrity failures (import-light: no jax)."""

from __future__ import annotations


class IntegrityError(RuntimeError):
    """A digest audit found corrupted state (or a verified checkpoint
    failed its restore recomputation) and in-memory containment was not
    possible.  Carries everything the containment layers key on: the
    audit check that tripped (``chain`` — the chunk-start digest does not
    match the previous boundary's streamed digest, i.e. the state was
    corrupted *at rest* between chunks; ``shadow`` — re-executing the
    chunk from its retained start copy yields a different digest, i.e.
    the corruption happened *inside* the chunk; ``checkpoint`` — a
    restored snapshot's recomputed digest does not match the manifest),
    the global step and chunk size, the localized ensemble member, and
    the device the serve scheduler should charge the strike to."""

    def __init__(self, message: str, *, check: str = "shadow",
                 step: int | None = None, chunk_steps: int | None = None,
                 member: int | None = None, device: str | None = None):
        super().__init__(message)
        self.check = check
        self.step = step
        self.chunk_steps = chunk_steps
        self.member = member
        self.device = device
