"""On-device state digests + the deterministic bitflip mutation.

The digest is a cheap fold over the bit patterns of every state leaf:
bitcast to uint32 words, positionally mixed (so transpositions and
offsetting paired flips cannot cancel in the commutative reductions),
then reduced by BOTH a wraparound sum and an XOR tree, combined with
Knuth multiplicative hashing.  Properties the integrity layer rests on:

* **deterministic** — integer arithmetic only, no rounding: the same
  state yields the same digest on every dispatch, layout, and shard
  partitioning (sum/xor are exact under reordering),
* **layout-invariant** — positions are LOGICAL indices (broadcasted
  iota), so a solo state, the same state as one vmapped ensemble member,
  and the same state pencil-sharded across a mesh all digest equal,
* **read-only** — a pure consumer of the state, like the sentinel
  reductions: trajectories are bit-identical digest-on vs digest-off,
* **single-bit sensitive** — any one flipped bit changes the XOR word
  and the positional mix, so the digest always moves.

This is an SDC *detector*, not a cryptographic MAC: an adversary could
collide it, a random upset practically cannot.

Everything here is traceable (jit / vmap / shard-safe); jax is imported
inside the functions so the module surface stays import-light.
"""

from __future__ import annotations

import numpy as np

#: 2^32 / golden ratio — Knuth's multiplicative-hash constant (odd, so
#: multiplication mod 2^32 is a bijection: no information is shed when
#: folding leaves/words together)
_GOLD = np.uint32(0x9E3779B1)
_KNUTH = np.uint32(2654435761)
#: FNV-1a offset basis — the fold seed
_SEED = np.uint32(0x811C9DC5)


def _leaf_digest(x):
    """uint32 digest of ONE array (any real/complex/bool/int dtype)."""
    import jax.numpy as jnp
    from jax import lax

    if jnp.issubdtype(x.dtype, jnp.complexfloating):
        return _leaf_digest(jnp.real(x)) * _GOLD + _leaf_digest(jnp.imag(x))
    if x.dtype == jnp.bool_:
        bits = x.astype(jnp.uint32)
    elif x.dtype.itemsize >= 4:
        # same- or double-width bitcast: f64/i64 gain a trailing dim of 2
        # uint32 words, f32/i32 map 1:1 — either way every payload bit
        # lands in exactly one word
        bits = lax.bitcast_convert_type(x, jnp.uint32)
    else:
        bits = lax.bitcast_convert_type(x, jnp.uint16).astype(jnp.uint32)
    if bits.ndim == 0:
        bits = bits[None]
    # positional mix: h(i0,...,ik) folds every logical index in, making
    # the otherwise-commutative reductions position-sensitive
    h = None
    for d in range(bits.ndim):
        i = lax.broadcasted_iota(jnp.uint32, bits.shape, d)
        h = i if h is None else h * jnp.uint32(1000003) + i
    mixed = bits ^ (h * _GOLD)
    axes = tuple(range(mixed.ndim))
    s = jnp.sum(mixed, dtype=jnp.uint32)
    xo = lax.reduce(mixed, jnp.uint32(0), lax.bitwise_xor, axes)
    return xo + s * _KNUTH


def digest_tree(state):
    """uint32 digest of a state pytree (scalar; ``(k,)`` under vmap).

    The per-leaf digests fold sequentially with a bijective multiplier,
    so the combined digest is order-sensitive across leaves (swapping
    velx/vely changes it) while each leaf's own reduction stays
    layout-invariant."""
    import jax
    import jax.numpy as jnp

    d = jnp.uint32(_SEED)
    for leaf in jax.tree_util.tree_leaves(state):
        d = d * _GOLD + _leaf_digest(jnp.asarray(leaf))
    return d


def default_flip_bit(dtype) -> int:
    """The mantissa MSB for the dtype's REAL component: flipping it is
    visibly wrong (O(1) relative error in that coefficient) yet provably
    finite — the exponent and sign are untouched, so no NaN/Inf can be
    minted and the CFL sentinel stays quiet."""
    real = np.empty(0, dtype).real.dtype
    return 51 if real.itemsize == 8 else 22


def flip_one_bit(arr, index: tuple, bit: int):
    """XOR one bit of one element (on device, bitcast — no rounding).

    ``index`` is a full multi-index into ``arr``; complex arrays flip in
    the real component.  Returns a new array (pure)."""
    import jax.numpy as jnp
    from jax import lax

    if jnp.issubdtype(arr.dtype, jnp.complexfloating):
        flipped = flip_one_bit(jnp.real(arr), index, bit)
        return lax.complex(flipped, jnp.imag(arr)).astype(arr.dtype)
    uint = jnp.uint64 if arr.dtype.itemsize == 8 else jnp.uint32
    bits = lax.bitcast_convert_type(arr, uint)
    bits = bits.at[index].set(bits[index] ^ uint(1 << bit))
    return lax.bitcast_convert_type(bits, arr.dtype)


def flip_state_bit(state, step: int, member: int | None = None,
                   col: int | None = None, bit: int | None = None):
    """Deterministically flip one spectral-coefficient bit in a state.

    The target leaf is ``temp`` (first field otherwise), the row is
    hashed from ``step`` (every process computes the same position, so a
    scoped injection stays a consistent collective), ``col`` pins the
    last (pencil) axis — the host-scope hook: the caller picks a column
    owned by the scoped host's devices — and ``member`` restricts the
    flip to one ensemble member's leading-axis slice.  Returns
    ``(new_state, info_dict)``."""
    name = "temp" if hasattr(state, "temp") else state._fields[0]
    arr = getattr(state, name)
    shape = arr.shape[1:] if member is not None else arr.shape
    n_last = int(shape[-1])
    c = int(col) if col is not None else int(step * 40503) % n_last
    idx = [int(step * int(_KNUTH)) % int(n) for n in shape[:-1]] + [c]
    if member is not None:
        idx = [int(member)] + idx
    if bit is None:
        bit = default_flip_bit(arr.dtype)
    flipped = flip_one_bit(arr, tuple(idx), int(bit))
    info = {"leaf": name, "index": tuple(idx), "bit": int(bit),
            "member": member}
    return state._replace(**{name: flipped}), info
