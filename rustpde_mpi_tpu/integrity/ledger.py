"""Durable per-device quarantine ledger (import-light: os/json/time).

One JSON file under the run dir records every integrity strike charged
to a device.  Strikes expire after ``strike_ttl_s`` (a transient upset
decays; sticky-bad silicon accumulates); a device whose LIVE strike
count reaches ``strikes`` is quarantined — the serve scheduler carves
sub-meshes around it, the fleet replica self-reports unhealthy, and the
journal carries ``device_quarantined``.

The file rides :func:`~rustpde_mpi_tpu.utils.fsutil.atomic_write_text`
(tmp + fsync + rename + dirsync) so a replica restart — or a sibling
replica scanning the shared run dir — always reads a consistent ledger.
"""

from __future__ import annotations

import json
import os
import time

from ..utils.fsutil import atomic_write_text

LEDGER_NAME = "quarantine.json"


class QuarantineLedger:
    """Strike/expiry bookkeeping for one run dir (device keys are plain
    strings — the scheduler uses ``<platform>:<device_id>@proc<p>``).

    ``clock`` is injectable for tests (defaults to ``time.time``)."""

    def __init__(self, run_dir: str, *, strikes: int = 2,
                 strike_ttl_s: float = 3600.0, clock=time.time):
        self.path = os.path.join(run_dir, LEDGER_NAME)
        self.strikes = int(strikes)
        self.strike_ttl_s = float(strike_ttl_s)
        self._clock = clock

    # -- persistence ---------------------------------------------------------

    def _load(self) -> dict:
        try:
            with open(self.path, encoding="utf-8") as fh:
                data = json.load(fh)
        except (OSError, ValueError):
            return {"strikes": {}, "quarantined": {}}
        data.setdefault("strikes", {})
        data.setdefault("quarantined", {})
        return data

    def _save(self, data: dict) -> None:
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        atomic_write_text(self.path, json.dumps(data, indent=1, sort_keys=True))

    # -- strikes -------------------------------------------------------------

    def _live(self, rows: list, now: float) -> list:
        ttl = self.strike_ttl_s
        return [r for r in rows if now - float(r.get("at", 0.0)) <= ttl]

    def strike(self, device: str, *, step: int | None = None,
               detail: str = "") -> bool:
        """Charge one strike; returns True when this strike NEWLY crosses
        the quarantine threshold (the caller journals
        ``device_quarantined`` and re-plans exactly once)."""
        now = float(self._clock())
        data = self._load()
        rows = self._live(data["strikes"].get(device, []), now)
        rows.append({"at": now, "step": step, "detail": detail})
        data["strikes"][device] = rows
        newly = False
        if len(rows) >= self.strikes and device not in data["quarantined"]:
            data["quarantined"][device] = {"at": now, "step": step,
                                           "strikes": len(rows)}
            newly = True
        self._save(data)
        return newly

    def strikes_for(self, device: str) -> int:
        """LIVE (unexpired) strikes currently charged to ``device``."""
        now = float(self._clock())
        return len(self._live(self._load()["strikes"].get(device, []), now))

    def quarantined(self) -> tuple:
        """Quarantined device keys, sorted (quarantine does not expire —
        releasing bad silicon back into the carve is a human decision:
        delete the ledger row)."""
        return tuple(sorted(self._load()["quarantined"]))

    def is_quarantined(self, device: str) -> bool:
        return device in self._load()["quarantined"]
