"""Field layer: data + transforms + solver-ingredient assembly.

TPU rebuild of the reference field layer (/root/reference/src/field.rs).
Unlike the reference's mutable ``FieldBase`` (v / vhat kept in sync by hand),
the JAX-native design treats the spectral coefficients ``vhat`` as the single
source of truth; physical values are computed on demand.  ``Field2`` is a
thin user-facing convenience — the jitted model step functions operate on raw
arrays.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from . import config
from .bases import BaseKind, Space2


def grid_deltas(x: np.ndarray, periodic: bool) -> np.ndarray:
    """Midpoint cell widths used for volumetric averages
    (/root/reference/src/field.rs:135-163)."""
    if periodic:
        return np.full(x.shape, x[2] - x[1])
    xs_left = np.concatenate([[x[0]], 0.5 * (x[1:] + x[:-1])])
    xs_right = np.concatenate([0.5 * (x[1:] + x[:-1]), [x[-1]]])
    return xs_right - xs_left


class Field2:
    """Two-dimensional field on a :class:`Space2`.

    Attributes mirror the reference vocabulary: ``v`` (physical), ``vhat``
    (spectral), ``x`` (coords), ``dx`` (grid deltas).  ``scale`` stretches
    the coordinates only — spectral operators receive scale explicitly, as in
    the reference (/root/reference/src/field.rs:93-100).
    """

    def __init__(self, space: Space2):
        self.space = space
        self.vhat = space.ndarray_spectral()
        self.x = [b.points.copy() for b in space.bases]
        self.dx = [
            grid_deltas(b.points, b.is_periodic) for b in space.bases
        ]

    def scale(self, scale):
        for i, s in enumerate(scale):
            self.x[i] = self.x[i] * s
            self.dx[i] = self.dx[i] * s

    # -- transforms ---------------------------------------------------------

    @property
    def v(self):
        return self.space.backward(self.vhat)

    @v.setter
    def v(self, values):
        # physical dtype is complex only for c2c x-bases
        dtype = (
            config.complex_dtype()
            if self.space.base_x.kind == BaseKind.FOURIER_C2C
            else config.real_dtype()
        )
        self.vhat = self.space.forward(jnp.asarray(values, dtype=dtype))

    def forward(self, v):
        self.vhat = self.space.forward(v)

    def backward(self):
        return self.space.backward(self.vhat)

    def to_ortho(self):
        return self.space.to_ortho(self.vhat)

    def from_ortho(self, c):
        self.vhat = self.space.from_ortho(c)

    def gradient(self, deriv, scale=None):
        return self.space.gradient(self.vhat, deriv, scale)

    # -- averages (volume-weighted, /root/reference/src/field/average.rs) ---

    def average_axis(self, axis: int):
        periodic = self.space.bases[axis].is_periodic
        return average_axis(self.v, self.x, self.dx, axis, periodic=periodic)

    def average(self):
        periodic = tuple(b.is_periodic for b in self.space.bases)
        return average(self.v, self.x, self.dx, periodic=periodic)

    # -- per-field HDF5 IO (reference ReadWrite trait,
    #    /root/reference/src/io/traits.rs:10-25, src/field/io.rs) -----------

    def write(self, filename: str, group: str) -> None:
        """Write this field as a ``{group}/{x,dx,y,dy,v,vhat}`` HDF5 group
        (create-or-append file semantics, like the reference)."""
        import h5py

        from .utils import checkpoint

        with h5py.File(filename, "a") as h5:
            checkpoint.write_field(h5, group, self.space, self.vhat, self.x, self.dx)

    def read(self, filename: str, group: str) -> None:
        """Restore spectral coefficients from a snapshot group (spectral
        interpolation on resolution mismatch, src/field/io.rs:74-83)."""
        import h5py

        from .utils import checkpoint

        with h5py.File(filename, "r") as h5:
            vhat = checkpoint.read_field_vhat(h5, group, self.space)
        self.vhat = jnp.asarray(vhat, dtype=self.space.spectral_dtype())


def _axis_length(x, dx, axis: int, periodic: bool) -> float:
    """Axis length for the average weight.  Deliberate fix over the reference
    (/root/reference/src/field/average.rs:28): a periodic axis spans a full
    period (|x[-1]-x[0]| + dx), so weights sum to 1 instead of n/(n-1)."""
    span = abs(float(x[axis][-1] - x[axis][0]))
    if periodic:
        span += float(dx[axis][0])
    return span


def average_weights(x: np.ndarray, periodic: bool) -> np.ndarray:
    """dx/L quadrature weights along one axis, summing to 1 (scale-invariant;
    the single home of the full-period periodic normalization)."""
    dx = grid_deltas(x, periodic)
    return dx / _axis_length([x], [dx], 0, periodic)


def average_axis(v, x, dx, axis: int, periodic: bool = False):
    """Volume-weighted average along ``axis`` (trapezoid-like dx weights)."""
    length = _axis_length(x, dx, axis, periodic)
    w = jnp.asarray(dx[axis] / length, dtype=v.dtype)
    shape = [1, 1]
    shape[axis] = w.shape[0]
    return jnp.sum(v * w.reshape(shape), axis=axis)


def average(v, x, dx, periodic: tuple[bool, bool] = (False, False)):
    """Full volume-weighted average."""
    ax = average_axis(v, x, dx, 0, periodic=periodic[0])
    length = _axis_length(x, dx, 1, periodic[1])
    w = jnp.asarray(dx[1] / length, dtype=v.dtype)
    return jnp.sum(ax * w)


def norm_l2(a) -> jnp.ndarray:
    """Frobenius norm matching the reference's norm_l2_f64/c64
    (/root/reference/src/navier_stokes/functions.rs:24-35)."""
    if jnp.iscomplexobj(a):
        return jnp.sqrt(jnp.sum(a.real**2 + a.imag**2))
    return jnp.sqrt(jnp.sum(a**2))


class Field1:
    """One-dimensional field on a :class:`~rustpde_mpi_tpu.bases.Space1`
    (reference ``Field1``, /root/reference/src/field.rs:59-72; used by the
    1-D Swift–Hohenberg example)."""

    def __init__(self, space):
        self.space = space
        self.vhat = space.ndarray_spectral()
        self.x = [space.base.points.copy()]
        self.dx = [grid_deltas(space.base.points, space.base.is_periodic)]

    def scale(self, scale):
        s = scale if isinstance(scale, (int, float)) else scale[0]
        self.x[0] = self.x[0] * s
        self.dx[0] = self.dx[0] * s

    @property
    def v(self):
        return self.space.backward(self.vhat)

    @v.setter
    def v(self, values):
        dtype = (
            config.complex_dtype()
            if self.space.base.kind == BaseKind.FOURIER_C2C
            else config.real_dtype()
        )
        self.vhat = self.space.forward(jnp.asarray(values, dtype=dtype))

    def forward(self, v):
        self.vhat = self.space.forward(v)

    def backward(self):
        return self.space.backward(self.vhat)

    def to_ortho(self):
        return self.space.to_ortho(self.vhat)

    def from_ortho(self, c):
        self.vhat = self.space.from_ortho(c)

    def gradient(self, deriv, scale=None):
        return self.space.gradient(self.vhat, deriv, scale)

    def average(self):
        periodic = self.space.base.is_periodic
        length = _axis_length(self.x, self.dx, 0, periodic)
        v = self.v
        w = jnp.asarray(self.dx[0] / length, dtype=v.dtype)
        return jnp.sum(v * w)
