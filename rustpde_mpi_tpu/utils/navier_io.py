"""Callback-side IO for the Navier models: snapshots, diagnostics, info.txt.

Rebuild of /root/reference/src/navier_stokes/navier_io.rs:84-149: write the
flow HDF5 snapshot (optionally throttled by ``write_intervall``), update and
persist statistics, print time / |div| / Nu / Nuvol / Re, and append a
``time nu nuvol re`` row to data/info.txt.

When the model carries an attached :class:`~rustpde_mpi_tpu.utils
.io_pipeline.IOPipeline` (``model.io_pipeline``, wired by the resilient
runner or set directly), the callback stops fencing the device queue:

* the flow snapshot is fetched to host here (the one sync the data needs)
  and serialized on the pipeline's background worker,
* the diagnostics line + info.txt row + in-memory ``diagnostics`` append
  are produced from an observable future and emitted once the values are
  ready — at most one save boundary late, in strict FIFO order, flushed
  completely at run end.

Without a pipeline the behavior is exactly the synchronous original.
"""

from __future__ import annotations

import os

from . import checkpoint


def _emit_info_line(model, t, vals, io_name: str, extra: str | None) -> None:
    """Print + persist one boundary's diagnostics (shared by the synchronous
    path and the pipeline's lagged emission)."""
    nu, nuvol, re, div = (float(v) for v in vals[:4])
    # an extended vocabulary (the passive-scalar sherwood) rides along by
    # name behind the conventional four — index 3 stays the NaN detector
    names = tuple(getattr(model, "observable_names", ()))[4:]
    extras = [(name, float(v)) for name, v in zip(names, vals[4:])]
    # in-memory diagnostics map — the hook the reference allocates but never
    # fills (/root/reference/src/navier_stokes/navier.rs:81)
    diag = getattr(model, "diagnostics", None)
    if diag is not None:
        rows = [("time", t), ("nu", nu), ("nuvol", nuvol), ("re", re), ("div", div)]
        for key, val in rows + extras:
            diag.setdefault(key, []).append(float(val))
    line = (
        f"time = {t:9.3f}      |div| = {div:4.2e}      "
        f"Nu = {nu:5.3e}      Nuv = {nuvol:5.3e}      Re = {re:5.3e}"
    )
    for name, val in extras:
        line += f"      {name.capitalize()} = {val:5.3e}"
    if extra:
        line += f"      {extra}"
    print(line)
    try:
        with open(io_name, "a", encoding="utf-8") as fh:
            fh.write(f"{t} {nu} {nuvol} {re}\n")
    except OSError as exc:
        print(f"unable to write {io_name}: {exc}")


def callback(
    model,
    flowname: str | None = None,
    io_name: str = "data/info.txt",
    suppress_io: bool = False,
    extra: str | None = None,
) -> None:
    t = model.get_time()
    dt = model.get_dt()
    os.makedirs("data", exist_ok=True)
    pipeline = getattr(model, "io_pipeline", None)

    # flow snapshot, throttled by write_intervall like the reference
    # (navier_io.rs:96-103)
    if flowname is None:
        flowname = f"data/flow{t:08.2f}.h5"
    write_intervall = getattr(model, "write_intervall", None)
    if write_intervall is None or (t + dt / 2.0) % write_intervall < dt:
        if pipeline is not None:
            # fetch now (the data is this boundary's), serialize off-thread;
            # flow writes stay never-fatal like the synchronous form
            snap = checkpoint.snapshot_to_host(model)

            def write_flow(snap=snap, flowname=flowname):
                try:
                    checkpoint.write_host_snapshot(snap, flowname)
                except OSError as exc:
                    print(f"unable to write {flowname}: {exc}")

            pipeline.submit_write(write_flow, flowname, nbytes=snap.nbytes)
        else:
            try:
                checkpoint.write_snapshot(model, flowname)
            except OSError as exc:  # never fatal, matching the reference
                print(f"unable to write {flowname}: {exc}")

    # statistics (navier_io.rs:105-121) — synchronous: the accumulation
    # itself consumes the state on the main thread either way
    stats = getattr(model, "statistics", None)
    if stats is not None:
        if (t + dt / 2.0) % stats.save_stat < dt:
            stats.update(model)
        if (t + dt / 2.0) % stats.write_stat < dt:
            try:
                stats.write("data/statistics.h5")
            except OSError as exc:
                # never fatal (reference semantics) but no longer silent: a
                # typed journal event + telemetry counter replace the
                # swallowed print (models/stats.report_stats_event)
                from ..models.stats import report_stats_event

                print(f"unable to write statistics: {exc}")
                report_stats_event(
                    model,
                    {
                        "event": "stats_write_failed",
                        "path": "data/statistics.h5",
                        "error": str(exc),
                    },
                )

    if suppress_io:
        return
    if pipeline is not None and hasattr(model, "get_observables_async"):
        fut = model.get_observables_async()

        def emit(vals, t=t):
            _emit_info_line(model, t, vals, io_name, extra)

        pipeline.push_diag(emit, fut)
        return
    _emit_info_line(model, t, model.get_observables(), io_name, extra)
