"""Profiling and benchmarking utilities.

Fills the reference's observability gap (SURVEY.md S5: wall-clock timing was
manual ``Instant`` prints, /root/reference/src/main.rs:27-33; no tracing):

* :func:`benchmark_steps` — the one honest way to time steps on the axon TPU
  (readback sync; ``block_until_ready`` alone measures dispatch).
* :class:`StepTimer` — lightweight per-chunk timing history a driver loop or
  callback can sample (the per-step timing API).
* :func:`trace` — ``jax.profiler`` trace context for XLA-level profiles.
* :func:`step_flops` / :func:`mfu_estimate` — XLA cost-analysis FLOPs of one
  model step (analytic GEMM-count fallback) and the resulting model-flops
  utilization against the chip's peak.
"""

from __future__ import annotations

import contextlib
import time

import numpy as np


def _sync(model) -> None:
    """Materialize one element on the host — the only reliable barrier
    through the axon TPU relay (see bench.py / SKILL.md gotcha)."""
    if hasattr(model, "state"):
        np.asarray(model.state.temp[:1, :1])
    else:  # models without .state (e.g. Swift-Hohenberg) expose .theta
        np.asarray(model.theta.ravel()[:1])


def benchmark_steps(model, steps: int, warmup: int | None = None, reps: int = 3) -> dict:
    """Slope-timed step rate.

    Times ``model.update_n`` at two window lengths (L = ``steps`` and 4L, both
    pre-compiled) and reports the slope ``(t_4L − t_L) / 3L`` — the per-step
    device time with the dispatch path's *fixed* per-call cost cancelled.  On
    the axon TPU relay that fixed cost is ~60–115 ms per dispatch, which a
    single-window measurement wrongly folds into the step time (a 64-step
    window under-reports a 3.16 ms/step model as ~5 ms/step — the round-3
    BENCH/BASELINE discrepancy).  Median of ``reps`` slope estimates; the
    fixed overhead is reported separately.

    Returns {steps_per_sec, ms_per_step, fixed_overhead_ms, elapsed_s,
    steps (timed window L), steps_total (all executed), slope_reps_ms}.
    """
    L = int(steps)
    L4 = 4 * L
    if warmup is None:
        warmup = L
    executed = 0
    if warmup:
        model.update_n(warmup)
        _sync(model)
        executed += warmup
    # compile/warm both window lengths before timing
    for n in (L, L4):
        model.update_n(n)
        _sync(model)
        executed += n
    slopes, fixeds = [], []
    t_all = time.perf_counter()
    for _ in range(max(1, reps)):
        t0 = time.perf_counter()
        model.update_n(L)
        _sync(model)
        t1 = time.perf_counter() - t0
        t0 = time.perf_counter()
        model.update_n(L4)
        _sync(model)
        t4 = time.perf_counter() - t0
        executed += L + L4
        slopes.append((t4 - t1) / (L4 - L))
        fixeds.append(t1 - L * slopes[-1])
    elapsed = time.perf_counter() - t_all
    slope = float(np.median(slopes))
    if slope <= 0:  # trivial model / timer noise: fall back to the naive rate
        slope = t4 / L4
    res = {
        "steps_per_sec": 1.0 / slope,
        "ms_per_step": 1e3 * slope,
        "fixed_overhead_ms": 1e3 * float(np.median(fixeds)),
        "elapsed_s": elapsed,
        "steps": L,
        "steps_total": executed,
        "slope_reps_ms": [round(1e3 * s, 4) for s in slopes],
    }
    # a batched ensemble (models/ensemble.py) advances K members per step:
    # aggregate member-steps/s is the number that compares against K solo
    # runs (its MFU comes from mfu_estimate, whose step FLOPs carry the K
    # factor through the vmapped jaxpr's batched dot_generals)
    k = int(getattr(model, "ensemble_size", 0) or 0)
    if k:
        res["ensemble_size"] = k
        res["member_steps_per_sec"] = k * res["steps_per_sec"]
        res["ms_per_member_step"] = res["ms_per_step"] / k
    return res


class StepTimer:
    """Rolling per-chunk step-rate history.

    Use from a driver loop:  ``timer.tick(n_steps)`` after each dispatch;
    ``timer.summary()`` gives mean/min/max steps/s over the recorded chunks.
    """

    def __init__(self):
        self.history: list[tuple[int, float]] = []  # (steps, seconds)
        self._last = time.perf_counter()

    def reset(self) -> None:
        self._last = time.perf_counter()

    def tick(self, steps: int) -> float:
        now = time.perf_counter()
        dt = now - self._last
        self._last = now
        self.history.append((steps, dt))
        return steps / dt if dt > 0 else float("inf")

    def summary(self) -> dict:
        if not self.history:
            return {"chunks": 0}
        rates = [s / t for s, t in self.history if t > 0]
        return {
            "chunks": len(self.history),
            "steps": sum(s for s, _ in self.history),
            "seconds": sum(t for _, t in self.history),
            "steps_per_sec_mean": float(np.mean(rates)),
            "steps_per_sec_min": float(np.min(rates)),
            "steps_per_sec_max": float(np.max(rates)),
        }


@contextlib.contextmanager
def trace(logdir: str = "/tmp/jax-trace"):
    """``jax.profiler`` trace context (view with TensorBoard/XProf/Perfetto).
    Falls back to a no-op if the backend cannot be traced (the axon relay
    does not export device traces)."""
    import jax

    started = False
    try:
        jax.profiler.start_trace(logdir)
        started = True
    except Exception as exc:  # relay backends may refuse
        print(f"profiler trace unavailable: {exc}")
    try:
        yield logdir
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
                print(f"profile written to {logdir}")
            except Exception as exc:
                print(f"profiler stop failed: {exc}")


def device_memory_stats() -> dict:
    """Per-local-device backend memory stats, None-safe by contract:
    ``{device_label: stats_dict_or_None}`` where ``stats_dict`` is whatever
    ``Device.memory_stats()`` reports (``bytes_in_use`` /
    ``peak_bytes_in_use`` on TPU/GPU) and None where the backend exposes
    nothing (CPU, the axon relay) — callers must treat a missing dict as
    "no data", never as zero.  The telemetry layer's device-memory
    watermark gauges (telemetry/compile_log.py) read through this one
    helper so the None-handling lives in one place."""
    out = {}
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        return out
    for dev in devices:
        label = f"{dev.platform}:{dev.id}"
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        out[label] = dict(stats) if stats else None
    return out


# ---------------------------------------------------------------------------
# FLOPs / MFU
# ---------------------------------------------------------------------------

# fp32 peak of the chip the tunnel exposes (TPU v5e: 197 TFLOP/s bf16; the
# package forces float32 matmuls via jax_default_matmul_precision=highest,
# which runs on the MXU at roughly 1/4 the bf16 rate).  Used only for the
# MFU *estimate* reported next to benchmark numbers.
PEAK_FLOPS = {
    "tpu_v5e_bf16": 197e12,
    "tpu_v5e_f32": 49e12,
    "cpu": 1e11,
}


#: analytic per-invocation flops of named Pallas kernels
#: (:func:`register_pallas_flops`): the jaxpr walk below sees a
#: ``pallas_call`` as ONE opaque eqn, so without this the MFU numbers
#: (serve_mfu gauge, bench rows) silently under-report on kernel paths.
#: Primary accounting recurses into the kernel jaxpr and multiplies by the
#: grid size (exact for GEMM kernels); the registry overrides by kernel
#: name for kernels whose body the walk cannot price (DMA/collective
#: kernels, recurrences whose flops are not dot_generals).
PALLAS_FLOPS: dict[str, float] = {}


def register_pallas_flops(name: str, flops: float) -> None:
    """Register the analytic flops of one invocation of the Pallas kernel
    dispatched under ``name`` (the ``pallas_call`` name) — kernels with
    shape-dependent cost should re-register at build time (last value
    wins; ops/pallas_conv.build_model_convs does)."""
    PALLAS_FLOPS[name] = float(flops)


def _pallas_eqn_flops(eqn) -> float:
    """Flops of one ``pallas_call`` eqn: registry by kernel name first, else
    the kernel-body dot count times the grid size."""
    import math

    name = getattr(eqn.params.get("name_and_src_info"), "name", None)
    if name in PALLAS_FLOPS:
        return PALLAS_FLOPS[name]
    grid_mapping = eqn.params.get("grid_mapping")
    grid = math.prod(getattr(grid_mapping, "grid", ()) or (1,))
    inner = eqn.params.get("jaxpr")
    if inner is not None and hasattr(inner, "eqns"):
        return grid * _jaxpr_dot_flops(inner)
    return 0.0


def _jaxpr_dot_flops(jaxpr) -> float:
    """Exact MXU flops of a jaxpr: walk every dot_general (recursing into
    scan/cond/pjit sub-jaxprs) and sum 2*batch*M*N*K from the operand
    shapes; ``pallas_call`` bodies are priced via :func:`_pallas_eqn_flops`
    (grid-scaled kernel dot count, registry override)."""
    import math

    total = 0.0
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "pallas_call":
            total += _pallas_eqn_flops(eqn)
            continue
        if eqn.primitive.name == "dot_general":
            a = eqn.invars[0].aval
            b = eqn.invars[1].aval
            (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
            k = math.prod(a.shape[i] for i in lc)
            batch = math.prod(a.shape[i] for i in lb)
            m = math.prod(
                d for i, d in enumerate(a.shape) if i not in lc and i not in lb
            )
            n = math.prod(
                d for i, d in enumerate(b.shape) if i not in rc and i not in rb
            )
            total += 2.0 * batch * m * n * k
        for val in eqn.params.values():
            vals = val if isinstance(val, (tuple, list)) else (val,)
            for v in vals:
                inner = getattr(v, "jaxpr", None)
                if inner is not None and hasattr(inner, "eqns"):
                    total += _jaxpr_dot_flops(inner)
                elif hasattr(v, "eqns"):
                    total += _jaxpr_dot_flops(v)
    return total


def step_flops(model, method: str = "auto") -> float | None:
    """FLOPs of one time step: XLA cost analysis when the backend exposes it,
    else an exact jaxpr-level dot_general count (the axon relay exposes no
    cost analysis; the dot count is exact for this GEMM-dominated workload
    and tracks every fold/fusion the layout actually executes), else the
    legacy analytic estimate.

    ``method="jaxpr"`` skips the cost-analysis pass (which COMPILES a fresh
    jit of the step) and goes straight to the trace-only dot count — the
    cheap form the serve scheduler's live MFU gauge uses per campaign."""
    import jax

    example = None
    if method == "jaxpr":
        try:
            example = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), model.state
            )
        except Exception:
            return _analytic_step_flops(model)
    else:
        try:
            example = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), model.state
            )
            lowered = jax.jit(model._make_step()).lower(example)
            cost = lowered.compile().cost_analysis()
            if isinstance(cost, (list, tuple)):  # newer jaxlib: one dict per device
                cost = cost[0] if cost else None
            if cost and cost.get("flops"):
                return float(cost["flops"])
        except Exception:
            pass
    try:
        closed = jax.make_jaxpr(model._make_step())(example)
        return _jaxpr_dot_flops(closed.jaxpr)
    except Exception:
        pass
    return _analytic_step_flops(model)


def _analytic_step_flops(model) -> float:
    """GEMM-count estimate for the dense-transform TPU path of one Navier2D
    step.  Per 2-D dense transform: 2 GEMMs = 2 * 2*n^3 flops at n x n.
    Counted per step (navier.py _make_step): 2 velocity backwards, 6
    convection gradient synth + 3 forwards, 3 implicit ADI solves (matvec +
    2 dense 1-D solves each ~ 3 GEMMs), Poisson fast-diag (4 GEMMs), plus
    elementwise O(n^2) terms (ignored)."""
    from ..ops.folded import folding_enabled

    nx, ny = model.nx, model.ny
    n = 0.5 * (nx + ny)
    gemms = (
        2 * 2  # velocity backwards
        + 6 * 2  # conv gradient backward_orthos
        + 3 * 2  # conv forwards
        + 3 * 3  # ADI solves (precond matvecs + inverse GEMMs)
        + 4  # fast-diag Poisson (parity-interleaved modal maps)
    )
    # folding factor from the matrices the model actually built: average the
    # per-matrix flops_factor over the transform pair of each variable space
    # (split-Fourier axes and mixed-BC bases report 1.0 or fold their own
    # way, so "hc"/periodic models are accounted correctly).  Sep-layout
    # spaces report the factors of their sep device matrices (same 0.5 GEMM
    # halving, measured from the actual impl blocks) — the natural-layout
    # cached matrices are never built there.
    factors = []
    for attr in ("temp_space", "velx_space", "field_space"):
        space = getattr(model, attr, None)
        if space is None:
            continue
        for axis, base in enumerate(getattr(space, "bases", ())):
            if getattr(space, "sep", (False, False))[axis]:
                cache = getattr(base, "_sep_cache", {})
                keys = ("fwd", "bwd") if cache else ()
                for key in keys:
                    fm = cache.get(key)
                    if fm is not None and hasattr(fm, "flops_factor"):
                        factors.append(fm.flops_factor)
                continue
            if not folding_enabled():
                factors.append(1.0)
                continue
            for mat_attr in ("_fwd_matrix", "_bwd_matrix", "_fwd_dev", "_bwd_dev"):
                try:
                    fm = getattr(base, mat_attr)
                except (ValueError, AttributeError):
                    continue
                if hasattr(fm, "flops_factor"):
                    factors.append(fm.flops_factor)
    factor = float(np.mean(factors)) if factors else (0.5 if folding_enabled() else 1.0)
    # an ensemble's step advances K members (the jaxpr paths above count this
    # via batched dot dims; the analytic estimate must scale explicitly)
    k = max(1, int(getattr(model, "ensemble_size", 1) or 1))
    return k * gemms * factor * 2.0 * n**3


def peak_flops_key(platform: str | None = None) -> str:
    """The :data:`PEAK_FLOPS` entry for a platform (default: the current
    backend) — ONE mapping shared by :func:`mfu_estimate` and the serve
    scheduler's live ``serve_mfu`` gauge, so a new platform/peak entry
    cannot silently diverge between them."""
    if platform is None:
        try:
            import jax

            platform = jax.devices()[0].platform
        except Exception:
            platform = "cpu"
    return "tpu_v5e_f32" if platform in ("tpu", "axon") else "cpu"


def mfu_estimate(model, steps_per_sec: float) -> dict:
    """Model-flops-utilization estimate: step FLOPs x rate / peak."""
    flops = step_flops(model)
    key = peak_flops_key()
    peak = PEAK_FLOPS[key]
    return {
        "flops_per_step": flops,
        "achieved_flops": flops * steps_per_sec,
        "peak_flops_assumed": peak,
        "peak_key": key,
        "mfu": flops * steps_per_sec / peak,
    }
