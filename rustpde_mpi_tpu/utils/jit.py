"""Constant-hoisting jit helper.

The spectral framework's jitted programs close over large dense operator
matrices (transforms, solver factorizations).  Tracing embeds those as HLO
literals, which (a) bloats the serialized program to O(n^2) per matrix —
~900 MB at 2049^2, more than the TPU compile service accepts — and (b)
re-uploads them on every recompile.  ``hoist_constants`` converts a closure
into an equivalent function taking the captured constants as explicit
device-resident arguments: trace once with ``make_jaxpr``, then replay the
jaxpr with ``eval_jaxpr`` feeding the constants as parameters.

(`jax.closure_convert` does NOT do this: it only hoists captured *tracers*,
leaving concrete arrays as embedded constants.)
"""

from __future__ import annotations

import jax
import jax.extend.core as jex_core
import jax.numpy as jnp


def run_scanned(step_n, state, n: int):
    """Advance ``n`` steps through ``step_n(state, bucket)`` in power-of-two
    buckets (plus a single 3-bucket size), so arbitrary ``n`` costs at most
    ~2*log2(n) distinct XLA compilations ever (a direct static-n scan would
    recompile for every new chunk length, e.g. the tail of an integrate
    interval).

    Buckets of size 1 are avoided (except ``n == 1`` itself): XLA fully
    inlines a ``length=1`` scan and re-fuses its body, which perturbs the
    result at the last bit relative to the loop-compiled ``length>=2`` form
    — an odd tail is dispatched as ``2+3`` instead of ``4+1`` so that two
    program variants sharing the step math (the plain and sentinel-armed
    chunks, models/navier.py) stay BIT-identical whenever their schedules
    agree."""
    for bucket in scan_buckets(n):
        state = step_n(state, bucket)
    return state


def scan_buckets(n: int) -> list:
    """The static bucket schedule :func:`run_scanned` dispatches for ``n``
    steps (in order).  Exposed so the warm pool can AOT-compile exactly the
    executables a ``chunk_steps``-sized dispatch will need — one source of
    truth for the decomposition."""
    out = []
    remaining = int(n)
    while remaining > 0:
        if remaining == 3:
            bucket = 3
        else:
            bucket = 1 << (remaining.bit_length() - 1)
            if bucket > 1 and remaining - bucket == 1:
                bucket //= 2  # leave a 3-tail instead of a 1-tail
        out.append(bucket)
        remaining -= bucket
    return out


def hoist_constants(fn, *example):
    """Return ``(converted, consts)`` where ``converted(consts, *args)``
    computes ``fn(*args)`` with every captured constant passed explicitly.

    ``example`` are abstract or concrete sample arguments (pytrees allowed).
    """
    closed, out_shape = jax.make_jaxpr(fn, return_shape=True)(*example)
    # device-resident, deduplicated by object identity
    seen: dict[int, int] = {}
    consts = []
    index = []
    for c in closed.consts:
        key = id(c)
        if key not in seen:
            seen[key] = len(consts)
            consts.append(jnp.asarray(c))
        index.append(seen[key])
    out_tree = jax.tree.structure(out_shape)

    def converted(consts, *args):
        flat_args, _ = jax.tree.flatten(args)
        expanded = [consts[i] for i in index]
        # jax.extend.core is the stable replay API (jax.core.eval_jaxpr is
        # deprecated); ClosedJaxpr accepts runtime tracers as consts, which is
        # exactly the hoisting trick
        replay = jex_core.jaxpr_as_fun(jex_core.ClosedJaxpr(closed.jaxpr, expanded))
        out_flat = replay(*flat_args)
        return jax.tree.unflatten(out_tree, out_flat)

    return converted, consts
