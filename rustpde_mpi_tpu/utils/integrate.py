"""Time-integration driver.

Rebuild of the reference's ``Integrate`` trait + ``integrate`` free function
(/root/reference/src/lib.rs:167-219).  The loop semantics (save-window test,
three stop criteria) are preserved; models may additionally advance many
steps per host round-trip via ``lax.scan`` inside their ``update`` (the
TPU-friendly path) — the driver only sees wall-clock-relevant boundaries.
"""

from __future__ import annotations

MAX_TIMESTEP = 10_000_000


class Integrate:
    """Duck-typed protocol: update(), get_time(), get_dt(), callback(), exit()."""

    def update(self) -> None:
        raise NotImplementedError

    def get_time(self) -> float:
        raise NotImplementedError

    def get_dt(self) -> float:
        raise NotImplementedError

    def callback(self) -> None:
        pass

    def exit(self) -> bool:
        return False


def integrate(pde, max_time: float, save_intervall: float | None = None) -> None:
    """Advance ``pde`` until ``max_time``; invoke ``pde.callback()`` whenever
    the time lands inside a half-dt window around a save interval."""
    timestep = 0
    eps_dt = pde.get_dt() * 1e-4
    while True:
        pde.update()
        timestep += 1

        if save_intervall is not None:
            t, dt = pde.get_time(), pde.get_dt()
            if (t % save_intervall) < dt / 2.0 or (t % save_intervall) > save_intervall - dt / 2.0:
                pde.callback()

        if pde.get_time() + eps_dt >= max_time:
            print(f"time limit reached: {pde.get_time()}")
            break
        if timestep >= MAX_TIMESTEP:
            print(f"timestep limit reached: {timestep}")
            break
        if pde.exit():
            print("break criteria triggered")
            break
