"""Time-integration driver.

Rebuild of the reference's ``Integrate`` trait + ``integrate`` free function
(/root/reference/src/lib.rs:167-219).  The loop semantics (save-window test,
three stop criteria) are preserved; models may additionally advance many
steps per host round-trip via ``lax.scan`` inside their ``update`` (the
TPU-friendly path) — the driver only sees wall-clock-relevant boundaries.

The driver returns a status string and accepts two hooks (both default to
the plain behavior) so a supervising harness — the resilient runner in
``utils/resilience.py`` — can wrap dispatches and act at chunk boundaries
without forking the loop:

* ``dispatch(pde, n)`` replaces the raw ``pde.update_n(n)`` / ``pde.update()``
  call (watchdog deadlines, fault injection),
* ``on_chunk(pde)`` runs after each chunk's callback/exit checks; returning
  truthy stops the loop with status ``"stopped"`` (checkpoint cadence,
  preemption signals).

Statuses: ``"time_limit"`` | ``"timestep_limit"`` | ``"break"`` (the model's
``exit()`` fired, e.g. NaN divergence) | ``"stopped"`` (``on_chunk`` asked).
"""

from __future__ import annotations

import math

MAX_TIMESTEP = 10_000_000


def _next_boundary(t: float, dt: float, save_intervall: float) -> float:
    """First absolute save boundary ``k * save_intervall`` strictly after
    ``t`` (half-dt tolerance, so a time that just landed on a boundary
    targets the following one).  Working with the integer boundary index
    keeps the save-window test exact at large ``t``, where the legacy
    ``t % save_intervall`` form has lost the float resolution to place a
    half-dt window reliably."""
    return (math.floor((t + dt / 2.0) / save_intervall) + 1) * save_intervall


class Integrate:
    """Duck-typed protocol: update(), get_time(), get_dt(), callback(), exit()."""

    def update(self) -> None:
        raise NotImplementedError

    def get_time(self) -> float:
        raise NotImplementedError

    def get_dt(self) -> float:
        raise NotImplementedError

    def callback(self) -> None:
        pass

    def exit(self) -> bool:
        return False


def integrate(
    pde,
    max_time: float,
    save_intervall: float | None = None,
    *,
    dispatch=None,
    on_chunk=None,
    overlap: bool | None = None,
) -> str:
    """Advance ``pde`` until ``max_time``; invoke ``pde.callback()`` whenever
    the time lands inside a half-dt window around a save interval.  Returns
    the stop status (module docstring).

    Models exposing ``update_n`` (the jitted ``lax.scan`` fast path) advance
    whole save intervals per device dispatch — essential on TPU where every
    dispatch crosses a host relay.  Stop criteria are then evaluated at
    interval boundaries instead of every step (same observable behavior: the
    reference only *acts* on them via prints/saves at those boundaries).

    Batched models degrade gracefully under this driver: a
    :class:`~rustpde_mpi_tpu.models.ensemble.NavierEnsemble` freezes
    individual diverged members inside its chunked step (per-member finite
    mask) and its ``exit()`` fires only once EVERY member is dead, so the
    loop keeps advancing the surviving members.

    ``overlap`` (chunked path only) opts into **dispatch double-buffering**:
    the per-boundary break check rides an ``exit_future`` instead of a
    blocking ``pde.exit()``, so the next chunk is enqueued before the
    previous one's break flag is fetched — the host never fences the device
    queue at a boundary.  Divergence is then detected at most ONE chunk
    late (the in-scan early-exit has already frozen the state, so the extra
    chunk is near-free identity work), and the final state is always
    resolved exactly before a ``"time_limit"`` return.  ``None`` defers to
    the model's ``io_overlap`` attribute."""
    if hasattr(pde, "update_n"):
        return _integrate_chunked(
            pde, max_time, save_intervall, dispatch, on_chunk, overlap
        )
    timestep = 0
    eps_dt = pde.get_dt() * 1e-4
    boundary = None
    if save_intervall is not None:
        boundary = _next_boundary(pde.get_time(), pde.get_dt(), save_intervall)
    while True:
        if dispatch is not None:
            dispatch(pde, 1)
        else:
            pde.update()
        timestep += 1

        if save_intervall is not None:
            t, dt = pde.get_time(), pde.get_dt()
            if t > boundary - dt / 2.0:
                # inside the half-dt window around the tracked boundary —
                # exact at large t (no modulo); past it (a dt change skipped
                # across), just re-aim at the next boundary
                if t < boundary + dt / 2.0:
                    pde.callback()
                boundary = _next_boundary(t, dt, save_intervall)

        if pde.get_time() + eps_dt >= max_time:
            print(f"time limit reached: {pde.get_time()}")
            return "time_limit"
        if timestep >= MAX_TIMESTEP:
            print(f"timestep limit reached: {timestep}")
            return "timestep_limit"
        if pde.exit():
            print("break criteria triggered")
            return "break"
        if on_chunk is not None and on_chunk(pde):
            return "stopped"


def _integrate_chunked(
    pde,
    max_time: float,
    save_intervall: float | None,
    dispatch=None,
    on_chunk=None,
    overlap: bool | None = None,
) -> str:
    """Chunked driver: one ``update_n`` dispatch per save interval.

    Each chunk aims at the next *absolute* save boundary (k * save_intervall)
    so callback times never drift, and the callback only fires when the time
    actually lands in the reference's half-dt save window.

    With ``overlap`` the break check is double-buffered (see
    :func:`integrate`): each boundary enqueues a fresh ``exit_future`` and
    blocks — if at all — only on the PREVIOUS boundary's future, whose
    device work was queued ahead of the chunk just dispatched and is
    therefore already complete.  NaN persistence makes the one-chunk lag
    safe: a frozen-NaN state (or an all-dead ensemble, or a latched
    sentinel catch) still reads as a break at the next boundary."""
    if overlap is None:
        overlap = bool(getattr(pde, "io_overlap", False))
    overlap = overlap and hasattr(pde, "exit_future")
    pending = None  # the previous boundary's unresolved exit_future
    dispatched = False  # any chunk run (guards the final exact resolve)

    def break_hit() -> bool:
        """Overlapped break check: resolves the newest future when it is
        already done (latch/fast device — exact, zero lag), else trades
        exactness for overlap by resolving the previous boundary's."""
        nonlocal pending
        fut = pde.exit_future()
        if fut.ready():
            pending = None
            return bool(fut.result())
        hit = bool(pending.result()) if pending is not None else False
        pending = fut
        return hit

    timestep = 0
    while True:
        # re-read dt every chunk: a supervising on_chunk/retry harness may
        # have shrunk it (set_dt) since the last boundary
        dt = pde.get_dt()
        eps_dt = dt * 1e-4
        t = pde.get_time()
        if t + eps_dt >= max_time:
            break
        boundary = None
        if save_intervall is not None:
            boundary = _next_boundary(t, dt, save_intervall)
            target = min(boundary, max_time)
        else:
            target = max_time
        n = max(1, round((target - t) / dt))
        n = min(n, MAX_TIMESTEP - timestep)
        if dispatch is not None:
            dispatch(pde, n)
        else:
            pde.update_n(n)
        timestep += n
        dispatched = True
        if boundary is not None:
            # the chunk aimed at one absolute boundary; fire the callback
            # only when the time actually landed in its half-dt window (a
            # governed/preempted dispatch may have advanced less) — exact at
            # large t, unlike the legacy ``t % save_intervall`` test
            if abs(pde.get_time() - boundary) < dt / 2.0:
                pde.callback()
        if timestep >= MAX_TIMESTEP:
            print(f"timestep limit reached: {timestep}")
            return "timestep_limit"
        if break_hit() if overlap else pde.exit():
            print("break criteria triggered")
            return "break"
        if pde.get_time() + eps_dt >= max_time:
            break  # completed: the time limit beats a late stop request
        if on_chunk is not None and on_chunk(pde):
            return "stopped"
    if overlap and dispatched and bool(pde.exit_future().result()):
        # the FINAL state must be judged exactly: a NaN arriving in the last
        # chunk still reports "break", matching the blocking driver
        print("break criteria triggered")
        return "break"
    print(f"time limit reached: {pde.get_time()}")
    return "time_limit"
