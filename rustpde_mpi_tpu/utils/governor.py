"""Proactive stability governor: CFL-targeting dt control on a rung ladder.

The reactive resilience layer (utils/resilience.py) only notices a blow-up
once NaNs appear: recovery is an expensive checkpoint-rollback and the dt
backoff compounds downward forever with no path back up.  This module is the
standard-CFD answer — a Courant-condition governor that keeps dt at the
stability edge — adapted to the JAX constraint that dt is *compiled into*
the solver factorizations:

* **on-device sentinels** (compiled into the scanned step chunk by
  ``Navier2D.set_stability`` / the ensemble engine): per-step max CFL
  number, volume-averaged kinetic energy (+ its per-step growth factor) and
  the pre-projection ``|div|`` residual, all cheap reductions over arrays
  the step already materializes.  A step whose CFL exceeds ``max_cfl``
  early-exits the scan with a typed ``pre_divergence`` status *before* NaNs
  propagate, and the chunk is recovered by a cheap **in-memory rollback**
  (the chunk-start snapshot the donation-safe dispatch already retains)
  instead of the checkpoint-restore path,
* **a geometric dt ladder** (:class:`DtLadder`): the controller only ever
  selects dt values ``dt_anchor * ratio**rung``, so the dt-baked solver
  factorizations + re-jits are cached per rung (``Navier2D.set_dt``) and
  the total recompile count over an arbitrarily long run is bounded by the
  ladder size,
* **hysteresis + regrowth** (:class:`StabilityGovernor`): shrink
  proactively when the chunk CFL crosses ``shrink_cfl``, drop hard (with
  rollback) on a ``pre_divergence`` catch, and after ``grow_after`` healthy
  chunks climb back up whenever the predicted CFL one rung up stays at or
  under ``target_cfl`` — the regrowth path the reactive backoff lacks,
* **physics health telemetry** (:class:`RunHealth`): dt trajectory,
  sentinel extrema, pre-divergence catches / checkpoint rollbacks avoided,
  dt adjustments and killed members, journaled at end of run.

The governor is deliberately host-side and model-agnostic: it consumes
:class:`ChunkStatus` records and returns :class:`GovernorDecision` values;
applying them (``set_dt``, member kills, journal events) is the runner's
job (utils/resilience.py).
"""

from __future__ import annotations

import dataclasses
import math
from typing import NamedTuple


class ChunkStatus(NamedTuple):
    """On-device sentinel summary of one ``update_n`` chunk.

    ``cfl_max``/``ke``/``ke_growth_max``/``div_max`` are chunk-reductions of
    the per-step sentinels (for ensembles: the batch max over members, with
    the per-member chunk-max CFL in ``cfl_members``).  ``pre_divergence``
    means the hard CFL ceiling tripped while the state was still finite: the
    chunk was rolled back in memory (state/time untouched) and the model's
    ``exit()`` latches True until a governor handles the event
    (``clear_pre_divergence``)."""

    requested: int  # steps asked of update_n
    steps_done: int  # steps actually executed before an early exit
    finite: bool  # state finite at chunk end (ensembles: any member alive)
    cfl_ok: bool  # no CFL-ceiling trip (ensembles: no alive member tripped)
    pre_divergence: bool  # ceiling tripped while finite -> chunk rolled back
    cfl_max: float  # max per-step CFL seen this chunk
    ke: float  # volume-averaged kinetic energy at chunk end
    ke_growth_max: float  # max per-step KE growth factor
    div_max: float  # max pre-projection |div| residual seen this chunk
    dt: float  # the dt the chunk ran at
    cfl_members: tuple | None = None  # per-member chunk-max CFL (ensembles)
    pinned: tuple | None = None  # per-member ceiling-trip mask (ensembles)


class GovernorDecision(NamedTuple):
    """What the governor wants done about one chunk.

    ``action``: ``"ok"`` (commit, no change) | ``"adjust"`` (commit, then
    ``set_dt(dt)``) | ``"retry"`` (chunk was rolled back: ``set_dt(dt)``,
    clear the latch, redo the chunk) | ``"kill_members"`` (roll-back case
    where the same ensemble members keep pinning the ceiling: mark
    ``members`` dead, clear the latch, redo the chunk) | ``"give_up"``
    (ladder exhausted: leave the latch set so the reactive
    checkpoint-rollback path takes over)."""

    action: str
    dt: float | None = None
    members: tuple = ()
    reason: str = ""


@dataclasses.dataclass
class RunHealth:
    """End-of-run physics health summary (journaled as ``run_health``)."""

    chunks: int = 0
    steps: int = 0
    cfl_max: float = 0.0
    ke_growth_max: float = 0.0
    div_max: float = 0.0
    pre_divergence_catches: int = 0
    rollbacks_avoided: int = 0  # catches recovered in-memory (no checkpoint)
    dt_adjusts: int = 0
    members_killed: int = 0
    dt_min_seen: float | None = None
    dt_max_seen: float | None = None
    # (step, dt) at every change, starting with the anchor
    dt_trajectory: list = dataclasses.field(default_factory=list)

    def asdict(self) -> dict:
        return dataclasses.asdict(self)


class DtLadder:
    """Geometric dt rungs ``dt_anchor * ratio**rung``, rung 0 = the anchor.

    Rungs run from ``bottom`` (<= 0, the ``dt_min`` side) to ``top`` (>= 0,
    the ``dt_max`` side); the anchor — the dt the run was configured with —
    is always rung 0 exactly, so an already-stable run never has its dt
    perturbed by quantization.  Rung dt values are computed once and reused,
    so every visit to a rung yields the *identical float* — the contract the
    per-rung solver/jit cache keys on."""

    def __init__(
        self,
        dt_anchor: float,
        ratio: float = 2.0,
        dt_min: float | None = None,
        dt_max: float | None = None,
    ):
        if not dt_anchor > 0.0:
            raise ValueError(f"dt_anchor must be positive, got {dt_anchor}")
        if not ratio > 1.0:
            raise ValueError(f"ladder ratio must exceed 1, got {ratio}")
        self.anchor = float(dt_anchor)
        self.ratio = float(ratio)
        if dt_max is None:
            dt_max = self.anchor
        if dt_min is None:
            dt_min = dt_max * self.ratio**-10
        if not 0.0 < dt_min <= self.anchor <= dt_max:
            raise ValueError(
                f"need 0 < dt_min <= dt_anchor <= dt_max, got "
                f"dt_min={dt_min}, dt_anchor={dt_anchor}, dt_max={dt_max}"
            )
        # rung counts from exact log ratios, tolerant of float representation
        self.top = int(math.floor(math.log(dt_max / self.anchor) / math.log(self.ratio) + 1e-9))
        self.bottom = -int(math.floor(math.log(self.anchor / dt_min) / math.log(self.ratio) + 1e-9))
        self._dts = {r: self.anchor * self.ratio**r for r in range(self.bottom, self.top + 1)}

    def __len__(self) -> int:
        return self.top - self.bottom + 1

    def dt(self, rung: int) -> float:
        return self._dts[self.clamp(rung)]

    def clamp(self, rung: int) -> int:
        return max(self.bottom, min(self.top, int(rung)))

    def rung_for(self, dt: float) -> int:
        """Nearest rung (in log space) to an arbitrary dt, clamped."""
        if not dt > 0.0:
            raise ValueError(f"dt must be positive, got {dt}")
        return self.clamp(round(math.log(dt / self.anchor) / math.log(self.ratio)))

    def rung_floor_for(self, dt: float) -> int:
        """Largest rung whose dt is <= the given dt (log-space floor, with a
        tolerance so an exactly-on-ladder dt maps to its own rung), clamped.
        Aligning a reactively backed-off dt must round DOWN: nearest-rung
        rounding would restore the very dt that just diverged whenever the
        backoff factor is milder than sqrt(ratio)."""
        if not dt > 0.0:
            raise ValueError(f"dt must be positive, got {dt}")
        return self.clamp(
            math.floor(math.log(dt / self.anchor) / math.log(self.ratio) + 1e-9)
        )

    def rungs_to_target(self, cfl: float, target: float) -> int:
        """How many rungs DOWN bring an observed CFL to <= target (>= 1)."""
        if not (cfl > target) or not math.isfinite(cfl):
            return 1 if math.isfinite(cfl) else len(self)
        return max(1, int(math.ceil(math.log(cfl / target) / math.log(self.ratio) - 1e-9)))


class StabilityGovernor:
    """Drive dt toward ``target_cfl`` on the rung ladder, with hysteresis.

    One instance per run; feed every chunk's :class:`ChunkStatus` through
    :meth:`on_chunk` and apply the returned :class:`GovernorDecision`.  The
    governor assumes the model's dt currently equals ``ladder.dt(rung)`` —
    the caller must apply every ``retry``/``adjust`` dt before the next
    chunk."""

    def __init__(self, cfg, dt_anchor: float):
        self.cfg = cfg
        self.ladder = DtLadder(
            dt_anchor,
            ratio=cfg.ladder_ratio,
            dt_min=cfg.dt_min,
            dt_max=cfg.dt_max,
        )
        self.shrink_cfl = (
            cfg.shrink_cfl if cfg.shrink_cfl is not None else 0.85 * cfg.max_cfl
        )
        self.rung = self.ladder.rung_for(dt_anchor)
        self.healthy = 0  # consecutive committed chunks at the current rung
        self._member_pins: dict[int, int] = {}  # member -> consecutive pins
        self.health = RunHealth()
        self.health.dt_trajectory.append((0, self.ladder.dt(self.rung)))
        self.health.dt_min_seen = self.health.dt_max_seen = self.ladder.dt(self.rung)

    # -- bookkeeping ---------------------------------------------------------

    def align(self, dt: float, step: int = 0) -> float | None:
        """Re-anchor the governor on an externally-set dt (a resume restored
        a reactive backoff, or a reactive rollback just shrank dt off the
        ladder): snap to the largest rung NOT ABOVE it — rounding to nearest
        would hand back the very dt that just diverged — and record the
        change in the health trajectory.  Returns the rung dt when the
        caller must ``set_dt`` it (off-ladder input), else None."""
        self.rung = self.ladder.rung_floor_for(dt)
        self.healthy = 0
        ladder_dt = self.ladder.dt(self.rung)
        last_dt = self.health.dt_trajectory[-1][1]
        if ladder_dt != last_dt:
            # an on-ladder external change (0.5 backoff on a ratio-2 ladder)
            # still belongs in the trajectory/extrema bookkeeping
            self._note_dt(step, ladder_dt)
        elif len(self.health.dt_trajectory) == 1 and self.health.dt_adjusts == 0:
            # initial call only: stamp the true starting step, no adjustment
            self.health.dt_trajectory[-1] = (int(step), ladder_dt)
        return ladder_dt if ladder_dt != float(dt) else None

    def _note_dt(self, step: int, dt: float) -> None:
        self.health.dt_adjusts += 1
        self.health.dt_trajectory.append((int(step), float(dt)))
        self.health.dt_min_seen = min(self.health.dt_min_seen, dt)
        self.health.dt_max_seen = max(self.health.dt_max_seen, dt)

    def _record(self, status: ChunkStatus) -> None:
        self.health.chunks += 1
        for field, value in (
            ("cfl_max", status.cfl_max),
            ("ke_growth_max", status.ke_growth_max),
            ("div_max", status.div_max),
        ):
            if math.isfinite(value):
                setattr(self.health, field, max(getattr(self.health, field), value))

    # -- the control law -----------------------------------------------------

    def on_chunk(self, status: ChunkStatus, step: int = 0) -> GovernorDecision:
        """Decide what to do about one chunk's sentinel record.

        **lag=1 contract** (overlapped dispatch, utils/io_pipeline.py): the
        status may describe a chunk that was already in flight when the
        previous decision's dt landed, so its CFL was observed at its OWN
        ``status.dt``, not the current rung's.  CFL is linear in dt — the
        thresholds below act on the observation rescaled to the current
        rung dt, otherwise a just-shrunk dt would be shrunk twice for the
        same cause (and a stale larger-dt chunk would block regrowth).  At
        lag 0 (``status.dt`` equals the rung dt — every synchronous run)
        the rescale is exactly 1 and the control law is unchanged."""
        cfg, ladder = self.cfg, self.ladder
        self._record(status)
        cfl_now = status.cfl_max
        cur_dt = ladder.dt(self.rung)
        if status.dt > 0.0 and status.dt != cur_dt and math.isfinite(cfl_now):
            cfl_now = cfl_now * (cur_dt / status.dt)

        if not status.finite:
            # genuine NaN divergence: not the governor's event — the reactive
            # checkpoint-rollback machinery owns it
            self.healthy = 0
            return GovernorDecision("ok", reason="nan_divergence")

        if status.pre_divergence:
            self.health.pre_divergence_catches += 1
            self.healthy = 0
            persistent = self._update_member_pins(status)
            if persistent and status.pinned is not None and not all(status.pinned):
                # the same members keep pinning the ceiling while the rest of
                # the batch is fine: dt drops haven't helped them, so feed
                # them to the respawn machinery instead of stalling the batch
                self._member_pins = {
                    m: c for m, c in self._member_pins.items() if m not in persistent
                }
                self.health.members_killed += len(persistent)
                self.health.rollbacks_avoided += 1
                return GovernorDecision(
                    "kill_members",
                    members=tuple(persistent),
                    reason=f"members {persistent} pinned the CFL ceiling "
                    f"{cfg.member_pin_patience}x despite dt drops",
                )
            if self.rung > ladder.bottom:
                down = ladder.rungs_to_target(cfl_now, cfg.target_cfl)
                self.rung = ladder.clamp(self.rung - down)
                new_dt = ladder.dt(self.rung)
                self._note_dt(step, new_dt)
                self.health.rollbacks_avoided += 1
                return GovernorDecision(
                    "retry",
                    dt=new_dt,
                    reason=f"cfl {status.cfl_max:.3g} > ceiling {cfg.max_cfl:g}",
                )
            # bottom rung still trips: nothing left on the ladder
            return GovernorDecision(
                "give_up",
                reason=f"CFL ceiling tripped at the bottom rung "
                f"(dt={ladder.dt(self.rung):g}, cfl {status.cfl_max:.3g})",
            )

        # committed chunk
        self.health.steps += status.steps_done
        self._member_pins.clear()
        cfl = cfl_now
        if math.isfinite(cfl) and cfl > self.shrink_cfl and self.rung > ladder.bottom:
            down = ladder.rungs_to_target(cfl, cfg.target_cfl)
            self.rung = ladder.clamp(self.rung - down)
            new_dt = ladder.dt(self.rung)
            self._note_dt(step, new_dt)
            self.healthy = 0
            return GovernorDecision(
                "adjust",
                dt=new_dt,
                reason=f"cfl {cfl:.3g} > shrink threshold {self.shrink_cfl:g}",
            )
        self.healthy += 1
        if (
            self.rung < ladder.top
            and self.healthy >= cfg.grow_after
            and math.isfinite(cfl)
            and cfl * ladder.ratio <= cfg.target_cfl
        ):
            self.rung += 1
            new_dt = ladder.dt(self.rung)
            self._note_dt(step, new_dt)
            self.healthy = 0
            return GovernorDecision(
                "adjust",
                dt=new_dt,
                reason=f"healthy {cfg.grow_after} chunks, predicted cfl "
                f"{cfl * ladder.ratio:.3g} <= target {cfg.target_cfl:g}",
            )
        return GovernorDecision("ok")

    def _update_member_pins(self, status: ChunkStatus) -> list[int]:
        """Track consecutive per-member ceiling pins; returns the members at
        or past ``member_pin_patience`` (candidates for respawn)."""
        if status.pinned is None:
            return []
        pins = {}
        for i, pinned in enumerate(status.pinned):
            if pinned:
                pins[i] = self._member_pins.get(i, 0) + 1
        self._member_pins = pins
        return sorted(i for i, c in pins.items() if c >= self.cfg.member_pin_patience)
