"""JSONL run journal: the append-only event log every harness layer shares.

One record per line, appended by :class:`JournalWriter` and read back by
:func:`read_journal`.  Two durability details matter enough to live in one
place instead of being re-implemented per consumer:

* **flush per event** — the writer keeps one handle open and flushes after
  every append, so a SIGKILL loses at most the line being written, never a
  buffered backlog of events that already "happened" (the serve layer's
  crash recovery replays this file to rebuild its request table — a stale
  journal would resurrect completed work or lose admitted requests),
* **torn-tail tolerance** — a SIGKILL (or power cut) mid-append can leave a
  truncated final line.  That is an EXPECTED artifact of the crash the
  journal exists to survive, so the reader skips a torn *trailing* record
  with a warning instead of raising.  Garbage in the *middle* of the file
  is a different animal — nothing in the append-only protocol produces it,
  so it means real corruption and raises a typed :class:`JournalError`
  (``on_error="skip"`` opts back into best-effort parsing for diagnostic
  consumers that prefer partial data over none).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time


class JournalError(RuntimeError):
    """A journal file is corrupt beyond the expected torn trailing line.

    Carries the offending path and line number — interior garbage cannot
    come from a crashed append (those only tear the tail), so it signals
    bit rot or concurrent writers and must not be silently skipped."""

    def __init__(self, path: str, lineno: int, message: str):
        super().__init__(f"{path}:{lineno}: {message}")
        self.path = path
        self.lineno = lineno


class JournalWriter:
    """Append-only JSONL writer with per-event flush.

    The handle opens lazily (the run_dir may not exist yet at construction)
    and stays open across appends; every append is one ``write`` + ``flush``
    so the line reaches the OS before the caller proceeds.  Thread-safe:
    async checkpoint completions journal from pipeline workers.  Append
    failures are reported to stderr, never raised — journaling must not
    kill the run it is documenting."""

    def __init__(self, path: str):
        self.path = path
        self._fh = None
        self._lock = threading.Lock()

    def append(self, record: dict) -> None:
        # every row carries an ABSOLUTE unix stamp next to whatever
        # run-relative clock the caller adds: per-incarnation wall_s values
        # cannot be compared across restarts, but request-trace assembly
        # (telemetry/reqtrace.py) must order one request's rows across any
        # number of incarnations on one timeline
        if "t" not in record:
            record = {"t": round(time.time(), 6), **record}
        try:
            with self._lock:
                if self._fh is None:
                    os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
                    self._fh = open(self.path, "a", encoding="utf-8")
                self._fh.write(json.dumps(record) + "\n")
                self._fh.flush()
        except OSError as exc:
            print(f"unable to append journal {self.path}: {exc}", file=sys.stderr)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


def read_journal(path: str, on_error: str = "raise") -> list[dict]:
    """Parse a JSONL journal into a list of dicts.

    A malformed FINAL line is the torn-append crash artifact: skipped with
    a warning (stderr), regardless of ``on_error``.  A malformed interior
    line raises :class:`JournalError` (``on_error="raise"``, default) or is
    skipped (``on_error="skip"`` — for best-effort diagnostic readers like
    the DivergenceError dt-trajectory report).  A missing file is an empty
    journal, not an error."""
    if on_error not in ("raise", "skip"):
        raise ValueError(f"on_error must be 'raise' or 'skip', got {on_error!r}")
    try:
        with open(path, encoding="utf-8") as fh:
            lines = fh.readlines()
    except OSError:
        return []
    records: list[dict] = []
    bad: list[tuple[int, str]] = []  # (lineno, line) parse failures
    for lineno, line in enumerate(lines, start=1):
        if not line.strip():
            continue
        try:
            records.append(json.loads(line))
        except ValueError:
            bad.append((lineno, line))
            records.append(None)  # placeholder: position decides tail vs interior
    # a trailing failure is the torn-append artifact; interior ones are not
    while records and records[-1] is None:
        lineno, line = bad.pop()
        records.pop()
        print(
            f"journal {path}: skipping torn trailing record at line {lineno} "
            f"({len(line)} bytes) — expected after a hard kill mid-append",
            file=sys.stderr,
        )
    if bad:
        lineno, _ = bad[0]
        if on_error == "raise":
            raise JournalError(
                path,
                lineno,
                "unparseable interior record (not a torn tail: a crashed "
                "append can only truncate the final line)",
            )
        records = [r for r in records if r is not None]
        print(
            f"journal {path}: skipped {len(bad)} corrupt interior record(s) "
            f"(first at line {lineno})",
            file=sys.stderr,
        )
    return records
