"""Vorticity post-processing: append ``omega = dv/dx - du/dy`` to snapshots.

TPU rebuild of /root/reference/src/navier_stokes/vorticity.rs:40-81: read the
velocity fields from a flow snapshot, compute the vorticity in spectral
space, dealias (2/3 rule), and append ``vorticity/{v,vhat}`` to the same
file.  The confined/periodic configuration is auto-detected from the stored
spectral dtype (complex datasets => periodic x-axis), with explicit
functions matching the reference's pair.
"""

from __future__ import annotations

import numpy as np

from ..bases import Space2, cheb_dirichlet, chebyshev, fourier_r2c
from .checkpoint import _write_array, read_field_vhat


def vorticity_from_file(fname: str) -> None:
    """Confined variant (vorticity.rs:40-57)."""
    _vorticity(fname, periodic=False)


def vorticity_from_file_periodic(fname: str) -> None:
    """Periodic-x variant (vorticity.rs:65-81)."""
    _vorticity(fname, periodic=True)


def vorticity_auto(fname: str) -> None:
    """Detect the configuration from the snapshot itself."""
    import h5py

    with h5py.File(fname, "r") as h5:
        periodic = "ux/vhat_re" in h5
    _vorticity(fname, periodic=periodic)


def _vorticity(fname: str, periodic: bool) -> None:
    import h5py

    with h5py.File(fname, "r") as h5:
        nx = h5["ux/x"].shape[0]
        ny = h5["ux/y"].shape[0]
        x_base = fourier_r2c if periodic else cheb_dirichlet
        x_full = fourier_r2c if periodic else chebyshev
        vel_space = Space2(x_base(nx), cheb_dirichlet(ny))
        vort_space = Space2(x_full(nx), chebyshev(ny))
        uxhat = read_field_vhat(h5, "ux", vel_space)
        uyhat = read_field_vhat(h5, "uy", vel_space)
    import jax.numpy as jnp

    uxhat = jnp.asarray(uxhat, dtype=vel_space.spectral_dtype())
    uyhat = jnp.asarray(uyhat, dtype=vel_space.spectral_dtype())
    dudz = vel_space.gradient(uxhat, (0, 1), (1.0, 1.0))
    dvdx = vel_space.gradient(uyhat, (1, 0), (1.0, 1.0))
    vort = dvdx - dudz
    mask = jnp.asarray(vort_space.dealias_mask(), dtype=vort.real.dtype)
    vort = vort * mask
    v = np.asarray(vort_space.backward_ortho(vort))

    with h5py.File(fname, "a") as h5:
        grp = h5.require_group("vorticity")
        _write_array(grp, "v", v)
        _write_array(grp, "vhat", vort_space.vhat_as_complex(vort))
