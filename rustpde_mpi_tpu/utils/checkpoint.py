"""HDF5 checkpoint/restart with the reference's snapshot layout.

Rebuild of /root/reference/src/navier_stokes/navier_io.rs + src/field/io.rs +
src/io/read_write_hdf5.rs:

* per-variable groups ``{var}/{x,dx,y,dy,v,vhat}`` with variables named
  ``ux, uy, temp, pres`` (+ ``tempbc``); complex spectral data stored as
  ``vhat_re``/``vhat_im`` dataset pairs
  (/root/reference/src/io/read_write_hdf5.rs:171-188),
* scalars ``time`` + physics params at the file root,
* restart restores spectral coefficients, supporting **resolution change via
  spectral truncation/zero-padding** with r2c Nyquist-mode bookkeeping (no
  Fourier renormalization — see :func:`interpolate_2d`; the reference's
  (new-1)/(old-1) factor compensates its unnormalized rustfft convention,
  /root/reference/src/field/io.rs:151-176).

One deliberate fix over the reference: the reference writes the coordinate
array into both the ``x`` and ``dx`` datasets (field/io.rs:96-99); here ``dx``
holds the actual grid deltas.  Readers that only consume ``x``/``y``/``v``
(the plot/ scripts, xmf generator) see identical layout.

Durability (utils/resilience.py rides on these guarantees):

* every snapshot writer is **atomic**: the file is written to
  ``<name>.<pid>.tmp``, flushed + fsynced, then ``os.replace``d over the
  target — a crash/preemption mid-write can never truncate a previously
  valid checkpoint,
* files are stamped with root attrs ``digest`` (sha256 over every dataset's
  path/shape/dtype/bytes), ``schema``, ``step`` and ``time``; readers verify
  the digest before restoring state,
* malformed/truncated files surface as :class:`CheckpointError` naming the
  file and the missing group/dataset (instead of a bare ``KeyError`` /
  h5py ``OSError``), which is what :func:`latest_checkpoint`'s
  skip-corrupt-files logic catches,
* :func:`rotate_checkpoints` keeps a rolling retention window.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
from contextlib import contextmanager

import numpy as np

from ..bases import BaseKind, Space2
from ..field import grid_deltas

_VARS = (("ux", "velx"), ("uy", "vely"), ("temp", "temp"), ("pres", "pres"))

#: bump when the on-disk layout changes incompatibly; readers accept files
#: without the attr (pre-resilience snapshots) unchanged
SCHEMA_VERSION = 1

_CKPT_PREFIX = "ckpt_"
_CKPT_SUFFIX = ".h5"


class CheckpointError(RuntimeError):
    """A checkpoint file is malformed, truncated or corrupt.

    Carries the offending ``filename`` and a cause message naming the missing
    group/dataset or the failed integrity check, so restart logic
    (utils/resilience.latest_checkpoint skip path, Navier2D.read_unwrap) has
    one typed error to catch instead of bare ``KeyError``/``OSError``.
    """

    def __init__(self, filename: str, message: str):
        super().__init__(f"{filename}: {message}")
        self.filename = filename


def _digest_update(digest, name: str, data: np.ndarray) -> None:
    digest.update(name.encode("utf-8") + b"\0")
    digest.update(str(data.dtype).encode() + b"\0")
    digest.update(str(data.shape).encode() + b"\0")
    digest.update(data.tobytes())


def content_digest(h5) -> str:
    """sha256 over every dataset (path + shape + dtype + raw bytes, visited
    in sorted path order).  Root *attrs* are deliberately excluded so the
    digest can be stored as one."""
    import h5py

    paths: list[str] = []

    def visit(name, obj):
        if isinstance(obj, h5py.Dataset):
            paths.append(name)

    h5.visititems(visit)
    digest = hashlib.sha256()
    for name in sorted(paths):
        _digest_update(digest, name, np.ascontiguousarray(h5[name][()]))
    return digest.hexdigest()


def _attrs_of(h5) -> dict:
    return {
        key: (val.decode() if isinstance(val, bytes) else val)
        for key, val in h5.attrs.items()
    }


def _verify_open_file(h5, filename: str) -> dict:
    """Digest-check an open file; returns its root attrs (digest-less files
    — pre-resilience snapshots — pass through unverified)."""
    attrs = _attrs_of(h5)
    stored = attrs.get("digest")
    if stored is not None and content_digest(h5) != stored:
        raise CheckpointError(
            filename,
            "content digest mismatch (bit rot or a partially copied file)",
        )
    return attrs


@contextmanager
def _open_checkpoint(filename: str):
    """Open a snapshot for reading with the error contract every reader
    shares: h5py's bare ``OSError`` (truncated/partial/not-HDF5) and any
    unhandled ``KeyError`` (missing root dataset) surface as
    :class:`CheckpointError` naming the file."""
    import h5py

    try:
        with h5py.File(filename, "r") as h5:
            yield h5
    except CheckpointError:
        raise
    except KeyError as exc:
        raise CheckpointError(
            filename, f"missing root dataset {exc.args[0]!r}"
        ) from exc
    except OSError as exc:
        raise CheckpointError(
            filename,
            f"unreadable HDF5 file (likely a truncated/partial write): {exc}",
        ) from exc


def read_attrs(filename: str) -> dict:
    """Root attrs of a snapshot WITHOUT the digest pass (cheap metadata
    lookup for files something else already verified — resume/rollback use
    this after :func:`latest_checkpoint` has digest-checked the file)."""
    with _open_checkpoint(filename) as h5:
        return _attrs_of(h5)


def verify_snapshot(filename: str) -> dict:
    """Open + digest-verify a snapshot; returns its root attrs.

    Raises :class:`CheckpointError` when the file is unreadable (truncated
    write, not HDF5) or its content hash does not match the stored digest."""
    with _open_checkpoint(filename) as h5:
        return _verify_open_file(h5, filename)


@dataclasses.dataclass
class HostSnapshot:
    """A snapshot fully fetched to host memory, not yet on disk.

    ``datasets`` is an ordered list of ``(h5path, array, kind)`` where
    ``kind`` is ``"field"`` (written through :func:`_write_array`: float64
    cast, complex split into ``_re``/``_im``) or ``"raw"`` (stored with the
    array's exact dtype — counters, masks, scalars).  The object is
    device-free: building one (:func:`snapshot_to_host` /
    :func:`ensemble_snapshot_to_host`) is the only part of a checkpoint
    that needs the model, so serialization + digest + fsync can run on a
    background thread (utils/io_pipeline.AsyncCheckpointWriter) while the
    device steps the next chunk."""

    datasets: list
    step: int | None = None
    time: float | None = None
    dt: float | None = None

    @property
    def nbytes(self) -> int:
        return sum(int(np.asarray(d).nbytes) for _, d, _ in self.datasets)


def _stored_arrays(path: str, data, kind: str):
    """The ``(name, array)`` pairs exactly as the writers lay them down on
    disk — the complex split and float64 cast :func:`_write_array` applies
    for ``"field"`` entries, the identity for ``"raw"`` ones."""
    if kind != "field":
        return [(path, np.ascontiguousarray(data))]
    if np.iscomplexobj(data):
        return [
            (
                f"{path}_re",
                np.asarray(np.ascontiguousarray(data.real), dtype=np.float64),
            ),
            (
                f"{path}_im",
                np.asarray(np.ascontiguousarray(data.imag), dtype=np.float64),
            ),
        ]
    return [(path, np.asarray(data, dtype=np.float64))]


def snapshot_digest(datasets) -> str:
    """The :func:`content_digest` a file holding ``datasets`` will have,
    computed from the in-memory arrays — so the write path never re-reads
    the file it just wrote (for multi-GB snapshots the read-back pass
    doubled checkpoint IO).  Byte-for-byte the same hash: the stored forms
    (:func:`_stored_arrays`) are hashed in the same sorted-path order
    ``content_digest`` visits, and a roundtrip is CI-asserted
    (tests/test_io_pipeline.py)."""
    expanded = []
    for path, data, kind in datasets:
        expanded.extend(_stored_arrays(path, data, kind))
    digest = hashlib.sha256()
    for name, arr in sorted(expanded, key=lambda kv: kv[0]):
        _digest_update(digest, name, np.ascontiguousarray(arr))
    return digest.hexdigest()


def _atomic_h5_write(
    filename: str,
    body,
    step: int | None = None,
    time: float | None = None,
    dt: float | None = None,
    digest_items=None,
) -> None:
    """Write an HDF5 file atomically: ``body(h5)`` fills a ``.tmp`` sibling,
    root attrs (schema/step/time + content digest) are stamped, the file is
    flushed + fsynced, then ``os.replace``d over the target (and the
    directory fsynced) — no code path can leave a truncated file where a
    previously valid checkpoint existed.

    ``digest_items`` (a :class:`HostSnapshot` ``datasets`` list) lets the
    digest be computed from the in-memory arrays instead of re-reading
    every dataset back out of the file just written."""
    import h5py

    dirname = os.path.dirname(filename) or "."
    os.makedirs(dirname, exist_ok=True)
    tmp = f"{filename}.{os.getpid()}.tmp"
    try:
        with h5py.File(tmp, "w") as h5:
            body(h5)
            h5.attrs["schema"] = SCHEMA_VERSION
            if step is not None:
                h5.attrs["step"] = int(step)
            if time is not None:
                h5.attrs["time"] = float(time)
            if dt is not None:
                # the step size the run was using — resume restores it so a
                # backed-off dt survives preemption (utils/resilience.py)
                h5.attrs["dt"] = float(dt)
            h5.attrs["digest"] = (
                snapshot_digest(digest_items)
                if digest_items is not None
                else content_digest(h5)
            )
            h5.flush()
        fd = os.open(tmp, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
        os.replace(tmp, filename)
        dfd = os.open(dirname, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def checkpoint_path(run_dir: str, step: int) -> str:
    """Canonical rolling-checkpoint name: ``<run_dir>/ckpt_<step:010d>.h5``
    (name-sortable by step)."""
    return os.path.join(run_dir, f"{_CKPT_PREFIX}{int(step):010d}{_CKPT_SUFFIX}")


def checkpoint_files(run_dir: str) -> list[str]:
    """All rolling checkpoints in ``run_dir``, oldest first (by step-encoded
    name); ``.tmp`` leftovers from interrupted writes are excluded."""
    try:
        names = os.listdir(run_dir)
    except OSError:
        return []
    return [
        os.path.join(run_dir, n)
        for n in sorted(names)
        if n.startswith(_CKPT_PREFIX) and n.endswith(_CKPT_SUFFIX)
    ]


def latest_checkpoint(run_dir: str) -> str | None:
    """Newest checkpoint in ``run_dir`` that passes digest verification.

    Corrupt/partial files (a crash mid-copy, bit rot) are skipped with a
    warning — resume logic falls back to the previous valid checkpoint
    instead of dying on the newest file."""
    for path in reversed(checkpoint_files(run_dir)):
        try:
            verify_snapshot(path)
        except CheckpointError as exc:
            print(f"skipping corrupt checkpoint: {exc}")
            continue
        return path
    return None


def rotate_checkpoints(run_dir: str, keep: int) -> list[str]:
    """Prune the rolling window to the newest ``keep`` checkpoints; returns
    the removed paths.  ``keep <= 0`` disables retention."""
    removed = []
    if keep <= 0:
        return removed
    files = checkpoint_files(run_dir)
    for path in files[:-keep] if len(files) > keep else []:
        try:
            os.remove(path)
            removed.append(path)
        except OSError:
            pass
    return removed


def _write_array(group, name: str, data: np.ndarray) -> None:
    if np.iscomplexobj(data):
        _write_array(group, f"{name}_re", np.ascontiguousarray(data.real))
        _write_array(group, f"{name}_im", np.ascontiguousarray(data.imag))
        return
    if name in group:
        del group[name]
    group.create_dataset(name, data=np.asarray(data, dtype=np.float64))


def _missing(group, name: str) -> CheckpointError:
    filename = getattr(getattr(group, "file", None), "filename", "<h5>")
    where = f"{group.name.rstrip('/')}/{name}"
    return CheckpointError(
        filename,
        f"missing group/dataset {where!r} — truncated write or a file that "
        "is not a snapshot in this layout",
    )


def _read_array(group, name: str, is_complex: bool) -> np.ndarray:
    try:
        if is_complex:
            return np.asarray(group[f"{name}_re"]) + 1j * np.asarray(
                group[f"{name}_im"]
            )
        return np.asarray(group[name])
    except KeyError as exc:
        raise _missing(group, f"{name}_re/_im" if is_complex else name) from exc


def interpolate_2d(
    old: np.ndarray,
    new_shape: tuple[int, int],
    kind_x: BaseKind,
    old_nx: int | None = None,
    new_nx: int | None = None,
) -> np.ndarray:
    """Spectral interpolation on resolution change: truncate / zero-pad the
    coefficient array (/root/reference/src/field/io.rs:151-176).

    Unlike the reference, no global Fourier renormalization is applied: the
    reference's rustfft forward is unnormalized (coefficients scale with n),
    while this repo's r2c forward is amplitude-normalized (rfft/n), so
    coefficients are grid-size independent.  What the r2c axis does need is
    the Nyquist-mode bookkeeping (``old_nx``/``new_nx`` are the physical grid
    sizes): an even-grid Nyquist coefficient represents cos(Nx) counted once,
    so when it becomes a regular +k mode of the new grid it must be halved,
    and when a regular +k/-k pair lands on the new grid's Nyquist it folds to
    double the real part.  This covers resolution changes that keep the
    spectral shape but flip grid parity (e.g. nx 16 -> 17)."""
    new = np.zeros(new_shape, dtype=old.dtype)
    s0 = min(old.shape[0], new_shape[0])
    s1 = min(old.shape[1], new_shape[1])
    new[:s0, :s1] = old[:s0, :s1]
    if kind_x == BaseKind.FOURIER_R2C:
        if old_nx is None:
            import warnings

            warnings.warn(
                "r2c restart interpolation without the source grid size "
                "(missing 'x' dataset): assuming an even source grid for "
                "Nyquist-mode bookkeeping",
                stacklevel=2,
            )
            old_nx = 2 * (old.shape[0] - 1)
        old_nyq = old.shape[0] - 1 if old_nx % 2 == 0 else None
        new_nyq = (
            new_shape[0] - 1 if new_nx is not None and new_nx % 2 == 0 else None
        )
        if old_nyq is not None and old_nyq < s0 and old_nyq != new_nyq:
            new[old_nyq, :] *= 0.5  # old Nyquist -> regular +k mode
        if new_nyq is not None and new_nyq < s0 and new_nyq != old_nyq:
            new[new_nyq, :] = 2.0 * new[new_nyq, :].real  # +-k fold onto Nyquist
    return new


def write_field(h5, varname: str, space: Space2, vhat, x, dx) -> None:
    """Write one field group in the reference layout.  Split-Fourier spaces
    store their coefficients in the complex convention (vhat_re/vhat_im), so
    files are layout-identical across backends."""
    grp = h5.require_group(varname)
    _write_array(grp, "x", x[0])
    _write_array(grp, "dx", dx[0])
    _write_array(grp, "y", x[1])
    _write_array(grp, "dy", dx[1])
    _write_array(grp, "v", np.asarray(space.backward(vhat)))
    _write_array(grp, "vhat", space.vhat_as_complex(vhat))


def read_field_vhat(h5, varname: str, space: Space2) -> np.ndarray:
    """Read one field's spectral coefficients, interpolating on mismatch.

    Files always carry the complex convention for periodic axes; a split
    target space converts after the (complex-domain) interpolation.

    A missing group/dataset raises :class:`CheckpointError` naming the file
    and what was expected (the corrupt-checkpoint skip logic catches it)."""
    try:
        grp = h5[varname]
    except KeyError as exc:
        raise _missing(h5, varname) from exc
    split = space.bases[0].kind.is_split
    is_complex = space.spectral_is_complex or split
    data = _read_array(grp, "vhat", is_complex)
    old_nx = grp["x"].shape[0] if "x" in grp else None
    if split:
        target_shape = (space.bases[0].m_complex, space.bases[1].m)
        kind_x = BaseKind.FOURIER_R2C
    else:
        target_shape = space.shape_spectral
        kind_x = space.base_kind(0)
    # interpolate on shape mismatch, and also when the shapes agree but the
    # r2c grid parity changed (nx 16 -> 17 keeps m = 9 yet re-types the
    # Nyquist row)
    parity_flip = (
        kind_x == BaseKind.FOURIER_R2C
        and old_nx is not None
        and old_nx % 2 != space.shape_physical[0] % 2
    )
    if data.shape != target_shape or parity_flip:
        data = interpolate_2d(
            data,
            target_shape,
            kind_x,
            old_nx=old_nx,
            new_nx=space.shape_physical[0],
        )
    # vhat_from_complex is also the sep-layout boundary (Space2 stores
    # Chebyshev spectral axes parity-permuted on the TPU path), so it must
    # run for non-split spaces too — h5 files always hold natural order
    return space.vhat_from_complex(data)


def _model_coords(model):
    xs = model.x  # scaled coords the model already derived
    dxs = [
        grid_deltas(b.points, b.is_periodic) * s
        for b, s in zip(model.field_space.bases, model.scale)
    ]
    return xs, dxs


def _field_host_datasets(path: str, space, vhat, v_phys, x, dx) -> list:
    """Host dataset list for one variable group — exactly the layout
    :func:`write_field` lays down (``v_phys`` is the already-dispatched
    physical field; ``vhat_as_complex`` fetches the coefficients)."""
    return [
        (f"{path}/x", np.asarray(x[0]), "field"),
        (f"{path}/dx", np.asarray(dx[0]), "field"),
        (f"{path}/y", np.asarray(x[1]), "field"),
        (f"{path}/dy", np.asarray(dx[1]), "field"),
        (f"{path}/v", np.asarray(v_phys), "field"),
        (f"{path}/vhat", space.vhat_as_complex(vhat), "field"),
    ]


def snapshot_to_host(model, step: int | None = None) -> HostSnapshot:
    """Fetch a flow snapshot into host memory WITHOUT touching disk.

    The one device sync a checkpoint inherently needs: every backward
    transform is dispatched first (the device pipelines them), then the
    results are fetched.  The returned :class:`HostSnapshot` feeds
    :func:`write_host_snapshot` — synchronously (:func:`write_snapshot`) or
    on the io_pipeline worker, off the dispatch critical path."""
    xs, dxs = _model_coords(model)
    datasets: list = []
    with model._scope():
        phys = {
            attr: getattr(model, f"{attr}_space").backward(
                getattr(model.state, attr)
            )
            for _, attr in _VARS
        }
        tempbc = getattr(model, "tempbc_ortho", None)
        phys_bc = model.field_space.backward(tempbc) if tempbc is not None else None
        for varname, attr in _VARS:
            space = getattr(model, f"{attr}_space")
            datasets += _field_host_datasets(
                varname, space, getattr(model.state, attr), phys[attr], xs, dxs
            )
        if tempbc is not None:
            datasets += _field_host_datasets(
                "tempbc", model.field_space, tempbc, phys_bc, xs, dxs
            )
    datasets.append(("time", np.asarray(float(model.time), dtype=np.float64), "raw"))
    for key, value in model.params.items():
        datasets.append((key, np.asarray(float(value), dtype=np.float64), "raw"))
    return HostSnapshot(
        datasets=datasets, step=step, time=float(model.time), dt=float(model.dt)
    )


def ensemble_snapshot_to_host(ens, step: int | None = None) -> HostSnapshot:
    """Ensemble analogue of :func:`snapshot_to_host`: per-member groups plus
    the root-level bookkeeping (``time``/``members``/``alive``/
    ``steps_done``/params), all fetched to host in one pass."""
    model = ens.model
    xs, dxs = _model_coords(model)
    datasets: list = []
    with model._scope():
        phys = {
            attr: [
                getattr(model, f"{attr}_space").backward(
                    getattr(ens.state, attr)[i]
                )
                for i in range(ens.k)
            ]
            for _, attr in _VARS
        }
        tempbc = getattr(model, "tempbc_ortho", None)
        phys_bc = model.field_space.backward(tempbc) if tempbc is not None else None
        for i in range(ens.k):
            for varname, attr in _VARS:
                space = getattr(model, f"{attr}_space")
                datasets += _field_host_datasets(
                    f"member{i}/{varname}",
                    space,
                    getattr(ens.state, attr)[i],
                    phys[attr][i],
                    xs,
                    dxs,
                )
        if tempbc is not None:
            datasets += _field_host_datasets(
                "tempbc", model.field_space, tempbc, phys_bc, xs, dxs
            )
        alive = np.asarray(ens.mask).astype(np.int8)
        steps_done = np.asarray(ens.steps_done, dtype=np.int64)
    datasets.append(("time", np.asarray(float(ens.time), dtype=np.float64), "raw"))
    datasets.append(("members", np.asarray(int(ens.k), dtype=np.int64), "raw"))
    datasets.append(("alive", alive, "raw"))
    datasets.append(("steps_done", steps_done, "raw"))
    for key, value in model.params.items():
        datasets.append((key, np.asarray(float(value), dtype=np.float64), "raw"))
    return HostSnapshot(
        datasets=datasets, step=step, time=float(ens.time), dt=float(ens.dt)
    )


def write_host_snapshot(snap: HostSnapshot, filename: str) -> None:
    """Serialize a :class:`HostSnapshot`: atomic, digest-stamped (from the
    in-memory arrays — no read-back pass), layout-identical to the legacy
    in-place writers.  Pure host-side work — safe on a background thread."""

    def body(h5):
        for path, data, kind in snap.datasets:
            gpath, _, name = path.rpartition("/")
            grp = h5.require_group(gpath) if gpath else h5
            if kind == "field":
                _write_array(grp, name, data)
            else:
                if name in grp:
                    del grp[name]
                grp.create_dataset(name, data=data)

    _atomic_h5_write(
        filename,
        body,
        step=snap.step,
        time=snap.time,
        dt=snap.dt,
        digest_items=snap.datasets,
    )


def write_snapshot(model, filename: str, step: int | None = None) -> None:
    """Write a flow snapshot (/root/reference/src/navier_stokes/navier_io.rs:44-62).

    Atomic (tmp + fsync + ``os.replace``) and digest-stamped; ``step`` is an
    optional run-step counter recorded as a root attr for resume logic.
    Implemented as fetch-then-serialize (:func:`snapshot_to_host` +
    :func:`write_host_snapshot`) so the synchronous and background-writer
    paths are ONE code path producing bit-identical files."""
    write_host_snapshot(snapshot_to_host(model, step=step), filename)


def write_ensemble_snapshot(ens, filename: str, step: int | None = None) -> None:
    """Write a K-member ensemble snapshot: groups ``member{i}`` each holding
    the reference single-run variable layout (:func:`write_field`), plus
    root-level ensemble bookkeeping — ``time``, ``members``, per-member
    ``alive`` mask and ``steps_done`` counters, physics params, and the
    shared ``tempbc`` lift field (written once, members share it).  Atomic
    and digest-stamped like :func:`write_snapshot`."""
    write_host_snapshot(ensemble_snapshot_to_host(ens, step=step), filename)


def read_ensemble_snapshot(ens, filename: str) -> None:
    """Restore an ensemble snapshot written by :func:`write_ensemble_snapshot`.

    Member count may differ from the target ensemble's — the state, mask and
    counters are rebuilt at the file's K.  Each member goes through
    :func:`read_field_vhat`, so per-member resolution interpolation works
    exactly like the single-run restart path.  ``pseu`` (the pressure
    increment, not stored — reference layout) restarts at zero."""
    import jax
    import jax.numpy as jnp

    from ..models.navier import NavierState

    model = ens.model
    with _open_checkpoint(filename) as h5:
        _verify_open_file(h5, filename)
        k = int(np.asarray(h5["members"]))
        members = []
        for i in range(k):
            try:
                grp = h5[f"member{i}"]
            except KeyError as exc:
                raise _missing(h5, f"member{i}") from exc
            updates = {}
            for varname, attr in _VARS:
                space = getattr(model, f"{attr}_space")
                vhat = read_field_vhat(grp, varname, space)
                updates[attr] = jnp.asarray(vhat, dtype=space.spectral_dtype())
            updates["pseu"] = jnp.zeros(
                model.pseu_space.shape_spectral, model.pseu_space.spectral_dtype()
            )
            members.append(NavierState(**updates))
        with model._scope():
            ens.state = jax.tree.map(lambda *xs: jnp.stack(xs), *members)
            ens.k = k
            ens.mask = jnp.asarray(np.asarray(h5["alive"], dtype=bool))
            ens.steps_done = jnp.asarray(
                np.asarray(h5["steps_done"]), dtype=jnp.int32
            )
        ens.time = float(np.asarray(h5["time"]))
    ens._obs_cache = None
    print(f" <== {filename} ({k} members)")


def read_snapshot(model, filename: str) -> None:
    """Restore a flow snapshot: spectral coefficients + time
    (/root/reference/src/navier_stokes/navier_io.rs:21-29).  Digest-verified
    when the file carries one; malformed files raise
    :class:`CheckpointError`."""
    import jax.numpy as jnp

    with _open_checkpoint(filename) as h5:
        _verify_open_file(h5, filename)
        updates = {}
        for varname, attr in _VARS:
            space = getattr(model, f"{attr}_space")
            vhat = read_field_vhat(h5, varname, space)
            updates[attr] = jnp.asarray(vhat, dtype=space.spectral_dtype())
        model.state = model.state._replace(**updates)
        model.time = float(np.asarray(h5["time"]))
    print(f" <== {filename}")
