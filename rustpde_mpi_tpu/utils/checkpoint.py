"""HDF5 checkpoint/restart with the reference's snapshot layout.

Rebuild of /root/reference/src/navier_stokes/navier_io.rs + src/field/io.rs +
src/io/read_write_hdf5.rs:

* per-variable groups ``{var}/{x,dx,y,dy,v,vhat}`` with variables named
  ``ux, uy, temp, pres`` (+ ``tempbc``); complex spectral data stored as
  ``vhat_re``/``vhat_im`` dataset pairs
  (/root/reference/src/io/read_write_hdf5.rs:171-188),
* scalars ``time`` + physics params at the file root,
* restart restores spectral coefficients, supporting **resolution change via
  spectral truncation/zero-padding** with r2c Nyquist-mode bookkeeping (no
  Fourier renormalization — see :func:`interpolate_2d`; the reference's
  (new-1)/(old-1) factor compensates its unnormalized rustfft convention,
  /root/reference/src/field/io.rs:151-176).

One deliberate fix over the reference: the reference writes the coordinate
array into both the ``x`` and ``dx`` datasets (field/io.rs:96-99); here ``dx``
holds the actual grid deltas.  Readers that only consume ``x``/``y``/``v``
(the plot/ scripts, xmf generator) see identical layout.

Durability (utils/resilience.py rides on these guarantees):

* every snapshot writer is **atomic**: the file is written to
  ``<name>.<pid>.tmp``, flushed + fsynced, then ``os.replace``d over the
  target — a crash/preemption mid-write can never truncate a previously
  valid checkpoint,
* files are stamped with root attrs ``digest`` (sha256 over every dataset's
  path/shape/dtype/bytes), ``schema``, ``step`` and ``time``; readers verify
  the digest before restoring state,
* malformed/truncated files surface as :class:`CheckpointError` naming the
  file and the missing group/dataset (instead of a bare ``KeyError`` /
  h5py ``OSError``), which is what :func:`latest_checkpoint`'s
  skip-corrupt-files logic catches,
* :func:`rotate_checkpoints` keeps a rolling retention window (and removes a
  sharded checkpoint's whole shard set with its manifest).

Distributed (multihost) checkpoints — the sharded two-phase layer:

The writers above fetch the FULL state through ``np.asarray``, which needs
every shard addressable from one process — true on single-controller meshes
but impossible on a real multi-controller pencil mesh.  The sharded layer
(the analog of the reference's rank-parallel IO pair io_mpi_sequ.rs /
io_mpi.rs) checkpoints through every process at once:

* each process serializes only its **addressable shards** to a per-host
  shard file ``<ckpt>.h5.shard<p>`` (atomic tmp+fsync+replace, per-shard
  sha256 digest computed write-side from the in-memory slabs; slab offsets
  are encoded in the dataset names so the digest covers placement),
* commit is **two-phase**: all hosts write+fsync shards, barrier
  (``sync_hosts``), digests ride one small allgather, then ROOT atomically
  writes the manifest ``<ckpt>.h5`` — global shapes/dtypes, mesh topology,
  shard->file map with digests, step/time/dt root attrs.  **Manifest
  presence IS the commit marker**: a crash or single-host kill anywhere in
  the sequence leaves the previous checkpoint fully valid (the shard files
  of the aborted attempt are orphans the rotation sweep collects),
* :func:`verify_snapshot` / :func:`latest_checkpoint` validate manifests
  end-to-end — any missing/corrupt shard rejects the WHOLE checkpoint and
  resume falls back to the previous one,
* restore is **topology-elastic** (:func:`read_sharded_snapshot`): a
  checkpoint written under any mesh/host count restores onto a different
  mesh shape, host count or a plain serial model — each host assembles only
  the slabs its own devices need (``jax.make_array_from_single_device_arrays``)
  and the restored state is bit-equal to the writer's.  Shard files store
  the raw device-layout state (exact dtypes, complex split into _re/_im),
  so the roundtrip is exact; resolution change stays with the gathered
  writers (:func:`write_snapshot`), which remain the plot/export format.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from contextlib import ExitStack, contextmanager

import numpy as np

from ..bases import BaseKind, Space2

from ..config import env_get
from . import fsutil
from .fsutil import fsync_dir
from ..field import grid_deltas

_VARS = (("ux", "velx"), ("uy", "vely"), ("temp", "temp"), ("pres", "pres"))

#: bump when the on-disk layout changes incompatibly; readers accept files
#: without the attr (pre-resilience snapshots) unchanged
SCHEMA_VERSION = 1

_CKPT_PREFIX = "ckpt_"
_CKPT_SUFFIX = ".h5"


class CheckpointError(RuntimeError):
    """A checkpoint file is malformed, truncated or corrupt.

    Carries the offending ``filename`` and a cause message naming the missing
    group/dataset or the failed integrity check, so restart logic
    (utils/resilience.latest_checkpoint skip path, Navier2D.read_unwrap) has
    one typed error to catch instead of bare ``KeyError``/``OSError``.
    """

    def __init__(self, filename: str, message: str):
        super().__init__(f"{filename}: {message}")
        self.filename = filename


def _digest_update(digest, name: str, data: np.ndarray) -> None:
    digest.update(name.encode("utf-8") + b"\0")
    digest.update(str(data.dtype).encode() + b"\0")
    digest.update(str(data.shape).encode() + b"\0")
    digest.update(data.tobytes())


def content_digest(h5) -> str:
    """sha256 over every dataset (path + shape + dtype + raw bytes, visited
    in sorted path order).  Root *attrs* are deliberately excluded so the
    digest can be stored as one."""
    import h5py

    paths: list[str] = []

    def visit(name, obj):
        if isinstance(obj, h5py.Dataset):
            paths.append(name)

    h5.visititems(visit)
    digest = hashlib.sha256()
    for name in sorted(paths):
        _digest_update(digest, name, np.ascontiguousarray(h5[name][()]))
    return digest.hexdigest()


def _attrs_of(h5) -> dict:
    return {
        key: (val.decode() if isinstance(val, bytes) else val)
        for key, val in h5.attrs.items()
    }


def _verify_open_file(h5, filename: str) -> dict:
    """Digest-check an open file; returns its root attrs (digest-less files
    — pre-resilience snapshots — pass through unverified)."""
    attrs = _attrs_of(h5)
    stored = attrs.get("digest")
    if stored is not None and content_digest(h5) != stored:
        raise CheckpointError(
            filename,
            "content digest mismatch (bit rot or a partially copied file)",
        )
    return attrs


@contextmanager
def _open_checkpoint(filename: str):
    """Open a snapshot for reading with the error contract every reader
    shares: h5py's bare ``OSError`` (truncated/partial/not-HDF5) and any
    unhandled ``KeyError`` (missing root dataset) surface as
    :class:`CheckpointError` naming the file."""
    import h5py

    try:
        with h5py.File(filename, "r") as h5:
            yield h5
    except CheckpointError:
        raise
    except KeyError as exc:
        raise CheckpointError(
            filename, f"missing root dataset {exc.args[0]!r}"
        ) from exc
    except OSError as exc:
        raise CheckpointError(
            filename,
            f"unreadable HDF5 file (likely a truncated/partial write): {exc}",
        ) from exc


def read_attrs(filename: str) -> dict:
    """Root attrs of a snapshot WITHOUT the digest pass (cheap metadata
    lookup for files something else already verified — resume/rollback use
    this after :func:`latest_checkpoint` has digest-checked the file)."""
    with _open_checkpoint(filename) as h5:
        return _attrs_of(h5)


def verify_snapshot(filename: str) -> dict:
    """Open + digest-verify a snapshot; returns its root attrs.

    For a sharded-checkpoint MANIFEST the verification is end-to-end: the
    manifest's own digest first, then every shard file in its shard map —
    existence, readability, and content digest against both the manifest's
    recorded value and the shard's own stamp.  ANY missing/corrupt shard
    rejects the whole checkpoint (``latest_checkpoint`` then falls back).

    Raises :class:`CheckpointError` when the file is unreadable (truncated
    write, not HDF5) or its content hash does not match the stored digest."""
    with _open_checkpoint(filename) as h5:
        attrs = _verify_open_file(h5, filename)
        meta = _read_manifest_meta(h5, filename) if attrs.get("sharded") else None
    if meta is not None:
        _verify_shard_set(filename, meta)
    return attrs


def read_root_data(filename: str) -> dict:
    """Root-level (replicated, digest-covered) datasets of a snapshot as
    numpy arrays, WITHOUT assembling any state — the cheap metadata peek
    the serve scheduler uses to learn a checkpoint's slot geometry
    (``members``, ``serve_slots``) before deciding how to size the fleet.
    For a sharded manifest these are the manifest-root datasets; for a
    gathered snapshot, the root datasets next to the state groups."""
    out: dict[str, np.ndarray] = {}
    with _open_checkpoint(filename) as h5:
        for name, obj in h5.items():
            if name != _MANIFEST_DS and hasattr(obj, "shape"):
                out[name] = np.asarray(obj)
    return out


@dataclasses.dataclass
class HostSnapshot:
    """A snapshot fully fetched to host memory, not yet on disk.

    ``datasets`` is an ordered list of ``(h5path, array, kind)`` where
    ``kind`` is ``"field"`` (written through :func:`_write_array`: float64
    cast, complex split into ``_re``/``_im``) or ``"raw"`` (stored with the
    array's exact dtype — counters, masks, scalars).  The object is
    device-free: building one (:func:`snapshot_to_host` /
    :func:`ensemble_snapshot_to_host`) is the only part of a checkpoint
    that needs the model, so serialization + digest + fsync can run on a
    background thread (utils/io_pipeline.AsyncCheckpointWriter) while the
    device steps the next chunk."""

    datasets: list
    step: int | None = None
    time: float | None = None
    dt: float | None = None

    @property
    def nbytes(self) -> int:
        return sum(int(np.asarray(d).nbytes) for _, d, _ in self.datasets)


def _stored_arrays(path: str, data, kind: str):
    """The ``(name, array)`` pairs exactly as the writers lay them down on
    disk — the complex split and float64 cast :func:`_write_array` applies
    for ``"field"`` entries, the identity for ``"raw"`` ones."""
    if kind != "field":
        return [(path, np.ascontiguousarray(data))]
    if np.iscomplexobj(data):
        return [
            (
                f"{path}_re",
                np.asarray(np.ascontiguousarray(data.real), dtype=np.float64),
            ),
            (
                f"{path}_im",
                np.asarray(np.ascontiguousarray(data.imag), dtype=np.float64),
            ),
        ]
    return [(path, np.asarray(data, dtype=np.float64))]


def snapshot_digest(datasets) -> str:
    """The :func:`content_digest` a file holding ``datasets`` will have,
    computed from the in-memory arrays — so the write path never re-reads
    the file it just wrote (for multi-GB snapshots the read-back pass
    doubled checkpoint IO).  Byte-for-byte the same hash: the stored forms
    (:func:`_stored_arrays`) are hashed in the same sorted-path order
    ``content_digest`` visits, and a roundtrip is CI-asserted
    (tests/test_io_pipeline.py)."""
    expanded = []
    for path, data, kind in datasets:
        expanded.extend(_stored_arrays(path, data, kind))
    digest = hashlib.sha256()
    for name, arr in sorted(expanded, key=lambda kv: kv[0]):
        _digest_update(digest, name, np.ascontiguousarray(arr))
    return digest.hexdigest()


def _atomic_h5_write(
    filename: str,
    body,
    step: int | None = None,
    time: float | None = None,
    dt: float | None = None,
    digest_items=None,
    digest: str | None = None,
) -> None:
    """Write an HDF5 file atomically: ``body(h5)`` fills a ``.tmp`` sibling,
    root attrs (schema/step/time + content digest) are stamped, the file is
    flushed + fsynced, then ``os.replace``d over the target (and the
    directory fsynced) — no code path can leave a truncated file where a
    previously valid checkpoint existed.

    ``digest_items`` (a :class:`HostSnapshot` ``datasets`` list) lets the
    digest be computed from the in-memory arrays instead of re-reading
    every dataset back out of the file just written; ``digest`` accepts an
    already-computed value (the sharded writer hashes its slabs once and
    reuses the hash for the manifest's shard map)."""
    import h5py

    dirname = os.path.dirname(filename) or "."
    os.makedirs(dirname, exist_ok=True)
    tmp = f"{filename}.{os.getpid()}.tmp"
    try:
        with h5py.File(tmp, "w") as h5:
            body(h5)
            h5.attrs["schema"] = SCHEMA_VERSION
            if step is not None:
                h5.attrs["step"] = int(step)
            if time is not None:
                h5.attrs["time"] = float(time)
            if dt is not None:
                # the step size the run was using — resume restores it so a
                # backed-off dt survives preemption (utils/resilience.py)
                h5.attrs["dt"] = float(dt)
            if digest is None:
                digest = (
                    snapshot_digest(digest_items)
                    if digest_items is not None
                    else content_digest(h5)
                )
            h5.attrs["digest"] = digest
            h5.flush()
        fd = os.open(tmp, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
        os.replace(tmp, filename)
        # strict: a failed dirsync must fail the write (the two-phase
        # commit would otherwise report a checkpoint committed whose
        # dirent can roll back across power loss)
        fsync_dir(dirname, strict=True)
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def checkpoint_path(run_dir: str, step: int) -> str:
    """Canonical rolling-checkpoint name: ``<run_dir>/ckpt_<step:010d>.h5``
    (name-sortable by step)."""
    return os.path.join(run_dir, f"{_CKPT_PREFIX}{int(step):010d}{_CKPT_SUFFIX}")


def checkpoint_files(run_dir: str) -> list[str]:
    """All rolling checkpoints in ``run_dir``, oldest first (by step-encoded
    name); ``.tmp`` leftovers from interrupted writes are excluded."""
    try:
        names = os.listdir(run_dir)
    except OSError:
        return []
    return [
        os.path.join(run_dir, n)
        for n in sorted(names)
        if n.startswith(_CKPT_PREFIX) and n.endswith(_CKPT_SUFFIX)
    ]


def latest_checkpoint(run_dir: str) -> str | None:
    """Newest checkpoint in ``run_dir`` that passes digest verification.

    Corrupt/partial files (a crash mid-copy, bit rot) are skipped with a
    warning — resume logic falls back to the previous valid checkpoint
    instead of dying on the newest file."""
    for path in reversed(checkpoint_files(run_dir)):
        try:
            verify_snapshot(path)
        except CheckpointError as exc:
            print(f"skipping corrupt checkpoint: {exc}")
            continue
        return path
    return None


def shard_path(manifest: str, index: int) -> str:
    """Per-host shard file of a sharded checkpoint: ``<manifest>.shard<p>``.
    The suffix keeps shards out of :func:`checkpoint_files`' ``.h5`` listing
    — only the manifest (the commit marker) is ever a resume candidate."""
    return f"{manifest}.shard{int(index)}"


def checkpoint_shard_files(manifest: str) -> list[str]:
    """Every shard file belonging to ``manifest`` (committed or orphaned)."""
    dirname = os.path.dirname(manifest) or "."
    base = os.path.basename(manifest) + ".shard"
    try:
        names = os.listdir(dirname)
    except OSError:
        return []
    return [os.path.join(dirname, n) for n in sorted(names) if n.startswith(base)]


def remove_checkpoint(manifest: str) -> None:
    """Remove one checkpoint atomically with respect to validity: the
    MANIFEST goes first (after which the checkpoint is uncommitted — a crash
    mid-removal can only leave harmless orphan shards, never a manifest
    pointing at deleted shards), then the shard set."""
    for path in [manifest, *checkpoint_shard_files(manifest)]:
        try:
            os.remove(path)
        except OSError:
            pass


def rotate_checkpoints(run_dir: str, keep: int) -> list[str]:
    """Prune the rolling window to the newest ``keep`` checkpoints; returns
    the removed manifest paths.  ``keep <= 0`` disables retention.

    Sharded checkpoints are removed as a unit (:func:`remove_checkpoint`:
    manifest first, then shards), and ORPHAN shard sets — shard files whose
    manifest never landed, i.e. a two-phase commit that died between shard
    fsync and manifest write, or a corrupt manifest a previous rotation
    removed — are swept once their step falls below the retention window
    (orphans at or above the oldest kept step may be an in-flight write on
    a peer host and are left alone)."""
    removed = []
    if keep <= 0:
        return removed
    files = checkpoint_files(run_dir)
    for path in files[:-keep] if len(files) > keep else []:
        remove_checkpoint(path)
        removed.append(path)
    kept = checkpoint_files(run_dir)
    if kept:
        oldest_kept = os.path.basename(kept[0])
        try:
            names = os.listdir(run_dir)
        except OSError:
            names = []
        for name in names:
            stem, sep, _ = name.partition(_CKPT_SUFFIX + ".shard")
            if not sep:
                continue
            manifest = stem + _CKPT_SUFFIX
            if manifest < oldest_kept and manifest not in names:
                try:
                    os.remove(os.path.join(run_dir, name))
                except OSError:
                    pass
    return removed


def _write_array(group, name: str, data: np.ndarray) -> None:
    if np.iscomplexobj(data):
        _write_array(group, f"{name}_re", np.ascontiguousarray(data.real))
        _write_array(group, f"{name}_im", np.ascontiguousarray(data.imag))
        return
    if name in group:
        del group[name]
    group.create_dataset(name, data=np.asarray(data, dtype=np.float64))


def _missing(group, name: str) -> CheckpointError:
    filename = getattr(getattr(group, "file", None), "filename", "<h5>")
    where = f"{group.name.rstrip('/')}/{name}"
    return CheckpointError(
        filename,
        f"missing group/dataset {where!r} — truncated write or a file that "
        "is not a snapshot in this layout",
    )


def _read_array(group, name: str, is_complex: bool) -> np.ndarray:
    try:
        if is_complex:
            return np.asarray(group[f"{name}_re"]) + 1j * np.asarray(
                group[f"{name}_im"]
            )
        return np.asarray(group[name])
    except KeyError as exc:
        raise _missing(group, f"{name}_re/_im" if is_complex else name) from exc


def interpolate_2d(
    old: np.ndarray,
    new_shape: tuple[int, int],
    kind_x: BaseKind,
    old_nx: int | None = None,
    new_nx: int | None = None,
) -> np.ndarray:
    """Spectral interpolation on resolution change: truncate / zero-pad the
    coefficient array (/root/reference/src/field/io.rs:151-176).

    Unlike the reference, no global Fourier renormalization is applied: the
    reference's rustfft forward is unnormalized (coefficients scale with n),
    while this repo's r2c forward is amplitude-normalized (rfft/n), so
    coefficients are grid-size independent.  What the r2c axis does need is
    the Nyquist-mode bookkeeping (``old_nx``/``new_nx`` are the physical grid
    sizes): an even-grid Nyquist coefficient represents cos(Nx) counted once,
    so when it becomes a regular +k mode of the new grid it must be halved,
    and when a regular +k/-k pair lands on the new grid's Nyquist it folds to
    double the real part.  This covers resolution changes that keep the
    spectral shape but flip grid parity (e.g. nx 16 -> 17)."""
    new = np.zeros(new_shape, dtype=old.dtype)
    s0 = min(old.shape[0], new_shape[0])
    s1 = min(old.shape[1], new_shape[1])
    new[:s0, :s1] = old[:s0, :s1]
    if kind_x == BaseKind.FOURIER_R2C:
        if old_nx is None:
            import warnings

            warnings.warn(
                "r2c restart interpolation without the source grid size "
                "(missing 'x' dataset): assuming an even source grid for "
                "Nyquist-mode bookkeeping",
                stacklevel=2,
            )
            old_nx = 2 * (old.shape[0] - 1)
        old_nyq = old.shape[0] - 1 if old_nx % 2 == 0 else None
        new_nyq = (
            new_shape[0] - 1 if new_nx is not None and new_nx % 2 == 0 else None
        )
        if old_nyq is not None and old_nyq < s0 and old_nyq != new_nyq:
            new[old_nyq, :] *= 0.5  # old Nyquist -> regular +k mode
        if new_nyq is not None and new_nyq < s0 and new_nyq != old_nyq:
            new[new_nyq, :] = 2.0 * new[new_nyq, :].real  # +-k fold onto Nyquist
    return new


def write_field(h5, varname: str, space: Space2, vhat, x, dx) -> None:
    """Write one field group in the reference layout.  Split-Fourier spaces
    store their coefficients in the complex convention (vhat_re/vhat_im), so
    files are layout-identical across backends."""
    grp = h5.require_group(varname)
    _write_array(grp, "x", x[0])
    _write_array(grp, "dx", dx[0])
    _write_array(grp, "y", x[1])
    _write_array(grp, "dy", dx[1])
    _write_array(grp, "v", np.asarray(space.backward(vhat)))
    _write_array(grp, "vhat", space.vhat_as_complex(vhat))


def read_field_vhat(h5, varname: str, space: Space2) -> np.ndarray:
    """Read one field's spectral coefficients, interpolating on mismatch.

    Files always carry the complex convention for periodic axes; a split
    target space converts after the (complex-domain) interpolation.

    A missing group/dataset raises :class:`CheckpointError` naming the file
    and what was expected (the corrupt-checkpoint skip logic catches it)."""
    try:
        grp = h5[varname]
    except KeyError as exc:
        raise _missing(h5, varname) from exc
    split = space.bases[0].kind.is_split
    is_complex = space.spectral_is_complex or split
    data = _read_array(grp, "vhat", is_complex)
    old_nx = grp["x"].shape[0] if "x" in grp else None
    if split:
        target_shape = (space.bases[0].m_complex, space.bases[1].m)
        kind_x = BaseKind.FOURIER_R2C
    else:
        target_shape = space.shape_spectral
        kind_x = space.base_kind(0)
    # interpolate on shape mismatch, and also when the shapes agree but the
    # r2c grid parity changed (nx 16 -> 17 keeps m = 9 yet re-types the
    # Nyquist row)
    parity_flip = (
        kind_x == BaseKind.FOURIER_R2C
        and old_nx is not None
        and old_nx % 2 != space.shape_physical[0] % 2
    )
    if data.shape != target_shape or parity_flip:
        data = interpolate_2d(
            data,
            target_shape,
            kind_x,
            old_nx=old_nx,
            new_nx=space.shape_physical[0],
        )
    # vhat_from_complex is also the sep-layout boundary (Space2 stores
    # Chebyshev spectral axes parity-permuted on the TPU path), so it must
    # run for non-split spaces too — h5 files always hold natural order
    return space.vhat_from_complex(data)


def _model_coords(model):
    xs = model.x  # scaled coords the model already derived
    dxs = [
        grid_deltas(b.points, b.is_periodic) * s
        for b, s in zip(model.field_space.bases, model.scale)
    ]
    return xs, dxs


def _field_host_datasets(path: str, space, vhat, v_phys, x, dx) -> list:
    """Host dataset list for one variable group — exactly the layout
    :func:`write_field` lays down (``v_phys`` is the already-dispatched
    physical field; ``vhat_as_complex`` fetches the coefficients)."""
    return [
        (f"{path}/x", np.asarray(x[0]), "field"),
        (f"{path}/dx", np.asarray(dx[0]), "field"),
        (f"{path}/y", np.asarray(x[1]), "field"),
        (f"{path}/dy", np.asarray(dx[1]), "field"),
        (f"{path}/v", np.asarray(v_phys), "field"),
        (f"{path}/vhat", space.vhat_as_complex(vhat), "field"),
    ]


def snapshot_to_host(model, step: int | None = None) -> HostSnapshot:
    """Fetch a flow snapshot into host memory WITHOUT touching disk.

    The one device sync a checkpoint inherently needs: every backward
    transform is dispatched first (the device pipelines them), then the
    results are fetched.  The returned :class:`HostSnapshot` feeds
    :func:`write_host_snapshot` — synchronously (:func:`write_snapshot`) or
    on the io_pipeline worker, off the dispatch critical path."""
    xs, dxs = _model_coords(model)
    datasets: list = []
    model_vars = getattr(model, "snapshot_vars", _VARS)
    with model._scope():
        phys = {
            attr: getattr(model, f"{attr}_space").backward(
                getattr(model.state, attr)
            )
            for _, attr in model_vars
        }
        tempbc = getattr(model, "tempbc_ortho", None)
        phys_bc = model.field_space.backward(tempbc) if tempbc is not None else None
        for varname, attr in model_vars:
            space = getattr(model, f"{attr}_space")
            datasets += _field_host_datasets(
                varname, space, getattr(model.state, attr), phys[attr], xs, dxs
            )
        if tempbc is not None:
            datasets += _field_host_datasets(
                "tempbc", model.field_space, tempbc, phys_bc, xs, dxs
            )
    datasets.append(("time", np.asarray(float(model.time), dtype=np.float64), "raw"))
    for key, value in model.params.items():
        datasets.append((key, np.asarray(float(value), dtype=np.float64), "raw"))
    # armed in-scan stats (models/stats.py): running sums + sample tick as
    # exact-dtype raw datasets, so a resume restores the averages bit-equal
    stats_items = getattr(model, "stats_host_items", None)
    if stats_items is not None:
        with model._scope():
            datasets.extend(stats_items())
    return HostSnapshot(
        datasets=datasets, step=step, time=float(model.time), dt=float(model.dt)
    )


def ensemble_snapshot_to_host(ens, step: int | None = None) -> HostSnapshot:
    """Ensemble analogue of :func:`snapshot_to_host`: per-member groups plus
    the root-level bookkeeping (``time``/``members``/``alive``/
    ``steps_done``/params), all fetched to host in one pass."""
    model = ens.model
    xs, dxs = _model_coords(model)
    datasets: list = []
    model_vars = getattr(model, "snapshot_vars", _VARS)
    with model._scope():
        phys = {
            attr: [
                getattr(model, f"{attr}_space").backward(
                    getattr(ens.state, attr)[i]
                )
                for i in range(ens.k)
            ]
            for _, attr in model_vars
        }
        tempbc = getattr(model, "tempbc_ortho", None)
        phys_bc = model.field_space.backward(tempbc) if tempbc is not None else None
        for i in range(ens.k):
            for varname, attr in model_vars:
                space = getattr(model, f"{attr}_space")
                datasets += _field_host_datasets(
                    f"member{i}/{varname}",
                    space,
                    getattr(ens.state, attr)[i],
                    phys[attr][i],
                    xs,
                    dxs,
                )
        if tempbc is not None:
            datasets += _field_host_datasets(
                "tempbc", model.field_space, tempbc, phys_bc, xs, dxs
            )
        alive = np.asarray(ens.mask).astype(np.int8)
        steps_done = np.asarray(ens.steps_done, dtype=np.int64)
    datasets.append(("time", np.asarray(float(ens.time), dtype=np.float64), "raw"))
    datasets.append(("members", np.asarray(int(ens.k), dtype=np.int64), "raw"))
    datasets.append(("alive", alive, "raw"))
    datasets.append(("steps_done", steps_done, "raw"))
    for key, value in model.params.items():
        datasets.append((key, np.asarray(float(value), dtype=np.float64), "raw"))
    stats_items = getattr(ens, "stats_host_items", None)
    if stats_items is not None:
        with model._scope():
            datasets.extend(stats_items())
    return HostSnapshot(
        datasets=datasets, step=step, time=float(ens.time), dt=float(ens.dt)
    )


def write_host_snapshot(snap: HostSnapshot, filename: str) -> None:
    """Serialize a :class:`HostSnapshot`: atomic, digest-stamped (from the
    in-memory arrays — no read-back pass), layout-identical to the legacy
    in-place writers.  Pure host-side work — safe on a background thread."""

    def body(h5):
        for path, data, kind in snap.datasets:
            gpath, _, name = path.rpartition("/")
            grp = h5.require_group(gpath) if gpath else h5
            if kind == "field":
                _write_array(grp, name, data)
            else:
                if name in grp:
                    del grp[name]
                grp.create_dataset(name, data=data)

    _atomic_h5_write(
        filename,
        body,
        step=snap.step,
        time=snap.time,
        dt=snap.dt,
        digest_items=snap.datasets,
    )


def write_snapshot(model, filename: str, step: int | None = None) -> None:
    """Write a flow snapshot (/root/reference/src/navier_stokes/navier_io.rs:44-62).

    Atomic (tmp + fsync + ``os.replace``) and digest-stamped; ``step`` is an
    optional run-step counter recorded as a root attr for resume logic.
    Implemented as fetch-then-serialize (:func:`snapshot_to_host` +
    :func:`write_host_snapshot`) so the synchronous and background-writer
    paths are ONE code path producing bit-identical files."""
    write_host_snapshot(snapshot_to_host(model, step=step), filename)


def write_ensemble_snapshot(ens, filename: str, step: int | None = None) -> None:
    """Write a K-member ensemble snapshot: groups ``member{i}`` each holding
    the reference single-run variable layout (:func:`write_field`), plus
    root-level ensemble bookkeeping — ``time``, ``members``, per-member
    ``alive`` mask and ``steps_done`` counters, physics params, and the
    shared ``tempbc`` lift field (written once, members share it).  Atomic
    and digest-stamped like :func:`write_snapshot`."""
    write_host_snapshot(ensemble_snapshot_to_host(ens, step=step), filename)


def read_ensemble_snapshot(ens, filename: str) -> None:
    """Restore an ensemble snapshot written by :func:`write_ensemble_snapshot`.

    Member count may differ from the target ensemble's — the state, mask and
    counters are rebuilt at the file's K.  Each member goes through
    :func:`read_field_vhat`, so per-member resolution interpolation works
    exactly like the single-run restart path.  ``pseu`` (the pressure
    increment, not stored — reference layout) restarts at zero.  A sharded
    manifest dispatches to :func:`read_sharded_snapshot` (same-K, exact)."""
    import jax
    import jax.numpy as jnp

    if is_sharded_checkpoint(filename):
        read_sharded_snapshot(ens, filename)
        return
    model = ens.model
    model_vars = getattr(model, "snapshot_vars", _VARS)
    state_cls = type(model.state)
    with _open_checkpoint(filename) as h5:
        _verify_open_file(h5, filename)
        k = int(np.asarray(h5["members"]))
        members = []
        for i in range(k):
            try:
                grp = h5[f"member{i}"]
            except KeyError as exc:
                raise _missing(h5, f"member{i}") from exc
            updates = {}
            for varname, attr in model_vars:
                space = getattr(model, f"{attr}_space")
                vhat = read_field_vhat(grp, varname, space)
                updates[attr] = jnp.asarray(vhat, dtype=space.spectral_dtype())
            for name in state_cls._fields:
                # leaves the gathered layout does not carry (``pseu``, the
                # reference layout; auxiliary campaign leaves) restart via
                # the model's fill rule (default zero) — the gathered format
                # is restart-equivalent, the sharded manifest is bit-exact
                if name not in updates:
                    like = getattr(model.state, name)
                    fill = getattr(model, "restart_fill", None)
                    updates[name] = (
                        fill(name, like) if fill else jnp.zeros_like(like)
                    )
            members.append(state_cls(**updates))
        with model._scope():
            ens.state = jax.tree.map(lambda *xs: jnp.stack(xs), *members)
            ens.k = k
            ens.mask = jnp.asarray(np.asarray(h5["alive"], dtype=bool))
            ens.steps_done = jnp.asarray(
                np.asarray(h5["steps_done"]), dtype=jnp.int32
            )
        ens.time = float(np.asarray(h5["time"]))
        _restore_stats(ens, h5)
    ens._obs_cache = None
    print(f" <== {filename} ({k} members)")


def _read_stats_group(h5) -> dict | None:
    """The ``stats_state/`` raw datasets of a gathered snapshot (None when
    the file predates the stats engine — restores then reset the averaging
    window instead of failing)."""
    if "stats_state" not in h5:
        return None
    grp = h5["stats_state"]
    return {name: np.asarray(grp[name]) for name in grp}


def _restore_stats(pde, h5) -> None:
    """Install a gathered snapshot's stats leaves on a stats-armed model/
    ensemble (no-op otherwise)."""
    if not getattr(pde, "stats_armed", False):
        return
    pde.apply_restored_stats(_read_stats_group(h5))


def read_snapshot(model, filename: str) -> None:
    """Restore a flow snapshot: spectral coefficients + time
    (/root/reference/src/navier_stokes/navier_io.rs:21-29).  Digest-verified
    when the file carries one; malformed files raise
    :class:`CheckpointError`.  A sharded-checkpoint manifest dispatches to
    the topology-elastic :func:`read_sharded_snapshot`."""
    import jax.numpy as jnp

    if is_sharded_checkpoint(filename):
        read_sharded_snapshot(model, filename)
        return
    base_vars = {attr for _, attr in _VARS}
    with _open_checkpoint(filename) as h5:
        _verify_open_file(h5, filename)
        updates = {}
        for varname, attr in getattr(model, "snapshot_vars", _VARS):
            space = getattr(model, f"{attr}_space")
            if varname not in h5 and attr not in base_vars:
                # scenario-extended leaf absent from an older snapshot:
                # restart it via the model's fill rule (the write side
                # stores it — snapshot_to_host uses the same var list)
                fill = getattr(model, "restart_fill", None)
                like = getattr(model.state, attr)
                updates[attr] = (
                    fill(attr, like) if fill else jnp.zeros_like(like)
                )
                continue
            vhat = read_field_vhat(h5, varname, space)
            updates[attr] = jnp.asarray(vhat, dtype=space.spectral_dtype())
        model.state = model.state._replace(**updates)
        model.time = float(np.asarray(h5["time"]))
        _restore_stats(model, h5)
    print(f" <== {filename}")


# ---------------------------------------------------------------------------
# sharded two-phase checkpoints (multihost-grade durability)
# ---------------------------------------------------------------------------

#: root dataset holding the manifest's JSON metadata (dataset, not attr, so
#: the manifest's own content digest covers it)
_MANIFEST_DS = "sharded_manifest"


def is_sharded_checkpoint(filename: str) -> bool:
    """True when ``filename`` is a sharded-checkpoint manifest (cheap attr
    sniff, no digest pass)."""
    try:
        return bool(read_attrs(filename).get("sharded"))
    except CheckpointError:
        return False


def _process_index() -> int:
    try:
        import jax

        return int(jax.process_index())
    except Exception:
        return 0


def _process_count() -> int:
    try:
        import jax

        return int(jax.process_count())
    except Exception:
        return 1


def _shard_crash_hook(point: str, step) -> None:
    """Deterministic crash injection inside the two-phase commit window
    (tests/test_multiprocess.py proves single-host-death recovery with it).

    ``RUSTPDE_SHARD_CRASH=<point>@<step>[:host<p>]`` hard-kills
    (``os._exit(9)``) the matching process when the writer reaches
    ``point`` for the checkpoint at ``step``:

    * ``after_shard``     — the host's shard file is fsynced and in place,
      the barrier/manifest commit has NOT run: the canonical "host dies
      between shard fsync and manifest commit" window,
    * ``before_manifest`` — root passed the barrier + digest exchange but
      has not written the manifest: the commit marker is missing even
      though EVERY shard landed.

    Parsing is STRICT (utils/faults.parse_shard_crash_spec): a malformed
    spec raises a typed FaultSpecError rather than silently never firing —
    a chaos test that isn't injecting is worse than none.  The harness
    constructors validate the env at startup too (faults.validate_fault_env),
    so the raise normally lands before any stepping."""
    from .faults import parse_shard_crash_spec

    plan = parse_shard_crash_spec(env_get("RUSTPDE_SHARD_CRASH"))
    if plan is None or step is None:
        return
    want, at, host = plan
    if want != point or at != int(step):
        return
    if host is not None and _process_index() != host:
        return
    os._exit(9)


def _normalize_index(idx, shape) -> tuple:
    """A shard's ``index`` (tuple of slices) as ``((start, stop), ...)``."""
    out = []
    for sl, n in zip(idx, shape):
        start, stop, _ = sl.indices(n)
        out.append((int(start), int(stop)))
    return tuple(out)


def _owned_slabs(arr, proc: int) -> list:
    """The slabs of ``arr`` THIS process must serialize: each distinct shard
    index is owned by the lowest-id device holding it (so replicated or
    partially-replicated arrays are written exactly once across the whole
    job), and this process writes the slabs whose owner is local.  Returns
    ``[(offset_tuple, numpy_slab), ...]`` (device->host fetch happens
    here)."""
    import jax

    if not isinstance(arr, jax.Array):
        data = np.asarray(arr)
        return [((0,) * data.ndim, data)] if proc == 0 else []
    try:
        imap = arr.sharding.devices_indices_map(arr.shape)
    except Exception:
        # no global placement metadata (single-device array): process 0 owns
        return [((0,) * arr.ndim, np.asarray(arr))] if proc == 0 else []
    owners: dict[tuple, object] = {}
    for dev, idx in imap.items():
        key = _normalize_index(idx, arr.shape)
        prev = owners.get(key)
        if prev is None or dev.id < prev.id:
            owners[key] = dev
    local = {
        _normalize_index(s.index, arr.shape): s.data
        for s in arr.addressable_shards
    }
    slabs = []
    for key, dev in sorted(owners.items()):
        if dev.process_index != proc:
            continue
        offset = tuple(start for start, _ in key)
        slabs.append((offset, np.ascontiguousarray(np.asarray(local[key]))))
    return slabs


def _storage_names(name: str, dtype) -> list[str]:
    """On-disk dataset names for one logical array: complex data splits into
    ``_re``/``_im`` float pairs (the repo-wide HDF5 convention), real data
    keeps its exact dtype under its own name."""
    if np.issubdtype(np.dtype(dtype), np.complexfloating):
        return [f"{name}_re", f"{name}_im"]
    return [name]


def _slab_ds_name(storage: str, offset: tuple) -> str:
    """Slab dataset path inside a shard file.  The offset is encoded in the
    NAME so the shard's content digest covers placement, not just bytes."""
    return f"{storage}/slab_" + "_".join(str(int(o)) for o in offset)


def _slab_offset_of(dsname: str) -> tuple | None:
    base = dsname.rsplit("/", 1)[-1]
    if not base.startswith("slab_"):
        return None
    try:
        return tuple(int(p) for p in base[len("slab_"):].split("_"))
    except ValueError:
        return None


@dataclasses.dataclass
class ShardSnapshot:
    """One process's share of a sharded checkpoint, fully fetched to host.

    ``slabs`` is ``[(storage_path, offset, numpy_array), ...]`` — only this
    host's owned slabs; ``root_datasets`` is the replicated manifest-side
    data (time, params, ensemble bookkeeping — HostSnapshot-style tuples);
    ``meta`` carries the global dataset catalog + mesh topology the root
    embeds in the manifest.  Like :class:`HostSnapshot`, the object is
    device-free: :func:`write_shard_file` (serialize + digest + fsync) can
    run on a background worker while the device steps on — the multihost
    re-enable of the PR-4 overlapped write path."""

    shard_index: int
    shard_count: int
    slabs: list
    root_datasets: list
    meta: dict
    step: int | None = None
    time: float | None = None
    dt: float | None = None
    digest: str | None = None  # set once the shard file is on disk

    @property
    def nbytes(self) -> int:
        return sum(int(arr.nbytes) for _, _, arr in self.slabs)


def sharded_snapshot_to_host(pde, step: int | None = None) -> ShardSnapshot:
    """Fetch THIS process's shard of a model/ensemble snapshot to host
    memory (the one device sync a checkpoint needs — only addressable
    shards move, never the global state).  Collective-free: every process
    calls it independently."""
    proc = _process_index()
    datasets_meta: dict[str, dict] = {}
    slabs: list = []
    for name, arr in pde.snapshot_state_items():
        dtype = np.dtype(arr.dtype)
        storage = _storage_names(name, dtype)
        datasets_meta[name] = {
            "shape": [int(s) for s in arr.shape],
            "dtype": str(dtype),
            "storage": storage,
        }
        for offset, block in _owned_slabs(arr, proc):
            if len(storage) == 2:
                slabs.append((storage[0], offset, np.ascontiguousarray(block.real)))
                slabs.append((storage[1], offset, np.ascontiguousarray(block.imag)))
            else:
                slabs.append((storage[0], offset, block))
    mesh = getattr(pde, "mesh", None)
    if mesh is None and hasattr(pde, "model"):
        mesh = getattr(pde.model, "mesh", None)
    meta = {
        "datasets": datasets_meta,
        "mesh": {
            "process_count": _process_count(),
            "devices": int(np.prod(mesh.devices.shape)) if mesh is not None else 1,
            "axes": list(mesh.axis_names) if mesh is not None else [],
        },
    }
    return ShardSnapshot(
        shard_index=proc,
        shard_count=_process_count(),
        slabs=slabs,
        root_datasets=pde.snapshot_root_items(),
        meta=meta,
        step=step,
        time=float(pde.get_time()),
        dt=float(pde.get_dt()),
    )


def write_shard_file(snap: ShardSnapshot, manifest: str) -> str:
    """Phase one for one host: serialize ``snap``'s slabs to the shard file
    of ``manifest``, atomic and digest-stamped (hash computed from the
    in-memory slabs, no read-back).  Pure host-side work — safe on the
    io_pipeline worker.  Sets ``snap.digest`` and returns the shard path."""
    filename = shard_path(manifest, snap.shard_index)
    items = [
        (_slab_ds_name(storage, offset), arr, "raw")
        for storage, offset, arr in snap.slabs
    ]
    digest = snapshot_digest(items)

    def body(h5):
        for dspath, arr, _ in items:
            gpath, _, dname = dspath.rpartition("/")
            grp = h5.require_group(gpath) if gpath else h5
            grp.create_dataset(dname, data=arr)
        h5.attrs["shard_index"] = int(snap.shard_index)
        h5.attrs["shard_count"] = int(snap.shard_count)

    _atomic_h5_write(
        filename, body, step=snap.step, time=snap.time, dt=snap.dt, digest=digest
    )
    snap.digest = digest
    _shard_crash_hook("after_shard", snap.step)
    return filename


def _pack_shard_report(snap: ShardSnapshot, ok: bool) -> np.ndarray:
    """(digest, nbytes, ok) as a fixed-size uint8 row for the allgather."""
    buf = np.zeros(41, np.uint8)
    if snap.digest is not None:
        buf[:32] = np.frombuffer(bytes.fromhex(snap.digest), np.uint8)
    buf[32:40] = np.frombuffer(np.int64(snap.nbytes).tobytes(), np.uint8)
    buf[40] = 1 if (ok and snap.digest is not None) else 0
    return buf


def commit_sharded_snapshot(
    snap: ShardSnapshot, manifest: str, local_ok: bool = True
) -> dict:
    """Phase two (collective — every process must call it at the same
    point): barrier so every shard is durably on disk, exchange per-shard
    digests + byte counts + ok flags in one small allgather, then ROOT
    atomically writes the manifest — whose presence commits the checkpoint.
    A second barrier keeps any host from acting on the new checkpoint
    (rotation, resume scans) before the commit marker exists.

    Returns ``{"ok", "shards", "bytes_host", "bytes_total", "barrier_s"}``;
    ``ok=False`` (some host failed its shard write) means NO manifest was
    written and the previous checkpoint is still the newest valid one —
    the caller decides whether that is fatal."""
    import time as _time

    from ..parallel import multihost

    t0 = _time.monotonic()
    multihost.sync_hosts("rustpde-ckpt-shards")
    barrier_s = _time.monotonic() - t0
    reports = multihost.allgather_host(_pack_shard_report(snap, local_ok))
    reports = np.atleast_2d(np.asarray(reports, np.uint8))
    oks = [bool(row[40]) for row in reports]
    digests = [bytes(row[:32]).hex() for row in reports]
    nbytes = [int(np.frombuffer(bytes(row[32:40]), np.int64)[0]) for row in reports]
    # the abort decision derives ONLY from the allgathered ok flags —
    # fleet-agreed data, so every host takes the same branch into the
    # abort barrier (lint RPD001 checks exactly this property)
    ok_all = all(oks)
    stats = {
        "ok": ok_all,
        "shards": int(snap.shard_count),
        "bytes_host": int(snap.nbytes),
        "bytes_total": int(sum(nbytes)),
        "barrier_s": round(barrier_s, 3),
    }
    if not ok_all:
        multihost.sync_hosts("rustpde-ckpt-abort")
        return stats
    if _process_index() == 0:
        _shard_crash_hook("before_manifest", snap.step)
        meta = dict(snap.meta)
        meta["shards"] = [
            {
                "file": os.path.basename(shard_path(manifest, i)),
                "process": i,
                "digest": digests[i],
                "nbytes": nbytes[i],
            }
            for i in range(snap.shard_count)
        ]

        def body(h5):
            for path, data, kind in snap.root_datasets:
                gpath, _, name = path.rpartition("/")
                grp = h5.require_group(gpath) if gpath else h5
                if kind == "field":
                    _write_array(grp, name, data)
                else:
                    grp.create_dataset(name, data=data)
            h5.create_dataset(
                _MANIFEST_DS, data=np.bytes_(json.dumps(meta, sort_keys=True))
            )
            h5.attrs["sharded"] = int(snap.shard_count)

        _atomic_h5_write(manifest, body, step=snap.step, time=snap.time, dt=snap.dt)
    multihost.sync_hosts("rustpde-ckpt-commit")
    return stats


def write_sharded_snapshot(pde, filename: str, step: int | None = None) -> dict:
    """Blocking collective sharded checkpoint: fetch this host's slabs,
    write+fsync the shard file, then run the two-phase commit.  Raises
    ``CheckpointError`` on every host when ANY host's shard write failed
    (no manifest is written, so the previous checkpoint stays newest-valid).
    Returns the commit stats dict."""
    snap = sharded_snapshot_to_host(pde, step=step)
    local_error: Exception | None = None
    try:
        write_shard_file(snap, filename)
    except Exception as exc:
        local_error = exc
    stats = commit_sharded_snapshot(snap, filename, local_ok=local_error is None)
    if not stats["ok"]:
        # chain the local cause when THIS host failed; peers raise without
        # one (their shard landed — the abort came from the allgather)
        raise CheckpointError(
            filename,
            "sharded checkpoint aborted: a host failed its shard write "
            "(no manifest committed; the previous checkpoint is intact)"
            + (f"; local cause: {local_error}" if local_error else ""),
        ) from local_error
    return stats


def _read_manifest_meta(h5, filename: str) -> dict:
    try:
        raw = h5[_MANIFEST_DS][()]
    except KeyError as exc:
        raise _missing(h5, _MANIFEST_DS) from exc
    if isinstance(raw, np.ndarray):
        raw = raw.item()
    if isinstance(raw, bytes):
        raw = raw.decode("utf-8")
    try:
        return json.loads(raw)
    except ValueError as exc:
        raise CheckpointError(filename, f"unparseable manifest JSON: {exc}") from exc


def _verify_shard_set(manifest: str, meta: dict, full: bool = True) -> None:
    """Verify every shard named by ``meta`` against its recorded digest.

    ``full=False`` is the cheap cross-check (existence + the shard's own
    digest stamp against the manifest's record, no re-hash of the data) —
    used by NON-ROOT hosts at restore time so a multihost resume reads the
    checkpoint ~2x instead of (N+1)x: root's :func:`verify_snapshot` /
    ``latest_checkpoint`` scan has already re-hashed every shard end-to-end
    before the step number is broadcast."""
    dirname = os.path.dirname(manifest) or "."
    for entry in meta.get("shards", []):
        path = os.path.join(dirname, entry["file"])
        if not os.path.exists(path):
            raise CheckpointError(
                manifest,
                f"missing shard file {entry['file']!r} — the shard set is "
                "incomplete (partial copy or deleted shard)",
            )
        with _open_checkpoint(path) as sh5:
            attrs = _attrs_of(sh5)
            bad = attrs.get("digest") != entry["digest"]
            if not bad and full:
                bad = content_digest(sh5) != entry["digest"]
            if bad:
                raise CheckpointError(
                    manifest,
                    f"shard {entry['file']!r} digest mismatch (bit rot or a "
                    "partially copied shard)",
                )


class _SlabCatalog:
    """Every slab of one verified shard set, indexed by storage path, with
    the owning h5 handles kept open for region reads."""

    def __init__(self, stack: ExitStack, manifest: str, meta: dict):
        import h5py

        self.slabs: dict[str, list] = {}
        dirname = os.path.dirname(manifest) or "."
        for entry in meta.get("shards", []):
            path = os.path.join(dirname, entry["file"])
            try:
                h5 = stack.enter_context(h5py.File(path, "r"))
            except OSError as exc:
                raise CheckpointError(manifest, f"unreadable shard: {exc}") from exc

            def visit(name, obj, h5=h5):
                if not isinstance(obj, h5py.Dataset):
                    return
                offset = _slab_offset_of(name)
                if offset is None:
                    return
                storage = name.rsplit("/", 1)[0]
                self.slabs.setdefault(storage, []).append(
                    (h5, name, offset, tuple(obj.shape))
                )

            h5.visititems(visit)

    def read_region(self, manifest: str, storage: str, region, dtype):
        """Assemble the rectangular ``region`` (tuple of (start, stop)) of
        global dataset ``storage`` from whichever slabs intersect it; only
        the intersecting slab bytes are read.  Incomplete coverage raises
        :class:`CheckpointError` (a shard set from a different layout)."""
        starts = [s for s, _ in region]
        sizes = [e - s for s, e in region]
        out = np.zeros(sizes, dtype=np.dtype(dtype))
        filled = np.zeros(sizes, dtype=bool)
        for h5, dsname, offset, sshape in self.slabs.get(storage, []):
            src_sel, dst_sel = [], []
            empty = False
            for (rs, re_), so, sn in zip(region, offset, sshape):
                lo, hi = max(rs, so), min(re_, so + sn)
                if lo >= hi:
                    empty = True
                    break
                src_sel.append(slice(lo - so, hi - so))
                dst_sel.append(slice(lo - rs, hi - rs))
            if empty:
                continue
            out[tuple(dst_sel)] = h5[dsname][tuple(src_sel)]
            filled[tuple(dst_sel)] = True
        if not filled.all():
            raise CheckpointError(
                manifest,
                f"shard set does not cover dataset {storage!r} region "
                f"{[(s, s + n) for s, n in zip(starts, sizes)]}",
            )
        return out

    def read_logical(self, manifest: str, name: str, dmeta: dict, region):
        """One logical dataset's region, re/im-merged back to its dtype."""
        dtype = np.dtype(dmeta["dtype"])
        storage = dmeta["storage"]
        if len(storage) == 2:
            fdt = np.zeros(0, dtype).real.dtype
            re_ = self.read_region(manifest, storage[0], region, fdt)
            im = self.read_region(manifest, storage[1], region, fdt)
            return (re_ + 1j * im).astype(dtype, copy=False)
        return self.read_region(manifest, storage[0], region, dtype)


def _target_region(idx, shape) -> tuple:
    return _normalize_index(idx, shape)


def read_sharded_snapshot(pde, filename: str) -> None:
    """Topology-elastic restore of a sharded checkpoint onto ``pde``.

    The writer's mesh shape, host count and device order are IRRELEVANT:
    each process assembles, for every state leaf, exactly the slab regions
    its own devices need under the TARGET layout — per-device buffers are
    built with :func:`jax.make_array_from_single_device_arrays` on a mesh
    (a serial model just gets the assembled global array) — so a checkpoint
    written under mesh ``(2,)`` restores onto serial, a 4-device mesh, a
    reversed-order mesh or a different host count, bit-equal to the
    writer's state.  Resolution/dtype changes are rejected with
    :class:`CheckpointError` (use the gathered snapshot format for
    spectral interpolation)."""
    import jax
    import jax.numpy as jnp

    from ..parallel.mesh import SPEC, pencil_sharding

    with _open_checkpoint(filename) as h5:
        attrs = _verify_open_file(h5, filename)
        if not attrs.get("sharded"):
            raise CheckpointError(filename, "not a sharded-checkpoint manifest")
        meta = _read_manifest_meta(h5, filename)
        root: dict[str, np.ndarray] = {}
        for name, obj in h5.items():
            if name != _MANIFEST_DS and hasattr(obj, "shape"):
                root[name] = np.asarray(obj)
    if hasattr(pde, "k") and "members" in root:
        # member-count mismatch gets ITS message, not the per-leaf shape
        # gate's interpolation advice (which would be wrong here)
        k = int(np.asarray(root["members"]))
        if k != int(pde.k):
            raise CheckpointError(
                filename,
                f"checkpoint holds {k} members but the ensemble has "
                f"{pde.k}; sharded restore is K-fixed (the gathered "
                "per-member format is the K-elastic one)",
            )
    # root re-hashes the full shard set; peers run the cheap digest-attr
    # cross-check — a multihost resume then costs ~2x the checkpoint bytes
    # in shared-storage reads, not (N+1)x (root already verified end-to-end
    # at selection time, and the assembly below reads only needed slabs)
    _verify_shard_set(filename, meta, full=_process_index() == 0)

    mesh = getattr(pde, "mesh", None)
    if mesh is None and hasattr(pde, "model"):
        mesh = getattr(pde.model, "mesh", None)
    scope = pde.model._scope if hasattr(pde, "model") else pde._scope

    updates: dict[str, object] = {}
    with ExitStack() as stack:
        catalog = _SlabCatalog(stack, filename, meta)
        for name, arr in pde.snapshot_state_items():
            dmeta = meta["datasets"].get(name)
            if dmeta is None:
                if name.startswith("stats/"):
                    # checkpoint written before the stats engine was armed:
                    # the averaging window restarts (apply_restored_state
                    # zero-fills the absent leaves) — the STATE restore
                    # stays bit-exact either way
                    print(
                        f"sharded checkpoint lacks {name!r}; running "
                        "averages restart from zero"
                    )
                    continue
                raise CheckpointError(filename, f"manifest lacks dataset {name!r}")
            if tuple(dmeta["shape"]) != tuple(arr.shape):
                raise CheckpointError(
                    filename,
                    f"{name}: checkpoint shape {tuple(dmeta['shape'])} != model "
                    f"shape {tuple(arr.shape)} — sharded restore is topology-"
                    "elastic but resolution-fixed (use the gathered format "
                    "to interpolate)",
                )
            if str(np.dtype(dmeta["dtype"])) != str(np.dtype(arr.dtype)):
                raise CheckpointError(
                    filename,
                    f"{name}: checkpoint dtype {dmeta['dtype']} != model dtype "
                    f"{arr.dtype} (precision mode mismatch)",
                )
            leaf = name.rsplit("/", 1)[-1]
            if mesh is None:
                full = catalog.read_logical(
                    filename, name, dmeta, tuple((0, n) for n in arr.shape)
                )
                updates[leaf] = jnp.asarray(full)
                continue
            target = pencil_sharding(mesh, SPEC, ndim=len(arr.shape))
            # explicit placement rejects non-divisible sharded dims (the odd
            # spectral sizes); GSPMD's constraint path rounds those to
            # replicated, so the restore target mirrors that rule — the
            # restored leaf then matches the layout the stepped model holds
            divisible = all(
                sp is None or arr.shape[i] % mesh.shape[sp] == 0
                for i, sp in enumerate(target.spec)
            )
            if not divisible:
                target = pencil_sharding(mesh, (None,) * len(arr.shape))
            idx_map = target.addressable_devices_indices_map(tuple(arr.shape))
            buffers = []
            for dev, idx in idx_map.items():
                region = _target_region(idx, arr.shape)
                block = catalog.read_logical(filename, name, dmeta, region)
                buffers.append(jax.device_put(block, dev))
            updates[leaf] = jax.make_array_from_single_device_arrays(
                tuple(arr.shape), target, buffers
            )
    with scope():
        pde.apply_restored_state(updates, attrs, root)
    print(f" <== {filename} (sharded, {int(attrs['sharded'])} shard(s))")


# -- durable parked continuations (serve/fleet) -------------------------------
#
# A parked mid-flight member state (elastic shrink, proactive dt
# re-bucket, QoS preemption) was process-local in PR 10: a replica death
# before the park was re-claimed restarted that request from step 0.  The
# fleet layer persists each park as a per-request continuation dir,
# two-phase like every other durable write in this file:
#
#     parked/<request-id>/shard_00000.h5   per-process state slabs,
#                                          digest-stamped, atomic
#     parked/<request-id>/manifest.json    the COMMIT MARKER (atomic
#                                          rename + dirsync): a crash
#                                          mid-write leaves shards with
#                                          no manifest = no continuation
#
# so ANY replica that later claims the request resumes the trajectory
# mid-flight from durable state instead of restarting.

CONTINUATION_MANIFEST = "manifest.json"


def continuation_dir(run_dir: str, request_id: str) -> str:
    """``<run_dir>/parked/<id>`` — one continuation dir per request."""
    return os.path.join(run_dir, "parked", str(request_id))


def continuation_exists(cont_dir: str) -> bool:
    """True when a COMMITTED continuation is present (manifest = marker)."""
    return os.path.exists(os.path.join(cont_dir, CONTINUATION_MANIFEST))


def continuation_meta(cont_dir: str) -> tuple[int, float] | None:
    """``(base_steps, time_base)`` of a committed continuation — the
    host-side progress accounting a scheduler plan needs BEFORE deciding
    to restore the (much larger) state shards; None when no committed
    continuation exists."""
    try:
        with open(
            os.path.join(cont_dir, CONTINUATION_MANIFEST), encoding="utf-8"
        ) as fh:
            record = json.load(fh)
        return int(record["base"]), float(record["time_base"])
    except (OSError, ValueError, KeyError):
        return None


def continuation_record(cont_dir: str) -> dict | None:
    """The full committed-continuation manifest record (progress, shard
    table, writer-supplied ``meta`` — request id, dt, and the sub-mesh
    stamp a gang park carries), host-side JSON only; None when no
    committed continuation exists.  The gang recovery path reads this to
    verify a parked SHARDED state's topology (``meta.submesh``,
    ``len(shards)``) matches the bucket re-forming over it, and the
    chaos-soak gates assert reclaimed-with-state through it."""
    try:
        with open(
            os.path.join(cont_dir, CONTINUATION_MANIFEST), encoding="utf-8"
        ) as fh:
            record = json.load(fh)
    except (OSError, ValueError):
        return None
    if "base" not in record or "time_base" not in record:
        return None
    return record


def write_continuation(
    cont_dir: str, state, *, base: int, time_base: float, meta: dict | None = None
) -> str:
    """Persist one parked member state, two-phase (collective on a
    multi-process runtime — every host calls this together, like the
    sharded checkpoint writer it mirrors): each process writes its
    host-local state slabs to ``shard_<p>.h5`` (fsynced, digest-stamped),
    digests are exchanged, then ROOT atomically writes the manifest whose
    presence commits the continuation.  Raises :class:`CheckpointError`
    on a failed shard write (no manifest is committed)."""
    from ..parallel import multihost

    proc = _process_index()
    nproc = _process_count()
    fields = list(state._fields)
    slabs = {name: multihost.host_local_array(getattr(state, name)) for name in fields}
    items = [(f"state/{name}", arr, "raw") for name, arr in sorted(slabs.items())]
    digest = snapshot_digest(items)
    shard_file = os.path.join(cont_dir, f"shard_{proc:05d}.h5")

    def body(h5):
        grp = h5.require_group("state")
        for name in fields:
            grp.create_dataset(name, data=slabs[name])
        h5.attrs["shard_index"] = int(proc)
        h5.attrs["shard_count"] = int(nproc)

    local_error: Exception | None = None
    try:
        _atomic_h5_write(shard_file, body, step=int(base), digest=digest)
    except Exception as exc:  # noqa: BLE001 — the commit exchange decides
        local_error = exc
    if nproc == 1:
        digests, oks = [digest], [local_error is None]
    else:
        # the allgather doubles as the phase barrier: it completes only
        # after every host's shard write attempt resolved
        rows = multihost.allgather_bytes(
            json.dumps(
                {"digest": digest, "ok": local_error is None}
            ).encode("utf-8")
        )
        parsed = [json.loads(r.decode("utf-8")) for r in rows]
        digests = [p["digest"] for p in parsed]
        oks = [bool(p["ok"]) for p in parsed]
    manifest = os.path.join(cont_dir, CONTINUATION_MANIFEST)
    if not all(oks):
        if nproc > 1:
            multihost.sync_hosts("rustpde-continuation-abort")
        raise CheckpointError(
            manifest,
            "continuation persist aborted: a host failed its shard write "
            "(no manifest committed)"
            + (f"; local cause: {local_error}" if local_error else ""),
        ) from local_error
    if proc == 0:
        record = {
            "schema": SCHEMA_VERSION,
            "base": int(base),
            "time_base": float(time_base),
            "fields": fields,
            "shards": [
                {"file": f"shard_{i:05d}.h5", "digest": d}
                for i, d in enumerate(digests)
            ],
            "meta": dict(meta or {}),
        }
        # the COMMIT marker: strict dirsync — a failed dirsync must
        # report the continuation NOT committed
        fsutil.atomic_write_text(
            manifest, json.dumps(record, sort_keys=True), strict=True
        )
    if nproc > 1:
        multihost.sync_hosts("rustpde-continuation-commit")
    return manifest


def read_continuation(cont_dir: str, template_state):
    """Restore a committed continuation: ``(state, base, time_base)``.

    Each process reads ITS shard (digest-verified end-to-end), checks
    every leaf's shape/dtype against ``template_state`` (a donor member
    state of the claiming ensemble — same compat bucket, so same shapes
    by construction), and on a multi-process runtime reassembles the
    host-local slabs into global arrays with the template leaf's
    sharding.  Raises :class:`CheckpointError` on a missing/uncommitted
    continuation or any verification failure — callers degrade to a
    from-scratch restart, never a torn state."""
    import h5py

    from ..parallel import multihost

    manifest = os.path.join(cont_dir, CONTINUATION_MANIFEST)
    try:
        with open(manifest, encoding="utf-8") as fh:
            record = json.load(fh)
    except (OSError, ValueError) as exc:
        raise CheckpointError(
            manifest, f"no committed continuation: {exc}"
        ) from exc
    fields = list(record.get("fields", ()))
    if fields != list(template_state._fields):
        raise CheckpointError(
            manifest,
            f"continuation fields {fields} != state fields "
            f"{list(template_state._fields)} (model kind changed?)",
        )
    proc = _process_index()
    shards = record.get("shards", [])
    if proc >= len(shards):
        raise CheckpointError(
            manifest,
            f"continuation holds {len(shards)} shard(s) but this is "
            f"process {proc}: written under a different topology",
        )
    path = os.path.join(cont_dir, shards[proc]["file"])
    with _open_checkpoint(path) as h5:
        attrs = _attrs_of(h5)
        if attrs.get("digest") != shards[proc]["digest"]:
            raise CheckpointError(
                manifest, f"shard {shards[proc]['file']!r} digest mismatch"
            )
        if content_digest(h5) != shards[proc]["digest"]:
            raise CheckpointError(
                manifest, f"shard {shards[proc]['file']!r} content mismatch"
            )
        slabs = {name: np.asarray(h5["state"][name]) for name in fields}
    leaves = {}
    for name in fields:
        tmpl = getattr(template_state, name)
        slab = slabs[name]
        if _process_count() == 1:
            if tuple(slab.shape) != tuple(tmpl.shape) or str(
                np.dtype(slab.dtype)
            ) != str(np.dtype(tmpl.dtype)):
                raise CheckpointError(
                    manifest,
                    f"{name}: continuation {slab.shape}/{slab.dtype} != "
                    f"state {tuple(tmpl.shape)}/{tmpl.dtype}",
                )
            leaves[name] = slab
        else:
            leaves[name] = multihost.global_array(slab, tmpl.sharding)
    return (
        type(template_state)(**leaves),
        int(record.get("base", 0)),
        float(record.get("time_base", 0.0)),
    )


def remove_continuation(cont_dir: str) -> None:
    """Retire a consumed continuation: the MANIFEST goes first (atomic
    uncommit — a crash mid-removal leaves shards with no marker, which
    reads as "no continuation", never a torn one), then the shards and
    the dir itself.  Root-only on multi-process runtimes (host-local
    filesystem work; the caller fences)."""
    manifest = os.path.join(cont_dir, CONTINUATION_MANIFEST)
    try:
        os.remove(manifest)
        fsync_dir(cont_dir)
    except OSError:
        pass
    try:
        for name in os.listdir(cont_dir):
            try:
                os.remove(os.path.join(cont_dir, name))
            except OSError:
                pass
        fsync_dir(cont_dir)
        os.rmdir(cont_dir)
        fsync_dir(os.path.dirname(cont_dir) or ".")
    except OSError:
        pass
