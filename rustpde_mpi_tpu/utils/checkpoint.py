"""HDF5 checkpoint/restart with the reference's snapshot layout.

Rebuild of /root/reference/src/navier_stokes/navier_io.rs + src/field/io.rs +
src/io/read_write_hdf5.rs:

* per-variable groups ``{var}/{x,dx,y,dy,v,vhat}`` with variables named
  ``ux, uy, temp, pres`` (+ ``tempbc``); complex spectral data stored as
  ``vhat_re``/``vhat_im`` dataset pairs
  (/root/reference/src/io/read_write_hdf5.rs:171-188),
* scalars ``time`` + physics params at the file root,
* restart restores spectral coefficients, supporting **resolution change via
  spectral truncation/zero-padding** with r2c Nyquist-mode bookkeeping (no
  Fourier renormalization — see :func:`interpolate_2d`; the reference's
  (new-1)/(old-1) factor compensates its unnormalized rustfft convention,
  /root/reference/src/field/io.rs:151-176).

One deliberate fix over the reference: the reference writes the coordinate
array into both the ``x`` and ``dx`` datasets (field/io.rs:96-99); here ``dx``
holds the actual grid deltas.  Readers that only consume ``x``/``y``/``v``
(the plot/ scripts, xmf generator) see identical layout.
"""

from __future__ import annotations

import os

import numpy as np

from ..bases import BaseKind, Space2
from ..field import grid_deltas

_VARS = (("ux", "velx"), ("uy", "vely"), ("temp", "temp"), ("pres", "pres"))


def _write_array(group, name: str, data: np.ndarray) -> None:
    if np.iscomplexobj(data):
        _write_array(group, f"{name}_re", np.ascontiguousarray(data.real))
        _write_array(group, f"{name}_im", np.ascontiguousarray(data.imag))
        return
    if name in group:
        del group[name]
    group.create_dataset(name, data=np.asarray(data, dtype=np.float64))


def _read_array(group, name: str, is_complex: bool) -> np.ndarray:
    if is_complex:
        return np.asarray(group[f"{name}_re"]) + 1j * np.asarray(group[f"{name}_im"])
    return np.asarray(group[name])


def interpolate_2d(
    old: np.ndarray,
    new_shape: tuple[int, int],
    kind_x: BaseKind,
    old_nx: int | None = None,
    new_nx: int | None = None,
) -> np.ndarray:
    """Spectral interpolation on resolution change: truncate / zero-pad the
    coefficient array (/root/reference/src/field/io.rs:151-176).

    Unlike the reference, no global Fourier renormalization is applied: the
    reference's rustfft forward is unnormalized (coefficients scale with n),
    while this repo's r2c forward is amplitude-normalized (rfft/n), so
    coefficients are grid-size independent.  What the r2c axis does need is
    the Nyquist-mode bookkeeping (``old_nx``/``new_nx`` are the physical grid
    sizes): an even-grid Nyquist coefficient represents cos(Nx) counted once,
    so when it becomes a regular +k mode of the new grid it must be halved,
    and when a regular +k/-k pair lands on the new grid's Nyquist it folds to
    double the real part.  This covers resolution changes that keep the
    spectral shape but flip grid parity (e.g. nx 16 -> 17)."""
    new = np.zeros(new_shape, dtype=old.dtype)
    s0 = min(old.shape[0], new_shape[0])
    s1 = min(old.shape[1], new_shape[1])
    new[:s0, :s1] = old[:s0, :s1]
    if kind_x == BaseKind.FOURIER_R2C:
        if old_nx is None:
            import warnings

            warnings.warn(
                "r2c restart interpolation without the source grid size "
                "(missing 'x' dataset): assuming an even source grid for "
                "Nyquist-mode bookkeeping",
                stacklevel=2,
            )
            old_nx = 2 * (old.shape[0] - 1)
        old_nyq = old.shape[0] - 1 if old_nx % 2 == 0 else None
        new_nyq = (
            new_shape[0] - 1 if new_nx is not None and new_nx % 2 == 0 else None
        )
        if old_nyq is not None and old_nyq < s0 and old_nyq != new_nyq:
            new[old_nyq, :] *= 0.5  # old Nyquist -> regular +k mode
        if new_nyq is not None and new_nyq < s0 and new_nyq != old_nyq:
            new[new_nyq, :] = 2.0 * new[new_nyq, :].real  # +-k fold onto Nyquist
    return new


def write_field(h5, varname: str, space: Space2, vhat, x, dx) -> None:
    """Write one field group in the reference layout.  Split-Fourier spaces
    store their coefficients in the complex convention (vhat_re/vhat_im), so
    files are layout-identical across backends."""
    grp = h5.require_group(varname)
    _write_array(grp, "x", x[0])
    _write_array(grp, "dx", dx[0])
    _write_array(grp, "y", x[1])
    _write_array(grp, "dy", dx[1])
    _write_array(grp, "v", np.asarray(space.backward(vhat)))
    _write_array(grp, "vhat", space.vhat_as_complex(vhat))


def read_field_vhat(h5, varname: str, space: Space2) -> np.ndarray:
    """Read one field's spectral coefficients, interpolating on mismatch.

    Files always carry the complex convention for periodic axes; a split
    target space converts after the (complex-domain) interpolation."""
    grp = h5[varname]
    split = space.bases[0].kind.is_split
    is_complex = space.spectral_is_complex or split
    data = _read_array(grp, "vhat", is_complex)
    old_nx = grp["x"].shape[0] if "x" in grp else None
    if split:
        target_shape = (space.bases[0].m_complex, space.bases[1].m)
        kind_x = BaseKind.FOURIER_R2C
    else:
        target_shape = space.shape_spectral
        kind_x = space.base_kind(0)
    # interpolate on shape mismatch, and also when the shapes agree but the
    # r2c grid parity changed (nx 16 -> 17 keeps m = 9 yet re-types the
    # Nyquist row)
    parity_flip = (
        kind_x == BaseKind.FOURIER_R2C
        and old_nx is not None
        and old_nx % 2 != space.shape_physical[0] % 2
    )
    if data.shape != target_shape or parity_flip:
        data = interpolate_2d(
            data,
            target_shape,
            kind_x,
            old_nx=old_nx,
            new_nx=space.shape_physical[0],
        )
    # vhat_from_complex is also the sep-layout boundary (Space2 stores
    # Chebyshev spectral axes parity-permuted on the TPU path), so it must
    # run for non-split spaces too — h5 files always hold natural order
    return space.vhat_from_complex(data)


def _model_coords(model):
    xs = model.x  # scaled coords the model already derived
    dxs = [
        grid_deltas(b.points, b.is_periodic) * s
        for b, s in zip(model.field_space.bases, model.scale)
    ]
    return xs, dxs


def write_snapshot(model, filename: str) -> None:
    """Write a flow snapshot (/root/reference/src/navier_stokes/navier_io.rs:44-62)."""
    import h5py

    os.makedirs(os.path.dirname(filename) or ".", exist_ok=True)
    xs, dxs = _model_coords(model)
    with h5py.File(filename, "w") as h5:
        for varname, attr in _VARS:
            space = getattr(model, f"{attr}_space")
            write_field(h5, varname, space, getattr(model.state, attr), xs, dxs)
        if getattr(model, "tempbc_ortho", None) is not None:
            write_field(h5, "tempbc", model.field_space, model.tempbc_ortho, xs, dxs)
        h5.create_dataset("time", data=float(model.time))
        for key, value in model.params.items():
            h5.create_dataset(key, data=float(value))


def write_ensemble_snapshot(ens, filename: str) -> None:
    """Write a K-member ensemble snapshot: groups ``member{i}`` each holding
    the reference single-run variable layout (:func:`write_field`), plus
    root-level ensemble bookkeeping — ``time``, ``members``, per-member
    ``alive`` mask and ``steps_done`` counters, physics params, and the
    shared ``tempbc`` lift field (written once, members share it)."""
    import h5py

    os.makedirs(os.path.dirname(filename) or ".", exist_ok=True)
    model = ens.model
    xs, dxs = _model_coords(model)
    with h5py.File(filename, "w") as h5:
        for i in range(ens.k):
            grp = h5.require_group(f"member{i}")
            for varname, attr in _VARS:
                space = getattr(model, f"{attr}_space")
                write_field(grp, varname, space, getattr(ens.state, attr)[i], xs, dxs)
        if getattr(model, "tempbc_ortho", None) is not None:
            write_field(h5, "tempbc", model.field_space, model.tempbc_ortho, xs, dxs)
        h5.create_dataset("time", data=float(ens.time))
        h5.create_dataset("members", data=int(ens.k))
        h5.create_dataset("alive", data=np.asarray(ens.mask).astype(np.int8))
        h5.create_dataset(
            "steps_done", data=np.asarray(ens.steps_done, dtype=np.int64)
        )
        for key, value in model.params.items():
            h5.create_dataset(key, data=float(value))


def read_ensemble_snapshot(ens, filename: str) -> None:
    """Restore an ensemble snapshot written by :func:`write_ensemble_snapshot`.

    Member count may differ from the target ensemble's — the state, mask and
    counters are rebuilt at the file's K.  Each member goes through
    :func:`read_field_vhat`, so per-member resolution interpolation works
    exactly like the single-run restart path.  ``pseu`` (the pressure
    increment, not stored — reference layout) restarts at zero."""
    import h5py

    import jax
    import jax.numpy as jnp

    from ..models.navier import NavierState

    model = ens.model
    with h5py.File(filename, "r") as h5:
        k = int(np.asarray(h5["members"]))
        members = []
        for i in range(k):
            grp = h5[f"member{i}"]
            updates = {}
            for varname, attr in _VARS:
                space = getattr(model, f"{attr}_space")
                vhat = read_field_vhat(grp, varname, space)
                updates[attr] = jnp.asarray(vhat, dtype=space.spectral_dtype())
            updates["pseu"] = jnp.zeros(
                model.pseu_space.shape_spectral, model.pseu_space.spectral_dtype()
            )
            members.append(NavierState(**updates))
        with model._scope():
            ens.state = jax.tree.map(lambda *xs: jnp.stack(xs), *members)
            ens.k = k
            ens.mask = jnp.asarray(np.asarray(h5["alive"], dtype=bool))
            ens.steps_done = jnp.asarray(
                np.asarray(h5["steps_done"]), dtype=jnp.int32
            )
        ens.time = float(np.asarray(h5["time"]))
    ens._obs_cache = None
    print(f" <== {filename} ({k} members)")


def read_snapshot(model, filename: str) -> None:
    """Restore a flow snapshot: spectral coefficients + time
    (/root/reference/src/navier_stokes/navier_io.rs:21-29)."""
    import h5py

    import jax.numpy as jnp

    with h5py.File(filename, "r") as h5:
        updates = {}
        for varname, attr in _VARS:
            space = getattr(model, f"{attr}_space")
            vhat = read_field_vhat(h5, varname, space)
            updates[attr] = jnp.asarray(vhat, dtype=space.spectral_dtype())
        model.state = model.state._replace(**updates)
        model.time = float(np.asarray(h5["time"]))
    print(f" <== {filename}")
