"""Deterministic fault-injection spec parsing (STRICT).

``RUSTPDE_FAULT`` and ``RUSTPDE_SHARD_CRASH`` drive every chaos test and
soak gate in the repo.  A malformed spec that silently injects *nothing* is
worse than no spec at all — the chaos run goes green while testing the
happy path — so every parse error here raises a typed
:class:`FaultSpecError` naming the spec and the expected grammar, and the
consumers (:class:`~rustpde_mpi_tpu.utils.resilience.ResilientRunner`,
``serve.SimServer``) validate the environment at STARTUP via
:func:`validate_fault_env`, before any stepping happens.

This module is import-light on purpose (no jax): utils/checkpoint.py calls
into it from inside the two-phase commit window.
"""

from __future__ import annotations

import dataclasses
import os

FAULT_KINDS = ("nan", "spike", "kill", "slow", "bitflip")
SHARD_CRASH_POINTS = ("after_shard", "before_manifest")


class FaultSpecError(ValueError):
    """A fault-injection spec (``RUSTPDE_FAULT`` / ``RUSTPDE_SHARD_CRASH``)
    failed to parse.  Subclasses ValueError so legacy callers catching that
    keep working; raised at startup so a chaos run that would silently
    inject nothing dies loudly instead."""

    def __init__(self, spec: str, expected: str, detail: str = ""):
        msg = f"bad fault spec {spec!r}: expected {expected}"
        if detail:
            msg += f" ({detail})"
        super().__init__(msg)
        self.spec = spec


def _parse_host_scope(token: str, spec: str, expected: str) -> int:
    if not token.startswith("host") or not token[4:].isdigit():
        raise FaultSpecError(
            spec, expected, f"bad host scope {token!r}, expected host<p>"
        )
    return int(token[4:])


def _parse_gang_scope(
    token: str, spec: str, expected: str
) -> tuple[int, int | None]:
    """``gang<g>`` or ``gang<g>member<m>`` -> (gang, member).  STRICT:
    anything else (missing indices, trailing junk) is a typed
    :class:`FaultSpecError` — a chaos soak whose kill silently never
    scopes is a green lie."""
    body = token[len("gang"):]
    gang_digits, sep, member_part = body.partition("member")
    if not gang_digits.isdigit():
        raise FaultSpecError(
            spec, expected,
            f"bad gang scope {token!r}, expected gang<g>[member<m>]",
        )
    if not sep:
        if member_part:
            raise FaultSpecError(
                spec, expected,
                f"bad gang scope {token!r}, expected gang<g>[member<m>]",
            )
        return int(gang_digits), None
    if not member_part.isdigit():
        raise FaultSpecError(
            spec, expected,
            f"bad gang scope {token!r}, expected gang<g>[member<m>]",
        )
    return int(gang_digits), int(member_part)


@dataclasses.dataclass
class FaultPlan:
    """Parsed ``RUSTPDE_FAULT`` spec ``<kind>@<step>[:host<p>]``: inject
    ``kind`` once when the run's global step counter reaches ``step``,
    optionally scoped to ONE process of a multihost job (``host`` = process
    index; every host still *fires* the plan at the same step so collective
    dispatch stays aligned — only the scoped host acts).

    * ``nan``   — poison the state (every recovery path downstream of the
      model's NaN break criterion); host-scoped, only the columns owned by
      that host's devices are poisoned (a single-host fault that then
      propagates through the collective step, the realistic multihost
      divergence shape),
    * ``spike`` — scale the velocity fields by ``spike_factor`` on-device:
      the state stays *finite* but its CFL number blows past the sentinel
      ceiling, so this exercises the stability governor's pre-divergence
      catch + in-memory rollback + dt-ladder descent/regrowth — and, on an
      ungoverned run, the incipient-blow-up-to-NaN path; host-scoped like
      ``nan``,
    * ``kill``  — SIGTERM this process (the preemption path).  HOST-SCOPED
      kill is a hard ``SIGKILL`` instead: one host of a multihost job dying
      without ceremony (the surviving hosts hit the next collective and
      need ``RUSTPDE_SYNC_TIMEOUT_S`` to convert the wedge into a
      structured ``DispatchHang``),
    * ``slow``  — stall the next dispatch past the watchdog deadline (the
      ``DispatchHang`` path); host-scoped, only that host stalls,
    * ``bitflip`` — flip ONE high-mantissa bit of one spectral coefficient
      on-device (deterministically positioned from ``step``): the state
      stays finite and CFL-sane, so this is INVISIBLE to every loud
      sentinel and caught only by the integrity layer's digest audits.
      Host-scoped, the flipped coefficient lives in a column owned by that
      host's devices (every process computes the same flip so collective
      dispatch stays aligned); ``:member<k>`` scopes the flip to one
      ensemble member's slice, exercising per-member digest localization.

    GANG scope (``:gang<g>`` or ``:gang<g>member<m>``, two-level serving):
    the fault acts only inside the gang campaign the scheduler BINDS at
    open (:meth:`bind_gang` — ``g`` is the carved sub-mesh index, ``m``
    the process's member rank within the gang).  A gang-scoped ``kill``
    is a hard ``SIGKILL`` like a host-scoped one: the exact dead-gang-
    member shape the gang barrier watchdog
    (``RUSTPDE_GANG_SYNC_TIMEOUT_S``) must convert into a typed
    ``GangMemberLost``.  Outside any bound gang the fault never acts.

    The two-phase checkpoint WINDOW faults (kill between shard fsync and
    manifest commit) are a separate hook — ``RUSTPDE_SHARD_CRASH``, parsed
    by :func:`parse_shard_crash_spec` — because they key on a phase of the
    commit protocol, not a step count."""

    kind: str
    step: int
    host: int | None = None
    gang: int | None = None
    member: int | None = None
    # bare ensemble-member scope (``:member<k>``, no gang): acts on every
    # process (the member axis is vmapped, not sharded) but the injected
    # corruption touches only member k's leading-axis slice
    only_member: int | None = None
    fired: bool = False
    # runtime binding (not part of the spec): the scheduler sets these at
    # gang-campaign open and clears them at close — None = not in a gang
    bound_gang: int | None = None
    bound_member: int | None = None

    KINDS = FAULT_KINDS
    EXPECTED = (
        "<nan|spike|kill|slow|bitflip>@<step>"
        "[:host<p>|:member<k>|:gang<g>[member<m>]]"
    )

    @classmethod
    def from_spec(cls, spec: str | None) -> "FaultPlan | None":
        if not spec:
            return None
        kind, sep, rest = spec.partition("@")
        at, hsep, scope = rest.partition(":")
        if kind not in cls.KINDS or not sep:
            raise FaultSpecError(spec, cls.EXPECTED, f"unknown kind {kind!r}")
        try:
            step = int(at)
        except ValueError:
            raise FaultSpecError(
                spec, cls.EXPECTED, f"bad step {at!r}, expected an integer"
            ) from None
        host = gang = member = only_member = None
        if hsep:
            if scope.startswith("gang"):
                gang, member = _parse_gang_scope(scope, spec, cls.EXPECTED)
            elif scope.startswith("member"):
                # bare ensemble-member scope (no gang): member<k>
                digits = scope[len("member"):]
                if not digits.isdigit():
                    raise FaultSpecError(
                        spec, cls.EXPECTED,
                        f"bad member scope {scope!r}, expected member<k>",
                    )
                only_member = int(digits)
            else:
                host = _parse_host_scope(scope, spec, cls.EXPECTED)
        return cls(
            kind=kind, step=step, host=host, gang=gang, member=member,
            only_member=only_member,
        )

    def bind_gang(self, gang: int | None, member: int | None) -> None:
        """Bind (or, with Nones, unbind) the running gang campaign: the
        serve scheduler calls this at gang-campaign open/close so a
        gang-scoped spec can resolve "am I the target?" locally."""
        self.bound_gang = gang
        self.bound_member = member

    def scoped_here(self) -> bool:
        """True when this process must ACT on the fault (unscoped, or the
        scope names this process / this bound gang member)."""
        if self.gang is not None:
            if self.bound_gang != self.gang:
                return False
            if self.member is not None:
                return self.bound_member == self.member
            return True
        if self.host is None:
            return True
        try:
            import jax

            return int(jax.process_index()) == self.host
        except Exception:
            return self.host == 0


_SHARD_CRASH_EXPECTED = "<after_shard|before_manifest>@<step>[:host<p>]"


def parse_shard_crash_spec(spec: str | None) -> tuple[str, int, int | None] | None:
    """Strict parse of ``RUSTPDE_SHARD_CRASH`` into ``(point, step, host)``.

    ``point`` names a phase of the two-phase commit protocol (see
    utils/checkpoint._shard_crash_hook); anything else — unknown point,
    non-integer step, malformed host scope — raises
    :class:`FaultSpecError` instead of silently never firing."""
    if not spec:
        return None
    point, sep, rest = spec.partition("@")
    if not sep or point not in SHARD_CRASH_POINTS:
        raise FaultSpecError(
            spec, _SHARD_CRASH_EXPECTED, f"unknown crash point {point!r}"
        )
    at, hsep, host = rest.partition(":")
    try:
        step = int(at)
    except ValueError:
        raise FaultSpecError(
            spec, _SHARD_CRASH_EXPECTED, f"bad step {at!r}, expected an integer"
        ) from None
    return point, step, (
        _parse_host_scope(host, spec, _SHARD_CRASH_EXPECTED) if hsep else None
    )


def validate_fault_env() -> None:
    """Parse every fault-injection env var once, at startup: a chaos run
    whose spec cannot fire must die HERE, not report green.  Called by the
    harness constructors (ResilientRunner, SimServer)."""
    FaultPlan.from_spec(os.environ.get("RUSTPDE_FAULT"))
    parse_shard_crash_spec(os.environ.get("RUSTPDE_SHARD_CRASH"))
