"""Overlapped I/O pipeline: async checkpoint writes + observable futures.

Every host-side IO the run loop performs today is synchronous and sits on
the device's critical path: a checkpoint write fetches the state, runs the
backward transforms, sha256-hashes every dataset and fsyncs the file while
the accelerator idles; a diagnostics callback blocks on four separate
device-to-host scalar transfers before the next chunk is dispatched.  At
production grid sizes (multi-GB snapshots, ~110 ms per host sync through
the TPU relay) that IO tax is pure dead time — the device work for the next
chunk is already known and could be in flight.

This module supplies the three pieces that take IO off the critical path
while keeping every durability guarantee of utils/checkpoint.py:

* **observable futures** (:class:`ObservableFuture`) — a handle to device
  values that have been *dispatched* but not fetched.  ``ready()`` is a
  non-blocking completion probe (``jax.Array.is_ready``), ``result()``
  fetches the whole pytree in ONE transfer and caches it.  The Navier
  models hand these out (``get_observables_async`` / ``exit_future``) so
  diagnostics and break-criterion checks can lag one chunk behind the
  device instead of fencing it every boundary.

* **an async checkpoint writer** (:class:`AsyncCheckpointWriter`) — a
  single background worker with a bounded submission queue.  The main
  thread fetches the state to host memory (the cheap part: one device sync
  it needed anyway) and hands a :class:`~.checkpoint.HostSnapshot` over;
  the serialization, digest and fsync (the expensive part) overlap the
  next chunks' compute.  Failures are never silent: the first write error
  is re-raised at the next ``submit``/``drain`` — the same turn a
  synchronous write would have raised, one cadence later.  The queue depth
  bounds both memory (one host snapshot in flight) and staleness (a
  submission blocks until the previous write lands, so checkpoint cadence
  can never outrun the disk).

* **a diagnostics lag queue** (:class:`IOPipeline.push_diag`) — callback
  output (the printed Nu line, info.txt rows, the in-memory diagnostics
  map) is produced from a future and emitted once the values are ready,
  at most ``diag_lag`` boundaries late.  Order is strictly FIFO, and
  ``flush_diags``/``drain`` emit everything at run end, so files and
  diagnostics histories are complete and chronologically ordered — just
  not written from inside the device's dispatch window.

Threading contract: ONLY host-side work (numpy, h5py, os) runs on the
worker thread.  Device fetches happen on the submitting thread — fetching
sharded jax Arrays from pool threads can starve the runtime's own thread
pool (the PR-1 ``slice_io`` deadlock), so the split is fetch-on-main,
serialize-on-worker by design.

Multihost: the WRITE side runs here too — each host's shard of a
distributed checkpoint (utils/checkpoint.ShardSnapshot) is serialized on
that host's own writer, and the resilient runner drains the writer before
the two-phase commit barrier (drain-before-barrier), so a manifest only
ever names fsynced shards.  The lagged break check stays single-process:
futures resolving on per-host device timing would desynchronize the
collective dispatch sequence (utils/resilience._setup_io).
"""

from __future__ import annotations

import threading
import time as _time
from collections import deque

from ..telemetry import metrics as _tm
from ..config import env_get


class AsyncWriteError(RuntimeError):
    """A background checkpoint/snapshot write failed.

    Raised on the SUBMITTING thread at the next ``submit``/``drain`` after
    the failure, carrying the offending path and the original error as
    ``__cause__`` — the deferred equivalent of a synchronous writer raising
    in place."""

    def __init__(self, path: str, cause: BaseException):
        super().__init__(f"background write of {path!r} failed: {cause}")
        self.path = path


def _leaves_ready(arrays) -> bool:
    """Non-blocking completion probe shared by every future type: True once
    each leaf's device computation is done (plain-numpy leaves, which have
    no ``is_ready``, count as done)."""
    import jax

    return all(
        leaf.is_ready()
        for leaf in jax.tree.leaves(arrays)
        if hasattr(leaf, "is_ready")
    )


class ObservableFuture:
    """Handle to device values dispatched but not yet fetched.

    ``arrays`` is any pytree of jax (or numpy) arrays; ``convert`` maps the
    fetched host pytree to the user-facing value (applied once, cached).
    ``ready()`` never blocks; ``result()`` fetches the WHOLE pytree in one
    ``jax.device_get`` — one host round-trip regardless of leaf count,
    where per-leaf ``float()`` conversion costs a round-trip each."""

    def __init__(self, arrays, convert=None):
        self._arrays = arrays
        self._convert = convert
        self._value = None
        self._done = False

    def ready(self) -> bool:
        if self._done:
            return True
        return _leaves_ready(self._arrays)

    def result(self):
        """Fetch (blocking, once) and return the converted value."""
        if not self._done:
            import jax

            host = jax.device_get(self._arrays)
            self._value = host if self._convert is None else self._convert(host)
            self._done = True
            self._arrays = None  # release the device buffers
        return self._value

class MappedFuture:
    """Derived future: ``fn`` applied to another future's result.  The
    device dispatch and the single fetch are shared with the parent —
    mapping never costs an extra host round-trip."""

    def __init__(self, parent, fn):
        self._parent = parent
        self._fn = fn
        self._value = None
        self._done = False

    def ready(self) -> bool:
        return self._parent.ready()

    def result(self):
        if not self._done:
            self._value = self._fn(self._parent.result())
            self._done = True
        return self._value


def immediate(value) -> ObservableFuture:
    """A future that is already resolved (host-side facts: latches, masks)."""
    fut = ObservableFuture(None)
    fut._value = value
    fut._done = True
    return fut


class PendingChunkStatus:
    """Deferred-commit handle for one sentinel-armed chunk — the governed
    half of dispatch double-buffering (the ``lag=1`` sentinel contract).

    Created by the models' ``update_n_pending``: the chunk is dispatched
    and the model PROVISIONALLY advanced to its end state, so the next
    chunk can be enqueued before this one's sentinel scalars are fetched.
    ``resolve()`` fetches the scalars (one host transfer) and hands them to
    ``finish``, which reproduces the synchronous chunk's exact semantics —
    on a CFL-ceiling trip the chunk-start snapshot (state AND time) is
    restored and ``exit()`` latches.  The synchronous sentinel chunk is
    literally ``update_n_pending(n).resolve()``, so the two paths cannot
    drift.

    Contract for callers running ahead (the resilient runner's lagged
    ``_advance``): when a resolve rolls the model back, any LATER pending
    chunk was dispatched from the rolled-back provisional state — it must
    be ``discard()``-ed, never resolved (its ``finish`` would clobber the
    restored snapshot)."""

    def __init__(self, arrays, finish):
        self._arrays = arrays
        self._finish = finish
        self._status = None
        self._discarded = False

    def ready(self) -> bool:
        """Non-blocking: True once the sentinel scalars can be fetched
        without waiting on the device."""
        if self._status is not None or self._discarded:
            return True
        return _leaves_ready(self._arrays)

    def resolve(self):
        """Fetch the sentinel scalars and commit/roll back the provisional
        advance; idempotent, returns the chunk's ChunkStatus."""
        if self._discarded:
            raise RuntimeError("resolve() on a discarded pending chunk")
        if self._status is None:
            import jax

            self._status = self._finish(jax.device_get(self._arrays))
            self._arrays = None
            self._finish = None
        return self._status

    def discard(self) -> None:
        """Drop an invalidated speculative chunk (a previous chunk's
        rollback already restored the model past it)."""
        self._discarded = True
        self._arrays = None
        self._finish = None


class WriteTicket:
    """Completion handle for one background write."""

    def __init__(self, path: str):
        self.path = path
        self.error: BaseException | None = None
        self._event = threading.Event()

    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: float | None = None) -> None:
        """Block until the write finished; re-raise its failure."""
        self._event.wait(timeout)
        if self.error is not None:
            raise AsyncWriteError(self.path, self.error) from self.error


class AsyncCheckpointWriter:
    """Single-worker background writer with a bounded in-flight window.

    ``submit(work, path)`` enqueues ``work()`` (pure host-side IO) and
    returns a :class:`WriteTicket`.  At most ``depth`` submissions are
    resident — queued *plus* the one being written — and an over-depth
    submit blocks until the oldest write LANDS, not merely until the
    worker picks it up (back-pressure: checkpoint cadence can never outrun
    the disk, and host memory holds at most ``depth`` pending snapshots).
    The first failure is sticky — it
    re-raises at every later ``submit`` and at ``drain`` until observed —
    so a dead disk stops the campaign at the next cadence, exactly where
    the synchronous writer would have stopped it.

    ``timeout_s`` (or ``RUSTPDE_IO_TIMEOUT_S`` via :class:`IOPipeline`;
    default off, like the dispatch watchdog) bounds how long ``submit``
    back-pressure and ``drain`` may block on the worker: a disk/NFS wedge
    mid-``fsync`` then dumps every thread's stack and raises a typed
    :class:`AsyncWriteError` (cause ``TimeoutError``) on the submitting
    thread instead of hanging the campaign silently — the io analogue of
    ``RUSTPDE_DISPATCH_TIMEOUT_S``/``DispatchHang``.  (A wedged disk hangs
    the SYNCHRONOUS writer identically, inside fsync; the async writer is
    simply the one that can convert it into a structured error.)"""

    def __init__(self, depth: int = 1, timeout_s: float | None = None):
        import queue

        self.depth = max(1, int(depth))
        self.timeout_s = timeout_s
        # the queue itself is unbounded: the residency bound is _slots,
        # released only after a write COMPLETES (a maxsize queue alone
        # would admit depth+1 snapshots once the worker get()s the head)
        self._queue: "queue.Queue" = queue.Queue()
        self._slots = threading.Semaphore(self.depth)
        self._worker: threading.Thread | None = None
        self._failed: deque[WriteTicket] = deque()
        self._inflight: deque[WriteTicket] = deque()
        self._lock = threading.Lock()
        self.writes = 0  # completed writes
        self.write_s = 0.0  # worker seconds spent writing
        self.wait_s = 0.0  # submitter seconds blocked on back-pressure
        self.bytes = 0  # payload bytes handed to the worker

    def _ensure_worker(self) -> None:
        if self._worker is not None and self._worker.is_alive():
            return
        self._worker = threading.Thread(
            target=self._run, name="io-pipeline-writer", daemon=True
        )
        self._worker.start()

    def _run(self) -> None:
        while True:
            item = self._queue.get()
            try:
                if item is None:
                    return
                work, ticket = item
                t0 = _time.monotonic()
                try:
                    work()
                except BaseException as exc:  # surfaced at submit/drain
                    ticket.error = exc
                    with self._lock:
                        self._failed.append(ticket)
                finally:
                    write_s = _time.monotonic() - t0
                    with self._lock:
                        self.writes += 1
                        self.write_s += write_s
                    _tm.counter(
                        "io_writes_total", "background writes completed"
                    ).inc()
                    _tm.counter(
                        "io_write_seconds_total", "worker seconds spent writing"
                    ).inc(write_s)
                    if ticket.error is not None:
                        _tm.counter(
                            "io_write_failures_total", "background writes that failed"
                        ).inc()
                    ticket._event.set()
                    self._slots.release()
            finally:
                self._queue.task_done()

    def _raise_failed(self) -> None:
        with self._lock:
            ticket = self._failed.popleft() if self._failed else None
        if ticket is not None:
            raise AsyncWriteError(ticket.path, ticket.error) from ticket.error

    def _hang(self, what: str, path: str) -> None:
        """Armed-timeout expiry: name the wedge, dump every thread's stack
        (the worker's shows where the disk is stuck), raise typed."""
        import faulthandler
        import sys

        print(
            f"io-pipeline writer stuck: {what} exceeded {self.timeout_s:.0f}s "
            f"({path!r}) — dumping all thread stacks",
            file=sys.stderr,
        )
        faulthandler.dump_traceback(all_threads=True, file=sys.stderr)
        err = TimeoutError(f"{what} exceeded {self.timeout_s:.0f}s")
        raise AsyncWriteError(path, err) from err

    def submit(self, work, path: str, nbytes: int = 0) -> WriteTicket:
        """Enqueue ``work()``; blocks while ``depth`` writes are in flight
        (at most ``timeout_s``, when armed).  Raises a pending
        :class:`AsyncWriteError` from an earlier failed write before
        enqueueing new work.  ``nbytes`` (the payload size, when the caller
        knows it) feeds the ``io_overlap`` telemetry."""
        self._raise_failed()
        self._ensure_worker()
        ticket = WriteTicket(path)
        with self._lock:
            self.bytes += int(nbytes)
        t0 = _time.monotonic()
        if not self._slots.acquire(timeout=self.timeout_s):
            self._hang(f"back-pressure wait ({self.depth} writes in flight)", path)
        waited = _time.monotonic() - t0
        self.wait_s += waited
        _tm.counter(
            "io_backpressure_seconds_total",
            "submitter seconds blocked on the in-flight write window",
        ).inc(waited)
        _tm.counter("io_bytes_total", "payload bytes handed to the writer").inc(
            int(nbytes)
        )
        with self._lock:
            while self._inflight and self._inflight[0].done():
                self._inflight.popleft()  # keep the deque bounded by depth+1
            self._inflight.append(ticket)
        self._queue.put((work, ticket))
        return ticket

    def drain(self, raise_errors: bool = True) -> None:
        """Block until every submitted write completed; re-raise the first
        unobserved failure (``raise_errors=False`` only waits — for cleanup
        paths that must not mask an in-flight exception).  With ``timeout_s``
        armed, the whole drain gets that long before the stuck write is
        surfaced as a typed hang (the in-flight window is bounded by
        ``depth``, so the budget covers at most ``depth`` writes)."""
        if self.timeout_s is None:
            self._queue.join()
        else:
            deadline = _time.monotonic() + self.timeout_s
            while True:
                with self._lock:
                    ticket = next(
                        (t for t in self._inflight if not t.done()), None
                    )
                if ticket is None:
                    break
                remaining = deadline - _time.monotonic()
                if remaining <= 0 or not ticket._event.wait(remaining):
                    self._hang("drain wait", ticket.path)
        if raise_errors:
            self._raise_failed()

    def pending_errors(self) -> bool:
        with self._lock:
            return bool(self._failed)

    def consume_errors(self) -> list[BaseException]:
        """Pop and return every sticky failure's ROOT CAUSE without
        raising.  A caller that can degrade on a failure class — the
        runner's ENOSPC containment turns disk-full checkpoints into
        in-memory-rollback-only mode — uses this to observe the causes
        and unwedge the writer; left in place, the backlog would
        re-raise at every later ``submit``, one write at a time."""
        out: list[BaseException] = []
        with self._lock:
            while self._failed:
                out.append(self._failed.popleft().error)
        return out

    def close(self) -> None:
        """Drain and stop the worker thread (errors NOT re-raised; call
        :meth:`drain` first when failures matter).  With ``timeout_s`` armed
        a wedged worker is ABANDONED (daemon thread) rather than joined
        forever — close runs on teardown paths that may already be
        propagating an exception."""
        if self._worker is None or not self._worker.is_alive():
            return
        if self.timeout_s is not None:
            try:
                self.drain(raise_errors=False)
            except AsyncWriteError:
                return  # wedged: leave the daemon thread behind
        else:
            self._queue.join()
        self._queue.put(None)
        self._worker.join(timeout=10.0)


class IOPipeline:
    """The per-run facade the models and the resilient runner share.

    One background :class:`AsyncCheckpointWriter` plus the diagnostics lag
    queue.  A model carrying this as its ``io_pipeline`` attribute has its
    callback IO (flow snapshots, the printed Nu line, info.txt rows) routed
    through it by ``utils/navier_io.callback`` / the ensemble callback."""

    def __init__(
        self,
        queue_depth: int = 1,
        diag_lag: int = 1,
        timeout_s: float | None = None,
    ):
        if timeout_s is None:
            import os

            env = env_get("RUSTPDE_IO_TIMEOUT_S")
            timeout_s = float(env) if env else None
        self.writer = AsyncCheckpointWriter(depth=queue_depth, timeout_s=timeout_s)
        self.diag_lag = max(0, int(diag_lag))
        self._diags: deque = deque()
        self._dropped_diags = 0

    # -- background writes ----------------------------------------------------

    def submit_write(self, work, path: str, nbytes: int = 0) -> WriteTicket:
        """Hand one host-side write to the worker (see
        :meth:`AsyncCheckpointWriter.submit`)."""
        return self.writer.submit(work, path, nbytes=nbytes)

    # -- lagged diagnostics ---------------------------------------------------

    def push_diag(self, emit, future) -> None:
        """Queue one callback emission: ``emit(future.result())`` runs once
        the values are ready, at most ``diag_lag`` pushes late, in FIFO
        order.  Ready entries are emitted immediately so a fast device (or
        the eager path) behaves exactly like the synchronous callback."""
        self._diags.append((emit, future))
        self._pump(block=False)

    def _pump(self, block: bool) -> None:
        while self._diags:
            emit, fut = self._diags[0]
            if not block and len(self._diags) <= self.diag_lag and not fut.ready():
                break  # young enough to stay pending
            self._diags.popleft()
            emit(fut.result())

    def flush_diags(self) -> None:
        """Emit every pending diagnostics entry (end of run)."""
        self._pump(block=True)

    def abandon_diags(self) -> int:
        """Drop pending diagnostic emissions WITHOUT resolving their
        futures.  For the :class:`~..utils.resilience.DispatchHang`
        teardown path only: those futures came from the wedged dispatch,
        so resolving them in a ``finally`` would block forever with no
        watchdog and swallow the structured raise.  Returns the number of
        lines lost (also surfaced as ``dropped_diags`` in :meth:`stats`)."""
        n = len(self._diags)
        self._dropped_diags += n
        self._diags.clear()
        return n

    # -- lifecycle ------------------------------------------------------------

    def drain(self, raise_errors: bool = True) -> None:
        """Flush diagnostics and wait for every background write; re-raises
        the first write failure unless ``raise_errors=False``."""
        self.flush_diags()
        self.writer.drain(raise_errors=raise_errors)

    def close(self) -> None:
        self.flush_diags()
        self.writer.close()

    def stats(self) -> dict:
        """Pipeline telemetry for run summaries/journals."""
        w = self.writer
        return {
            "writes": w.writes,
            "bytes": w.bytes,
            "write_s": round(w.write_s, 3),
            "queue_wait_s": round(w.wait_s, 3),
            "pending_diags": len(self._diags),
            "dropped_diags": self._dropped_diags,
        }
