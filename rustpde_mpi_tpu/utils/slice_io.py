"""Hyperslab (slice) HDF5 IO.

Rebuild of the reference's ``io::read_write_slice_hdf5``
(/root/reference/src/io/read_write_slice_hdf5.rs:18-60): create-or-open a
dataset of a known global shape and read/write one rank's rectangular slab.
The reference uses this for rank-sequential parallel IO
(field_mpi/io_mpi_sequ.rs); here the same surface serves pencil-slab IO
under the single-controller model — ``write_pencils`` streams a sharded
array to disk slab-by-slab without materializing the global array twice.
Complex data is stored as ``{name}_re``/``{name}_im`` pairs like the rest of
the checkpoint layer (/root/reference/src/io/read_write_hdf5.rs:171-188).
"""

from __future__ import annotations

import numpy as np


def _h5():
    import h5py

    return h5py


def write_slice(filename, dsname: str, data, offset, global_shape) -> None:
    """Write ``data`` into the hyperslab at ``offset`` of dataset ``dsname``
    (created with ``global_shape`` on first touch; file append-or-create)."""
    data = np.asarray(data)
    if np.iscomplexobj(data):
        write_slice(filename, dsname + "_re", data.real, offset, global_shape)
        write_slice(filename, dsname + "_im", data.imag, offset, global_shape)
        return
    sel = tuple(slice(o, o + s) for o, s in zip(offset, data.shape))
    with _h5().File(filename, "a") as f:
        if dsname in f:
            ds = f[dsname]
            if tuple(ds.shape) != tuple(global_shape):
                raise ValueError(
                    f"dataset {dsname} exists with shape {ds.shape}, "
                    f"expected {tuple(global_shape)}"
                )
        else:
            ds = f.create_dataset(dsname, shape=tuple(global_shape), dtype=data.dtype)
        ds[sel] = data


def read_slice(filename, dsname: str, offset, shape, is_complex: bool = False):
    """Read the hyperslab at ``offset`` of extent ``shape``."""
    if is_complex:
        re = read_slice(filename, dsname + "_re", offset, shape)
        im = read_slice(filename, dsname + "_im", offset, shape)
        return re + 1j * im
    sel = tuple(slice(o, o + s) for o, s in zip(offset, shape))
    with _h5().File(filename, "r") as f:
        return np.asarray(f[dsname][sel])


def write_pencils(filename, dsname: str, arr, decomp, pencil: str = "y") -> None:
    """Stream a pencil-sharded global-view array to disk one rank-slab at a
    time (the reference's rank-serialized writer, io_mpi_sequ.rs) — each
    slab is fetched and written independently, so peak host memory is one
    slab, not the global array.

    The HDF5 file is opened ONCE for the whole dataset (``write_slice``'s
    open/append/close per slab costs a metadata flush + page-cache walk per
    rank, which dominates at high rank counts); complex data recurses into
    the ``_re``/``_im`` pair like :func:`write_slice`."""
    get = decomp.y_pencil if pencil == "y" else decomp.x_pencil
    global_shape = tuple(decomp.global_shape)
    dtype = np.dtype(arr.dtype)  # metadata only — no device probe
    if np.issubdtype(dtype, np.complexfloating):
        write_pencils(filename, dsname + "_re", np.real(arr), decomp, pencil)
        write_pencils(filename, dsname + "_im", np.imag(arr), decomp, pencil)
        return
    with _h5().File(filename, "a") as f:
        if dsname in f:
            ds = f[dsname]
            if tuple(ds.shape) != global_shape:
                raise ValueError(
                    f"dataset {dsname} exists with shape {ds.shape}, "
                    f"expected {global_shape}"
                )
        else:
            ds = f.create_dataset(dsname, shape=global_shape, dtype=dtype)
        for rank in range(decomp.nprocs):
            p = get(rank)
            sel = tuple(slice(st, st + s) for st, s in zip(p.st, p.sz))
            ds[sel] = np.asarray(arr[sel])  # fetches only this slab's shards


def read_pencil(filename, dsname: str, decomp, rank: int, pencil: str = "y",
                is_complex: bool = False):
    """One rank's slab of a dataset."""
    p = (decomp.y_pencil if pencil == "y" else decomp.x_pencil)(rank)
    return read_slice(filename, dsname, p.st, p.sz, is_complex=is_complex)


def write_pencils_concurrent(
    filename, dsname: str, arr, decomp, pencil: str = "y", max_workers=None
) -> None:
    """Concurrent pencil writer — the TPU-native analog of the reference's
    concurrent MPIO path, which it ships disabled
    (/root/reference/src/field_mpi/io_mpi.rs:14-108 behind the off-by-default
    ``mpio`` feature; SURVEY S2 rows field_mpi::io_mpi /
    io::future_read_write_mpi_hdf5).

    Parallel HDF5 needs an MPI-enabled libhdf5; instead each rank-slab is
    written to its own shard file (``{filename}.{dsname}.shardN``) from a
    thread pool, and the main file exposes the global dataset as an HDF5
    *virtual dataset* over the shards — readers (``read_slice`` /
    ``read_pencil`` / h5py) see the same global dataset as the sequential
    writer produces, with zero stitching copies.  A caveat on the in-process
    concurrency: h5py serializes ALL HDF5 library calls behind one
    process-wide lock, even across separate files, so the pooled shard
    writes overlap only the main thread's fetch-ahead of the next slabs and
    whatever the OS buffers beneath the serialized writes — the
    single-process speedup is bounded, not Nx.  The design earns its name in
    a multi-host deployment, where each host writes its own shard file
    natively and only the virtual-dataset stitching is centralized.  The
    shard files must travel with the main file (HDF5 resolves them relative
    to it)."""
    import os
    from concurrent.futures import ThreadPoolExecutor

    # complex data splits into _re/_im virtual datasets like write_slice
    probe = np.asarray(arr[tuple(slice(0, 1) for _ in decomp.global_shape)])
    if np.iscomplexobj(probe):
        write_pencils_concurrent(
            filename, dsname + "_re", np.real(arr), decomp, pencil, max_workers
        )
        write_pencils_concurrent(
            filename, dsname + "_im", np.imag(arr), decomp, pencil, max_workers
        )
        return
    h5py = _h5()
    get = decomp.y_pencil if pencil == "y" else decomp.x_pencil
    global_shape = tuple(decomp.global_shape)
    pencils = [get(rank) for rank in range(decomp.nprocs)]
    base = os.path.basename(filename)

    def write_shard(rank, block):
        # per-shard digest attr, byte-compatible with the checkpoint
        # layer's content_digest (utils/checkpoint.py): readers can verify
        # any shard standalone with verify: sha256(content) == attrs digest
        from .checkpoint import snapshot_digest

        shard = f"{filename}.{dsname.replace('/', '_')}.shard{rank}"
        with h5py.File(shard, "w") as f:
            f.create_dataset("slab", data=block)
            f.attrs["digest"] = snapshot_digest([("slab", block, "raw")])
        return rank, block.dtype

    # slab fetches run on the MAIN thread: a sliced read of a sharded jax
    # Array dispatches a gather computation, and concurrent dispatch from
    # pool threads deadlocks the runtime's own thread pool (observed on the
    # CPU backend: every worker parked inside Array.__getitem__).  Only the
    # h5py shard writes go to the pool; in-flight slabs are bounded to the
    # worker count so peak host memory stays O(workers) slabs.
    workers = max_workers or min(8, len(pencils))
    dtypes = {}
    with ThreadPoolExecutor(max_workers=workers) as ex:
        pending = []
        for rank, p in enumerate(pencils):
            sel = tuple(slice(st, st + s) for st, s in zip(p.st, p.sz))
            block = np.ascontiguousarray(np.asarray(arr[sel]))
            pending.append(ex.submit(write_shard, rank, block))
            if len(pending) > workers:
                r, dt = pending.pop(0).result()
                dtypes[r] = dt
        for fut in pending:
            r, dt = fut.result()
            dtypes[r] = dt
    layout = h5py.VirtualLayout(shape=global_shape, dtype=dtypes[0])
    for rank, p in enumerate(pencils):
        sel = tuple(slice(st, st + s) for st, s in zip(p.st, p.sz))
        vs = h5py.VirtualSource(
            f"./{base}.{dsname.replace('/', '_')}.shard{rank}",
            "slab",
            shape=tuple(p.sz),
        )
        layout[sel] = vs
    with h5py.File(filename, "a") as f:
        if dsname in f:
            del f[dsname]
        f.create_virtual_dataset(dsname, layout)
