"""Hyperslab (slice) HDF5 IO.

Rebuild of the reference's ``io::read_write_slice_hdf5``
(/root/reference/src/io/read_write_slice_hdf5.rs:18-60): create-or-open a
dataset of a known global shape and read/write one rank's rectangular slab.
The reference uses this for rank-sequential parallel IO
(field_mpi/io_mpi_sequ.rs); here the same surface serves pencil-slab IO
under the single-controller model — ``write_pencils`` streams a sharded
array to disk slab-by-slab without materializing the global array twice.
Complex data is stored as ``{name}_re``/``{name}_im`` pairs like the rest of
the checkpoint layer (/root/reference/src/io/read_write_hdf5.rs:171-188).
"""

from __future__ import annotations

import numpy as np


def _h5():
    import h5py

    return h5py


def write_slice(filename, dsname: str, data, offset, global_shape) -> None:
    """Write ``data`` into the hyperslab at ``offset`` of dataset ``dsname``
    (created with ``global_shape`` on first touch; file append-or-create)."""
    data = np.asarray(data)
    if np.iscomplexobj(data):
        write_slice(filename, dsname + "_re", data.real, offset, global_shape)
        write_slice(filename, dsname + "_im", data.imag, offset, global_shape)
        return
    sel = tuple(slice(o, o + s) for o, s in zip(offset, data.shape))
    with _h5().File(filename, "a") as f:
        if dsname in f:
            ds = f[dsname]
            if tuple(ds.shape) != tuple(global_shape):
                raise ValueError(
                    f"dataset {dsname} exists with shape {ds.shape}, "
                    f"expected {tuple(global_shape)}"
                )
        else:
            ds = f.create_dataset(dsname, shape=tuple(global_shape), dtype=data.dtype)
        ds[sel] = data


def read_slice(filename, dsname: str, offset, shape, is_complex: bool = False):
    """Read the hyperslab at ``offset`` of extent ``shape``."""
    if is_complex:
        re = read_slice(filename, dsname + "_re", offset, shape)
        im = read_slice(filename, dsname + "_im", offset, shape)
        return re + 1j * im
    sel = tuple(slice(o, o + s) for o, s in zip(offset, shape))
    with _h5().File(filename, "r") as f:
        return np.asarray(f[dsname][sel])


def write_pencils(filename, dsname: str, arr, decomp, pencil: str = "y") -> None:
    """Stream a pencil-sharded global-view array to disk one rank-slab at a
    time (the reference's rank-serialized writer, io_mpi_sequ.rs) — each
    slab is fetched and written independently, so peak host memory is one
    slab, not the global array."""
    get = decomp.y_pencil if pencil == "y" else decomp.x_pencil
    global_shape = decomp.global_shape
    for rank in range(decomp.nprocs):
        p = get(rank)
        sel = tuple(slice(st, st + s) for st, s in zip(p.st, p.sz))
        block = np.asarray(arr[sel])  # fetches only this slab's shards
        write_slice(filename, dsname, block, p.st, global_shape)


def read_pencil(filename, dsname: str, decomp, rank: int, pencil: str = "y",
                is_complex: bool = False):
    """One rank's slab of a dataset."""
    p = (decomp.y_pencil if pencil == "y" else decomp.x_pencil)(rank)
    return read_slice(filename, dsname, p.st, p.sz, is_complex=is_complex)
