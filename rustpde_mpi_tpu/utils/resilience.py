"""Resilient run harness: the machinery that keeps long DNS campaigns alive.

The reference treats restart-from-HDF5 as a first-class operation
(navier_io.rs; rebuilt in utils/checkpoint.py) but has no story for
*surviving* the failures long Rayleigh–Bénard campaigns actually hit.  This
module adds the production-harness layer on top of the ``integrate`` driver:

* **durable checkpoints** — rolling, atomic, digest-stamped snapshots
  (utils/checkpoint.py) written on a wall-clock and/or sim-time cadence,
  with a retention window and auto-resume from the newest *valid* file;
  on multi-process meshes (or with ``IOConfig.sharded_checkpoints``) the
  SHARDED two-phase format is used — each host writes only its addressable
  shards, root commits via an atomic manifest whose presence is the commit
  marker, and restore is topology-elastic (a checkpoint written at one
  mesh/host count resumes on another, or serially, bit-equal),
* **preemption safety** — SIGTERM/SIGINT handlers that finish the in-flight
  chunk, checkpoint, journal and exit cleanly; on multihost meshes rank 0
  decides and the decision is broadcast so every host snapshots the same
  step,
* **proactive stability governance** — with a ``StabilityConfig`` the model
  compiles on-device CFL/energy sentinels into its scanned chunks and a
  :class:`~rustpde_mpi_tpu.utils.governor.StabilityGovernor` drives dt
  toward a target Courant number on a rung-cached geometric ladder: a hard
  CFL ceiling early-exits the chunk *before* NaNs appear and the recovery
  is a cheap in-memory rollback (no checkpoint IO), with regrowth back up
  the ladder after a healthy stretch (utils/governor.py),
* **divergence recovery** — when the model's NaN break criterion fires (the
  reactive last resort once the governor is out of ladder), roll back to
  the last good checkpoint, shrink dt by ``dt_backoff`` (rebuilding the
  dt-baked solvers via ``set_dt``, floored at ``dt_min``) and retry up to
  ``max_retries``; ensembles can additionally respawn dead members from
  perturbed healthy donors at rollback,
* **hang watchdogs** — device dispatches run under a deadline
  (:func:`call_with_watchdog`); expiry dumps all-thread stacks via
  ``faulthandler`` and raises a structured :class:`DispatchHang` instead of
  wedging the job silently (the failure mode that ate PR 1's tier-1 budget),
* **an overlapped I/O pipeline** — with the default
  :class:`~rustpde_mpi_tpu.config.IOConfig`, cadence checkpoints are
  fetched to host on the main thread and serialized/hashed/fsynced on a
  background worker, break checks and callback diagnostics ride observable
  futures one chunk behind the device, and dispatches are no longer fenced
  per chunk (``block_until_ready`` only runs when a watchdog deadline needs
  it) — so at a given cadence the device steps through checkpoint writes
  instead of idling behind them (utils/io_pipeline.py; the writer drains
  before every rollback/resume read and at run end, so durability and
  recovery semantics are unchanged),
* **a JSONL run journal** — every checkpoint, fault, retry and outcome is an
  appended JSON line (step, time, Nu, wall seconds, attempt), so a campaign's
  failure history is machine-readable after the fact,
* **deterministic fault injection** — ``RUSTPDE_FAULT=nan@<step>`` /
  ``spike@<step>`` / ``kill@<step>`` / ``slow@<step>`` (or the ``fault=``
  argument) exercises every recovery path — including every governor path,
  via the finite velocity-spike incipient blow-up — in tests and
  ``bench.py`` without waiting for real failures.

This checkpoint/resume/watchdog shape is exactly the preemption-safe
training-loop pattern (ROADMAP north star): swap "spectral coefficients" for
"optimizer state" and the harness transfers unchanged.
"""

from __future__ import annotations

import contextlib
import dataclasses
import errno as _errno
import faulthandler
import os
import signal
import sys
import threading
import time as _time

import numpy as np

from ..telemetry import metrics as _tm
from ..telemetry import tracing as _tr
from ..telemetry.exporters import MetricsDumper
from . import checkpoint
from .faults import FaultPlan, FaultSpecError, validate_fault_env  # noqa: F401
from .governor import StabilityGovernor
from .integrate import integrate

from .. import config
from ..config import env_get
from ..parallel import sanitizer as _sanitizer
from .io_pipeline import AsyncWriteError, IOPipeline
from .journal import JournalWriter, read_journal


class DispatchHang(RuntimeError):
    """A device dispatch (or host barrier) exceeded its watchdog deadline.

    Raised with all-thread stacks already dumped to stderr — the structured
    replacement for a silent job-wide hang.  The abandoned worker thread may
    still be blocked inside the runtime; the process should checkpoint what
    it can and exit/restart rather than keep dispatching."""

    def __init__(self, label: str, timeout_s: float):
        super().__init__(
            f"{label} did not complete within {timeout_s:.1f}s "
            "(all-thread stacks dumped to stderr)"
        )
        self.label = label
        self.timeout_s = timeout_s


class DivergenceError(RuntimeError):
    """A run diverged and could not be recovered (retries exhausted, or no
    valid checkpoint to roll back to)."""


def call_with_watchdog(fn, timeout_s: float | None, label: str = "dispatch"):
    """Run ``fn()`` under a deadline: the call executes in a worker thread
    while the caller waits ``timeout_s``; on expiry every thread's stack is
    dumped via ``faulthandler`` and :class:`DispatchHang` is raised.  A
    ``None``/non-positive timeout calls ``fn()`` directly (no thread).

    The expired worker is a daemon and keeps blocking in the background —
    by design: there is no safe way to cancel a wedged runtime call, so the
    caller gets control back to checkpoint/exit while the corpse is left to
    the OS."""
    if not timeout_s or timeout_s <= 0:
        return fn()
    result: list = []
    error: list = []

    def target():
        try:
            result.append(fn())
        except BaseException as exc:  # re-raised in the caller below
            error.append(exc)

    worker = threading.Thread(target=target, name=f"watchdog:{label}", daemon=True)
    worker.start()
    worker.join(timeout_s)
    if worker.is_alive():
        sys.stderr.write(
            f"[resilience] {label} stuck past its {timeout_s:.1f}s deadline; "
            "all-thread stacks:\n"
        )
        sys.stderr.flush()
        faulthandler.dump_traceback(all_threads=True)
        raise DispatchHang(label, timeout_s)
    if error:
        raise error[0]
    return result[0]


def _single_process() -> bool:
    """True when the JAX runtime is (or defaults to) one process.  The
    blanket except treats an unimportable/uninitialized runtime as single —
    the caller then takes the local (non-collective) path, which is the
    only one that can work without a runtime."""
    try:
        import jax

        return jax.process_count() == 1
    except Exception:
        return True


def _host_column_mask(pde, host: int, leaf, hit, miss=1.0):
    """Per-leaf multiplier that applies ``hit`` only on the spectral
    columns owned by process ``host``'s devices (the pencil axis is the
    LAST one under the x-pencil SPEC layout) and ``miss`` elsewhere.

    Every process builds the identical mask from the mesh metadata alone,
    so a host-scoped fault stays a CONSISTENT collective dispatch — the
    fault originates on one host's shard and propagates through the
    coupled step, like a real single-host memory corruption would."""
    import jax.numpy as jnp

    from ..parallel.mesh import SPEC, pencil_sharding

    mesh = getattr(pde, "mesh", None)
    n = leaf.shape[-1]
    # dtype from metadata only — np.asarray(leaf) would fetch the whole
    # leaf, which raises on a real multi-controller mesh (non-addressable
    # shards), the very platform host-scoped faults exist for
    cols = np.full(n, miss, dtype=np.empty(0, leaf.dtype).real.dtype)
    if mesh is None:
        if host in (0, None):
            cols[:] = hit
    else:
        s = pencil_sharding(mesh, SPEC, ndim=len(leaf.shape))
        try:
            imap = s.devices_indices_map(tuple(leaf.shape))
        except ValueError:  # uneven dim: replicated layout, host 0 owns all
            imap = None
        if imap is None:
            if host == 0:
                cols[:] = hit
        else:
            for dev, idx in imap.items():
                if dev.process_index != host:
                    continue
                start, stop, _ = idx[-1].indices(n)
                cols[start:stop] = hit
    return jnp.asarray(cols)


def poison_state(pde, host: int | None = None) -> None:
    """Multiply every state leaf by NaN (the deterministic stand-in for a
    numerical blow-up; used by fault injection).  With ``host`` given, only
    the spectral columns owned by that process's devices are poisoned —
    the multihost single-host-corruption shape (the NaN infects the rest
    of the domain through the next coupled step)."""
    import jax

    scope = pde.model._scope if hasattr(pde, "model") else pde._scope
    with scope():
        if host is None:
            pde.state = jax.tree.map(lambda x: x * float("nan"), pde.state)
        else:
            mdl = pde.model if hasattr(pde, "model") else pde
            pde.state = jax.tree.map(
                lambda x: x * _host_column_mask(mdl, host, x, float("nan")),
                pde.state,
            )
        if hasattr(pde, "mask") and hasattr(pde, "_finite_mask"):
            pde.mask = pde._finite_mask(pde.state)
    pde._obs_cache = None


def spike_state(pde, factor: float = 50.0, host: int | None = None) -> None:
    """Scale the velocity fields by ``factor`` on-device: a deterministic
    incipient blow-up — finite state, CFL far past the stability ceiling.
    Under the governor this is caught pre-NaN (rollback happens in memory
    and dt descends the ladder until the spiked flow is Courant-stable);
    without sentinels the over-CFL explicit convection grows it into the
    NaN divergence path within a few steps.  For ensembles the spike hits
    every member (the state leaves carry the leading K axis).  With
    ``host``, only that process's spectral columns are scaled."""
    scope = pde.model._scope if hasattr(pde, "model") else pde._scope
    with scope():
        st = pde.state
        if host is None:
            fx = fy = factor
        else:
            mdl = pde.model if hasattr(pde, "model") else pde
            fx = _host_column_mask(mdl, host, st.velx, factor)
            fy = _host_column_mask(mdl, host, st.vely, factor)
        pde.state = st._replace(velx=st.velx * fx, vely=st.vely * fy)
    pde._obs_cache = None


def _host_owned_column(pde, host: int, leaf, step: int = 0) -> int | None:
    """One spectral column (last/pencil axis) owned by process ``host``'s
    devices, hashed from ``step`` within the owned span — computed from
    mesh metadata alone, so every process picks the SAME column and a
    host-scoped bitflip stays a consistent collective dispatch.  ``None``
    when ``host`` owns no columns (caller falls back to the hashed
    default)."""
    from ..parallel.mesh import SPEC, pencil_sharding

    mesh = getattr(pde, "mesh", None)
    n = leaf.shape[-1]
    if mesh is None:
        return None
    s = pencil_sharding(mesh, SPEC, ndim=len(leaf.shape))
    try:
        imap = s.devices_indices_map(tuple(leaf.shape))
    except ValueError:  # uneven dim: replicated layout, host 0 owns all
        imap = None
    if imap is None:
        return 0 if host == 0 else None
    spans = []
    for dev, idx in imap.items():
        if dev.process_index != host:
            continue
        start, stop, _ = idx[-1].indices(n)
        if stop > start:
            spans.append((start, stop))
    if not spans:
        return None
    start, stop = min(spans)
    return start + int(step) * 40503 % (stop - start)


def bitflip_state(pde, step: int, host: int | None = None,
                  member: int | None = None, bit: int | None = None) -> dict:
    """Flip ONE mantissa bit of one spectral coefficient on device — the
    deterministic silent-data-corruption injection
    (``RUSTPDE_FAULT=bitflip@<step>[:host<p>|:member<k>]``).  The flipped
    state is finite and CFL-sane (integrity/digest.default_flip_bit never
    touches exponent or sign), so every loud sentinel — NaN criterion,
    CFL ceiling, watchdogs — stays quiet: only the integrity layer's
    digest audits can see it.  With ``host``, the flipped column is one
    owned by that process's devices (real single-host HBM corruption
    shape); with ``member``, only that ensemble member's leading-axis
    slice is touched (per-member digests localize it).  Returns the flip
    info dict (leaf/index/bit/member/host) for the journal."""
    from ..integrity import flip_state_bit

    scope = pde.model._scope if hasattr(pde, "model") else pde._scope
    mdl = pde.model if hasattr(pde, "model") else pde
    with scope():
        st = pde.state
        name = "temp" if hasattr(st, "temp") else st._fields[0]
        col = None
        if host is not None:
            col = _host_owned_column(mdl, host, getattr(st, name), step=step)
        pde.state, info = flip_state_bit(
            st, step, member=member, col=col, bit=bit
        )
    pde._obs_cache = None
    info["host"] = host
    return info


def _is_root() -> bool:
    try:
        from ..parallel import multihost

        return multihost.is_root()
    except Exception:
        return True


class ResilientRunner:
    """Wrap a model (``Navier2D`` / ``NavierEnsemble`` / any ``Integrate``
    implementer with ``read``/``write`` snapshots) in the full resilience
    harness: cadenced atomic checkpoints, JSONL journal, auto-resume,
    checkpoint-then-exit on SIGTERM/SIGINT, divergence retry with dt
    backoff, and dispatch watchdogs.

    Typical use (examples/navier_rbc_resilient.py)::

        model = Navier2D.new_confined(129, 129, 1e7, 1.0, 2e-3, 1.0, "rbc")
        runner = ResilientRunner(model, max_time=100.0, save_intervall=1.0,
                                 run_dir="data/run1", checkpoint_every_s=300)
        summary = runner.run()   # resumes automatically if run1 has state

    ``run()`` returns a summary dict whose ``outcome`` is ``"done"`` or
    ``"preempted"`` (clean checkpoint written either way) and raises
    :class:`DivergenceError` / :class:`DispatchHang` when recovery is
    impossible."""

    def __init__(
        self,
        pde,
        max_time: float,
        save_intervall: float | None = None,
        *,
        run_dir: str = "data/resilient",
        checkpoint_every_s: float | None = 300.0,
        checkpoint_every_t: float | None = None,
        keep: int = 3,
        max_retries: int = 3,
        dt_backoff: float = 0.5,
        dt_min: float = 0.0,
        respawn_members: bool = False,
        respawn_amp: float = 1e-3,
        respawn_seed: int | None = None,
        dispatch_timeout_s: float | None = None,
        fault: str | None = None,
        spike_factor: float | None = None,
        resume: bool = True,
        max_chunk_steps: int = 1024,
        stability=None,
        io=None,
    ):
        self.pde = pde
        self.max_time = float(max_time)
        self.save_intervall = save_intervall
        self.run_dir = run_dir
        self.checkpoint_every_s = checkpoint_every_s
        self.checkpoint_every_t = checkpoint_every_t
        self.keep = int(keep)
        self.max_retries = int(max_retries)
        self.dt_backoff = float(dt_backoff)
        # hard floor under the compounding divergence backoff AND the
        # governor ladder default — without it repeated retries drive dt
        # toward denormals (each one paying a solver refactorization for a
        # step size that can no longer make progress)
        self.dt_min = float(dt_min)
        self.respawn_members = bool(respawn_members)
        self.respawn_amp = float(respawn_amp)
        self.respawn_seed = respawn_seed
        if dispatch_timeout_s is None:
            env = env_get("RUSTPDE_DISPATCH_TIMEOUT_S", "")
            dispatch_timeout_s = float(env) if env else None
        self.dispatch_timeout_s = dispatch_timeout_s
        # STRICT env validation at construction (utils/faults): a malformed
        # RUSTPDE_FAULT / RUSTPDE_SHARD_CRASH must kill the run before any
        # stepping — a chaos spec that silently never fires reports green
        # while testing nothing
        validate_fault_env()
        self.fault = FaultPlan.from_spec(
            fault if fault is not None else env_get("RUSTPDE_FAULT")
        )
        if spike_factor is None:
            env = env_get("RUSTPDE_SPIKE_FACTOR", "")
            spike_factor = float(env) if env else 50.0
        self.spike_factor = float(spike_factor)
        self.resume = bool(resume)
        self.max_chunk_steps = int(max_chunk_steps)
        # proactive stability governor (utils/governor.py): an explicit
        # StabilityConfig wins; otherwise inherit sentinels the model
        # already has armed (NavierConfig.stability -> set_stability)
        self.stability = (
            stability if stability is not None else getattr(pde, "_stability", None)
        )
        self.governor: StabilityGovernor | None = None
        self._dt0 = float(pde.get_dt())  # governor ladder anchor (pre-resume)
        # overlapped-IO pipeline (utils/io_pipeline.py): defaults ON —
        # async cadence checkpoints + dispatch double-buffering; multihost
        # meshes keep async SHARD writes (per-host writer, commit deferred
        # to the next boundary) but disable the lagged break check
        from ..config import IOConfig

        self.io = io if io is not None else IOConfig()
        self._io: IOPipeline | None = None
        self._async_ckpt = False
        self._overlap = False
        self._sharded = False  # distributed two-phase checkpoint format
        # one deferred sharded commit may be in flight: (snap, path, reason,
        # journal event) — committed at the next chunk boundary
        self._pending_commit: tuple | None = None
        # disk-full containment: once a checkpoint write bottoms out in
        # ENOSPC the run DEGRADES to in-memory rollback only — further
        # disk checkpoints are suppressed (journaled) instead of the
        # writer's sticky failure re-wedging every later submit
        self._ckpt_disabled = False
        self._io_snapshot_s = 0.0  # main-thread seconds staging host snapshots
        self._lock = threading.Lock()  # ckpt-path updates (journal has its own)
        self.journal_path = os.path.join(run_dir, "journal.jsonl")
        # per-event-flushed shared writer (utils/journal): an embedding
        # harness (serve.SimServer) may hand the runner ITS writer so
        # request_* and checkpoint events ride one file — see set_journal
        self._journal_writer: JournalWriter | None = None
        self._journal_owned = True  # close on teardown unless set_journal'd

        # live telemetry (rustpde_mpi_tpu/telemetry): the SLO throughput
        # baseline journaling `perf_degraded` (replaceable — tests inject a
        # fake clock), the cadenced metrics.jsonl dumper (armed per session,
        # root only) and the flight-recorder exit hook disarm callable
        self.slo = _tm.ThroughputMonitor()
        self._slo_last_step = 0
        self._metrics_dumper: MetricsDumper | None = None
        self._exit_disarm = None

        # physics-health streaming (models/stats.py, armed via the model's
        # set_stats): one health future in flight, resolved a boundary
        # later (lag=1 — no fence), exported as gauges + typed journal
        # events with crossing latches (warn once per excursion, re-arm
        # after the signal halves)
        self._stats_health_pending = None
        self._stats_res_latched = False
        self._stats_budget_latched = False
        self._saved_pde_journal = None

        # end-to-end integrity (integrity/): armed when the model carries
        # an IntegrityConfig (set_integrity / RUSTPDE_INTEGRITY=1) —
        # boundary digests streamed with every commit (chain check: the
        # state must arrive at the next chunk unmutated), shadow
        # re-execution audits at the config cadence, verified-snapshot
        # in-memory rollback, and the durable per-device quarantine ledger
        self._integ_prev = None      # (step, digest future) at last commit
        self._integ_verified = None  # (step, snapshot) last audit-verified
        self._integ_chunks = 0       # committed chunks (cadence counter)
        self._integ_ledger = None    # QuarantineLedger, built lazily

        self.step = 0  # global step counter (survives resume via ckpt attrs)
        self.attempt = 0  # divergence retries so far
        self.resumed = False  # set by session(): a checkpoint was restored
        self._interrupt: int | None = None
        self._slow_pending = False
        self._t0 = _time.monotonic()
        self._last_ckpt_wall = self._t0
        self._last_ckpt_time = 0.0
        self._last_ckpt_path: str | None = None  # newest verified/written
        self._prev_handlers: dict = {}
        self._is_ensemble = hasattr(pde, "member_state")

    @classmethod
    def from_config(cls, pde, rcfg, max_time, save_intervall=None, **overrides):
        """Build from a :class:`~rustpde_mpi_tpu.config.ResilienceConfig`
        (``None`` uses the defaults); keyword overrides win.  A shallow
        field copy, NOT ``dataclasses.asdict`` — the nested
        ``StabilityConfig`` must arrive as the dataclass, not a dict."""
        kwargs = (
            {f.name: getattr(rcfg, f.name) for f in dataclasses.fields(rcfg)}
            if rcfg is not None
            else {}
        )
        kwargs.update(overrides)
        return cls(pde, max_time, save_intervall, **kwargs)

    # -- journal -------------------------------------------------------------

    def set_journal(self, writer: JournalWriter) -> None:
        """Adopt an externally-owned journal writer (the serve scheduler's:
        one journal for request_* AND runner events).  The runner then never
        closes it — the owner does."""
        self._journal_writer = writer
        self._journal_owned = False
        self.journal_path = writer.path

    def _journal(self, event: dict) -> None:
        """Append one JSON line to ``<run_dir>/journal.jsonl`` (root only).

        Thread-safe and flushed per event (utils/journal.JournalWriter):
        async checkpoint completions journal from the pipeline worker, and
        a SIGKILL can tear at most the line in flight.  Events carrying
        their own ``step``/``time`` (captured at submit) override the
        defaults, so a write that lands mid-chunk is stamped with the step
        it snapshot."""
        if not _is_root():
            return
        if self._journal_writer is None:
            self._journal_writer = JournalWriter(self.journal_path)
            self._journal_owned = True
        record = {
            "wall_s": round(_time.monotonic() - self._t0, 3),
            "step": self.step,
            "time": round(float(self.pde.get_time()), 9),
            "attempt": self.attempt,
            **event,
        }
        self._journal_writer.append(record)

    def _nu(self):
        """Scalar Nu for the journal: the value for a single run, the
        alive-member mean for an ensemble; None when unavailable."""
        try:
            nu = self.pde.eval_nu()
        except Exception:
            return None
        if self._is_ensemble:
            alive = np.asarray(self.pde.alive())
            nu = np.asarray(nu)
            return float(nu[alive].mean()) if alive.any() else None
        nu = float(nu)
        return nu if np.isfinite(nu) else None

    # -- signals -------------------------------------------------------------

    def _install_signals(self) -> None:
        try:
            for sig in (signal.SIGTERM, signal.SIGINT):
                self._prev_handlers[sig] = signal.signal(sig, self._on_signal)
        except ValueError:  # not the main thread: run un-guarded
            self._prev_handlers = {}

    def _restore_signals(self) -> None:
        for sig, handler in self._prev_handlers.items():
            signal.signal(sig, handler)
        self._prev_handlers = {}

    def _on_signal(self, signum, frame) -> None:
        # defer: the flag is acted on at the next chunk boundary, where the
        # state is at a consistent step (checkpoint-then-exit)
        self._interrupt = signum

    def _root_decides(self, local: bool) -> bool:
        """Root-decides handshake for anything that leads into a collective
        (preemption stop, cadence checkpoint): on a multihost mesh rank 0's
        flag is broadcast so every host takes the same branch — hosts
        evaluating wall clocks or signals locally would disagree and wedge
        the next collective.  Single-host: the local flag.  One shared
        primitive (:func:`~rustpde_mpi_tpu.parallel.multihost.root_decides`)
        — the serve scheduler's handshakes ride the identical code."""
        try:
            from ..parallel import multihost
        except Exception:  # no runtime at all: the local path is the only one
            return bool(local)
        return multihost.root_decides(local)

    def _preempt_agreed(self) -> bool:
        """Preemption stop (a stray local signal on a non-root host is
        ignored; real preemption hits every host)."""
        return self._root_decides(self._interrupt is not None)

    # -- checkpointing -------------------------------------------------------

    def _state_ok(self) -> bool:
        """Never checkpoint a dead state: a NaN single-run state (or an
        all-dead ensemble) must not overwrite the rollback target.  Models
        distinguishing "exit because done" from "exit because dead" (the
        steady-state finder converging is a SUCCESS worth checkpointing)
        expose ``state_healthy``; the break criterion stays ``exit()``."""
        healthy = getattr(self.pde, "state_healthy", None)
        try:
            if healthy is not None:
                return bool(healthy())
            return not self.pde.exit()
        except Exception:
            return False

    @staticmethod
    def _is_enospc(exc) -> bool:
        """True when a write failure's cause chain bottoms out in an
        out-of-space errno (:class:`AsyncWriteError` wraps the worker's
        ``OSError`` as ``__cause__``; h5/shutil re-raises chain through
        ``__context__``)."""
        hops = 0
        while exc is not None and hops < 8:
            if getattr(exc, "errno", None) == _errno.ENOSPC:
                return True
            exc = exc.__cause__ if exc.__cause__ is not None else exc.__context__
            hops += 1
        return False

    def _degrade_checkpoints(self, exc, reason: str) -> None:
        """Disk-full containment: journal ``checkpoint_failed`` WITH the
        errno, consume the writer's sticky failure backlog (later
        submits/drains must not re-raise the wedge just contained), and
        flip ``_ckpt_disabled`` — the run continues on in-memory rollback
        snapshots only.  The last durable checkpoint stays valid; only
        the on-disk chain stops advancing.  Admission-side containment
        (the queue's ``storage_full`` 503) lives in serve/queue.py."""
        self._ckpt_disabled = True
        if self._io is not None:
            try:
                self._io.writer.drain(raise_errors=False)
            except Exception:  # a wedged drain must not mask containment
                pass
            self._io.writer.consume_errors()
        _tm.counter(
            "checkpoints_degraded_total",
            "runs degraded to in-memory rollback after ENOSPC",
        ).inc()
        self._journal(
            {
                "event": "checkpoint_failed",
                "reason": reason,
                "errno": _errno.ENOSPC,
                "error": str(exc) if exc is not None else "no space left on device",
                "degraded": "in_memory_rollback_only",
                "step": self.step,
            }
        )

    def _checkpoint(self, reason: str) -> str | None:
        """Write a rolling checkpoint (root only) and barrier all hosts.

        Single-process runs with ``io.async_checkpoints`` take the
        overlapped path (:meth:`_checkpoint_async`): state fetched to host
        here, serialization/digest/fsync on the pipeline worker.  Edge
        checkpoints (anchor/final/preempt) drain immediately after
        submitting, so their durability and journal ordering match the
        synchronous writer; only cadence checkpoints overlap stepping.

        Multi-controller meshes (and forced ``io.sharded_checkpoints``)
        take the SHARDED two-phase path (:meth:`_checkpoint_sharded`): each
        process writes only its addressable shards and root commits via an
        atomic manifest — the per-host slab IO the gathered writers (which
        fetch the full state via ``np.asarray``) cannot provide.  A write
        failure on ANY host aborts the commit collectively (no manifest),
        so every host sees a clean raise instead of a wedged job."""
        if not self._state_ok():
            self._journal({"event": "checkpoint_skipped", "reason": reason})
            return None
        if self._ckpt_disabled:
            # disk full earlier in the run: in-memory rollback only
            self._journal(
                {"event": "checkpoint_skipped", "reason": reason,
                 "cause": "storage_full"}
            )
            return None
        path = checkpoint.checkpoint_path(self.run_dir, self.step)
        if self._sharded:
            return self._checkpoint_sharded(path, reason)
        if self._async_ckpt and self._io is not None:
            return self._checkpoint_async(path, reason)
        if self._io is not None:
            # a queued background write may still be in flight: settle the
            # directory before this synchronous write + rotation
            try:
                self._io.writer.drain()
            except AsyncWriteError as exc:
                if not self._is_enospc(exc):
                    raise
                self._degrade_checkpoints(exc, reason)
                return None
        t0 = _time.monotonic()
        write_error = None
        if _is_root():
            try:
                if self._is_ensemble:
                    checkpoint.write_ensemble_snapshot(self.pde, path, step=self.step)
                else:
                    checkpoint.write_snapshot(self.pde, path, step=self.step)
                checkpoint.rotate_checkpoints(self.run_dir, self.keep)
            except Exception as exc:  # must not skip the barrier below
                write_error = exc
        try:
            from ..parallel import multihost

            multihost.sync_hosts("rustpde-checkpoint")
        except DispatchHang:
            raise
        except Exception:
            pass
        # every host must agree on failure (root alone raising would leave
        # the others hanging at the next collective)
        if self._root_decides(write_error is not None):
            if self._root_decides(self._is_enospc(write_error)):
                # disk full is CONTAINED, not fatal: every host flips to
                # in-memory-rollback-only together (both branches above
                # are root-broadcast, so the flag stays host-identical)
                self._degrade_checkpoints(write_error, reason)
                return None
            self._journal(
                {"event": "checkpoint_failed", "reason": reason, "error": str(write_error)}
            )
            if write_error is not None:
                raise write_error
            raise RuntimeError("checkpoint write failed on the root host")
        self._last_ckpt_wall = _time.monotonic()
        self._last_ckpt_time = float(self.pde.get_time())
        self._last_ckpt_path = path
        write_s = _time.monotonic() - t0
        _tm.histogram(
            "checkpoint_write_seconds", "serialize+digest+fsync seconds"
        ).observe(write_s)
        _tm.counter("checkpoints_total", "checkpoints written", reason=reason).inc()
        self._journal(
            {
                "event": "checkpoint",
                "reason": reason,
                "path": path,
                "write_s": round(write_s, 3),
                "nu": self._nu(),
            }
        )
        return path

    def _checkpoint_async(self, path: str, reason: str) -> str | None:
        """Overlapped checkpoint: the device sync (host snapshot fetch) and
        the Nu readout happen here, on the boundary state the run needed
        anyway; the expensive part — h5 serialization, the content digest,
        two fsyncs, rotation — runs on the io_pipeline worker while the
        device steps on.  ``_last_ckpt_path`` only advances once the write
        is durably on disk (worker side), and every rollback/resume read
        drains the writer first, so recovery can never target a file that
        is still being written."""
        t0 = _time.monotonic()
        with _tr.span("checkpoint_stage", reason=reason, step=self.step):
            if self._is_ensemble:
                snap = checkpoint.ensemble_snapshot_to_host(self.pde, step=self.step)
            else:
                snap = checkpoint.snapshot_to_host(self.pde, step=self.step)
        snapshot_s = _time.monotonic() - t0
        self._io_snapshot_s += snapshot_s
        _tm.histogram(
            "checkpoint_snapshot_seconds", "main-thread device->host staging"
        ).observe(snapshot_s)
        event = {
            "event": "checkpoint",
            "reason": reason,
            "path": path,
            "async": True,
            "step": self.step,
            "time": round(float(self.pde.get_time()), 9),
            "snapshot_s": round(snapshot_s, 3),
            "nu": self._nu(),
        }

        def work():
            w0 = _time.monotonic()
            try:
                checkpoint.write_host_snapshot(snap, path)
                checkpoint.rotate_checkpoints(self.run_dir, self.keep)
            except BaseException as exc:
                self._journal(
                    {
                        "event": "checkpoint_failed",
                        "reason": reason,
                        "error": str(exc),
                        "step": event["step"],
                        **({"errno": _errno.ENOSPC}
                           if self._is_enospc(exc) else {}),
                    }
                )
                raise
            with self._lock:
                self._last_ckpt_path = path
            write_s = _time.monotonic() - w0
            _tm.histogram(
                "checkpoint_write_seconds", "serialize+digest+fsync seconds"
            ).observe(write_s)
            _tm.counter(
                "checkpoints_total", "checkpoints written", reason=reason
            ).inc()
            self._journal({**event, "write_s": round(write_s, 3)})

        try:
            self._io.submit_write(work, path, nbytes=snap.nbytes)
        except AsyncWriteError as exc:
            # an EARLIER background write failed and surfaced here; a
            # disk-full cause degrades (satellite: the writer path must
            # journal checkpoint_failed{errno} and fall back to
            # in-memory rollback, not wedge every later submit)
            if not self._is_enospc(exc):
                raise
            self._degrade_checkpoints(exc, reason)
            return None
        # cadence clocks restart at SUBMIT time: the snapshot point is what
        # bounds data loss, not when the bytes landed
        self._last_ckpt_wall = _time.monotonic()
        self._last_ckpt_time = float(self.pde.get_time())
        if reason != "cadence":
            # anchor/final/preempt must be durable before the run proceeds
            try:
                self._io.writer.drain()
            except AsyncWriteError as exc:
                if not self._is_enospc(exc):
                    raise
                self._degrade_checkpoints(exc, reason)
                return None
        return path

    def _checkpoint_sharded(self, path: str, reason: str) -> str:
        """Distributed two-phase checkpoint (every host enters together —
        the caller's decision was root-broadcast): fetch THIS host's
        addressable slabs, write+fsync the shard file, barrier, exchange
        digests, root commits the manifest (utils/checkpoint).

        With the pipeline armed, a CADENCE checkpoint overlaps: the shard
        serialization runs on this host's background writer while the
        device steps the next chunk, and the collective commit is deferred
        to the next chunk boundary (:meth:`_commit_pending`) — after a
        local drain, so the barrier only ever sees fsynced shards.  Edge
        checkpoints (anchor/final/preempt) write and commit inline."""
        self._commit_pending()  # at most one deferred commit in flight
        t0 = _time.monotonic()
        with _tr.span("checkpoint_stage", reason=reason, step=self.step):
            snap = checkpoint.sharded_snapshot_to_host(self.pde, step=self.step)
        snapshot_s = _time.monotonic() - t0
        self._io_snapshot_s += snapshot_s
        _tm.histogram(
            "checkpoint_snapshot_seconds", "main-thread device->host staging"
        ).observe(snapshot_s)
        event = {
            "event": "checkpoint",
            "reason": reason,
            "path": path,
            "sharded": snap.shard_count,
            "step": self.step,
            "time": round(float(self.pde.get_time()), 9),
            "snapshot_s": round(snapshot_s, 3),
            "nu": self._nu(),
        }
        if self._async_ckpt and self._io is not None and reason == "cadence":
            self._io.submit_write(
                lambda: checkpoint.write_shard_file(snap, path),
                checkpoint.shard_path(path, snap.shard_index),
                nbytes=snap.nbytes,
            )
            self._pending_commit = (snap, path, reason, dict(event, async_=True))
            self._last_ckpt_wall = _time.monotonic()
            self._last_ckpt_time = float(self.pde.get_time())
            return path
        local_ok = True
        try:
            checkpoint.write_shard_file(snap, path)
        except Exception as exc:
            local_ok = False
            self._journal(
                {"event": "checkpoint_failed", "reason": reason, "error": str(exc),
                 **({"errno": _errno.ENOSPC} if self._is_enospc(exc) else {})}
            )
        self._finish_sharded_commit(snap, path, reason, event, local_ok)
        return path

    def _commit_pending(self) -> None:
        """Settle a deferred sharded cadence commit (every host calls this
        at the same points: each chunk boundary, before any rollback/resume
        checkpoint scan, before the next checkpoint, and at run end).
        Drain-before-barrier: the local writer is drained first, so this
        host's shard is durably on disk before the commit barrier."""
        if self._pending_commit is None:
            return
        snap, path, reason, event = self._pending_commit
        self._pending_commit = None
        local_ok = True
        if self._io is not None:
            try:
                self._io.writer.drain()
            except Exception as exc:
                local_ok = False
                self._journal(
                    {
                        "event": "checkpoint_failed",
                        "reason": reason,
                        "error": str(exc),
                        "step": event["step"],
                        **({"errno": _errno.ENOSPC}
                           if self._is_enospc(exc) else {}),
                    }
                )
        is_async = event.pop("async_", False)
        self._finish_sharded_commit(
            snap, path, reason, dict(event, **({"async": True} if is_async else {})),
            local_ok,
        )

    def _finish_sharded_commit(
        self, snap, path: str, reason: str, event: dict, local_ok: bool
    ) -> None:
        """The collective half: commit (barrier + digest allgather + root
        manifest), rotate on success, journal the ``checkpoint_sharded``
        telemetry (shard count, bytes/host, barrier wait seconds)."""
        w0 = _time.monotonic()
        with _tr.span("checkpoint_commit", step=self.step):
            stats = checkpoint.commit_sharded_snapshot(snap, path, local_ok=local_ok)
        _tm.counter(
            "checkpoint_barrier_seconds_total",
            "seconds waiting at the two-phase commit barrier",
        ).inc(float(stats.get("barrier_s") or 0.0))
        if not stats["ok"]:
            if local_ok:
                # the failing host already journaled its local cause; only
                # hosts learning of the abort here add an event (one
                # failure = one checkpoint_failed line per host)
                self._journal(
                    {
                        "event": "checkpoint_failed",
                        "reason": reason,
                        "error": "sharded checkpoint aborted (a host failed "
                        "its shard write); no manifest committed",
                        "step": event.get("step", self.step),
                    }
                )
            raise checkpoint.CheckpointError(
                path,
                "sharded checkpoint aborted: a host failed its shard write "
                "(no manifest committed; the previous checkpoint is intact)",
            )
        if _is_root():
            checkpoint.rotate_checkpoints(self.run_dir, self.keep)
        _tm.counter("checkpoints_total", "checkpoints written", reason=reason).inc()
        with self._lock:
            self._last_ckpt_path = path
        self._last_ckpt_wall = _time.monotonic()
        self._last_ckpt_time = event.get("time", float(self.pde.get_time()))
        self._journal(
            {
                **event,
                "commit_s": round(_time.monotonic() - w0, 3),
                "checkpoint_sharded": {
                    "shards": stats["shards"],
                    "bytes_host": stats["bytes_host"],
                    "bytes_total": stats["bytes_total"],
                    "barrier_s": stats["barrier_s"],
                },
            }
        )

    def _pick_checkpoint(self) -> str | None:
        """Newest valid checkpoint, chosen by ROOT and broadcast: each host
        scanning its own view of run_dir could disagree (filesystem
        visibility skew; a host-local run_dir would be outright divergent),
        and a host restoring a different step than its peers wedges the
        next collective.  The broadcast carries the step number — the
        step-encoded filename is the cross-host contract (multihost
        resume/rollback requires run_dir on shared storage)."""
        # an uncommitted sharded cadence checkpoint must commit (or abort)
        # before any scan: rollback/resume must never race the two-phase
        # window — drain-before-barrier, then manifest, then read
        self._commit_pending()
        if self._io is not None:
            # never read/scan past an in-flight background write: rollback
            # and resume must see a settled directory (a failed write
            # re-raises here, where the caller can still decide).  A
            # disk-full failure degrades instead — the scan proceeds on
            # whatever is durably on disk (the failed file never rotated
            # in, so the newest VALID checkpoint is still correct)
            try:
                self._io.writer.drain()
            except AsyncWriteError as exc:
                if not self._is_enospc(exc):
                    raise
                self._degrade_checkpoints(exc, "scan")
        if _single_process():
            return checkpoint.latest_checkpoint(self.run_dir)
        from ..parallel import multihost

        step = -1
        if _is_root():
            path = checkpoint.latest_checkpoint(self.run_dir)
            if path is not None:
                step = int(checkpoint.read_attrs(path).get("step", -1))
        step = int(multihost.broadcast(np.int64(step)))
        if step < 0:
            return None
        return checkpoint.checkpoint_path(self.run_dir, step)

    def _maybe_resume(self) -> bool:
        if not self.resume:
            return False
        path = self._pick_checkpoint()
        if path is None:
            return False
        # latest_checkpoint digest-verified the file (and read() verifies
        # again); the attrs lookup can skip the hash pass
        attrs = checkpoint.read_attrs(path)
        self.pde.read(path)
        self.step = int(attrs.get("step", 0))
        self._restore_dt(attrs)
        self._last_ckpt_time = float(self.pde.get_time())
        self._last_ckpt_path = path
        self._journal({"event": "resumed", "path": path})
        return True

    def _restore_dt(self, attrs: dict) -> None:
        """Restore the step size the checkpoint was written at: a run whose
        dt was backed off after a divergence and then got preempted must NOT
        resume at the original (diverging) dt — that would re-diverge and
        burn a fresh retry budget every preemption cycle."""
        dt = attrs.get("dt")
        if dt is None or not hasattr(self.pde, "set_dt"):
            return
        dt = float(dt)
        if dt != float(self.pde.get_dt()):
            self.pde.set_dt(dt)
            self._journal({"event": "dt_restored", "dt": dt})

    # -- dispatch (fault injection + watchdog) -------------------------------

    def _update(self, pde, n: int):
        """One watchdog-guarded dispatch; returns the model's
        :class:`~rustpde_mpi_tpu.utils.governor.ChunkStatus` when stability
        sentinels are armed (None otherwise)."""

        def work():
            if self._slow_pending:
                self._slow_pending = False
                _time.sleep(
                    max(2.0 * (self.dispatch_timeout_s or 0.0), 1.0)
                )
            if hasattr(pde, "update_n"):
                result = pde.update_n(n)
            else:
                result = None
                for _ in range(n):
                    pde.update()
            # force the device work into the deadline window ONLY when a
            # watchdog is armed: update_n dispatches asynchronously and the
            # hang materializes at the sync — but an unconditional fence
            # here would serialize the overlapped pipeline (the whole point
            # of dispatch double-buffering is to keep the queue full)
            if self.dispatch_timeout_s:
                state = getattr(pde, "state", None)
                if state is not None:
                    import jax

                    jax.block_until_ready(state)
            return result

        with _tr.span("dispatch", steps=n, step=self.step):
            return call_with_watchdog(
                work, self.dispatch_timeout_s, label=f"update_n({n}) @ step {self.step}"
            )

    def _advance(self, pde, n: int) -> None:
        """Advance n steps in sub-chunks of at most ``max_chunk_steps``, so
        a run launched without save boundaries (``save_intervall=None``
        would otherwise dispatch the WHOLE horizon as one chunk) still hands
        control back at a bounded cadence for signals and checkpoints.  The
        early break is root-decided, so every host stops after the same
        sub-chunk; returning with fewer steps advanced is safe — the
        chunked driver re-reads ``pde.get_time()`` every iteration.

        With the governor active every sub-chunk's sentinel status is fed
        through it here: a ``pre_divergence`` catch was already rolled back
        in memory by ``update_n``, so the governor's dt/member decision is
        applied and the loop returns (the driver re-plans at the new dt and
        the same sim-time — that IS the retry)."""
        cap = self.max_chunk_steps if self.max_chunk_steps > 0 else n
        if (
            self._overlap
            and self.governor is not None
            and hasattr(pde, "update_n_pending")
        ):
            return self._advance_lagged(pde, n, cap)
        while n > 0:
            k = min(n, cap)
            rec = self._integ_predispatch(pde, self.step)
            dt_before = pde.get_dt()
            status = self._update(pde, k)
            if status is not None and self.governor is not None:
                committed = self._govern(pde, status)
                if committed:
                    self.step += k
                    n -= k
                    _tm.counter("runner_steps_total", "committed simulation steps").inc(k)
                    if not self._integ_commit(pde, k, rec):
                        return  # integrity rollback: driver re-plans
                else:
                    self._integ_drop()
                if not committed or pde.get_dt() != dt_before:
                    # rolled back (retry at the governor's new dt) or dt
                    # adjusted: the remaining step budget was planned at the
                    # old dt — hand control back so the driver re-plans
                    return
            elif status is not None and status.pre_divergence:
                # sentinels armed but no governor: leave the latch for the
                # reactive path (exit() fires at the chunk boundary)
                self._integ_drop()
                return
            else:
                self.step += k
                n -= k
                _tm.counter("runner_steps_total", "committed simulation steps").inc(k)
                if not self._integ_commit(pde, k, rec):
                    return  # integrity rollback: driver re-plans
            if n > 0 and self._root_decides(self._interrupt is not None):
                return  # integrate()'s on_chunk acts at the boundary

    def _advance_lagged(self, pde, n: int, cap: int) -> None:
        """Governed sub-chunking with dispatch double-buffering — the lag=1
        sentinel contract: sub-chunk i+1 is dispatched, from chunk i's
        PROVISIONAL end state, before chunk i's sentinel scalars are
        fetched, so the device queue stays full while the governor reads
        chunk i.  Exactness is preserved by construction:

        * the hard CFL ceiling lives ON DEVICE (the in-scan early exit), so
          when chunk i trips, the speculative chunk steps a finite state
          whose work is simply discarded — ``resolve()`` of chunk i
          restores the chunk-i start snapshot, and the in-flight pending
          is ``discard()``-ed unresolved,
        * a dt adjustment decided from chunk i lands after chunk i+1 was
          dispatched at the old dt: that chunk is valid physics and is
          committed — the governor rescales its stale-dt CFL
          (StabilityGovernor.on_chunk) — and control returns to the driver
          to re-plan at the new dt.

        ``self.step`` counts only resolved-and-committed chunks, so
        checkpoint filenames, journal stamps and fault-injection points are
        identical to the synchronous path."""
        # each in-flight entry: (PendingChunkStatus, k, integrity record,
        # end-of-chunk digest future).  The digest of a chunk's PROVISIONAL
        # end state is dispatched right behind the chunk itself — by its
        # commit (one iteration later) the uint32 is long on host, so the
        # lag=1 device-queue contract survives the integrity layer intact.
        # ``disp_step`` tracks the DISPATCH frontier (self.step lags it by
        # the in-flight chunk) so chain-check steps line up.
        pending: tuple | None = None
        disp_step = self.step
        while n > 0 or pending is not None:
            nxt = None
            if n > 0:
                k = min(n, cap)
                rec = self._integ_predispatch(pde, disp_step)
                chunk = self._update_pending(pde, k)
                live = (
                    pde.state_digest_async() if rec is not None else None
                )
                nxt = (chunk, k, rec, live)
                disp_step += k
                n -= k
            if pending is not None:
                chunk, kprev, rec_p, live_p = pending
                dt_before = pde.get_dt()
                status = self._resolve_pending(chunk, kprev)
                committed = self._govern(pde, status)
                if committed:
                    self.step += kprev
                    _tm.counter("runner_steps_total", "committed simulation steps").inc(kprev)
                    if not self._integ_commit(pde, kprev, rec_p, live=live_p):
                        # integrity rollback: the speculative chunk stepped
                        # a corrupt state — drop it unresolved
                        if nxt is not None:
                            nxt[0].discard()
                        return
                if not committed:
                    # chunk kprev rolled back in memory (retry/kill/giveup):
                    # the speculative chunk stepped a doomed state — drop it
                    # unresolved and let the driver re-plan
                    self._integ_drop()
                    if nxt is not None:
                        nxt[0].discard()
                    return
                if pde.get_dt() != dt_before:
                    # dt adjusted: settle the in-flight old-dt chunk (valid
                    # physics; the governor rescales its stale-dt CFL), then
                    # hand back so the driver re-plans at the new dt
                    if nxt is not None:
                        chunk2, k2, rec2, live2 = nxt
                        status2 = self._resolve_pending(chunk2, k2)
                        if self._govern(pde, status2):
                            self.step += k2
                            _tm.counter(
                                "runner_steps_total", "committed simulation steps"
                            ).inc(k2)
                            self._integ_commit(pde, k2, rec2, live=live2)
                        else:
                            self._integ_drop()
                    return
            pending = nxt
            if (
                pending is not None
                and n > 0
                and self._root_decides(self._interrupt is not None)
            ):
                n = 0  # interrupt: settle the in-flight chunk, then return

    def _update_pending(self, pde, k: int):
        """Watchdog-guarded DISPATCH of one deferred-commit sentinel chunk
        (enqueue only — the matching sync point is :meth:`_resolve_pending`,
        which carries its own watchdog)."""

        def work():
            if self._slow_pending:
                self._slow_pending = False
                _time.sleep(max(2.0 * (self.dispatch_timeout_s or 0.0), 1.0))
            return pde.update_n_pending(k)

        with _tr.span("dispatch_pending", steps=k, step=self.step):
            return call_with_watchdog(
                work,
                self.dispatch_timeout_s,
                label=f"update_n_pending({k}) @ step {self.step}",
            )

    def _resolve_pending(self, chunk, k: int):
        """Watchdog-guarded resolve: a wedged device materializes here, at
        the sentinel fetch, instead of at the dispatch."""
        with _tr.span("resolve", steps=k, step=self.step):
            return call_with_watchdog(
                chunk.resolve,
                self.dispatch_timeout_s,
                label=f"resolve({k}) @ step {self.step}",
            )

    def _govern(self, pde, status) -> bool:
        """Feed one chunk's sentinel status through the governor and apply
        its decision; returns True when the chunk was committed (state
        advanced), False when it was rolled back in memory."""
        gov = self.governor
        decision = gov.on_chunk(status, step=self.step)
        # live governor gauges: the host-side sentinel scalars the chunk
        # already fetched — never an extra device transfer
        _tm.gauge("governor_cfl", "chunk-max advective CFL").set(status.cfl_max)
        _tm.gauge("governor_rung", "dt-ladder rung index").set(gov.rung)
        _tm.gauge("governor_dt", "current governed dt").set(status.dt)
        if status.pre_divergence:
            _tm.counter(
                "runner_pre_divergence_total", "CFL-ceiling sentinel catches"
            ).inc()
        self._journal(
            {
                "event": "cfl",
                "cfl_max": status.cfl_max,
                "ke": status.ke,
                "ke_growth_max": status.ke_growth_max,
                "div_max": status.div_max,
                "dt": status.dt,
                "rung": gov.rung,
                "pre_divergence": status.pre_divergence,
            }
        )
        if status.pre_divergence:
            self._journal(
                {
                    "event": "pre_divergence",
                    "cfl_max": status.cfl_max,
                    "dt": status.dt,
                    "steps_done": status.steps_done,
                    "pinned": list(status.pinned) if status.pinned else None,
                }
            )
            if decision.action == "retry":
                pde.set_dt(decision.dt)
                _tm.counter("runner_dt_adjust_total", "governor dt changes").inc()
                self._journal(
                    {
                        "event": "dt_adjust",
                        "dt": decision.dt,
                        "rung": gov.rung,
                        "reason": decision.reason,
                    }
                )
                pde.clear_pre_divergence()
                return False
            if decision.action == "kill_members":
                pde.mark_dead(decision.members)
                self._journal(
                    {
                        "event": "member_killed",
                        "members": list(decision.members),
                        "reason": decision.reason,
                    }
                )
                if self.respawn_members and hasattr(pde, "respawn_dead"):
                    respawned = pde.respawn_dead(
                        amp=self.respawn_amp, seed=self._respawn_seed_arg()
                    )
                    self._journal({"event": "respawn", "respawned": respawned})
                pde.clear_pre_divergence()
                return False
            # give_up: the ladder is exhausted — leave the latch set so
            # integrate() returns "break" and the reactive checkpoint
            # rollback (which may shrink dt below the ladder) takes over
            self._journal({"event": "governor_giveup", "reason": decision.reason})
            return False
        if decision.action == "adjust":
            pde.set_dt(decision.dt)
            _tm.counter("runner_dt_adjust_total", "governor dt changes").inc()
            self._journal(
                {
                    "event": "dt_adjust",
                    "dt": decision.dt,
                    "rung": gov.rung,
                    "reason": decision.reason,
                }
            )
        return True

    # -- end-to-end integrity (integrity/) ------------------------------------

    def _integrity_on(self, pde) -> bool:
        return bool(getattr(pde, "integrity_armed", False))

    def _integrity_ledger(self):
        if self._integ_ledger is None:
            from ..integrity import QuarantineLedger

            cfg = getattr(self.pde, "integrity_config", None)
            self._integ_ledger = QuarantineLedger(
                self.run_dir,
                strikes=getattr(cfg, "strikes", 2),
                strike_ttl_s=getattr(cfg, "strike_ttl_s", 3600.0),
            )
        return self._integ_ledger

    def _integ_device(self, host: int | None = None) -> str:
        """Ledger/journal device key: ``<platform>:<id>@proc<p>`` — the
        localized host's first device when the audit could attribute the
        corruption, this process's first local device otherwise."""
        try:
            import jax

            if host is not None:
                for d in jax.devices():
                    if getattr(d, "process_index", 0) == host:
                        return f"{d.platform}:{d.id}@proc{host}"
            d = jax.local_devices()[0]
            return f"{d.platform}:{d.id}@proc{getattr(d, 'process_index', 0)}"
        except Exception:
            return "unknown:0@proc0"

    def _integ_predispatch(self, pde, start_step: int):
        """Chunk-start integrity bookkeeping: anchor the first verified
        snapshot (the IC, or whatever a digest-verified restore installed),
        stream the chunk-start digest for the boundary chain check, and
        retain the chunk-start state copy when this chunk is audit-due.
        Returns the record :meth:`_integ_commit` consumes, or None."""
        if not self._integrity_on(pde):
            return None
        cad = max(1, int(pde.integrity_config.resolved_cadence()))
        due = (self._integ_chunks + 1) % cad == 0
        snap = None
        if due or self._integ_verified is None:
            snap = pde.integrity_snapshot()
            if self._integ_verified is None:
                self._integ_verified = (start_step, snap)
        start_fut = pde.state_digest_async()
        return (start_step, start_fut, snap if due else None, pde.get_dt())

    def _integ_commit(self, pde, k: int, rec, live=None) -> bool:
        """Commit-side integrity hook: stream the end-of-chunk digest,
        chain-check EVERY boundary (the chunk-start digest must bit-equal
        the previous commit's — corruption of the state at rest between
        chunks is invisible to a shadow re-execution, which would
        faithfully reproduce it), and at the audit cadence re-execute the
        chunk from its retained start copy and compare (``shadow``).
        Returns False when a mismatch was contained by an in-memory
        rollback — the caller hands control back so the driver re-plans
        from the restored sim-time."""
        if rec is None:
            return True
        start_step, start_fut, snap, disp_dt = rec
        prev = self._integ_prev
        if live is None:
            with _tr.span("integrity_digest", step=self.step):
                live = pde.state_digest_async()
        self._integ_prev = (self.step, live)
        self._integ_chunks += 1
        checks = {}
        if prev is not None and prev[0] == start_step:
            # both futures were dispatched at least one chunk ago — these
            # resolves fetch long-materialized uint32 scalars, no fence
            checks["chain"] = (
                np.asarray(prev[1].result()),  # lint-ok: RPD005 replicated uint32 digest scalar
                np.asarray(start_fut.result()),  # lint-ok: RPD005 replicated uint32 digest scalar
            )
        if snap is not None and pde.get_dt() == disp_dt:
            # a governor dt change between dispatch and commit would make
            # the shadow re-execution run at the wrong dt — skip it for
            # this chunk (the chain check above still ran); the driver is
            # about to re-plan anyway
            with _tr.span("integrity_shadow", steps=k, step=self.step):
                d_shadow = np.asarray(  # lint-ok: RPD005 digest scalar
                    pde.shadow_digest_async(snap, k).result()
                )
            checks["shadow"] = (
                d_shadow,
                np.asarray(live.result()),  # lint-ok: RPD005 replicated uint32 digest scalar
            )
        failed = {c: p for c, p in checks.items() if not np.array_equal(*p)}
        if failed:
            return self._integ_contain(pde, k, rec, failed)
        if snap is not None:
            # full audit passed: the end state becomes the new verified
            # in-memory rollback target
            self._integ_verified = (self.step, pde.integrity_snapshot())
            _tm.counter(
                "runner_integrity_audit_total", "shadow audits passed"
            ).inc()
            self._journal({
                "event": "integrity_audit",
                "result": "ok",
                "chunk_steps": k,
                "checks": sorted(checks),
                "digest": [int(x) for x in
                           np.asarray(live.result()).reshape(-1)],  # lint-ok: RPD005 replicated uint32 digest
            })
        return True

    def _integ_contain(self, pde, k: int, rec, failed) -> bool:
        """Containment: journal the typed mismatch, charge a ledger strike
        (root-decided), roll back to the last digest-verified snapshot —
        or raise :class:`~rustpde_mpi_tpu.integrity.IntegrityError` when
        no verified snapshot exists or the device just crossed the
        quarantine threshold (the serve scheduler re-carves around it)."""
        from ..integrity import IntegrityError

        start_step = rec[0]
        check = "chain" if "chain" in failed else "shadow"
        want, got = failed[check]
        members = None
        if got.ndim:  # ensemble (k,) digests localize the corrupted member
            members = [int(i) for i in np.flatnonzero(got != want)]
        verified = self._integ_verified
        host = None
        if (
            check == "chain"
            and rec[2] is not None
            and verified is not None
            and verified[0] == start_step
        ):
            # clean and corrupt copies of the SAME step exist — per-host
            # masked digests attribute the corrupted pencil column
            host = self._integ_localize_host(pde, rec[2], verified[1])
        device = self._integ_device(host)
        _tm.counter(
            "runner_integrity_mismatch_total", "digest audit mismatches"
        ).inc()
        _tr.instant("integrity_mismatch", check=check, step=self.step)
        self._journal({
            "event": "integrity_mismatch",
            "check": check,
            "chunk_steps": k,
            "start_step": start_step,
            "members": members,
            "device": device,
        })
        newly = False
        if _is_root():
            newly = self._integrity_ledger().strike(
                device, step=self.step, detail=check
            )
        # the raise below must be collectively consistent — broadcast
        # root's threshold verdict like every other pre-collective decision
        newly = self._root_decides(newly)
        if newly:
            self._journal({
                "event": "device_quarantined",
                "device": device,
                "strikes": self._integrity_ledger().strikes_for(device)
                if _is_root() else None,
            })
        self._integ_prev = None
        member = members[0] if members else None
        if verified is None or newly:
            raise IntegrityError(
                f"digest {check} audit failed at step {self.step} and "
                + ("the device crossed the quarantine threshold" if newly
                   else "no verified snapshot exists to roll back to"),
                check=check, step=self.step, chunk_steps=k,
                member=member, device=device,
            )
        v_step, v_snap = verified
        pde.integrity_restore(v_snap)
        self.step = v_step
        self._slo_last_step = min(self._slo_last_step, v_step)
        _tm.counter(
            "runner_integrity_rollback_total", "in-memory integrity rollbacks"
        ).inc()
        self._journal({"event": "integrity_rollback", "to_step": v_step})
        return False

    def _integ_localize_host(self, pde, snap_corrupt, snap_clean):
        """Attribute an at-rest corruption to the owning process: digest
        each host's pencil columns of the corrupt and clean copies (mask
        built from mesh metadata — collectively consistent) and return the
        process whose masked digests differ.  None when unattributable."""
        try:
            import jax

            nproc = jax.process_count()
        except Exception:
            return None
        if nproc <= 1:
            return 0
        mdl = pde.model if hasattr(pde, "model") else pde
        scope = mdl._scope
        for h in range(nproc):
            def masked(st, h=h):
                with scope():
                    return jax.tree.map(
                        lambda x: x
                        * _host_column_mask(mdl, h, x, 1.0, miss=0.0),
                        st,
                    )

            dc = np.asarray(  # lint-ok: RPD005 replicated digest scalar
                pde.digest_of_async(masked(snap_corrupt["state"])).result()
            )
            dv = np.asarray(  # lint-ok: RPD005 replicated digest scalar
                pde.digest_of_async(masked(snap_clean["state"])).result()
            )
            if not np.array_equal(dc, dv):
                return h
        return None

    def _integ_drop(self) -> None:
        """A chunk was rolled back in memory (governor retry, sentinel
        latch): the streamed digest chain no longer describes the live
        state — restart it at the next commit.  The verified snapshot
        STAYS valid (it is a committed, audited state)."""
        self._integ_prev = None

    def _dispatch(self, pde, n: int) -> None:
        fault = self.fault
        fire_at = None
        if (
            fault is not None
            and not fault.fired
            and (fault.gang is None or fault.bound_gang == fault.gang)
        ):
            # a GANG-scoped plan is consumed only while its gang campaign
            # is bound (the serve scheduler's bind_gang at open): the step
            # threshold crossing during some other bucket's campaign must
            # not burn the trigger as a silent no-op.  If the matching
            # campaign opens after the threshold already passed, the plan
            # fires on its first gang dispatch instead — still
            # collectively aligned, because the gang binding verdict was
            # root-broadcast at campaign open.
            if self.step < fault.step <= self.step + n:
                fire_at = fault.step
            elif fault.gang is not None and fault.step <= self.step:
                fire_at = self.step
        if fire_at is not None:
            pre = fire_at - self.step
            if pre > 0:
                self._advance(pde, pre)
            if self.step != fire_at:
                return  # pre-advance stopped early (signal); fire later
            fault.fired = True
            _tr.instant("fault_injected", kind=fault.kind, step=self.step)
            row = {"event": "fault_injected", "kind": fault.kind,
                   "host": fault.host}
            if fault.gang is not None:
                row["gang"] = fault.gang
                row["member"] = fault.member
            self._journal(row)
            if fault.kind == "nan":
                # host-scoped or not, EVERY process dispatches the same
                # (masked) poison computation — collective consistency
                poison_state(pde, host=fault.host)
                return  # run is over either way; exit() fires at the boundary
            if fault.kind == "kill":
                if fault.host is None and fault.gang is None:
                    os.kill(os.getpid(), signal.SIGTERM)
                elif fault.scoped_here():
                    # hard single-host (or gang-member) death, no
                    # checkpoint-then-exit: the survivors wedge at the next
                    # collective, which the sync watchdog — or the gang
                    # barrier watchdog — converts into a structured hang
                    os.kill(os.getpid(), signal.SIGKILL)
            elif fault.kind == "slow":
                if fault.scoped_here():
                    self._slow_pending = True
            elif fault.kind == "spike":
                # finite incipient blow-up: stepping continues below, so the
                # sentinels (or, ungoverned, the NaN criterion) see it
                spike_state(pde, self.spike_factor, host=fault.host)
                # a LOUD intentional mutation — restart the digest chain so
                # the integrity layer doesn't flag physics it can see coming
                self._integ_drop()
            elif fault.kind == "bitflip":
                # one silent mantissa flip: finite, CFL-sane, invisible to
                # every loud sentinel.  Stepping continues below, and the
                # digest chain is deliberately NOT reset — the injection
                # simulates corruption the runner does not know about, and
                # only an armed integrity audit may catch it
                info = bitflip_state(
                    pde, fire_at, host=fault.host, member=fault.only_member
                )
                self._journal({
                    "event": "bitflip_injected",
                    **{kk: vv for kk, vv in info.items() if kk != "index"},
                    "index": list(info["index"]),
                })
            rem = n - pre
            if rem > 0:
                self._dispatch(pde, rem)
            return
        self._advance(pde, n)

    def _on_chunk(self, pde) -> bool:
        # settle a deferred sharded commit FIRST (collective; the pending
        # flag was set at a root-broadcast cadence decision, so every host
        # is here together) — this is where the overlapped shard write
        # rejoins the two-phase protocol, one chunk after its submit
        self._commit_pending()
        # boundary telemetry: feed the SLO throughput baseline the steps
        # committed since the previous boundary (host-side counters only);
        # a regression below the rolling baseline journals the typed
        # perf_degraded event — observability feeding back into robustness
        delta = self.step - self._slo_last_step
        self._slo_last_step = self.step
        degraded = self.slo.record(delta)
        if degraded is not None:
            _tm.counter(
                "runner_perf_degraded_total", "SLO throughput regressions"
            ).inc()
            _tr.instant("perf_degraded", **degraded)
            self._journal({"event": "perf_degraded", **degraded})
            # observability closing the loop on robustness: the FIRST
            # perf_degraded per process triggers a one-shot jax.profiler
            # capture of the slow window (telemetry/compile_log.py) — the
            # profile of the regression lands next to the row flagging it
            from ..telemetry import compile_log as _cl

            capture = _cl.capture_on_perf_degraded(self.run_dir)
            if capture is not None:
                self._journal({"event": "profile_capture", **capture})
        if self._metrics_dumper is not None:
            self._metrics_dumper.maybe_dump(step=self.step)
        self._stats_boundary()
        if self._preempt_agreed():
            return True  # integrate() returns "stopped"; run() checkpoints
        due = False
        if self.checkpoint_every_s is not None:
            due = _time.monotonic() - self._last_ckpt_wall >= self.checkpoint_every_s
        if not due and self.checkpoint_every_t is not None:
            due = (
                pde.get_time() - self._last_ckpt_time
                >= self.checkpoint_every_t - pde.get_dt() / 2.0
            )
        # the wall-clock part of `due` is host-local (clocks drift, root pays
        # the write time) but _checkpoint enters a collective barrier, so the
        # decision must be root's
        if self._root_decides(due):
            self._checkpoint("cadence")
        return False

    # -- physics-health streaming (models/stats.py) ---------------------------

    def _stats_boundary(self) -> None:
        """Per-boundary health streaming for a stats-armed model: resolve
        the PREVIOUS boundary's health future (lag=1 — by now the scalars
        are long since on host, so this fences nothing), export the gauges
        and the threshold-crossing journal events, then dispatch a fresh
        readout.  Every host dispatches (the readout is a collective
        program on a mesh); only root journals."""
        if not getattr(self.pde, "stats_armed", False):
            self._stats_health_pending = None
            return
        fut = self._stats_health_pending
        self._stats_health_pending = None
        if fut is not None:
            try:
                self._stats_health_report(fut.result())
            except Exception:
                pass  # health telemetry must never kill the run
        try:
            self._stats_health_pending = self.pde.stats_health_async()
        except Exception:
            self._stats_health_pending = None

    def _stats_health_report(self, vals) -> None:
        """Gauges + typed events from one resolved health vector (ensemble
        vectors reduce as the max over members — the worst member is the
        one the alert is about)."""
        from ..models.stats import HEALTH_NAMES

        arrs, d = {}, {}
        for name, v in zip(HEALTH_NAMES, vals):
            arr = np.asarray(v, dtype=np.float64).reshape(-1)  # lint-ok: RPD005 health futures resolve to host numpy scalars
            arrs[name] = arr
            # worst-member reduction: BL point counts are a LOW-is-bad
            # signal (too few grid points in the layer), everything else
            # is HIGH-is-bad — both reduce to the worst member
            red = np.min if name.startswith("bl_") else np.max
            d[name] = float(red(arr)) if arr.size else 0.0
        if d["samples"] < 1.0:
            return  # nothing accumulated yet — every readout would be 0
        # the budget alert must be SELF-CONSISTENT: every budget field in
        # the event comes from the one worst member (argmax nu_residual),
        # not a per-field max that mixes members into numbers whose own
        # plate/flux gap would not reproduce the reported residual
        worst_m = (
            int(arrs["nu_residual"].argmax()) if arrs["nu_residual"].size else 0
        )
        budget = {
            name: float(arrs[name][worst_m])
            for name in (
                "nu_residual", "ke_residual",
                "nu_plate_avg", "nu_flux_avg", "samples",
            )
        }
        tails = {
            ("temp", "x"): d["tail_t_x"],
            ("temp", "y"): d["tail_t_y"],
            ("ux", "x"): d["tail_ux_x"],
            ("ux", "y"): d["tail_ux_y"],
            ("uy", "x"): d["tail_uy_x"],
            ("uy", "y"): d["tail_uy_y"],
        }
        for (field, axis), val in tails.items():
            _tm.gauge(
                "stats_tail_energy_fraction",
                "energy fraction in the top third of the ortho spectrum",
                field=field,
                axis=axis,
            ).set(val)
        _tm.gauge(
            "stats_bl_points", "grid points inside the boundary layer",
            layer="thermal",
        ).set(d["bl_thermal_pts"])
        _tm.gauge(
            "stats_bl_points", "grid points inside the boundary layer",
            layer="viscous",
        ).set(d["bl_visc_pts"])
        _tm.gauge(
            "stats_budget_residual", "budget-closure residual", budget="ke"
        ).set(d["ke_residual"])
        _tm.gauge(
            "stats_budget_residual", "budget-closure residual", budget="nu"
        ).set(d["nu_residual"])
        _tm.gauge("stats_samples", "in-scan stats samples accumulated").set(
            d["samples"]
        )
        eng = self.pde.stats_engine
        tail_max = max(tails.values())
        worst = max(tails, key=tails.get)
        if tail_max > eng.tail_warn:
            if not self._stats_res_latched:
                self._stats_res_latched = True
                _tm.counter(
                    "stats_resolution_warnings_total",
                    "spectral-tail under-resolution warnings",
                ).inc()
                self._journal(
                    {
                        "event": "resolution_warning",
                        "field": worst[0],
                        "axis": worst[1],
                        "tail_fraction": tail_max,
                        "threshold": eng.tail_warn,
                        "samples": d["samples"],
                    }
                )
        elif tail_max < 0.5 * eng.tail_warn:
            self._stats_res_latched = False
        if d["nu_residual"] > eng.budget_warn and d["samples"] >= 2:
            if not self._stats_budget_latched:
                self._stats_budget_latched = True
                _tm.counter(
                    "stats_budget_drift_total",
                    "Nu budget-closure drift warnings",
                ).inc()
                self._journal(
                    {
                        "event": "budget_drift",
                        "member": worst_m,
                        "nu_residual": budget["nu_residual"],
                        "ke_residual": budget["ke_residual"],
                        "nu_plate_avg": budget["nu_plate_avg"],
                        "nu_flux_avg": budget["nu_flux_avg"],
                        "threshold": eng.budget_warn,
                        "samples": budget["samples"],
                    }
                )
        elif d["nu_residual"] < 0.5 * eng.budget_warn:
            self._stats_budget_latched = False

    # -- divergence recovery -------------------------------------------------

    def _respawn_seed_arg(self):
        """Seed handed to ``respawn_dead``: the config-carried campaign seed
        (folded with step/attempt so every respawn draws fresh-but-
        reproducible noise), the ensemble's own carried stream (``None``
        lets it use it), or the legacy step+attempt fallback."""
        if self.respawn_seed is not None:
            return (int(self.respawn_seed), self.step, self.attempt)
        if getattr(self.pde, "respawn_seed", None) is not None:
            return None
        return self.step + self.attempt

    def _dt_trajectory(self) -> list:
        """Every journaled dt change as ``(event, step, dt)`` — the evidence
        trail :class:`DivergenceError` reports when retries are exhausted."""
        traj = []
        for rec in read_journal(self.journal_path, on_error="skip"):
            dt = rec.get("dt")
            if dt is not None and rec.get("event") in (
                "start",
                "dt_restored",
                "dt_adjust",
                "retry",
                "divergence",
            ):
                traj.append((rec["event"], rec.get("step"), dt))
        return traj

    def _rollback(self) -> None:
        _tm.counter(
            "runner_rollbacks_total", "reactive checkpoint rollbacks"
        ).inc()
        _tr.instant("rollback", step=self.step, attempt=self.attempt)
        path = self._pick_checkpoint()
        if path is None:
            raise DivergenceError(
                f"diverged at step {self.step} with no valid checkpoint in "
                f"{self.run_dir!r} to roll back to; journaled dt trajectory: "
                f"{self._dt_trajectory()}"
            )
        attrs = checkpoint.read_attrs(path)  # latest_checkpoint verified it
        self.pde.read(path)
        self.step = int(attrs.get("step", 0))
        # the restored state predates everything the integrity layer
        # tracked: drop the digest chain AND the verified snapshot (it may
        # lie in the rolled-back future) — the next chunk re-anchors
        self._integ_prev = None
        self._integ_verified = None
        self._slo_last_step = min(self._slo_last_step, self.step)
        if hasattr(self.pde, "clear_pre_divergence"):
            # the restored checkpoint predates any latched sentinel catch
            self.pde.clear_pre_divergence()
        # NOTE: deliberately no _restore_dt here — backoff compounds from
        # the CURRENT dt, so consecutive retries keep shrinking instead of
        # resetting to the (larger) dt the rollback checkpoint was written
        # at — but never below the dt_min floor (a retry at a dt that can
        # no longer make progress just burns refactorizations)
        new_dt = None
        if hasattr(self.pde, "set_dt") and 0.0 < self.dt_backoff < 1.0:
            new_dt = max(self.pde.get_dt() * self.dt_backoff, self.dt_min)
            if new_dt != float(self.pde.get_dt()):
                self.pde.set_dt(new_dt)
        if self.governor is not None:
            # keep the governor's rung honest after an off-ladder backoff
            aligned = self.governor.align(float(self.pde.get_dt()), self.step)
            if aligned is not None:
                self.pde.set_dt(aligned)
        respawned = 0
        if self.respawn_members and hasattr(self.pde, "respawn_dead"):
            respawned = self.pde.respawn_dead(
                amp=self.respawn_amp, seed=self._respawn_seed_arg()
            )
        self._last_ckpt_time = float(self.pde.get_time())
        self._last_ckpt_path = path
        self._journal(
            {
                "event": "retry",
                "rollback_path": path,
                "dt": float(self.pde.get_dt()) if new_dt is not None else None,
                "dt_floor": bool(self.dt_min and new_dt == self.dt_min),
                "respawned": respawned,
            }
        )

    # -- the harness loop ----------------------------------------------------

    @contextlib.contextmanager
    def session(self, install_signals: bool = True, resume: bool | None = None):
        """Arm the harness WITHOUT the driver loop — the embedding surface
        for supervisors that own their own scheduling (serve.SimServer's
        continuously-batched slot loop).  Inside the block the runner's
        services are live exactly as under :meth:`run`: the IO pipeline and
        checkpoint format are selected, the governor armed, signals
        installed (``install_signals=False`` leaves them to the embedder),
        and a resume restores the newest valid checkpoint (``resume``
        overrides the constructor flag; the result is ``self.resumed``).
        The embedder drives :meth:`advance` / :meth:`checkpoint_now` /
        :meth:`drain_requested` and the context exit settles the pipeline
        and restores signal handlers — including on the
        :class:`DispatchHang` path, where lagged diagnostics are abandoned
        rather than resolved against a wedged device."""
        # long-lived entry point: arm the persistent compile cache so a
        # restarted incarnation deserializes its executables instead of
        # recompiling (RUSTPDE_COMPILE_CACHE=0 opts out; idempotent)
        config.ensure_compile_cache()
        self.resumed = False
        if install_signals:
            self._install_signals()
        self._setup_io()
        self._stats_health_pending = None
        # hand the model the run's journal writer for the session: model-
        # side statistics failures (stats_mismatch / stats_write_failed,
        # models/stats.report_stats_event) land as typed events in THIS
        # run's journal instead of vanishing into stdout (root only — the
        # journal is root-owned)
        self._saved_pde_journal = getattr(self.pde, "journal_writer", None)
        if _is_root():
            if self._journal_writer is None:
                self._journal_writer = JournalWriter(self.journal_path)
            self.pde.journal_writer = self._journal_writer
            if hasattr(self.pde, "model"):
                self.pde.model.journal_writer = self._journal_writer
        # telemetry arming (root only: run_dir is shared on multihost):
        # cadenced metrics.jsonl for headless runs + the unclean-exit
        # flight-record hook — disarmed on ANY session exit below (the
        # exception paths dump explicitly, with a better reason)
        if _is_root():
            self._metrics_dumper = MetricsDumper(
                os.path.join(self.run_dir, "metrics.jsonl")
            )
            self._exit_disarm = _tr.arm_exit_dump(self.run_dir, lambda: self.step)
        # a collective-desync trip mid-session should drop its flight
        # record next to the journal, like every other incident dump
        _sanitizer.set_run_dir(self.run_dir)
        try:
            if self.resume if resume is None else resume:
                self.resumed = self._maybe_resume()
            self._setup_governor()
            yield self
        except DispatchHang:
            # the runtime is wedged: teardown's diag flush would fetch from
            # the dead dispatch and block forever (un-watchdogged), eating
            # the structured raise — drop the lagged lines instead (the
            # background writer holds host-side data only, so its drain in
            # _teardown_io stays safe)
            if self._io is not None:
                self._io.abandon_diags()
            self.incident_dump("dispatch_hang")
            raise
        except BaseException as exc:
            # every incident ships with a timeline: DivergenceError, write
            # failures, KeyboardInterrupt — dumped before teardown so the
            # ring still holds the events leading in
            self.incident_dump(type(exc).__name__)
            raise
        finally:
            if self._exit_disarm is not None:
                self._exit_disarm()
                self._exit_disarm = None
            self._teardown_io()
            if install_signals:
                self._restore_signals()

    def incident_dump(self, reason: str) -> None:
        """Best-effort flight-recorder dump into the run_dir (root only) +
        a journal pointer — incident telemetry must never mask the
        incident itself.  Public: part of the embedding surface (the serve
        scheduler dumps with reason ``drain``), also driven internally on
        every exception escaping a session and on preemption."""
        if not _is_root():
            return
        try:
            path = _tr.dump_flight_record(self.run_dir, reason, step=self.step)
            if path is not None:
                # the dump's sequence number + the trace ids of the
                # requests that were on the device: a chaos soak's dump
                # pile stays attributable and chronologically sortable.
                # The seq comes from THIS dump's filename — a counter read
                # here could name a concurrent dump's id instead
                import re as _re

                from ..telemetry import reqtrace as _reqtrace

                m = _re.search(r"_n(\d+)\.json$", path)
                self._journal(
                    {
                        "event": "flight_record",
                        "reason": reason,
                        "path": path,
                        "seq": int(m.group(1)) if m else None,
                        "trace_ids": _reqtrace.active_ids() or None,
                    }
                )
        except Exception:
            pass

    # -- the embedding surface (serve.SimServer) ------------------------------

    def advance(self, n: int) -> None:
        """Advance up to ``n`` steps through the full dispatch stack —
        fault injection, watchdog deadlines, sub-chunking, governor — the
        supervisor-facing form of the private ``integrate`` hook.  May
        commit fewer than ``n`` steps (pending signal, governor re-plan);
        ``self.step`` counts what actually committed, so the caller loops
        on its own accounting."""
        self._dispatch(self.pde, n)

    def checkpoint_now(self, reason: str = "manual") -> str | None:
        """Write a checkpoint outside the cadence (drain, slot-table edge):
        same collective/async semantics as the internal cadence writer."""
        return self._checkpoint(reason)

    def request_drain(self) -> None:
        """Programmatic SIGTERM-equivalent: the next chunk boundary sees
        :meth:`drain_requested` true — the serve drain path rides the same
        deferred-interrupt machinery as real preemption."""
        self._interrupt = signal.SIGTERM

    def drain_requested(self) -> bool:
        """True when a signal (or :meth:`request_drain`) asked for a stop —
        root-decided on multihost, like every collective-adjacent flag."""
        return self._preempt_agreed()

    def on_boundary(self) -> bool:
        """Chunk-boundary housekeeping for embedding supervisors — exactly
        the hook ``integrate()`` drives: settle any deferred sharded
        commit, write a cadence checkpoint when due, and return True when
        a drain/preemption was requested."""
        return bool(self._on_chunk(self.pde))

    def run(self) -> dict:
        """Drive the model to ``max_time``, surviving what can be survived.

        Returns a summary dict (``outcome``: ``"done"`` | ``"preempted"``,
        final step/time/dt, retry count, final Nu, journal path).  Raises
        :class:`DivergenceError` once retries are exhausted and
        :class:`DispatchHang` when a dispatch blows its deadline."""
        pde = self.pde
        if not self.resume and checkpoint.checkpoint_files(self.run_dir):
            # a later rollback would splice the OLD campaign's trajectory
            # into this run — refuse rather than silently mix campaigns
            raise ValueError(
                f"resume=False but {self.run_dir!r} already holds "
                "checkpoints from a previous run; clear the directory or "
                "drop resume=False"
            )
        with self.session():
            self._journal(
                {
                    "event": "start",
                    "resumed": self.resumed,
                    "dt": float(pde.get_dt()),
                    "max_time": self.max_time,
                    "governed": self.governor is not None,
                    "io": {
                        "async_checkpoints": self._async_ckpt,
                        "overlap_dispatch": self._overlap,
                        "sharded_checkpoints": self._sharded,
                    },
                    "fault": dataclasses.asdict(self.fault) if self.fault else None,
                }
            )
            if self._last_ckpt_path is None:
                # rollback anchor: divergence recovery needs at least one
                # valid checkpoint to return to (_maybe_resume sets the
                # path when it restored one — no extra run_dir scan here)
                self._checkpoint("anchor")
            while True:
                try:
                    status = integrate(
                        pde,
                        self.max_time,
                        self.save_intervall,
                        dispatch=self._dispatch,
                        on_chunk=self._on_chunk,
                        overlap=self._overlap,
                    )
                except DispatchHang as exc:
                    self._journal(
                        {
                            "event": "dispatch_hang",
                            "label": exc.label,
                            "timeout_s": exc.timeout_s,
                        }
                    )
                    raise
                if status in ("time_limit", "timestep_limit"):
                    self._checkpoint("final")
                    self._drain_io()
                    self._journal_health()
                    self._journal({"event": "done", "status": status, "nu": self._nu()})
                    return self._summary("done")
                if status == "stopped":
                    self._checkpoint("preempt")
                    self._drain_io()
                    self._journal_health()
                    self._journal({"event": "preempted", "signal": self._interrupt})
                    # a preemption IS an incident: ship the timeline with it
                    self.incident_dump("preempt")
                    return self._summary("preempted")
                # status == "break": the model's NaN criterion fired (or a
                # sentinel catch the governor gave up on)
                self._journal({"event": "divergence", "dt": float(pde.get_dt())})
                if self.attempt >= self.max_retries:
                    self._journal({"event": "giveup", "retries": self.attempt})
                    self._journal_health()
                    raise DivergenceError(
                        f"diverged at step {self.step} and exhausted "
                        f"{self.max_retries} retries (dt now {pde.get_dt():g}); "
                        f"journaled dt trajectory: {self._dt_trajectory()}"
                    )
                self.attempt += 1
                self._rollback()

    def _setup_io(self) -> None:
        """Build the overlapped-IO pipeline for this run (run() entry).

        The checkpoint FORMAT is picked here too: ``io.sharded_checkpoints``
        ``None`` auto-selects the distributed two-phase format
        (utils/checkpoint.write_sharded_snapshot) on multi-process runtimes
        — the gathered writers need every shard addressable from root,
        which a real multi-controller mesh cannot provide — and the
        gathered single-file format otherwise; True/False force either.

        Async checkpointing runs single-process AND multihost-sharded: on a
        multihost mesh each host overlaps its own shard serialization on a
        per-host background writer, and the collective two-phase commit is
        deferred to the next chunk boundary — every host drains its writer
        before the barrier (drain-before-barrier), so the manifest only
        ever names fsynced shards.  Dispatch overlap (the lagged break
        check) stays single-process-only: a break flag resolving on
        per-host device-queue timing would desynchronize the collective
        dispatch sequence, so multihost break decisions remain un-lagged
        and root-broadcast (the same reason PR-2 made cadence decisions
        root-broadcast).  The dispatch overlap additionally needs the model
        to offer ``exit_future``.  The model's ``io_pipeline`` attribute is
        pointed at the run's pipeline so its callback IO (flow snapshots,
        diagnostics lines) shares the worker and lag queue — restored on
        exit."""
        io = self.io
        single = _single_process()
        self._sharded = bool(
            io.sharded_checkpoints
            if io.sharded_checkpoints is not None
            else not single
        ) and hasattr(self.pde, "snapshot_state_items")
        self._async_ckpt = bool(io.async_checkpoints and (single or self._sharded))
        self._overlap = bool(
            io.overlap_dispatch and single and hasattr(self.pde, "exit_future")
        )
        self._pending_commit = None
        self._io_snapshot_s = 0.0  # per-run, like the pipeline's own stats
        self._saved_pde_io = getattr(self.pde, "io_pipeline", None)
        if self._async_ckpt or self._overlap:
            self._io = IOPipeline(queue_depth=io.queue_depth, diag_lag=io.diag_lag)
            self.pde.io_pipeline = self._io

    @property
    def last_checkpoint(self) -> str | None:
        """Path of the newest verified/committed checkpoint (None before the
        first write).  Public embedding surface — workload drivers report it
        instead of reaching into runner internals."""
        return self._last_ckpt_path

    def drain_io(self) -> None:
        """Settle the IO pipeline: flush lagged diagnostics, commit any
        pending sharded write, wait for background writers and surface the
        first write failure.  Public embedding surface (workload drivers
        settle before sweeping spent checkpoints); :meth:`run` calls it at
        every normal completion."""
        self._drain_io()

    def _drain_io(self) -> None:
        """Flush lagged diagnostics + wait for background writes, surfacing
        the first write failure (the normal-completion settle point), then
        journal one ``io_overlap`` summary: payload bytes, main-thread
        staging seconds (device fetch), worker write seconds, submitter
        seconds lost to back-pressure, and the configured queue depth."""
        self._commit_pending()
        if self._io is not None:
            try:
                self._io.drain()
            except AsyncWriteError as exc:
                # normal-completion settle: a disk-full write failure is
                # contained (journaled with errno) — the run's RESULTS
                # are in memory/observables; only the checkpoint is lost
                if not self._is_enospc(exc):
                    raise
                self._degrade_checkpoints(exc, "drain")
            self._journal(
                {
                    "event": "io_overlap",
                    **self._io.stats(),
                    "snapshot_s": round(self._io_snapshot_s, 3),
                    "queue_depth": self.io.queue_depth,
                    "diag_lag": self.io.diag_lag,
                }
            )
        if self._metrics_dumper is not None:
            # run-end flush: headless campaigns always leave at least one
            # metrics.jsonl line next to the journal
            self._metrics_dumper.dump(step=self.step)

    def _teardown_io(self) -> None:
        """run() exit: settle the pipeline WITHOUT masking an in-flight
        exception (write failures were either surfaced at the last
        submit/drain or remain journaled as ``checkpoint_failed``), stop
        the worker, and give the model its previous pipeline back.

        A still-pending sharded commit is ABANDONED here, not committed:
        teardown may be running on an exception path where the collective
        barrier would wedge against hosts that already died.  The orphaned
        shard files are harmless (no manifest = not committed) and the
        rotation sweep collects them."""
        if self._pending_commit is not None:
            _, path, reason, _ = self._pending_commit
            self._pending_commit = None
            self._journal(
                {"event": "checkpoint_abandoned", "reason": reason, "path": path}
            )
        if self._io is not None:
            try:
                self._io.drain(raise_errors=False)
            finally:
                self._io.close()
        saved = getattr(self, "_saved_pde_io", None)
        if getattr(self.pde, "io_pipeline", None) is not saved:
            self.pde.io_pipeline = saved
        # give the model its previous journal hook back (adopted writers
        # belong to the embedding supervisor; owned ones close below)
        if getattr(self.pde, "journal_writer", None) is not self._saved_pde_journal:
            self.pde.journal_writer = self._saved_pde_journal
            if hasattr(self.pde, "model"):
                self.pde.model.journal_writer = self._saved_pde_journal
        self._stats_health_pending = None
        # release the journal handle (reopens lazily if journaled again);
        # an adopted writer belongs to the embedding supervisor — not ours
        if self._journal_writer is not None and self._journal_owned:
            self._journal_writer.close()

    def _setup_governor(self) -> None:
        """Arm the sentinels + build the dt governor (run() start, after a
        possible resume so an off-ladder restored dt can be re-aligned).
        The ladder anchors at the dt the runner was CONSTRUCTED with — the
        campaign's nominal dt — so a resumed backed-off run can climb back
        to it; ``dt_min`` (when set) floors the ladder too."""
        if self.stability is None or not hasattr(self.pde, "set_stability"):
            return
        if getattr(self.pde, "_stability", None) is not self.stability:
            self.pde.set_stability(self.stability)
        if getattr(self.pde, "_step_n_sent", None) is None:
            return  # GSPMD-fallback path: set_stability already warned
        cfg = self.stability
        if cfg.dt_min is None and self.dt_min > 0.0:
            cfg = dataclasses.replace(cfg, dt_min=min(self.dt_min, self._dt0))
        self.governor = StabilityGovernor(cfg, self._dt0)
        aligned = self.governor.align(float(self.pde.get_dt()), self.step)
        if aligned is not None:
            self.pde.set_dt(aligned)
            self._journal(
                {
                    "event": "dt_adjust",
                    "dt": aligned,
                    "rung": self.governor.rung,
                    "reason": "resumed dt quantized to the governor ladder",
                }
            )

    def _journal_health(self) -> None:
        """End-of-run physics health summary (governed runs)."""
        if self.governor is not None:
            self._journal({"event": "run_health", **self.governor.health.asdict()})

    def _summary(self, outcome: str) -> dict:
        return {
            "outcome": outcome,
            "step": self.step,
            "time": float(self.pde.get_time()),
            "dt": float(self.pde.get_dt()),
            "retries": self.attempt,
            "nu": self._nu(),
            "journal": self.journal_path,
            # tracked, not re-scanned: latest_checkpoint re-hashes every
            # file, which is pure waste for multi-GB snapshots
            "checkpoint": self._last_ckpt_path,
            # physics health telemetry (governed runs): dt trajectory,
            # sentinel extrema, pre-divergence catches / rollbacks avoided
            "health": (
                self.governor.health.asdict() if self.governor is not None else None
            ),
            # overlapped-IO telemetry: background writes, worker seconds,
            # submitter seconds lost to back-pressure
            "io": self._io.stats() if self._io is not None else None,
            # physics-stats health readout (stats-armed models): the
            # HEALTH_NAMES scalars — spectral tails, BL point counts,
            # budget residuals, Nu estimators, sample count
            "stats": (
                self.pde.stats_summary()
                if getattr(self.pde, "stats_armed", False)
                else None
            ),
        }
