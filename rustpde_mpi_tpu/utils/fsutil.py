"""Durability primitives shared by every module with an on-disk guarantee.

``os.replace``/``os.remove`` mutate the parent DIRECTORY: until the
directory inode itself is fsynced, the new dirent lives only in page
cache and a power loss can roll the rename back even though the file's
own bytes were fsynced.  One shared :func:`fsync_dir` (extracted from
serve/queue.py's ``_fsync_dir``) keeps the pattern in one place — lint
rule RPD004 requires every ``os.replace``/``os.rename`` in a
durability-critical module to be paired with it.

Import-light on purpose (os only): utils/checkpoint.py calls it from
inside the two-phase commit window and background writer threads.
"""

from __future__ import annotations

import os


def atomic_write_text(path: str, text: str, strict: bool = False) -> None:
    """The one durable small-file write: tmp sibling (pid-suffixed), write
    + flush + fsync, ``os.replace`` over the target, parent dirsync.  The
    queue's request files, the fleet's lease/heartbeat records and the
    continuation manifest all ride this exact sequence — extracted here so
    four durability-critical modules cannot drift apart (one copy quietly
    losing its dirsync is how the rename-rollback bug returns).
    ``strict`` propagates a failed dirsync (commit-marker writers must
    report such a write FAILED, not committed)."""
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write(text)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    fsync_dir(os.path.dirname(path) or ".", strict=strict)


def fsync_dir(path: str, strict: bool = False) -> None:
    """fsync a DIRECTORY so a just-renamed/removed dirent survives power
    loss.  Default is best-effort (filesystems that reject directory fsync
    — some network mounts — degrade quietly, the queue's historical
    behavior); ``strict=True`` propagates the OSError instead, for writers
    whose COMMIT semantics ride on the dirent being durable (the
    checkpoint two-phase protocol must report such a write failed, not
    committed)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        if strict:
            raise
        return
    try:
        os.fsync(fd)
    except OSError:
        if strict:
            raise
    finally:
        os.close(fd)
