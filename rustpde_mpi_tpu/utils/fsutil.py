"""Durability primitives shared by every module with an on-disk guarantee.

``os.replace``/``os.remove`` mutate the parent DIRECTORY: until the
directory inode itself is fsynced, the new dirent lives only in page
cache and a power loss can roll the rename back even though the file's
own bytes were fsynced.  One shared :func:`fsync_dir` (extracted from
serve/queue.py's ``_fsync_dir``) keeps the pattern in one place — lint
rule RPD004 requires every ``os.replace``/``os.rename`` in a
durability-critical module to be paired with it.

Import-light on purpose (os only): utils/checkpoint.py calls it from
inside the two-phase commit window and background writer threads.
"""

from __future__ import annotations

import os


def fsync_dir(path: str, strict: bool = False) -> None:
    """fsync a DIRECTORY so a just-renamed/removed dirent survives power
    loss.  Default is best-effort (filesystems that reject directory fsync
    — some network mounts — degrade quietly, the queue's historical
    behavior); ``strict=True`` propagates the OSError instead, for writers
    whose COMMIT semantics ride on the dirent being durable (the
    checkpoint two-phase protocol must report such a write failed, not
    committed)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        if strict:
            raise
        return
    try:
        os.fsync(fd)
    except OSError:
        if strict:
            raise
    finally:
        os.close(fd)
