"""Pallas TPU kernels: the implicit half of the Navier step as fused stages.

BENCH_r05 puts the flagship rbc2049_f64 run at ~2.6% MFU — the chip idles
because every stage of the implicit half of the step (the Helmholtz
velocity/temperature solves, the pressure Poisson solve, and the
synthesis/projection glue between them) round-trips HBM between ~4-8
separate GEMM dispatches per stage.  This module fuses each stage into ONE
``pl.pallas_call`` with the modal intermediates resident in VMEM:

    rhs assembly      sum_t  L_t @ x_t @ R_t^T      (stage-1 GEMMs, tiled)
    [+ BC-lift]       + const                        (host-precomputed)
    [modal solve]     * (1 / (lam0_i + lam1_j))      (fast-diag scaling)
    [modal backward]  B0 @ . @ B1^T                  (composite coefficients)
    [singular pin]    * mask                          (pressure zero mode)

The per-stage term lists are composed host-side (numpy f64) from the stable
``Base.axis_operator`` accessor plus the ``solver`` module's public modal
data (``hholtz_axis_solve_matrix`` / ``modal_data_split``) — no private
folding internals — so one generalized kernel covers all eight step stages:

* ``velx``/``vely``/``temp``/``scal`` — convection RHS + pressure-gradient +
  buoyancy/Coriolis terms with the ADI Helmholtz inverse folded into every
  term's axis matrices (solve == A0 @ rhs @ A1^T; the dense path's banded
  recurrences and the precomputed dense inverse solve the identical system).
* ``div`` — the divergence RHS (two gradient terms) in scratch-ortho space.
* ``poisson`` — fast-diagonalisation pressure solve (modal forward GEMM ->
  per-eigenvalue scaling -> modal backward GEMM) with the singular-mode pin
  folded as an output mask.  The same discrete system as solver.TensorSolver
  / the ``pallas_banded`` recurrence (tests/test_golden.py); the fast-diag
  scaling form is the MXU-native choice, and ``bench.py bandedsolve``
  records the recurrence-vs-GEMM crossover per PR.
* ``projx``/``projy`` — the pressure-gradient velocity correction
  (projection x gradient cross-space GEMMs), subtracted outside the kernel.

Layouts: confined sep Chebyshev, split-sep periodic, and complex periodic
(complex arrays convert to stacked ``[Re; Im]`` planes at the kernel
boundary, exactly the ``FusedConv`` convention).  Interpreter mode runs the
same kernels on CPU (tests/test_pallas_step.py + the PARITY.json
``pallas_step`` probe); natively on an attached TPU.  vmap/ensemble
batching rides the standard ``pallas_call`` batching rule.

Selection mirrors ``RUSTPDE_CONV_KERNEL``: ``RUSTPDE_STEP_KERNEL=dense|
pallas`` (default ``dense`` until the on-chip A/B — ``bench.py pallasconv``
grows a ``stepkernel`` leg recording ms/step, MFU, HBM-traffic estimate and
parity deltas).  VMEM budget note: each stage holds its whole-width
right-side operand ``R_t^T`` and the output block resident across grid
steps — comfortable through ~513^2 at f32; the 1025^2/2049^2 output-column
tiling rides the chip A/B round, same staging as FusedConv.

``RUSTPDE_F64_HYBRID`` convention: ``build_model_step`` keeps the solve
stages in full f64 (cast=None) — matching the dense path, whose hybrid cast
covers only the convection transforms while the implicit solves stay f64.
The ``cast`` parameter exists for direct A/B of an all-f32 solve chain.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import config

LANE = 128
SUBLANE = 8


def step_kernel_choice() -> str:
    """The ``RUSTPDE_STEP_KERNEL`` knob: ``"dense"`` (default — the unfused
    solver chain) or ``"pallas"`` (the fused stage kernels).  Read at model
    compile time, like ``conv_kernel_choice``."""
    return config.env_get("RUSTPDE_STEP_KERNEL", "dense")


def _ceil_to(x: int, m: int) -> int:
    return -(-int(x) // m) * m


class StageTerm(NamedTuple):
    """One ``L @ x @ R^T`` term of a fused stage, in storage layout.

    ``l`` may be None for single-term stages whose input is already in the
    stage-1 row space (the periodic Poisson forward: Fourier modes are
    already modal).  ``complex_in``: the input array is complex and converts
    to stacked ``[Re; Im]`` planes at the kernel boundary."""

    l: np.ndarray | None
    r: np.ndarray
    complex_in: bool


def _stage_kernel(*refs, nt, nj, ni, has_l, has_const, has_dinv, has_b1,
                  has_b0, has_mask):
    """Grid (i over stage-1 row tiles, j over contraction tiles; j
    innermost).  Stage 1 accumulates each term's ``L_t @ x_t`` into VMEM
    scratch; the j-final epilogue contracts with ``R_t^T``, sums the terms,
    applies const/modal-scaling/backward maps, and writes (or, with a modal
    backward ``B0``, accumulates over i) the output block."""
    from jax.experimental import pallas as pl

    pos = 0
    ls = refs[pos:pos + nt] if has_l else ()
    pos += nt if has_l else 0
    xs = refs[pos:pos + nt]
    pos += nt
    rts = refs[pos:pos + nt]
    pos += nt
    const = refs[pos] if has_const else None
    pos += 1 if has_const else 0
    dinv = refs[pos] if has_dinv else None
    pos += 1 if has_dinv else 0
    b1t = refs[pos] if has_b1 else None
    pos += 1 if has_b1 else 0
    b0 = refs[pos] if has_b0 else None
    pos += 1 if has_b0 else 0
    mask = refs[pos] if has_mask else None
    pos += 1 if has_mask else 0
    o = refs[pos]
    accs = refs[pos + 1:]

    i = pl.program_id(0)
    j = pl.program_id(1)
    acc_t = o.dtype
    prec = jax.lax.Precision.HIGHEST

    if has_l:
        for t in range(nt):
            part = jnp.dot(ls[t][...], xs[t][...], precision=prec,
                           preferred_element_type=acc_t)

            @pl.when(j == 0)
            def _init(acc=accs[t], part=part):
                acc[...] = part

            @pl.when(j > 0)
            def _accum(acc=accs[t], part=part):
                acc[...] = acc[...] + part

    @pl.when(j == nj - 1)
    def _epilogue():
        m = None
        for t in range(nt):
            src = accs[t][...] if has_l else xs[t][...]
            part = jnp.dot(src, rts[t][...], precision=prec,
                           preferred_element_type=acc_t)
            m = part if m is None else m + part
        if has_dinv:
            m = m * dinv[...]
        if has_b1:
            m = jnp.dot(m, b1t[...], precision=prec,
                        preferred_element_type=acc_t)
        if has_const:
            m = m + const[...]
        if has_b0:
            part = jnp.dot(b0[...], m, precision=prec,
                           preferred_element_type=acc_t)

            @pl.when(i == 0)
            def _first():
                o[...] = part

            @pl.when(i > 0)
            def _rest():
                o[...] = o[...] + part

            if has_mask:
                @pl.when(i == ni - 1)
                def _pin():
                    o[...] = o[...] * mask[...]
        else:
            if has_mask:
                m = m * mask[...]
            o[...] = m


class FusedStage:
    """One fused step stage: ``apply(*xs) == sum_t L_t @ xs[t] @ R_t^T
    [+ const] [-> modal scale -> backward] [* mask]`` in ONE Pallas kernel,
    the per-term matrices given in storage layout (conjugated with the
    spaces' sep/split permutations by the builder).

    ``modal=(dinv, b0, b1)``: the fast-diag solve stage — elementwise
    ``1/(lam0_i + lam1_j)`` scaling between the term contraction and the
    backward maps (either of ``b0``/``b1`` may be None for periodic axes).
    ``mask``: multiplicative output mask (the pressure singular-mode pin).
    ``cast`` mirrors the FusedConv convention (store matrices in that dtype,
    run the chain through it); ``interpret`` defaults to True off-TPU.
    ``reference()`` is the same chain unfused (plain XLA dots over the same
    padded constants) — the kernel-plumbing A/B; the model-level dense A/B
    lives in tests/test_pallas_step.py and the bench stepkernel leg."""

    def __init__(self, name, terms, complex_out, const=None, modal=None,
                 mask=None, cast=None, interpret: bool | None = None,
                 block_rows: int | None = None, block_k: int | None = None):
        self.terms = list(terms)
        nt = len(self.terms)
        if nt == 0:
            raise ValueError("a fused stage needs at least one term")
        self.complex_out = bool(complex_out)
        self.has_l = self.terms[0].l is not None
        if any((t.l is None) != (not self.has_l) for t in self.terms):
            raise ValueError("terms must uniformly carry or omit L matrices")
        if not self.has_l and nt != 1:
            raise ValueError("L-less stages are single-term only")

        dinv = b0 = b1 = None
        if modal is not None:
            dinv, b0, b1 = modal
        if const is not None and (modal is not None or b0 is not None):
            raise ValueError("const is a post-solve fold; modal stages "
                             "carry their lift in the rhs terms instead")

        # true (unpadded) dims
        self.q1 = int(self.terms[0].r.shape[0])
        if any(int(t.r.shape[0]) != self.q1 for t in self.terms):
            raise ValueError("stage terms must share the output column space")
        if self.has_l:
            self.r0 = int(self.terms[0].l.shape[0])
            if any(int(t.l.shape[0]) != self.r0 for t in self.terms):
                raise ValueError("stage terms must share the stage-1 row space")
            self._k0 = [int(t.l.shape[1]) for t in self.terms]
        else:
            self.r0 = int(dinv.shape[0]) if dinv is not None else None
            if self.r0 is None:
                raise ValueError("L-less stages need modal data to fix rows")
            self._k0 = [self.r0]
        self._k1 = [int(t.r.shape[1]) for t in self.terms]
        self.p0 = int(b0.shape[0]) if b0 is not None else self.r0
        self.p1 = int(b1.shape[0]) if b1 is not None else self.q1

        # padded dims + tiles (FusedConv sizing: row tiles from block_rows,
        # common contraction padded to the largest term, LANE-quantized)
        br = int(block_rows or config.env_get("RUSTPDE_PALLAS_CONV_BLOCK", 256))
        br = max(LANE, _ceil_to(br, LANE))
        self._r0p = _ceil_to(self.r0, br)
        self._bi = min(br, self._r0p)
        self._k0p = _ceil_to(max(self._k0), LANE)
        bj = int(block_k or config.env_get("RUSTPDE_PALLAS_CONV_BLOCK_K", 512))
        bj = max(LANE, (bj // LANE) * LANE)
        if self.has_l:
            while self._k0p % bj:
                bj -= LANE
        else:
            bj = self._k0p
        self._bj = bj
        self._k1p = [_ceil_to(k, LANE) for k in self._k1]
        self._q1p = _ceil_to(self.q1, LANE)
        self._p1p = _ceil_to(self.p1, LANE)
        self._p0p = _ceil_to(self.p0, SUBLANE) if b0 is not None else self._r0p

        self.name = name
        self.kernel_name = f"fused_step_{name}_{self.p0}x{self.p1}_t{nt}"
        self._cast = np.dtype(cast) if cast is not None else None
        dt = self._cast or config.real_dtype()
        from .folded import pad_dense

        with jax.ensure_compile_time_eval():

            def place(m, rows, cols):
                return jnp.asarray(pad_dense(np.asarray(m), rows, cols).astype(dt))

            self._ls = (
                [place(t.l, self._r0p, self._k0p) for t in self.terms]
                if self.has_l else None
            )
            self._rts = [
                place(t.r.T, k1p, self._q1p)
                for t, k1p in zip(self.terms, self._k1p)
            ]
            self._const = (
                place(const, self._r0p, self._q1p) if const is not None else None
            )
            # modal denominators are built at TRUE shape, then zero-padded:
            # the pad region multiplies zero-padded data, so exact zeros
            # (not 1/0 = inf) keep the padding mathematically inert
            self._dinv = (
                place(dinv, self._r0p, self._q1p) if dinv is not None else None
            )
            self._b1t = place(b1.T, self._q1p, self._p1p) if b1 is not None else None
            self._b0 = place(b0, self._p0p, self._r0p) if b0 is not None else None
            mrows = self._p0p if b0 is not None else self._r0p
            self._mask = place(mask, mrows, self._p1p) if mask is not None else None
        if interpret is None:
            interpret = jax.devices()[0].platform not in ("tpu", "axon")
        self.interpret = bool(interpret)

    # -- flop / traffic accounting (profiling satellites) ---------------------

    @property
    def flops(self) -> float:
        """Analytic MXU flops of ONE kernel invocation at the UNPADDED
        shapes (useful model flops, comparable to the dense path's jaxpr dot
        count) — registered with utils/profiling.register_pallas_flops.
        Tile padding shows up as *lower* MFU, the honest A/B signal."""
        f = 0.0
        for k0, k1 in zip(self._k0, self._k1):
            if self.has_l:
                f += 2.0 * self.r0 * k0 * k1  # stage-1  L_t @ x_t
            f += 2.0 * self.r0 * k1 * self.q1  # epilogue (.) @ R_t^T
        if self._b1t is not None:
            f += 2.0 * self.r0 * self.q1 * self.p1
        if self._b0 is not None:
            f += 2.0 * self.p0 * self.r0 * self.p1
        return f

    @property
    def hbm_bytes(self) -> float:
        """HBM bytes ONE fused invocation moves: every operand (padded
        operator constants + padded inputs) read once, the output written
        once — the megakernel side of the step traffic estimate."""
        item = np.dtype(self._cast or config.real_dtype()).itemsize
        n = sum(m.size for m in (self._ls or []))
        n += sum(m.size for m in self._rts)
        for extra in (self._const, self._dinv, self._b1t, self._b0, self._mask):
            if extra is not None:
                n += extra.size
        rows = self._k0p if self.has_l else self._r0p
        n += sum(rows * k1p for k1p in self._k1p)  # inputs
        if self._b0 is not None:
            n += self._p0p * self._p1p
        else:
            n += self._r0p * self._p1p
        return float(n) * item

    @property
    def dense_hbm_bytes(self) -> float:
        """Analytic HBM bytes of the UNFUSED chain computing the same stage:
        each per-axis apply / elementwise op reads and writes a full array
        (the intermediates this kernel keeps in VMEM), plus the same
        operator constants read once.  Coarse by design — a dispatch-count
        model, not a cache simulation — but it is the dense side of the
        BASELINE.md traffic table and makes the fusion win quantitative."""
        item = np.dtype(self._cast or config.real_dtype()).itemsize
        s = float(self.r0 * self.q1) * item  # working array size
        ops = 0
        for _ in self.terms:
            ops += 2 if self.has_l else 1  # one apply per side
        ops += len(self.terms) - 1  # rhs adds
        if self._const is not None:
            ops += 1
        if self._dinv is not None:
            ops += 1  # elementwise divide
        if self._b1t is not None:
            ops += 1
        if self._b0 is not None:
            ops += 1
        if self._mask is not None:
            ops += 1
        mats = sum(float(np.prod(t.l.shape)) for t in self.terms if t.l is not None)
        mats += sum(float(np.prod(t.r.shape)) for t in self.terms)
        return 2.0 * ops * s + mats * item

    # -- the fused stage ------------------------------------------------------

    def _pallas_call(self):
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        gi = self._r0p // self._bi
        gj = (self._k0p // self._bj) if self.has_l else 1
        bi, bj = self._bi, self._bj
        in_specs = []
        if self.has_l:
            in_specs += [
                pl.BlockSpec((bi, bj), lambda i, j: (i, j))
                for _ in self.terms
            ]
            in_specs += [
                pl.BlockSpec((bj, k1p), lambda i, j: (j, 0))
                for k1p in self._k1p
            ]
        else:
            in_specs += [
                pl.BlockSpec((bi, k1p), lambda i, j: (i, 0))
                for k1p in self._k1p
            ]
        in_specs += [
            pl.BlockSpec((k1p, self._q1p), lambda i, j: (0, 0))
            for k1p in self._k1p
        ]
        if self._const is not None:
            in_specs.append(pl.BlockSpec((bi, self._q1p), lambda i, j: (i, 0)))
        if self._dinv is not None:
            in_specs.append(pl.BlockSpec((bi, self._q1p), lambda i, j: (i, 0)))
        if self._b1t is not None:
            in_specs.append(pl.BlockSpec((self._q1p, self._p1p), lambda i, j: (0, 0)))
        has_b0 = self._b0 is not None
        if has_b0:
            in_specs.append(pl.BlockSpec((self._p0p, bi), lambda i, j: (0, i)))
            out_spec = pl.BlockSpec((self._p0p, self._p1p), lambda i, j: (0, 0))
            out_shape = (self._p0p, self._p1p)
        else:
            out_spec = pl.BlockSpec((bi, self._p1p), lambda i, j: (i, 0))
            out_shape = (self._r0p, self._p1p)
        if self._mask is not None:
            mrows = self._p0p if has_b0 else bi
            midx = (lambda i, j: (0, 0)) if has_b0 else (lambda i, j: (i, 0))
            in_specs.append(pl.BlockSpec((mrows, self._p1p), midx))
        dt = self._rts[0].dtype
        scratch = (
            [pltpu.VMEM((bi, k1p), dt) for k1p in self._k1p]
            if self.has_l else []
        )
        return pl.pallas_call(
            functools.partial(
                _stage_kernel,
                nt=len(self.terms), nj=gj, ni=gi,
                has_l=self.has_l,
                has_const=self._const is not None,
                has_dinv=self._dinv is not None,
                has_b1=self._b1t is not None,
                has_b0=has_b0,
                has_mask=self._mask is not None,
            ),
            grid=(gi, gj),
            in_specs=in_specs,
            out_specs=out_spec,
            out_shape=jax.ShapeDtypeStruct(out_shape, dt),
            scratch_shapes=scratch,
            interpret=self.interpret,
            name=self.kernel_name,
        )

    def _prep(self, x, t):
        if self.terms[t].complex_in:
            x = jnp.concatenate([x.real, x.imag], axis=0)
        dt = self._rts[0].dtype
        rows = self._k0p if self.has_l else self._r0p
        return jnp.pad(
            x.astype(dt),
            ((0, rows - x.shape[0]), (0, self._k1p[t] - x.shape[1])),
        )

    def _finish(self, out, out_dtype):
        out = out[: self.p0, : self.p1]
        if self.complex_out:
            mc = self.p0 // 2
            rdt = np.zeros(0, dtype=out_dtype).real.dtype
            return (out[:mc].astype(rdt) + 1j * out[mc:].astype(rdt)).astype(out_dtype)
        return out.astype(out_dtype)

    def apply(self, *xs):
        """The fused stage; output in the stage's composite/ortho storage
        layout — drop-in for the dense chain's result."""
        if len(xs) != len(self.terms):
            raise ValueError(
                f"stage {self.name!r} takes {len(self.terms)} inputs, got {len(xs)}"
            )
        out_dtype = xs[0].dtype
        args = [self._prep(x, t) for t, x in enumerate(xs)]
        if self.has_l:
            args = self._ls + args
        args += self._rts
        for extra in (self._const, self._dinv, self._b1t, self._b0, self._mask):
            if extra is not None:
                args.append(extra)
        return self._finish(self._pallas_call()(*args), out_dtype)

    def reference(self, *xs):
        """The same chain as plain unfused XLA dots over the same padded
        constants — the kernel-plumbing A/B denominator (the model-level
        dense A/B compares whole steps instead)."""
        out_dtype = xs[0].dtype
        prec = jax.lax.Precision.HIGHEST
        m = None
        for t, x in enumerate(xs):
            y = self._prep(x, t)
            if self.has_l:
                y = jnp.dot(self._ls[t], y, precision=prec)
            y = jnp.dot(y, self._rts[t], precision=prec)
            m = y if m is None else m + y
        if self._dinv is not None:
            m = m * self._dinv
        if self._b1t is not None:
            m = jnp.dot(m, self._b1t, precision=prec)
        if self._const is not None:
            m = m + self._const
        if self._b0 is not None:
            m = jnp.dot(self._b0, m, precision=prec)
        if self._mask is not None:
            m = m * self._mask
        return self._finish(m, out_dtype)


# -- model builders -----------------------------------------------------------


def _storage(mat, sep_in: bool, sep_out: bool) -> np.ndarray:
    """Conjugate a natural/split-form axis matrix into storage layout (the
    per-axis parity permutations of sep spaces; identity otherwise)."""
    from .folded import dense_operator

    return dense_operator(np.asarray(mat, dtype=np.float64),
                          sep_in=sep_in, sep_out=sep_out)


def _nat(space, axis: int, key):
    """Natural-order (split-form for periodic) per-axis operator matrix."""
    return space.bases[axis].axis_operator(key, sep=False).matrix


def _stack_host(arr) -> np.ndarray:
    a = np.asarray(arr)
    if np.iscomplexobj(a):
        a = np.concatenate([a.real, a.imag], axis=0)
    return a


def build_model_step(model, interpret: bool | None = None) -> dict:
    """Fused stage kernels for a Navier2D model's implicit half, keyed by
    stage tag: ``velx``/``vely`` (inputs: state field, pres, [temp,] conv
    output[, cross-velocity when Coriolis is active]), ``temp``/``scal``
    (state field, conv output), ``div`` (velx_n, vely_n), ``poisson``
    (div), ``projx``/``projy`` (pseu_n).  Registers each kernel's analytic
    flops with utils/profiling.  Raises on layouts the fused step does not
    cover (an active mesh routes around this builder)."""
    from .. import solver as slv
    from ..utils import profiling

    sp_u, sp_t = model.velx_space, model.temp_space
    sp_p, sp_q, sp_f = model.pres_space, model.pseu_space, model.field_space
    spaces = (sp_u, sp_t, sp_p, sp_q, sp_f)
    sep = sp_u.sep
    if any(s.sep != sep for s in spaces):
        raise ValueError("fused step stages need uniform sep flags across spaces")
    cplx = sp_u.spectral_is_complex
    if any(s.spectral_is_complex != cplx for s in spaces):
        raise ValueError("fused step stages need a uniform complex flag")

    dt = model.dt
    nu, ka = model.params["nu"], model.params["ka"]
    scale = model.scale
    sx2, sy2 = scale[0] ** 2, scale[1] ** 2
    coriolis = model._coriolis()
    has_scal = model._scalar_active()

    # Helmholtz dense-equivalent axis factors (solve == A0 @ rhs @ A1^T)
    A0u = slv.hholtz_axis_solve_matrix(sp_u, 0, dt * nu / sx2)
    A1u = slv.hholtz_axis_solve_matrix(sp_u, 1, dt * nu / sy2)
    A0t = slv.hholtz_axis_solve_matrix(sp_t, 0, dt * ka / sx2)
    A1t = slv.hholtz_axis_solve_matrix(sp_t, 1, dt * ka / sy2)

    st0u, st1u = _nat(sp_u, 0, "stencil"), _nat(sp_u, 1, "stencil")
    st0p, st1p = _nat(sp_p, 0, "stencil"), _nat(sp_p, 1, "stencil")
    st0t, st1t = _nat(sp_t, 0, "stencil"), _nat(sp_t, 1, "stencil")
    st0q, st1q = _nat(sp_q, 0, "stencil"), _nat(sp_q, 1, "stencil")
    g1p0, g1p1 = _nat(sp_p, 0, ("grad", 1)), _nat(sp_p, 1, ("grad", 1))
    g1u0, g1u1 = _nat(sp_u, 0, ("grad", 1)), _nat(sp_u, 1, ("grad", 1))
    g1q0, g1q1 = _nat(sp_q, 0, ("grad", 1)), _nat(sp_q, 1, ("grad", 1))
    p0u, p1u = _nat(sp_u, 0, "proj"), _nat(sp_u, 1, "proj")

    def term(lnat, rnat, space_in, sep_out):
        return StageTerm(
            _storage(lnat, space_in.sep[0], sep_out[0]),
            _storage(rnat, space_in.sep[1], sep_out[1]),
            space_in.spectral_is_complex,
        )

    def lift_const(L, R, arr, factor):
        """Post-solve BC-lift fold: conjugate the solve factors from the
        lift field's (field-space) storage flags into the output space's
        and bake the product (``A (rhs + c*lift) == A rhs + c * A lift A^T``)."""
        if arr is None:
            return None
        Lc = _storage(L, sp_f.sep[0], sep[0])
        Rc = _storage(R, sp_f.sep[1], sep[1])
        return factor * (Lc @ _stack_host(arr) @ Rc.T)

    cast = None  # solves stay f64 under RUSTPDE_F64_HYBRID (see module doc)
    kw = dict(cast=cast, interpret=interpret)
    nx, ny = model.nx, model.ny

    # velocity stages: state + pressure-gradient + convection (+ buoyancy,
    # +/- Coriolis cross-coupling); the Helmholtz inverse folded into L/R
    terms_vx = [
        term(A0u @ st0u, A1u @ st1u, sp_u, sep),
        term((-dt / scale[0]) * (A0u @ g1p0), A1u @ st1p, sp_p, sep),
        term(-dt * A0u, A1u, sp_f, sep),
    ]
    terms_vy = [
        term(A0u @ st0u, A1u @ st1u, sp_u, sep),
        term((-dt / scale[1]) * (A0u @ st0p), A1u @ g1p1, sp_p, sep),
        term(dt * (A0u @ st0t), A1u @ st1t, sp_t, sep),
        term(-dt * A0u, A1u, sp_f, sep),
    ]
    if coriolis:
        terms_vx.append(term(dt * coriolis * (A0u @ st0u), A1u @ st1u, sp_u, sep))
        terms_vy.append(term(-dt * coriolis * (A0u @ st0u), A1u @ st1u, sp_u, sep))
    # buoyancy lift: A (rhs + dt*that) == A rhs + dt * A @ tb @ A^T
    const_vy = lift_const(A0u, A1u, model.tempbc_ortho, dt)

    stages = {
        "velx": FusedStage(f"velx_{nx}x{ny}", terms_vx, cplx, **kw),
        "vely": FusedStage(f"vely_{nx}x{ny}", terms_vy, cplx,
                           const=const_vy, **kw),
    }

    # temperature / passive scalar: state + convection + diffusion lift
    terms_t = [
        term(A0t @ st0t, A1t @ st1t, sp_t, sep),
        term(-dt * A0t, A1t, sp_f, sep),
    ]
    const_t = lift_const(A0t, A1t, model._tempbc_diff, 1.0)
    stages["temp"] = FusedStage(f"temp_{nx}x{ny}", terms_t, cplx,
                                const=const_t, **kw)
    if has_scal:
        kc = model._scalar_kappa()
        A0c = slv.hholtz_axis_solve_matrix(sp_t, 0, dt * kc / sx2)
        A1c = slv.hholtz_axis_solve_matrix(sp_t, 1, dt * kc / sy2)
        terms_c = [
            term(A0c @ st0t, A1c @ st1t, sp_t, sep),
            term(-dt * A0c, A1c, sp_f, sep),
        ]
        const_c = lift_const(A0c, A1c, model._tempbc_diff, kc / ka)
        stages["scal"] = FusedStage(f"scal_{nx}x{ny}", terms_c, cplx,
                                    const=const_c, **kw)

    # divergence RHS in scratch-ortho space (the projection solve input and
    # the pressure-update/div-norm array)
    terms_div = [
        term(g1u0 / scale[0], st1u, sp_u, sep),
        term(st0u, g1u1 / scale[1], sp_u, sep),
    ]
    stages["div"] = FusedStage(f"div_{nx}x{ny}", terms_div, cplx, **kw)

    # pressure Poisson: fast-diag modal solve with the singular pin folded
    # as an output mask (the step still calls pin_zero_mode — idempotent)
    from .folded import parity_perm

    lam0, f0, b0m = slv.modal_data_split(sp_q, 0, 1.0 / sx2, 1.0)
    lam1, f1, b1m = slv.modal_data_split(sp_q, 1, 1.0 / sy2, 1.0)
    s0 = sep[0] and f0 is not None
    s1 = sep[1] and f1 is not None
    if s0:
        lam0 = lam0[parity_perm(len(lam0))]
    if s1:
        lam1 = lam1[parity_perm(len(lam1))]
    if abs(lam0[0]) < 1e-10:
        # singular-mode nudge, exactly solver.FastDiag's fix_singular
        lam0 = lam0 - 1e-10
    dinv = 1.0 / (lam0[:, None] + lam1[None, :])
    pin = np.ones((len(lam0), b1m.shape[0] if b1m is not None else len(lam1)))
    pin[0, 0] = 0.0
    if sp_q.bases[0].kind.is_periodic:
        pin[len(lam0) // 2, 0] = 0.0  # the Im row of the k=0 mode
    if f0 is not None:
        tpo = StageTerm(_storage(f0, sep[0], s0), _storage(f1, sep[1], s1), cplx)
    else:
        tpo = StageTerm(None, _storage(f1, sep[1], s1), cplx)
    modal = (
        dinv,
        _storage(b0m, s0, sep[0]) if b0m is not None else None,
        _storage(b1m, s1, sep[1]) if b1m is not None else None,
    )
    stages["poisson"] = FusedStage(f"poisson_{nx}x{ny}", [tpo], cplx,
                                   modal=modal, mask=pin, **kw)

    # pressure-gradient projection (subtracted from the velocities outside)
    stages["projx"] = FusedStage(
        f"projx_{nx}x{ny}",
        [term((p0u @ g1q0) / scale[0], p1u @ st1q, sp_q, sep)], cplx, **kw)
    stages["projy"] = FusedStage(
        f"projy_{nx}x{ny}",
        [term(p0u @ st0q, (p1u @ g1q1) / scale[1], sp_q, sep)], cplx, **kw)

    for st in stages.values():
        profiling.register_pallas_flops(st.kernel_name, st.flops)
    return stages


def step_traffic_estimate(model) -> dict:
    """Analytic HBM bytes/step of the implicit (solve) half: the unfused
    dense chain vs the fused stage kernels — the quantity the megakernel
    exists to shrink (BASELINE.md traffic table; recorded by the bench
    ``stepkernel`` leg).  Uses the model's live fused stages when present,
    else builds a throwaway set."""
    stages = getattr(model, "_step_impl", None)
    if stages is None:
        stages = build_model_step(model, interpret=True)
    dense = sum(s.dense_hbm_bytes for s in stages.values())
    fused = sum(s.hbm_bytes for s in stages.values())
    return {
        "dense_bytes_per_step": dense,
        "pallas_bytes_per_step": fused,
        "traffic_ratio": dense / fused if fused else float("nan"),
    }
