"""Pallas TPU kernel: batched banded forward/backward substitution.

The SURVEY (S7 "hard parts") flags the banded solve as the make-or-break TPU
kernel: the reference's rayon lane-parallel Thomas sweeps
(/root/reference/src/solver/fdma.rs:177-191) have no free parallel axis on a
TPU core except the 128-wide vector lanes.  This kernel keeps the transverse
lanes on the VPU lane dimension and marches the banded LU recurrence over
rows in VMEM:

    forward:   y_i = b_i - sum_{d=1..p} L[i, i-d] * y_{i-d}
    backward:  x_i = (y_i - sum_{d=1..q} U[i, i+d] * x_{i+d}) / U[i, i]

**Measured role** (see bench_banded_paths / BASELINE.md): on v5e the f32
model path solves these systems faster through the precomputed dense-inverse
GEMM (ops/banded.DenseSolver) — the MXU at ~0.4 MFU beats a sequential
n-step VMEM recurrence despite doing O(n/(p+q)) times more flops.  The
Pallas path wins where matmuls are weak: emulated f64, and very large n
where the O(n^2) dense-inverse memory becomes the constraint.  Solver
selection (solver.default_method) stays measurement-driven; this kernel is
the validated alternative, exact to the banded scan path on both backends
(tests/test_pallas_banded.py runs it in interpreter mode on CPU and natively
when a TPU is attached).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

LANE = 128


def _kernel(low_ref, upp_ref, b_ref, o_ref, *, p: int, q: int, n: int):
    # factor refs live in SMEM — the recurrence coefficients are true
    # scalars with a dynamically-indexed row, which VMEM vector loads
    # cannot express
    from jax.experimental import pallas as pl

    # forward substitution into o_ref.  Out-of-range neighbor reads are
    # clamped and masked with a select (not a multiply: the clamped row is
    # uninitialized memory, and 0 * NaN would poison the result)
    def fwd(i, carry):
        acc = b_ref[pl.ds(i, 1), :]
        for d in range(1, p + 1):
            prev = o_ref[pl.ds(jnp.maximum(i - d, 0), 1), :]
            coef = (low_ref[d - 1, i]).astype(acc.dtype)
            acc = acc - jnp.where(i >= d, coef * prev, 0.0)
        o_ref[pl.ds(i, 1), :] = acc
        return carry

    jax.lax.fori_loop(0, n, fwd, 0)

    # backward substitution in place
    def bwd(k, carry):
        i = n - 1 - k
        acc = o_ref[pl.ds(i, 1), :]
        for d in range(1, q + 1):
            nxt = o_ref[pl.ds(jnp.minimum(i + d, n - 1), 1), :]
            coef = (upp_ref[d, i]).astype(acc.dtype)
            acc = acc - jnp.where(i + d <= n - 1, coef * nxt, 0.0)
        o_ref[pl.ds(i, 1), :] = acc / upp_ref[0, i]
        return carry

    jax.lax.fori_loop(0, n, bwd, 0)


@functools.partial(jax.jit, static_argnames=("p", "q", "interpret"))
def banded_solve_pallas(lower, upper, b, p: int, q: int, interpret: bool = False):
    """Solve the banded LU system along axis 0 of ``b`` (n, lanes).

    ``lower`` (p, n) / ``upper`` (q+1, n) are the factors of
    ops.banded.banded_lu_factor (single factor set, broadcast over lanes).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, lanes = b.shape
    pad = (-lanes) % LANE
    bb = jnp.pad(b, ((0, 0), (0, pad))) if pad else b
    grid = (bb.shape[1] // LANE,)
    out = pl.pallas_call(
        functools.partial(_kernel, p=p, q=q, n=n),
        grid=grid,
        in_specs=[
            pl.BlockSpec((p, n), lambda j: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((q + 1, n), lambda j: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((n, LANE), lambda j: (0, j)),
        ],
        out_specs=pl.BlockSpec((n, LANE), lambda j: (0, j)),
        out_shape=jax.ShapeDtypeStruct(bb.shape, bb.dtype),
        interpret=interpret,
    )(lower, upper, bb)
    return out[:, :lanes] if pad else out


class PallasBandedSolver:
    """Drop-in ``solve(b, axis)`` wrapper around the Pallas kernel (single
    factor set; the ADI-solver use case)."""

    def __init__(self, dense: np.ndarray, p: int, q: int, dtype=None,
                 interpret: bool | None = None):
        from .banded import banded_lu_factor

        if np.asarray(dense).ndim != 2:
            raise ValueError("PallasBandedSolver takes a single (n, n) matrix")
        lower, upper = banded_lu_factor(dense, p, q)
        dt = dtype or jnp.zeros(0).dtype
        self.p, self.q = p, q
        self.n = dense.shape[-1]
        self.lower = jnp.asarray(lower, dtype=dt)
        self.upper = jnp.asarray(upper, dtype=dt)
        if interpret is None:
            interpret = jax.devices()[0].platform not in ("tpu", "axon")
        self.interpret = interpret

    def solve(self, b, axis: int):
        moved = jnp.moveaxis(b, axis, 0)
        shape = moved.shape
        flat = moved.reshape(shape[0], -1)
        out = banded_solve_pallas(
            self.lower, self.upper, flat, self.p, self.q, interpret=self.interpret
        )
        return jnp.moveaxis(out.reshape(shape), 0, axis)


def bench_banded_paths(n: int = 1023, lanes: int = 1025, repeats: int = 50):
    """Microbenchmark: Pallas recurrence vs dense-inverse GEMM vs lax.scan
    on this backend at the ADI solver's real shapes.  Returns seconds per
    solve for each path — the measurement behind solver.default_method."""
    import time

    from .banded import BandedSolver, DenseSolver

    rng = np.random.default_rng(0)
    p, q = 2, 4
    dense = np.eye(n) * 4.0
    for d, off in ((-2, 0.5), (2, 0.7), (4, 0.3)):
        dense += np.diag(np.full(n - abs(d), off), k=d)
    b = jnp.asarray(rng.standard_normal((n, lanes)), dtype=jnp.zeros(0).dtype)

    solvers = {
        "pallas": PallasBandedSolver(dense, p, q),
        "dense_gemm": DenseSolver(dense),
        "banded_scan": BandedSolver(dense, p, q),
    }
    results = {}
    for name, s in solvers.items():
        out = s.solve(b, 0)
        np.asarray(out[:1, :1])  # warm + sync
        t0 = time.perf_counter()
        for _ in range(repeats):
            out = s.solve(b, 0)
        np.asarray(out[:1, :1])
        results[name] = (time.perf_counter() - t0) / repeats
    return results
