"""Parity-folded matrix application: two half-size GEMMs instead of one.

Every Chebyshev operator in this framework inherits the even/odd symmetry of
the basis — the same structure the reference exploits with its stride-2
banded solvers (/root/reference/src/solver/tdma.rs:49-82, offsets (-2,0,2)).
On TPU the equivalent trick halves the MXU flops of the dense transforms:

* physical<->spectral matrices satisfy a reflection symmetry
  (``M[j, n-1-i] = (-1)^j M[j, i]`` for analysis-type, transposed for
  synthesis-type), so folding the physical side into symmetric/antisymmetric
  halves turns one (r x n) GEMM into an (r_e x ~n/2) + (r_o x ~n/2) pair;
* spectral->spectral operators (derivative matrices, implicit-solve
  inverses) are checkerboard-sparse (``M[j, k] = 0`` unless ``j + k + s``
  is even), foldable the same way by index parity.

Detection is numerical at build time; matrices without the structure (e.g.
the mixed Dirichlet-Neumann base's operators) fall back to the plain GEMM.
Folded and plain paths agree to machine epsilon (tests/test_folded.py) —
each output element is the same reduction, reassociated only across the
explicitly-zero half of the terms.

Enable/disable with RUSTPDE_FOLDED (default on).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

# Structure detection tolerance.  Every foldable matrix in this framework is
# built with its symmetry *exact* (mirror-constructed transform matrices,
# parity-blocked eigendecompositions, analytically banded operators), so the
# tolerance only needs to absorb true floating-point zeros that are written
# as ~1-ulp garbage (e.g. sin(pi*k) at a Nyquist column).  At 1e-11 a
# near-symmetric matrix could be folded and silently perturbed; 1e-14 keeps
# the folded/plain agreement at genuine machine epsilon.
_ATOL = 1e-14
_CIRC_MIN_DIM = 256  # circular folds engage only for large transforms
_MAX_BAND_OFFSETS = 8  # banded shift-apply engages up to this many diagonals


def folding_enabled() -> bool:
    return os.environ.get("RUSTPDE_FOLDED", "1") != "0"


def _move(a, axis):
    return jnp.moveaxis(a, axis, 0)


def _unmove(a, axis):
    return jnp.moveaxis(a, 0, axis)


# even/odd row interleave shared with the cumsum-derivative kernel
from .transforms import _interleave0 as _interleave  # noqa: E402


class _BandedApply:
    """Matrix with few nonzero diagonals applied as diagonal-scaled shifted
    adds: ``out[i] = sum_d w_d[i] * x[i+d]`` — O(#offsets * n) per lane
    instead of the O(n^2) GEMM.  This is how the exactly-banded operator
    family (stencils S, the B2 quasi-inverse preconditioner, restricted
    eyes) should hit the TPU: a handful of fused VPU multiply-adds streaming
    HBM once, leaving the MXU to the genuinely dense work.  (The reference
    gets the same effect from explicit banded storage in its Tdma/Fdma
    kernels, /root/reference/src/solver/tdma.rs.)"""

    kind = "banded"

    def __init__(self, mat: np.ndarray, offsets: np.ndarray):
        r, c = mat.shape
        self.r, self.c = r, c
        self.offsets = [int(d) for d in offsets]
        if self.offsets:
            ws = np.zeros((len(self.offsets), r))
            for t, d in enumerate(self.offsets):
                i0, i1 = max(0, -d), min(r, c - d)
                idx = np.arange(i0, i1)
                ws[t, i0:i1] = mat[idx, idx + d]
            self.weights = ws
            self.flops_factor = len(self.offsets) / c
        else:  # structurally zero matrix
            self.weights = np.zeros((0, r))
            self.flops_factor = 0.0

    def device_parts(self, to_dev):
        return (to_dev(self.weights),)

    def apply(self, dev, a, axis: int):
        (w,) = dev
        x = _move(a, axis)
        r = self.r
        batch = x.shape[1:]
        if not self.offsets:
            return _unmove(jnp.zeros((r,) + batch, dtype=x.dtype), axis)
        lo = max(0, -min(self.offsets))
        hi = max(0, max(self.offsets) + r - self.c)
        xp = jnp.pad(x, [(lo, hi)] + [(0, 0)] * len(batch))
        bshape = (r,) + (1,) * len(batch)
        out = None
        for t, d in enumerate(self.offsets):
            term = w[t].reshape(bshape) * jax.lax.slice_in_dim(xp, lo + d, lo + d + r, axis=0)
            out = term if out is None else out + term
        return _unmove(out, axis)


class _Plain:
    kind = "plain"

    def __init__(self, mat: np.ndarray):
        self.mat = mat
        self.flops_factor = 1.0

    def apply(self, dev, a, axis: int):
        from .transforms import apply_matrix

        (m,) = dev
        return apply_matrix(m, a, axis)

    def device_parts(self, to_dev):
        return (to_dev(self.mat),)


class _AnalysisFold:
    """M[j, n-1-i] = (-1)^j M[j, i]: fold the (physical) input side."""

    kind = "analysis"

    def __init__(self, mat: np.ndarray):
        r, n = mat.shape
        h = n // 2
        self.n = n
        self.h = h
        even = mat[0::2, :]
        odd = mat[1::2, :]
        m_e = even[:, :h]
        if n % 2 == 1:
            m_e = np.concatenate([m_e, even[:, h : h + 1]], axis=1)
        self.m_e = m_e  # (r_e, h [+1])
        self.m_o = odd[:, :h]  # (r_o, h)
        self.r = r
        self.flops_factor = 0.5

    def device_parts(self, to_dev):
        return (to_dev(self.m_e), to_dev(self.m_o))

    def apply(self, dev, a, axis: int):
        m_e, m_o = dev
        x = _move(a, axis)
        h, n = self.h, self.n
        xr = x[::-1]
        u = x[:h] + xr[:h]
        v = x[:h] - xr[:h]
        if n % 2 == 1:
            u = jnp.concatenate([u, x[h : h + 1]], axis=0)
        y_e = jnp.tensordot(m_e, u, axes=([1], [0]))
        y_o = jnp.tensordot(m_o, v, axes=([1], [0]))
        return _unmove(_interleave(y_e, y_o, self.r), axis)


class _SynthesisFold:
    """M[n-1-i, k] = (-1)^k M[i, k]: fold the (physical) output side."""

    kind = "synthesis"

    def __init__(self, mat: np.ndarray):
        n, c = mat.shape
        ceil = (n + 1) // 2
        self.n = n
        self.ceil = ceil
        self.m_e = mat[:ceil, 0::2]  # couples even spectral modes
        self.m_o = mat[:ceil, 1::2]
        self.flops_factor = 0.5

    def device_parts(self, to_dev):
        return (to_dev(self.m_e), to_dev(self.m_o))

    def apply(self, dev, a, axis: int):
        m_e, m_o = dev
        x = _move(a, axis)
        A = jnp.tensordot(m_e, x[0::2], axes=([1], [0]))
        B = jnp.tensordot(m_o, x[1::2], axes=([1], [0]))
        top = A + B
        floor = self.n // 2
        bottom = (A - B)[:floor][::-1]
        return _unmove(jnp.concatenate([top, bottom], axis=0), axis)


class _CheckerFold:
    """M[j, k] = 0 unless (j + k + shift) even: fold both spectral sides."""

    kind = "checker"

    def __init__(self, mat: np.ndarray, shift: int):
        r, c = mat.shape
        self.r = r
        self.shift = shift
        # output row j couples inputs of parity (j + shift) % 2
        self.m_e = mat[0::2, shift % 2 :: 2]
        self.m_o = mat[1::2, (1 + shift) % 2 :: 2]
        self.flops_factor = 0.5

    def device_parts(self, to_dev):
        return (to_dev(self.m_e), to_dev(self.m_o))

    def apply(self, dev, a, axis: int):
        m_e, m_o = dev
        x = _move(a, axis)
        s = self.shift % 2
        y_e = jnp.tensordot(m_e, x[s::2], axes=([1], [0]))
        y_o = jnp.tensordot(m_o, x[(1 + s) % 2 :: 2], axes=([1], [0]))
        return _unmove(_interleave(y_e, y_o, self.r), axis)


def _detect(mat: np.ndarray):
    if not folding_enabled():
        return _Plain(mat)
    if np.iscomplexobj(mat) or mat.ndim != 2 or min(mat.shape) < 4:
        return _Plain(mat)
    r, c = mat.shape
    scale = np.abs(mat).max() or 1.0
    # small-bandwidth matrices: shifted adds beat any GEMM fold.  Cheap
    # nnz pre-check first so dense matrices skip the O(nnz) index
    # materialization (np.nonzero on a 2049^2 transform is ~67 MB transient)
    mask = np.abs(mat) > _ATOL * scale
    if np.count_nonzero(mask) <= _MAX_BAND_OFFSETS * max(r, c):
        rows, cols = np.nonzero(mask)
        offs = np.unique(cols - rows)
        if offs.size <= _MAX_BAND_OFFSETS and offs.size * 4 <= c:
            return _BandedApply(mat, offs)
    # synthesis-type first: pure transform matrices of even N carry BOTH
    # reflection structures (quarter-constructed, ops/chebyshev.py) and the
    # output-side fold is measured cheaper on TPU — its flip/concat touches
    # the half-size result, while the input-side (analysis) fold streams a
    # full-array reverse before the GEMM
    sgn_c = (-1.0) ** np.arange(c)[None, :]
    if np.abs(mat[::-1, :] - sgn_c * mat).max() < _ATOL * scale:
        return _SynthesisFold(mat)
    # analysis-type: input reflection <-> output index parity
    sgn_r = (-1.0) ** np.arange(r)[:, None]
    if np.abs(mat[:, ::-1] - sgn_r * mat).max() < _ATOL * scale:
        return _AnalysisFold(mat)
    # checkerboard
    j = np.arange(r)[:, None]
    k = np.arange(c)[None, :]
    for shift in (0, 1):
        mask = (j + k + shift) % 2 == 1
        if np.abs(mat[mask]).max(initial=0.0) < _ATOL * scale:
            return _CheckerFold(mat, shift)
    # circular (Fourier) reflection folds.  Size-gated: the index gathers
    # they add are pure overhead on dispatch-bound small GEMMs (measured:
    # the 128x65 periodic config runs faster plain), while at SH-2048-class
    # sizes the flop saving dominates.
    if min(r, c) < _CIRC_MIN_DIM:
        return _Plain(mat)
    cls_in = _classify_circular(mat, on_rows=True)
    # the column classification is only needed for the square quarter-fold
    # candidates and the synthesis fallback — skip the O(r*c) pass otherwise
    cls_out = (
        _classify_circular(mat, on_rows=False)
        if (r == c and cls_in is not None) or cls_in is None
        else None
    )
    if cls_in is not None and cls_out is not None and r == c:
        # single global output class -> rows mirror with one sign: quarter fold
        cols_s, cols_a = cls_out
        if cols_a.size == 0:
            return _CircBothFold(mat, +1.0)
        if cols_s.size == 0 or np.abs(mat[:, cols_s]).max(initial=0.0) < _ATOL * scale:
            return _CircBothFold(mat, -1.0)
    if cls_in is not None:
        return _CircAnalysisFold(mat, *cls_in)
    if cls_out is not None:
        return _CircSynthesisFold(mat, *cls_out)
    return _Plain(mat)


class FoldedMatrix:
    """Device-resident matrix application with automatic parity folding.

    Drop-in for the ``tr.apply_matrix(dev_matrix, a, axis)`` pattern:
    ``FoldedMatrix(host_matrix, to_dev).apply(a, axis)``.  ``to_dev`` is the
    host->device constant placement (bases._dev)."""

    def __init__(self, mat: np.ndarray, to_dev):
        self._impl = _detect(np.asarray(mat))
        self._dev = self._impl.device_parts(to_dev)
        # drop the host copies — apply() reads only the device parts and the
        # scalar shape metadata (at 2049^2 f64 a retained inverse is ~33 MB);
        # recurse into wrapped impls (_CircBothFold holds an inner fold)
        stack = [self._impl]
        while stack:
            impl = stack.pop()
            for attr in ("mat", "m_e", "m_o"):
                if hasattr(impl, attr):
                    setattr(impl, attr, None)
            inner = getattr(impl, "_inner", None)
            if inner is not None:
                stack.append(inner)

    @property
    def kind(self) -> str:
        return self._impl.kind

    @property
    def flops_factor(self) -> float:
        return self._impl.flops_factor

    def apply(self, a, axis: int):
        return self._impl.apply(self._dev, a, axis)


class _CircAnalysisFold:
    """Circular input fold: columns pair under j -> (n-j) mod n and every
    output row is symmetric (+) or antisymmetric (-) across that pairing —
    the structure of the split-Fourier forward matrices (cos rows +, sin
    rows -; fixed points j=0 and, for even n, j=n/2)."""

    kind = "circ_analysis"

    def __init__(self, mat: np.ndarray, rows_s: np.ndarray, rows_a: np.ndarray):
        r, n = mat.shape
        self.r = r
        fixed = [0] + ([n // 2] if n % 2 == 0 else [])
        pair = np.arange(1, (n - 1) // 2 + 1)
        self._fixed = np.asarray(fixed)
        self._pair = pair
        self._partner = n - pair
        # inverse permutation scattering concat(y_s, y_a) back to row order
        perm = np.concatenate([rows_s, rows_a])
        self._inv = np.argsort(perm)
        self.m_e = mat[np.ix_(rows_s, np.concatenate([self._fixed, pair]))]
        self.m_o = mat[np.ix_(rows_a, pair)] if rows_a.size else None
        self.flops_factor = 0.5

    def device_parts(self, to_dev):
        return (to_dev(self.m_e), to_dev(self.m_o) if self.m_o is not None else None)

    def apply(self, dev, a, axis: int):
        m_e, m_o = dev
        x = _move(a, axis)
        u = jnp.concatenate([x[self._fixed], x[self._pair] + x[self._partner]])
        parts = [jnp.tensordot(m_e, u, axes=([1], [0]))]
        if m_o is not None:
            v = x[self._pair] - x[self._partner]
            parts.append(jnp.tensordot(m_o, v, axes=([1], [0])))
        out = jnp.concatenate(parts, axis=0)[self._inv]
        return _unmove(out, axis)


class _CircSynthesisFold:
    """Circular output fold: rows pair under i -> (n-i) mod n, each input
    column symmetric (+) or antisymmetric (-) — the split-Fourier backward
    matrices (cos columns +, sin columns -)."""

    kind = "circ_synthesis"

    def __init__(self, mat: np.ndarray, cols_s: np.ndarray, cols_a: np.ndarray):
        n, c = mat.shape
        self.n = n
        keep = n // 2 + 1  # rows 0..n//2 inclusive
        self._cols_s = cols_s
        self._cols_a = cols_a
        self.m_e = mat[np.ix_(np.arange(keep), cols_s)]
        self.m_o = mat[np.ix_(np.arange(keep), cols_a)] if cols_a.size else None
        # bottom rows n-1..n//2+1 mirror i = 1..ceil(n/2)-1
        self._mirror = np.arange(1, (n + 1) // 2)[::-1]
        self.flops_factor = 0.5

    def device_parts(self, to_dev):
        return (to_dev(self.m_e), to_dev(self.m_o) if self.m_o is not None else None)

    def apply(self, dev, a, axis: int):
        m_e, m_o = dev
        x = _move(a, axis)
        A = jnp.tensordot(m_e, x[self._cols_s], axes=([1], [0]))
        if m_o is not None:
            B = jnp.tensordot(m_o, x[self._cols_a], axes=([1], [0]))
            top, bottom = A + B, A - B
        else:
            top = bottom = A
        out = jnp.concatenate([top, bottom[self._mirror]], axis=0)
        return _unmove(out, axis)


def _classify_circular(mat: np.ndarray, on_rows: bool):
    """Partition rows (on_rows=False: columns) into symmetric/antisymmetric
    classes under the circular reflection of the other index; None if any
    vector is neither."""
    m = mat if on_rows else mat.T  # classify rows of m under column pairing
    r, n = m.shape
    idx = (-np.arange(n)) % n
    refl = m[:, idx]
    scale = np.abs(m).max() or 1.0
    sym = np.abs(refl - m).max(axis=1) < _ATOL * scale
    asym = np.abs(refl + m).max(axis=1) < _ATOL * scale
    if not np.all(sym | asym):
        return None
    # ambiguous (zero) vectors count as symmetric
    rows_s = np.where(sym)[0]
    rows_a = np.where(~sym & asym)[0]
    return rows_s, rows_a


class _CircBothFold:
    """Quarter-flops circular fold for matrices with BOTH circular
    symmetries and a single output class: input columns pair under
    j -> (n-j) mod n (per-row sym/antisym), and every output row mirrors as
    ``M[(n-i) mod n, :] = t * M[i, :]`` with one global sign t — the DFT
    cos (t=+1) and sin (t=-1) matrices.  Computes the kept rows 0..n//2 via
    the half-input fold, then mirrors the bottom rows."""

    kind = "circ_both"

    def __init__(self, mat: np.ndarray, sign: float):
        n = mat.shape[0]
        keep = n // 2 + 1
        kept = mat[:keep]
        cls = _classify_circular(kept, on_rows=True)
        self._inner = _CircAnalysisFold(kept, *cls)
        self._sign = sign
        self._mirror = np.arange(1, (n + 1) // 2)[::-1]
        self.flops_factor = 0.25
        # host copies live on self._inner; FoldedMatrix's cleanup recurses

    def device_parts(self, to_dev):
        return self._inner.device_parts(to_dev)

    def apply(self, dev, a, axis: int):
        x = _move(a, axis)
        top = self._inner.apply(dev, x, 0)
        bottom = self._sign * top[self._mirror]
        return _unmove(jnp.concatenate([top, bottom], axis=0), axis)
