"""Parity-folded matrix application: two half-size GEMMs instead of one.

Every Chebyshev operator in this framework inherits the even/odd symmetry of
the basis — the same structure the reference exploits with its stride-2
banded solvers (/root/reference/src/solver/tdma.rs:49-82, offsets (-2,0,2)).
On TPU the equivalent trick halves the MXU flops of the dense transforms:

* physical<->spectral matrices satisfy a reflection symmetry
  (``M[j, n-1-i] = (-1)^j M[j, i]`` for analysis-type, transposed for
  synthesis-type), so folding the physical side into symmetric/antisymmetric
  halves turns one (r x n) GEMM into an (r_e x ~n/2) + (r_o x ~n/2) pair;
* spectral->spectral operators (derivative matrices, implicit-solve
  inverses) are checkerboard-sparse (``M[j, k] = 0`` unless ``j + k + s``
  is even), foldable the same way by index parity.

Detection is numerical at build time; matrices without the structure (e.g.
the mixed Dirichlet-Neumann base's operators) fall back to the plain GEMM.
Folded and plain paths agree to machine epsilon (tests/test_folded.py) —
each output element is the same reduction, reassociated only across the
explicitly-zero half of the terms.

Enable/disable with RUSTPDE_FOLDED (default on).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from .. import config

# Structure detection tolerance.  Every foldable matrix in this framework is
# built with its symmetry *exact* (mirror-constructed transform matrices,
# parity-blocked eigendecompositions, analytically banded operators), so the
# tolerance only needs to absorb true floating-point zeros that are written
# as ~1-ulp garbage (e.g. sin(pi*k) at a Nyquist column).  At 1e-11 a
# near-symmetric matrix could be folded and silently perturbed; 1e-14 keeps
# the folded/plain agreement at genuine machine epsilon.
_ATOL = 1e-14
_CIRC_MIN_DIM = 256  # circular folds engage only for large transforms
_MAX_BAND_OFFSETS = 8  # banded shift-apply engages up to this many diagonals


def folding_enabled() -> bool:
    return config.env_get("RUSTPDE_FOLDED", "1") != "0"


# ---------------------------------------------------------------------------
# Parity-separated ("sep") spectral layout
# ---------------------------------------------------------------------------
#
# The folded applies above still pay strided gathers (``x[0::2]``), full-array
# reverses and interleave scatters around every GEMM.  In the sep layout a
# spectral axis of length m is stored parity-permuted — ``[0,2,4,...,1,3,...]``
# (evens then odds) — so every parity-structured operator acts on *contiguous
# slices* and reassembles with a concat (which XLA fuses into the output
# buffers): zero data-movement passes.  The physical side keeps natural order
# (elementwise products, masks, observables unchanged); analysis-type applies
# produce sep output directly (concat instead of interleave), synthesis-type
# consume it directly (slices instead of strided gathers).  This is the
# layout-level completion of the reference's stride-2 structure
# (/root/reference/src/solver/tdma.rs:49-82).


def parity_perm(m: int) -> np.ndarray:
    """Natural -> sep order: position p holds natural index perm[p]."""
    return np.concatenate([np.arange(0, m, 2), np.arange(1, m, 2)])


class AxisOperator(NamedTuple):
    """One per-axis transform operator in its *storage layout* — the stable
    accessor contract the fused-kernel builders consume (ops/pallas_conv.py,
    the manual-sharding conv region in parallel/decomp.py) instead of
    reaching into the private folding internals above.

    * ``matrix`` — dense host matrix equal, element for element, to what the
      folded/sep device applies compute: sep permutations baked into the
      rows/columns, dealias-dead output rows zeroed.  Applying it with one
      plain GEMM reproduces the folded apply exactly up to floating-point
      reassociation (the folds are lossless).
    * ``parity`` — ``(sep_in, sep_out)``: which sides are stored in the
      parity-separated order (ops/folded.py sep layout).
    * ``dealias_rows`` — number of kept NATURAL-order output rows under the
      2/3-rule cut (None: no cut baked in).
    * ``kept_rows`` — storage-layout indices of the rows that stay nonzero
      under the cut (None: all rows); the contiguous-run structure a kernel
      epilogue uses to drop the dead rows from its GEMM and zero-fill the
      output."""

    matrix: np.ndarray
    parity: tuple
    dealias_rows: int | None
    kept_rows: np.ndarray | None


def dense_operator(
    mat: np.ndarray,
    sep_in: bool = False,
    sep_out: bool = False,
    keep_rows: int | None = None,
) -> np.ndarray:
    """The dense storage-layout matrix equivalent to
    ``FoldedMatrix(mat, sep_in=…, sep_out=…, keep_rows=…)`` — THE single
    source of truth for how the sep layout permutes operator matrices (the
    same conjugation `_detect_sep` applies to unstructured fallbacks).
    Dead dealias rows are zeroed in NATURAL order before any permutation,
    exactly like the ``keep_rows`` row-drop of `_AnalysisSep`."""
    mat = np.asarray(mat)
    r, c = mat.shape
    if keep_rows is not None and keep_rows < r:
        mat = np.where(np.arange(r)[:, None] < max(0, keep_rows), mat, 0.0)
    if sep_out:
        mat = mat[parity_perm(r), :]
    if sep_in:
        mat = mat[:, parity_perm(c)]
    return mat


def pad_dense(mat: np.ndarray, rows: int, cols: int) -> np.ndarray:
    """Zero-pad a host operator matrix to ``(rows, cols)`` — the one shared
    tile-padding helper of the fused-kernel builders (zero rows/columns are
    mathematically inert through the linear chains)."""
    mat = np.asarray(mat)
    out = np.zeros((rows, cols), dtype=mat.dtype)
    out[: mat.shape[0], : mat.shape[1]] = mat
    return out


def kept_storage_rows(r: int, keep_rows: int, sep_out: bool) -> np.ndarray:
    """Storage-layout row indices that survive a ``keep_rows`` natural-order
    prefix cut: ``arange(keep_rows)`` in natural order; under the sep
    permutation the kept rows form one contiguous run per parity block."""
    if not sep_out:
        return np.arange(max(0, min(keep_rows, r)))
    return np.where(parity_perm(r) < keep_rows)[0]


def parity_perm_inv(m: int) -> np.ndarray:
    """Sep -> natural: position i holds sep position of natural index i."""
    return np.argsort(parity_perm(m))


def _move(a, axis):
    return jnp.moveaxis(a, axis, 0)


def _unmove(a, axis):
    return jnp.moveaxis(a, 0, axis)


# even/odd row interleave shared with the cumsum-derivative kernel
from .transforms import _interleave0 as _interleave  # noqa: E402


class _BandedApply:
    """Matrix with few nonzero diagonals applied as diagonal-scaled shifted
    adds: ``out[i] = sum_d w_d[i] * x[i+d]`` — O(#offsets * n) per lane
    instead of the O(n^2) GEMM.  This is how the exactly-banded operator
    family (stencils S, the B2 quasi-inverse preconditioner, restricted
    eyes) should hit the TPU: a handful of fused VPU multiply-adds streaming
    HBM once, leaving the MXU to the genuinely dense work.  (The reference
    gets the same effect from explicit banded storage in its Tdma/Fdma
    kernels, /root/reference/src/solver/tdma.rs.)"""

    kind = "banded"

    def __init__(self, mat: np.ndarray, offsets: np.ndarray):
        r, c = mat.shape
        self.r, self.c = r, c
        self.offsets = [int(d) for d in offsets]
        if self.offsets:
            ws = np.zeros((len(self.offsets), r))
            for t, d in enumerate(self.offsets):
                i0, i1 = max(0, -d), min(r, c - d)
                idx = np.arange(i0, i1)
                ws[t, i0:i1] = mat[idx, idx + d]
            self.weights = ws
            self.flops_factor = len(self.offsets) / c
        else:  # structurally zero matrix
            self.weights = np.zeros((0, r))
            self.flops_factor = 0.0

    def device_parts(self, to_dev):
        return (to_dev(self.weights),)

    def apply(self, dev, a, axis: int):
        (w,) = dev
        x = _move(a, axis)
        r = self.r
        batch = x.shape[1:]
        if not self.offsets:
            return _unmove(jnp.zeros((r,) + batch, dtype=x.dtype), axis)
        lo = max(0, -min(self.offsets))
        hi = max(0, max(self.offsets) + r - self.c)
        xp = jnp.pad(x, [(lo, hi)] + [(0, 0)] * len(batch))
        bshape = (r,) + (1,) * len(batch)
        out = None
        for t, d in enumerate(self.offsets):
            term = w[t].reshape(bshape) * jax.lax.slice_in_dim(xp, lo + d, lo + d + r, axis=0)
            out = term if out is None else out + term
        return _unmove(out, axis)


class _Plain:
    kind = "plain"

    def __init__(self, mat: np.ndarray):
        self.mat = mat
        self.flops_factor = 1.0

    def apply(self, dev, a, axis: int):
        from .transforms import apply_matrix

        (m,) = dev
        return apply_matrix(m, a, axis)

    def device_parts(self, to_dev):
        return (to_dev(self.mat),)


class _AnalysisFold:
    """M[j, n-1-i] = (-1)^j M[j, i]: fold the (physical) input side."""

    kind = "analysis"

    #: optional per-impl matmul precision override (None = session default);
    #: set by FoldedMatrix for the dealiased-forward 3-pass mode
    #: (RUSTPDE_FWD_PRECISION) — same hook as _SynthesisSep.precision
    precision = None

    def __init__(self, mat: np.ndarray):
        r, n = mat.shape
        h = n // 2
        self.n = n
        self.h = h
        even = mat[0::2, :]
        odd = mat[1::2, :]
        m_e = even[:, :h]
        if n % 2 == 1:
            m_e = np.concatenate([m_e, even[:, h : h + 1]], axis=1)
        self.m_e = m_e  # (r_e, h [+1])
        self.m_o = odd[:, :h]  # (r_o, h)
        self.r = r
        self.flops_factor = 0.5

    def device_parts(self, to_dev):
        return (to_dev(self.m_e), to_dev(self.m_o))

    def _combine(self, y_e, y_o):
        return _interleave(y_e, y_o, self.r)

    def apply(self, dev, a, axis: int):
        m_e, m_o = dev
        x = _move(a, axis)
        h, n = self.h, self.n
        xr = x[::-1]
        u = x[:h] + xr[:h]
        v = x[:h] - xr[:h]
        if n % 2 == 1:
            u = jnp.concatenate([u, x[h : h + 1]], axis=0)
        y_e = jnp.tensordot(m_e, u, axes=([1], [0]), precision=self.precision)
        y_o = jnp.tensordot(m_o, v, axes=([1], [0]), precision=self.precision)
        return _unmove(self._combine(y_e, y_o), axis)


class _SynthesisFold:
    """M[n-1-i, k] = (-1)^k M[i, k]: fold the (physical) output side."""

    kind = "synthesis"

    def __init__(self, mat: np.ndarray):
        n, c = mat.shape
        ceil = (n + 1) // 2
        self.n = n
        self.ceil = ceil
        self.m_e = mat[:ceil, 0::2]  # couples even spectral modes
        self.m_o = mat[:ceil, 1::2]
        self.flops_factor = 0.5

    def device_parts(self, to_dev):
        return (to_dev(self.m_e), to_dev(self.m_o))

    def apply(self, dev, a, axis: int):
        m_e, m_o = dev
        x = _move(a, axis)
        A = jnp.tensordot(m_e, x[0::2], axes=([1], [0]))
        B = jnp.tensordot(m_o, x[1::2], axes=([1], [0]))
        top = A + B
        floor = self.n // 2
        bottom = (A - B)[:floor][::-1]
        return _unmove(jnp.concatenate([top, bottom], axis=0), axis)


class _CheckerFold:
    """M[j, k] = 0 unless (j + k + shift) even: fold both spectral sides."""

    kind = "checker"

    def __init__(self, mat: np.ndarray, shift: int):
        r, c = mat.shape
        self.r = r
        self.shift = shift
        # output row j couples inputs of parity (j + shift) % 2
        self.m_e = mat[0::2, shift % 2 :: 2]
        self.m_o = mat[1::2, (1 + shift) % 2 :: 2]
        self.flops_factor = 0.5

    def device_parts(self, to_dev):
        return (to_dev(self.m_e), to_dev(self.m_o))

    def apply(self, dev, a, axis: int):
        m_e, m_o = dev
        x = _move(a, axis)
        s = self.shift % 2
        y_e = jnp.tensordot(m_e, x[s::2], axes=([1], [0]))
        y_o = jnp.tensordot(m_o, x[(1 + s) % 2 :: 2], axes=([1], [0]))
        return _unmove(_interleave(y_e, y_o, self.r), axis)


class _AnalysisSep(_AnalysisFold):
    """Analysis-type apply with sep-layout output: the even/odd half-GEMM
    results concatenate contiguously instead of interleaving.

    ``keep_rows``: only the first ``keep_rows`` NATURAL output modes are
    nonzero (a prefix dealias cut); the GEMMs drop the dead rows and the
    output is zero-padded — the 2/3-rule forward costs 2/3 of the flops and
    needs no separate mask multiply."""

    kind = "analysis_sep"

    def __init__(self, mat: np.ndarray, keep_rows: int | None = None):
        super().__init__(mat)
        r = self.r
        self.re = (r + 1) // 2  # even-block size of the sep output
        if keep_rows is None or keep_rows >= r:
            self.keep = None
        else:
            k = max(0, keep_rows)
            self.keep = ((k + 1) // 2, k // 2)  # kept rows per parity block
            self.m_e = self.m_e[: self.keep[0]]
            self.m_o = self.m_o[: self.keep[1]]
            self.flops_factor = 0.5 * k / r if r else 0.0
            self.kind = "analysis_sep_cut"

    def _combine(self, y_e, y_o):
        if self.keep is None:
            return jnp.concatenate([y_e, y_o], axis=0)
        ke, ko = self.keep
        batch = y_e.shape[1:]
        z_e = jnp.zeros((self.re - ke,) + batch, dtype=y_e.dtype)
        z_o = jnp.zeros((self.r - self.re - ko,) + batch, dtype=y_o.dtype)
        return jnp.concatenate([y_e, z_e, y_o, z_o], axis=0)


class _SynthesisSep(_SynthesisFold):
    """Synthesis-type apply with sep-layout input: contiguous slices instead
    of strided gathers.

    ``sign``: +1 for the plain synthesis symmetry ``M[n-1-i,k] =
    (-1)^k M[i,k]``; -1 for the sign-shifted variant ``(-1)^(k+1)`` that
    synthesis-of-odd-derivative fusions (``Syn @ D @ S``) carry."""

    kind = "synthesis_sep"

    #: optional per-impl matmul precision override (None = session default);
    #: set by FoldedMatrix for experiments like the synthesis-only 3-pass
    #: mode (RUSTPDE_SYNTH_PRECISION)
    precision = None

    def __init__(self, mat: np.ndarray, sign: float = 1.0):
        super().__init__(mat)
        self.ce = (mat.shape[1] + 1) // 2  # even-block size of the sep input
        self.sign = sign

    def apply(self, dev, a, axis: int):
        m_e, m_o = dev
        x = _move(a, axis)
        A = jnp.tensordot(m_e, x[: self.ce], axes=([1], [0]), precision=self.precision)
        B = jnp.tensordot(m_o, x[self.ce :], axes=([1], [0]), precision=self.precision)
        top = A + B
        floor = self.n // 2
        bottom = (self.sign * (A - B))[:floor][::-1]
        return _unmove(jnp.concatenate([top, bottom], axis=0), axis)


class _StripTrapezoid:
    """Upper-trapezoidal dense block (the Chebyshev derivative factors
    ``D^o @ S``: row k couples only columns ``>= k - bandwidth``): split the
    output rows into strips, each strip's GEMM starting at its first nonzero
    column — the zero lower-left triangle the full dense GEMM pays for is
    skipped.  4 strips recover ~37% of a perfectly triangular block's flops;
    the strips stay MXU-sized (>=256 rows at the production grids)."""

    kind = "trapezoid"

    def __init__(self, mat: np.ndarray, row_starts, col_starts):
        self.bounds = []
        mats = []
        r, c = mat.shape
        for i, (r0, c0) in enumerate(zip(row_starts, col_starts)):
            r1 = row_starts[i + 1] if i + 1 < len(row_starts) else r
            self.bounds.append((r0, r1, c0))
            mats.append(np.ascontiguousarray(mat[r0:r1, c0:]))
        self.mats = mats  # host copies; dropped by FoldedMatrix cleanup
        self.flops_factor = (
            sum((r1 - r0) * (c - c0) for r0, r1, c0 in self.bounds) / (r * c)
            if r * c
            else 0.0
        )

    def device_parts(self, to_dev):
        return tuple(to_dev(m) for m in self.mats)

    def apply(self, dev, a, axis: int):
        x = _move(a, axis)
        parts = [
            jnp.tensordot(m, x[c0:], axes=([1], [0]))
            for m, (_, _, c0) in zip(dev, self.bounds)
        ]
        return _unmove(jnp.concatenate(parts, axis=0), axis)


_TRAP_MIN_DIM = 192  # strips below this lose more to GEMM granularity than
_TRAP_MAX_FACTOR = 0.85  # ... the skipped flops save; engage only when the
#                          trapezoid actually removes >=15% of the block


def _detect_trapezoid(mat: np.ndarray):
    """Strip decomposition when the block has a zero lower-left triangle
    (exact zeros — the derivative/stencil products are constructed so)."""
    r, c = mat.shape
    if min(r, c) < _TRAP_MIN_DIM:
        return None
    nz = mat != 0.0
    if not nz.any():
        return None
    # first nonzero column of each row (c for all-zero rows)
    first = np.where(nz.any(axis=1), nz.argmax(axis=1), c)
    strips = max(2, min(8, r // _TRAP_MIN_DIM))
    row_starts = [(r * i) // strips for i in range(strips)]
    col_starts = []
    for i, r0 in enumerate(row_starts):
        r1 = row_starts[i + 1] if i + 1 < len(row_starts) else r
        col_starts.append(int(first[r0:r1].min(initial=c)))
    trap = _StripTrapezoid(mat, row_starts, col_starts)
    if trap.flops_factor > _TRAP_MAX_FACTOR:
        return None
    return trap


def _detect_block(mat: np.ndarray):
    """Banded / trapezoid / plain detection for the parity blocks of a sep
    operator."""
    r, c = mat.shape
    if min(r, c) >= 4:
        scale = np.abs(mat).max() or 1.0
        mask = np.abs(mat) > _ATOL * scale
        if np.count_nonzero(mask) <= _MAX_BAND_OFFSETS * max(r, c):
            rows, cols = np.nonzero(mask)
            offs = np.unique(cols - rows)
            if offs.size <= _MAX_BAND_OFFSETS and offs.size * 4 <= c:
                kept = np.isin(np.arange(c)[None, :] - np.arange(r)[:, None], offs)
                # same lossless-only acceptance as _detect: the banded apply
                # drops off-band entries, so they must be exact zeros
                if not np.any(np.where(kept, 0.0, mat)):
                    return _BandedApply(mat, offs)
        trap = _detect_trapezoid(mat)
        if trap is not None:
            return trap
    return _Plain(mat)


class _SepBoth:
    """Spectral->spectral operator between sep-layout axes: parity-preserving
    (shift 0, e->e/o->o) or parity-flipping (shift 1, e->o/o->e) applies on
    the contiguous parity blocks — no gathers, no interleaves; banded blocks
    keep their shifted-add form with halved offsets."""

    def __init__(self, mat: np.ndarray, shift: int):
        r, c = mat.shape
        self.r = r
        self.ce = (c + 1) // 2
        self.shift = shift
        if shift == 0:
            subs = (mat[0::2, 0::2], mat[1::2, 1::2])
        else:  # even OUT rows couple odd IN cols and vice versa
            subs = (mat[0::2, 1::2], mat[1::2, 0::2])
        self.blocks = tuple(_detect_block(np.ascontiguousarray(s)) for s in subs)
        tot = sum(b.flops_factor * s.size for b, s in zip(self.blocks, subs))
        self.flops_factor = tot / (r * c) if r * c else 0.0
        self.kind = (
            f"sep_{'preserve' if shift == 0 else 'flip'}"
            f"[{self.blocks[0].kind},{self.blocks[1].kind}]"
        )

    def device_parts(self, to_dev):
        return tuple(b.device_parts(to_dev) for b in self.blocks)

    def apply(self, dev, a, axis: int):
        x = _move(a, axis)
        x_e, x_o = x[: self.ce], x[self.ce :]
        b_e, b_o = self.blocks
        if self.shift == 0:
            y_e = b_e.apply(dev[0], x_e, 0)
            y_o = b_o.apply(dev[1], x_o, 0)
        else:
            y_e = b_e.apply(dev[0], x_o, 0)
            y_o = b_o.apply(dev[1], x_e, 0)
        return _unmove(jnp.concatenate([y_e, y_o], axis=0), axis)


def _detect_sep(mat: np.ndarray, sep_in: bool, sep_out: bool, keep_rows=None):
    """Impl selection for sep-layout sides.  Unstructured matrices absorb the
    permutation into the dense operator (conjugation on the host — zero
    runtime cost); structured ones get the gather-free block applies."""
    if np.iscomplexobj(mat) or mat.ndim != 2:
        raise ValueError("sep layout requires real 2-D operator matrices")
    r, c = mat.shape
    scale = np.abs(mat).max() or 1.0
    structured = folding_enabled() and min(r, c) >= 4
    if sep_in and sep_out:
        if structured:
            j = np.arange(r)[:, None]
            k = np.arange(c)[None, :]
            for shift in (0, 1):
                zero_part = mat[(j + k + shift) % 2 == 1]
                if np.abs(zero_part).max(initial=0.0) < _ATOL * scale:
                    return _SepBoth(mat, shift)
        return _Plain(mat[np.ix_(parity_perm(r), parity_perm(c))])
    if sep_out:  # physical/natural input -> sep output (analysis position)
        if structured:
            sgn_r = (-1.0) ** np.arange(r)[:, None]
            if np.abs(mat[:, ::-1] - sgn_r * mat).max() < _ATOL * scale:
                return _AnalysisSep(mat, keep_rows=keep_rows)
        if keep_rows is not None and keep_rows < r:
            mat = np.where(np.arange(r)[:, None] < keep_rows, mat, 0.0)
        return _Plain(mat[parity_perm(r), :])
    # sep input -> physical/natural output (synthesis position)
    if structured:
        sgn_c = (-1.0) ** np.arange(c)[None, :]
        for sign in (1.0, -1.0):
            if np.abs(mat[::-1, :] - sign * sgn_c * mat).max() < _ATOL * scale:
                return _SynthesisSep(mat, sign)
    return _Plain(mat[:, parity_perm(c)])


def _detect(mat: np.ndarray, sep_in: bool = False, sep_out: bool = False, keep_rows=None):
    if sep_in or sep_out:
        return _detect_sep(np.asarray(mat), sep_in, sep_out, keep_rows)
    if not folding_enabled():
        return _Plain(mat)
    if np.iscomplexobj(mat) or mat.ndim != 2 or min(mat.shape) < 4:
        return _Plain(mat)
    r, c = mat.shape
    scale = np.abs(mat).max() or 1.0
    # small-bandwidth matrices: shifted adds beat any GEMM fold.  Cheap
    # nnz pre-check first so dense matrices skip the O(nnz) index
    # materialization (np.nonzero on a 2049^2 transform is ~67 MB transient)
    mask = np.abs(mat) > _ATOL * scale
    if np.count_nonzero(mask) <= _MAX_BAND_OFFSETS * max(r, c):
        rows, cols = np.nonzero(mask)
        offs = np.unique(cols - rows)
        if offs.size <= _MAX_BAND_OFFSETS and offs.size * 4 <= c:
            # the banded apply DROPS everything off the kept diagonals, so
            # it is only taken when the dropped entries are exact zeros —
            # every current banded operator (stencils, B2, restricted eyes)
            # is constructed that way.  A near-banded matrix with nonzero
            # sub-tolerance off-band entries falls through to the lossless
            # folds/dense applies instead of being silently truncated.
            kept = np.isin(np.arange(c)[None, :] - np.arange(r)[:, None], offs)
            if not np.any(np.where(kept, 0.0, mat)):
                return _BandedApply(mat, offs)
    # synthesis-type first: pure transform matrices of even N carry BOTH
    # reflection structures (quarter-constructed, ops/chebyshev.py) and the
    # output-side fold is measured cheaper on TPU — its flip/concat touches
    # the half-size result, while the input-side (analysis) fold streams a
    # full-array reverse before the GEMM
    sgn_c = (-1.0) ** np.arange(c)[None, :]
    if np.abs(mat[::-1, :] - sgn_c * mat).max() < _ATOL * scale:
        return _SynthesisFold(mat)
    # analysis-type: input reflection <-> output index parity
    sgn_r = (-1.0) ** np.arange(r)[:, None]
    if np.abs(mat[:, ::-1] - sgn_r * mat).max() < _ATOL * scale:
        return _AnalysisFold(mat)
    # checkerboard
    j = np.arange(r)[:, None]
    k = np.arange(c)[None, :]
    for shift in (0, 1):
        mask = (j + k + shift) % 2 == 1
        if np.abs(mat[mask]).max(initial=0.0) < _ATOL * scale:
            return _CheckerFold(mat, shift)
    # circular (Fourier) reflection folds.  Size-gated: the index gathers
    # they add are pure overhead on dispatch-bound small GEMMs (measured:
    # the 128x65 periodic config runs faster plain), while at SH-2048-class
    # sizes the flop saving dominates.
    if min(r, c) < _CIRC_MIN_DIM:
        return _Plain(mat)
    cls_in = _classify_circular(mat, on_rows=True)
    # the column classification is only needed for the square quarter-fold
    # candidates and the synthesis fallback — skip the O(r*c) pass otherwise
    cls_out = (
        _classify_circular(mat, on_rows=False)
        if (r == c and cls_in is not None) or cls_in is None
        else None
    )
    if cls_in is not None and cls_out is not None and r == c:
        # single global output class -> rows mirror with one sign: quarter fold
        cols_s, cols_a = cls_out
        if cols_a.size == 0:
            return _CircBothFold(mat, +1.0)
        if cols_s.size == 0 or np.abs(mat[:, cols_s]).max(initial=0.0) < _ATOL * scale:
            return _CircBothFold(mat, -1.0)
    if cls_in is not None:
        return _CircAnalysisFold(mat, *cls_in)
    if cls_out is not None:
        return _CircSynthesisFold(mat, *cls_out)
    return _Plain(mat)


class FoldedMatrix:
    """Device-resident matrix application with automatic parity folding.

    Drop-in for the ``tr.apply_matrix(dev_matrix, a, axis)`` pattern:
    ``FoldedMatrix(host_matrix, to_dev).apply(a, axis)``.  ``to_dev`` is the
    host->device constant placement (bases._dev)."""

    def __init__(
        self, mat: np.ndarray, to_dev, sep_in: bool = False, sep_out: bool = False,
        keep_rows=None, cast=None,
    ):
        """``cast``: store the device parts in this dtype and run apply()
        through it (input cast in, output cast back to the input dtype) —
        the f64-hybrid mode's f32 convection transforms (Base._sep_dev)."""
        self._impl = _detect(np.asarray(mat), sep_in, sep_out, keep_rows)
        self._cast = np.dtype(cast) if cast is not None else None
        if self._cast is None:
            place = to_dev
        else:
            def place(m, _c=self._cast):
                import jax

                # cast on the HOST and place directly (bypassing to_dev,
                # whose astype(config.real_dtype()) would undo the cast):
                # half the bytes over the wire and no transient f64 device
                # buffer; ensure_compile_time_eval keeps the constant
                # concrete under lazy in-trace materialization, like
                # bases._dev itself
                with jax.ensure_compile_time_eval():
                    return jnp.asarray(np.asarray(m).astype(_c))
        self._dev = self._impl.device_parts(place)
        # drop the host copies — apply() reads only the device parts and the
        # scalar shape metadata (at 2049^2 f64 a retained inverse is ~33 MB);
        # recurse into wrapped impls (_CircBothFold holds an inner fold,
        # _SepBoth holds per-parity blocks)
        stack = [self._impl]
        while stack:
            impl = stack.pop()
            for attr in ("mat", "m_e", "m_o", "mats"):
                if hasattr(impl, attr):
                    setattr(impl, attr, None)
            inner = getattr(impl, "_inner", None)
            if inner is not None:
                stack.append(inner)
            stack.extend(getattr(impl, "blocks", ()))

    @property
    def kind(self) -> str:
        return self._impl.kind

    @property
    def flops_factor(self) -> float:
        return self._impl.flops_factor

    def set_precision(self, precision: str | None) -> bool:
        """Override the matmul precision of the underlying apply, where the
        impl supports one (the ``_SynthesisSep`` family declares a
        ``precision`` hook).  Returns whether the override took — callers
        must not assume it did: unstructured ``_Plain`` fallbacks stay at
        session precision rather than silently carrying a dead attr.  The
        public face of what bases.py used to do by reaching into
        ``_impl``."""
        if precision and hasattr(type(self._impl), "precision"):
            self._impl.precision = precision
            return True
        return False

    def apply(self, a, axis: int):
        if self._cast is not None and a.dtype != self._cast:
            if jnp.iscomplexobj(a) and not jnp.issubdtype(
                self._cast, jnp.complexfloating
            ):
                # astype(real) silently DROPS the imaginary part; the hybrid
                # cast is only defined real->real (f64 state through f32
                # transforms).  Complex spectral data must stay complex —
                # split-Fourier layouts reach here as real re/im planes.
                raise TypeError(
                    f"FoldedMatrix hybrid cast: complex operand ({a.dtype}) "
                    f"cannot be cast to real {self._cast} without losing the "
                    "imaginary part"
                )
            out = self._impl.apply(self._dev, a.astype(self._cast), axis)
            return out.astype(a.dtype)
        return self._impl.apply(self._dev, a, axis)


class _CircAnalysisFold:
    """Circular input fold: columns pair under j -> (n-j) mod n and every
    output row is symmetric (+) or antisymmetric (-) across that pairing —
    the structure of the split-Fourier forward matrices (cos rows +, sin
    rows -; fixed points j=0 and, for even n, j=n/2)."""

    kind = "circ_analysis"

    def __init__(self, mat: np.ndarray, rows_s: np.ndarray, rows_a: np.ndarray):
        r, n = mat.shape
        self.r = r
        fixed = [0] + ([n // 2] if n % 2 == 0 else [])
        pair = np.arange(1, (n - 1) // 2 + 1)
        self._fixed = np.asarray(fixed)
        self._pair = pair
        self._partner = n - pair
        # inverse permutation scattering concat(y_s, y_a) back to row order
        perm = np.concatenate([rows_s, rows_a])
        self._inv = np.argsort(perm)
        self.m_e = mat[np.ix_(rows_s, np.concatenate([self._fixed, pair]))]
        self.m_o = mat[np.ix_(rows_a, pair)] if rows_a.size else None
        self.flops_factor = 0.5

    def device_parts(self, to_dev):
        return (to_dev(self.m_e), to_dev(self.m_o) if self.m_o is not None else None)

    def apply(self, dev, a, axis: int):
        m_e, m_o = dev
        x = _move(a, axis)
        u = jnp.concatenate([x[self._fixed], x[self._pair] + x[self._partner]])
        parts = [jnp.tensordot(m_e, u, axes=([1], [0]))]
        if m_o is not None:
            v = x[self._pair] - x[self._partner]
            parts.append(jnp.tensordot(m_o, v, axes=([1], [0])))
        out = jnp.concatenate(parts, axis=0)[self._inv]
        return _unmove(out, axis)


class _CircSynthesisFold:
    """Circular output fold: rows pair under i -> (n-i) mod n, each input
    column symmetric (+) or antisymmetric (-) — the split-Fourier backward
    matrices (cos columns +, sin columns -)."""

    kind = "circ_synthesis"

    def __init__(self, mat: np.ndarray, cols_s: np.ndarray, cols_a: np.ndarray):
        n, c = mat.shape
        self.n = n
        keep = n // 2 + 1  # rows 0..n//2 inclusive
        self._cols_s = cols_s
        self._cols_a = cols_a
        self.m_e = mat[np.ix_(np.arange(keep), cols_s)]
        self.m_o = mat[np.ix_(np.arange(keep), cols_a)] if cols_a.size else None
        # bottom rows n-1..n//2+1 mirror i = 1..ceil(n/2)-1
        self._mirror = np.arange(1, (n + 1) // 2)[::-1]
        self.flops_factor = 0.5

    def device_parts(self, to_dev):
        return (to_dev(self.m_e), to_dev(self.m_o) if self.m_o is not None else None)

    def apply(self, dev, a, axis: int):
        m_e, m_o = dev
        x = _move(a, axis)
        A = jnp.tensordot(m_e, x[self._cols_s], axes=([1], [0]))
        if m_o is not None:
            B = jnp.tensordot(m_o, x[self._cols_a], axes=([1], [0]))
            top, bottom = A + B, A - B
        else:
            top = bottom = A
        out = jnp.concatenate([top, bottom[self._mirror]], axis=0)
        return _unmove(out, axis)


def _classify_circular(mat: np.ndarray, on_rows: bool):
    """Partition rows (on_rows=False: columns) into symmetric/antisymmetric
    classes under the circular reflection of the other index; None if any
    vector is neither."""
    m = mat if on_rows else mat.T  # classify rows of m under column pairing
    r, n = m.shape
    idx = (-np.arange(n)) % n
    refl = m[:, idx]
    scale = np.abs(m).max() or 1.0
    sym = np.abs(refl - m).max(axis=1) < _ATOL * scale
    asym = np.abs(refl + m).max(axis=1) < _ATOL * scale
    if not np.all(sym | asym):
        return None
    # ambiguous (zero) vectors count as symmetric
    rows_s = np.where(sym)[0]
    rows_a = np.where(~sym & asym)[0]
    return rows_s, rows_a


class _CircBothFold:
    """Quarter-flops circular fold for matrices with BOTH circular
    symmetries and a single output class: input columns pair under
    j -> (n-j) mod n (per-row sym/antisym), and every output row mirrors as
    ``M[(n-i) mod n, :] = t * M[i, :]`` with one global sign t — the DFT
    cos (t=+1) and sin (t=-1) matrices.  Computes the kept rows 0..n//2 via
    the half-input fold, then mirrors the bottom rows."""

    kind = "circ_both"

    def __init__(self, mat: np.ndarray, sign: float):
        n = mat.shape[0]
        keep = n // 2 + 1
        kept = mat[:keep]
        cls = _classify_circular(kept, on_rows=True)
        self._inner = _CircAnalysisFold(kept, *cls)
        self._sign = sign
        self._mirror = np.arange(1, (n + 1) // 2)[::-1]
        self.flops_factor = 0.25
        # host copies live on self._inner; FoldedMatrix's cleanup recurses

    def device_parts(self, to_dev):
        return self._inner.device_parts(to_dev)

    def apply(self, dev, a, axis: int):
        x = _move(a, axis)
        top = self._inner.apply(dev, x, 0)
        bottom = self._sign * top[self._mirror]
        return _unmove(jnp.concatenate([top, bottom], axis=0), axis)
