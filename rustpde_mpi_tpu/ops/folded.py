"""Parity-folded matrix application: two half-size GEMMs instead of one.

Every Chebyshev operator in this framework inherits the even/odd symmetry of
the basis — the same structure the reference exploits with its stride-2
banded solvers (/root/reference/src/solver/tdma.rs:49-82, offsets (-2,0,2)).
On TPU the equivalent trick halves the MXU flops of the dense transforms:

* physical<->spectral matrices satisfy a reflection symmetry
  (``M[j, n-1-i] = (-1)^j M[j, i]`` for analysis-type, transposed for
  synthesis-type), so folding the physical side into symmetric/antisymmetric
  halves turns one (r x n) GEMM into an (r_e x ~n/2) + (r_o x ~n/2) pair;
* spectral->spectral operators (derivative matrices, implicit-solve
  inverses) are checkerboard-sparse (``M[j, k] = 0`` unless ``j + k + s``
  is even), foldable the same way by index parity.

Detection is numerical at build time; matrices without the structure (e.g.
the mixed Dirichlet-Neumann base's operators) fall back to the plain GEMM.
Folded and plain paths agree to machine epsilon (tests/test_folded.py) —
each output element is the same reduction, reassociated only across the
explicitly-zero half of the terms.

Enable/disable with RUSTPDE_FOLDED (default on).
"""

from __future__ import annotations

import os

import jax.numpy as jnp
import numpy as np

_ATOL = 1e-11


def folding_enabled() -> bool:
    return os.environ.get("RUSTPDE_FOLDED", "1") != "0"


def _move(a, axis):
    return jnp.moveaxis(a, axis, 0)


def _unmove(a, axis):
    return jnp.moveaxis(a, 0, axis)


def _interleave(even, odd, n: int):
    """Rows 0,2,4,.. from ``even`` and 1,3,5,.. from ``odd`` -> (n, ...)."""
    h_e = even.shape[0]
    batch = even.shape[1:]
    if n % 2 == 0:
        stacked = jnp.stack([even, odd], axis=1)  # (h, 2, ...)
        return stacked.reshape((n,) + batch)
    # odd n: even part has one extra row; interleave the first 2*h_o rows,
    # append the last even row
    h_o = odd.shape[0]
    stacked = jnp.stack([even[:h_o], odd], axis=1).reshape((2 * h_o,) + batch)
    return jnp.concatenate([stacked, even[h_o:]], axis=0)


class _Plain:
    kind = "plain"

    def __init__(self, mat: np.ndarray):
        self.mat = mat
        self.flops_factor = 1.0

    def apply(self, dev, a, axis: int):
        from .transforms import apply_matrix

        (m,) = dev
        return apply_matrix(m, a, axis)

    def device_parts(self, to_dev):
        return (to_dev(self.mat),)


class _AnalysisFold:
    """M[j, n-1-i] = (-1)^j M[j, i]: fold the (physical) input side."""

    kind = "analysis"

    def __init__(self, mat: np.ndarray):
        r, n = mat.shape
        h = n // 2
        self.n = n
        self.h = h
        even = mat[0::2, :]
        odd = mat[1::2, :]
        m_e = even[:, :h]
        if n % 2 == 1:
            m_e = np.concatenate([m_e, even[:, h : h + 1]], axis=1)
        self.m_e = m_e  # (r_e, h [+1])
        self.m_o = odd[:, :h]  # (r_o, h)
        self.r = r
        self.flops_factor = 0.5

    def device_parts(self, to_dev):
        return (to_dev(self.m_e), to_dev(self.m_o))

    def apply(self, dev, a, axis: int):
        m_e, m_o = dev
        x = _move(a, axis)
        h, n = self.h, self.n
        xr = x[::-1]
        u = x[:h] + xr[:h]
        v = x[:h] - xr[:h]
        if n % 2 == 1:
            u = jnp.concatenate([u, x[h : h + 1]], axis=0)
        y_e = jnp.tensordot(m_e, u, axes=([1], [0]))
        y_o = jnp.tensordot(m_o, v, axes=([1], [0]))
        return _unmove(_interleave(y_e, y_o, self.r), axis)


class _SynthesisFold:
    """M[n-1-i, k] = (-1)^k M[i, k]: fold the (physical) output side."""

    kind = "synthesis"

    def __init__(self, mat: np.ndarray):
        n, c = mat.shape
        ceil = (n + 1) // 2
        self.n = n
        self.ceil = ceil
        self.m_e = mat[:ceil, 0::2]  # couples even spectral modes
        self.m_o = mat[:ceil, 1::2]
        self.flops_factor = 0.5

    def device_parts(self, to_dev):
        return (to_dev(self.m_e), to_dev(self.m_o))

    def apply(self, dev, a, axis: int):
        m_e, m_o = dev
        x = _move(a, axis)
        A = jnp.tensordot(m_e, x[0::2], axes=([1], [0]))
        B = jnp.tensordot(m_o, x[1::2], axes=([1], [0]))
        top = A + B
        floor = self.n // 2
        bottom = (A - B)[:floor][::-1]
        return _unmove(jnp.concatenate([top, bottom], axis=0), axis)


class _CheckerFold:
    """M[j, k] = 0 unless (j + k + shift) even: fold both spectral sides."""

    kind = "checker"

    def __init__(self, mat: np.ndarray, shift: int):
        r, c = mat.shape
        self.r = r
        self.shift = shift
        # output row j couples inputs of parity (j + shift) % 2
        self.m_e = mat[0::2, shift % 2 :: 2]
        self.m_o = mat[1::2, (1 + shift) % 2 :: 2]
        self.flops_factor = 0.5

    def device_parts(self, to_dev):
        return (to_dev(self.m_e), to_dev(self.m_o))

    def apply(self, dev, a, axis: int):
        m_e, m_o = dev
        x = _move(a, axis)
        s = self.shift % 2
        y_e = jnp.tensordot(m_e, x[s::2], axes=([1], [0]))
        y_o = jnp.tensordot(m_o, x[(1 + s) % 2 :: 2], axes=([1], [0]))
        return _unmove(_interleave(y_e, y_o, self.r), axis)


def _detect(mat: np.ndarray):
    if not folding_enabled():
        return _Plain(mat)
    if np.iscomplexobj(mat) or mat.ndim != 2 or min(mat.shape) < 4:
        return _Plain(mat)
    r, c = mat.shape
    scale = np.abs(mat).max() or 1.0
    # analysis-type: input reflection <-> output index parity
    sgn_r = (-1.0) ** np.arange(r)[:, None]
    if np.abs(mat[:, ::-1] - sgn_r * mat).max() < _ATOL * scale:
        return _AnalysisFold(mat)
    # synthesis-type: output reflection <-> input index parity
    sgn_c = (-1.0) ** np.arange(c)[None, :]
    if np.abs(mat[::-1, :] - sgn_c * mat).max() < _ATOL * scale:
        return _SynthesisFold(mat)
    # checkerboard
    j = np.arange(r)[:, None]
    k = np.arange(c)[None, :]
    for shift in (0, 1):
        mask = (j + k + shift) % 2 == 1
        if np.abs(mat[mask]).max(initial=0.0) < _ATOL * scale:
            return _CheckerFold(mat, shift)
    return _Plain(mat)


class FoldedMatrix:
    """Device-resident matrix application with automatic parity folding.

    Drop-in for the ``tr.apply_matrix(dev_matrix, a, axis)`` pattern:
    ``FoldedMatrix(host_matrix, to_dev).apply(a, axis)``.  ``to_dev`` is the
    host->device constant placement (bases._dev)."""

    def __init__(self, mat: np.ndarray, to_dev):
        self._impl = _detect(np.asarray(mat))
        self._dev = self._impl.device_parts(to_dev)
        # drop the host copies — apply() reads only the device parts and the
        # scalar shape metadata (at 2049^2 f64 a retained inverse is ~33 MB)
        for attr in ("mat", "m_e", "m_o"):
            if hasattr(self._impl, attr):
                setattr(self._impl, attr, None)

    @property
    def kind(self) -> str:
        return self._impl.kind

    @property
    def flops_factor(self) -> float:
        return self._impl.flops_factor

    def apply(self, a, axis: int):
        return self._impl.apply(self._dev, a, axis)
