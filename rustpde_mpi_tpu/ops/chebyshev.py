"""Host-side (numpy, f64) operator builders for Chebyshev bases.

This module is the TPU rebuild of the Chebyshev half of the external
``funspace`` crate the reference depends on (API reconstructed in SURVEY.md
S2.2; usage sites e.g. /root/reference/src/field.rs:195-249).  Everything here
runs once at model-build time on the host; the resulting dense/banded matrices
are shipped to the device as constants and applied with MXU-friendly matmuls
(or FFT-based transforms, see ops/transforms.py).

Conventions (ours, not a copy of funspace's):

* Grid: Chebyshev–Gauss–Lobatto points in **ascending** order,
  ``x_j = -cos(pi j / (n-1))`` so ``x[0] = -1`` (bottom) and ``x[-1] = +1``
  (top).  The reference only ever addresses boundaries through ``x[0]`` /
  ``x[-1]`` (e.g. boundary profiles,
  /root/reference/src/navier_stokes/boundary_conditions.rs:24-29), so this
  choice is observationally equivalent.
* Spectral coefficients are genuine Chebyshev coefficients: ``u(x) = sum_k
  uhat_k T_k(x)``.  Because our points ascend, the DCT-I picks up a
  ``(-1)^k`` diagonal relative to the classic descending-point transform;
  that diagonal is folded into the transform, never into operators.
"""

from __future__ import annotations

import numpy as np

# ----------------------------------------------------------------------------
# grid + transform matrices
# ----------------------------------------------------------------------------


def cgl_points(n: int) -> np.ndarray:
    """Ascending Chebyshev–Gauss–Lobatto points on [-1, 1]."""
    if n < 2:
        raise ValueError("need at least 2 points")
    return -np.cos(np.pi * np.arange(n) / (n - 1))


def synthesis_matrix(n: int) -> np.ndarray:
    """B[j, k] = T_k(x_j) at ascending CGL points (backward transform).

    The bottom half is mirror-constructed from the top via the exact identity
    ``B[N-j, k] = (-1)^k B[j, k]`` so the reflection symmetry holds to the
    *bit* — evaluating cos at both arguments leaves ~1e-13 asymmetry at
    n >= 1025, below which ops/folded.py's structure detection must not dip."""
    N = n - 1
    half = N // 2 + 1
    j = np.arange(half)[:, None]
    k = np.arange(n)[None, :]
    # T_k(-cos t) = (-1)^k cos(k t)
    sgn = (-1.0) ** k
    top = sgn * np.cos(np.pi * k * j / N)
    if N % 2 == 0:
        # self-mirror row j = N/2: odd-k entries are cos(pi*k/2) = 0 exactly,
        # but evaluate to ~1e-13 argument-rounding garbage at large k
        top[N // 2, 1::2] = 0.0
    B = np.empty((n, n))
    B[:half] = top
    B[half:] = (sgn * top[: n - half])[::-1]
    return B


def analysis_matrix(n: int) -> np.ndarray:
    """F such that ``uhat = F @ u`` (forward transform), exact inverse of
    :func:`synthesis_matrix` via DCT-I orthogonality (no matrix inversion).
    Right half mirror-constructed from the exact identity
    ``F[k, N-j] = (-1)^k F[k, j]``; for even N the bottom row half is also
    mirror-constructed from ``F[N-k, j] = (-1)^j F[k, j]`` (sigma and the
    column weights are reflection-symmetric), so F carries BOTH reflection
    structures bit-exactly and ops/folded.py can pick the cheaper
    output-side (synthesis) fold for it."""
    N = n - 1
    half = N // 2 + 1
    if N % 2 == 0:
        # quarter construction: rows k=0..N/2, cols j=0..N/2
        k = np.arange(half)[:, None]
        j = np.arange(half)[None, :]
        sgnk = (-1.0) ** k
        q = sgnk * np.cos(np.pi * k * j / N)
        q[1::2, N // 2] = 0.0  # cos(pi*k/2) = 0 exactly for odd k
        q[N // 2, 1::2] = 0.0  # cos(pi*j/2) = 0 exactly for odd j
        top = np.empty((half, n))
        top[:, :half] = q
        top[:, half:] = (sgnk * q[:, : n - half])[:, ::-1]
        F = np.empty((n, n))
        F[:half] = top
        sgnj = (-1.0) ** np.arange(n)[None, :]
        F[half:] = (sgnj * top[: n - half])[::-1]
    else:
        j = np.arange(half)[None, :]
        k = np.arange(n)[:, None]
        sgn = (-1.0) ** k
        left = sgn * np.cos(np.pi * k * j / N)
        F = np.empty((n, n))
        F[:, :half] = left
        F[:, half:] = (sgn * left[:, : n - half])[:, ::-1]
    F[:, 1:-1] *= 2.0
    sigma = np.full(n, 1.0 / N)
    sigma[0] = sigma[-1] = 1.0 / (2.0 * N)
    return sigma[:, None] * F


def diff_matrix(n: int, order: int = 1) -> np.ndarray:
    """Differentiation in coefficient space: ``(d/dx)^order`` as an
    upper-triangular n x n matrix acting on Chebyshev coefficients.

    Uses T'_p = 2p * sum_{k < p, p-k odd} T_k / ctilde_k  (ctilde_0 = 2).
    """
    D = np.zeros((n, n))
    for p in range(1, n):
        for k in range(p - 1, -1, -2):
            D[k, p] = 2.0 * p
    D[0, :] *= 0.5
    out = np.eye(n)
    for _ in range(order):
        out = D @ out
    return out


# ----------------------------------------------------------------------------
# quasi-inverse of D2 ("laplace_inv" in funspace terms)
# ----------------------------------------------------------------------------


def quasi_inverse_b2(n: int) -> np.ndarray:
    """Banded pseudo-inverse B2 of the second-derivative operator D2.

    Rows 0,1 are zero; row k >= 2 has entries at columns k-2, k, k+2 chosen so
    that ``(B2 @ D2)[k, :] = e_k`` for all k >= 2 (the reference calls that
    product ``laplace_inv_eye``, /root/reference/src/field.rs:203).

    Classic closed form (ctilde_0 = 2, else 1):
        B2[k, k-2] = ctilde_{k-2} / (4 k (k-1))
        B2[k, k]   = -1 / (2 (k^2 - 1))
        B2[k, k+2] = 1 / (4 k (k+1))

    Columns n-2 and n-1 are zeroed: they would multiply second-derivative
    modes that a degree-(n-1) polynomial cannot have (rows n-2, n-1 of D2 are
    zero, so the ``laplace_inv_eye`` identity is unaffected).  This matches
    the funspace/pypde convention — verified against the reference's embedded
    pypde golden solutions (/root/reference/src/solver/poisson.rs:287-291,
    hholtz_adi.rs:203-211, tests/test_golden.py) — and it makes the
    B2-preconditioned eigenpencil exactly real-diagonalizable for every
    composite Chebyshev base (with the untruncated B2 the Neumann pencil has
    complex pairs, which the reference's utils::eig would silently drop).
    """
    B2 = np.zeros((n, n))
    for k in range(2, n):
        ct = 2.0 if k - 2 == 0 else 1.0
        B2[k, k - 2] = ct / (4.0 * k * (k - 1.0))
        B2[k, k] = -1.0 / (2.0 * (k * k - 1.0))
        if k + 2 < n:
            B2[k, k + 2] = 1.0 / (4.0 * k * (k + 1.0))
    B2[:, n - 2 :] = 0.0
    return B2


def restricted_eye(n: int) -> np.ndarray:
    """(n-2) x n matrix selecting rows 2..n ('laplace_inv_eye' restricted)."""
    return np.eye(n)[2:, :]


# ----------------------------------------------------------------------------
# composite (Galerkin) bases: stencil matrices S, n x (n-2)
# u_ortho = S @ u_composite
# ----------------------------------------------------------------------------


def stencil_chebyshev(n: int) -> np.ndarray:
    """Orthogonal base: identity stencil."""
    return np.eye(n)


def stencil_dirichlet(n: int) -> np.ndarray:
    """phi_k = T_k - T_{k+2};  u(-1) = u(1) = 0."""
    m = n - 2
    S = np.zeros((n, m))
    for k in range(m):
        S[k, k] = 1.0
        S[k + 2, k] = -1.0
    return S


def stencil_neumann(n: int) -> np.ndarray:
    """phi_k = T_k - (k/(k+2))^2 T_{k+2};  u'(-1) = u'(1) = 0."""
    m = n - 2
    S = np.zeros((n, m))
    for k in range(m):
        S[k, k] = 1.0
        S[k + 2, k] = -((k / (k + 2.0)) ** 2)
    return S


def stencil_dirichlet_neumann(n: int) -> np.ndarray:
    """phi_k = T_k + a_k T_{k+1} + b_k T_{k+2};  u(-1) = 0, u'(1) = 0.

    Solving phi_k(-1) = 0 and phi_k'(1) = 0 with T_k(-1) = (-1)^k and
    T_k'(1) = k^2 gives
        b_k = -(k^2 + (k+1)^2) / ((k+1)^2 + (k+2)^2),   a_k = 1 + b_k.
    (Leads to the 7-diagonal Helmholtz system the reference solves with
    `PdmaPlus2`, /root/reference/src/solver/hholtz_adi.rs:64.)
    """
    m = n - 2
    S = np.zeros((n, m))
    for k in range(m):
        b = -(k**2 + (k + 1.0) ** 2) / ((k + 1.0) ** 2 + (k + 2.0) ** 2)
        a = 1.0 + b
        S[k, k] = 1.0
        S[k + 1, k] += a
        S[k + 2, k] += b
    return S


def cheb_weights(n: int) -> np.ndarray:
    """Diagonal of the T-space inner-product Gram matrix, up to the constant
    pi/2 (which cancels in every projection built from it): diag(ctilde)."""
    w = np.ones(n)
    w[0] = 2.0
    return w


def projection_matrix(S: np.ndarray) -> np.ndarray:
    """P with ``u_composite = P @ u_ortho``: the weighted Galerkin projection
    (S^T W S)^{-1} S^T W  (funspace's `from_ortho`)."""
    n = S.shape[0]
    W = np.diag(cheb_weights(n))
    G = S.T @ W @ S
    return np.linalg.solve(G, S.T @ W)
