"""Pallas TPU kernel: the fused convection-transform chain.

BASELINE.md's flop decomposition puts the convection family at 54-55% of
step dot-flops on the confined flagships (71.5% at periodic1024), dispatched
as ~22 separate XLA ops per step with full HBM round-trips between the
derivative syntheses, the pointwise product, and the dealiased forward.
This kernel fuses the whole chain

    dvdx = synthesis-of-d/dx(vhat)        (one GEMM per axis)
    dvdy = synthesis-of-d/dy(vhat)
    total = ux*dvdx + uy*dvdy [+ BC-lift terms]
    out  = dealiased forward(total)       (dead 2/3-rule rows DROPPED)

into one ``pl.pallas_call``: the transform GEMMs are tiled through VMEM over
physical-x blocks (grid axis 0) with the spectral-y contraction split over
grid axis 1 (VMEM scratch accumulators), so the physical-space intermediates
``dvdx``/``dvdy``/``total`` never touch HBM, and the 2/3-rule row-drop plus
dealias mask are folded into the kernel epilogue (the forward matrices carry
only the kept rows; dead rows are zero-filled outside).

The per-axis operator matrices come from the stable
``Base.axis_operator(key)`` accessor (ops/folded.py ``AxisOperator`` — sep
permutations and the dealias cut baked in), so the kernel is exact to the
dense unfused path up to floating-point reassociation on every layout:
confined (sep Chebyshev x sep Chebyshev), periodic (complex r2c converted to
the split Re/Im planes at the chain boundary), and split-sep (the TPU
layout).  Interpreter mode runs the same kernel on CPU
(tests/test_pallas_conv.py), natively on an attached TPU.

Selection stays measurement-driven like ``solver.default_method``:
``RUSTPDE_CONV_KERNEL=dense|pallas`` (default dense until the on-chip A/B
lands — ``bench.py pallasconv`` records ms/step, MFU and bit-tolerance
deltas).  VMEM budget note: the whole-width operands (``fyt``, the output
block, the y-synthesis columns) are resident across grid steps — at f32 this
fits comfortably through ~1025^2; the 2049^2 output-column tiling rides the
chip A/B round.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .. import config

LANE = 128
SUBLANE = 8


def conv_kernel_choice() -> str:
    """The ``RUSTPDE_CONV_KERNEL`` knob: ``"dense"`` (default — the unfused
    per-GEMM chain) or ``"pallas"`` (this kernel).  Read at model
    compile time, like the solver-method selection."""
    return config.env_get("RUSTPDE_CONV_KERNEL", "dense")


def _ceil_to(x: int, m: int) -> int:
    return -(-int(x) // m) * m


def _conv_kernel(*refs, with_bc: bool, nj: int):
    """Grid (i over physical-x tiles, j over spectral-y contraction tiles;
    j innermost).  Stage 1 accumulates the two derivative syntheses into
    VMEM scratch; the j-final epilogue forms the pointwise product and the
    dealiased forward, accumulating the output block over the i tiles."""
    from jax.experimental import pallas as pl

    if with_bc:
        (gx1, gx0, v, gy0t, gy1t, ux, uy, bcdx, bcdy, fx, fyt, o, adx, ady) = refs
    else:
        (gx1, gx0, v, gy0t, gy1t, ux, uy, fx, fyt, o, adx, ady) = refs
        bcdx = bcdy = None
    i = pl.program_id(0)
    j = pl.program_id(1)
    acc_t = o.dtype
    prec = jax.lax.Precision.HIGHEST
    # stage 1: this (i, j) tile's contribution to the two half-transforms —
    # a1/a0 are (bx, bj) column slices, their y-syntheses accumulate over j
    a1 = jnp.dot(gx1[...], v[...], precision=prec, preferred_element_type=acc_t)
    a0 = jnp.dot(gx0[...], v[...], precision=prec, preferred_element_type=acc_t)
    pdx = jnp.dot(a1, gy0t[...], precision=prec, preferred_element_type=acc_t)
    pdy = jnp.dot(a0, gy1t[...], precision=prec, preferred_element_type=acc_t)

    @pl.when(j == 0)
    def _init():
        adx[...] = pdx
        ady[...] = pdy

    @pl.when(j > 0)
    def _accum():
        adx[...] = adx[...] + pdx
        ady[...] = ady[...] + pdy

    @pl.when(j == nj - 1)
    def _epilogue():
        dvdx = adx[...]
        dvdy = ady[...]
        if with_bc:
            # ux*tb_dx + uy*tb_dy folded as a shift of the derivatives
            dvdx = dvdx + bcdx[...]
            dvdy = dvdy + bcdy[...]
        total = ux[...] * dvdx + uy[...] * dvdy
        part = jnp.dot(total, fyt[...], precision=prec, preferred_element_type=acc_t)
        part = jnp.dot(fx[...], part, precision=prec, preferred_element_type=acc_t)

        @pl.when(i == 0)
        def _first():
            o[...] = part

        @pl.when(i > 0)
        def _rest():
            o[...] = o[...] + part


class FusedConv:
    """The fused convection chain for one (input space, scratch space) pair:
    ``apply(ux, uy, vhat[, bc_dx, bc_dy])`` == the unfused
    ``forward_dealiased(ux*d(vhat)/dx + uy*d(vhat)/dy [+ bc])`` of
    models/navier.py's ``conv``, computed in one Pallas kernel.

    ``cast`` mirrors the f64-hybrid convention of ``Base._sep_dev``: store
    the operator matrices in that dtype and run the chain through it, casting
    the f64 inputs in and the output back (the hybrid keeps ONE round-trip
    where the per-GEMM dense path casts around every apply — strictly fewer
    roundings).  ``interpret`` defaults to True off-TPU (the CI parity
    suite); ``reference()`` is the unfused chain for A/B and tests."""

    def __init__(
        self,
        space_in,
        field_space,
        scale,
        cast=None,
        interpret: bool | None = None,
        block_x: int | None = None,
        block_k: int | None = None,
    ):
        self.space_in = space_in
        self.field_space = field_space
        self.scale = tuple(scale)
        if space_in.shape_physical != field_space.shape_physical:
            raise ValueError("conv spaces must share the physical grid")
        bx_in, by_in = space_in.bases
        fx_b, fy_b = field_space.bases
        self.complex_in = bx_in.spectral_is_complex
        self.complex_out = fx_b.spectral_is_complex
        if self.complex_in != self.complex_out:
            raise ValueError("mixed complex/real x-axes are unsupported")

        gx1 = bx_in.axis_operator(("bwd_grad", 1), sep=space_in.sep[0]).matrix
        gx0 = bx_in.axis_operator("bwd", sep=space_in.sep[0]).matrix
        gy1 = by_in.axis_operator(("bwd_grad", 1), sep=space_in.sep[1]).matrix
        gy0 = by_in.axis_operator("bwd", sep=space_in.sep[1]).matrix
        op_fx = fx_b.axis_operator("fwd_cut", sep=field_space.sep[0])
        op_fy = fy_b.axis_operator("fwd_cut", sep=field_space.sep[1])
        gx1 = gx1 / self.scale[0]
        gy1 = gy1 / self.scale[1]
        kept_x = (
            op_fx.kept_rows
            if op_fx.kept_rows is not None
            else np.arange(op_fx.matrix.shape[0])
        )
        kept_y = (
            op_fy.kept_rows
            if op_fy.kept_rows is not None
            else np.arange(op_fy.matrix.shape[0])
        )
        fxm = op_fx.matrix[kept_x]
        fym = op_fy.matrix[kept_y]
        self._kept_x = kept_x
        self._kept_y = kept_y

        nx, ny = space_in.shape_physical
        mx, my = gx0.shape[1], gy0.shape[1]
        kx, ky = fxm.shape[0], fym.shape[0]
        self.nx, self.ny, self.mx, self.my, self.kx, self.ky = nx, ny, mx, my, kx, ky

        bx = int(block_x or config.env_get("RUSTPDE_PALLAS_CONV_BLOCK", 256))
        bx = max(LANE, _ceil_to(bx, LANE))
        self.nxp = _ceil_to(nx, bx)
        self.bx = min(bx, self.nxp)
        self.mxp = _ceil_to(mx, LANE)
        self.myp = _ceil_to(my, LANE)
        bj = int(block_k or config.env_get("RUSTPDE_PALLAS_CONV_BLOCK_K", 512))
        bj = max(LANE, (bj // LANE) * LANE)
        while self.myp % bj:
            bj -= LANE
        self.bj = bj
        self.nyp = _ceil_to(ny, LANE)
        self.kxp = _ceil_to(kx, SUBLANE)
        self.kyp = _ceil_to(ky, LANE)

        # shape-keyed kernel name: the flop registry prices pallas_call eqns
        # BY NAME, so two chains with different shapes must not collide
        # (equal shapes share the entry harmlessly)
        self.kernel_name = (
            f"fused_conv_{nx}x{ny}_{mx}x{my}_{kx}x{ky}"
        )
        self._cast = np.dtype(cast) if cast is not None else None
        dt = self._cast or config.real_dtype()
        from .folded import pad_dense

        with jax.ensure_compile_time_eval():

            def place(m, rows, cols):
                return jnp.asarray(pad_dense(np.asarray(m), rows, cols).astype(dt))

            self._gx1 = place(gx1, self.nxp, self.mxp)
            self._gx0 = place(gx0, self.nxp, self.mxp)
            self._gy0t = place(gy0.T, self.myp, self.nyp)
            self._gy1t = place(gy1.T, self.myp, self.nyp)
            self._fx = place(fxm, self.kxp, self.nxp)
            self._fyt = place(fym.T, self.nyp, self.kyp)
        if interpret is None:
            interpret = jax.devices()[0].platform not in ("tpu", "axon")
        self.interpret = bool(interpret)

    # -- flop accounting (profiling.step_flops satellite) ---------------------

    @property
    def flops(self) -> float:
        """Analytic MXU flops of ONE kernel invocation, at the UNPADDED
        chain shapes (the useful model flops, directly comparable to the
        dense path's jaxpr dot count) — registered with
        utils/profiling.register_pallas_flops so the jaxpr walk (which sees
        ``pallas_call`` as one opaque eqn) stays honest on this path.  Tile
        padding shows up as *lower* MFU, which is the right signal for the
        kernel-vs-dense A/B."""
        stage1 = 2.0 * self.nx * self.mx * self.my * 2  # a1, a0
        stage1 += 2.0 * self.nx * self.my * self.ny * 2  # y syntheses
        epi = 2.0 * self.nx * self.ny * self.ky + 2.0 * self.kx * self.nx * self.ky
        return stage1 + epi

    # -- the fused chain ------------------------------------------------------

    def _pallas_call(self, with_bc: bool, batch: bool = False):
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        del batch
        gi = self.nxp // self.bx
        gj = self.myp // self.bj
        in_specs = [
            pl.BlockSpec((self.bx, self.mxp), lambda i, j: (i, 0)),  # gx1
            pl.BlockSpec((self.bx, self.mxp), lambda i, j: (i, 0)),  # gx0
            pl.BlockSpec((self.mxp, self.bj), lambda i, j: (0, j)),  # vhat
            pl.BlockSpec((self.bj, self.nyp), lambda i, j: (j, 0)),  # gy0t
            pl.BlockSpec((self.bj, self.nyp), lambda i, j: (j, 0)),  # gy1t
            pl.BlockSpec((self.bx, self.nyp), lambda i, j: (i, 0)),  # ux
            pl.BlockSpec((self.bx, self.nyp), lambda i, j: (i, 0)),  # uy
        ]
        if with_bc:
            in_specs += [
                pl.BlockSpec((self.bx, self.nyp), lambda i, j: (i, 0)),  # bc dx
                pl.BlockSpec((self.bx, self.nyp), lambda i, j: (i, 0)),  # bc dy
            ]
        in_specs += [
            pl.BlockSpec((self.kxp, self.bx), lambda i, j: (0, i)),  # fx
            pl.BlockSpec((self.nyp, self.kyp), lambda i, j: (0, 0)),  # fyt
        ]
        dt = self._gx1.dtype
        return pl.pallas_call(
            functools.partial(_conv_kernel, with_bc=with_bc, nj=gj),
            grid=(gi, gj),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((self.kxp, self.kyp), lambda i, j: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((self.kxp, self.kyp), dt),
            scratch_shapes=[
                pltpu.VMEM((self.bx, self.nyp), dt),
                pltpu.VMEM((self.bx, self.nyp), dt),
            ],
            interpret=self.interpret,
            name=self.kernel_name,
        )

    def _pad_phys(self, a, dt):
        return jnp.pad(
            a.astype(dt), ((0, self.nxp - self.nx), (0, self.nyp - self.ny))
        )

    def apply(self, ux, uy, vhat, bc_dx=None, bc_dy=None):
        """The fused chain; output in the scratch space's spectral storage
        layout with the dealias-dead rows zero-filled — drop-in for the
        dense ``forward_dealiased(...)`` result."""
        out_dtype = vhat.dtype
        if self.complex_in:
            v = jnp.concatenate([vhat.real, vhat.imag], axis=0)
        else:
            v = vhat
        dt = self._gx1.dtype
        v = jnp.pad(
            v.astype(dt), ((0, self.mxp - self.mx), (0, self.myp - self.my))
        )
        args = [self._gx1, self._gx0, v, self._gy0t, self._gy1t,
                self._pad_phys(ux, dt), self._pad_phys(uy, dt)]
        with_bc = bc_dx is not None
        if with_bc:
            args += [self._pad_phys(bc_dx, dt), self._pad_phys(bc_dy, dt)]
        args += [self._fx, self._fyt]
        out = self._pallas_call(with_bc)(*args)[: self.kx, : self.ky]
        shape = self.field_space.shape_spectral
        if self.complex_out:
            # split kept rows are [0:kc] (Re) and [mc:mc+kc] (Im), compacted
            # by the kernel to [0:kc]+[kc:2kc]: reassemble the complex modes
            kc = self.kx // 2
            rdt = np.zeros(0, dtype=out_dtype).real.dtype
            res = (out[:kc].astype(rdt) + 1j * out[kc:].astype(rdt)).astype(out_dtype)
            full = jnp.zeros(shape, dtype=out_dtype)
            return full.at[np.ix_(np.arange(kc), self._kept_y)].set(res)
        full = jnp.zeros(shape, dtype=out_dtype)
        return full.at[np.ix_(self._kept_x, self._kept_y)].set(
            out.astype(out_dtype)
        )

    def reference(self, ux, uy, vhat, bc_dx=None, bc_dy=None, fast=True):
        """The unfused dense chain (exactly models/navier.py's ``conv``):
        the A/B denominator of the parity tests and the pallasconv bench."""
        sp, fs = self.space_in, self.field_space
        dvdx = sp.backward_gradient(vhat, (1, 0), self.scale, fast=fast)
        dvdy = sp.backward_gradient(vhat, (0, 1), self.scale, fast=fast)
        total = ux * dvdx + uy * dvdy
        if bc_dx is not None:
            total = total + ux * bc_dx + uy * bc_dy
        if any(fs.sep):
            return fs.forward_dealiased(total, fast=fast)
        mask = jnp.asarray(fs.dealias_mask(), dtype=config.real_dtype())
        return fs.forward(total) * mask


def hybrid_cast():
    """The f64-hybrid cast the model convection path runs under
    ``RUSTPDE_F64_HYBRID=1`` (same convention as ``Base._sep_dev``):
    operator matrices stored f32, f64 state cast through the chain."""
    if config.X64 and config.env_get("RUSTPDE_F64_HYBRID") == "1":
        return np.float32
    return None


def build_model_convs(model, interpret: bool | None = None) -> dict:
    """``{id(space): FusedConv}`` for a Navier-family model's convection
    spaces (velx/vely share one space object; temp has its own), keyed so
    the step's ``conv(ux, uy, space, vhat)`` can route by identity.
    Registers each kernel's analytic flops with utils/profiling."""
    from ..utils import profiling

    cast = hybrid_cast()
    specs: dict[int, FusedConv] = {}
    for space in (model.velx_space, model.temp_space):
        if id(space) in specs:
            continue
        fc = FusedConv(space, model.field_space, model.scale, cast=cast,
                       interpret=interpret)
        specs[id(space)] = fc
        profiling.register_pallas_flops(fc.kernel_name, fc.flops)
    return specs


def bench_conv_paths(n: int = 129, repeats: int = 20):
    """Microbenchmark: fused Pallas chain vs the unfused dense chain on this
    backend at a confined grid — the measurement behind the
    RUSTPDE_CONV_KERNEL default (interpreter mode off-TPU measures only
    correctness plumbing, not speed; the honest A/B needs a chip)."""
    import time

    from ..bases import Space2, cheb_dirichlet, chebyshev

    sp = Space2(cheb_dirichlet(n), cheb_dirichlet(n))
    fs = Space2(chebyshev(n), chebyshev(n))
    fc = FusedConv(sp, fs, (1.0, 1.0))
    rng = np.random.default_rng(0)
    rdt = config.real_dtype()
    ux = jnp.asarray(rng.standard_normal((n, n)), dtype=rdt)
    uy = jnp.asarray(rng.standard_normal((n, n)), dtype=rdt)
    vhat = sp.forward(jnp.asarray(rng.standard_normal((n, n)), dtype=rdt))
    results = {}
    for name, fn in (
        ("pallas", jax.jit(fc.apply)),
        ("dense", jax.jit(fc.reference)),
    ):
        out = fn(ux, uy, vhat)
        np.asarray(out.real[:1, :1])
        t0 = time.perf_counter()
        for _ in range(repeats):
            out = fn(ux, uy, vhat)
        np.asarray(out.real[:1, :1])
        results[name] = (time.perf_counter() - t0) / repeats
    return results
