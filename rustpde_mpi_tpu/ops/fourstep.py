"""Four-step (Bailey) FFT factorizations as batched MXU GEMMs.

The reference outsources its transforms to rustfft/rustdct — O(n log n)
recursive FFTs (/root/reference/Cargo.toml:17 via funspace, SURVEY.md S2.2).
A literal radix-2 FFT is the wrong shape for a TPU: log2(n) sequential
stages of tiny butterflies starve the MXU.  The TPU-native equivalent is the
*four-step* factorization n = n1*n2:

    X[k2 + n2*k1] = sum_{j1} w1^{j1 k1} [ w^{j1 k2} sum_{j2} w2^{j2 k2}
                                          x[j1 + n1*j2] ]

i.e. (1) reshape, (2) a length-n2 DFT over all n1*batch lanes, (3) an
elementwise twiddle, (4) a length-n1 DFT — O(n*(n1+n2)) flops instead of the
dense transform's O(n^2).  Complex arithmetic is *blocked into single real
GEMMs*: the cos/sin matrix pair and the Re/Im operand pair are stacked so
each stage is ONE matrix product with a 2x contraction dim — measured faster
on the v5e MXU than the 4-GEMM formulation (half-sized K starves the 128x128
systolic array) and the axon backend has no complex dtypes anyway.
Real-input (r2c) transforms compute only the k2 half spectrum in stage 2
(Hermitian mirror is a slice+flip) and only k1 <= n1//2 in stage 4;
real-*output* transforms (the DCT cores, the c2r synthesis) drop the
imaginary accumulators of their final stage.

The Chebyshev DCT-I rides the same core: the cosine kernel of size N+1 is
the real part of the length-2N r2c DFT of the even extension, so both the
analysis and the synthesis direction reduce to ``RfftPlan.re`` plus diagonal
pre/post scalings.

Everything here is exact to reassociation; tests pin equality against the
dense transform matrices at 1e-12 (f64).
"""

from __future__ import annotations


import numpy as np

import jax.numpy as jnp

from .. import config

# NOTE: _MODE/_MIN are re-read from the environment on every enabled() call
# (they are cheap lookups), so tests/scripts may toggle RUSTPDE_FOURSTEP*
# after import.  Plans already built into a Base/Space are NOT invalidated —
# transform path selection is construction-time, like every other operator
# choice in the package (rebuild the Space to change it).  config.X64 is
# process-level (jax_enable_x64 at import) and cannot toggle mid-process.
_MODE = config.env_get("RUSTPDE_FOURSTEP", "auto")
# Per-kind auto thresholds on the DFT length, measured on the v5e in f32
# (scripts/bench_transforms.py + scripts/profile_step.py): below these the
# folded dense GEMM wins (it is one well-shaped MXU op; the factored path's
# smaller-K stages + twiddle/mirror passes only pay off once the dense
# O(n^2) bill is large enough).  Measured ratios dense/fourstep: r2c 0.44x
# @1024 -> 2.1x @2048; c2c 2.0x @1024, 2.9x @2048.  The DCT core never wins
# at the production grid sizes: a batch-1025 microbench showed 1.2x at core
# 4096, but in model context at 2049^2 (batch 2049) the dense pair runs
# 1.13 ms vs 2.22 ms fourstep — so the DCT gate sits above every current
# grid (re-measure before lowering).
_MIN = {
    "dft": int(config.env_get("RUSTPDE_FOURSTEP_MIN", "2048")),
    "c2c": int(config.env_get("RUSTPDE_FOURSTEP_MIN_C2C", "1024")),
    "dct": int(config.env_get("RUSTPDE_FOURSTEP_MIN_DCT", "8192")),
}


def enabled(n: int, kind: str = "dft") -> bool:
    """Whether the four-step path should replace the dense transform GEMM for
    a length-n DFT of the given kind ("dft" = r2c/c2r, "c2c", "dct" — n is
    the *DFT core* length, 2N for a size-(N+1) DCT-I).  ``RUSTPDE_FOURSTEP``:
    "auto" (default; per-kind measured thresholds above), "1" (whenever
    factorable, incl. small sizes — used by tests), "0" (never).

    Auto never engages in x64 mode: measured on the v5e in emulated f64 the
    factored path loses at EVERY size (0.18-0.49x; the non-MXU twiddle/
    mirror/stacking passes emulate far worse than the dense GEMM's extra
    flops cost — same asymmetry as the cumsum derivative)."""
    mode = config.env_get("RUSTPDE_FOURSTEP", _MODE)
    if mode == "0":
        return False
    if mode == "1":
        return viable(n, 4)
    if config.X64:
        return False
    env_min = {
        "dft": config.env_get("RUSTPDE_FOURSTEP_MIN"),
        "c2c": config.env_get("RUSTPDE_FOURSTEP_MIN_C2C"),
        "dct": config.env_get("RUSTPDE_FOURSTEP_MIN_DCT"),
    }.get(kind)
    lo = int(env_min) if env_min else _MIN.get(kind, _MIN["dft"])
    return n >= lo and viable(n)


def default_factors(n: int) -> tuple[int, int]:
    """Split n = n1*n2 with n1 <= n2, n1 as close to sqrt(n) as divisibility
    allows (balanced stages minimize total GEMM flops ~ n*(n1+n2)).
    ``RUSTPDE_FOURSTEP_N1`` forces n1 for hardware tuning."""
    forced = config.env_get("RUSTPDE_FOURSTEP_N1")
    if forced:
        n1 = int(forced)
        if n % n1 == 0:
            a, b = sorted((n1, n // n1))
            return a, b
    n1 = int(np.sqrt(n))
    while n1 > 1 and n % n1 != 0:
        n1 -= 1
    return n1, n // n1


def viable(n: int, min_factor: int = 8) -> bool:
    """A four-step plan only pays off when both stages are real GEMMs."""
    n1, _ = default_factors(n)
    return n1 >= min_factor


def _twiddle(n1: int, n2: int, n: int, transpose: bool = False):
    """cos/sin(2pi j1 k2 / n) tables; (n2, n1) rows k2 (or transposed)."""
    k2 = np.arange(n2)[:, None]
    j1 = np.arange(n1)[None, :]
    ang = 2.0 * np.pi * k2 * j1 / n
    c, s = np.cos(ang), np.sin(ang)
    if transpose:
        return c.T, s.T
    return c, s


class RfftPlan:
    """Real-input forward DFT of length n (batched along the other dims).

    ``split(x)``  -> (2m, ...) stacked [Re; Im] of the *unnormalized* rfft,
    ``re(x)``     -> (m, ...) real part only (the DCT-I core),
    m = n//2 + 1.  ``x`` must already have the transform axis moved to 0.
    """

    def __init__(self, n: int, to_dev, n1: int | None = None):
        self.n = n
        if n1 is None:
            n1, n2 = default_factors(n)
        else:
            n2 = n // n1
        assert n1 * n2 == n
        self.n1, self.n2 = n1, n2
        self.m = n // 2 + 1
        m2 = n2 // 2 + 1
        self.m2 = m2
        h1 = n1 // 2 + 1
        self.h1 = h1
        j2 = np.arange(n2)[None, :]
        k2 = np.arange(m2)[:, None]
        ang2 = 2.0 * np.pi * k2 * j2 / n2
        # stage 2: one (2*m2 x n2) GEMM producing [Re; Im] rows
        self._m2mat = to_dev(np.concatenate([np.cos(ang2), -np.sin(ang2)], axis=0))
        twc, tws = _twiddle(n1, n2, n)
        self._twc = to_dev(twc)  # (n2, n1)
        self._tws = to_dev(tws)
        j1 = np.arange(n1)[None, :]
        k1h = np.arange(h1)[:, None]
        ang1 = 2.0 * np.pi * k1h * j1 / n1
        c1, s1 = np.cos(ang1), np.sin(ang1)
        # stage 4 blocked over the stacked [Zre | Zim] contraction:
        #   Re rows: [ C1 | S1 ],  Im rows: [ -S1 | C1 ]
        self._m4_re = to_dev(np.concatenate([c1, s1], axis=1))  # (h1, 2n1)
        self._m4_full = to_dev(
            np.block([[c1, s1], [-s1, c1]])  # (2h1, 2n1)
        )

    # -- stages ------------------------------------------------------------

    def _stage123(self, x):
        """x: (n, ...) real -> twiddled Z stacked (n2, 2*n1, ...)."""
        n1, n2, m2 = self.n1, self.n2, self.m2
        batch = x.shape[1:]
        a = x.reshape((n2, n1) + batch)  # a[j2, j1] = x[j1 + n1*j2]
        y = jnp.tensordot(self._m2mat, a, axes=([1], [0]))  # (2m2, n1, ...)
        yre, yim = y[:m2], y[m2:]
        # Hermitian mirror to the full k2 range: rows n2-k2 for k2=m2..n2-1
        mir = slice(1, n2 - m2 + 1)
        yre = jnp.concatenate([yre, jnp.flip(yre[mir], 0)], axis=0)
        yim = jnp.concatenate([yim, -jnp.flip(yim[mir], 0)], axis=0)
        shape = (n2, n1) + (1,) * len(batch)
        twc = self._twc.reshape(shape)
        tws = self._tws.reshape(shape)
        # w^{j1 k2} = cos - i sin
        zre = twc * yre + tws * yim
        zim = twc * yim - tws * yre
        return jnp.concatenate([zre, zim], axis=1)  # (n2, 2n1, ...)

    def _finalize(self, block, rows: int):
        """(n2, rows_per_part*?, ...) stage-4 output -> k = k2 + n2*k1 order:
        transposing (n2, h1) to (h1, n2) and flattening C-order lists index
        k1*n2 + k2 = k; slice to m."""
        out = jnp.moveaxis(block, 1, 0)  # (rows, n2, ...)
        return out.reshape((rows * self.n2,) + out.shape[2:])[: self.m]

    def re(self, x):
        """Re(rfft(x)) along axis 0, unnormalized."""
        z = self._stage123(x)
        blk = jnp.einsum("kj,cj...->ck...", self._m4_re, z)  # (n2, h1, ...)
        return self._finalize(blk, self.h1)

    def split(self, x):
        """[Re; Im] of rfft(x) along axis 0, unnormalized (2m rows)."""
        h1 = self.h1
        z = self._stage123(x)
        blk = jnp.einsum("kj,cj...->ck...", self._m4_full, z)  # (n2, 2h1, ...)
        re = self._finalize(blk[:, :h1], h1)
        im = self._finalize(blk[:, h1:], h1)
        return jnp.concatenate([re, im], axis=0)


class IrfftPlan:
    """Real-output inverse DFT: split spectrum [Re; Im] (2m rows) ->
    ``v_j = Re sum_{k=0}^{n-1} chat_k e^{+2pi i jk/n}`` with chat the
    Hermitian extension weighted exactly like
    ops/fourier.split_backward_matrix (normalization is the caller's)."""

    def __init__(self, n: int, to_dev, n1: int | None = None):
        self.n = n
        if n1 is None:
            n1, n2 = default_factors(n)
        else:
            n2 = n // n1
        assert n1 * n2 == n
        self.n1, self.n2 = n1, n2
        self.m = n // 2 + 1
        j1 = np.arange(n1)[:, None]
        k1 = np.arange(n1)[None, :]
        ang1 = 2.0 * np.pi * j1 * k1 / n1
        c1, s1 = np.cos(ang1), np.sin(ang1)
        # stage 2 blocked over stacked [Wre; Wim] (contract k1, sign +):
        #   Gre rows: [ C1 | -S1 ],  Gim rows: [ S1 | C1 ]
        self._m2 = to_dev(np.block([[c1, -s1], [s1, c1]]))  # (2n1, 2n1)
        twc, tws = _twiddle(n1, n2, n, transpose=True)  # (n1, n2)
        self._twc = to_dev(twc)
        self._tws = to_dev(tws)
        j2 = np.arange(n2)[:, None]
        k2 = np.arange(n2)[None, :]
        ang2 = 2.0 * np.pi * j2 * k2 / n2
        # stage 4 real output (sign +): v = [ C2 | -S2 ] @ [Hre; Him]
        self._m4 = to_dev(np.concatenate([np.cos(ang2), -np.sin(ang2)], axis=1))

    def apply(self, s):
        """s: (2m, ...) split spectrum, transform axis already moved to 0."""
        n, n1, n2, m = self.n, self.n1, self.n2, self.m
        batch = s.shape[1:]
        re, im = s[:m], s[m:]
        # Hermitian extension chat[k], k=0..n-1 (interior modes twice)
        mir = slice(1, n - m + 1)
        cre = jnp.concatenate([re, jnp.flip(re[mir], 0)], axis=0)
        cim = jnp.concatenate([im, -jnp.flip(im[mir], 0)], axis=0)
        w = jnp.concatenate(
            [cre.reshape((n1, n2) + batch), cim.reshape((n1, n2) + batch)], axis=0
        )  # (2n1, n2, ...): [Wre; Wim] with W[k1, k2] = chat[n2*k1 + k2]
        g = jnp.tensordot(self._m2, w, axes=([1], [0]))  # (2n1, n2, ...)
        gre, gim = g[:n1], g[n1:]
        shape = (n1, n2) + (1,) * len(batch)
        twc = self._twc.reshape(shape)
        tws = self._tws.reshape(shape)
        hre = twc * gre - tws * gim
        him = twc * gim + tws * gre
        h = jnp.concatenate([hre, him], axis=1)  # (n1, 2n2, ...)
        v = jnp.einsum("mk,jk...->mj...", self._m4, h)  # (n2, n1, ...)
        return v.reshape((n,) + batch)  # (j2, j1) flattens to j1 + n1*j2


class C2cPlan:
    """Complex-to-complex DFT on split re/im planes.

    ``sign=-1`` is the forward convention (e^{-2pi i jk/n}), ``sign=+1`` the
    inverse (no 1/n — normalization is the caller's).  Input and output are
    ``(re, im)`` pairs with the transform axis moved to 0.
    """

    def __init__(self, n: int, to_dev, sign: float, n1: int | None = None):
        self.n = n
        self.sign = float(sign)
        if n1 is None:
            n1, n2 = default_factors(n)
        else:
            n2 = n // n1
        assert n1 * n2 == n
        self.n1, self.n2 = n1, n2
        sg = self.sign
        j2 = np.arange(n2)[None, :]
        k2 = np.arange(n2)[:, None]
        ang2 = 2.0 * np.pi * k2 * j2 / n2
        c2, s2 = np.cos(ang2), sg * np.sin(ang2)
        # stage 2 over stacked [Are; Aim]: Yre = C*Are - sg*S*Aim, etc.
        self._m2 = to_dev(np.block([[c2, -s2], [s2, c2]]))  # (2n2, 2n2)
        twc, tws = _twiddle(n1, n2, n)
        self._twc = to_dev(twc)
        self._tws = to_dev(sg * tws)
        j1 = np.arange(n1)[None, :]
        k1 = np.arange(n1)[:, None]
        ang1 = 2.0 * np.pi * k1 * j1 / n1
        c1, s1 = np.cos(ang1), sg * np.sin(ang1)
        self._m4 = to_dev(np.block([[c1, -s1], [s1, c1]]))  # (2n1, 2n1)

    def apply(self, xre, xim):
        n1, n2 = self.n1, self.n2
        batch = xre.shape[1:]
        a = jnp.concatenate(
            [xre.reshape((n2, n1) + batch), xim.reshape((n2, n1) + batch)], axis=0
        )  # (2n2, n1, ...)
        y = jnp.tensordot(self._m2, a, axes=([1], [0]))  # (2n2, n1, ...)
        yre, yim = y[:n2], y[n2:]
        shape = (n2, n1) + (1,) * len(batch)
        twc = self._twc.reshape(shape)
        tws = self._tws.reshape(shape)
        zre = twc * yre - tws * yim
        zim = twc * yim + tws * yre
        z = jnp.concatenate([zre, zim], axis=1)  # (n2, 2n1, ...)
        b = jnp.einsum("kj,cj...->ck...", self._m4, z)  # (n2, 2n1, ...)
        # (k2, k1) -> k = k2 + n2*k1: transpose then flatten
        bre = jnp.moveaxis(b[:, :n1], 1, 0).reshape((self.n,) + batch)
        bim = jnp.moveaxis(b[:, n1:], 1, 0).reshape((self.n,) + batch)
        return bre, bim


class Dct1Plan:
    """Fast DCT-I cosine-kernel application of size n = N+1 (any N whose
    doubling 2N factors well): ``out_k = sum_j colw_j x_j cos(pi j k / N)``
    with the natural even-extension column weights colw = [1, 2, ..., 2, 1]
    — exactly ``Re(rfft(ext(x)))`` where ext is the length-2N even
    extension.  Both Chebyshev transform directions are diagonal scalings
    around this core (ops/chebyshev.analysis_matrix / synthesis_matrix)."""

    def __init__(self, n: int, to_dev, n1: int | None = None):
        self.n = n
        self.N = n - 1
        self._plan = RfftPlan(2 * self.N, to_dev, n1=n1)

    def apply(self, x):
        """x: (n, ...), transform axis already at 0 -> (n, ...)."""
        ext = jnp.concatenate([x, jnp.flip(x[1:-1], 0)], axis=0)
        return self._plan.re(ext)  # (N+1, ...) = (n, ...)
