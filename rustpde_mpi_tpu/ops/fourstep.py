"""Four-step (Bailey) FFT factorizations as batched MXU GEMMs.

The reference outsources its transforms to rustfft/rustdct — O(n log n)
recursive FFTs (/root/reference/Cargo.toml:17 via funspace, SURVEY.md S2.2).
A literal radix-2 FFT is the wrong shape for a TPU: log2(n) sequential
stages of tiny butterflies starve the MXU.  The TPU-native equivalent is the
*four-step* factorization n = n1*n2:

    X[k2 + n2*k1] = sum_{j1} w1^{j1 k1} [ w^{j1 k2} sum_{j2} w2^{j2 k2}
                                          x[j1 + n1*j2] ]

i.e. (1) reshape, (2) a length-n2 DFT as one GEMM over all n1*batch lanes,
(3) an elementwise twiddle, (4) a length-n1 DFT as one GEMM — O(n*(n1+n2))
flops instead of the dense transform's O(n^2), with both stages still large
MXU-friendly matrix products in *real* arithmetic (the axon TPU backend has
no complex dtypes).  Real-input (r2c) transforms compute only the k2 half
spectrum in stage 2 (Hermitian mirror is a slice+flip) and only k1 <=
ceil(n1/2) in stage 4; real-*output* transforms (the DCT cores and the c2r
synthesis) drop the imaginary accumulators of their final stage.

The Chebyshev DCT-I rides the same core: the cosine kernel of size N+1 is
the real part of the length-2N r2c DFT of the even extension, so both the
analysis and the synthesis direction reduce to ``rfft_re`` plus diagonal
pre/post scalings (ops/transforms.py keeps the FFT-path equivalents).

Everything here is exact to reassociation; tests pin equality against the
dense transform matrices at 1e-12 (f64).
"""

from __future__ import annotations

import os

import numpy as np

import jax.numpy as jnp

_MODE = os.environ.get("RUSTPDE_FOURSTEP", "auto")
_MIN = int(os.environ.get("RUSTPDE_FOURSTEP_MIN", "512"))


def enabled(n: int) -> bool:
    """Whether the four-step path should replace the dense transform GEMM for
    a length-n DFT.  ``RUSTPDE_FOURSTEP``: "auto" (default; engages at
    n >= RUSTPDE_FOURSTEP_MIN=512 where the factored flops dominate the extra
    dispatch), "1" (whenever factorable, incl. small sizes — used by tests),
    "0" (never)."""
    if _MODE == "0":
        return False
    if _MODE == "1":
        return viable(n, 4)
    return n >= _MIN and viable(n)


def default_factors(n: int) -> tuple[int, int]:
    """Split n = n1*n2 with n1 <= n2, n1 as close to sqrt(n) as divisibility
    allows (balanced stages minimize total GEMM flops ~ n*(n1+n2))."""
    n1 = int(np.sqrt(n))
    while n1 > 1 and n % n1 != 0:
        n1 -= 1
    return n1, n // n1


def viable(n: int, min_factor: int = 8) -> bool:
    """A four-step plan only pays off when both stages are real GEMMs."""
    n1, _ = default_factors(n)
    return n1 >= min_factor


class RfftPlan:
    """Real-input forward DFT of length n (batched along the other dims).

    ``split(x)``  -> (2m, ...) stacked [Re; Im] of the *unnormalized* rfft,
    ``re(x)``     -> (m, ...) real part only (the DCT-I core),
    m = n//2 + 1.  ``x`` must already have the transform axis moved to 0.
    """

    def __init__(self, n: int, to_dev, n1: int | None = None):
        self.n = n
        if n1 is None:
            n1, n2 = default_factors(n)
        else:
            n2 = n // n1
        assert n1 * n2 == n
        self.n1, self.n2 = n1, n2
        self.m = n // 2 + 1
        m2 = n2 // 2 + 1
        self.m2 = m2
        h1 = n1 // 2 + 1
        self.h1 = h1
        j2 = np.arange(n2)[None, :]
        k2 = np.arange(m2)[:, None]
        ang2 = 2.0 * np.pi * k2 * j2 / n2
        j1 = np.arange(n1)[None, :]
        k1h = np.arange(h1)[:, None]
        ang1 = 2.0 * np.pi * k1h * j1 / n1
        k2f = np.arange(n2)[:, None]
        tw = 2.0 * np.pi * k2f * j1 / n
        self._c2 = to_dev(np.cos(ang2))  # (m2, n2)
        self._s2 = to_dev(np.sin(ang2))
        self._twc = to_dev(np.cos(tw))  # (n2, n1)
        self._tws = to_dev(np.sin(tw))
        self._c1 = to_dev(np.cos(ang1))  # (h1, n1)
        self._s1 = to_dev(np.sin(ang1))

    # -- stages ------------------------------------------------------------

    def _stage12(self, x):
        """x: (n, ...) real -> twiddled Z (n2, n1, ...) complex as (re, im)."""
        n1, n2, m2 = self.n1, self.n2, self.m2
        batch = x.shape[1:]
        a = x.reshape((n2, n1) + batch)  # a[j2, j1] = x[j1 + n1*j2]
        yre = jnp.tensordot(self._c2, a, axes=([1], [0]))  # (m2, n1, ...)
        yim = -jnp.tensordot(self._s2, a, axes=([1], [0]))
        # Hermitian mirror to the full k2 range: rows n2-k2 for k2=m2..n2-1
        mir = slice(1, n2 - m2 + 1)
        yre = jnp.concatenate([yre, jnp.flip(yre[mir], 0)], axis=0)
        yim = jnp.concatenate([yim, -jnp.flip(yim[mir], 0)], axis=0)
        shape = (n2, n1) + (1,) * len(batch)
        twc = self._twc.reshape(shape)
        tws = self._tws.reshape(shape)
        # w^{j1 k2} = cos - i sin
        zre = twc * yre + tws * yim
        zim = twc * yim - tws * yre
        return zre, zim

    def _finalize(self, block):
        """(n2, h1, ...) stage-4 output -> (m, ...) in k = k2 + n2*k1 order.

        The k-gather is a pure transpose: block.T flattened C-order lists
        k1*n2 + k2 ... no: transposing to (h1, n2) and flattening gives index
        q*n2 + r at (q, r) = (k1, k2), which is exactly k.  Slice to m."""
        out = jnp.moveaxis(block, 1, 0)  # (h1, n2, ...)
        return out.reshape((self.h1 * self.n2,) + out.shape[2:])[: self.m]

    def re(self, x):
        """Re(rfft(x)) along axis 0, unnormalized."""
        zre, zim = self._stage12(x)
        # Re part of sum_j1 (cos - i sin)(2pi j1 k1/n1) * Z
        blk = jnp.einsum("kj,cj...->ck...", self._c1, zre) + jnp.einsum(
            "kj,cj...->ck...", self._s1, zim
        )
        return self._finalize(blk)

    def split(self, x):
        """[Re; Im] of rfft(x) along axis 0, unnormalized (2m rows)."""
        zre, zim = self._stage12(x)
        bre = jnp.einsum("kj,cj...->ck...", self._c1, zre) + jnp.einsum(
            "kj,cj...->ck...", self._s1, zim
        )
        bim = jnp.einsum("kj,cj...->ck...", self._c1, zim) - jnp.einsum(
            "kj,cj...->ck...", self._s1, zre
        )
        return jnp.concatenate([self._finalize(bre), self._finalize(bim)], axis=0)


class IrfftPlan:
    """Real-output inverse DFT: split spectrum [Re; Im] (2m rows, amplitude
    convention ``c = rfft/n``-style is the *caller's* business — this class
    computes ``v_j = Re sum_{k=0}^{n-1} chat_k e^{+2pi i jk/n}`` with chat the
    Hermitian extension weighted 1/2/1 exactly like
    ops/fourier.split_backward_matrix)."""

    def __init__(self, n: int, to_dev, n1: int | None = None):
        self.n = n
        if n1 is None:
            n1, n2 = default_factors(n)
        else:
            n2 = n // n1
        assert n1 * n2 == n
        self.n1, self.n2 = n1, n2
        self.m = n // 2 + 1
        j1 = np.arange(n1)[:, None]
        k1 = np.arange(n1)[None, :]
        ang1 = 2.0 * np.pi * j1 * k1 / n1
        j2 = np.arange(n2)[:, None]
        k2 = np.arange(n2)[None, :]
        ang2 = 2.0 * np.pi * j2 * k2 / n2
        tw = 2.0 * np.pi * np.arange(n1)[:, None] * np.arange(n2)[None, :] / n
        self._c1 = to_dev(np.cos(ang1))  # (n1, n1) contract k1
        self._s1 = to_dev(np.sin(ang1))
        self._c2 = to_dev(np.cos(ang2))  # (n2, n2) contract k2
        self._s2 = to_dev(np.sin(ang2))
        self._twc = to_dev(np.cos(tw))  # (n1, n2)
        self._tws = to_dev(np.sin(tw))

    def apply(self, s):
        """s: (2m, ...) split spectrum, transform axis already moved to 0."""
        n, n1, n2, m = self.n, self.n1, self.n2, self.m
        batch = s.shape[1:]
        re, im = s[:m], s[m:]
        # Hermitian extension chat[k], k=0..n-1 (interior modes twice)
        mir = slice(1, n - m + 1)
        cre = jnp.concatenate([re, jnp.flip(re[mir], 0)], axis=0)
        cim = jnp.concatenate([im, -jnp.flip(im[mir], 0)], axis=0)
        wre = cre.reshape((n1, n2) + batch)  # W[k1, k2] = chat[n2*k1 + k2]
        wim = cim.reshape((n1, n2) + batch)
        # stage 2: G[j1, k2] = sum_k1 (cos + i sin)(2pi j1 k1/n1) W[k1, k2]
        gre = jnp.tensordot(self._c1, wre, axes=([1], [0])) - jnp.tensordot(
            self._s1, wim, axes=([1], [0])
        )
        gim = jnp.tensordot(self._c1, wim, axes=([1], [0])) + jnp.tensordot(
            self._s1, wre, axes=([1], [0])
        )
        # stage 3: twiddle w^{+j1 k2}
        shape = (n1, n2) + (1,) * len(batch)
        twc = self._twc.reshape(shape)
        tws = self._tws.reshape(shape)
        hre = twc * gre - tws * gim
        him = twc * gim + tws * gre
        # stage 4 (real output): v[j2, j1] = sum_k2 cos(2pi j2 k2/n2) Hre
        #                                   - sin(...) Him
        v = jnp.einsum("mk,jk...->mj...", self._c2, hre) - jnp.einsum(
            "mk,jk...->mj...", self._s2, him
        )
        return v.reshape((n,) + batch)  # (j2, j1) flattens to j1 + n1*j2


class C2cPlan:
    """Complex-to-complex DFT on split re/im planes.

    ``sign=-1`` is the forward convention (e^{-2pi i jk/n}), ``sign=+1`` the
    inverse (no 1/n — normalization is the caller's).  Input and output are
    ``(re, im)`` pairs with the transform axis moved to 0.
    """

    def __init__(self, n: int, to_dev, sign: float, n1: int | None = None):
        self.n = n
        self.sign = float(sign)
        if n1 is None:
            n1, n2 = default_factors(n)
        else:
            n2 = n // n1
        assert n1 * n2 == n
        self.n1, self.n2 = n1, n2
        j2 = np.arange(n2)[None, :]
        k2 = np.arange(n2)[:, None]
        ang2 = 2.0 * np.pi * k2 * j2 / n2
        j1 = np.arange(n1)[None, :]
        k1 = np.arange(n1)[:, None]
        ang1 = 2.0 * np.pi * k1 * j1 / n1
        tw = 2.0 * np.pi * np.arange(n2)[:, None] * np.arange(n1)[None, :] / n
        self._c2 = to_dev(np.cos(ang2))  # (n2, n2)
        self._s2 = to_dev(np.sin(ang2))
        self._c1 = to_dev(np.cos(ang1))  # (n1, n1)
        self._s1 = to_dev(np.sin(ang1))
        self._twc = to_dev(np.cos(tw))  # (n2, n1)
        self._tws = to_dev(np.sin(tw))

    def apply(self, xre, xim):
        n1, n2, sg = self.n1, self.n2, self.sign
        batch = xre.shape[1:]
        are = xre.reshape((n2, n1) + batch)
        aim = xim.reshape((n2, n1) + batch)
        # stage 2: contract j2 with (cos + i*sg*sin)
        yre = jnp.tensordot(self._c2, are, axes=([1], [0])) - sg * jnp.tensordot(
            self._s2, aim, axes=([1], [0])
        )
        yim = jnp.tensordot(self._c2, aim, axes=([1], [0])) + sg * jnp.tensordot(
            self._s2, are, axes=([1], [0])
        )
        # stage 3 twiddle
        shape = (n2, n1) + (1,) * len(batch)
        twc = self._twc.reshape(shape)
        tws = sg * self._tws.reshape(shape)
        zre = twc * yre - tws * yim
        zim = twc * yim + tws * yre
        # stage 4: contract j1
        bre = jnp.einsum("kj,cj...->ck...", self._c1, zre) - sg * jnp.einsum(
            "kj,cj...->ck...", self._s1, zim
        )
        bim = jnp.einsum("kj,cj...->ck...", self._c1, zim) + sg * jnp.einsum(
            "kj,cj...->ck...", self._s1, zre
        )
        # (k2, k1) -> k = k2 + n2*k1: transpose then flatten
        bre = jnp.moveaxis(bre, 1, 0).reshape((self.n,) + batch)
        bim = jnp.moveaxis(bim, 1, 0).reshape((self.n,) + batch)
        return bre, bim


class Dct1Plan:
    """Fast DCT-I cosine-kernel application of size n = N+1 (any N whose
    doubling 2N factors well): ``out_k = sum_j colw_j x_j cos(pi j k / N)`` with
    the natural even-extension column weights colw = [1, 2, ..., 2, 1] —
    exactly ``Re(rfft(ext(x)))`` where ext is the length-2N even extension.

    Both Chebyshev transform directions are diagonal scalings around this
    core (ops/chebyshev.analysis_matrix / synthesis_matrix conventions)."""

    def __init__(self, n: int, to_dev, n1: int | None = None):
        self.n = n
        self.N = n - 1
        self._plan = RfftPlan(2 * self.N, to_dev, n1=n1)

    def apply(self, x):
        """x: (n, ...), transform axis already at 0 -> (n, ...)."""
        ext = jnp.concatenate([x, jnp.flip(x[1:-1], 0)], axis=0)
        return self._plan.re(ext)  # (N+1, ...) = (n, ...)
