"""Host-side operator builders for Fourier bases (r2c and c2c).

TPU rebuild of funspace's ``fourier_r2c`` / ``fourier_c2c`` (SURVEY.md S2.2).
Domain convention: x in [0, 2*pi), uniform points, integer wavenumbers.  The
physical aspect ratio enters exactly as in the reference — through the
``scale`` argument of gradients/solvers, never through the base itself
(/root/reference/src/navier_stokes/navier.rs:225).
"""

from __future__ import annotations

import numpy as np


def fourier_points(n: int) -> np.ndarray:
    """Uniform grid on [0, 2*pi)."""
    return 2.0 * np.pi * np.arange(n) / n


def wavenumbers_r2c(n: int) -> np.ndarray:
    """k = 0..n//2 (real-to-complex half spectrum)."""
    return np.arange(n // 2 + 1, dtype=np.float64)


def wavenumbers_c2c(n: int) -> np.ndarray:
    """Standard FFT ordering 0, 1, ..., -1."""
    return np.fft.fftfreq(n, d=1.0 / n)


def split_forward_matrix(n: int) -> np.ndarray:
    """(2m x n) real matrix F with ``[Re(c); Im(c)] = F @ v`` equal to the
    amplitude-normalized r2c transform (rfft/n), m = n//2+1.

    The split representation is the TPU-native form of the r2c spectrum: the
    axon backend has no complex dtypes and no FFT, so the transform runs as
    one real MXU matmul over stacked Re/Im blocks."""
    m = n // 2 + 1
    j = np.arange(n)[None, :]
    k = np.arange(m)[:, None]
    ang = 2.0 * np.pi * k * j / n
    return np.concatenate([np.cos(ang), -np.sin(ang)], axis=0) / n


def split_backward_matrix(n: int) -> np.ndarray:
    """(n x 2m) real synthesis matrix B with ``v = B @ [Re(c); Im(c)]``
    (inverse of :func:`split_forward_matrix`; mode weights 1/2/1 for
    k = 0 / interior / Nyquist-of-even-n)."""
    m = n // 2 + 1
    j = np.arange(n)[:, None]
    k = np.arange(m)[None, :]
    ang = 2.0 * np.pi * j * k / n
    w = np.full(m, 2.0)
    w[0] = 1.0
    if n % 2 == 0:
        w[-1] = 1.0
    return np.concatenate([w * np.cos(ang), -w * np.sin(ang)], axis=1)


def diff_diag(k: np.ndarray, order: int, n: int, r2c: bool) -> np.ndarray:
    """Diagonal of (d/dx)^order in spectral space: (i k)^order.

    The Nyquist mode of an even-length r2c (or c2c) transform cannot represent
    odd derivatives of a real signal; it is zeroed for odd orders (standard
    practice; keeps gradients of real fields real-representable).
    """
    d = (1j * k) ** order
    if order % 2 == 1 and n % 2 == 0:
        d = d.copy()
        if r2c:
            d[-1] = 0.0
        else:
            d[n // 2] = 0.0
    return d
