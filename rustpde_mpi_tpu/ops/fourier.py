"""Host-side operator builders for Fourier bases (r2c and c2c).

TPU rebuild of funspace's ``fourier_r2c`` / ``fourier_c2c`` (SURVEY.md S2.2).
Domain convention: x in [0, 2*pi), uniform points, integer wavenumbers.  The
physical aspect ratio enters exactly as in the reference — through the
``scale`` argument of gradients/solvers, never through the base itself
(/root/reference/src/navier_stokes/navier.rs:225).
"""

from __future__ import annotations

import numpy as np


def fourier_points(n: int) -> np.ndarray:
    """Uniform grid on [0, 2*pi)."""
    return 2.0 * np.pi * np.arange(n) / n


def wavenumbers_r2c(n: int) -> np.ndarray:
    """k = 0..n//2 (real-to-complex half spectrum)."""
    return np.arange(n // 2 + 1, dtype=np.float64)


def wavenumbers_c2c(n: int) -> np.ndarray:
    """Standard FFT ordering 0, 1, ..., -1."""
    return np.fft.fftfreq(n, d=1.0 / n)


def split_forward_matrix(n: int) -> np.ndarray:
    """(2m x n) real matrix F with ``[Re(c); Im(c)] = F @ v`` equal to the
    amplitude-normalized r2c transform (rfft/n), m = n//2+1.

    The split representation is the TPU-native form of the r2c spectrum: the
    axon backend has no complex dtypes and no FFT, so the transform runs as
    one real MXU matmul over stacked Re/Im blocks.  The right column half is
    mirror-constructed from the exact circular identities
    ``cos(2pi k (n-j)/n) = cos(2pi k j/n)`` / ``sin -> -sin`` so the
    reflection fold in ops/folded.py detects *exact* structure."""
    m = n // 2 + 1
    half = n // 2 + 1  # columns 0..n//2; the rest mirror j -> n-j
    j = np.arange(half)[None, :]
    k = np.arange(m)[:, None]
    ang = 2.0 * np.pi * k * j / n
    cos_l = np.cos(ang)
    sin_l = -np.sin(ang)
    if n % 2 == 0:
        # sin(pi*k) / sin(pi*j) are 0 exactly but evaluate to ~1e-13
        # argument-rounding garbage: Nyquist column (j = n/2) and, for the
        # Nyquist row (k = m-1), every column
        sin_l[:, half - 1] = 0.0
        sin_l[m - 1, :] = 0.0
    cos = np.empty((m, n))
    sin = np.empty((m, n))
    cos[:, :half] = cos_l
    sin[:, :half] = sin_l
    cos[:, half:] = cos_l[:, 1 : n - half + 1][:, ::-1]
    sin[:, half:] = -sin_l[:, 1 : n - half + 1][:, ::-1]
    return np.concatenate([cos, sin], axis=0) / n


def split_backward_matrix(n: int) -> np.ndarray:
    """(n x 2m) real synthesis matrix B with ``v = B @ [Re(c); Im(c)]``
    (inverse of :func:`split_forward_matrix`; mode weights 1/2/1 for
    k = 0 / interior / Nyquist-of-even-n).  Bottom row half is
    mirror-constructed (see :func:`split_forward_matrix`)."""
    m = n // 2 + 1
    half = n // 2 + 1
    j = np.arange(half)[:, None]
    k = np.arange(m)[None, :]
    ang = 2.0 * np.pi * j * k / n
    w = np.full(m, 2.0)
    w[0] = 1.0
    if n % 2 == 0:
        w[-1] = 1.0
    cos_t = w * np.cos(ang)
    sin_t = -w * np.sin(ang)
    if n % 2 == 0:
        sin_t[:, m - 1] = 0.0  # Nyquist mode: sin(pi*j) = 0 exactly
        sin_t[half - 1, :] = 0.0  # self-mirror row j = n/2: sin(pi*k) = 0
    B = np.empty((n, 2 * m))
    B[:half] = np.concatenate([cos_t, sin_t], axis=1)
    B[half:] = np.concatenate([cos_t, -sin_t], axis=1)[1 : n - half + 1][::-1]
    return B


def split_diff_matrix(n: int, order: int) -> np.ndarray:
    """(2m x 2m) real matrix of ``(ik)^order`` on the split Re/Im blocks —
    the dense form of :meth:`~rustpde_mpi_tpu.bases.SplitFourierBase.gradient`'s
    block rotation (``i^order`` cycles (re, im) through the four quadrants,
    times ``k^order``; Nyquist of odd derivatives zeroed exactly like
    :func:`diff_diag`).  Consumed by the fused-kernel builders, which need
    the derivative as a matrix to compose with the synthesis."""
    m = n // 2 + 1
    k = wavenumbers_r2c(n) ** order
    if order % 2 == 1 and n % 2 == 0:
        k = k.copy()
        k[-1] = 0.0
    K = np.diag(k)
    Z = np.zeros((m, m))
    quadrant = order % 4
    if quadrant == 0:
        blocks = [[K, Z], [Z, K]]
    elif quadrant == 1:
        blocks = [[Z, -K], [K, Z]]
    elif quadrant == 2:
        blocks = [[-K, Z], [Z, -K]]
    else:
        blocks = [[Z, K], [-K, Z]]
    return np.block(blocks)


def dft_cos_matrix(n: int) -> np.ndarray:
    """(n x n) matrix ``cos(2pi k j / n)`` with both the row and the column
    mirror (k -> n-k, j -> n-j) exact by construction — the quarter-fold
    (`circ_both`) structure ops/folded.py exploits."""
    half = n // 2 + 1
    j = np.arange(half)[:, None]
    k = np.arange(half)[None, :]
    q = np.cos(2.0 * np.pi * j * k / n)
    top = np.empty((half, n))
    top[:, :half] = q
    top[:, half:] = q[:, 1 : n - half + 1][:, ::-1]
    M = np.empty((n, n))
    M[:half] = top
    M[half:] = top[1 : n - half + 1][::-1]
    return M


def dft_sin_matrix(n: int) -> np.ndarray:
    """(n x n) matrix ``sin(2pi k j / n)``, mirrors exact (antisymmetric in
    both directions; see :func:`dft_cos_matrix`)."""
    half = n // 2 + 1
    j = np.arange(half)[:, None]
    k = np.arange(half)[None, :]
    q = np.sin(2.0 * np.pi * j * k / n)
    if n % 2 == 0:
        q[half - 1, :] = 0.0  # sin(pi*k) = 0 exactly (self-mirror row)
        q[:, half - 1] = 0.0  # sin(pi*j) = 0 exactly (self-mirror column)
    top = np.empty((half, n))
    top[:, :half] = q
    top[:, half:] = -q[:, 1 : n - half + 1][:, ::-1]
    M = np.empty((n, n))
    M[:half] = top
    M[half:] = -top[1 : n - half + 1][::-1]
    return M


def diff_diag(k: np.ndarray, order: int, n: int, r2c: bool) -> np.ndarray:
    """Diagonal of (d/dx)^order in spectral space: (i k)^order.

    The Nyquist mode of an even-length r2c (or c2c) transform cannot represent
    odd derivatives of a real signal; it is zeroed for odd orders (standard
    practice; keeps gradients of real fields real-representable).
    """
    d = (1j * k) ** order
    if order % 2 == 1 and n % 2 == 0:
        d = d.copy()
        if r2c:
            d[-1] = 0.0
        else:
            d[n // 2] = 0.0
    return d
