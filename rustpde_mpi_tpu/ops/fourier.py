"""Host-side operator builders for Fourier bases (r2c and c2c).

TPU rebuild of funspace's ``fourier_r2c`` / ``fourier_c2c`` (SURVEY.md S2.2).
Domain convention: x in [0, 2*pi), uniform points, integer wavenumbers.  The
physical aspect ratio enters exactly as in the reference — through the
``scale`` argument of gradients/solvers, never through the base itself
(/root/reference/src/navier_stokes/navier.rs:225).
"""

from __future__ import annotations

import numpy as np


def fourier_points(n: int) -> np.ndarray:
    """Uniform grid on [0, 2*pi)."""
    return 2.0 * np.pi * np.arange(n) / n


def wavenumbers_r2c(n: int) -> np.ndarray:
    """k = 0..n//2 (real-to-complex half spectrum)."""
    return np.arange(n // 2 + 1, dtype=np.float64)


def wavenumbers_c2c(n: int) -> np.ndarray:
    """Standard FFT ordering 0, 1, ..., -1."""
    return np.fft.fftfreq(n, d=1.0 / n)


def diff_diag(k: np.ndarray, order: int, n: int, r2c: bool) -> np.ndarray:
    """Diagonal of (d/dx)^order in spectral space: (i k)^order.

    The Nyquist mode of an even-length r2c (or c2c) transform cannot represent
    odd derivatives of a real signal; it is zeroed for odd orders (standard
    practice; keeps gradients of real fields real-representable).
    """
    d = (1j * k) ** order
    if order % 2 == 1 and n % 2 == 0:
        d = d.copy()
        if r2c:
            d[-1] = 0.0
        else:
            d[n // 2] = 0.0
    return d
