"""Batched banded linear solvers.

TPU rebuild of the reference's banded kernel family — Sdma (diagonal), Tdma
(-2,0,2), Fdma (-2,0,2,4), PdmaPlus2 (-2..+4) — redesigned for XLA instead of
translated (SURVEY.md S2 rows `Sdma`..`PdmaPlus2`):

* One **generic banded-LU kernel** covers every offset family.  LU
  factorization (no pivoting; the Galerkin operators are safely conditioned)
  runs ONCE on the host in numpy f64 — including the whole batch of
  per-eigenvalue matrices of the tensor solver, fixing the reference's
  re-sweep-per-solve inefficiency (/root/reference/src/solver/poisson.rs:226-228).
* The device solve is a `lax.scan` forward/backward substitution whose batch
  dimension is all transverse lanes (the reference's rayon `par_for_each`
  becomes VPU-vectorized lanes).
* For static matrices there is also a **dense-inverse path** (a single MXU
  GEMM) — preferable for f32 TPU runs; the scan path wins for emulated f64.

Factors are stored as diagonals so a batch of M different matrices costs
O(M n (p+q)) memory, not O(M n^2).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def banded_lu_factor(dense: np.ndarray, p: int, q: int):
    """LU-factor (no pivoting) a banded matrix, batched over leading dims.

    ``dense``: (..., n, n) with lower bandwidth p, upper bandwidth q.
    Returns (lower, upper): lower (..., p, n) holds L[i, i-d] at [d-1, i];
    upper (..., q+1, n) holds U[i, i+d] at [d, i].
    """
    a = np.array(dense, dtype=np.float64, copy=True)
    n = a.shape[-1]
    for i in range(n - 1):
        piv = a[..., i, i]
        if np.any(np.abs(piv) < 1e-300):
            raise ZeroDivisionError(f"zero pivot at row {i}")
        jmax = min(i + p, n - 1)
        for j in range(i + 1, jmax + 1):
            m = a[..., j, i] / piv
            a[..., j, i] = m
            kmax = min(i + q, n - 1)
            a[..., j, i + 1 : kmax + 1] -= m[..., None] * a[..., i, i + 1 : kmax + 1]
    batch = a.shape[:-2]
    lower = np.zeros(batch + (p, n))
    upper = np.zeros(batch + (q + 1, n))
    idx = np.arange(n)
    for d in range(1, p + 1):
        lower[..., d - 1, d:] = a[..., idx[d:], idx[d:] - d]
    for d in range(0, q + 1):
        upper[..., d, : n - d] = a[..., idx[: n - d], idx[: n - d] + d]
    return lower, upper


class BandedSolver:
    """Precomputed banded LU; solves along a chosen axis of a device array.

    ``lower``/``upper`` may carry leading batch dims that broadcast against
    the rhs (e.g. one factored matrix per eigenvalue lane of the tensor
    solver).
    """

    def __init__(self, dense: np.ndarray, p: int, q: int, dtype=None):
        lower, upper = banded_lu_factor(dense, p, q)
        dt = dtype or jnp.zeros(0).dtype
        self.p, self.q = p, q
        self.n = dense.shape[-1]
        self.lower = jnp.asarray(lower, dtype=dt)
        self.upper = jnp.asarray(upper, dtype=dt)

    def solve(self, b, axis: int):
        """Solve A x = b along ``axis``.  Batch dims of the factors must align
        with the *leading* dims of ``b`` after moving ``axis`` last."""
        moved = jnp.moveaxis(b, axis, -1)
        out = _banded_solve_moved(self.lower, self.upper, self.p, self.q, moved)
        return jnp.moveaxis(out, -1, axis)


def _banded_solve_moved(lower, upper, p: int, q: int, b):
    """Forward/backward substitution along the last axis of ``b``."""
    n = b.shape[-1]

    if jnp.iscomplexobj(b):
        re = _banded_solve_moved(lower, upper, p, q, b.real)
        im = _banded_solve_moved(lower, upper, p, q, b.imag)
        return re + 1j * im

    # broadcast factors against b's batch dims: factors (..., p, n) -> index [..., d, i]
    batch_shape = jnp.broadcast_shapes(lower.shape[:-2], b.shape[:-1])
    bb = jnp.broadcast_to(b, batch_shape + (n,))
    low = jnp.broadcast_to(lower, batch_shape + lower.shape[-2:])
    upp = jnp.broadcast_to(upper, batch_shape + upper.shape[-2:])

    from ..parallel.mesh import active_mesh

    if active_mesh() is not None:
        return _banded_solve_while(low, upp, p, q, bb, n, batch_shape)

    # forward substitution: y_i = b_i - sum_d L[i, i-d] y_{i-d}
    def fwd_step(carry, xs):
        b_i, l_i = xs  # (batch,), (batch, p)
        acc = b_i
        for d in range(p):
            acc = acc - l_i[..., d] * carry[d]
        new_carry = (acc,) + carry[:-1] if p > 0 else carry
        return new_carry, acc

    carry0 = tuple(jnp.zeros(batch_shape, dtype=b.dtype) for _ in range(max(p, 1)))
    xs = (jnp.moveaxis(bb, -1, 0), jnp.moveaxis(low, -1, 0))
    _, y = jax.lax.scan(fwd_step, carry0, xs)
    # y: (n, batch)

    # backward substitution: x_i = (y_i - sum_d U[i, i+d] x_{i+d}) / U[i, i]
    def bwd_step(carry, xs):
        y_i, u_i = xs
        acc = y_i
        for d in range(1, q + 1):
            acc = acc - u_i[..., d] * carry[d - 1]
        x_i = acc / u_i[..., 0]
        new_carry = (x_i,) + carry[:-1] if q > 0 else carry
        return new_carry, x_i

    carry0 = tuple(jnp.zeros(batch_shape, dtype=b.dtype) for _ in range(max(q, 1)))
    xs = (y[::-1], jnp.moveaxis(upp, -1, 0)[::-1])
    _, x_rev = jax.lax.scan(bwd_step, carry0, xs)
    x = x_rev[::-1]
    return jnp.moveaxis(x, 0, -1)


def _banded_solve_while(low, upp, p: int, q: int, bb, n: int, batch_shape):
    """Substitutions as explicit ``while_loop``s with an int32 counter.

    Functionally identical to the scan path above; used under an active mesh
    because ``lax.scan``'s induction variable lowers to s64 in x64 mode, and
    XLA's SPMD partitioner mixes it with its own s32 shard offsets inside the
    ys ``dynamic_update_slice`` — the post-partitioning HLO verifier then
    rejects the program ("compare with different element types: s64[] and
    s32[]").  Explicit i32 indices keep every slice dtype consistent.  This
    path is not reverse-differentiable (``while_loop``); sharded autodiff
    through the implicit solves would need the scan path."""
    dt = bb.dtype
    batch_shape = tuple(batch_shape)
    bb_m = jnp.moveaxis(bb, -1, 0)  # (n, *batch)
    low_m = jnp.moveaxis(low, -1, 0)  # (n, *batch, p)
    upp_m = jnp.moveaxis(upp, -1, 0)  # (n, *batch, q+1)

    def zeros(k):
        return tuple(jnp.zeros(batch_shape, dtype=dt) for _ in range(max(k, 1)))

    # forward substitution: y_i = b_i - sum_d L[i, i-d] y_{i-d}
    def fwd_body(state):
        i, carry, y = state
        b_i = jax.lax.dynamic_index_in_dim(bb_m, i, 0, keepdims=False)
        l_i = jax.lax.dynamic_index_in_dim(low_m, i, 0, keepdims=False)
        acc = b_i
        for d in range(p):
            acc = acc - l_i[..., d] * carry[d]
        new_carry = (acc,) + carry[:-1] if p > 0 else carry
        return i + 1, new_carry, jax.lax.dynamic_update_index_in_dim(y, acc, i, 0)

    i0 = jnp.asarray(0, jnp.int32)
    buf = jnp.zeros((n,) + batch_shape, dtype=dt)
    _, _, y = jax.lax.while_loop(lambda s: s[0] < n, fwd_body, (i0, zeros(p), buf))

    # backward substitution: x_i = (y_i - sum_d U[i, i+d] x_{i+d}) / U[i, i]
    def bwd_body(state):
        i, carry, x = state
        y_i = jax.lax.dynamic_index_in_dim(y, i, 0, keepdims=False)
        u_i = jax.lax.dynamic_index_in_dim(upp_m, i, 0, keepdims=False)
        acc = y_i
        for d in range(1, q + 1):
            acc = acc - u_i[..., d] * carry[d - 1]
        x_i = acc / u_i[..., 0]
        new_carry = (x_i,) + carry[:-1] if q > 0 else carry
        return i - 1, new_carry, jax.lax.dynamic_update_index_in_dim(x, x_i, i, 0)

    iN = jnp.asarray(n - 1, jnp.int32)
    _, _, x = jax.lax.while_loop(lambda s: s[0] >= 0, bwd_body, (iN, zeros(q), buf))
    return jnp.moveaxis(x, 0, -1)


def _cached_inverse(dense: np.ndarray) -> np.ndarray:
    """Host matrix inversion with a best-effort disk cache (content-hash
    keyed, exact f64 round-trip) — the O(n^3) inversions are a visible part
    of flagship-size model build time."""
    import hashlib
    import os

    from .. import config

    n = dense.shape[-1]
    if n < 512:  # cheap; not worth the IO
        return np.linalg.inv(dense)
    key = hashlib.blake2b(dense.tobytes(), digest_size=12).hexdigest()
    path = os.path.join(config.host_cache_dir(), f"inv_{n}_{key}.npy")
    try:
        return np.load(path)
    except Exception:  # missing/corrupt/format-drift: recompute
        pass
    inv = np.linalg.inv(dense)
    config.host_cache_store(path, lambda tmp: np.save(tmp, inv))
    return inv


class DenseSolver:
    """Precomputed dense inverse; solve = one GEMM (MXU path for static
    well-conditioned systems).  Parity-preserving operators (every pure-
    Chebyshev Helmholtz pencil) have checkerboard-sparse inverses, which the
    FoldedMatrix wrapper turns into two half-size GEMMs (ops/folded.py); under
    ``sep=True`` the solve consumes/produces the parity-separated layout
    (contiguous block GEMMs, no gathers)."""

    def __init__(self, dense: np.ndarray, dtype=None, sep: bool = False):
        from .folded import FoldedMatrix

        dt = dtype or jnp.zeros(0).dtype
        inv = _cached_inverse(np.asarray(dense, dtype=np.float64))
        self._folded = FoldedMatrix(
            inv, lambda m: jnp.asarray(m, dtype=dt), sep_in=sep, sep_out=sep
        )

    def solve(self, b, axis: int):
        return self._folded.apply(b, axis)


class SepWrapped:
    """Adapter running a natural-order axis solver under a sep-layout axis:
    permutes sep -> natural around the solve.  Costs two explicit gathers —
    the correctness fallback for the sequential banded/Pallas paths (the TPU
    path uses the sep-aware dense inverse, which needs none)."""

    def __init__(self, solver, m: int):
        from .folded import parity_perm, parity_perm_inv

        self.solver = solver
        self._perm = jnp.asarray(parity_perm(m))
        self._inv = jnp.asarray(parity_perm_inv(m))

    def solve(self, b, axis: int):
        # sep position p holds natural index perm[p]: natural[i] = sep[inv[i]]
        nat = jnp.take(b, self._inv, axis=axis)
        out = self.solver.solve(nat, axis)
        return jnp.take(out, self._perm, axis=axis)


class DiagSolver:
    """Diagonal solve (the reference's Sdma, Fourier axes)."""

    def __init__(self, diag: np.ndarray, dtype=None):
        dt = dtype or jnp.zeros(0).dtype
        d = np.asarray(diag)
        if np.iscomplexobj(d) and np.allclose(d.imag, 0.0):
            d = d.real
        self.diag = jnp.asarray(d, dtype=dt)

    def solve(self, b, axis: int):
        d = self.diag
        shape = [1] * b.ndim
        shape[axis] = d.shape[0]
        return b / d.reshape(shape)
