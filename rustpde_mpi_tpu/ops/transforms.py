"""Device-side transform kernels (jax.numpy).

Two interchangeable execution paths for every transform, selected per-space:

* ``"fft"``  — FFT-based (XLA FFT).  O(n log n); the natural choice on CPU
  and for f32 TPU runs.
* ``"matmul"`` — dense transform matrices on the MXU.  O(n^2) flops but
  MXU-batched; competitive on TPU and exact in emulated f64 where the TPU
  FFT path is unavailable.

The Chebyshev transform is a DCT-I realised through an even extension +
rfft — the same mathematical object rustdct provides to the reference's
funspace dependency (SURVEY.md S2.2), rebuilt here on XLA.
All functions are shape-polymorphic over leading/trailing batch dims and
operate along ``axis``.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def _move(a, axis):
    return jnp.moveaxis(a, axis, -1)


def _unmove(a, axis):
    return jnp.moveaxis(a, -1, axis)


# ----------------------------------------------------------------------------
# DCT-I (Chebyshev at ascending CGL points)
# ----------------------------------------------------------------------------


def _dct1_real(u):
    """DCT-I along last axis of a real array: returns c with
    u_j = sum_k c_k cos(pi j k / N), N = n-1."""
    n = u.shape[-1]
    N = n - 1
    ext = jnp.concatenate([u, u[..., -2:0:-1]], axis=-1)  # even extension, len 2N
    R = jnp.fft.rfft(ext, axis=-1).real  # length N+1
    sigma = np.full(n, 1.0 / N)
    sigma[0] = sigma[-1] = 1.0 / (2.0 * N)
    return R * jnp.asarray(sigma, dtype=R.dtype)


def _idct1_real(c):
    """Inverse of :func:`_dct1_real` (synthesis) along last axis."""
    n = c.shape[-1]
    N = n - 1
    H = c * jnp.asarray(
        np.concatenate([[2.0 * N], np.full(n - 2, float(N)), [2.0 * N]]),
        dtype=c.dtype,
    )
    v = jnp.fft.irfft(H.astype(jnp.complex128 if c.dtype == jnp.float64 else jnp.complex64), n=2 * N, axis=-1)
    return v[..., :n]


def _complex_map(fn, a):
    if jnp.iscomplexobj(a):
        return fn(a.real) + 1j * fn(a.imag)
    return fn(a)


def cheb_forward_fft(u, axis: int):
    """Physical values at ascending CGL points -> Chebyshev coefficients."""
    x = _move(u, axis)
    c = _complex_map(_dct1_real, x)
    n = x.shape[-1]
    signs = jnp.asarray((-1.0) ** np.arange(n), dtype=c.real.dtype)
    return _unmove(c * signs, axis)


def cheb_backward_fft(uh, axis: int):
    """Chebyshev coefficients -> physical values at ascending CGL points."""
    x = _move(uh, axis)
    n = x.shape[-1]
    signs = jnp.asarray((-1.0) ** np.arange(n), dtype=x.real.dtype)
    u = _complex_map(_idct1_real, x * signs)
    return _unmove(u, axis)


# ----------------------------------------------------------------------------
# Fourier r2c / c2c
# ----------------------------------------------------------------------------


def fourier_r2c_forward_fft(u, axis: int):
    x = _move(u, axis)
    n = x.shape[-1]
    return _unmove(jnp.fft.rfft(x, axis=-1) / n, axis)


def fourier_r2c_backward_fft(uh, axis: int, n: int):
    x = _move(uh, axis)
    return _unmove(jnp.fft.irfft(x * n, n=n, axis=-1), axis)


def fourier_c2c_forward_fft(u, axis: int):
    x = _move(u, axis)
    n = x.shape[-1]
    return _unmove(jnp.fft.fft(x, axis=-1) / n, axis)


def fourier_c2c_backward_fft(uh, axis: int, n: int):
    x = _move(uh, axis)
    return _unmove(jnp.fft.ifft(x * n, axis=-1), axis)


# ----------------------------------------------------------------------------
# Chebyshev coefficient-space derivative via parity-split reversed cumsums
# ----------------------------------------------------------------------------


def _interleave0(even, odd, n: int):
    """Rows 0,2,4,.. from ``even`` and 1,3,5,.. from ``odd`` along axis 0."""
    batch = even.shape[1:]
    if n % 2 == 0:
        return jnp.stack([even, odd], axis=1).reshape((n,) + batch)
    h_o = odd.shape[0]
    body = jnp.stack([even[:h_o], odd], axis=1).reshape((2 * h_o,) + batch)
    return jnp.concatenate([body, even[h_o:]], axis=0)


def cheb_derivative(c, order: int, axis: int):
    """(d/dx)^order on Chebyshev coefficients via the coefficient recurrence,
    O(n) work per lane instead of the O(n^2) upper-triangular GEMM.

    The dense operator (ops/chebyshev.diff_matrix) is
    ``(Dc)_k = 2 * sum_{p>k, p-k odd} p c_p`` (halved at k=0) — each output
    is a strictly-upper sum over the opposite index parity, i.e. two
    parity-split reversed cumulative sums of ``p * c_p``.  Same reduction as
    the GEMM, reassociated; agreement is at machine epsilon
    (tests/test_bases.py)."""
    x = jnp.moveaxis(c, axis, 0)
    n = x.shape[0]
    rdt = x.real.dtype if jnp.iscomplexobj(x) else x.dtype
    j = jnp.arange(n, dtype=rdt).reshape((n,) + (1,) * (x.ndim - 1))
    ne = (n + 1) // 2
    no = n // 2
    for _ in range(order):
        w = x * j
        rev_e = jnp.cumsum(jnp.flip(w[0::2], 0), axis=0)[::-1]  # sum_{p even >= k}
        rev_o = jnp.cumsum(jnp.flip(w[1::2], 0), axis=0)[::-1]  # sum_{p odd >= k}
        # even outputs k=2t: odd p > k  <->  odd-index t' >= t
        out_e = 2.0 * rev_o
        if ne > no:  # odd n: top even mode has an empty sum
            out_e = jnp.concatenate([out_e, jnp.zeros_like(out_e[:1])], axis=0)
        # odd outputs k=2t+1: even p > k  <->  even-index t' >= t+1
        out_o = 2.0 * rev_e[1:]
        if no > ne - 1:  # even n: top odd mode has an empty sum
            out_o = jnp.concatenate([out_o, jnp.zeros_like(out_o[:1])], axis=0)
        x = _interleave0(out_e, out_o, n)
        x = x.at[0].multiply(0.5)
    return jnp.moveaxis(x, 0, axis)


def cheb_derivative_sep(c, order: int, axis: int):
    """:func:`cheb_derivative` for coefficients in the parity-separated
    layout (ops/folded.py): the parity split the recurrence needs is already
    the storage order, so the strided gathers and the output interleave
    become contiguous slices and a concat."""
    x = jnp.moveaxis(c, axis, 0)
    n = x.shape[0]
    rdt = x.real.dtype if jnp.iscomplexobj(x) else x.dtype
    ne = (n + 1) // 2
    no = n // 2
    shape_e = (ne,) + (1,) * (x.ndim - 1)
    shape_o = (no,) + (1,) * (x.ndim - 1)
    j_e = (2.0 * jnp.arange(ne, dtype=rdt)).reshape(shape_e)
    j_o = (2.0 * jnp.arange(no, dtype=rdt) + 1.0).reshape(shape_o)
    for _ in range(order):
        w_e = x[:ne] * j_e
        w_o = x[ne:] * j_o
        rev_e = jnp.cumsum(jnp.flip(w_e, 0), axis=0)[::-1]  # sum_{p even >= k}
        rev_o = jnp.cumsum(jnp.flip(w_o, 0), axis=0)[::-1]  # sum_{p odd >= k}
        out_e = 2.0 * rev_o
        if ne > no:  # odd n: top even mode has an empty sum
            out_e = jnp.concatenate([out_e, jnp.zeros_like(out_e[:1])], axis=0)
        out_o = 2.0 * rev_e[1:]
        if no > ne - 1:  # even n: top odd mode has an empty sum
            out_o = jnp.concatenate([out_o, jnp.zeros_like(out_o[:1])], axis=0)
        x = jnp.concatenate([out_e, out_o], axis=0)
        x = x.at[0].multiply(0.5)  # natural mode 0 sits at sep position 0
    return jnp.moveaxis(x, 0, axis)


# ----------------------------------------------------------------------------
# matmul application (MXU path); mat is a host numpy or jnp constant
# ----------------------------------------------------------------------------


def apply_matrix(mat, a, axis: int):
    """Apply ``mat`` along ``axis`` of ``a``: out[..., i, ...] = mat[i, j] a[..., j, ...]."""
    mat = jnp.asarray(mat)
    if jnp.iscomplexobj(a) and not jnp.iscomplexobj(mat):
        mat = mat.astype(a.dtype)
    moved = jnp.moveaxis(a, axis, 0)
    out = jnp.tensordot(mat, moved, axes=([1], [0]))
    return jnp.moveaxis(out, 0, axis)


def apply_diag(d, a, axis: int):
    """Multiply by a diagonal along ``axis``."""
    d = jnp.asarray(d)
    shape = [1] * a.ndim
    shape[axis] = d.shape[0]
    return a * d.reshape(shape)
