"""Spectral bases and 2-D tensor-product spaces.

TPU-native rebuild of the basis layer the reference re-exports from the
external ``funspace`` crate (/root/reference/src/bases.rs:11-19; full contract
reconstructed in SURVEY.md S2.2).  Public vocabulary matches the reference:

    chebyshev(n), cheb_dirichlet(n), cheb_neumann(n),
    cheb_dirichlet_neumann(n), fourier_r2c(n), fourier_c2c(n), Space2

Design (idiomatic JAX, not a port): every base precomputes small dense/banded
operator matrices on the host in numpy f64 — stencil S (composite -> ortho),
Galerkin projection P (ortho -> composite), coefficient-space derivatives,
the Chebyshev quasi-inverse B2 — and the device work is FFTs/DCTs or batched
matmuls over those constants.  No in-place mutation anywhere; fields are
plain arrays.
"""

from __future__ import annotations

import enum
from functools import cached_property

import jax
import jax.numpy as jnp
import numpy as np

from . import config
from .ops import chebyshev as chb
from .ops import fourier as fou
from .ops import fourstep
from .ops import transforms as tr
from .ops.folded import FoldedMatrix


class BaseKind(enum.Enum):
    CHEBYSHEV = "chebyshev"
    CHEB_DIRICHLET = "cheb_dirichlet"
    CHEB_NEUMANN = "cheb_neumann"
    CHEB_DIRICHLET_NEUMANN = "cheb_dirichlet_neumann"
    FOURIER_R2C = "fourier_r2c"
    FOURIER_C2C = "fourier_c2c"
    FOURIER_R2C_SPLIT = "fourier_r2c_split"

    @property
    def is_chebyshev(self) -> bool:
        return self in (
            BaseKind.CHEBYSHEV,
            BaseKind.CHEB_DIRICHLET,
            BaseKind.CHEB_NEUMANN,
            BaseKind.CHEB_DIRICHLET_NEUMANN,
        )

    @property
    def is_periodic(self) -> bool:
        return self in (
            BaseKind.FOURIER_R2C,
            BaseKind.FOURIER_C2C,
            BaseKind.FOURIER_R2C_SPLIT,
        )

    @property
    def is_split(self) -> bool:
        return self == BaseKind.FOURIER_R2C_SPLIT


_FAST_DERIV = config.env_get("RUSTPDE_FAST_DERIV", "auto")
_FAST_DERIV_MIN = int(config.env_get("RUSTPDE_FAST_DERIV_MIN", "2048"))


def _fast_deriv_enabled(n: int, sep: bool = False) -> bool:
    """Chebyshev derivatives via the parity-cumsum recurrence
    (ops/transforms.cheb_derivative) instead of dense triangular GEMMs.
    ``RUSTPDE_FAST_DERIV``: "auto" (default), "1" (always), "0" (never).
    Auto is measured on the v5e (scripts/profile_step.py + /tmp A/B runs,
    round 3): f32 cumsum 0.22 vs GEMM 0.46 ms at 2049 but 0.11 vs 0.07 at
    1025 (dispatch/bandwidth bound), and in *emulated f64* the cumsum's scan
    ops are 2-5x slower than the MXU GEMM at every tested size — so the
    recurrence engages only for f32 at n >= 2048.  Under the parity-
    separated layout the GEMM gradient is gather-free block MXU work and
    auto never engages: measured at the 2049^2 step (round 4), cumsum
    18.7 ms vs GEMM 16.4 ms."""
    if _FAST_DERIV == "0":
        return False
    if _FAST_DERIV == "1":
        return True
    return n >= _FAST_DERIV_MIN and not config.X64 and not sep


def _dev(mat: np.ndarray):
    """Host f64 matrix -> device constant in the configured precision.

    ``ensure_compile_time_eval`` keeps the constant concrete even when the
    first (lazy) materialization happens inside a jit trace — otherwise the
    cached value would be a leaked tracer."""
    import jax

    with jax.ensure_compile_time_eval():
        if np.iscomplexobj(mat):
            return jnp.asarray(mat.astype(config.complex_dtype()))
        return jnp.asarray(mat.astype(config.real_dtype()))


class Base:
    """One spectral base along one axis.

    ``n``: physical grid size; ``m``: number of spectral modes
    (n-2 for composite Galerkin bases, n//2+1 for r2c, else n).
    """

    def __init__(self, kind: BaseKind, n: int):
        self.kind = kind
        self.n = n
        self._diff_cache: dict = {}
        self._grad_cache: dict = {}
        self._grad_dev_cache: dict = {}
        # fused projection-gradient device operators (fused_projection_gradient):
        # living on the instance ties their lifetime to the weak _BASE_CACHE
        self._proj_grad_cache: dict = {}
        if kind in (BaseKind.CHEBYSHEV, BaseKind.FOURIER_C2C):
            self.m = n
        elif kind == BaseKind.FOURIER_R2C:
            self.m = n // 2 + 1
        else:
            self.m = n - 2

    def __repr__(self):
        return f"Base({self.kind.value}, n={self.n})"

    # -- grid ---------------------------------------------------------------

    @cached_property
    def points(self) -> np.ndarray:
        if self.kind.is_chebyshev:
            return chb.cgl_points(self.n)
        return fou.fourier_points(self.n)

    @property
    def is_periodic(self) -> bool:
        return self.kind.is_periodic

    @property
    def spectral_is_complex(self) -> bool:
        return self.kind.is_periodic

    # -- host operator matrices (funspace contract, SURVEY.md S2.2) ---------

    @cached_property
    def stencil(self) -> np.ndarray:
        """S, (n x m): composite coefficients -> orthogonal coefficients."""
        if self.kind == BaseKind.CHEBYSHEV:
            return chb.stencil_chebyshev(self.n)
        if self.kind == BaseKind.CHEB_DIRICHLET:
            return chb.stencil_dirichlet(self.n)
        if self.kind == BaseKind.CHEB_NEUMANN:
            return chb.stencil_neumann(self.n)
        if self.kind == BaseKind.CHEB_DIRICHLET_NEUMANN:
            return chb.stencil_dirichlet_neumann(self.n)
        return np.eye(self.m)

    @cached_property
    def projection(self) -> np.ndarray:
        """P, (m x n): weighted Galerkin projection ortho -> composite
        (funspace `from_ortho`)."""
        if self.kind.is_chebyshev:
            return chb.projection_matrix(self.stencil)
        return np.eye(self.m)

    @cached_property
    def wavenumbers(self) -> np.ndarray:
        if self.kind == BaseKind.FOURIER_R2C:
            return fou.wavenumbers_r2c(self.n)
        if self.kind == BaseKind.FOURIER_C2C:
            return fou.wavenumbers_c2c(self.n)
        raise ValueError("wavenumbers only defined for Fourier bases")

    def diff_ortho(self, order: int) -> np.ndarray:
        """Derivative operator in the *orthogonal* coefficient space.

        Chebyshev: dense (n x n) upper-triangular recurrence matrix.
        Fourier: returned as a diagonal (1-D array) of (i k)^order.
        """
        if order not in self._diff_cache:
            if self.kind.is_chebyshev:
                self._diff_cache[order] = chb.diff_matrix(self.n, order)
            else:
                self._diff_cache[order] = fou.diff_diag(
                    self.wavenumbers, order, self.n, self.kind == BaseKind.FOURIER_R2C
                )
        return self._diff_cache[order]

    def gradient_matrix(self, order: int) -> np.ndarray:
        """D^order @ S: composite coefficients -> ortho derivative coeffs.

        For Fourier bases this is diagonal and returned 1-D.
        """
        if order not in self._grad_cache:
            if self.kind.is_chebyshev:
                self._grad_cache[order] = self.diff_ortho(order) @ self.stencil
            else:
                self._grad_cache[order] = self.diff_ortho(order)
        return self._grad_cache[order]

    # funspace operator-matrix contract used by the solver layer
    # (/root/reference/src/field.rs:195-249)

    def mass(self) -> np.ndarray:
        """The stencil S (identity for orthogonal/Fourier bases)."""
        return self.stencil

    def laplace(self) -> np.ndarray:
        """D2 in ortho coefficient space (dense for Chebyshev, diag for Fourier)."""
        if self.kind.is_chebyshev:
            return self.diff_ortho(2)
        return np.diag(-(self.wavenumbers**2))

    def laplace_inv(self) -> np.ndarray:
        """Chebyshev quasi-inverse B2 of D2 (rows 0,1 zero)."""
        if not self.kind.is_chebyshev:
            raise ValueError("laplace_inv only defined for Chebyshev bases")
        return chb.quasi_inverse_b2(self.n)

    def laplace_inv_eye(self) -> np.ndarray:
        """(n-2) x n restriction selecting rows 2.. (B2 @ D2 restricted = I)."""
        if not self.kind.is_chebyshev:
            raise ValueError("laplace_inv_eye only defined for Chebyshev bases")
        return chb.restricted_eye(self.n)

    # -- device transforms --------------------------------------------------

    # transform/operator matrices are wrapped in FoldedMatrix: the even/odd
    # parity every pure-Chebyshev operator carries (the reference's stride-2
    # structure, solver/tdma.rs:49-82) halves the GEMM flops; matrices
    # without the structure (mixed-BC bases) automatically run the plain GEMM

    @cached_property
    def _sep_cache(self) -> dict:
        """Device matrices for the parity-separated spectral layout
        (ops/folded.py sep classes), cached per shared Base instance."""
        return {}

    def _sep_dev(self, key) -> FoldedMatrix:
        """Sep-layout counterpart of the folded device matrices.  ``key``:
        "fwd" | "bwd" | "stencil" | "proj" | "synthesis" | "fwd_cut" |
        ("grad", order) | ("bwd_grad", order); appending "fast" to a
        synthesis-type key — ("bwd", "fast") / ("bwd_grad", order, "fast") —
        selects the 3-pass variant below.

        "fast" synthesis variants: the DNS step's convection syntheses
        (spectral -> physical values feeding the dealiased products) run the
        3-pass bf16 MXU mode in f32: measured on the v5e at Ra=1e9, step
        rate +17-18% (1025^2 -> ~667 steps/s, 2049^2 -> ~93), shadow drift
        vs f64 1.6e-5 (gate 1e-2), and a 4096-step random-IC trajectory
        statistically indistinguishable from "highest" (Re to 4 digits, same
        div decay).  ONLY the explicit fast keys downgrade — general
        backward()/get_field/observables/IO keep full precision (a global
        default corrupted the standalone-Poisson MMS readback to 3.7e-2).
        The round-2 NaN came from GLOBAL "high"; solves and analysis
        forwards always stay "highest".  RUSTPDE_SYNTH_PRECISION=highest
        disables (build-time gate); f64 never downgrades."""
        if not self.kind.is_chebyshev:
            raise ValueError("sep layout is defined for Chebyshev-family bases only")
        cache = self._sep_cache
        fast = isinstance(key, tuple) and key[-1] == "fast"
        base_key = (key[0] if len(key) == 2 else key[:-1]) if fast else key
        if key in cache:
            return cache[key]
        synth_prec = None
        cast = None
        if fast and not config.X64:
            if base_key == "fwd_cut":
                # the dealiased convection FORWARD has its own knob, default
                # OFF (highest): unlike the syntheses it writes the solve
                # rhs directly, so the downgrade ships only once measured
                # on-chip + shadow-gated (RUSTPDE_FWD_PRECISION=high to A/B)
                env = config.env_get("RUSTPDE_FWD_PRECISION", "highest")
            else:
                env = config.env_get("RUSTPDE_SYNTH_PRECISION", "high")
            synth_prec = None if env in ("", "highest") else env
        elif fast and config.X64 and config.env_get("RUSTPDE_F64_HYBRID") == "1":
            # f64-hybrid (SURVEY S7 / VERDICT r4 next #3b): the convection
            # transforms — the step's fast keys, nothing else — run as f32
            # GEMMs (device matrices stored f32, inputs cast in, outputs cast
            # back to f64), dodging the ~16x f64 MXU emulation on the
            # dominant transform flops while every solve, analysis forward,
            # observable and IO stays full f64.  Opt-in; validated against
            # the 129^2 parity trajectory + shadow gate before any default
            # flip.
            cast = np.float32
        if fast and synth_prec is None and cast is None:
            # no downgrade requested (f64 without hybrid, or
            # RUSTPDE_*_PRECISION=highest): the fast key is byte-identical to
            # the base entry — alias it instead of re-detecting and
            # double-placing the device matrix
            cache[key] = self._sep_dev(base_key)
            return cache[key]
        if base_key == "fwd":
            fm = FoldedMatrix(
                self.projection @ chb.analysis_matrix(self.n), _dev, sep_out=True, cast=cast
            )
        elif base_key == "bwd":
            fm = FoldedMatrix(
                chb.synthesis_matrix(self.n) @ self.stencil, _dev, sep_in=True, cast=cast
            )
        elif base_key == "stencil":
            fm = FoldedMatrix(self.stencil, _dev, sep_in=True, sep_out=True, cast=cast)
        elif base_key == "proj":
            fm = FoldedMatrix(self.projection, _dev, sep_in=True, sep_out=True, cast=cast)
        elif base_key == "synthesis":
            fm = FoldedMatrix(chb.synthesis_matrix(self.n), _dev, sep_in=True, cast=cast)
        elif base_key == "fwd_cut":
            # forward with the 2/3-rule dealias folded in: the zeroed output
            # modes are dropped from the GEMM (keep_rows), so the dealiased
            # forward costs 2/3 flops and no mask multiply
            fm = FoldedMatrix(
                self.projection @ chb.analysis_matrix(self.n),
                _dev,
                sep_out=True,
                keep_rows=self.m * 2 // 3,
                cast=cast,
            )
        elif isinstance(base_key, tuple) and base_key[0] == "bwd_grad":
            # synthesis-of-derivative fusion: physical values of the order-th
            # derivative straight from composite coefficients — one GEMM
            # instead of gradient + synthesis (the odd-order product carries
            # the sign-shifted synthesis symmetry, _SynthesisSep sign=-1)
            fm = FoldedMatrix(
                chb.synthesis_matrix(self.n) @ self.gradient_matrix(base_key[1]),
                _dev,
                sep_in=True,
                cast=cast,
            )
        else:  # ("grad", order)
            fm = FoldedMatrix(
                self.gradient_matrix(base_key[1]),
                _dev,
                sep_in=True,
                sep_out=True,
                cast=cast,
            )
        # only impls that declare the hook honor an override (the
        # _SynthesisSep family); unstructured _Plain fallbacks stay at
        # session precision rather than silently carrying a dead attr
        fm.set_precision(synth_prec)
        cache[key] = fm
        return cache[key]

    @cached_property
    def _fwd_matrix(self) -> FoldedMatrix:
        if self.kind.is_chebyshev:
            return FoldedMatrix(self.projection @ chb.analysis_matrix(self.n), _dev)
        raise ValueError("matmul transform only for Chebyshev bases")

    @cached_property
    def _bwd_matrix(self) -> FoldedMatrix:
        if self.kind.is_chebyshev:
            return FoldedMatrix(chb.synthesis_matrix(self.n) @ self.stencil, _dev)
        raise ValueError("matmul transform only for Chebyshev bases")

    @cached_property
    def _stencil_dev(self) -> FoldedMatrix:
        return FoldedMatrix(self.stencil, _dev)

    @cached_property
    def _proj_dev(self) -> FoldedMatrix:
        return FoldedMatrix(self.projection, _dev)

    @cached_property
    def _synthesis_dev(self) -> FoldedMatrix:
        return FoldedMatrix(chb.synthesis_matrix(self.n), _dev)

    # -- four-step fast DCT path (ops/fourstep.py) ---------------------------
    #
    # Both Chebyshev transform directions are diagonal scalings around the
    # size-(N+1) cosine kernel, which factors through a length-2N four-step
    # real DFT: O(n^1.5) MXU flops instead of the O(n^2) dense matrices the
    # funspace reference pays rustdct to avoid (SURVEY.md S2.2).

    @cached_property
    def _dct_plan(self):
        N = self.n - 1
        if N < 2 or not fourstep.enabled(2 * N, "dct"):
            return None
        return fourstep.Dct1Plan(self.n, _dev)

    @cached_property
    def _dct_diags(self):
        """(sigma*(-1)^k analysis row scale, (-1)^k signs) device constants;
        reshaped for axis-0 broadcasting at the call sites."""
        n = self.n
        N = n - 1
        sigma = np.full(n, 1.0 / N)
        sigma[0] = sigma[-1] = 1.0 / (2.0 * N)
        signs = (-1.0) ** np.arange(n)
        return _dev(sigma * signs), _dev(signs)

    def _fast_analysis(self, v, axis: int):
        """uhat = analysis_matrix @ u == sigma*(-1)^k * Re(rfft(ext(u)))."""
        x = jnp.moveaxis(v, axis, 0)
        row_scale, _ = self._dct_diags
        out = self._dct_plan.apply(x)
        out = out * row_scale.reshape((self.n,) + (1,) * (out.ndim - 1)).astype(
            out.real.dtype
        )
        return jnp.moveaxis(out, 0, axis)

    def _fast_synthesis(self, c, axis: int):
        """u = synthesis_matrix @ c via the same cosine core:
        with g = (-1)^k * c,  u_j = 0.5*core(g)_j + 0.5*(g_0 + (-1)^j g_N)."""
        x = jnp.moveaxis(c, axis, 0)
        _, signs = self._dct_diags
        sg = signs.reshape((self.n,) + (1,) * (x.ndim - 1)).astype(x.real.dtype)
        g = x * sg
        out = 0.5 * self._dct_plan.apply(g) + 0.5 * (g[0][None] + sg * g[-1][None])
        return jnp.moveaxis(out, 0, axis)

    def _gradient_dev(self, order: int):
        """Chebyshev: FoldedMatrix; Fourier: cached device diagonal."""
        if order not in self._grad_dev_cache:
            mat = self.gradient_matrix(order)
            self._grad_dev_cache[order] = (
                FoldedMatrix(mat, _dev) if self.kind.is_chebyshev else _dev(mat)
            )
        return self._grad_dev_cache[order]

    def forward(self, v, axis: int, method: str = "fft", sep: bool = False):
        """Physical -> (composite) spectral along ``axis``."""
        if self.kind.is_chebyshev:
            if sep:
                # sep layout: matmul only (the fast DCT/FFT cores produce the
                # natural interleaved order)
                return self._sep_dev("fwd").apply(v, axis)
            if method == "matmul":
                if self.kind == BaseKind.CHEBYSHEV and self._dct_plan is not None:
                    # pure base: projection is the identity, so the whole
                    # forward is the fast DCT core (composite bases keep the
                    # fused dense P @ F GEMM — P is dense-checkerboard, so
                    # splitting it out would not reduce flops)
                    return self._fast_analysis(v, axis)
                return self._fwd_matrix.apply(v, axis)
            c = tr.cheb_forward_fft(v, axis)
            return self.from_ortho(c, axis)
        if self.kind == BaseKind.FOURIER_R2C:
            return tr.fourier_r2c_forward_fft(v, axis)
        return tr.fourier_c2c_forward_fft(v, axis)

    def backward(self, vhat, axis: int, method: str = "fft", sep: bool = False):
        """(Composite) spectral -> physical along ``axis``."""
        if self.kind.is_chebyshev:
            if sep:
                return self._sep_dev("bwd").apply(vhat, axis)
            if method == "matmul":
                if self._dct_plan is not None:
                    # banded stencil + fast DCT synthesis — cheaper than the
                    # fused dense synthesis @ S GEMM in every composite case
                    return self._fast_synthesis(self.to_ortho(vhat, axis), axis)
                return self._bwd_matrix.apply(vhat, axis)
            return tr.cheb_backward_fft(self.to_ortho(vhat, axis), axis)
        if self.kind == BaseKind.FOURIER_R2C:
            return tr.fourier_r2c_backward_fft(vhat, axis, self.n)
        return tr.fourier_c2c_backward_fft(vhat, axis, self.n)

    def backward_ortho(self, c, axis: int, method: str = "fft", sep: bool = False):
        """Synthesize physical values from *orthogonal* coefficients along
        ``axis`` (no composite cast — gradients already live in ortho space)."""
        if self.kind.is_chebyshev:
            if sep:
                return self._sep_dev("synthesis").apply(c, axis)
            if method == "matmul":
                if self._dct_plan is not None:
                    return self._fast_synthesis(c, axis)
                return self._synthesis_dev.apply(c, axis)
            return tr.cheb_backward_fft(c, axis)
        if self.kind == BaseKind.FOURIER_R2C:
            return tr.fourier_r2c_backward_fft(c, axis, self.n)
        return tr.fourier_c2c_backward_fft(c, axis, self.n)

    def to_ortho(self, vhat, axis: int, sep: bool = False):
        if self.kind in (BaseKind.CHEBYSHEV, BaseKind.FOURIER_R2C, BaseKind.FOURIER_C2C):
            return vhat
        if sep:
            return self._sep_dev("stencil").apply(vhat, axis)
        return self._stencil_dev.apply(vhat, axis)

    def from_ortho(self, c, axis: int, sep: bool = False):
        if self.kind in (BaseKind.CHEBYSHEV, BaseKind.FOURIER_R2C, BaseKind.FOURIER_C2C):
            return c
        if sep:
            return self._sep_dev("proj").apply(c, axis)
        return self._proj_dev.apply(c, axis)

    def gradient(self, vhat, order: int, axis: int, sep: bool = False):
        """Composite spectral -> ortho-space derivative coefficients."""
        if order == 0:
            return self.to_ortho(vhat, axis, sep)
        if self.kind.is_chebyshev:
            if sep:
                if _fast_deriv_enabled(self.n, sep=True):
                    # the recurrence's parity split IS the sep storage order
                    return tr.cheb_derivative_sep(
                        self.to_ortho(vhat, axis, sep=True), order, axis
                    )
                return self._sep_dev(("grad", order)).apply(vhat, axis)
            if _fast_deriv_enabled(self.n):
                # banded stencil + parity-cumsum recurrence: O(n) per lane
                # instead of the dense upper-triangular D^order @ S GEMM
                return tr.cheb_derivative(self.to_ortho(vhat, axis), order, axis)
            return self._gradient_dev(order).apply(vhat, axis)
        return tr.apply_diag(self._gradient_dev(order), vhat, axis)

    def dealias_cut(self) -> np.ndarray:
        """1-D 2/3-rule mask over this base's spectral rows
        (/root/reference/src/navier_stokes/functions.rs:72-82); the single
        home of the cutoff convention for every space class."""
        cut = np.ones(self.m)
        cut[self.m * 2 // 3 :] = 0.0
        return cut

    def axis_operator(self, key, sep: bool = False):
        """Stable public accessor for the dense per-axis operator matrix in
        this base's *storage layout* — what the fused-kernel builders
        (ops/pallas_conv.py, the manual-sharding conv region) consume
        instead of reaching into the private folding internals.  ``key``
        uses the `_sep_dev` vocabulary: ``"fwd" | "fwd_cut" | "bwd" |
        "synthesis" | "stencil" | "proj" | ("bwd_grad", order) |
        ("grad", order)``.  Returns an
        :class:`~rustpde_mpi_tpu.ops.folded.AxisOperator`; applying its
        ``matrix`` with one plain GEMM reproduces the folded/sep device
        apply exactly up to floating-point reassociation.

        Periodic r2c bases return the SPLIT Re/Im real-matrix form (the only
        dense-matrix form of the r2c transform); for the complex
        representation the caller converts at the boundary
        (``[Re(c); Im(c)]`` stacking, bases.SplitFourierBase.to_complex)."""
        from .ops.folded import AxisOperator, dense_operator, kept_storage_rows

        if self.kind.is_periodic:
            if self.kind == BaseKind.FOURIER_C2C:
                raise ValueError("axis_operator is not defined for c2c bases")
            if sep:
                raise ValueError("sep layout is not defined for Fourier axes")
            m2 = 2 * (self.n // 2 + 1)
            if key == "fwd":
                return AxisOperator(fou.split_forward_matrix(self.n), (False, False), None, None)
            if key == "fwd_cut":
                # per-complex-mode 2/3 cut applied to the Re and Im blocks
                # alike (SplitFourierBase.dealias_cut — also the convention
                # the complex base's dealias_mask follows per mode)
                mc = self.n // 2 + 1
                cut = np.ones(m2)
                cut[mc * 2 // 3 : mc] = 0.0
                cut[mc + mc * 2 // 3 :] = 0.0
                mat = fou.split_forward_matrix(self.n) * cut[:, None]
                return AxisOperator(mat, (False, False), mc * 2 // 3, np.where(cut > 0)[0])
            if key in ("bwd", "synthesis"):
                return AxisOperator(fou.split_backward_matrix(self.n), (False, False), None, None)
            if isinstance(key, tuple) and key[0] == "bwd_grad":
                mat = fou.split_backward_matrix(self.n) @ fou.split_diff_matrix(self.n, key[1])
                return AxisOperator(mat, (False, False), None, None)
            if isinstance(key, tuple) and key[0] == "grad":
                return AxisOperator(fou.split_diff_matrix(self.n, key[1]), (False, False), None, None)
            if key in ("stencil", "proj"):
                return AxisOperator(np.eye(m2), (False, False), None, None)
            raise ValueError(f"unknown axis_operator key {key!r}")
        if not self.kind.is_chebyshev:  # pragma: no cover - no other kinds
            raise ValueError(f"axis_operator undefined for {self.kind}")
        keep = None
        if key == "fwd":
            mat, sin, sout = self.projection @ chb.analysis_matrix(self.n), False, sep
        elif key == "fwd_cut":
            mat, sin, sout = self.projection @ chb.analysis_matrix(self.n), False, sep
            keep = self.m * 2 // 3
        elif key == "bwd":
            mat, sin, sout = chb.synthesis_matrix(self.n) @ self.stencil, sep, False
        elif key == "synthesis":
            mat, sin, sout = chb.synthesis_matrix(self.n), sep, False
        elif key == "stencil":
            mat, sin, sout = self.stencil, sep, sep
        elif key == "proj":
            mat, sin, sout = self.projection, sep, sep
        elif isinstance(key, tuple) and key[0] == "bwd_grad":
            mat = chb.synthesis_matrix(self.n) @ self.gradient_matrix(key[1])
            sin, sout = sep, False
        elif isinstance(key, tuple) and key[0] == "grad":
            mat, sin, sout = self.gradient_matrix(key[1]), sep, sep
        else:
            raise ValueError(f"unknown axis_operator key {key!r}")
        kept = None if keep is None else kept_storage_rows(mat.shape[0], keep, sout)
        return AxisOperator(
            dense_operator(mat, sep_in=sin, sep_out=sout, keep_rows=keep),
            (sin, sout),
            keep,
            kept,
        )


class SplitFourierBase(Base):
    """Real r2c Fourier base in the split Re/Im representation: spectral
    arrays are real with 2m rows, ``[Re(c_0..c_{m-1}); Im(c_0..c_{m-1})]``,
    m = n//2+1.

    This is the TPU form of ``fourier_r2c`` (the axon backend implements
    neither complex dtypes nor FFT): transforms are single real MXU matmuls,
    the (ik)^order spectral derivative becomes a block rotation of the Re/Im
    halves, and diagonal solver ingredients carry each eigenvalue twice.
    Numerically identical to the complex base — tested block-for-block
    (tests/test_split.py)."""

    def __init__(self, n: int):
        super().__init__(BaseKind.FOURIER_R2C_SPLIT, n)
        self.m_complex = n // 2 + 1
        self.m = 2 * self.m_complex

    @cached_property
    def wavenumbers(self) -> np.ndarray:  # type: ignore[override]
        """Each mode's k, duplicated across the Re and Im blocks — so the
        diagonal operator algebra (-k^2 laplacians, modal solves) applies to
        the split representation unchanged."""
        k = fou.wavenumbers_r2c(self.n)
        return np.concatenate([k, k])

    @property
    def spectral_is_complex(self) -> bool:  # type: ignore[override]
        return False

    # (operator matrices — mass/laplace/stencil/projection — inherit from
    # Base: its non-Chebyshev branches already use the overridden duplicated
    # wavenumbers and identity stencils)

    # -- transforms ----------------------------------------------------------

    @cached_property
    def _fwd_dev(self) -> FoldedMatrix:
        # circular-reflection fold (cos rows symmetric / sin rows antisym
        # under j -> n-j) halves the split-transform GEMM (ops/folded.py)
        return FoldedMatrix(fou.split_forward_matrix(self.n), _dev)

    @cached_property
    def _bwd_dev(self) -> FoldedMatrix:
        return FoldedMatrix(fou.split_backward_matrix(self.n), _dev)

    @cached_property
    def _rfft_plan(self):
        if not fourstep.enabled(self.n, "dft"):
            return None
        return fourstep.RfftPlan(self.n, _dev)

    @cached_property
    def _irfft_plan(self):
        if not fourstep.enabled(self.n, "dft"):
            return None
        return fourstep.IrfftPlan(self.n, _dev)

    def forward(self, v, axis: int, method: str = "matmul", sep: bool = False):
        del method  # matmul is the only (and native) path
        assert not sep, "sep layout is not defined for split-Fourier axes"
        if self._rfft_plan is not None:
            x = jnp.moveaxis(v, axis, 0)
            out = self._rfft_plan.split(x) / self.n
            return jnp.moveaxis(out, 0, axis)
        return self._fwd_dev.apply(v, axis)

    def backward(self, vhat, axis: int, method: str = "matmul", sep: bool = False):
        del method
        assert not sep, "sep layout is not defined for split-Fourier axes"
        if self._irfft_plan is not None:
            x = jnp.moveaxis(vhat, axis, 0)
            return jnp.moveaxis(self._irfft_plan.apply(x), 0, axis)
        return self._bwd_dev.apply(vhat, axis)

    def backward_ortho(self, c, axis: int, method: str = "matmul", sep: bool = False):
        return self.backward(c, axis)

    def to_ortho(self, vhat, axis: int, sep: bool = False):
        return vhat

    def from_ortho(self, c, axis: int, sep: bool = False):
        return c

    def gradient(self, vhat, order: int, axis: int, sep: bool = False):
        """(ik)^order on the split blocks: i^order cycles through
        (1, i, -1, -i), i.e. (re, im) -> (re, im), (-k im, k re),
        -(re, im), (k im, -k re) times k^order."""
        if order == 0:
            return vhat
        mc = self.m_complex
        k = fou.wavenumbers_r2c(self.n) ** order
        if order % 2 == 1 and self.n % 2 == 0:
            k = k.copy()
            k[-1] = 0.0  # Nyquist of odd derivatives (see fourier.diff_diag)
        kd = jnp.asarray(k, dtype=vhat.dtype)
        shape = [1] * vhat.ndim
        shape[axis] = mc
        kd = kd.reshape(shape)
        re = jax.lax.slice_in_dim(vhat, 0, mc, axis=axis)
        im = jax.lax.slice_in_dim(vhat, mc, 2 * mc, axis=axis)
        quadrant = order % 4
        if quadrant == 0:
            re_n, im_n = kd * re, kd * im
        elif quadrant == 1:
            re_n, im_n = -kd * im, kd * re
        elif quadrant == 2:
            re_n, im_n = -kd * re, -kd * im
        else:
            re_n, im_n = kd * im, -kd * re
        return jnp.concatenate([re_n, im_n], axis=axis)

    def dealias_cut(self) -> np.ndarray:
        """2/3-rule applied per complex mode — the Re and Im blocks get the
        same cutoff."""
        mc = self.m_complex
        cut = np.ones(self.m)
        cut[mc * 2 // 3 : mc] = 0.0
        cut[mc + mc * 2 // 3 :] = 0.0
        return cut

    # -- complex interop (checkpoint IO keeps the reference layout) ----------

    def to_complex(self, vhat_split: np.ndarray, axis: int = 0) -> np.ndarray:
        a = np.moveaxis(np.asarray(vhat_split), axis, 0)
        out = a[: self.m_complex] + 1j * a[self.m_complex :]
        return np.moveaxis(out, 0, axis)

    def from_complex(self, vhat_c: np.ndarray, axis: int = 0) -> np.ndarray:
        a = np.moveaxis(np.asarray(vhat_c), axis, 0)
        out = np.concatenate([a.real, a.imag], axis=0)
        return np.moveaxis(out, 0, axis)


import weakref

_BASE_CACHE: "weakref.WeakValueDictionary[tuple[BaseKind, int], Base]" = (
    weakref.WeakValueDictionary()
)


def _cached_base(kind: BaseKind, n: int) -> Base:
    """Bases are immutable operator factories — share one instance per
    (kind, n) so repeated constructions (e.g. the velx and vely spaces of a
    model) reuse the same device-resident transform matrices.  Weak values:
    once no space references a base, its O(n^2) device matrices are freed."""
    key = (kind, n)
    base = _BASE_CACHE.get(key)
    if base is None:
        base = (
            SplitFourierBase(n) if kind == BaseKind.FOURIER_R2C_SPLIT else Base(kind, n)
        )
        _BASE_CACHE[key] = base
    return base


def chebyshev(n: int) -> Base:
    return _cached_base(BaseKind.CHEBYSHEV, n)


def cheb_dirichlet(n: int) -> Base:
    return _cached_base(BaseKind.CHEB_DIRICHLET, n)


def cheb_neumann(n: int) -> Base:
    return _cached_base(BaseKind.CHEB_NEUMANN, n)


def cheb_dirichlet_neumann(n: int) -> Base:
    return _cached_base(BaseKind.CHEB_DIRICHLET_NEUMANN, n)


def fourier_r2c(n: int) -> Base:
    """Real-to-complex Fourier base.  On backends without complex dtypes
    (the TPU chip) this transparently returns the split Re/Im representation
    (:class:`SplitFourierBase`), so periodic models run unchanged there."""
    if not config.supports_complex():
        return fourier_r2c_split(n)
    return _cached_base(BaseKind.FOURIER_R2C, n)


def fourier_r2c_split(n: int) -> Base:
    """Explicitly request the split Re/Im r2c base (any backend)."""
    return _cached_base(BaseKind.FOURIER_R2C_SPLIT, n)


def fourier_c2c(n: int) -> Base:
    return _cached_base(BaseKind.FOURIER_C2C, n)


class Space2:
    """Tensor product of two bases (axis 0 = x, axis 1 = y).

    Equivalent of funspace's ``Space2`` as used by the reference field layer
    (/root/reference/src/field.rs:59-129).  ``method`` picks the transform
    execution path: "fft" or "matmul" (Chebyshev axes only), default
    auto-selected: FFT everywhere except f64-on-TPU, where the emulated FFT
    path is unavailable and dense MXU transforms are used instead.
    """

    def __init__(
        self, base_x: Base, base_y: Base, method: str | None = None, sep=None
    ):
        if base_y.kind.is_periodic and not base_x.kind.is_periodic:
            raise ValueError("periodic y-axis under non-periodic x is unsupported")
        self.bases = (base_x, base_y)
        if base_y.kind.is_split:
            raise NotImplementedError(
                "the split Re/Im representation is implemented for the "
                "x-axis only (the IO/pinning helpers assume a split axis 0); "
                "doubly-periodic split spaces are unsupported"
            )
        if any(b.spectral_is_complex for b in self.bases) and not config.supports_complex():
            raise NotImplementedError(
                "complex Fourier bases are unsupported on this backend "
                "(no complex dtypes); use fourier_r2c_split / the "
                "fourier_r2c factory, which auto-selects the split "
                "representation."
            )
        if method is None:
            # TPU (axon): no FFT and no complex dtypes -> dense MXU transforms.
            method = "matmul" if config.is_tpu_like() else "fft"
        self.method = method
        # Parity-separated spectral layout (ops/folded.py): spectral axes are
        # stored parity-permuted ([evens..., odds...]) so every structured
        # operator runs on contiguous slices — no gathers/interleaves around
        # the GEMMs.  ``sep``: None -> RUSTPDE_SEP env ("auto" default: on
        # for all-Chebyshev matmul spaces, where the layout is defined and
        # measured to win); True/False force.  Per-axis: only Chebyshev-
        # family axes separate (split-Fourier axes keep their layout).
        if sep is None:
            env = config.env_get("RUSTPDE_SEP", "auto")
            if env == "auto":
                sep = method == "matmul" and all(
                    b.kind.is_chebyshev for b in self.bases
                )
            else:
                sep = env == "1"
        self.sep = (
            bool(sep) and base_x.kind.is_chebyshev and method == "matmul",
            bool(sep) and base_y.kind.is_chebyshev and method == "matmul",
        )

    @property
    def base_x(self) -> Base:
        return self.bases[0]

    @property
    def base_y(self) -> Base:
        return self.bases[1]

    @property
    def shape_physical(self) -> tuple[int, int]:
        return (self.bases[0].n, self.bases[1].n)

    @property
    def shape_spectral(self) -> tuple[int, int]:
        return (self.bases[0].m, self.bases[1].m)

    @property
    def spectral_is_complex(self) -> bool:
        return any(b.spectral_is_complex for b in self.bases)

    def spectral_dtype(self):
        return config.complex_dtype() if self.spectral_is_complex else config.real_dtype()

    def base_kind(self, axis: int) -> BaseKind:
        return self.bases[axis].kind

    def coords(self) -> list[np.ndarray]:
        return [b.points for b in self.bases]

    def ndarray_physical(self):
        return jnp.zeros(self.shape_physical, dtype=config.real_dtype())

    def ndarray_spectral(self):
        return jnp.zeros(self.shape_spectral, dtype=self.spectral_dtype())

    # -- transforms ---------------------------------------------------------
    #
    # Pencil discipline (active only under a parallel mesh): physical data is
    # a y-pencil (axis 0 sharded), spectral an x-pencil (axis 1 sharded); each
    # 2-D transform works on its local axis, flips pencils in between —
    # exactly funspace's forward_inplace_mpi = [transform y][transpose y->x]
    # [transform x] (/root/reference/src/field_mpi.rs:324-333), with the
    # all-to-all left to XLA GSPMD.

    def _axis_method(self, axis: int) -> str:
        """Per-axis transform path; under an active mesh Chebyshev axes use
        the (identical) matmul form — GSPMD shards GEMMs cleanly, while the
        XLA CPU FFT rejects the padded layouts non-divisible shardings
        produce."""
        from .parallel.mesh import active_mesh

        if active_mesh() is not None and self.bases[axis].kind.is_chebyshev:
            return "matmul"
        return self.method

    # All transforms are polymorphic over extra *leading* batch dims: the
    # tensor axes are the trailing two (models stack same-space fields and
    # transform them in one batched GEMM; mesh constraints replicate the
    # leading dims).

    @staticmethod
    def _batch_ax(arr) -> int:
        """Index of the first tensor axis; loud failure below rank 2 (a 1-D
        slice would otherwise transform one axis twice and return garbage)."""
        if arr.ndim < 2:
            raise ValueError(f"Space2 expects a (..., nx, ny) array, got rank {arr.ndim}")
        return arr.ndim - 2

    def forward(self, v):
        """Physical (..., n_x, n_y) -> spectral (..., m_x, m_y)."""
        from .parallel.mesh import PHYS, SPEC, constrain

        ax = self._batch_ax(v)
        out = self.bases[1].forward(
            constrain(v, PHYS), ax + 1, self._axis_method(1), sep=self.sep[1]
        )
        out = self.bases[0].forward(
            constrain(out, SPEC), ax, self._axis_method(0), sep=self.sep[0]
        )
        return constrain(out, SPEC)

    def backward(self, vhat):
        """Spectral (..., m_x, m_y) -> physical (..., n_x, n_y)."""
        from .parallel.mesh import PHYS, SPEC, constrain

        ax = self._batch_ax(vhat)
        out = self.bases[0].backward(
            constrain(vhat, SPEC), ax, self._axis_method(0), sep=self.sep[0]
        )
        out = self.bases[1].backward(
            constrain(out, PHYS), ax + 1, self._axis_method(1), sep=self.sep[1]
        )
        return constrain(out, PHYS)

    def backward_ortho(self, c):
        """Physical values from orthogonal-space coefficients (the space the
        reference's scratch ``field`` provides, /root/reference/src/navier_stokes/navier.rs:256)."""
        from .parallel.mesh import PHYS, SPEC, constrain

        ax = self._batch_ax(c)
        out = self.bases[0].backward_ortho(
            constrain(c, SPEC), ax, self._axis_method(0), sep=self.sep[0]
        )
        out = self.bases[1].backward_ortho(
            constrain(out, PHYS), ax + 1, self._axis_method(1), sep=self.sep[1]
        )
        return constrain(out, PHYS)

    def forward_dealiased(self, v, fast: bool = False):
        """Physical -> spectral with the 2/3-rule mask applied, in one fused
        form: sep axes drop the dead rows from their forward GEMMs (2/3
        flops, no mask pass); non-sep axes (e.g. the split-Fourier axis of a
        periodic space) run their plain forward and get their 1-D cut as a
        vector multiply.  Callers keep a ``forward() * mask`` fallback for
        fully non-sep spaces.  ``fast=True`` selects the 3-pass variant
        gated by RUSTPDE_FWD_PRECISION (default off — see Base._sep_dev)."""
        from .parallel.mesh import PHYS, SPEC, constrain

        if not any(self.sep):
            raise ValueError("forward_dealiased requires at least one sep axis")
        ax = self._batch_ax(v)
        key = ("fwd_cut", "fast") if fast else "fwd_cut"
        out = constrain(v, PHYS)
        if self.sep[1]:
            out = self.bases[1]._sep_dev(key).apply(out, ax + 1)
        else:
            out = self.bases[1].forward(out, ax + 1, self._axis_method(1))
        out = constrain(out, SPEC)
        if self.sep[0]:
            out = self.bases[0]._sep_dev(key).apply(out, ax)
        else:
            out = self.bases[0].forward(out, ax, self._axis_method(0))
        for axis in (0, 1):
            if not self.sep[axis]:
                cut = self.bases[axis].dealias_cut()
                shape = [1] * out.ndim
                shape[ax + axis] = cut.shape[0]
                out = out * jnp.asarray(
                    cut.reshape(shape), dtype=config.real_dtype()
                )
        return constrain(out, SPEC)

    def backward_gradient(self, vhat, deriv, scale=None, fast=False):
        """Physical values of d^deriv[0]/dx d^deriv[1]/dy — the fused
        ``backward_ortho(gradient(...))``: each sep axis is ONE
        synthesis-of-derivative GEMM (key ("bwd_grad", order); order 0 is the
        plain fused backward), saving the separate gradient apply.  Non-sep
        axes (e.g. the split-Fourier axis of a periodic space) fall back to
        gradient-then-synthesis on that axis only, so mixed spaces still
        fuse their Chebyshev axis.  ``fast=True`` selects the 3-pass
        synthesis variants (DNS convection path only — see Base._sep_dev)."""
        from .parallel.mesh import PHYS, SPEC, constrain

        if not any(self.sep):
            return self.backward_ortho(self.gradient(vhat, deriv, scale))
        ax = self._batch_ax(vhat)
        out = constrain(vhat, SPEC)
        for axis in (0, 1):
            b = self.bases[axis]
            a = ax + axis
            if self.sep[axis]:
                key = ("bwd_grad", deriv[axis]) if deriv[axis] else "bwd"
                if fast:
                    key = (key, "fast") if isinstance(key, str) else key + ("fast",)
                out = b._sep_dev(key).apply(out, a)
            else:
                out = b.gradient(out, deriv[axis], a, sep=False)
                out = b.backward_ortho(out, a, self._axis_method(axis))
            # pencil flip: the half-transformed intermediate moves to the
            # physical (y-pencil) layout before the axis-1 apply, as in
            # backward()/backward_ortho()
            out = constrain(out, PHYS)
        if scale is not None:
            factor = (scale[0] ** deriv[0]) * (scale[1] ** deriv[1])
            if factor != 1.0:
                out = out / factor
        return out

    def backward_fast(self, vhat):
        """``backward`` via the fast synthesis variants (DNS convection
        velocities only); falls back to the exact backward off-sep."""
        if not all(self.sep):
            return self.backward(vhat)
        return self.backward_gradient(vhat, (0, 0), None, fast=True)

    def to_ortho(self, vhat):
        ax = self._batch_ax(vhat)
        out = self.bases[0].to_ortho(vhat, ax, sep=self.sep[0])
        return self.bases[1].to_ortho(out, ax + 1, sep=self.sep[1])

    def from_ortho(self, c):
        ax = self._batch_ax(c)
        out = self.bases[0].from_ortho(c, ax, sep=self.sep[0])
        return self.bases[1].from_ortho(out, ax + 1, sep=self.sep[1])

    def gradient(self, vhat, deriv, scale=None):
        """d^deriv[0]/dx d^deriv[1]/dy in ortho space; divides by
        scale^deriv like the reference (/root/reference/src/field.rs:127)."""
        ax = self._batch_ax(vhat)
        out = self.bases[0].gradient(vhat, deriv[0], ax, sep=self.sep[0])
        out = self.bases[1].gradient(out, deriv[1], ax + 1, sep=self.sep[1])
        if scale is not None:
            factor = (scale[0] ** deriv[0]) * (scale[1] ** deriv[1])
            if factor != 1.0:
                out = out / factor
        return out

    # -- representation-aware helpers ---------------------------------------

    def dealias_mask(self) -> np.ndarray:
        """2/3-rule mask over this space's spectral shape
        (/root/reference/src/navier_stokes/functions.rs:72-82); for a split
        Fourier axis the cutoff applies per complex mode, i.e. to the Re and
        Im blocks alike (Base.dealias_cut); sep axes get the mask in their
        parity-permuted order."""
        from .ops.folded import parity_perm

        cuts = [base.dealias_cut() for base in self.bases]
        cuts = [
            c[parity_perm(len(c))] if s else c for c, s in zip(cuts, self.sep)
        ]
        return cuts[0][:, None] * cuts[1][None, :]

    # -- sep-layout boundary (host side) -------------------------------------

    def spectral_to_natural(self, vhat: np.ndarray) -> np.ndarray:
        """Host copy of spectral coefficients in the natural index order
        (identity for non-sep spaces) — the IO/parity boundary."""
        from .ops.folded import parity_perm_inv

        a = np.asarray(vhat)
        for axis, s in enumerate(self.sep):
            if s:
                a = np.take(a, parity_perm_inv(a.shape[axis - 2]), axis=axis - 2)
        return a

    def spectral_from_natural(self, vhat: np.ndarray) -> np.ndarray:
        from .ops.folded import parity_perm

        a = np.asarray(vhat)
        for axis, s in enumerate(self.sep):
            if s:
                a = np.take(a, parity_perm(a.shape[axis - 2]), axis=axis - 2)
        return a

    def pin_zero_mode(self, vhat):
        """Zero the constant mode (the pressure singularity pin,
        /root/reference/src/navier_stokes/navier_eq.rs:158-162); a split
        x-axis pins both the Re and the Im row of k=0."""
        out = vhat.at[0, 0].set(0.0)
        if self.bases[0].kind.is_split:
            out = out.at[self.bases[0].m_complex, 0].set(0.0)
        return out

    def vhat_as_complex(self, vhat) -> np.ndarray:
        """Host view of the coefficients in the complex convention (identity
        for non-split spaces) — keeps checkpoint files layout-identical
        across backends."""
        if self.bases[0].kind.is_split:
            # a forced-sep y-axis still needs its unpermute (different axes,
            # order-independent)
            return self.bases[0].to_complex(self.spectral_to_natural(vhat), axis=0)
        return self.spectral_to_natural(vhat)

    def vhat_from_complex(self, vhat_c: np.ndarray):
        if self.bases[0].kind.is_split:
            return self.spectral_from_natural(
                self.bases[0].from_complex(vhat_c, axis=0)
            )
        return self.spectral_from_natural(vhat_c)


def fused_projection_gradient(space_out: "Space2", space_in: "Space2", deriv):
    """Per-axis cross-space operators applying
    ``space_out.from_ortho(space_in.gradient(., deriv))`` as ONE GEMM per
    axis: ``P_out @ D^order @ S_in`` (the pressure-projection velocity
    correction in the Navier/LNSE/adjoint steps).  Returns a FoldedMatrix
    pair, or None when the fusion does not apply (periodic axes — the
    Fourier gradient is diagonal logic — or non-matmul transform methods,
    where the unfused path uses the O(n) recurrences the fusion was never
    benchmarked against).

    Deduplicated by VALUE key (base kinds + sizes + order + sep flags —
    operator matrices depend on nothing else), so e.g. the d/dx and d/dy
    corrections of a square grid share their device constants.  The cache
    dict lives ON the output-axis Base instance (which _BASE_CACHE holds
    only weakly), so the device matrices are freed with their bases instead
    of accumulating module-globally across many model sizes (ADVICE r4)."""
    bases_all = tuple(space_in.bases) + tuple(space_out.bases)
    if any(b.kind.is_periodic for b in bases_all):
        return None
    if space_out.method != "matmul" or space_in.method != "matmul":
        return None
    mats = []
    for ax, order in enumerate(deriv):
        b_out, b_in = space_out.bases[ax], space_in.bases[ax]
        cache = b_out._proj_grad_cache
        key = (b_in.kind, b_in.n, order, space_in.sep[ax], space_out.sep[ax])
        fm = cache.get(key)
        if fm is None:
            fm = FoldedMatrix(
                b_out.projection @ b_in.gradient_matrix(order),
                _dev,
                sep_in=space_in.sep[ax],
                sep_out=space_out.sep[ax],
            )
            cache[key] = fm
        mats.append(fm)
    return tuple(mats)


class Space1:
    """One-dimensional spectral space — the funspace ``Space1`` analog the
    reference's 1-D fields are built on (/root/reference/src/field.rs:59-72;
    consumed by examples/swift_hohenberg_1d.rs and the 1-D demos).

    Same execution-path selection as :class:`Space2`: FFT transforms except
    on the TPU backend, where dense MXU matmuls are used.  ``fourier_r2c``
    transparently becomes the split Re/Im representation there, so 1-D
    periodic models run on-chip unchanged.
    """

    def __init__(self, base: Base, method: str | None = None):
        if base.spectral_is_complex and not config.supports_complex():
            raise NotImplementedError(
                "complex Fourier bases are unsupported on this backend; "
                "use the fourier_r2c factory (auto-selects the split "
                "representation)"
            )
        self.base = base
        self.bases = (base,)
        if method is None:
            method = "matmul" if config.is_tpu_like() else "fft"
        self.method = method

    @property
    def shape_physical(self) -> tuple[int]:
        return (self.base.n,)

    @property
    def shape_spectral(self) -> tuple[int]:
        return (self.base.m,)

    @property
    def spectral_is_complex(self) -> bool:
        return self.base.spectral_is_complex

    def spectral_dtype(self):
        return config.complex_dtype() if self.spectral_is_complex else config.real_dtype()

    def base_kind(self, axis: int = 0) -> BaseKind:
        return self.base.kind

    def coords(self) -> list[np.ndarray]:
        return [self.base.points]

    def ndarray_physical(self):
        return jnp.zeros(self.shape_physical, dtype=config.real_dtype())

    def ndarray_spectral(self):
        return jnp.zeros(self.shape_spectral, dtype=self.spectral_dtype())

    def forward(self, v):
        return self.base.forward(v, 0, self.method)

    def backward(self, vhat):
        return self.base.backward(vhat, 0, self.method)

    def backward_ortho(self, c):
        return self.base.backward_ortho(c, 0, self.method)

    def to_ortho(self, vhat):
        return self.base.to_ortho(vhat, 0)

    def from_ortho(self, c):
        return self.base.from_ortho(c, 0)

    def gradient(self, vhat, deriv, scale=None):
        """d^deriv/dx in ortho space, divided by scale^deriv like the
        reference (/root/reference/src/field.rs:127).  ``deriv`` may be an
        int or a 1-element sequence."""
        order = deriv if isinstance(deriv, int) else deriv[0]
        out = self.base.gradient(vhat, order, 0)
        if scale is not None:
            s = scale if isinstance(scale, (int, float)) else scale[0]
            factor = float(s) ** order
            if factor != 1.0:
                out = out / factor
        return out

    def dealias_mask(self) -> np.ndarray:
        """2/3-rule mask (the 1-D form of Space2.dealias_mask; matches the
        reference's 1-D cutoff, examples/swift_hohenberg_1d.rs dealias)."""
        return self.base.dealias_cut()

    def pin_zero_mode(self, vhat):
        out = vhat.at[0].set(0.0)
        if self.base.kind.is_split:
            out = out.at[self.base.m_complex].set(0.0)
        return out

    def vhat_as_complex(self, vhat) -> np.ndarray:
        if self.base.kind.is_split:
            return self.base.to_complex(np.asarray(vhat), axis=0)
        return np.asarray(vhat)

    def vhat_from_complex(self, vhat_c: np.ndarray):
        if self.base.kind.is_split:
            return self.base.from_complex(vhat_c, axis=0)
        return vhat_c


class BiPeriodicSpace2:
    """Doubly-periodic real 2-D space (Fourier x Fourier), split Re/Im layout.

    The reference's Swift–Hohenberg demo runs on ``fourier_c2c x fourier_r2c``
    with complex coefficients (/root/reference/examples/swift_hohenberg_2d.rs).
    A complex c2c axis cannot ride the per-axis split trick of
    :class:`SplitFourierBase` (a c2c transform mixes Re and Im across the
    *other* axis's blocks), so the doubly-periodic case gets its own space:
    spectral data is a real ``(2, nx, my)`` array — plane 0 = Re, plane 1 =
    Im of the c2c x r2c coefficients, ``my = ny//2+1`` — and the transforms
    run either as XLA FFTs (CPU) or as real MXU matmuls handling the Re/Im
    mixing explicitly (TPU: no FFT, no complex dtypes).  Normalization is
    amplitude (fft/n per axis), matching ops/fourier.
    """

    def __init__(self, nx: int, ny: int, method: str | None = None):
        self.nx, self.ny = nx, ny
        self.my = ny // 2 + 1
        if method is None:
            method = "matmul" if config.is_tpu_like() else "fft"
        self.method = method
        self.kx = fou.wavenumbers_c2c(nx)
        self.ky = fou.wavenumbers_r2c(ny)

    # -- geometry -----------------------------------------------------------

    @property
    def shape_physical(self) -> tuple[int, int]:
        return (self.nx, self.ny)

    @property
    def shape_spectral(self) -> tuple[int, int, int]:
        return (2, self.nx, self.my)

    def coords(self) -> list[np.ndarray]:
        return [fou.fourier_points(self.nx), fou.fourier_points(self.ny)]

    def ndarray_physical(self):
        return jnp.zeros(self.shape_physical, dtype=config.real_dtype())

    def ndarray_spectral(self):
        return jnp.zeros(self.shape_spectral, dtype=config.real_dtype())

    # -- transform matrices (host, lazily built) ----------------------------

    @cached_property
    def _y_fwd(self) -> FoldedMatrix:
        return FoldedMatrix(fou.split_forward_matrix(self.ny), _dev)  # (2my, ny)

    @cached_property
    def _y_bwd(self) -> FoldedMatrix:
        return FoldedMatrix(fou.split_backward_matrix(self.ny), _dev)  # (ny, 2my)

    @cached_property
    def _x_cos(self) -> FoldedMatrix:
        return FoldedMatrix(fou.dft_cos_matrix(self.nx), _dev)

    @cached_property
    def _x_sin(self) -> FoldedMatrix:
        return FoldedMatrix(fou.dft_sin_matrix(self.nx), _dev)

    # four-step plans (ops/fourstep.py); None below the size gate
    @cached_property
    def _y_rfft_plan(self):
        return fourstep.RfftPlan(self.ny, _dev) if fourstep.enabled(self.ny, "dft") else None

    @cached_property
    def _y_irfft_plan(self):
        return fourstep.IrfftPlan(self.ny, _dev) if fourstep.enabled(self.ny, "dft") else None

    @cached_property
    def _x_c2c_fwd(self):
        return (
            fourstep.C2cPlan(self.nx, _dev, sign=-1.0)
            if fourstep.enabled(self.nx, "c2c")
            else None
        )

    @cached_property
    def _x_c2c_bwd(self):
        return (
            fourstep.C2cPlan(self.nx, _dev, sign=+1.0)
            if fourstep.enabled(self.nx, "c2c")
            else None
        )

    # -- transforms ----------------------------------------------------------

    def forward(self, v):
        """Real physical (nx, ny) -> split spectral (2, nx, my)."""
        if self.method == "fft":
            c = jnp.fft.fft(jnp.fft.rfft(v, axis=1) / self.ny, axis=0) / self.nx
            return jnp.stack([c.real, c.imag]).astype(v.dtype)
        if self._y_rfft_plan is not None:
            w = jnp.moveaxis(
                self._y_rfft_plan.split(jnp.moveaxis(v, 1, 0)) / self.ny, 0, 1
            )
        else:
            w = self._y_fwd.apply(v, 1)  # (nx, 2my): [Re | Im] of the y-r2c
        re1, im1 = w[:, : self.my], w[:, self.my :]
        # x-axis c2c forward F = C - iS applied to re1 + i*im1
        if self._x_c2c_fwd is not None:
            re, im = self._x_c2c_fwd.apply(re1, im1)
            return jnp.stack([re / self.nx, im / self.nx])
        # forward c2c matrices are the backward pair scaled by 1/nx — share
        # the device constants and fold the scalar in here
        cos, sin = self._x_cos, self._x_sin
        re = (cos.apply(re1, 0) + sin.apply(im1, 0)) / self.nx
        im = (cos.apply(im1, 0) - sin.apply(re1, 0)) / self.nx
        return jnp.stack([re, im])

    def backward(self, s):
        """Split spectral (2, nx, my) -> real physical (nx, ny)."""
        if self.method == "fft":
            c = (s[0] + 1j * s[1]).astype(config.complex_dtype())
            mid = jnp.fft.ifft(c * self.nx, axis=0)
            return jnp.fft.irfft(mid * self.ny, n=self.ny, axis=1).astype(s.dtype)
        # x-axis inverse c2c B = C + iS
        if self._x_c2c_bwd is not None:
            mid_re, mid_im = self._x_c2c_bwd.apply(s[0], s[1])
        else:
            cos, sin = self._x_cos, self._x_sin
            mid_re = cos.apply(s[0], 0) - sin.apply(s[1], 0)
            mid_im = cos.apply(s[1], 0) + sin.apply(s[0], 0)
        # y-axis r2c synthesis on the [Re | Im] blocks (imag part of the
        # physical signal is structurally zero and never materialized)
        mid = jnp.concatenate([mid_re, mid_im], axis=1)
        if self._y_irfft_plan is not None:
            return jnp.moveaxis(
                self._y_irfft_plan.apply(jnp.moveaxis(mid, 1, 0)), 0, 1
            )
        return self._y_bwd.apply(mid, 1)

    # -- spectral operators --------------------------------------------------

    def _grad_factor(self, deriv) -> np.ndarray:
        """(i kx)^dx (i ky)^dy over the (nx, my) mode grid (complex host
        array), odd-order Nyquist modes zeroed (see ops/fourier.diff_diag)."""
        fx = fou.diff_diag(self.kx, deriv[0], self.nx, r2c=False)
        fy = fou.diff_diag(self.ky, deriv[1], self.ny, r2c=True)
        return fx[:, None] * fy[None, :]

    def gradient(self, s, deriv, scale=None):
        """Mixed derivative in spectral space on the split layout."""
        f = self._grad_factor(deriv)
        if scale is not None:
            f = f / ((scale[0] ** deriv[0]) * (scale[1] ** deriv[1]))
        fre = jnp.asarray(f.real, dtype=s.dtype)
        fim = jnp.asarray(f.imag, dtype=s.dtype)
        return jnp.stack(
            [fre * s[0] - fim * s[1], fre * s[1] + fim * s[0]]
        )

    def dealias_mask(self) -> np.ndarray:
        """2/3-rule over both axes, shape (nx, my).  Same integer-floor
        cutoff convention as Base.dealias_cut (keep |k| < floor(2m/3)); the
        c2c x-axis is cut by wavenumber magnitude."""
        mx = self.nx // 2 + 1
        cx = (np.abs(self.kx) < (mx * 2) // 3).astype(np.float64)
        cy = np.ones(self.my)
        cy[(self.my * 2) // 3 :] = 0.0
        return cx[:, None] * cy[None, :]

    def pin_zero_mode(self, s):
        return s.at[:, 0, 0].set(0.0)

    def enforce_hermitian_x(self, s):
        """Make the self-conjugate ky columns conjugate-symmetric in kx — a
        real physical field demands c(-kx, ky) = conj(c(kx, ky)) at ky = 0
        and, for even ny, at the ky-Nyquist column (both map to themselves
        under ky -> -ky); anti-Hermitian roundoff there is amplified without
        bound by the diagonal implicit update wherever the mode is linearly
        unstable.  The reference's helper notes the Nyquist case but fixes
        only ky=0 (/root/reference/examples/swift_hohenberg_2d.rs
        enforce_hermitian_symmetry); both columns are projected here."""
        # conjugate pairing index: k -> (nx - k) % nx
        idx = (-jnp.arange(self.nx)) % self.nx
        cols = [0] + ([self.my - 1] if self.ny % 2 == 0 else [])
        for c in cols:
            sym_re = 0.5 * (s[0, :, c] + s[0, idx, c])
            sym_im = 0.5 * (s[1, :, c] - s[1, idx, c])
            s = s.at[0, :, c].set(sym_re).at[1, :, c].set(sym_im)
        return s

    # -- complex interop (checkpoint IO keeps the reference layout) ----------

    def vhat_as_complex(self, s) -> np.ndarray:
        a = np.asarray(s)
        return a[0] + 1j * a[1]

    def vhat_from_complex(self, c: np.ndarray) -> np.ndarray:
        c = np.asarray(c)
        return np.stack([c.real, c.imag])
