"""Explicit pencil decomposition + collectives (the MPI-parity surface).

The models shard through GSPMD constraints (parallel/mesh.py) and never call
these directly — XLA places the all-to-alls.  This module provides the
*explicit* counterpart of the reference's distributed API for user code and
custom kernels: funspace's ``Decomp2d`` bookkeeping with its
``transpose_x_to_y``/``transpose_y_to_x`` repartitions as
``shard_map`` + ``jax.lax.all_to_all`` over the ICI mesh, and the collectives
the reference re-exports (``all_gather_sum``, ``broadcast_scalar``,
gather/scatter to root) — SURVEY.md S2.2 (/root/reference/src/mpi/mod.rs:2-12,
src/field_mpi.rs:455-477).

Pencil convention (reference field_mpi.rs:71-88):

* **y-pencil**: axis 0 (x) distributed, axis 1 contiguous — physical data.
* **x-pencil**: axis 1 (y) distributed, axis 0 contiguous — spectral data.

The explicit transposes accept arbitrary (odd) extents — the equal-tile
all_to_all runs on a zero-padded shape and the pad is sliced away — so the
MPI-parity surface expresses the production grids (129/1025/2049) just like
funspace's transpose_x_to_y.  The GSPMD constraint path in the models
remains the execution path for the physics.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .mesh import AXIS, PHYS, SPEC, make_mesh  # noqa: F401  (re-exported)
from ..config import env_get

try:  # jax>=0.4.35
    from jax import shard_map

    def _smap(f, mesh, in_specs, out_specs):
        return shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)

except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

    def _smap(f, mesh, in_specs, out_specs):
        return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


@dataclass(frozen=True)
class Pencil:
    """One rank's slab of one pencil orientation (reference ``Decomp2d``
    pencils expose st/en/sz, src/field_mpi.rs:128-135)."""

    st: tuple[int, int]  # global start index per axis (inclusive)
    en: tuple[int, int]  # global end index per axis (inclusive)
    sz: tuple[int, int]  # local shape
    dist_axis: int  # which axis is distributed

    @property
    def axis_contig(self) -> int:
        """The undivided axis (field_mpi/average.rs:50)."""
        return 1 - self.dist_axis


def _split(n: int, nprocs: int, rank: int) -> tuple[int, int]:
    """Balanced contiguous split: first (n % nprocs) ranks get one extra."""
    base, extra = divmod(n, nprocs)
    st = rank * base + min(rank, extra)
    sz = base + (1 if rank < extra else 0)
    return st, sz


class Decomp2d:
    """Pencil bookkeeping + explicit repartitions over a 1-D device mesh.

    ``x_pencil(rank)`` / ``y_pencil(rank)`` give each rank's slab exactly as
    the reference's decomp object does; ``transpose_x_to_y`` /
    ``transpose_y_to_x`` are the all-to-all repartitions (jittable,
    differentiable, runnable inside other shard_mapped code via the
    ``*_local`` variants).
    """

    def __init__(self, global_shape: tuple[int, int], mesh: Mesh | None = None):
        self.mesh = mesh if mesh is not None else make_mesh()
        self.nprocs = self.mesh.shape[AXIS]
        self.global_shape = tuple(global_shape)

    # -- bookkeeping ---------------------------------------------------------

    def _pencil(self, rank: int, dist_axis: int) -> Pencil:
        n0, n1 = self.global_shape
        if dist_axis == 0:
            st0, sz0 = _split(n0, self.nprocs, rank)
            return Pencil((st0, 0), (st0 + sz0 - 1, n1 - 1), (sz0, n1), 0)
        st1, sz1 = _split(n1, self.nprocs, rank)
        return Pencil((0, st1), (n0 - 1, st1 + sz1 - 1), (n0, sz1), 1)

    def y_pencil(self, rank: int) -> Pencil:
        """Axis 0 distributed (physical-data layout)."""
        return self._pencil(rank, 0)

    def x_pencil(self, rank: int) -> Pencil:
        """Axis 1 distributed (spectral-data layout)."""
        return self._pencil(rank, 1)

    # -- explicit repartitions ----------------------------------------------

    def _pad(self, arr):
        """Zero-pad both extents up to the next mesh multiple so the tiled
        all_to_all exchanges equal blocks; the flagship grids are odd
        (129/1025/2049 — funspace's transpose_x_to_y takes any extent,
        SURVEY.md S2.2, and so does this).  The pad rows/cols ride the
        collective and are sliced away by the caller."""
        n0, n1 = self.global_shape
        p0 = (-n0) % self.nprocs
        p1 = (-n1) % self.nprocs
        if p0 or p1:
            arr = jnp.pad(arr, ((0, p0), (0, p1)))
        return arr

    @staticmethod
    def transpose_x_to_y_local(block):
        """Inside-shard_map body: x-pencil block (n0, n1/P) -> y-pencil
        block (n0/P, n1) (funspace transpose_x_to_y)."""
        return jax.lax.all_to_all(block, AXIS, split_axis=0, concat_axis=1, tiled=True)

    @staticmethod
    def transpose_y_to_x_local(block):
        """Inside-shard_map body: y-pencil block (n0/P, n1) -> x-pencil
        block (n0, n1/P)."""
        return jax.lax.all_to_all(block, AXIS, split_axis=1, concat_axis=0, tiled=True)

    def transpose_x_to_y(self, arr, method: str | None = None):
        """Global-view repartition: axis-1-sharded -> axis-0-sharded.
        Any extents (pad-and-slice around the equal-tile exchange);
        ``method``: None = the RUSTPDE_TRANSPOSE default, "alltoall" |
        "ring" (see :func:`make_transpose_local`)."""
        n0, n1 = self.global_shape
        fn = _smap(
            make_transpose_local(self.nprocs, x_to_y=True, method=method),
            self.mesh,
            in_specs=PartitionSpec(*SPEC),
            out_specs=PartitionSpec(*PHYS),
        )
        return fn(self._pad(arr))[:n0, :n1]

    def transpose_y_to_x(self, arr, method: str | None = None):
        n0, n1 = self.global_shape
        fn = _smap(
            make_transpose_local(self.nprocs, x_to_y=False, method=method),
            self.mesh,
            in_specs=PartitionSpec(*PHYS),
            out_specs=PartitionSpec(*SPEC),
        )
        return fn(self._pad(arr))[:n0, :n1]

    # -- placement helpers ---------------------------------------------------

    def place_y_pencil(self, arr):
        return jax.device_put(
            jnp.asarray(arr), NamedSharding(self.mesh, PartitionSpec(*PHYS))
        )

    def place_x_pencil(self, arr):
        return jax.device_put(
            jnp.asarray(arr), NamedSharding(self.mesh, PartitionSpec(*SPEC))
        )


# ---------------------------------------------------------------------------
# explicit ring transposes (the SNIPPETS [1]/[2] remote-copy pattern)
# ---------------------------------------------------------------------------
#
# ``jax.lax.all_to_all`` leaves the collective's placement and scheduling to
# the compiler, which serializes the pencil flip behind the surrounding
# GEMMs.  The ring path expresses the same repartition as P-1 explicit
# shift-permute steps INSIDE the shard_map region, so each step's chunk
# exchange can overlap with per-pencil transform compute instead of waiting
# for a compiler-placed fused collective:
#
# * off-TPU (and for CI equivalence): ``lax.ppermute`` shift rounds —
#   semantically identical data movement, testable on the virtual CPU mesh;
# * on TPU: a Pallas kernel pushing each chunk straight into the destination
#   device's output slab with ``pltpu.make_async_remote_copy`` (direct ICI
#   RDMA, one DMA per ring step, no intermediate staging buffer).
#
# Selection: RUSTPDE_TRANSPOSE=alltoall (default) | ring, plus the
# per-call ``method=`` override; RUSTPDE_RING_IMPL=ppermute pins the
# ppermute form on TPU (A/B of the DMA kernel vs XLA's collective-permute).


def transpose_method() -> str:
    """The RUSTPDE_TRANSPOSE knob (default ``alltoall``) — selection stays
    measurement-driven like solver.default_method; ``bench.py pallasconv``
    records the A/B when a chip is attached."""
    return env_get("RUSTPDE_TRANSPOSE", "alltoall")


def _pallas_ring_available() -> bool:
    return (
        jax.devices()[0].platform in ("tpu", "axon")
        and env_get("RUSTPDE_RING_IMPL", "pallas") != "ppermute"
    )


def make_transpose_local(nprocs: int, x_to_y: bool, method: str | None = None):
    """Inside-shard_map transpose body for an equal-tile pencil flip.

    ``x_to_y``: (n0, n1/P) -> (n0/P, n1) (spectral x-pencil to physical
    y-pencil); else the inverse.  The returned callable is what the manual-
    sharding conv region and the Decomp2d global-view transposes dispatch."""
    if method is None:
        method = transpose_method()
    if method not in ("alltoall", "ring"):
        raise ValueError(f"unknown transpose method {method!r}")
    if method == "alltoall":
        return (
            Decomp2d.transpose_x_to_y_local if x_to_y else Decomp2d.transpose_y_to_x_local
        )
    if _pallas_ring_available():
        return functools.partial(_ring_transpose_pallas, nprocs=nprocs, x_to_y=x_to_y)
    return functools.partial(_ring_transpose_ppermute, nprocs=nprocs, x_to_y=x_to_y)


def _ring_transpose_ppermute(block, *, nprocs: int, x_to_y: bool):
    """Shift-permute ring form of the tiled all_to_all: at step s every
    device sends the chunk destined s ranks ahead and receives from s ranks
    behind, placing it at the sender's slot — P-1 uniform shifts, the exact
    data movement of the TPU remote-copy kernel, testable on any backend."""
    me = jax.lax.axis_index(AXIS)
    if x_to_y:
        c = block.shape[0] // nprocs
        w = block.shape[1]
        out = jnp.zeros((c, w * nprocs), dtype=block.dtype)
        take = lambda t: jax.lax.dynamic_slice_in_dim(block, t * c, c, axis=0)
        put = lambda o, chunk, r: jax.lax.dynamic_update_slice_in_dim(
            o, chunk, r * w, axis=1
        )
    else:
        c = block.shape[1] // nprocs
        h = block.shape[0]
        out = jnp.zeros((h * nprocs, c), dtype=block.dtype)
        take = lambda t: jax.lax.dynamic_slice_in_dim(block, t * c, c, axis=1)
        put = lambda o, chunk, r: jax.lax.dynamic_update_slice_in_dim(
            o, chunk, r * h, axis=0
        )
    out = put(out, take(me), me)  # own diagonal chunk, no exchange
    for shift in range(1, nprocs):
        perm = [(d, (d + shift) % nprocs) for d in range(nprocs)]
        recv = jax.lax.ppermute(take((me + shift) % nprocs), AXIS, perm)
        out = put(out, recv, (me - shift) % nprocs)
    return out


def _ring_transpose_kernel(in_ref, out_ref, send_sem, recv_sem, local_sem,
                           *, nprocs: int, x_to_y: bool):
    """Direct-DMA transpose: each ring step pushes one chunk into the
    destination device's output slab at the SENDER's slot
    (``pltpu.make_async_remote_copy``, SNIPPETS [1]/[2]).  Every step is a
    uniform shift, so each device's per-step wait() pairs its send with the
    matching inbound DMA; the own-rank diagonal chunk is a local async
    copy overlapped with the first remote step."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    me = jax.lax.axis_index(AXIS)
    if x_to_y:
        c = in_ref.shape[0] // nprocs
        w = out_ref.shape[1] // nprocs
        src_at = lambda t: in_ref.at[pl.ds(t * c, c), :]
        dst_at = lambda r: out_ref.at[:, pl.ds(r * w, w)]
    else:
        c = in_ref.shape[1] // nprocs
        h = out_ref.shape[0] // nprocs
        src_at = lambda t: in_ref.at[:, pl.ds(t * c, c)]
        dst_at = lambda r: out_ref.at[pl.ds(r * h, h), :]
    local = pltpu.make_async_copy(src_at(me), dst_at(me), local_sem)
    local.start()
    for shift in range(1, nprocs):
        dst = jax.lax.rem(me + shift, nprocs)
        rdma = pltpu.make_async_remote_copy(
            src_ref=src_at(dst),
            dst_ref=dst_at(me),
            send_sem=send_sem,
            recv_sem=recv_sem,
            device_id=(dst,),
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdma.wait()
    local.wait()


# Each traced ring-transpose call draws a FRESH collective id: two
# independent transposes in one program (ShardedConv's t1/t0 pair) may be
# scheduled in different relative orders per device, and sharing one
# barrier-semaphore id across concurrent non-identical collectives
# mismatches the send/recv pairing (hang or corrupted chunks).  The counter
# is deterministic because every process traces the same program in the
# same order, so all devices agree on each call site's id.
import itertools

_RING_COLLECTIVE_IDS = itertools.count(16)


def _ring_transpose_pallas(block, *, nprocs: int, x_to_y: bool):
    """TPU entry for the remote-copy ring (inside shard_map; HBM-resident
    refs, the DMAs stream chunks without a VMEM round-trip)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if x_to_y:
        out_shape = (block.shape[0] // nprocs, block.shape[1] * nprocs)
    else:
        out_shape = (block.shape[0] * nprocs, block.shape[1] // nprocs)
    return pl.pallas_call(
        functools.partial(_ring_transpose_kernel, nprocs=nprocs, x_to_y=x_to_y),
        out_shape=jax.ShapeDtypeStruct(out_shape, block.dtype),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA] * 3,
        compiler_params=pltpu.TPUCompilerParams(
            collective_id=next(_RING_COLLECTIVE_IDS)
        ),
        name="ring_transpose",
    )(block)


# ---------------------------------------------------------------------------
# manual-partitioned convection chain (the GSPMD split-sep bypass)
# ---------------------------------------------------------------------------


class ShardedConv:
    """The convection-transform chain as ONE ``shard_map`` region: per-pencil
    transform GEMMs on the locally-full axis with explicit pencil transposes
    (all_to_all or the remote-copy ring, RUSTPDE_TRANSPOSE) between them —
    manual partitioning instead of GSPMD propagation.

    This is the sharded sibling of ops/pallas_conv.FusedConv, built from the
    same ``Base.axis_operator`` dense matrices, and the mechanism that
    retires the per-stage eager fallback on the split-sep periodic layout:
    the upstream GSPMD miscompile lives in the compiler's partitioning of
    the fused transform graph, and a shard_map region is opaque to that
    propagation — inside it, every collective is placed BY HAND, so the
    fused step compiles correctly under an active mesh (de-xfailed in
    tests/test_parallel.py; ``RUSTPDE_FORCE_FUSED_GSPMD=1`` keeps a pinned
    sibling tracking the upstream bug).

    Unlike the dealiased-forward row-drop of the Pallas kernel, the dead
    2/3-rule rows stay zeroed in the forward matrices here — uniform tile
    shapes keep the equal-tile transposes trivial; the flop cost of the
    zero rows is the price of the manual layout until the ring+kernel
    fusion lands on-chip."""

    def __init__(self, space_in, field_space, scale, mesh: Mesh):
        from .. import config

        self.mesh = mesh
        self.nprocs = int(mesh.shape[AXIS])
        P = self.nprocs
        bx_in, by_in = space_in.bases
        fx_b, fy_b = field_space.bases
        if bx_in.spectral_is_complex or fx_b.spectral_is_complex:
            raise ValueError(
                "ShardedConv expects the split Re/Im x-representation "
                "(the layout real multichip meshes run)"
            )
        gx1 = bx_in.axis_operator(("bwd_grad", 1), sep=space_in.sep[0]).matrix
        gx0 = bx_in.axis_operator("bwd", sep=space_in.sep[0]).matrix
        gy1 = by_in.axis_operator(("bwd_grad", 1), sep=space_in.sep[1]).matrix
        gy0 = by_in.axis_operator("bwd", sep=space_in.sep[1]).matrix
        fxm = fx_b.axis_operator("fwd_cut", sep=field_space.sep[0]).matrix
        fym = fy_b.axis_operator("fwd_cut", sep=field_space.sep[1]).matrix
        gx1 = gx1 / float(scale[0])
        gy1 = gy1 / float(scale[1])

        self.nx, self.ny = space_in.shape_physical
        self.mx, self.my = gx0.shape[1], gy0.shape[1]
        self.mxf, self.myf = fxm.shape[0], fym.shape[0]
        self.nxp = -(-self.nx // P) * P
        self.myp = -(-self.my // P) * P
        self.myfp = -(-self.myf // P) * P
        from ..ops.folded import pad_dense as pad

        rdt = config.real_dtype()
        with jax.ensure_compile_time_eval():
            self._gx1 = jnp.asarray(pad(gx1, self.nxp, self.mx), dtype=rdt)
            self._gx0 = jnp.asarray(pad(gx0, self.nxp, self.mx), dtype=rdt)
            self._gy0t = jnp.asarray(pad(gy0.T, self.myp, self.ny), dtype=rdt)
            self._gy1t = jnp.asarray(pad(gy1.T, self.myp, self.ny), dtype=rdt)
            self._fx = jnp.asarray(pad(fxm, self.mxf, self.nxp), dtype=rdt)
            self._fyt = jnp.asarray(pad(fym.T, self.ny, self.myfp), dtype=rdt)

        x2y = make_transpose_local(P, x_to_y=True)
        y2x = make_transpose_local(P, x_to_y=False)

        def region(gx1m, gx0m, gy0tm, gy1tm, fxm_, fytm, vb, uxb, uyb, bdxb, bdyb):
            # spectral x-pencil: x-axis locally full — synthesis(-of-d/dx)
            t1 = gx1m @ vb
            t0 = gx0m @ vb
            # pencil flip, then the y syntheses on the locally-full y axis
            dvdx = x2y(t1) @ gy0tm
            dvdy = x2y(t0) @ gy1tm
            total = uxb * (dvdx + bdxb) + uyb * (dvdy + bdyb)
            # dealiased forward: y first (local), flip back, then x
            fy = total @ fytm
            return fxm_ @ y2x(fy)

        rep = PartitionSpec()
        self._region = _smap(
            region,
            mesh,
            in_specs=(rep,) * 6
            + (PartitionSpec(*SPEC),)
            + (PartitionSpec(*PHYS),) * 4,
            out_specs=PartitionSpec(*SPEC),
        )

    def apply(self, ux, uy, vhat, bc_dx=None, bc_dy=None):
        """Global-view conv: (ux, uy) physical y-pencils, ``vhat`` spectral
        x-pencil -> dealiased spectral x-pencil (zeros in the dead rows),
        identical in value to the unfused serial chain."""
        padp = ((0, self.nxp - self.nx), (0, 0))
        pads = ((0, 0), (0, self.myp - self.my))
        z = jnp.zeros_like(ux) if bc_dx is None else bc_dx
        z2 = jnp.zeros_like(uy) if bc_dy is None else bc_dy
        out = self._region(
            self._gx1, self._gx0, self._gy0t, self._gy1t, self._fx, self._fyt,
            jnp.pad(vhat, pads),
            jnp.pad(ux, padp), jnp.pad(uy, padp),
            jnp.pad(z, padp), jnp.pad(z2, padp),
        )
        return out[:, : self.myf]


class ShardedSynthesis:
    """Manual-partitioned 2-D backward synthesis (spectral x-pencil ->
    physical y-pencil): the convection-velocity transforms of the manual
    split-sep step, same shard_map + explicit-transpose structure as
    :class:`ShardedConv` and built from the same ``axis_operator``
    matrices."""

    def __init__(self, space, scale_unused, mesh: Mesh):
        from .. import config

        del scale_unused
        self.mesh = mesh
        P = self.nprocs = int(mesh.shape[AXIS])
        bx_in, by_in = space.bases
        gx0 = bx_in.axis_operator("bwd", sep=space.sep[0]).matrix
        gy0 = by_in.axis_operator("bwd", sep=space.sep[1]).matrix
        self.nx, self.ny = space.shape_physical
        self.mx, self.my = gx0.shape[1], gy0.shape[1]
        self.nxp = -(-self.nx // P) * P
        self.myp = -(-self.my // P) * P
        from ..ops.folded import pad_dense as pad

        rdt = config.real_dtype()
        with jax.ensure_compile_time_eval():
            self._gx0 = jnp.asarray(pad(gx0, self.nxp, self.mx), dtype=rdt)
            self._gy0t = jnp.asarray(pad(gy0.T, self.myp, self.ny), dtype=rdt)
        x2y = make_transpose_local(P, x_to_y=True)

        def region(gx0m, gy0tm, vb):
            return x2y(gx0m @ vb) @ gy0tm

        rep = PartitionSpec()
        self._region = _smap(
            region,
            mesh,
            in_specs=(rep, rep, PartitionSpec(*SPEC)),
            out_specs=PartitionSpec(*PHYS),
        )

    def apply(self, vhat):
        out = self._region(
            self._gx0, self._gy0t,
            jnp.pad(vhat, ((0, 0), (0, self.myp - self.my))),
        )
        return out[: self.nx, :]


class ShardedPoisson:
    """The pressure-Poisson fast-diagonalisation solve as one manual
    shard_map region — THE stage the GSPMD miscompile localizes to.

    Bisection on the 8-device CPU mesh (every other stage toggled between
    GSPMD and manual regions, 8-step trajectories vs serial): with the
    whole step under GSPMD the split-sep periodic layout diverges from
    step 1 (div_norm 0.42); making conv/syntheses/gradients/orthos manual
    leaves the error unchanged (pres 0.177); making ONLY this solve manual
    drops the full-step error to ~1.6e-15.  The fused FastDiag on the
    split-Fourier axis (modal identity on axis 0, eigendecomposed GEMMs on
    axis 1, 2-D modal denominator) is what XLA's SPMD propagation
    mispartitions when fused with its neighbors.

    Structure (x-pencil in/out, all collectives hand-placed): transpose to
    the y-pencil, ``fwd1`` eigen-map on the locally-full y axis, divide by
    the lane-sharded modal denominator, ``bwd1`` back, transpose to the
    x-pencil.  The Fourier axis-0 maps are identity (asserted)."""

    def __init__(self, solver, space, mesh: Mesh):
        from .. import config
        from ..solver import FastDiag

        fd = getattr(solver, "_solver", solver)
        if not isinstance(fd, FastDiag) or fd.fwd[0] is not None or fd.bwd[0] is not None:
            raise ValueError(
                "ShardedPoisson wraps the fast-diagonalisation solver with a "
                "modal (Fourier) axis 0 — the split-sep periodic layout"
            )
        self._fwd1, self._bwd1 = fd.fwd[1], fd.bwd[1]
        P = self.nprocs = int(mesh.shape[AXIS])
        self.mx = space.shape_spectral[0]
        self.my_in = space.bases[1].n  # ortho rhs rows along y
        self.my_out = space.shape_spectral[1]
        self.mxp = -(-self.mx // P) * P
        self.myip = -(-self.my_in // P) * P
        self.myop = -(-self.my_out // P) * P
        denom = np.ones((self.mxp, np.asarray(fd.denom).shape[1]))
        denom[: self.mx] = np.asarray(fd.denom)  # pad lanes divide by 1
        rdt = config.real_dtype()
        with jax.ensure_compile_time_eval():
            self._denom = jnp.asarray(denom, dtype=rdt)
        x2y = make_transpose_local(P, x_to_y=True)
        y2x = make_transpose_local(P, x_to_y=False)
        my_in, myop = self.my_in, self.myop
        fwd1, bwd1 = self._fwd1, self._bwd1

        def region(denom_blk, rhs_blk):
            t = x2y(rhs_blk)[:, :my_in]
            if fwd1 is not None:
                t = fwd1.apply(t, 1)
            t = t / denom_blk.astype(t.dtype)
            if bwd1 is not None:
                t = bwd1.apply(t, 1)
            t = jnp.pad(t, ((0, 0), (0, myop - t.shape[1])))
            return y2x(t)

        self._region = _smap(
            region,
            mesh,
            in_specs=(PartitionSpec(AXIS), PartitionSpec(*SPEC)),
            out_specs=PartitionSpec(*SPEC),
        )

    def solve(self, rhs):
        out = self._region(
            self._denom,
            jnp.pad(rhs, ((0, self.mxp - self.mx), (0, self.myip - rhs.shape[1]))),
        )
        return out[: self.mx, : self.my_out]


# ---------------------------------------------------------------------------
# collectives (reference src/mpi/mod.rs re-exports)
# ---------------------------------------------------------------------------


def all_gather_sum(arr, mesh: Mesh | None = None, spec=PHYS):
    """Sum a sharded array's per-rank contributions so every rank holds the
    global sum — the reference's ``all_gather_sum``
    (/root/reference/src/navier_stokes_mpi/functions.rs:137-139).  ``arr`` is
    the global view sharded by ``spec``; the result is fully replicated."""
    mesh = mesh if mesh is not None else make_mesh()

    def body(block):
        return jax.lax.psum(jnp.sum(block), AXIS)

    fn = _smap(
        body, mesh, in_specs=PartitionSpec(*spec), out_specs=PartitionSpec()
    )
    return fn(arr)


def broadcast_scalar(value, mesh: Mesh | None = None):
    """Root rank's value to all ranks (reference ``broadcast_scalar``; under
    the single-controller model every process already holds host scalars, so
    this is the in-mesh form: rank 0's lane wins)."""
    mesh = mesh if mesh is not None else make_mesh()
    nprocs = mesh.shape[AXIS]

    def body(vals):  # vals: (1,) per rank
        mine = jnp.where(jax.lax.axis_index(AXIS) == 0, vals[0], 0.0)
        return jnp.full((1,), jax.lax.psum(mine, AXIS))

    per_rank = jnp.asarray(value, dtype=jnp.result_type(value, 0.0)).reshape(())
    stacked = jnp.broadcast_to(per_rank, (nprocs,))
    fn = _smap(body, mesh, in_specs=PartitionSpec(AXIS), out_specs=PartitionSpec(AXIS))
    return fn(stacked)[0]


def gather_root(arr) -> np.ndarray:
    """Full global array on the host — the reference's gather-to-root IO path
    (/root/reference/src/field_mpi/io.rs:45-70).  Under JAX's
    single-controller model this is one device-to-host fetch; across real
    multi-host meshes use jax.experimental.multihost_utils instead."""
    return np.asarray(arr)


def scatter_root(values, decomp: Decomp2d, pencil: str = "y"):
    """Host array -> pencil-sharded device array (reference scatter,
    field_mpi.rs:359-453)."""
    if pencil == "y":
        return decomp.place_y_pencil(values)
    return decomp.place_x_pencil(values)
