"""Explicit pencil decomposition + collectives (the MPI-parity surface).

The models shard through GSPMD constraints (parallel/mesh.py) and never call
these directly — XLA places the all-to-alls.  This module provides the
*explicit* counterpart of the reference's distributed API for user code and
custom kernels: funspace's ``Decomp2d`` bookkeeping with its
``transpose_x_to_y``/``transpose_y_to_x`` repartitions as
``shard_map`` + ``jax.lax.all_to_all`` over the ICI mesh, and the collectives
the reference re-exports (``all_gather_sum``, ``broadcast_scalar``,
gather/scatter to root) — SURVEY.md S2.2 (/root/reference/src/mpi/mod.rs:2-12,
src/field_mpi.rs:455-477).

Pencil convention (reference field_mpi.rs:71-88):

* **y-pencil**: axis 0 (x) distributed, axis 1 contiguous — physical data.
* **x-pencil**: axis 1 (y) distributed, axis 0 contiguous — spectral data.

The explicit transposes accept arbitrary (odd) extents — the equal-tile
all_to_all runs on a zero-padded shape and the pad is sliced away — so the
MPI-parity surface expresses the production grids (129/1025/2049) just like
funspace's transpose_x_to_y.  The GSPMD constraint path in the models
remains the execution path for the physics.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .mesh import AXIS, PHYS, SPEC, make_mesh  # noqa: F401  (re-exported)

try:  # jax>=0.4.35
    from jax import shard_map

    def _smap(f, mesh, in_specs, out_specs):
        return shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)

except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

    def _smap(f, mesh, in_specs, out_specs):
        return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


@dataclass(frozen=True)
class Pencil:
    """One rank's slab of one pencil orientation (reference ``Decomp2d``
    pencils expose st/en/sz, src/field_mpi.rs:128-135)."""

    st: tuple[int, int]  # global start index per axis (inclusive)
    en: tuple[int, int]  # global end index per axis (inclusive)
    sz: tuple[int, int]  # local shape
    dist_axis: int  # which axis is distributed

    @property
    def axis_contig(self) -> int:
        """The undivided axis (field_mpi/average.rs:50)."""
        return 1 - self.dist_axis


def _split(n: int, nprocs: int, rank: int) -> tuple[int, int]:
    """Balanced contiguous split: first (n % nprocs) ranks get one extra."""
    base, extra = divmod(n, nprocs)
    st = rank * base + min(rank, extra)
    sz = base + (1 if rank < extra else 0)
    return st, sz


class Decomp2d:
    """Pencil bookkeeping + explicit repartitions over a 1-D device mesh.

    ``x_pencil(rank)`` / ``y_pencil(rank)`` give each rank's slab exactly as
    the reference's decomp object does; ``transpose_x_to_y`` /
    ``transpose_y_to_x`` are the all-to-all repartitions (jittable,
    differentiable, runnable inside other shard_mapped code via the
    ``*_local`` variants).
    """

    def __init__(self, global_shape: tuple[int, int], mesh: Mesh | None = None):
        self.mesh = mesh if mesh is not None else make_mesh()
        self.nprocs = self.mesh.shape[AXIS]
        self.global_shape = tuple(global_shape)

    # -- bookkeeping ---------------------------------------------------------

    def _pencil(self, rank: int, dist_axis: int) -> Pencil:
        n0, n1 = self.global_shape
        if dist_axis == 0:
            st0, sz0 = _split(n0, self.nprocs, rank)
            return Pencil((st0, 0), (st0 + sz0 - 1, n1 - 1), (sz0, n1), 0)
        st1, sz1 = _split(n1, self.nprocs, rank)
        return Pencil((0, st1), (n0 - 1, st1 + sz1 - 1), (n0, sz1), 1)

    def y_pencil(self, rank: int) -> Pencil:
        """Axis 0 distributed (physical-data layout)."""
        return self._pencil(rank, 0)

    def x_pencil(self, rank: int) -> Pencil:
        """Axis 1 distributed (spectral-data layout)."""
        return self._pencil(rank, 1)

    # -- explicit repartitions ----------------------------------------------

    def _pad(self, arr):
        """Zero-pad both extents up to the next mesh multiple so the tiled
        all_to_all exchanges equal blocks; the flagship grids are odd
        (129/1025/2049 — funspace's transpose_x_to_y takes any extent,
        SURVEY.md S2.2, and so does this).  The pad rows/cols ride the
        collective and are sliced away by the caller."""
        n0, n1 = self.global_shape
        p0 = (-n0) % self.nprocs
        p1 = (-n1) % self.nprocs
        if p0 or p1:
            arr = jnp.pad(arr, ((0, p0), (0, p1)))
        return arr

    @staticmethod
    def transpose_x_to_y_local(block):
        """Inside-shard_map body: x-pencil block (n0, n1/P) -> y-pencil
        block (n0/P, n1) (funspace transpose_x_to_y)."""
        return jax.lax.all_to_all(block, AXIS, split_axis=0, concat_axis=1, tiled=True)

    @staticmethod
    def transpose_y_to_x_local(block):
        """Inside-shard_map body: y-pencil block (n0/P, n1) -> x-pencil
        block (n0, n1/P)."""
        return jax.lax.all_to_all(block, AXIS, split_axis=1, concat_axis=0, tiled=True)

    def transpose_x_to_y(self, arr):
        """Global-view repartition: axis-1-sharded -> axis-0-sharded.
        Any extents (pad-and-slice around the equal-tile all_to_all)."""
        n0, n1 = self.global_shape
        fn = _smap(
            self.transpose_x_to_y_local,
            self.mesh,
            in_specs=PartitionSpec(*SPEC),
            out_specs=PartitionSpec(*PHYS),
        )
        return fn(self._pad(arr))[:n0, :n1]

    def transpose_y_to_x(self, arr):
        n0, n1 = self.global_shape
        fn = _smap(
            self.transpose_y_to_x_local,
            self.mesh,
            in_specs=PartitionSpec(*PHYS),
            out_specs=PartitionSpec(*SPEC),
        )
        return fn(self._pad(arr))[:n0, :n1]

    # -- placement helpers ---------------------------------------------------

    def place_y_pencil(self, arr):
        return jax.device_put(
            jnp.asarray(arr), NamedSharding(self.mesh, PartitionSpec(*PHYS))
        )

    def place_x_pencil(self, arr):
        return jax.device_put(
            jnp.asarray(arr), NamedSharding(self.mesh, PartitionSpec(*SPEC))
        )


# ---------------------------------------------------------------------------
# collectives (reference src/mpi/mod.rs re-exports)
# ---------------------------------------------------------------------------


def all_gather_sum(arr, mesh: Mesh | None = None, spec=PHYS):
    """Sum a sharded array's per-rank contributions so every rank holds the
    global sum — the reference's ``all_gather_sum``
    (/root/reference/src/navier_stokes_mpi/functions.rs:137-139).  ``arr`` is
    the global view sharded by ``spec``; the result is fully replicated."""
    mesh = mesh if mesh is not None else make_mesh()

    def body(block):
        return jax.lax.psum(jnp.sum(block), AXIS)

    fn = _smap(
        body, mesh, in_specs=PartitionSpec(*spec), out_specs=PartitionSpec()
    )
    return fn(arr)


def broadcast_scalar(value, mesh: Mesh | None = None):
    """Root rank's value to all ranks (reference ``broadcast_scalar``; under
    the single-controller model every process already holds host scalars, so
    this is the in-mesh form: rank 0's lane wins)."""
    mesh = mesh if mesh is not None else make_mesh()
    nprocs = mesh.shape[AXIS]

    def body(vals):  # vals: (1,) per rank
        mine = jnp.where(jax.lax.axis_index(AXIS) == 0, vals[0], 0.0)
        return jnp.full((1,), jax.lax.psum(mine, AXIS))

    per_rank = jnp.asarray(value, dtype=jnp.result_type(value, 0.0)).reshape(())
    stacked = jnp.broadcast_to(per_rank, (nprocs,))
    fn = _smap(body, mesh, in_specs=PartitionSpec(AXIS), out_specs=PartitionSpec(AXIS))
    return fn(stacked)[0]


def gather_root(arr) -> np.ndarray:
    """Full global array on the host — the reference's gather-to-root IO path
    (/root/reference/src/field_mpi/io.rs:45-70).  Under JAX's
    single-controller model this is one device-to-host fetch; across real
    multi-host meshes use jax.experimental.multihost_utils instead."""
    return np.asarray(arr)


def scatter_root(values, decomp: Decomp2d, pencil: str = "y"):
    """Host array -> pencil-sharded device array (reference scatter,
    field_mpi.rs:359-453)."""
    if pencil == "y":
        return decomp.place_y_pencil(values)
    return decomp.place_x_pencil(values)
