"""Device mesh + pencil-sharding layer.

TPU rebuild of the reference's distributed backend (funspace::spaces_mpi /
Decomp2d, SURVEY.md S2.2-S2.3): a 1-D device mesh over which 2-D fields are
pencil-decomposed.  The reference's convention is kept exactly —

* **physical** data in y-pencils: axis 0 (x) distributed, P("p", None)
* **spectral** data in x-pencils: axis 1 (y) distributed, P(None, "p")

but instead of hand-written MPI all-to-alls
(/root/reference/src/field_mpi.rs:455-477) the repartitions are expressed as
``jax.lax.with_sharding_constraint`` at the pencil-flip points inside
transforms and solvers; XLA GSPMD inserts the all-to-all collectives and
overlaps them with compute.  One code path serves serial and sharded
execution: with no active mesh every constraint is a no-op, so the physics
layer (models/navier.py) is written once — the reference's duplicated
navier_stokes vs navier_stokes_mpi modules collapse into one.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

AXIS = "p"

_ACTIVE: Mesh | None = None

# pencil specs (reference convention, /root/reference/src/field_mpi.rs:71-88)
PHYS = (AXIS, None)  # y-pencil: x distributed
SPEC = (None, AXIS)  # x-pencil: y distributed


def make_mesh(devices=None) -> Mesh:
    """1-D mesh over all (or the given) devices."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.array(devices), (AXIS,))


def set_mesh(mesh: Mesh | None) -> None:
    """Install ``mesh`` as the active pencil mesh (None disables sharding)."""
    global _ACTIVE
    _ACTIVE = mesh


def active_mesh() -> Mesh | None:
    return _ACTIVE


class use_mesh:
    """Context manager scoping an active mesh."""

    def __init__(self, mesh: Mesh | None):
        self.mesh = mesh
        self.prev: Mesh | None = None

    def __enter__(self):
        self.prev = active_mesh()
        set_mesh(self.mesh)
        return self.mesh

    def __exit__(self, *exc):
        set_mesh(self.prev)
        return False


def pencil_sharding(mesh: Mesh, spec: tuple, ndim: int | None = None) -> NamedSharding:
    """NamedSharding for ``spec`` on an EXPLICIT mesh.  When ``ndim``
    exceeds the spec rank the spec applies to the *trailing* dims (leading
    dims are replicated batch).  This is the active-mesh-free form — the
    sharded-checkpoint restore (utils/checkpoint.read_sharded_snapshot)
    builds target layouts for meshes that are not installed as the active
    pencil mesh."""
    if ndim is not None and ndim > len(spec):
        spec = (None,) * (ndim - len(spec)) + tuple(spec)
    return NamedSharding(mesh, PartitionSpec(*spec))


def sharding(spec: tuple, ndim: int | None = None) -> NamedSharding | None:
    """NamedSharding for ``spec`` on the ACTIVE mesh; when ``ndim`` exceeds
    the spec rank the spec applies to the *trailing* dims (leading dims are
    replicated batch — the stacked-field transforms in models/navier.py)."""
    mesh = active_mesh()
    if mesh is None:
        return None
    return pencil_sharding(mesh, spec, ndim)


# Small arrays whose sharded dim does not divide the mesh are PLACED fully
# replicated instead of being left uncommitted: a tiny parameter (the
# f64[9,17] `pres` in MULTICHIP_r05.json) that enters a dispatch with a
# leftover compiler-chosen partial sharding (e.g. [2,1,4]
# last_tile_dim_replicate) that the executable's parameter layout cannot
# consume forces an "[SPMD] Involuntary full rematerialization" — a full
# replicate-then-repartition on EVERY dispatch.  An explicitly replicated
# input is the one layout every executable can consume with at worst a
# local slice.  Large non-divisible arrays (the odd spectral sizes 129,
# 1025, ...) are still left to the in-jit padded constraints — replication
# there would be real memory.
REPLICATE_MAX_ELEMS = 1 << 14


def constrain(x, spec: tuple):
    """Pin ``x`` to a pencil layout inside a jitted computation; no-op without
    an active mesh.  This is the TPU equivalent of the reference's
    transpose_x_to_y/transpose_y_to_x calls — the collective itself is left
    to XLA.  Outside a trace (eager setup code) it becomes a resharding.
    Arrays with more dims than the spec treat the extra leading dims as
    replicated batch.

    NOTE in-jit constraints deliberately do NOT take the small-array
    replicated pin below: the pencil-flip constraint pattern inside the
    transforms is what the serial==sharded 1e-12 equality tests validate,
    and rewriting it for small grids changes GSPMD's fusion choices (the
    17^2/33x32 sharded test grids all sit under any useful size
    threshold).  Only EAGER placement (``device_put``) canonicalizes."""
    s = sharding(spec, np.ndim(x))
    if s is None:
        return x
    if _is_tracer(x):
        return jax.lax.with_sharding_constraint(x, s)
    return device_put(x, spec)


_TRACER_TYPE = getattr(jax.core, "Tracer", None)  # deprecated home; may vanish


def _is_tracer(x) -> bool:
    if _TRACER_TYPE is not None:
        return isinstance(x, _TRACER_TYPE)
    # fallback for JAX releases that drop jax.core.Tracer: concrete arrays
    # expose addressable shards, while a tracer's accessor raises (a
    # ConcretizationTypeError, i.e. TypeError — hasattr doesn't swallow it)
    if not isinstance(x, jax.Array):
        return False
    try:
        x.addressable_shards
    except Exception:
        return True
    return False


def device_put(x, spec: tuple):
    """Place an array in pencil layout (host->device with sharding).

    Spectral grid sizes are typically odd (129, 1025, ...), so sharded dims
    are often not divisible by the mesh.  Explicit placement (device_put /
    out_shardings) rejects that in JAX; only in-jit sharding constraints pad.
    Non-divisible arrays are therefore left as-is here — the constraints
    inside the first jitted step distribute them."""
    mesh = active_mesh()
    if mesh is None:
        return x
    import jax.numpy as jnp

    arr = jnp.asarray(x)
    s = sharding(spec, arr.ndim)
    # one source of truth for the leading-batch padding: read the padded
    # spec back off the sharding itself
    divisible = all(
        sp is None or arr.shape[i] % mesh.shape[sp] == 0
        for i, sp in enumerate(s.spec)
    )
    if divisible:
        return jax.device_put(arr, s)
    if arr.size <= REPLICATE_MAX_ELEMS:
        # explicit replication is always a legal placement; it also matches
        # the in-jit constraint for the same array (see constrain), so no
        # executable ever has to repartition it involuntarily
        return jax.device_put(
            arr, NamedSharding(mesh, PartitionSpec(*([None] * arr.ndim)))
        )
    return arr
