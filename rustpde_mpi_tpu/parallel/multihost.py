"""Multi-host (multi-slice / DCN) entry points.

The reference scales across nodes with MPI ranks (rsmpi over system MPI,
/root/reference/src/mpi/mod.rs); the JAX equivalent is one *controller per
host* with a global device mesh — intra-slice traffic rides ICI, inter-slice
DCN, and the same GSPMD/pencil code (parallel/mesh.py, parallel/decomp.py)
runs unchanged on the larger mesh.  This module is the thin glue:

* :func:`initialize_distributed` — ``jax.distributed.initialize`` with the
  standard env-var conventions (the MPI_Init analog).
* :func:`global_pencil_mesh` — the 1-D pencil mesh over every device of
  every host.
* :func:`host_local_array` / :func:`global_array` — host-slab <-> global
  array conversion for IO (the gather/scatter-to-root analog across hosts).
* :func:`sync_hosts` — barrier.
* :func:`allgather_host` / :func:`broadcast` — small host-value collectives
  (the sharded-checkpoint digest exchange and the root-decides handshakes
  in utils/resilience.py ride these).

Single-host processes (including this container's one-chip tunnel and the
virtual CPU mesh) can call everything here unchanged: initialization is a
no-op fallback and the conversions degenerate to identity, which is what the
single-controller tests exercise.  True multi-host execution needs one
process per host started with the same script (the driver/launcher's job),
exactly as the reference needs ``mpirun``.
"""

from __future__ import annotations

import json
import os

import jax
import numpy as np

from .mesh import make_mesh
from . import sanitizer as _sanitizer
from ..config import env_get


def _cluster_env_configured() -> bool:
    """True when the environment really describes a multi-host cluster — an
    initialization failure must then propagate, not silently degrade to N
    independent single-host runs.  A coordinator address is definitive; a
    worker-hostname list counts only when it names more than one host (TPU
    plugins set TPU_WORKER_HOSTNAMES=localhost even on one chip)."""
    if os.environ.get("JAX_COORDINATOR_ADDRESS") or os.environ.get(
        "MEGASCALE_COORDINATOR_ADDRESS"
    ):
        return True
    if "," in os.environ.get("TPU_WORKER_HOSTNAMES", ""):
        return True
    # schedulers jax.distributed auto-detects: gate on *per-step* launch
    # variables (set by srun/mpirun for this very process), not allocation-
    # level ones — a single `python` inside an --ntasks=8 batch allocation
    # is still a single-host run
    for var in ("SLURM_STEP_NUM_TASKS", "OMPI_COMM_WORLD_SIZE"):
        try:
            if int(os.environ.get(var, "1")) > 1:
                return True
        except ValueError:
            pass
    return False


#: pre-collective device fence (set_device_fence): while a campaign runs on
#: a PROPER sub-mesh, host-level collectives here (full-device barriers and
#: broadcasts) can start on the sub-mesh's IDLE complement immediately and
#: their wire traffic interleaves nondeterministically with the campaign's
#: still-in-flight collectives on the same transport pairs — gloo then
#: mispairs ops across hosts ("op.preamble.length <= op.nbytes").  A full
#: mesh never hits this: the barrier executable cannot start anywhere until
#: the step program releases the devices, so wire order is host-consistent.
#: The serve scheduler installs a fence that blocks on the active campaign's
#: dispatches; every entry point below runs it before touching the wire.
_device_fence = None


def set_device_fence(fn) -> None:
    """Install (``fn``) or clear (``None``) the pre-collective device fence —
    the serve scheduler's sub-mesh campaign guard (see ``_device_fence``)."""
    global _device_fence
    _device_fence = fn


def _fence() -> None:
    fence = _device_fence
    if fence is not None:
        fence()


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Initialize the multi-process runtime (MPI_Init analog).

    Arguments default to the standard env vars (JAX_COORDINATOR_ADDRESS,
    JAX_NUM_PROCESSES, JAX_PROCESS_ID) or cloud auto-detection — None values
    are passed through to ``jax.distributed.initialize`` so its own
    auto-detection stays in charge.  Returns True if a multi-process runtime
    was initialized, False when running single-process (no cluster
    configured) — callers need no branches, jax.devices() is global either
    way."""
    if num_processes is not None and (
        coordinator_address is None
        and os.environ.get("JAX_COORDINATOR_ADDRESS") is None
    ):
        raise ValueError(
            "num_processes given but no coordinator address (argument or "
            "JAX_COORDINATOR_ADDRESS)"
        )
    # CPU clusters need an explicit cross-process collectives backend: since
    # jax 0.4.37 a multi-process CPU computation without one dies with
    # "Multiprocess computations aren't implemented on the CPU backend".
    # Select gloo BEFORE backend init when the run is pinned to CPU (the
    # 2-process test/bench harness, tests/mp_worker.py); other platforms
    # keep their native transports (ICI/DCN).
    if (jax.config.jax_platforms or "").split(",")[0] == "cpu":
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass  # older jax: single-process CPU still works unchanged
    explicit = any(
        v is not None for v in (coordinator_address, num_processes, process_id)
    )
    if not explicit and not _cluster_env_configured():
        # plain single-host launch: probe auto-detection, degrade quietly
        try:
            jax.distributed.initialize()
        except Exception:
            return False
        return jax.process_count() > 1
    # a cluster is configured (explicitly or via env) — failures are real
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return jax.process_count() > 1


def global_pencil_mesh() -> jax.sharding.Mesh:
    """1-D pencil mesh over all devices of all hosts — pass as ``mesh=`` to
    any model; pencil axes then span ICI within a slice and DCN across."""
    return make_mesh()


def process_index() -> int:
    """This host's rank (the reference's ``nrank``)."""
    return jax.process_index()


def is_root() -> bool:
    """Rank-0 check for root-guarded IO/logging
    (/root/reference/src/mpi/mod.rs:57-74)."""
    return jax.process_index() == 0


def global_array(host_local: np.ndarray, sharding) -> jax.Array:
    """Assemble per-host slabs into one global sharded array
    (scatter analog).  Identity-like on a single host."""
    if jax.process_count() == 1:
        return jax.device_put(host_local, sharding)
    from jax.experimental import multihost_utils

    return multihost_utils.host_local_array_to_global_array(
        host_local, sharding.mesh, sharding.spec
    )


def host_local_array(arr: jax.Array, spec: tuple | None = None) -> np.ndarray:
    """This host's slab of a global array (gather analog for per-host IO);
    the full array on a single host.

    The conversion needs a mesh+spec.  Arrays coming out of jitted steps
    carry an inferred GSPMDSharding (no mesh attached), so such arrays are
    first re-placed onto the canonical pencil mesh with ``spec`` (default:
    the spectral x-pencil layout every model state uses) — a same-device
    resharding, metadata-only when the layouts already agree."""
    if jax.process_count() == 1:
        return np.asarray(arr)  # lint-ok: RPD005 single-process: every shard is addressable by definition
    from jax.experimental import multihost_utils

    from .mesh import SPEC, make_mesh

    if not isinstance(arr.sharding, jax.sharding.NamedSharding):
        named = jax.sharding.NamedSharding(
            make_mesh(), jax.sharding.PartitionSpec(*(SPEC if spec is None else spec))
        )
        # jit-resharding rather than device_put: GSPMD pads non-divisible
        # dims (the odd spectral grid sizes), eager placement rejects them
        arr = jax.jit(lambda a: a, out_shardings=named)(arr)
    return multihost_utils.global_array_to_host_local_array(
        arr, arr.sharding.mesh, arr.sharding.spec
    )


def allgather_host(value) -> np.ndarray:
    """Allgather a small host value across processes: every host gets the
    stacked ``(nproc, ...)`` array (rank order).  The sharded-checkpoint
    commit uses this to exchange per-shard digests/byte counts so root can
    write the manifest without re-reading any shard file.  Single-host:
    the value with a length-1 leading axis."""
    _sanitizer.record("allgather", payload=value)
    if jax.process_count() == 1:
        return np.asarray(value)[None]  # lint-ok: RPD005 allgather payloads are small host values by contract
    _fence()
    from jax.experimental import multihost_utils

    out = np.asarray(multihost_utils.process_allgather(np.asarray(value)))  # lint-ok: RPD005 allgather payloads are small host values by contract
    _sanitizer.maybe_verify()
    return out


def broadcast(value, is_source: bool | None = None):
    """Root-decides broadcast of a small host value (the preemption/rollback
    handshake in utils/resilience.py and every serve-scheduler decision:
    rank 0 decides, every host acts on the same decision).  Identity on a
    single host; returns a numpy value.

    Like :func:`sync_hosts`, honors ``RUSTPDE_SYNC_TIMEOUT_S``: a peer that
    died while this host is blocked inside the collective would otherwise
    wedge the job forever — the root-coordinated scheduler runs several
    broadcasts per boundary, most of them outside any dispatch watchdog, so
    the structured-exit contract (journaled error stop, requests recovered
    on restart) needs the timeout here too."""
    if _sanitizer.skip_broadcast_injected():
        # armed desync injection (tests): this host skips the collective
        # entirely — no record, no broadcast — the PR-10 bug shape
        return np.asarray(value)  # lint-ok: RPD005 broadcast payloads are small host values by contract
    _sanitizer.record("broadcast", payload=value)
    if jax.process_count() == 1:
        return np.asarray(value)  # lint-ok: RPD005 broadcast payloads are small host values by contract
    _fence()
    from jax.experimental import multihost_utils

    def run():
        return multihost_utils.broadcast_one_to_all(
            np.asarray(value), is_source=is_source  # lint-ok: RPD005 broadcast payloads are small host values by contract
        )

    timeout = float(env_get("RUSTPDE_SYNC_TIMEOUT_S", "0") or 0.0)
    if timeout <= 0:
        out = run()
    else:
        from ..utils.resilience import call_with_watchdog

        out = call_with_watchdog(run, timeout, label="broadcast")
    _sanitizer.maybe_verify()
    return out


def allgather_bytes(data: bytes) -> list[bytes]:
    """Allgather one variable-length byte blob per host: every host returns
    ``[host0_bytes, host1_bytes, ...]`` in rank order.  Two allgathers ride
    underneath — a length exchange, then a padded uint8 buffer — because
    ``process_allgather`` needs identical shapes on every host.  The
    telemetry layer's fleet aggregation (metrics snapshots, request-trace
    gathers) rides this one primitive.  Single-host: ``[data]``."""
    if jax.process_count() == 1:
        return [bytes(data)]
    blob = np.frombuffer(bytes(data), np.uint8)
    lengths = allgather_host(np.int64(blob.size))
    width = max(1, int(lengths.max()))
    padded = np.zeros(width, np.uint8)
    padded[: blob.size] = blob
    stack = allgather_host(padded)
    return [
        bytes(stack[i, : int(lengths[i])]) for i in range(stack.shape[0])
    ]


def broadcast_obj(obj=None):
    """Root-decides broadcast of an arbitrary JSON-able host object (the
    serve scheduler's per-boundary decision plans: bucket keys, slot
    claim/refill assignments, retry/requeue verdicts).  Non-root callers
    pass anything (ignored); every host returns root's object.  Two
    broadcasts ride underneath — a length, then a padded byte buffer —
    because ``broadcast_one_to_all`` needs an identical shape on every
    host.  Identity on a single host.

    JSON round-trips tuples into lists; callers holding tuple-shaped keys
    re-tuple with :func:`tuplify`."""
    import jax

    if jax.process_count() == 1:
        return obj
    payload = b""
    if is_root():
        payload = json.dumps(obj).encode("utf-8")
    n = int(broadcast(np.int64(len(payload))))
    buf = np.zeros(n, dtype=np.uint8)
    if is_root():
        buf[:] = np.frombuffer(payload, dtype=np.uint8)
    # the collective may widen the dtype (psum upcast): cast back before
    # reinterpreting the element values as utf-8 bytes
    data = np.asarray(broadcast(buf)).astype(np.uint8)  # lint-ok: RPD005 broadcast returns a host numpy value
    return json.loads(data.tobytes().decode("utf-8"))


def root_decides(local: bool) -> bool:
    """Root's verdict for a host flag that leads into a collective
    (preemption/drain stops, cadence checkpoints, serve-loop exits): rank
    0's value is broadcast so every host takes the same branch — hosts
    evaluating signals or wall clocks locally would disagree and wedge the
    next collective.  A stray local flag on a non-root host is therefore
    deliberately IGNORED.  Single-host (or uninitialized runtime): the
    local flag.  One copy of the primitive — the resilient runner and the
    serve scheduler both ride it, so the handshake cannot drift."""
    _sanitizer.record("root_decides")
    try:
        if jax.process_count() == 1:
            return bool(local)
    except Exception:
        return bool(local)
    return bool(int(broadcast(np.int32(1 if local else 0))))


def tuplify(obj):
    """Recursively convert lists back to tuples (the inverse of the
    tuple->list coercion a JSON round-trip applies to compat keys)."""
    if isinstance(obj, list):
        return tuple(tuplify(v) for v in obj)
    return obj


def sync_hosts(tag: str = "barrier", timeout_s: float | None = None) -> None:
    """Cross-host barrier (the reference's MPI barrier,
    src/field_mpi/io_mpi_sequ.rs:46); no-op single-host.

    ``sync_global_devices`` blocks FOREVER if a peer host died (the silent
    job-wide hang that ate PR 1's tier-1 budget).  ``RUSTPDE_SYNC_TIMEOUT_S``
    (default off) arms a watchdog: after the deadline every thread's stack is
    dumped to stderr together with the barrier tag, and a structured
    :class:`~rustpde_mpi_tpu.utils.resilience.DispatchHang` is raised so the
    scheduler sees a crash it can restart instead of a wedged job.

    ``timeout_s`` overrides the env knob for callers with a tighter
    deadline contract than the job-wide default — the gang barrier
    (serve/fleet/gang.py) passes ``RUSTPDE_GANG_SYNC_TIMEOUT_S`` here so
    a dead gang member surfaces in seconds, not the global sync budget."""
    _sanitizer.record("sync", tag=tag)
    if jax.process_count() == 1:
        return
    _fence()
    from jax.experimental import multihost_utils

    if timeout_s is not None:
        timeout = float(timeout_s)
    else:
        timeout = float(env_get("RUSTPDE_SYNC_TIMEOUT_S", "0") or 0.0)
    if timeout <= 0:
        multihost_utils.sync_global_devices(tag)
    else:
        from ..utils.resilience import call_with_watchdog

        call_with_watchdog(
            lambda: multihost_utils.sync_global_devices(tag),
            timeout,
            label=f"sync_hosts({tag!r})",
        )
    _sanitizer.maybe_verify()
