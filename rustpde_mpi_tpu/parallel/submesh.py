"""Sub-mesh carving: failure-domain partitioning of the fleet's devices.

The ROADMAP's two-level-serve item in mechanism form: the global device
set is carved into SUB-MESHES so one pencil-sharded flagship campaign (a
gang, serve/fleet/gang.py) claims a slice of the fleet while vmapped
small-grid buckets keep the remainder — one service, both regimes, and a
gang death is contained to its own slice.

Two halves, deliberately separated:

* **Canonicalization** (:func:`shape_for`, :func:`grid_fits`) is PURE —
  no jax, no devices: the admission tier (the stateless proxies above
  all, which never initialize a JAX runtime) stamps the sub-mesh shape
  into the request from the CONFIGURED shape list alone, so equal grids
  always land in the same bucket (`SimRequest.compat_key` gains the
  stamp) no matter which front admitted them.
* **Carving** (:func:`carve`) binds shapes to actual devices at campaign
  time, on the serving replica: devices are interleaved round-robin
  across processes so every process contributes equally to every
  sub-mesh — a multihost collective over any sub-mesh then involves
  every process (no process is ever absent from a barrier), while the
  DEVICES of different sub-meshes stay disjoint (the failure-domain
  boundary the gang lease fate-shares over).

A fleet that shrank below a stamped shape does not strand the bucket:
:meth:`SubmeshPlan.place` re-maps it onto the largest still-fitting
sub-mesh and reports the remap so the scheduler can journal a
``gang_replanned`` row (the elastic re-carve).
"""

from __future__ import annotations

import dataclasses


def grid_fits(nx: int, ny: int, shape: int) -> bool:
    """Can an ``nx`` x ``ny`` grid be pencil-sharded over ``shape``
    devices?  Conservative divisibility rule: each dimension must split
    evenly either as the full extent or as the interior (``n - 2``, the
    Chebyshev spectral extent the transpose pipeline actually shards).
    ``shape == 1`` always fits (unsharded)."""
    if shape <= 1:
        return True

    def dim_ok(n: int) -> bool:
        return n % shape == 0 or (n - 2) % shape == 0

    return dim_ok(int(nx)) and dim_ok(int(ny))


def shape_for(nx: int, ny: int, cfg) -> int:
    """The canonical sub-mesh stamp for one request grid under a
    :class:`~rustpde_mpi_tpu.config.SubmeshConfig`: ``0`` (vmapped
    default traffic) for grids below ``shard_min_nx``, else the SMALLEST
    configured shape the grid divides onto — smallest, so flagship
    traffic takes no more of the fleet than it needs and the choice is
    deterministic across admission fronts.  Returns ``-1`` when the grid
    must shard (at/above ``shard_min_nx``) but no configured shape fits:
    the caller rejects at POST time (``reason="no_submesh"``) instead of
    durably enqueuing a poison pill no replica can ever serve."""
    if max(int(nx), int(ny)) < int(cfg.shard_min_nx):
        return 0
    for shape in sorted(int(s) for s in cfg.shapes):
        if shape > 1 and grid_fits(nx, ny, shape):
            return shape
    return -1


@dataclasses.dataclass(frozen=True)
class Submesh:
    """One carved slice: its ordinal (the gang index faults/journals name),
    its device count, and the devices themselves (process-interleaved)."""

    index: int
    shape: int
    devices: tuple

    def mesh(self):
        """The jax Mesh over exactly these devices (pencil axis ``p``)."""
        from . import mesh as _mesh

        return _mesh.make_mesh(list(self.devices))


@dataclasses.dataclass
class SubmeshPlan:
    """The root plan's carve of the device set: gang sub-meshes first (in
    configured-shape order), the remainder as the DEFAULT sub-mesh serving
    vmapped traffic.  Built by :func:`carve`; root computes it once per
    serve incarnation and every process derives the identical plan from
    the identical (globally-consistent) ``jax.devices()`` order."""

    submeshes: tuple  # gang-capable slices, disjoint devices
    default: Submesh | None  # the vmapped remainder (None: nothing left)
    nproc: int = 1

    def by_shape(self, shape: int) -> Submesh | None:
        """The first carved sub-mesh of exactly ``shape`` devices."""
        for sm in self.submeshes:
            if sm.shape == int(shape):
                return sm
        return None

    def place(self, nx: int, ny: int, shape: int):
        """Bind one stamped bucket to a carved sub-mesh.  Exact stamp
        match when the carve still has it; otherwise the elastic re-carve:
        the largest carved sub-mesh the grid still divides onto (fleet
        shrank between admission and service).  Returns
        ``(submesh, replanned)``; ``(None, False)`` when nothing fits —
        the bucket stays queued for a bigger fleet."""
        sm = self.by_shape(shape)
        if sm is not None and grid_fits(nx, ny, sm.shape):
            return sm, False
        best = None
        for cand in sorted(
            self.submeshes, key=lambda s: s.shape, reverse=True
        ):
            if grid_fits(nx, ny, cand.shape):
                best = cand
                break
        return best, best is not None


def interleave(devices, nproc: int | None = None) -> list:
    """Process-interleaved device order: position ``k`` holds the
    ``k // nproc``-th local device of process ``k % nproc``, so any
    contiguous chunk of ``m * nproc`` devices takes exactly ``m`` devices
    from EVERY process.  Devices without a ``process_index`` (CPU test
    doubles) are treated as one process."""
    by_proc: dict[int, list] = {}
    for d in devices:
        by_proc.setdefault(int(getattr(d, "process_index", 0)), []).append(d)
    procs = sorted(by_proc)
    out = []
    depth = max(len(v) for v in by_proc.values()) if by_proc else 0
    for i in range(depth):
        for p in procs:
            if i < len(by_proc[p]):
                out.append(by_proc[p][i])
    return out


def carve(devices, shapes, nproc: int | None = None) -> SubmeshPlan:
    """Partition ``devices`` into gang sub-meshes of the configured
    ``shapes`` (largest first, so big gangs claim contiguous interleaved
    runs before small ones fragment them) plus the default remainder.

    Shapes that no longer fit the device count are DROPPED, not an error:
    the plan serves what the fleet can actually hold and the scheduler's
    placement re-maps stamped buckets elastically.  On a multi-process
    runtime every shape must take equal devices from every process
    (``shape % nproc == 0``) — a sub-mesh missing a process entirely
    would break the every-process-participates collective contract."""
    devs = list(devices)
    nproc = int(nproc) if nproc else len(
        {int(getattr(d, "process_index", 0)) for d in devs} or {0}
    )
    ordered = interleave(devs, nproc)
    slices = []
    cursor = 0
    for shape in sorted((int(s) for s in shapes), reverse=True):
        if shape <= 1 or shape % nproc != 0 and nproc > 1:
            continue
        if cursor + shape > len(ordered):
            continue  # fleet too small for this shape now: dropped
        slices.append((shape, tuple(ordered[cursor : cursor + shape])))
        cursor += shape
    submeshes = tuple(
        Submesh(index=i, shape=shape, devices=devs)
        for i, (shape, devs) in enumerate(slices)
    )
    rest = tuple(ordered[cursor:])
    default = (
        Submesh(index=len(submeshes), shape=len(rest), devices=rest)
        if rest
        else None
    )
    return SubmeshPlan(submeshes=submeshes, default=default, nproc=nproc)


def serve_key(model_key: tuple, shape: int) -> tuple:
    """The serve-side bucket key: the model 10-tuple, extended by the
    sub-mesh stamp when (and only when) the request is gang traffic —
    ``shape == 0`` keeps the bare 10-tuple, so with sub-meshes disabled
    every key is byte-identical to today's."""
    key = tuple(model_key)
    return key + (int(shape),) if int(shape) > 0 else key


def model_key(key: tuple) -> tuple:
    """Strip a serve key back to the model 10-tuple the workloads
    registry builds from (identity for bare keys)."""
    key = tuple(key)
    return key[:10] if len(key) == 11 else key


def key_shape(key: tuple) -> int:
    """The sub-mesh stamp of a serve key (0 = vmapped default traffic)."""
    key = tuple(key)
    return int(key[10]) if len(key) == 11 else 0
