"""Runtime collective-sequence sanitizer (``RUSTPDE_SANITIZE=1``).

The multihost correctness contract (README "Multihost campaigns") is that
EVERY host executes the identical sequence of collectives — each scheduling
decision root-computed and broadcast before any collective dispatch.  The
reference gets this for free from MPI's rigid call structure; our port
re-derives it by hand, and the repo's own history shows the failure mode:
a drain check evaluated outside the root plan left one host's collectives
out of phase (PR 10 review), and the symptom of any such desync is a
SILENT fleet wedge — every host blocked in a collective its peers never
entered, diagnosed only by a watchdog stack dump long after the divergent
decision ran.

With the sanitizer armed, every collective entry point in
:mod:`~rustpde_mpi_tpu.parallel.multihost` (``broadcast``,
``broadcast_obj`` via its inner broadcasts, ``allgather_host``,
``sync_hosts``, ``root_decides``) records ``(seq, kind, tag, call site,
payload-schema digest)`` into a bounded per-host ring plus a running
sha256 over the full history.  Every ``RUSTPDE_SANITIZE_CADENCE``
executed collectives, a fixed-shape hash compare rides one extra
``allgather_host`` — the trigger counts EXECUTED collectives, which stay
in lockstep across hosts at the transport level even when one host skipped
a call, so the verification exchange always pairs with itself.  On a hash
mismatch the hosts exchange their rings and every host raises a typed
:class:`CollectiveDesyncError` naming the FIRST divergent call site (and
dumps the telemetry flight recorder), turning the silent wedge into an
immediate, located diagnosis within one cadence.

Overhead contract: ``RUSTPDE_SANITIZE`` unset/0 costs one module-bool
branch per collective and records nothing — runs are bit-identical (the
sanitizer is host-side only and never touches traced programs; armed runs
are bit-identical too, gated in ``bench.py governor129``).  Armed, each
record is a frame walk + sha256 update — microseconds against the
milliseconds any real collective costs.

Injection (tests): ``RUSTPDE_SANITIZE_INJECT=skip_broadcast@<n>[:host<p>]``
makes the scoped host SKIP its ``<n>``-th broadcast entirely (no record,
no collective) — the exact shape of the PR-10 bug — so the 2-process test
can assert both hosts raise within one cadence.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import threading
from collections import deque

import numpy as np

from ..config import env_get

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SKIP_FILES = (os.sep + "multihost.py", os.sep + "sanitizer.py")


class CollectiveDesyncError(RuntimeError):
    """The cross-host collective sequences diverged.  ``seq`` is the global
    index of the first divergent record, ``sites`` maps process index ->
    that host's record at ``seq`` (or None where the host has no record —
    e.g. it skipped the call), ``site`` is the first divergent call site
    as a ``file:line`` string (the majority/root form, for log grepping)."""

    def __init__(self, message: str, seq: int | None = None,
                 sites: dict | None = None, site: str | None = None):
        super().__init__(message)
        self.seq = seq
        self.sites = sites or {}
        self.site = site


class _InjectPlan:
    """Parsed ``RUSTPDE_SANITIZE_INJECT`` spec (strict, like utils/faults)."""

    EXPECTED = "skip_broadcast@<n>[:host<p>]"

    def __init__(self, call: int, host: int | None):
        self.call = call
        self.host = host
        self.seen = 0

    @classmethod
    def from_spec(cls, spec: str | None) -> "_InjectPlan | None":
        if not spec:
            return None
        kind, sep, rest = spec.partition("@")
        if kind != "skip_broadcast" or not sep:
            raise ValueError(
                f"bad RUSTPDE_SANITIZE_INJECT {spec!r}: expected {cls.EXPECTED}"
            )
        at, hsep, host = rest.partition(":")
        if not at.isdigit():
            raise ValueError(
                f"bad RUSTPDE_SANITIZE_INJECT {spec!r}: bad call index {at!r}"
            )
        hostidx = None
        if hsep:
            if not host.startswith("host") or not host[4:].isdigit():
                raise ValueError(
                    f"bad RUSTPDE_SANITIZE_INJECT {spec!r}: bad host scope {host!r}"
                )
            hostidx = int(host[4:])
        return cls(int(at), hostidx)


class _State:
    def __init__(self):
        self.lock = threading.RLock()
        self.reload()

    def reload(self):
        self.enabled = env_get("RUSTPDE_SANITIZE", "0") == "1"
        self.cadence = max(1, int(env_get("RUSTPDE_SANITIZE_CADENCE", "32") or 32))
        capacity = max(8, int(env_get("RUSTPDE_SANITIZE_RING", "256") or 256))
        self.ring: deque = deque(maxlen=capacity)
        self.seq = 0
        self.hash = hashlib.sha256()
        # the verification trigger counts EXECUTED collectives (paired 1:1
        # across hosts at the transport level), NOT ring records: a
        # root_decides record carries intent without its own transport
        # slot, so record counts may skew across hosts after a skipped
        # call while executed counts cannot
        self.executed = 0
        self.last_verify_exec = 0
        self.in_verify = False
        self.run_dir: str | None = None
        self.records = 0
        self.verifies = 0
        self.desyncs = 0
        self.inject = _InjectPlan.from_spec(env_get("RUSTPDE_SANITIZE_INJECT"))


_STATE = _State()


def enabled() -> bool:
    return _STATE.enabled


def set_enabled(flag: bool) -> None:
    """Arm/disarm in-process (``RUSTPDE_SANITIZE`` env default; the bench
    overhead leg and tests toggle this)."""
    _STATE.enabled = bool(flag)


def reset() -> None:
    """Re-read every knob and clear the ring/counters (tests, and fresh
    service incarnations that want a clean sequence history)."""
    _STATE.reload()


def set_run_dir(path: str | None) -> None:
    """Where a desync trip dumps the telemetry flight record (the runner /
    serve session arms this alongside its own incident dumps)."""
    _STATE.run_dir = path


def stats() -> dict:
    """Host-local counters: records, verifies, desyncs, seq."""
    return {
        "enabled": _STATE.enabled,
        "records": _STATE.records,
        "executed": _STATE.executed,
        "verifies": _STATE.verifies,
        "desyncs": _STATE.desyncs,
        "seq": _STATE.seq,
        "cadence": _STATE.cadence,
    }


def np_schema(value) -> str:
    """Payload-schema digest of a small host value: dtype + shape (host-
    invariant when the fleet is in sync — values may differ, shapes not)."""
    try:
        a = np.asarray(value)
        return f"{a.dtype}{list(a.shape)}"
    except Exception:
        return type(value).__name__


def _call_site() -> str:
    """First stack frame outside multihost.py/sanitizer.py, repo-relative
    (hosts run the same tree, so sites are host-invariant)."""
    frame = sys._getframe(1)
    while frame is not None:
        fname = frame.f_code.co_filename
        if not fname.endswith(_SKIP_FILES):
            try:
                rel = os.path.relpath(fname, _REPO_ROOT)
            except ValueError:
                rel = fname
            if not rel.startswith(".."):
                fname = rel
            return f"{fname}:{frame.f_lineno}"
        frame = frame.f_back
    return "<unknown>"


def skip_broadcast_injected() -> bool:
    """True when the armed injection plan says THIS broadcast call must be
    skipped on THIS host (no record, no collective — the PR-10 bug shape)."""
    plan = _STATE.inject
    if plan is None:
        return False
    with _STATE.lock:
        plan.seen += 1
        if plan.seen != plan.call:
            return False
    if plan.host is None:
        return True
    try:
        import jax

        return int(jax.process_index()) == plan.host
    except Exception:
        return plan.host == 0


def record(kind: str, tag: str = "", payload=None) -> None:
    """Append one collective record (kind, tag, call site, payload schema)
    to the ring + running hash.  No-op when disarmed or inside the
    verification exchange itself — the payload-schema digest is computed
    lazily AFTER the enabled gate, so the disarmed cost at every
    collective entry stays one function call + one branch."""
    st = _STATE
    if not st.enabled or st.in_verify:
        return
    schema = np_schema(payload) if payload is not None else ""
    site = _call_site()
    with st.lock:
        st.seq += 1
        st.records += 1
        entry = {"seq": st.seq, "kind": kind, "tag": tag, "site": site,
                 "schema": schema}
        st.ring.append(entry)
        st.hash.update(
            f"{st.seq}|{kind}|{tag}|{site}|{schema}".encode("utf-8", "replace")
        )


def _hash_words() -> tuple[int, int]:
    digest = _STATE.hash.digest()
    return (
        int.from_bytes(digest[:8], "big"),
        int.from_bytes(digest[8:16], "big"),
    )


def _gather(value):
    """Verification exchange: one allgather_host, optionally under the
    ``RUSTPDE_SYNC_TIMEOUT_S`` watchdog (a peer that died mid-window must
    become a structured DispatchHang, not a wedge)."""
    from . import multihost

    timeout = float(env_get("RUSTPDE_SYNC_TIMEOUT_S", "0") or 0.0)
    if timeout <= 0:
        return multihost.allgather_host(value)
    from ..utils.resilience import call_with_watchdog

    return call_with_watchdog(
        lambda: multihost.allgather_host(value), timeout, label="sanitizer_verify"
    )


def maybe_verify() -> None:
    """Cadenced cross-host sequence verification, called by multihost after
    each EXECUTED collective.  Executed collectives pair 1:1 across hosts
    at the transport level, so every host crosses the cadence threshold
    after the SAME paired collective and the verification exchange pairs
    with itself — even when the recorded sequences already diverged."""
    st = _STATE
    if not st.enabled or st.in_verify:
        return
    st.executed += 1
    if st.executed - st.last_verify_exec < st.cadence:
        return
    verify()


def verify() -> None:
    """One verification round: fixed-shape hash compare; on mismatch,
    exchange rings, locate the first divergent record, dump the flight
    recorder and raise :class:`CollectiveDesyncError` on EVERY host."""
    st = _STATE
    if not st.enabled or st.in_verify:
        return
    import jax

    if jax.process_count() == 1:
        st.last_verify_exec = st.executed
        return
    st.in_verify = True
    try:
        st.verifies += 1
        st.last_verify_exec = st.executed
        h0, h1 = _hash_words()
        rows = np.asarray(_gather(np.array([st.seq, h0, h1], dtype=np.uint64)))
        if bool((rows == rows[0]).all()):
            return
        st.desyncs += 1
        _raise_desync(rows)
    finally:
        st.in_verify = False


def _raise_desync(rows) -> None:
    """Rings ride a second (length-padded) exchange; every host runs the
    identical comparison on the identical gathered rings, so every host
    raises the same first-divergence diagnosis."""
    payload = json.dumps(list(_STATE.ring)).encode("utf-8")
    lengths = np.asarray(_gather(np.int64(len(payload)))).reshape(-1)
    width = int(lengths.max())
    buf = np.zeros(width, dtype=np.uint8)
    buf[: len(payload)] = np.frombuffer(payload, dtype=np.uint8)
    gathered = np.asarray(_gather(buf)).astype(np.uint8)
    rings: dict[int, dict[int, dict]] = {}
    for proc in range(gathered.shape[0]):
        raw = gathered[proc, : int(lengths[proc])].tobytes().decode("utf-8")
        rings[proc] = {e["seq"]: e for e in json.loads(raw)}
    # compare only the COMMON seq window: ring eviction points differ when
    # hosts recorded different amounts, and a seq present on one host only
    # because the other evicted it is a window artifact, not a divergence
    lo = max((min(r) for r in rings.values() if r), default=0)
    all_seqs = sorted(
        s for s in set().union(*[set(r) for r in rings.values()]) if s >= lo
    )
    first_seq, sites = None, {}
    for seq in all_seqs:
        entries = {p: rings[p].get(seq) for p in rings}
        keys = {
            p: (e["kind"], e["tag"], e["site"], e["schema"]) if e else None
            for p, e in entries.items()
        }
        if len(set(keys.values())) > 1:
            first_seq, sites = seq, entries
            break
    if first_seq is None:
        counts = ", ".join(f"host{int(p)}: seq={int(rows[p][0])}" for p in range(len(rows)))
        message = (
            "collective sequences diverged BEFORE the ring window "
            f"({counts}); raise RUSTPDE_SANITIZE_RING or lower "
            "RUSTPDE_SANITIZE_CADENCE to catch the first divergent call"
        )
        site = None
    else:
        parts = []
        for p in sorted(sites):
            e = sites[p]
            parts.append(
                f"host{p}: {e['kind']}[{e['tag']}] at {e['site']} ({e['schema']})"
                if e
                else f"host{p}: <no collective recorded at seq {first_seq}>"
            )
        site = next((e["site"] for e in sites.values() if e), None)
        message = (
            f"collective sequence desync at global call #{first_seq}: "
            + "; ".join(parts)
            + " — a host-local decision reached a collective without going "
            "through root_decides/broadcast_obj (see README 'Static "
            "analysis & sanitizer')"
        )
    try:
        from ..telemetry import tracing

        tracing.instant("collective_desync", seq=first_seq, site=site)
        # dump only into an armed run_dir (the runner/serve session wires
        # set_run_dir): bare multihost usage must not litter the cwd
        if _STATE.run_dir:
            tracing.dump_flight_record(
                _STATE.run_dir, "collective_desync",
                extra={"seq": first_seq, "site": site},
            )
    except Exception:
        pass
    raise CollectiveDesyncError(message, seq=first_seq, sites=sites, site=site)
