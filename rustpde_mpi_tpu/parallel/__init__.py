"""Distributed execution layer: device mesh + pencil sharding."""

from .mesh import (  # noqa: F401
    AXIS,
    PHYS,
    SPEC,
    active_mesh,
    constrain,
    device_put,
    make_mesh,
    set_mesh,
    sharding,
    use_mesh,
)
