"""Distributed execution layer: device mesh + pencil sharding.

Two surfaces: the GSPMD constraint layer (``mesh``, what the models use —
XLA places the collectives) and the explicit shard_map/all_to_all layer
(``decomp``, the MPI-parity Decomp2d/collectives API for user code)."""

from .decomp import (  # noqa: F401
    Decomp2d,
    Pencil,
    all_gather_sum,
    broadcast_scalar,
    gather_root,
    scatter_root,
)
from .mesh import (  # noqa: F401
    AXIS,
    PHYS,
    SPEC,
    active_mesh,
    constrain,
    device_put,
    make_mesh,
    set_mesh,
    sharding,
    use_mesh,
)
