"""Inhomogeneous-boundary-condition lift profiles.

TPU rebuild of the reference's boundary-condition fields
(/root/reference/src/navier_stokes/boundary_conditions.rs).  The reference
returns mutable ``Field2`` objects; here each function returns the *physical
values* of the lift profile on the grid as a plain numpy array — the model
layer transforms them once at build time into orthogonal-space device
constants (the lift-field trick: the BC-satisfying field lives in the full
Chebyshev/Fourier space, the evolved remainder in the homogeneous Galerkin
space).
"""

from __future__ import annotations

import numpy as np


def bc_rbc_values(x: np.ndarray, y: np.ndarray) -> np.ndarray:
    """Rayleigh–Bénard temperature lift: T = +0.5 at the bottom plate,
    -0.5 at the top (linear conduction profile,
    /root/reference/src/navier_stokes/boundary_conditions.rs:18-36)."""
    y1, y2 = 0.5, -0.5
    x1, x2 = y[0], y[-1]
    m = (y2 - y1) / (x2 - x1)
    n = (y1 * x2 - y2 * x1) / (x2 - x1)
    profile = m * y + n
    return np.broadcast_to(profile[None, :], (x.shape[0], y.shape[0])).copy()


def pres_bc_rbc_values(x: np.ndarray, y: np.ndarray) -> np.ndarray:
    """Rayleigh–Bénard pressure lift: parabola a*y^2 + b*y whose derivative
    matches the hydrostatic buoyancy +-0.5 at the plates
    (/root/reference/src/navier_stokes/boundary_conditions.rs:40-70)."""
    df_l, df_r = 0.5, -0.5
    y_l, y_r = y[0], y[-1]
    a = 0.5 * (df_r - df_l) / (y_r - y_l)
    b = df_l - 2.0 * a * y_l
    parabola = a * y**2 + b * y
    return np.broadcast_to(parabola[None, :], (x.shape[0], y.shape[0])).copy()


def bc_hc_values(x: np.ndarray, y: np.ndarray) -> np.ndarray:
    """Horizontal-convection temperature lift: T = -0.5*cos(2 pi (x-x0)/L) at
    the bottom, T = T' = 0 at the top — realised as a parabola in y with
    vertex at the top wall (/root/reference/src/navier_stokes/boundary_conditions.rs:101-136)."""
    x0 = x[0]
    length = x[-1] - x[0]
    f_x = -0.5 * np.cos(2.0 * np.pi * (x - x0) / length)  # bottom value per column
    y_l, y_r = y[0], y[-1]
    a = f_x / (y_l - y_r) ** 2  # parabola through (y_l, f_x) with vertex at y_r
    return a[:, None] * (y[None, :] - y_r) ** 2


def transfer_function(x: np.ndarray, v_l: float, v_m: float, v_r: float, k: float) -> np.ndarray:
    """Smooth sidewall transition profile
    (/root/reference/src/navier_stokes/boundary_conditions.rs:262-274)."""
    length = x[-1] - x[0]
    xs = x * 2.0 / length
    neg = -1.0 * k * xs / (k + xs + 1.0) * (v_l - v_m) + v_m
    pos = 1.0 * k * xs / (k - xs + 1.0) * (v_r - v_m) + v_m
    return np.where(xs < 0.0, neg, pos)


def bc_zero_values(x: np.ndarray, y: np.ndarray, k: float) -> np.ndarray:
    """Zero-sidewall temperature lift with smooth transfer to +-0.5 plates
    (/root/reference/src/navier_stokes/boundary_conditions.rs:80-94)."""
    profile = transfer_function(y, 0.5, 0.0, -0.5, k)
    return np.broadcast_to(profile[None, :], (x.shape[0], y.shape[0])).copy()
