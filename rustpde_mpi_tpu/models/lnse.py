"""Navier2DLnse / Navier2DNonLin — linearized & perturbation-form NSE with
adjoint-based sensitivity, TPU-native.

Rebuild of /root/reference/src/navier_stokes_lnse/ (lnse.rs, lnse_eq.rs,
lnse_adj_eq.rs, lnse_adj_grad.rs, lnse_fd_grad.rs, nonlin*.rs):

* :class:`Navier2DLnse` — NSE linearized about a :class:`MeanFields` base
  state; convection ``u . grad(U) + U . grad(u)`` (lnse_eq.rs:59-110), same
  implicit-diffusion / pressure-projection scheme as Navier2D.
* :class:`Navier2DNonLin` — full nonlinear equations stated as a perturbation
  about the base state (adds ``u.grad(u)`` and the mean-balance terms,
  nonlin_eq.rs), recording the forward trajectory for the adjoint loop.
* ``grad_adjoint`` — the reference's discrete hand-adjoint: forward loop to
  ``max_time``, energy functional, backward adjoint loop, gradient w.r.t.
  the initial condition (lnse_adj_grad.rs:105-205).  Kept for parity with
  the reference's validation tolerance (~30%: it is a continuous-adjoint
  approximation).
* ``grad_autodiff`` — the TPU-native alternative: ``jax.grad`` through the
  scanned forward loop, giving the *exact* gradient of the discrete
  objective (matches finite differences to ~1e-6 instead of ~30%).
* ``grad_fd`` — brute-force finite differences (lnse_fd_grad.rs:32-58),
  vmapped over perturbation batches instead of the reference's sequential
  per-grid-point loop.

The whole forward/adjoint loops run as ``lax.scan`` on device; a host
round-trip happens only at the energy evaluation between them.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
import numpy as np

from .. import config
from ..field import norm_l2
from ..utils.integrate import Integrate
from .campaign import CampaignModelBase
from .meanfield import MeanFields
from .navier import Navier2D, NavierState

#: Solve maximization problem instead of minimization (lnse_adj_grad.rs:16)
MAXIMIZE = False


def l2_norm(a1, a2, b1, b2, c1, c2, beta1: float, beta2: float):
    """0.5 * sum(beta1*(a1*a2 + b1*b2) + beta2*c1*c2) over grid points
    (/root/reference/src/navier_stokes_lnse/functions.rs:32-57)."""
    return 0.5 * jnp.sum(beta1 * (a1 * a2 + b1 * b2) + beta2 * (c1 * c2))


class Navier2DLnse(CampaignModelBase, Integrate):
    """Linearized NSE about a mean field; Navier2D parameter vocabulary plus
    ``mean`` (defaults to the analytic bc profile).

    A full campaign model (models/campaign.py): the direct step is hoisted
    into ``_step_cc`` so eigenmode sweeps run as vmapped
    :class:`~rustpde_mpi_tpu.models.ensemble.NavierEnsemble` batches under
    ``ResilientRunner`` and the serve scheduler — observables are the
    perturbation energies ``(energy, ke, te, div)``, whose chunk-boundary
    trajectory the eigenmode workload fits growth rates from
    (workloads/eigenmodes.py)."""

    MODEL_KIND = "lnse"
    observable_names = ("energy", "ke", "te", "div")

    #: include the perturbation self-convection + mean-balance terms
    NONLINEAR = False

    def __init__(
        self,
        nx: int,
        ny: int,
        ra: float,
        pr: float,
        dt: float,
        aspect: float,
        bc: str,
        periodic: bool = False,
        mean: MeanFields | None = None,
        mesh=None,
    ):
        self.navier = Navier2D(nx, ny, ra, pr, dt, aspect, bc, periodic, mesh=mesh)
        if mean is None:
            mean = MeanFields.read_from(nx, ny, "mean.h5", bc=bc, periodic=periodic)
        if mean.space.shape_physical != self.navier.field_space.shape_physical:
            raise ValueError(
                f"mean field grid {mean.space.shape_physical} != model grid "
                f"{self.navier.field_space.shape_physical}"
            )
        self.mean = mean
        self.mesh = mesh
        self.dt = dt
        self.params = self.navier.params
        self.scale = self.navier.scale
        self.write_intervall: float | None = None
        self.statistics = None
        self._init_campaign()
        self._compile_entry_points()
        self.state = NavierState(*self.navier.state)

    @property
    def nx(self) -> int:
        return self.navier.nx

    @property
    def ny(self) -> int:
        return self.navier.ny

    # space delegates (checkpoint layer vocabulary)
    @property
    def temp_space(self):
        return self.navier.temp_space

    @property
    def velx_space(self):
        return self.navier.velx_space

    @property
    def vely_space(self):
        return self.navier.vely_space

    @property
    def pres_space(self):
        return self.navier.pres_space

    @property
    def pseu_space(self):
        return self.navier.pseu_space

    @property
    def field_space(self):
        return self.navier.field_space

    @property
    def x(self):
        return self.navier.x

    def _compat_fields(self) -> tuple:
        return (
            int(self.navier.nx),
            int(self.navier.ny),
            float(self.params["ra"]),
            float(self.params["pr"]),
            float(self.dt),
            float(self.scale[0]),
            str(self.navier.bc),
            bool(self.navier.periodic),
            (),  # scenario slot (modifiers are a DNS axis)
        )

    def _gspmd_split_sep_fallback(self) -> bool:
        # the DNS step routes this layout through manual shard_map regions
        # (ShardedConv/ShardedPoisson); the LNSE step has no manual
        # counterpart yet — shared eager-guard policy
        return self.navier._split_sep_eager_unless_forced()

    def _state_example(self):
        nav = self.navier
        return jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
            NavierState(*nav.state[:5]),
        )

    @classmethod
    def new_confined(cls, nx, ny, ra, pr, dt, aspect, bc, mean=None, mesh=None):
        return cls(nx, ny, ra, pr, dt, aspect, bc, periodic=False, mean=mean, mesh=mesh)

    @classmethod
    def new_periodic(cls, nx, ny, ra, pr, dt, aspect, bc, mean=None, mesh=None):
        return cls(nx, ny, ra, pr, dt, aspect, bc, periodic=True, mean=mean, mesh=mesh)

    # -- mean-field device constants -----------------------------------------

    def _mean_constants(self):
        """Physical values + physical gradients of the base state, as device
        constants closed over by the jitted steps."""
        sp = self.navier.field_space
        scale = self.scale

        def phys(vhat, deriv=(0, 0)):
            if deriv == (0, 0):
                return sp.backward_ortho(vhat)
            return sp.backward_ortho(sp.gradient(vhat, deriv, scale))

        m = self.mean
        return {
            "U": phys(m.velx),
            "V": phys(m.vely),
            "T": phys(m.temp),
            "dUdx": phys(m.velx, (1, 0)),
            "dUdy": phys(m.velx, (0, 1)),
            "dVdx": phys(m.vely, (1, 0)),
            "dVdy": phys(m.vely, (0, 1)),
            "dTdx": phys(m.temp, (1, 0)),
            "dTdy": phys(m.temp, (0, 1)),
        }

    # -- direct (forward) step ------------------------------------------------

    def _make_step(self, with_sentinels: bool = False):
        """The linearized step; ``with_sentinels=True`` additionally returns
        ``(cfl, ke, |div|)`` — the advective CFL uses the TOTAL velocity
        (mean + perturbation: the mean advects the perturbation, so it
        bounds the explicit convection's stability), ke is the perturbation
        kinetic energy, |div| the pre-projection residual."""
        nav = self.navier
        dt = self.dt
        scale = self.scale
        nu, ka = self.params["nu"], self.params["ka"]
        inv_dx, inv_dy = nav._inv_dx, nav._inv_dy
        w0s, w1s = nav._w0, nav._w1
        sp_t, sp_u, sp_v = nav.temp_space, nav.velx_space, nav.vely_space
        sp_p, sp_q, sp_f = nav.pres_space, nav.pseu_space, nav.field_space
        from ..bases import fused_projection_gradient

        _gx = fused_projection_gradient(sp_u, sp_q, (1, 0))
        _gy = fused_projection_gradient(sp_v, sp_q, (0, 1))
        proj_grad = (*_gx, *_gy) if _gx and _gy else None
        mask = nav._dealias
        mc = self._mean_constants()
        sol_u, sol_v, sol_t, sol_p = (
            nav.solver_velx, nav.solver_vely, nav.solver_temp, nav.solver_pres,
        )
        nonlinear = self.NONLINEAR
        mean = self.mean

        def gphys(space, vhat, deriv):
            return sp_f.backward_ortho(space.gradient(vhat, deriv, scale))

        def conv(total):
            if any(sp_f.sep):
                return sp_f.forward_dealiased(total)
            return sp_f.forward(total) * mask

        # mean-balance constants of the perturbation form (nonlin_eq.rs):
        # mean-mean convection and mean diffusion enter the rhs every step
        if nonlinear:
            conv_mm_x = np.asarray(
                conv(mc["U"] * mc["dUdx"] + mc["V"] * mc["dUdy"])
            )
            conv_mm_y = np.asarray(
                conv(mc["U"] * mc["dVdx"] + mc["V"] * mc["dVdy"])
            )
            conv_mm_t = np.asarray(
                conv(mc["U"] * mc["dTdx"] + mc["V"] * mc["dTdy"])
            )
            lap_u_m = np.asarray(
                sp_f.gradient(mean.velx, (2, 0), scale)
                + sp_f.gradient(mean.velx, (0, 2), scale)
            )
            lap_v_m = np.asarray(
                sp_f.gradient(mean.vely, (2, 0), scale)
                + sp_f.gradient(mean.vely, (0, 2), scale)
            )
            lap_t_m = np.asarray(
                sp_f.gradient(mean.temp, (2, 0), scale)
                + sp_f.gradient(mean.temp, (0, 2), scale)
            )
            that_mean = np.asarray(mean.temp)

        def step(state: NavierState) -> NavierState:
            temp, velx, vely, pres, pseu = state
            that = sp_t.to_ortho(temp)
            if nonlinear:
                that = that + that_mean  # buoyancy incl. base state
            ux = sp_u.backward(velx)
            uy = sp_v.backward(vely)

            if with_sentinels:
                # advective CFL of the TOTAL velocity (mean + perturbation)
                # + perturbation KE, from arrays the step needs anyway
                cfl = dt * jnp.max(
                    jnp.abs(mc["U"] + ux) * inv_dx[:, None]
                    + jnp.abs(mc["V"] + uy) * inv_dy[None, :]
                )
                ke = 0.5 * jnp.sum(
                    (ux**2 + uy**2) * w0s[:, None] * w1s[None, :]
                )

            # linearized convection: u.grad(U) + U.grad(u) (lnse_eq.rs:59-110)
            du_dx = gphys(sp_u, velx, (1, 0))
            du_dy = gphys(sp_u, velx, (0, 1))
            dv_dx = gphys(sp_v, vely, (1, 0))
            dv_dy = gphys(sp_v, vely, (0, 1))
            dT_dx = gphys(sp_t, temp, (1, 0))
            dT_dy = gphys(sp_t, temp, (0, 1))
            cx = ux * mc["dUdx"] + uy * mc["dUdy"] + mc["U"] * du_dx + mc["V"] * du_dy
            cy = ux * mc["dVdx"] + uy * mc["dVdy"] + mc["U"] * dv_dx + mc["V"] * dv_dy
            ct = ux * mc["dTdx"] + uy * mc["dTdy"] + mc["U"] * dT_dx + mc["V"] * dT_dy
            if nonlinear:
                # + u.grad(u) and + U.grad(U) (nonlin_eq.rs:59-120)
                cx = cx + ux * du_dx + uy * du_dy
                cy = cy + ux * dv_dx + uy * dv_dy
                ct = ct + ux * dT_dx + uy * dT_dy
            conv_x, conv_y, conv_t = conv(cx), conv(cy), conv(ct)
            if nonlinear:
                conv_x = conv_x + conv_mm_x
                conv_y = conv_y + conv_mm_y
                conv_t = conv_t + conv_mm_t

            rhs = sp_u.to_ortho(velx)
            rhs = rhs - dt * sp_p.gradient(pres, (1, 0), scale)
            rhs = rhs - dt * conv_x
            if nonlinear:
                rhs = rhs + dt * nu * lap_u_m
            velx_n = sol_u.solve(rhs)

            rhs = sp_v.to_ortho(vely)
            rhs = rhs - dt * sp_p.gradient(pres, (0, 1), scale)
            rhs = rhs + dt * that
            rhs = rhs - dt * conv_y
            if nonlinear:
                rhs = rhs + dt * nu * lap_v_m
            vely_n = sol_v.solve(rhs)

            div = sp_u.gradient(velx_n, (1, 0), scale) + sp_v.gradient(
                vely_n, (0, 1), scale
            )
            pseu_n = sol_p.solve(div)
            pseu_n = sp_q.pin_zero_mode(pseu_n)
            if proj_grad is not None:
                gx0, gx1, gy0, gy1 = proj_grad
                pax = pseu_n.ndim - 2
                velx_n = velx_n - gx1.apply(gx0.apply(pseu_n, pax), pax + 1) / scale[0]
                vely_n = vely_n - gy1.apply(gy0.apply(pseu_n, pax), pax + 1) / scale[1]
            else:
                velx_n = velx_n - sp_u.from_ortho(sp_q.gradient(pseu_n, (1, 0), scale))
                vely_n = vely_n - sp_v.from_ortho(sp_q.gradient(pseu_n, (0, 1), scale))
            pres_n = pres - nu * div + sp_q.to_ortho(pseu_n) / dt

            rhs = sp_t.to_ortho(temp)
            rhs = rhs - dt * conv_t
            if nonlinear:
                rhs = rhs + dt * ka * lap_t_m
            temp_n = sol_t.solve(rhs)

            state_n = NavierState(temp_n, velx_n, vely_n, pres_n, pseu_n)
            if with_sentinels:
                return state_n, (cfl, ke, norm_l2(div))
            return state_n

        return step

    def _make_observables(self):
        """Fused perturbation diagnostics ``(energy, ke, te, |div|)``:
        the same plain grid-point sums :meth:`energy` uses (``energy`` ==
        ``energy(0.5, 0.5)``), so growth-rate fits over the observable
        trajectory and the optimization objective agree; |div| is the
        NaN detector (observable_names index 3 by convention)."""
        nav = self.navier
        sp_t, sp_u, sp_v = nav.temp_space, nav.velx_space, nav.vely_space
        scale = self.scale

        def observables(state: NavierState):
            u = sp_u.backward(state.velx)
            v = sp_v.backward(state.vely)
            t = sp_t.backward(state.temp)
            ke = 0.5 * jnp.sum(u * u + v * v)
            te = 0.5 * jnp.sum(t * t)
            div = norm_l2(
                sp_u.gradient(state.velx, (1, 0), scale)
                + sp_v.gradient(state.vely, (0, 1), scale)
            )
            return 0.5 * (ke + te), ke, te, div

        return observables

    # -- adjoint step ----------------------------------------------------------

    def _make_adjoint_step(self):
        """One backward (adjoint) step; with history ``h = (uh, vh, th)``
        vhats from the forward loop for the nonlinear variant
        (lnse_adj_eq.rs / nonlin_adj_eq.rs)."""
        nav = self.navier
        dt = self.dt
        scale = self.scale
        nu = self.params["nu"]
        sp_t, sp_u, sp_v = nav.temp_space, nav.velx_space, nav.vely_space
        sp_p, sp_q, sp_f = nav.pres_space, nav.pseu_space, nav.field_space
        from ..bases import fused_projection_gradient

        _gx = fused_projection_gradient(sp_u, sp_q, (1, 0))
        _gy = fused_projection_gradient(sp_v, sp_q, (0, 1))
        proj_grad = (*_gx, *_gy) if _gx and _gy else None
        mask = nav._dealias
        mc = self._mean_constants()
        sol_u, sol_v, sol_t, sol_p = (
            nav.solver_velx, nav.solver_vely, nav.solver_temp, nav.solver_pres,
        )
        nonlinear = self.NONLINEAR

        def gphys(space, vhat, deriv):
            return sp_f.backward_ortho(space.gradient(vhat, deriv, scale))

        def conv(total):
            if any(sp_f.sep):
                return sp_f.forward_dealiased(total)
            return sp_f.forward(total) * mask

        def step(state: NavierState, history=None) -> NavierState:
            temp, velx, vely, pres, pseu = state
            uyhat = sp_v.to_ortho(vely)  # adjoint buoyancy source (pre-update)
            us = sp_u.backward(velx)
            vs = sp_v.backward(vely)
            ts = sp_t.backward(temp)

            U, V = mc["U"], mc["V"]
            dUdx, dVdx, dTdx = mc["dUdx"], mc["dVdx"], mc["dTdx"]
            dUdy, dVdy, dTdy = mc["dUdy"], mc["dVdy"], mc["dTdy"]
            # adjoint convection (lnse_adj_eq.rs:21-92):
            # + U.grad(u*) - (u* dUdx + v* dVdx + T* dTdx) etc.
            cx = (
                U * gphys(sp_u, velx, (1, 0))
                + V * gphys(sp_u, velx, (0, 1))
                - us * dUdx - vs * dVdx - ts * dTdx
            )
            cy = (
                U * gphys(sp_v, vely, (1, 0))
                + V * gphys(sp_v, vely, (0, 1))
                - us * dUdy - vs * dVdy - ts * dTdy
            )
            ct = U * gphys(sp_t, temp, (1, 0)) + V * gphys(sp_t, temp, (0, 1))
            if nonlinear:
                # history contributions (nonlin_adj_eq.rs:21-125)
                uh, vh, th = history
                Uh = sp_f.backward_ortho(uh)
                Vh = sp_f.backward_ortho(vh)
                cx = cx + (
                    Uh * gphys(sp_u, velx, (1, 0))
                    + Vh * gphys(sp_u, velx, (0, 1))
                    - us * sp_f.backward_ortho(sp_f.gradient(uh, (1, 0), scale))
                    - vs * sp_f.backward_ortho(sp_f.gradient(vh, (1, 0), scale))
                    - ts * sp_f.backward_ortho(sp_f.gradient(th, (1, 0), scale))
                )
                cy = cy + (
                    Uh * gphys(sp_v, vely, (1, 0))
                    + Vh * gphys(sp_v, vely, (0, 1))
                    - us * sp_f.backward_ortho(sp_f.gradient(uh, (0, 1), scale))
                    - vs * sp_f.backward_ortho(sp_f.gradient(vh, (0, 1), scale))
                    - ts * sp_f.backward_ortho(sp_f.gradient(th, (0, 1), scale))
                )
                ct = ct + Uh * gphys(sp_t, temp, (1, 0)) + Vh * gphys(sp_t, temp, (0, 1))
            conv_x, conv_y, conv_t = conv(cx), conv(cy), conv(ct)

            rhs = sp_u.to_ortho(velx)
            rhs = rhs - dt * sp_p.gradient(pres, (1, 0), scale)
            rhs = rhs + dt * conv_x
            velx_n = sol_u.solve(rhs)

            rhs = sp_v.to_ortho(vely)
            rhs = rhs - dt * sp_p.gradient(pres, (0, 1), scale)
            rhs = rhs + dt * conv_y
            vely_n = sol_v.solve(rhs)

            div = sp_u.gradient(velx_n, (1, 0), scale) + sp_v.gradient(
                vely_n, (0, 1), scale
            )
            pseu_n = sol_p.solve(div)
            pseu_n = sp_q.pin_zero_mode(pseu_n)
            if proj_grad is not None:
                gx0, gx1, gy0, gy1 = proj_grad
                pax = pseu_n.ndim - 2
                velx_n = velx_n - gx1.apply(gx0.apply(pseu_n, pax), pax + 1) / scale[0]
                vely_n = vely_n - gy1.apply(gy0.apply(pseu_n, pax), pax + 1) / scale[1]
            else:
                velx_n = velx_n - sp_u.from_ortho(sp_q.gradient(pseu_n, (1, 0), scale))
                vely_n = vely_n - sp_v.from_ortho(sp_q.gradient(pseu_n, (0, 1), scale))
            pres_n = pres - nu * div + sp_q.to_ortho(pseu_n) / dt

            rhs = sp_t.to_ortho(temp)
            rhs = rhs + dt * conv_t
            rhs = rhs + dt * uyhat  # adjoint buoyancy
            temp_n = sol_t.solve(rhs)

            return NavierState(temp_n, velx_n, vely_n, pres_n, pseu_n)

        return step

    # -- compiled entry points -------------------------------------------------

    # dt-baked artifacts (campaign rung cache) include the adjoint entries
    _DT_ARTIFACTS = ("_adj_n", "_adj_consts") + CampaignModelBase._DT_ARTIFACTS

    def _dt_changed(self, dt: float) -> None:
        """Propagate a campaign dt change into the embedded Navier2D (whose
        implicit solvers the linearized step shares) — its own rung cache
        bounds the rebuild cost."""
        self.navier.set_dt(dt)

    def _compile_entry_points_impl(self) -> None:
        """The campaign entry points (hoisted ``_step_cc``/``_obs_cc``,
        chunked scans, sentinels — CampaignModelBase) plus the lnse-specific
        ADJOINT loop entries of the linearized model.  Overrides the IMPL
        hook (not the timed wrapper), so the per-kind compile attribution
        covers the adjoint-loop hoist+jit too."""
        super()._compile_entry_points_impl()
        if self.NONLINEAR:
            return
        from ..utils.jit import hoist_constants

        nav = self.navier
        example = self._state_example()
        adj = self._make_adjoint_step()
        with nav._scope():
            adj_cc, adj_consts = hoist_constants(lambda s: adj(s), example)
        self._adj_consts = adj_consts

        def adj_n(consts, state, n: int):
            return jax.lax.scan(
                lambda c, _: (adj_cc(consts, c), None), state, None, length=n
            )[0]

        adj_n_jit = jax.jit(adj_n, static_argnames=("n",))
        self._adj_n = lambda s, n: adj_n_jit(self._adj_consts, s, n=n)

    # -- Integrate protocol ----------------------------------------------------
    # update/update_n/update_n_pending, sentinels, set_dt, observable
    # futures and exit/exit_future come from CampaignModelBase

    def update_direct(self) -> None:
        self.update()

    def _sync_navier(self) -> None:
        self.navier.state = NavierState(*self.state)
        self.navier.time = self.time
        self.navier._obs_cache = None

    def eval_nu(self) -> float:
        """DNS-vocabulary Nu of the perturbation state (legacy IO paths);
        the campaign observables are the perturbation energies."""
        self._sync_navier()
        return self.navier.get_observables()[0]

    def callback(self) -> None:
        from ..utils import navier_io

        self._sync_navier()
        self.navier.write_intervall = self.write_intervall
        self.navier.statistics = self.statistics
        navier_io.callback(self.navier)

    # -- field access ----------------------------------------------------------

    def init_random(self, amp: float, seed: int = 0) -> None:
        self.navier.init_random(amp, seed)
        self.state = NavierState(*self.navier.state)
        self._obs_cache = None

    def set_velocity(self, amp: float, m: float, n: float) -> None:
        """Seed one velocity eigenmode shape (the eigenmode-sweep IC)."""
        self._sync_navier()
        self.navier.set_velocity(amp, m, n)
        self.state = NavierState(*self.navier.state)
        self._obs_cache = None

    def set_temperature(self, amp: float, m: float, n: float) -> None:
        self._sync_navier()
        self.navier.set_temperature(amp, m, n)
        self.state = NavierState(*self.navier.state)
        self._obs_cache = None

    def set_field(self, name: str, values) -> None:
        self._sync_navier()
        self.navier.set_field(name, values)
        self.state = NavierState(*self.navier.state)
        self._obs_cache = None

    def get_field(self, name: str):
        self._sync_navier()
        return self.navier.get_field(name)

    def write(self, filename: str) -> None:
        self._sync_navier()
        self.navier.write(filename)

    def read(self, filename: str) -> None:
        from ..utils import checkpoint

        if checkpoint.is_sharded_checkpoint(filename):
            # topology-elastic manifest restore targets THIS model's
            # snapshot surface (state/... names), not the embedded DNS's
            checkpoint.read_sharded_snapshot(self, filename)
            return
        self.navier.read(filename)
        self.state = NavierState(*self.navier.state)
        self.time = self.navier.time
        self._obs_cache = None

    # -- energy / gradient machinery -------------------------------------------

    def _phys(self, state: NavierState):
        nav = self.navier
        return (
            nav.velx_space.backward(state.velx),
            nav.vely_space.backward(state.vely),
            nav.temp_space.backward(state.temp),
        )

    def energy(self, beta1: float, beta2: float, target: MeanFields | None = None):
        """l2_norm of the current (optionally target-shifted) state."""
        u, v, t = self._phys(self.state)
        if target is not None:
            tu, tv, tt = target.physical()
            u, v, t = u - tu, v - tv, t - tt
        return float(l2_norm(u, u, v, v, t, t, beta1, beta2))

    def _zero_state(self) -> NavierState:
        return NavierState(
            temp=jnp.zeros_like(self.state.temp),
            velx=jnp.zeros_like(self.state.velx),
            vely=jnp.zeros_like(self.state.vely),
            pres=jnp.zeros_like(self.state.pres),
            pseu=jnp.zeros_like(self.state.pseu),
        )

    def _adjoint_ic(self, state, beta1, beta2, target):
        """Terminal condition of the adjoint loop: fields scaled by the norm
        weights (minus target) with pressure kept (lnse_adj_grad.rs:155-168)."""
        nav = self.navier
        velx, vely, temp = state.velx, state.vely, state.temp
        if target is not None:
            velx = velx - nav.velx_space.from_ortho(target.velx)
            vely = vely - nav.vely_space.from_ortho(target.vely)
            temp = temp - nav.temp_space.from_ortho(target.temp)
        return state._replace(
            velx=velx * beta1, vely=vely * beta1, temp=temp * beta2
        )

    def grad_adjoint(
        self,
        max_time: float,
        save_intervall: float | None = None,
        beta1: float = 0.5,
        beta2: float = 0.5,
        target: MeanFields | None = None,
        outfile: str | None = None,
    ):
        """Hand-adjoint gradient of the final energy w.r.t. the initial
        condition (lnse_adj_grad.rs:105-205).

        Returns ``(fun_val, (grad_u, grad_v, grad_t))`` with gradients as
        physical-space numpy arrays.  MAXIMIZE flips the sign.
        """
        del save_intervall  # device loop; intermediate snapshots not written
        n = max(1, round(max_time / self.dt))
        self.update_n(n)
        fun_val = self.energy(beta1, beta2, target)

        with self.navier._scope():
            self.state = self._adjoint_ic(self.state, beta1, beta2, target)
            from ..utils.jit import run_scanned

            self.state = run_scanned(self._adj_n, self.state, n)
        self.reset_time()

        fac = 1.0 if MAXIMIZE else -1.0
        u, v, t = self._phys(self.state)
        grads = (fac * np.asarray(u), fac * np.asarray(v), fac * np.asarray(t))
        if outfile:
            self._write_grad(outfile, grads)
        return fun_val, grads

    def _write_grad(self, filename, grads):
        import os

        import h5py

        from ..field import grid_deltas
        from ..utils.checkpoint import write_field

        nav = self.navier
        os.makedirs(os.path.dirname(filename) or ".", exist_ok=True)
        xs, dxs = (
            [b.points * s for b, s in zip(nav.field_space.bases, self.scale)],
            [
                grid_deltas(b.points, b.is_periodic) * s
                for b, s in zip(nav.field_space.bases, self.scale)
            ],
        )
        names = ("ux", "uy", "temp")
        spaces = (nav.velx_space, nav.vely_space, nav.temp_space)
        with h5py.File(filename, "a") as h5:
            for name, space, g in zip(names, spaces, grads):
                vhat = space.forward(jnp.asarray(g, dtype=config.real_dtype()))
                write_field(h5, name, space, vhat, xs, dxs)

    # -- exact discrete gradient via JAX autodiff ------------------------------

    def _objective_fn(self, n: int, beta1, beta2, target: MeanFields | None):
        """J(u0, v0, T0 physical) = energy after n forward steps."""
        nav = self.navier
        step = self._make_step()
        if target is not None:
            tu, tv, tt = target.physical()

        def objective(u0, v0, t0):
            state = self._zero_state()._replace(
                velx=nav.velx_space.forward(u0),
                vely=nav.vely_space.forward(v0),
                temp=nav.temp_space.forward(t0),
            )
            ckpt_step = jax.checkpoint(step)
            state = jax.lax.scan(
                lambda c, _: (ckpt_step(c), None), state, None, length=n
            )[0]
            u, v, t = self._phys(state)
            if target is not None:
                u, v, t = u - tu, v - tv, t - tt
            return l2_norm(u, u, v, v, t, t, beta1, beta2)

        return objective

    def grad_autodiff(
        self,
        max_time: float,
        beta1: float = 0.5,
        beta2: float = 0.5,
        target: MeanFields | None = None,
    ):
        """Exact gradient of the discrete objective w.r.t. the physical
        initial condition, by reverse-mode autodiff through the scanned
        forward loop (``jax.checkpoint`` bounds the memory).  The TPU-native
        answer to the reference's continuous hand-adjoint — exact to
        roundoff instead of O(30%).

        Starts from the CURRENT state (like grad_adjoint); does not advance
        the model.  MAXIMIZE flips the sign to match grad_adjoint's
        descent/ascent convention.
        """
        n = max(1, round(max_time / self.dt))
        u0, v0, t0 = self._phys(self.state)
        objective = self._objective_fn(n, beta1, beta2, target)
        with self.navier._scope():
            val, grads = jax.jit(jax.value_and_grad(objective, argnums=(0, 1, 2)))(
                u0, v0, t0
            )
        # grad_adjoint returns the descent direction -dJ/du0 under
        # MAXIMIZE=False (+dJ/du0 under MAXIMIZE); mirror that convention
        fac = 1.0 if MAXIMIZE else -1.0
        return float(val), tuple(fac * np.asarray(g) for g in grads)

    def grad_fd(
        self,
        max_time: float,
        beta1: float = 0.5,
        beta2: float = 0.5,
        eps: float = 1e-5,
        batch: int = 64,
    ):
        """Finite-difference gradient (lnse_fd_grad.rs:32-58): perturb every
        physical grid point of every field.  The reference integrates one
        perturbation at a time; here perturbations run vmapped in batches —
        the same O(N^2) work as a single batched scan per chunk.

        Returns physical-space FD gradients (forward differences, matching
        the reference's (E(x+eps)-E(x))/eps).
        """
        n = max(1, round(max_time / self.dt))
        u0, v0, t0 = (np.asarray(a) for a in self._phys(self.state))
        objective = self._objective_fn(n, beta1, beta2, None)
        obj_jit = jax.jit(objective)
        e_base = float(obj_jit(u0, v0, t0))

        obj_batch = jax.jit(jax.vmap(objective, in_axes=(0, 0, 0)))
        grads = []
        for idx, base in enumerate((u0, v0, t0)):
            flat = base.size
            grad = np.zeros(flat)
            for start in range(0, flat, batch):
                count = min(batch, flat - start)
                pert = np.tile(base.ravel(), (count, 1))
                pert[np.arange(count), start + np.arange(count)] += eps
                pert = pert.reshape((count,) + base.shape)
                args = [
                    np.broadcast_to(a, (count,) + a.shape) for a in (u0, v0, t0)
                ]
                args[idx] = pert
                energies = np.asarray(obj_batch(*args))
                grad[start : start + count] = (energies - e_base) / eps
            grads.append(grad.reshape(base.shape))
        return tuple(grads)


class Navier2DNonLin(Navier2DLnse):
    """Full nonlinear equations as a perturbation about the base state
    (nonlin.rs:23-57); the forward loop records the trajectory history the
    adjoint convection terms need (nonlin_adj_grad.rs:186-190)."""

    NONLINEAR = True

    def _compile_entry_points_impl(self) -> None:
        # impl-hook override (see the linear model's note): the nonlinear
        # trajectory-recording entries stay inside the timed attribution
        super()._compile_entry_points_impl()
        nav = self.navier
        example = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), NavierState(*nav.state)
        )
        from ..utils.jit import hoist_constants

        step = self._make_step()
        sp_u, sp_v, sp_t = nav.velx_space, nav.vely_space, nav.temp_space

        def fwd_with_history(state):
            new = step(state)
            # ortho-space history of the *new* fields (the reference stores
            # the post-step state, nonlin_adj_grad.rs:66-76)
            hist = (
                sp_u.to_ortho(new.velx),
                sp_v.to_ortho(new.vely),
                sp_t.to_ortho(new.temp),
            )
            return new, hist

        with nav._scope():
            fwd_cc, fwd_consts = hoist_constants(fwd_with_history, example)
        adj = self._make_adjoint_step()
        sds = jax.ShapeDtypeStruct(
            nav.field_space.shape_spectral, nav.field_space.spectral_dtype()
        )
        hist_sds = (sds, sds, sds)
        with nav._scope():
            adj_cc, adj_consts = hoist_constants(
                lambda s, h: adj(s, history=h), example, hist_sds
            )
        self._fwd_consts = fwd_consts
        self._nl_adj_consts = adj_consts

        def fwd_scan(consts, state, n: int):
            return jax.lax.scan(
                lambda c, _: fwd_cc(consts, c), state, None, length=n
            )

        def adj_scan(consts, state, history):
            return jax.lax.scan(
                lambda c, h: (adj_cc(consts, c, h), None),
                state,
                jax.tree.map(lambda x: x[::-1], history),
            )[0]

        self._fwd_scan = jax.jit(fwd_scan, static_argnames=("n",))
        self._adj_scan = jax.jit(adj_scan)

    def grad_adjoint(
        self,
        max_time: float,
        save_intervall: float | None = None,
        beta1: float = 0.5,
        beta2: float = 0.5,
        target: MeanFields | None = None,
        outfile: str | None = None,
    ):
        """Nonlinear variant: the adjoint loop consumes the recorded forward
        trajectory backward (nonlin_adj_grad.rs:120-223)."""
        del save_intervall
        n = max(1, round(max_time / self.dt))
        with self.navier._scope():
            self.state, history = self._fwd_scan(self._fwd_consts, self.state, n=n)
        self.time += n * self.dt
        fun_val = self.energy(beta1, beta2, target)

        with self.navier._scope():
            self.state = self._adjoint_ic(self.state, beta1, beta2, target)
            self.state = self._adj_scan(self._nl_adj_consts, self.state, history)
        self.reset_time()

        fac = 1.0 if MAXIMIZE else -1.0
        u, v, t = self._phys(self.state)
        grads = (fac * np.asarray(u), fac * np.asarray(v), fac * np.asarray(t))
        if outfile:
            self._write_grad(outfile, grads)
        return fun_val, grads
