"""Physics helper functions for the Navier–Stokes models.

TPU rebuild of /root/reference/src/navier_stokes/functions.rs — dimensionless
groups, dealiasing masks and initial-condition constructors.  The observables
(eval_nu/nuvol/re) live as jitted closures on the model in navier.py, since
they close over spaces and average weights.
"""

from __future__ import annotations

import numpy as np


def get_nu(ra: float, pr: float, height: float) -> float:
    """Viscosity from Ra, Pr and cell height: sqrt(Pr / (Ra/h^3))
    (/root/reference/src/navier_stokes/functions.rs:12-15)."""
    return float(np.sqrt(pr / (ra / height**3)))


def get_ka(ra: float, pr: float, height: float) -> float:
    """Diffusivity from Ra, Pr and cell height: sqrt(1 / (Ra/h^3 * Pr))
    (/root/reference/src/navier_stokes/functions.rs:18-21)."""
    return float(np.sqrt(1.0 / ((ra / height**3) * pr)))


# (the 2/3-rule dealias mask lives on Space2.dealias_mask — it needs the
# per-axis representation, e.g. the split Re/Im blocks)


def _normalized_coords(x: np.ndarray) -> np.ndarray:
    return (x - x[0]) / (x[-1] - x[0])


def sin_cos_values(x: np.ndarray, y: np.ndarray, amp: float, m: float, n: float) -> np.ndarray:
    """amp * sin(pi m x~) cos(pi n y~) on normalized coordinates
    (/root/reference/src/navier_stokes/functions.rs:85-104)."""
    xn = _normalized_coords(x)
    yn = _normalized_coords(y)
    return amp * np.sin(np.pi * m * xn)[:, None] * np.cos(np.pi * n * yn)[None, :]


def cos_sin_values(x: np.ndarray, y: np.ndarray, amp: float, m: float, n: float) -> np.ndarray:
    """amp * cos(pi m x~) sin(pi n y~) on normalized coordinates
    (/root/reference/src/navier_stokes/functions.rs:106-126)."""
    xn = _normalized_coords(x)
    yn = _normalized_coords(y)
    return amp * np.cos(np.pi * m * xn)[:, None] * np.sin(np.pi * n * yn)[None, :]


def random_values(shape: tuple[int, int], amp: float, rng: np.random.Generator) -> np.ndarray:
    """Uniform disturbance in [-amp, amp]
    (/root/reference/src/navier_stokes/functions.rs:128-140)."""
    return rng.uniform(-amp, amp, size=shape)
