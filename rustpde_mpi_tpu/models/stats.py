"""On-device physics-statistics engine: in-scan turbulence statistics,
spectral-health sentinels, and budget-closure drift detection.

The reference port's :class:`~rustpde_mpi_tpu.models.statistics.Statistics`
is an eager host-side numpy accumulator — single-model only, synchronous in
the IO callback, invisible to ensembles/sharded meshes/serve, and its
running averages silently restart from zero after every crash.  This module
is the production replacement: a :class:`StatsState` pytree of running sums
carried *through the scanned step chunk* alongside the model state —

* updated ON DEVICE at a configured ``stride`` (a handful of extra
  syntheses per sample, ~1/stride amortized overhead, bench-gated ≤5%),
* vmapped per ensemble member and pencil-sharded under a mesh (the
  accumulation is a pure function of one member state, so the batch axis
  and GSPMD propagation come for free),
* registered in the models' ``snapshot_state_items`` so long-horizon
  averages ride the two-phase sharded checkpoints (and the gathered
  single-file format) and survive kill/resume BIT-exactly,
* read, never fed back: the state trajectory is bit-identical stats-on vs
  stats-off (CI-asserted — the same contract the PR-3 sentinels and PR-8
  telemetry ship under).

What is accumulated (per member):

* the legacy-parity set — running spectral-space sums of T (ortho, no BC
  lift), ux, uy, and the pointwise Nusselt field (with lift, dealiased) —
  the engine matches the eager legacy accumulator to fp tolerance
  (PARITY.json ``"stats"``), and :func:`export_stats` writes the reference
  ``statistics.h5`` layout plus engine extras,
* x-averaged profiles: mean T, second moments of T/ux/uy (RMS profiles),
  convective flux ``uy*T``,
* per-axis energy-spectrum accumulators for T/ux/uy (the under-resolution
  detector's raw material),
* budget scalars: plate-flux Nu, volume Nu, the exact-relation flux Nu
  ``1 + <uy*T>*2*sy/ka``, kinetic energy (first/last sample + running sum),
  buoyancy production ``<uy*T>`` and viscous dissipation.

On top of the accumulators, :data:`HEALTH_NAMES` scalars are compiled as a
separate jitted readout (streamed through the existing observable-future
plumbing, exported as telemetry gauges, journal-typed by the runner):
spectral-tail energy fraction per field/axis, thermal/viscous boundary-layer
point counts, and budget-closure residuals (kinetic-energy balance;
Nu-consistency between the plate-flux, volume and flux estimators) — the
physics-invariant drift detectors the f64 precision ladder and the Pallas
A/B flips gate on.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

from .. import config


class StatsState(NamedTuple):
    """Running-sum pytree carried through the scan (one member's leaves;
    ensembles stack a leading K axis on every leaf).  Scalars are shape
    ``(1,)`` so the sharded checkpoint writer's slab addressing covers
    them like any other dataset."""

    # legacy-parity spectral running sums (ortho field-space layout)
    t_sum: object      # T composite->ortho, NO BC lift (statistics.rs t_avg)
    ux_sum: object
    uy_sum: object
    nusselt_sum: object  # pointwise Nusselt field (with lift, dealiased)
    # per-axis energy-spectrum sums, rows (T, ux, uy)
    spec_x: object     # (3, x-rows)  |coeff|^2 summed over the y axis
    spec_y: object     # (3, ny_spec) |coeff|^2 summed over the other axes
    # x-averaged physical profiles (ny,)
    t_prof_sum: object    # mean T (WITH lift: the physical temperature)
    t2_prof_sum: object   # second moments -> RMS profiles
    ux2_prof_sum: object
    uy2_prof_sum: object
    flux_prof_sum: object  # uy * T convective-flux profile
    # budget scalars, shape (1,)
    nu_plate_sum: object   # plate-flux Nu per sample
    nuvol_sum: object      # volume Nu per sample (the eval_nuvol integrand)
    flux_vol_sum: object   # <uy*T> * 2*sy/ka  (Nu_flux = 1 + avg of this)
    ke_sum: object         # volume-avg kinetic energy
    buoy_sum: object       # buoyancy production <uy*T>
    diss_sum: object       # viscous dissipation nu*<|grad u|^2>
    ke_first: object       # KE at the first sample (dKE/dt window anchor)
    ke_last: object        # KE at the newest sample
    # window span in SIM time, accumulated per sample at that sample's OWN
    # stride*dt (the accumulator is rebuilt per governor dt rung, so a
    # ladder move mid-window keeps the dKE/dt span exact — reconstructing
    # it from the current dt would mis-scale old-rung samples)
    span_sum: object       # sum of stride*dt over the samples
    span_first: object     # span_sum at the first sample (elapsed anchor)
    samples: object        # sample count (real dtype; exact far past any run)


#: the compiled health readout's scalar vocabulary, in order
#: (:meth:`StatsEngine.health_fn` returns exactly these)
HEALTH_NAMES = (
    "tail_t_x",
    "tail_t_y",
    "tail_ux_x",
    "tail_ux_y",
    "tail_uy_x",
    "tail_uy_y",
    "bl_thermal_pts",
    "bl_visc_pts",
    "ke_residual",
    "nu_residual",
    "nu_plate_avg",
    "nu_flux_avg",
    "samples",
)


# typed replacements for the legacy statistics flow's silent failure paths:
# event name -> (telemetry counter, help)
_EVENT_COUNTERS = {
    "stats_mismatch": (
        "stats_mismatch_total",
        "legacy statistics time-mismatch rejections (averages NOT updated)",
    ),
    "stats_write_failed": (
        "stats_write_failed_total",
        "statistics.h5 write failures (averages survive in memory only)",
    ),
}


def report_stats_event(model, event: dict) -> None:
    """Surface a statistics-flow failure as a telemetry counter + (when the
    model carries an attached ``journal_writer`` — the resilient runner
    wires its own during a session) a typed journal event, so a production
    run can't lose its averages invisibly behind a swallowed ``print``."""
    from ..telemetry import metrics as _tm

    counter = _EVENT_COUNTERS.get(event.get("event"))
    if counter is not None:
        _tm.counter(*counter).inc()
    writer = getattr(model, "journal_writer", None)
    if writer is not None:
        writer.append(dict(event))


class StatsEngine:
    """Builder of the compiled stats machinery for ONE model (dns only —
    the accumulators read temp/velx/vely through the DNS spaces).

    The engine owns the *math*: :meth:`sample_fn` (one state's contribution
    as a StatsState), :meth:`accum_fn` (fold a sample into the running
    sums), :meth:`health_fn` (the :data:`HEALTH_NAMES` readout) and
    :meth:`init_state` (zeros).  The *threading* — hoisting these into the
    scanned chunk with the stride cond, vmapping them per member, carrying
    the state through checkpoints — lives in
    :class:`~rustpde_mpi_tpu.models.campaign.CampaignModelBase` and
    :class:`~rustpde_mpi_tpu.models.ensemble.NavierEnsemble`, exactly where
    the step's own machinery lives."""

    def __init__(self, model, cfg=None):
        if getattr(model, "MODEL_KIND", "") != "dns":
            raise TypeError(
                "the stats engine reads DNS fields (temp/velx/vely); model "
                f"kind {getattr(model, 'MODEL_KIND', '?')!r} is not supported"
            )
        self.model = model
        self.cfg = cfg
        stride = getattr(cfg, "stride", None)
        if stride is None:
            stride = int(config.env_get("RUSTPDE_STATS_STRIDE", "16"))
        self.stride = max(1, int(stride))
        tail_warn = getattr(cfg, "tail_warn", None)
        if tail_warn is None:
            tail_warn = float(config.env_get("RUSTPDE_STATS_TAIL_WARN", "1e-3"))
        self.tail_warn = float(tail_warn)
        budget_warn = getattr(cfg, "budget_warn", None)
        if budget_warn is None:
            budget_warn = float(
                config.env_get("RUSTPDE_STATS_BUDGET_WARN", "0.5")
            )
        self.budget_warn = float(budget_warn)
        self._example = None  # ShapeDtypeStruct pytree, computed lazily

    # -- compiled pieces -----------------------------------------------------

    def sample_fn(self):
        """One state's StatsState contribution (``samples == 1``): the pure
        function the accumulator and the zero-state shapes derive from.
        Every ingredient mirrors the eager legacy accumulator
        (models/statistics.py) and the fused observables
        (models/navier._make_observables) so the engine-vs-legacy parity
        holds at fp tolerance by construction."""
        import jax.numpy as jnp

        m = self.model
        sp_t, sp_u, sp_v = m.temp_space, m.velx_space, m.vely_space
        sp_f = m.field_space
        scale = m.scale
        nu = m.params["nu"]
        ka = m.params["ka"]
        tb = m.tempbc_ortho
        mask = m._dealias
        w0, w1 = m._w0, m._w1
        rdt = config.real_dtype()
        # this rung's per-sample time span (the entry points — and so this
        # sample fn — are rebuilt per dt rung via the _DT_ARTIFACTS cache)
        stride_dt = float(self.stride) * float(m.dt)

        def avg_x(v):
            return jnp.sum(v * w0[:, None], axis=0)

        def avg(v):
            return jnp.sum(v * w0[:, None] * w1[None, :])

        from ..bases import BaseKind

        def spec_fns(space):
            """Per-axis (fold_x, fold_y) mapping stored-row energies to
            NATURAL ascending-mode order, so ``tails()``'s "top third of
            rows" really is the high-wavenumber tail on every layout:
            split-Fourier stores [Re | Im] half-blocks (fold per mode),
            sep axes store the parity permutation (invert it), c2c FFT
            order puts high |k| mid-array (reorder); plain Chebyshev and
            r2c storage is already ascending."""
            from ..ops.folded import parity_perm

            def fold(axis):
                base = space.bases[axis]
                if getattr(base.kind, "is_split", False):
                    mc = base.m_complex
                    return lambda e: e[:mc] + e[mc:]
                if space.sep[axis]:
                    return lambda e: e[np.argsort(parity_perm(e.shape[0]))]
                if base.kind == BaseKind.FOURIER_C2C:
                    return lambda e: e[
                        np.argsort(
                            np.abs(np.fft.fftfreq(e.shape[0])), kind="stable"
                        )
                    ]
                return lambda e: e

            return fold(0), fold(1)

        folds = {sp: spec_fns(sp) for sp in (sp_t, sp_u, sp_v)}

        def spec_pair(c, space):
            """Per-axis energy of one spectral array in natural mode order:
            (x-modes, y-modes)."""
            e = jnp.abs(c) ** 2
            fx, fy = folds[space]
            sx = fx(jnp.sum(e, axis=-1))
            sy = fy(jnp.sum(e, axis=0))
            return sx.astype(rdt), sy.astype(rdt)

        def s1(v):
            return jnp.reshape(v, (1,)).astype(rdt)

        def sample(state):
            that_h = sp_t.to_ortho(state.temp)
            uxhat = sp_u.to_ortho(state.velx)
            uyhat = sp_v.to_ortho(state.vely)
            that = that_h + tb  # full physical temperature (with BC lift)
            temp_p = sp_f.backward_ortho(that)
            ux_p = sp_u.backward(state.velx)
            uy_p = sp_v.backward(state.vely)
            # physical dT/dy, shared by the plate-flux Nu, the volume Nu
            # and the pointwise Nusselt field (statistics.rs:246-270)
            dtdy_p = sp_f.backward_gradient(that, (0, 1), None)
            dtdz = dtdy_p / (-scale[1])
            nusselt_v = (dtdz + uy_p * temp_p / ka) * 2.0 * scale[1]
            nusselt = sp_f.forward(nusselt_v) * mask
            tx, ty = spec_pair(that_h, sp_t)
            uxx, uxy = spec_pair(uxhat, sp_u)
            uyx, uyy = spec_pair(uyhat, sp_v)
            x_avg = avg_x(dtdy_p) * (-2.0 / scale[1])
            nu_plate = 0.5 * (x_avg[0] + x_avg[-1])
            flux = uy_p * temp_p
            ke = 0.5 * avg(ux_p**2 + uy_p**2)
            # viscous dissipation nu * <|grad u|^2> (KE-balance sink)
            duxdx = sp_u.backward_gradient(state.velx, (1, 0), scale)
            duxdy = sp_u.backward_gradient(state.velx, (0, 1), scale)
            duydx = sp_v.backward_gradient(state.vely, (1, 0), scale)
            duydy = sp_v.backward_gradient(state.vely, (0, 1), scale)
            diss = nu * avg(duxdx**2 + duxdy**2 + duydx**2 + duydy**2)
            return StatsState(
                t_sum=that_h,
                ux_sum=uxhat,
                uy_sum=uyhat,
                nusselt_sum=nusselt,
                spec_x=jnp.stack([tx, uxx, uyx]),
                spec_y=jnp.stack([ty, uxy, uyy]),
                t_prof_sum=avg_x(temp_p).astype(rdt),
                t2_prof_sum=avg_x(temp_p**2).astype(rdt),
                ux2_prof_sum=avg_x(ux_p**2).astype(rdt),
                uy2_prof_sum=avg_x(uy_p**2).astype(rdt),
                flux_prof_sum=avg_x(flux).astype(rdt),
                nu_plate_sum=s1(nu_plate),
                nuvol_sum=s1(avg(nusselt_v)),
                flux_vol_sum=s1(avg(flux) * 2.0 * scale[1] / ka),
                ke_sum=s1(ke),
                buoy_sum=s1(avg(flux)),
                diss_sum=s1(diss),
                ke_first=s1(ke),
                ke_last=s1(ke),
                span_sum=jnp.full((1,), stride_dt, rdt),
                span_first=jnp.full((1,), stride_dt, rdt),
                samples=jnp.ones((1,), rdt),
            )

        return sample

    def accum_fn(self):
        """``(stats_state, state) -> stats_state`` — fold one sample in.
        Running sums add; ``ke_first`` keeps the first sample's value and
        ``ke_last`` the newest (the dKE/dt window anchors)."""
        import jax
        import jax.numpy as jnp

        sample = self.sample_fn()

        def accum(ss, state):
            c = sample(state)
            out = jax.tree.map(jnp.add, ss, c)
            return out._replace(
                ke_first=jnp.where(ss.samples > 0, ss.ke_first, c.ke_first),
                ke_last=c.ke_last,
                span_first=jnp.where(
                    ss.samples > 0, ss.span_first, out.span_sum
                ),
            )

        return accum

    def health_fn(self):
        """``stats_state ->`` the :data:`HEALTH_NAMES` scalars — a cheap
        compiled readout over the running sums (no field transforms), so it
        can stream through an observable future at every chunk boundary."""
        import jax.numpy as jnp

        m = self.model
        rdt = config.real_dtype()
        ys = np.asarray(m.field_space.bases[1].points, dtype=np.float64)
        ys = ys * m.scale[1]
        # distance from the nearest plate, per y grid point (ordering-proof)
        dist = np.minimum(ys - ys.min(), ys.max() - ys)
        dist_dev = jnp.asarray(dist, dtype=rdt)
        dy0 = abs(ys[1] - ys[0])
        dy1 = abs(ys[-1] - ys[-2])

        def tails(spec):
            """Energy fraction in the top third of the stored rows, rows
            (T, ux, uy).  A well-resolved spectral run keeps this tiny;
            energy piling at the dealias cut reads as under-resolution."""
            tot = jnp.sum(spec, axis=-1)
            cut = (2 * int(spec.shape[-1])) // 3
            t = jnp.sum(spec[:, cut:], axis=-1) / jnp.maximum(tot, 1e-300)
            return jnp.where(tot > 0, t, 0.0)

        def health(ss):
            n = jnp.maximum(ss.samples[0], 1.0)
            has = ss.samples[0] > 0
            tx = tails(ss.spec_x)
            ty = tails(ss.spec_y)
            t_prof = ss.t_prof_sum / n
            # thermal BL thickness from the mean-profile wall slope:
            # delta_T = (dT/2) / |dT/dy|_wall, grid points within it counted
            slope = 0.5 * (
                jnp.abs(t_prof[1] - t_prof[0]) / dy0
                + jnp.abs(t_prof[-1] - t_prof[-2]) / dy1
            )
            d_temp = jnp.abs(t_prof[-1] - t_prof[0])
            delta_t = 0.5 * d_temp / jnp.maximum(slope, 1e-300)
            bl_thermal = jnp.sum((dist_dev < delta_t).astype(rdt))
            # viscous BL: distance of the horizontal-velocity-RMS peak from
            # the nearest plate (the standard delta_u definition)
            ux_rms = jnp.sqrt(jnp.maximum(ss.ux2_prof_sum / n, 0.0))
            delta_u = dist_dev[jnp.argmax(ux_rms)]
            bl_visc = jnp.sum((dist_dev < delta_u).astype(rdt))
            # budget closures
            nu_plate = ss.nu_plate_sum[0] / n
            nu_flux = 1.0 + ss.flux_vol_sum[0] / n
            nu_resid = jnp.abs(nu_plate - nu_flux) / jnp.maximum(
                jnp.abs(nu_flux), 1.0
            )
            prod = ss.buoy_sum[0] / n
            dis = ss.diss_sum[0] / n
            # elapsed sim time first->last sample, exact across governor
            # dt-rung moves (each sample accumulated its own stride*dt);
            # one sample => span ~0 and dkedt reads 0 (ke_last == ke_first)
            span = jnp.maximum(ss.span_sum[0] - ss.span_first[0], 1e-300)
            dkedt = (ss.ke_last[0] - ss.ke_first[0]) / span
            ke_resid = jnp.abs(prod - dis - dkedt) / jnp.maximum(
                jnp.maximum(jnp.abs(prod), jnp.abs(dis)), 1e-9
            )

            def z(v):
                return jnp.where(has, v, jnp.zeros_like(v))

            return (
                z(tx[0]), z(ty[0]),
                z(tx[1]), z(ty[1]),
                z(tx[2]), z(ty[2]),
                z(bl_thermal), z(bl_visc),
                z(ke_resid), z(nu_resid),
                z(nu_plate), z(nu_flux),
                ss.samples[0],
            )

        return health

    # -- state construction ---------------------------------------------------

    def state_example(self):
        """ShapeDtypeStruct pytree of one member's StatsState."""
        import jax

        if self._example is None:
            self._example = jax.eval_shape(
                self.sample_fn(), self.model._state_example()
            )
        return self._example

    def init_state(self, k: int | None = None):
        """Zeroed StatsState (``k`` adds a leading member axis)."""
        import jax
        import jax.numpy as jnp

        ex = self.state_example()

        def zeros(leaf):
            shape = leaf.shape if k is None else (int(k),) + tuple(leaf.shape)
            return jnp.zeros(shape, dtype=leaf.dtype)

        return jax.tree.map(zeros, ex)

    def host_items(self, stats_state, tick) -> list:
        """``(h5path, numpy array, "raw")`` rows the GATHERED snapshot
        format appends for the stats leaves (exact dtypes — the restore is
        bit-equal).  Gathered writers require fully-addressable state, the
        same contract the baselined state writers carry."""
        items = [
            (f"stats_state/{name}", np.asarray(getattr(stats_state, name)), "raw")
            for name in stats_state._fields
        ]
        items.append(("stats_state/tick", np.asarray(tick), "raw"))
        return items

    def split_restored(self, updates: dict) -> dict:
        """Pull this engine's leaf entries (+ ``tick``) out of a restore
        ``updates`` dict (mutated in place); the remainder is the caller's
        state leaves.  Feed the result to :meth:`restore_state`."""
        names = self.state_example()._fields + ("tick",)
        return {n: updates.pop(n) for n in names if n in updates}

    def restore_state(self, data: dict | None, k: int | None = None):
        """``(stats_state, tick)`` from a restore dict (leaf names +
        ``tick``) — the ONE implementation behind every gathered/sharded
        restore path.  ``None``/missing leaves reset to zero: a checkpoint
        written before the engine was armed restarts the averaging window
        instead of failing the restore."""
        import jax.numpy as jnp

        init = self.init_state(k=k)
        zero_tick = jnp.zeros((1,), jnp.int32)
        if not data:
            return init, zero_tick
        for name in init._fields:
            arr = data.get(name)
            want = tuple(getattr(init, name).shape)
            if arr is not None and tuple(np.shape(arr)) != want:
                # resolution-elastic gathered restart: the STATE leaves
                # interpolate onto the new grid, but running sums on the
                # old spectrum cannot — restart the averaging window
                # instead of handing the stats chunk a shape mismatch
                print(
                    f"restored stats leaf {name!r} has shape "
                    f"{tuple(np.shape(arr))} != {want}; running averages "
                    "restart from zero"
                )
                return init, zero_tick
        fields = {}
        for name in init._fields:
            arr = data.get(name)
            fields[name] = (
                jnp.asarray(arr, dtype=getattr(init, name).dtype)
                if arr is not None
                else getattr(init, name)
            )
        tick = data.get("tick")
        if tick is not None:
            tick = jnp.asarray(
                np.asarray(tick),  # lint-ok: RPD005 tick is a replicated (1,) leaf
                jnp.int32,
            ).reshape((1,))
        return type(init)(**fields), tick if tick is not None else zero_tick


# -- host-side export ---------------------------------------------------------


def _averages(host: StatsState) -> dict:
    """Host-side running averages from a fetched (numpy) StatsState."""
    n = max(float(np.asarray(host.samples).reshape(-1)[0]), 1.0)
    out = {"samples": int(np.asarray(host.samples).reshape(-1)[0])}
    for name in ("t_sum", "ux_sum", "uy_sum", "nusselt_sum"):
        out[name[:-4] + "_avg"] = np.asarray(getattr(host, name)) / n
    out["t_prof"] = np.asarray(host.t_prof_sum) / n
    out["t_rms"] = np.sqrt(
        np.maximum(np.asarray(host.t2_prof_sum) / n - out["t_prof"] ** 2, 0.0)
    )
    out["ux_rms"] = np.sqrt(np.maximum(np.asarray(host.ux2_prof_sum) / n, 0.0))
    out["uy_rms"] = np.sqrt(np.maximum(np.asarray(host.uy2_prof_sum) / n, 0.0))
    out["flux_prof"] = np.asarray(host.flux_prof_sum) / n
    out["spec_x"] = np.asarray(host.spec_x) / n
    out["spec_y"] = np.asarray(host.spec_y) / n
    return out


def _write_member(h5, prefix: str, model, host: StatsState, tot_time: float) -> None:
    """One member's engine export: the legacy ``statistics.h5`` group
    layout (``{temp,ux,uy,nusselt}/{x,dx,y,dy,v,vhat}`` + counters/params,
    statistics.rs:140-158 — so the reference readers keep working) plus the
    engine extras under ``profiles/`` and ``spectra/``.  ``tot_time`` comes
    from the RUNNING object (an ensemble advances its own clock; the
    template model's never moves)."""
    from ..field import grid_deltas
    from ..utils.checkpoint import write_field

    avgs = _averages(host)
    sp = model.field_space
    xs = [b.points * s for b, s in zip(sp.bases, model.scale)]
    dxs = [
        grid_deltas(b.points, b.is_periodic) * s
        for b, s in zip(sp.bases, model.scale)
    ]
    import jax.numpy as jnp

    root = h5.require_group(prefix) if prefix else h5
    for varname, key in (
        ("temp", "t_avg"),
        ("ux", "ux_avg"),
        ("uy", "uy_avg"),
        ("nusselt", "nusselt_avg"),
    ):
        vhat = jnp.asarray(avgs[key], dtype=sp.spectral_dtype())
        write_field(root, varname, sp, vhat, xs, dxs)
    for key, value in (
        ("tot_time", float(tot_time)),
        # accumulated per sample at that sample's own stride*dt — exact
        # across governor dt-rung moves (a current-dt reconstruction would
        # misreport windows that crossed a ladder move)
        ("avg_time", float(np.asarray(host.span_sum).reshape(-1)[0])),
        ("num_save", float(avgs["samples"])),
    ):
        if key in root:
            del root[key]
        root.create_dataset(key, data=value)
    for key, value in model.params.items():
        if key in root:
            del root[key]
        root.create_dataset(key, data=float(value))
    prof = root.require_group("profiles")
    for key, data in (
        ("y", xs[1]),
        ("t_mean", avgs["t_prof"]),
        ("t_rms", avgs["t_rms"]),
        ("ux_rms", avgs["ux_rms"]),
        ("uy_rms", avgs["uy_rms"]),
        ("flux", avgs["flux_prof"]),
    ):
        if key in prof:
            del prof[key]
        prof.create_dataset(key, data=np.asarray(data, dtype=np.float64))
    spec = root.require_group("spectra")
    for key, data in (("x", avgs["spec_x"]), ("y", avgs["spec_y"])):
        if key in spec:
            del spec[key]
        spec.create_dataset(key, data=np.asarray(data, dtype=np.float64))


def export_stats(pde, filename: str) -> None:
    """Write the engine's running averages to HDF5.

    A single model exports the legacy root layout (readable by every
    ``statistics.h5`` consumer) + ``profiles``/``spectra`` groups; an
    ensemble exports per-member groups ``member{i}/...`` (same inner
    layout) with a root ``members`` scalar.  ``plot/plot_statistics.py``
    reads both."""
    import os

    import h5py
    import jax

    if not getattr(pde, "stats_armed", False):
        raise RuntimeError("export_stats needs an armed stats engine (set_stats)")
    os.makedirs(os.path.dirname(filename) or ".", exist_ok=True)
    is_ens = hasattr(pde, "member_state")
    model = pde.model if is_ens else pde
    host = jax.tree.map(np.asarray, pde.stats_state)
    with h5py.File(filename, "a") as h5:
        h5.attrs["stats_engine"] = 1
        h5.attrs["stride"] = int(model.stats_engine.stride)
        if is_ens:
            if "members" in h5:
                del h5["members"]
            h5.create_dataset("members", data=int(pde.k))
            for i in range(pde.k):
                member = jax.tree.map(lambda x, i=i: x[i], host)
                _write_member(h5, f"member{i}", model, member, pde.get_time())
        else:
            _write_member(h5, "", model, host, pde.get_time())
