"""Gradient-based optimization routines.

Rebuild of /root/reference/src/navier_stokes_lnse/opt_routines.rs:15-56.
"""

from __future__ import annotations

import numpy as np

from .lnse import l2_norm


def steepest_descent_energy_constrained(
    velx_0: np.ndarray,
    vely_0: np.ndarray,
    temp_0: np.ndarray,
    grad_velx: np.ndarray,
    grad_vely: np.ndarray,
    grad_temp: np.ndarray,
    beta1: float,
    beta2: float,
    alpha: float,
):
    """Steepest descent without energy increase: project the gradient
    perpendicular to the state, then rotate on the constant-energy sphere by
    angle ``alpha`` (opt_routines.rs:15-56).

    Returns ``(velx_new, vely_new, temp_new)`` (the reference mutates its
    output arguments; this is the functional form).
    """
    if alpha > 2.0 * np.pi:
        raise ValueError("alpha must be less than 2 pi")
    n = velx_0.size
    e0 = float(l2_norm(velx_0, velx_0, vely_0, vely_0, temp_0, temp_0, beta1, beta2)) / n
    eg = float(
        l2_norm(grad_velx, velx_0, grad_vely, vely_0, grad_temp, temp_0, beta1, beta2)
    ) / n

    # project gradient perpendicular to x0
    ee = eg / e0
    gu = grad_velx - ee * velx_0
    gv = grad_vely - ee * vely_0
    gt = grad_temp - ee * temp_0

    # linear combination of old field and gradient on the energy sphere
    eg = float(l2_norm(gu, gu, gv, gv, gt, gt, beta1, beta2)) / n
    ee2 = np.sqrt(e0 / eg)
    ca, sa = np.cos(alpha), np.sin(alpha)
    velx_new = velx_0 * ca + gu * (ee2 * sa)
    vely_new = vely_0 * ca + gv * (ee2 * sa)
    temp_new = temp_0 * ca + gt * (ee2 * sa)
    return velx_new, vely_new, temp_new
