"""Volume-penalization masks for solid–fluid interaction.

TPU rebuild of /root/reference/src/navier_stokes/solid_masks.rs:34-197.  Each
builder returns ``(mask, value)``: ``mask`` in [0, 1] marks solid cells (with
a tanh smoothing layer per arXiv:1903.11914 eq. 12), ``value`` is the field
value the solid enforces (temperature of the obstacle; velocity targets are
zero).

Unlike the reference — which stores the mask on the model but never applies
it in the update loop (navier.rs:86, SURVEY.md S7.8) — this framework wires
the penalization into the time step: ``Navier2D.set_solid`` adds an implicit
pointwise Brinkman relaxation ``du/dt = ... - (mask/eta) (u - u_s)`` solved
exactly per sub-step, unconditionally stable for any penalty ``eta``.
"""

from __future__ import annotations

import numpy as np


def _smooth_layer(dist: np.ndarray, thickness: float) -> np.ndarray:
    """Tanh smoothing ramp: 1 deep inside (dist << 0), 0 outside
    (arXiv:1903.11914 eq. 12 as used in solid_masks.rs:49-52)."""
    return 0.5 * (1.0 - np.tanh(2.0 * dist / thickness))


def solid_cylinder_inner(
    x: np.ndarray, y: np.ndarray, x0: float, y0: float, radius: float
) -> tuple[np.ndarray, np.ndarray]:
    """Solid cylinder: r < radius is solid, tanh layer of radius/10
    (/root/reference/src/navier_stokes/solid_masks.rs:34-60)."""
    r = np.sqrt((x0 - x[:, None]) ** 2 + (y0 - y[None, :]) ** 2)
    thickness = radius / 10.0
    mask = np.where(
        r < radius - thickness,
        1.0,
        np.where(r < radius + thickness, _smooth_layer(r - radius, thickness), 0.0),
    )
    return mask, np.zeros_like(mask)


def solid_rectangle(
    x: np.ndarray, y: np.ndarray, x0: float, y0: float, dx: float, dy: float
) -> tuple[np.ndarray, np.ndarray]:
    """Axis-aligned solid rectangle of half-widths (dx, dy)
    (/root/reference/src/navier_stokes/solid_masks.rs:63-83)."""
    inside = (np.abs(x[:, None] - x0) < dx) & (np.abs(y[None, :] - y0) < dy)
    mask = inside.astype(np.float64)
    return mask, np.zeros_like(mask)


def solid_roughness_sinusoid(
    x: np.ndarray, y: np.ndarray, height: float, wavenumber: float
) -> tuple[np.ndarray, np.ndarray]:
    """Sinusoidal roughness elements on both plates; the solid enforces the
    plate temperatures (+0.5 bottom, -0.5 top)
    (/root/reference/src/navier_stokes/solid_masks.rs:86-123)."""
    bottom, top = y[0], y[-1]
    thickness = height / 10.0
    y_rough = height * (top - bottom) / 2.0 * (np.sin(wavenumber * x) + 0.5)
    yr = y_rough[:, None]
    mask = np.zeros((x.size, y.size))
    value = np.zeros_like(mask)
    # bottom plate
    d = (y[None, :] - bottom) - yr
    m_bot = np.where(d <= 0.0, 1.0, np.where(d <= thickness, _smooth_layer(d, thickness), 0.0))
    mask = np.maximum(mask, m_bot)
    value = np.where(m_bot > 0.0, 0.5, value)
    # top plate
    d = (top - y[None, :]) - yr
    m_top = np.where(d <= 0.0, 1.0, np.where(d <= thickness, _smooth_layer(d, thickness), 0.0))
    mask = np.maximum(mask, m_top)
    value = np.where(m_top > 0.0, -0.5, value)
    return mask, value


def solid_porosity(
    x: np.ndarray, y: np.ndarray, diameter: float, porosity: float
) -> tuple[np.ndarray, np.ndarray]:
    """Regular array of circles approximating the requested porosity
    (/root/reference/src/navier_stokes/solid_masks.rs:127-162)."""
    radius = diameter / 2.0
    length = x[-1] - x[0]
    height = y[-1] - y[0]
    ncx = round(np.sqrt((1.0 - porosity) * 4.0 * length**2 / (np.pi * diameter**2)))
    ncy = round(np.sqrt((1.0 - porosity) * 4.0 * height**2 / (np.pi * diameter**2)))
    dist_x = (length - ncx * diameter) / (ncx + 1.0)
    dist_y = (height - ncy * diameter) / (ncy + 1.0)
    mask = np.zeros((x.size, y.size))
    ox = x[0] + dist_x + radius
    for _ in range(int(ncx)):
        oy = y[0] + dist_y + radius
        for _ in range(int(ncy)):
            mask += solid_cylinder_inner(x, y, ox, oy, radius)[0]
            oy += dist_y + diameter
        ox += dist_x + diameter
    return mask, np.zeros_like(mask)


def solid_porosity_interpolate(
    nx: int, ny: int, diameter: float, porosity: float
) -> tuple[np.ndarray, np.ndarray]:
    """Build the porosity mask on a fixed 513x513 Chebyshev grid, then
    spectrally interpolate (coefficient truncation/zero-pad) onto the
    requested chebyshev x chebyshev grid — grid-converged masks independent
    of the target resolution
    (/root/reference/src/navier_stokes/solid_masks.rs:166-196)."""
    import jax.numpy as jnp

    from ..bases import Space2, chebyshev

    n = 513
    src = Space2(chebyshev(n), chebyshev(n))
    dst = Space2(chebyshev(nx), chebyshev(ny))
    xs, ys = src.bases[0].points, src.bases[1].points
    out = []
    for values in solid_porosity(xs, ys, diameter, porosity):
        # truncate/zero-pad the LOWEST modes, i.e. in natural coefficient
        # order — the spaces themselves may store spectral axes
        # parity-permuted (sep layout) on the TPU path
        vhat = src.spectral_to_natural(src.forward(jnp.asarray(values)))
        sh = (min(n, nx), min(n, ny))
        padded = np.zeros((nx, ny))
        padded[: sh[0], : sh[1]] = vhat[: sh[0], : sh[1]]
        padded = dst.spectral_from_natural(padded)
        out.append(np.asarray(dst.backward(jnp.asarray(padded))))
    return out[0], out[1]
