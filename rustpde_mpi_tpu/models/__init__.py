"""Physics model layer: Navier-Stokes DNS and derived solvers."""

from .navier import Navier2D, NavierState  # noqa: F401
