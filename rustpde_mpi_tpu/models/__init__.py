"""Physics model layer: Navier-Stokes DNS and derived solvers.

Every model here satisfying the CampaignModel contract
(:mod:`~rustpde_mpi_tpu.models.campaign`) — ``Navier2D``, ``Navier2DLnse``,
``Navier2DAdjoint`` — runs under the shared ensemble/resilience/serve
stack; the workload drivers live in :mod:`rustpde_mpi_tpu.workloads`.
"""

from .campaign import CAMPAIGN_MODEL_ATTRS, CampaignModelBase  # noqa: F401
from .ensemble import NavierEnsemble  # noqa: F401
from .lnse import Navier2DLnse, Navier2DNonLin  # noqa: F401
from .meanfield import MeanFields  # noqa: F401
from .navier import (  # noqa: F401
    Navier2D,
    NavierScalarState,
    NavierState,
    scenario_signature,
)
from .opt_routines import steepest_descent_energy_constrained  # noqa: F401
from .statistics import Statistics  # noqa: F401
from .stats import (  # noqa: F401
    HEALTH_NAMES,
    StatsEngine,
    StatsState,
    export_stats,
)
from .steady_adjoint import AdjointState, Navier2DAdjoint  # noqa: F401
