"""Physics model layer: Navier-Stokes DNS and derived solvers."""

from .navier import Navier2D, NavierState  # noqa: F401
from .statistics import Statistics  # noqa: F401
from .steady_adjoint import Navier2DAdjoint  # noqa: F401
