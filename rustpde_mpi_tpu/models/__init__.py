"""Physics model layer: Navier-Stokes DNS and derived solvers."""

from .ensemble import NavierEnsemble  # noqa: F401
from .lnse import Navier2DLnse, Navier2DNonLin  # noqa: F401
from .meanfield import MeanFields  # noqa: F401
from .navier import Navier2D, NavierState  # noqa: F401
from .opt_routines import steepest_descent_energy_constrained  # noqa: F401
from .statistics import Statistics  # noqa: F401
from .steady_adjoint import Navier2DAdjoint  # noqa: F401
