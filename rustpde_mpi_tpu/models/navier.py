"""Navier2D — 2-D Boussinesq Rayleigh–Bénard DNS, TPU-native.

Rebuild of the reference's physics layer
(/root/reference/src/navier_stokes/{navier,navier_eq}.rs) as a *functional*
JAX model: the simulation state is an immutable pytree of spectral
coefficients, one time step is a pure jitted function, and many steps run per
host round-trip through ``lax.scan``.  One model class covers both the
fully-confined (Chebyshev x Chebyshev) and horizontally-periodic
(Fourier x Chebyshev) configurations — the reference's serial/MPI module
duplication is intentionally not reproduced; sharding is layered on top in
``parallel/`` without touching the physics.

Numerical scheme (identical to the reference, navier_eq.rs):

* implicit Euler diffusion via ADI Helmholtz solves,
* explicit convection with 2/3-rule dealiasing,
* pressure projection: Poisson solve for a pseudo-pressure, velocity
  correction, pressure update ``pres += -nu*div + pseu/dt``,
* inhomogeneous BCs through constant lift fields (boundary_conditions.py).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import config
from ..bases import (
    Space2,
    cheb_dirichlet,
    cheb_dirichlet_neumann,
    cheb_neumann,
    chebyshev,
    fourier_r2c,
)
from ..field import average_weights, norm_l2
from ..solver import HholtzAdi, Poisson
from ..utils.integrate import Integrate
from . import boundary_conditions as bcs
from . import functions as fns
from .campaign import CampaignModelBase


class NavierState(NamedTuple):
    """Spectral-coefficient pytree threaded through the jitted step."""

    temp: jax.Array
    velx: jax.Array
    vely: jax.Array
    pres: jax.Array
    pseu: jax.Array


class NavierScalarState(NamedTuple):
    """NavierState plus a passive scalar (the ``passive_scalar`` scenario
    modifier): ``scal`` is advected by the flow and diffused at the scalar
    diffusivity, with the temperature BC lift as its boundary forcing — so a
    scalar released equal to the temperature with matched diffusivity stays
    identically equal (the scenario's exact validation case)."""

    temp: jax.Array
    velx: jax.Array
    vely: jax.Array
    pres: jax.Array
    pseu: jax.Array
    scal: jax.Array


def scenario_signature(scenario) -> tuple:
    """Canonical compat-key signature of a scenario-modifier config (any
    object carrying ``coriolis`` / ``passive_scalar`` / ``scalar_kappa``,
    e.g. :class:`~rustpde_mpi_tpu.workloads.modifiers.ScenarioConfig`, or a
    plain dict as carried by a :class:`~rustpde_mpi_tpu.serve.SimRequest`).
    Modifier terms are baked into the compiled step, so they MUST flow
    through ``compat_key`` — an empty/default scenario signs as ``()``,
    equal to no scenario at all."""
    if scenario is None:
        return ()
    get = (
        scenario.get
        if isinstance(scenario, dict)
        else lambda k, d=None: getattr(scenario, k, d)
    )
    items = []
    f = float(get("coriolis", 0.0) or 0.0)
    if f:
        items.append(("coriolis", f))
    if get("passive_scalar", False):
        kappa = get("scalar_kappa", None)
        if kappa is not None and float(kappa) <= 0.0:
            # 0.0 would collide with the thermal-default sentinel below
            # (and a non-diffusive implicit solve is not supported)
            raise ValueError(
                f"scalar_kappa must be positive (got {kappa}); omit it for "
                "the thermal diffusivity"
            )
        items.append(
            ("passive_scalar", float(kappa) if kappa is not None else 0.0)
        )
    return tuple(items)


def brinkman_factors(model, mask, value=None, eta: float | None = None):
    """The pointwise implicit-Brinkman penalization factors
    ``(fac, temp_add)`` for one obstacle on ``model``'s grid — THE single
    implementation shared by :meth:`Navier2D.set_solid` (which bakes them
    into the step) and the vmapped geometry sweep
    (workloads/modifiers.py, which feeds them as per-member runtime
    inputs); the sweep's bit-match-solo guarantee rests on this sharing.

    ``fac = 1 / (1 + (dt/eta) mask)``; ``temp_add`` relaxes the
    temperature toward ``value`` minus the BC lift (the temp state
    excludes the lift field)."""
    rdt = config.real_dtype()
    mask = np.asarray(mask, dtype=np.float64)
    if value is None:
        value = np.zeros_like(mask)
    if eta is None:
        eta = model.dt / 10.0
    a = (model.dt / float(eta)) * mask
    fac = 1.0 / (1.0 + a)
    # temp state excludes the BC lift field: target = value - tempbc
    sp = model.field_space
    with model._scope():
        tempbc_phys = np.asarray(sp.backward_ortho(model.tempbc_ortho))
    temp_add = a * (value - tempbc_phys) * fac
    return jnp.asarray(fac, dtype=rdt), jnp.asarray(temp_add, dtype=rdt)


class Navier2D(CampaignModelBase, Integrate):
    """2-D Rayleigh–Bénard convection solver.

    Construct via :meth:`new_confined` (Chebyshev x Chebyshev) or
    :meth:`new_periodic` (Fourier x Chebyshev); parameter vocabulary matches
    the reference (nx, ny, ra, pr, dt, aspect, bc in {"rbc", "hc"}).

    The campaign-model machinery (chunked scans, sentinels, dt rung cache,
    observable futures, snapshot surface — everything the ensemble engine,
    governor, checkpoints and serve scheduler ride on) lives in
    :class:`~rustpde_mpi_tpu.models.campaign.CampaignModelBase`; this class
    supplies the physics: spaces, solvers, the step, the observables, and
    the config-carried scenario modifiers (rotating-frame Coriolis term,
    passive-scalar transport)."""

    MODEL_KIND = "dns"

    @property
    def observable_names(self) -> tuple:
        """The fused-observables vocabulary.  A passive-scalar scenario
        appends ``sherwood`` (the scalar-transfer analog of the plate-flux
        Nusselt number) AFTER the conventional four — index 3 stays the
        NaN-detector |div| every consumer keys on."""
        base = ("nu", "nuvol", "re", "div")
        if self._scalar_active():
            return base + ("sherwood",)
        return base

    def __init__(
        self,
        nx: int,
        ny: int,
        ra: float,
        pr: float,
        dt: float,
        aspect: float,
        bc: str,
        periodic: bool,
        mesh=None,
        scenario=None,
    ):
        if bc not in ("rbc", "hc"):
            raise ValueError(f"boundary condition type {bc!r} not recognized")
        # pencil-sharding mesh (None = single device); one model serves both —
        # the reference's navier_stokes vs navier_stokes_mpi duplication is
        # deliberately not reproduced (SURVEY.md S1 note)
        self.mesh = mesh
        self.nx, self.ny = nx, ny
        self.dt = dt
        self.periodic = periodic
        self.bc = bc
        self.scale = (float(aspect), 1.0)
        nu = fns.get_nu(ra, pr, self.scale[1] * 2.0)
        ka = fns.get_ka(ra, pr, self.scale[1] * 2.0)
        self.params = {"ra": ra, "pr": pr, "nu": nu, "ka": ka}
        self.write_intervall: float | None = None
        self.statistics = None
        self._init_campaign()  # obs cache, sentinels, dt rung cache
        self._solid = None  # (penalization factors) set via set_solid()
        # config-carried scenario step modifiers (rotating-frame Coriolis,
        # passive scalar — see workloads/modifiers.ScenarioConfig); baked
        # into the compiled step, signed into compat_key
        self._scenario = scenario
        # diagnostics history appended by the IO callback — the map the
        # reference allocates but never writes (navier.rs:81)
        self.diagnostics: dict[str, list[float]] = {}

        x_base = fourier_r2c if periodic else cheb_dirichlet
        x_full = fourier_r2c if periodic else chebyshev
        x_neumann = fourier_r2c if periodic else cheb_neumann

        # spaces per variable (/root/reference/src/navier_stokes/navier.rs:235-256,356-376);
        # velx/vely share one space object (identical bases -> shared operator
        # constants on device)
        self.velx_space = Space2(x_base(nx), cheb_dirichlet(ny))
        self.vely_space = self.velx_space
        temp_ybase = cheb_dirichlet(ny) if bc == "rbc" else cheb_dirichlet_neumann(ny)
        self.temp_space = Space2(x_neumann(nx), temp_ybase)
        self.pres_space = Space2(x_full(nx), chebyshev(ny))
        self.pseu_space = Space2(x_neumann(nx), cheb_neumann(ny))
        # scratch space for convection/observables (full ortho bases)
        self.field_space = Space2(x_full(nx), chebyshev(ny))

        # grid (unscaled master coords; physical coords = coords * scale)
        self.x = [b.points * s for b, s in zip(self.field_space.bases, self.scale)]
        xs, ys = (b.points for b in self.field_space.bases)
        # average weights dx/L as in the reference's average_axis
        # (/root/reference/src/field/average.rs:26-35), with this repo's
        # full-period normalization for periodic axes (field.average_weights)
        w0 = average_weights(xs, self.field_space.base_x.is_periodic)
        w1 = average_weights(ys, False)
        rdt = config.real_dtype()
        self._w0 = jnp.asarray(w0, dtype=rdt)
        self._w1 = jnp.asarray(w1, dtype=rdt)
        # per-point inverse grid spacing (physical, scaled) for the pointwise
        # advective CFL sentinel dt*max(|ux|/dx + |uy|/dy): cell widths from
        # the same midpoint rule the averages use — near a Chebyshev wall the
        # spacing is O(1/N^2) but the no-slip velocity vanishes linearly, so
        # the pointwise ratio self-limits to the local shear rate
        from ..field import grid_deltas

        dx0 = grid_deltas(xs, self.field_space.base_x.is_periodic) * self.scale[0]
        dy0 = grid_deltas(ys, False) * self.scale[1]
        self._inv_dx = jnp.asarray(1.0 / dx0, dtype=rdt)
        self._inv_dy = jnp.asarray(1.0 / dy0, dtype=rdt)

        # implicit solvers (/root/reference/src/navier_stokes/navier.rs:263-275)
        sx2, sy2 = self.scale[0] ** 2, self.scale[1] ** 2
        self.solver_velx = HholtzAdi(self.velx_space, (dt * nu / sx2, dt * nu / sy2))
        self.solver_vely = self.solver_velx  # identical operator, shared factors
        self.solver_temp = HholtzAdi(self.temp_space, (dt * ka / sx2, dt * ka / sy2))
        self.solver_pres = Poisson(self.pseu_space, (1.0 / sx2, 1.0 / sy2))
        # passive-scalar solver (scenario modifier): the scalar shares the
        # temperature's composite space and BC lift; at matched diffusivity
        # it shares the temperature solver's factorizations outright
        self.solver_scal = self._build_scalar_solver()

        # dealiasing mask over the scratch spectral shape (split-aware)
        self._dealias = jnp.asarray(self.field_space.dealias_mask(), dtype=rdt)

        # fused convection-chain impls keyed by id(space) — FusedConv
        # (RUSTPDE_CONV_KERNEL=pallas, ops/pallas_conv.py) or ShardedConv
        # (manual-partitioned split-sep path, parallel/decomp.py); None
        # keeps the unfused dense chain (the measured default)
        self._conv_impl = self._build_conv_kernels()

        # fused projection-gradient operators for the velocity correction
        # (confined only; the periodic x-axis gradient is diagonal logic):
        # velx -= P_u (D S_q) pseu / sx  per axis — one cross-space matrix
        # per axis instead of gradient + to_ortho + 2 projection applies
        from ..bases import fused_projection_gradient

        gx = fused_projection_gradient(self.velx_space, self.pseu_space, (1, 0))
        gy = fused_projection_gradient(self.vely_space, self.pseu_space, (0, 1))
        self._proj_grad = (*gx, *gy) if gx and gy else None

        # boundary-condition lift fields as device constants
        with self._scope():
            self._build_bc_fields(xs, ys)

        # fused implicit-half stage kernels (RUSTPDE_STEP_KERNEL=pallas,
        # ops/pallas_step.py): Helmholtz/Poisson solves + divergence +
        # projection as VMEM-resident Pallas stages; None keeps the dense
        # solver chain (the measured default).  Built AFTER the BC fields
        # (the buoyancy/diffusion lift constants fold into the stages).
        self._step_impl = self._build_step_kernels()

        # jitted step + observables
        # jit with closure-converted constants: the dense transform / solver
        # matrices are hoisted out of the traced program and passed as
        # device-resident runtime arguments instead of being embedded in the
        # HLO — at 2049^2 the embedded-constant program exceeds what the TPU
        # compile service accepts (hundreds of MB), while the hoisted program
        # is a few hundred KB for any grid size.
        self._compile_entry_points()

        with self._scope():
            self.state = self._state_cls()(
                **{
                    name: self._place(space.ndarray_spectral())
                    for name, space in self._state_fields()
                }
            )

    # one-time-warning latch for the GSPMD split-sep fallback (class-level:
    # one warning per process, not per model)
    _warned_split_sep_fallback = False

    def _build_conv_kernels(self):
        """Fused convection-chain implementations the step's ``conv()``
        routes through by space identity (None: the unfused dense chain).

        * no mesh + ``RUSTPDE_CONV_KERNEL=pallas``: the VMEM-tiled Pallas
          kernel (ops/pallas_conv.py; interpreter mode off-TPU);
        * active mesh on the split-sep periodic layout (default mode
          "manual"): the manually-partitioned shard_map region
          (parallel/decomp.ShardedConv) — explicit per-pencil GEMMs +
          transposes instead of the GSPMD propagation that miscompiles the
          fused step there;
        * any other meshed model keeps the dense chain: its convection
          GEMMs partition cleanly under GSPMD."""
        from ..ops import pallas_conv

        if self.mesh is not None:
            if self._split_sep_mode() == "manual":
                from ..parallel.decomp import (
                    ShardedConv,
                    ShardedPoisson,
                    ShardedSynthesis,
                )

                specs = {}
                for space in (self.velx_space, self.temp_space):
                    if id(space) not in specs:
                        specs[id(space)] = ShardedConv(
                            space, self.field_space, self.scale, self.mesh
                        )
                # the convection-velocity syntheses ride their own region,
                # and the pressure-Poisson fast-diag solve — the stage the
                # miscompile bisects to (see ShardedPoisson) — MUST be
                # manual for the fused step to compile correctly
                self._manual_synth = {
                    id(self.velx_space): ShardedSynthesis(
                        self.velx_space, None, self.mesh
                    )
                }
                self._manual_poisson = ShardedPoisson(
                    self.solver_pres, self.pseu_space, self.mesh
                )
                return specs
            self._manual_synth = None
            self._manual_poisson = None
            return None
        self._manual_synth = None
        self._manual_poisson = None
        if pallas_conv.conv_kernel_choice() != "pallas":
            return None
        return pallas_conv.build_model_convs(self)

    def _build_step_kernels(self):
        """Fused implicit-half stage kernels the step routes through
        (None: the dense solver chain).  Single-device only — meshed
        models keep the dense/manual-shard_map paths; the sharded fused
        stages ride the shard_map follow-up (ROADMAP)."""
        from ..ops import pallas_step

        if self.mesh is not None:
            return None
        if pallas_step.step_kernel_choice() != "pallas":
            return None
        return pallas_step.build_model_step(self)

    def _split_sep_poisoned(self) -> bool:
        """The layout the upstream GSPMD bug miscompiles: split Re/Im
        Fourier x sep Chebyshev under an active mesh (see
        ``_gspmd_split_sep_fallback``)."""
        if self.mesh is None or not self.periodic:
            return False
        sp = self.temp_space
        return sp.bases[0].kind.is_split and any(sp.sep)

    def _split_sep_eager_unless_forced(self) -> bool:
        """Eager-guard policy for wrapper models (Navier2DLnse /
        Navier2DAdjoint) whose steps have no manual shard_map counterpart
        yet: per-stage eager whenever the poisoned layout is active, unless
        ``RUSTPDE_FORCE_FUSED_GSPMD=1`` pins the fused path — ONE shared
        helper so the two wrappers cannot drift when their manual regions
        eventually land."""
        if config.env_get("RUSTPDE_FORCE_FUSED_GSPMD") == "1":
            return False
        return self._split_sep_poisoned()

    def _split_sep_mode(self) -> str:
        """How a split-sep periodic model executes under an active mesh:

        * ``"fused"`` — the plain GSPMD-fused step (non-poisoned layouts;
          or ``RUSTPDE_FORCE_FUSED_GSPMD=1``, which keeps the pinned xfail
          tracking the upstream miscompile);
        * ``"manual"`` (default on the poisoned layout) — fused scanned
          step with the convection transforms in manually-partitioned
          shard_map regions (ShardedConv): correct AND compiled, retiring
          the per-stage eager fallback;
        * ``"eager"`` (``RUSTPDE_SPLIT_SEP_FALLBACK=eager``) — the old
          per-stage dispatch path, kept for triage A/Bs."""
        if config.env_get("RUSTPDE_FORCE_FUSED_GSPMD") == "1":
            return "fused"
        if not self._split_sep_poisoned():
            return "fused"
        mode = config.env_get("RUSTPDE_SPLIT_SEP_FALLBACK", "manual")
        if mode not in ("manual", "eager"):
            raise ValueError(
                f"RUSTPDE_SPLIT_SEP_FALLBACK must be 'manual' or 'eager', got {mode!r}"
            )
        return mode

    # -- scenario modifiers ---------------------------------------------------

    def _scn(self, key, default=None):
        """Scenario attribute lookup (dataclass or request-carried dict)."""
        scn = self._scenario
        if scn is None:
            return default
        if isinstance(scn, dict):
            return scn.get(key, default)
        return getattr(scn, key, default)

    def _coriolis(self) -> float:
        return float(self._scn("coriolis", 0.0) or 0.0)

    def _scalar_active(self) -> bool:
        return bool(self._scn("passive_scalar", False))

    def _scalar_kappa(self) -> float:
        """Scalar diffusivity (``None`` defaults to the thermal one — the
        matched-diffusivity configuration whose scalar mirrors the
        temperature; non-positive values are rejected, see
        :func:`scenario_signature`)."""
        kappa = self._scn("scalar_kappa", None)
        if kappa is None:
            return float(self.params["ka"])
        kappa = float(kappa)
        if kappa <= 0.0:
            raise ValueError(f"scalar_kappa must be positive, got {kappa}")
        return kappa

    def _build_scalar_solver(self):
        if not self._scalar_active():
            return None
        kc = self._scalar_kappa()
        if kc == float(self.params["ka"]):
            return self.solver_temp  # identical operator, shared factors
        sx2, sy2 = self.scale[0] ** 2, self.scale[1] ** 2
        return HholtzAdi(self.temp_space, (self.dt * kc / sx2, self.dt * kc / sy2))

    def _scan_ok(self, state):
        """The in-scan divergence detector.  A NaN in the FLOW infects temp
        within one step (buoyancy/convection), but the passive scalar is
        one-way coupled — a scal-only NaN would never reach temp — so the
        scalar leaf joins the finiteness probe when the scenario carries
        one (one extra reduction, scalar models only)."""
        probe = jnp.sum(state.temp)
        if self._scalar_active():
            probe = probe + jnp.sum(state.scal)
        return jnp.isfinite(probe)

    @property
    def scal_space(self):
        """The passive scalar rides the temperature's composite space."""
        return self.temp_space

    @property
    def scenario(self):
        return self._scenario

    def set_scenario(self, scenario) -> None:
        """Install (or clear, ``None``) the scenario step modifiers on a
        live model: the modifier terms are operator constants, so the entry
        points recompile and every dt rung is invalidated.  Toggling the
        passive scalar restructures the state pytree (the ``scal`` leaf is
        added zero-initialized / dropped); all other leaves are kept."""
        self._scenario = scenario
        self._dt_cache.clear()
        self.solver_scal = self._build_scalar_solver()
        # scenario terms (Coriolis cross-coupling, the scalar stage) are
        # baked into the fused stage kernels — rebuild alongside the solver
        self._step_impl = self._build_step_kernels()
        want_scal = self._scalar_active()
        have_scal = hasattr(self.state, "scal")
        if want_scal and not have_scal:
            with self._scope():
                self.state = NavierScalarState(
                    *self.state,
                    scal=self._place(self.temp_space.ndarray_spectral()),
                )
        elif not want_scal and have_scal:
            self.state = NavierState(*self.state[:5])
        self._compile_entry_points()
        self._obs_cache = None

    def _state_fields(self) -> list:
        """Ordered ``(leaf_name, space)`` of the state pytree (the scenario
        decides whether the scalar leaf exists)."""
        fields = [
            ("temp", self.temp_space),
            ("velx", self.velx_space),
            ("vely", self.vely_space),
            ("pres", self.pres_space),
            ("pseu", self.pseu_space),
        ]
        if self._scalar_active():
            fields.append(("scal", self.temp_space))
        return fields

    def _state_cls(self):
        return NavierScalarState if self._scalar_active() else NavierState

    def _state_example(self):
        return self._state_cls()(
            **{
                name: jax.ShapeDtypeStruct(
                    space.shape_spectral, space.spectral_dtype()
                )
                for name, space in self._state_fields()
            }
        )

    @property
    def snapshot_vars(self) -> tuple:
        """``(h5 var name, state attr)`` rows the gathered snapshot format
        carries — the checkpoint layer consults this so scenario-extended
        states round-trip (utils/checkpoint)."""
        base = (("ux", "velx"), ("uy", "vely"), ("temp", "temp"), ("pres", "pres"))
        if self._scalar_active():
            return base + (("scal", "scal"),)
        return base

    def _compile_eager_entry_points(self) -> None:
        """The campaign base's per-stage eager fallback, plus the one-time
        (per-process) warning naming the GSPMD miscompile it routes around."""
        if not Navier2D._warned_split_sep_fallback:
            import warnings

            warnings.warn(
                "the fused split-sep periodic step is miscompiled by "
                "GSPMD under an active mesh (xfailed in "
                "tests/test_parallel.py); falling back to per-stage "
                "eager execution — multichip periodic runs are slower "
                "but correct.  Set RUSTPDE_FORCE_FUSED_GSPMD=1 to force "
                "the fused path.",
                RuntimeWarning,
                stacklevel=2,
            )
            Navier2D._warned_split_sep_fallback = True
        super()._compile_eager_entry_points()

    def _gspmd_split_sep_fallback(self) -> bool:
        """True when the step must run the per-stage EAGER path.  GSPMD
        miscompiles the fused split-sep periodic step under an active mesh
        (container jax 0.4.37 regression — every stage matches serial to
        ~1e-17 jitted separately and the eager per-op sharded step is exact,
        but the fused program yields wrong vely/pres from step 1; pinned
        xfail in tests/test_parallel.py under RUSTPDE_FORCE_FUSED_GSPMD=1).
        The DEFAULT on that layout is no longer eager: the convection
        transforms run as manually-partitioned shard_map regions
        (``_split_sep_mode() == "manual"``, parallel/decomp.ShardedConv),
        which sidesteps the broken SPMD propagation by construction and
        keeps the fused scanned chunk — eager remains only as the
        ``RUSTPDE_SPLIT_SEP_FALLBACK=eager`` triage pin."""
        return self._split_sep_mode() == "eager"

    def _compat_fields(self) -> tuple:
        """Everything (beyond the kind prefix) baked into the model's
        operator constants — grid, physics parameters, dt (the implicit
        solvers factorize ``dt*nu``), geometry, BC family, and the scenario
        modifier signature.  Two requests with equal keys can share one
        compiled step jaxpr (and therefore one ensemble batch: the serve
        scheduler buckets by this key); anything differing forces a fresh
        model build + compile."""
        return (
            int(self.nx),
            int(self.ny),
            float(self.params["ra"]),
            float(self.params["pr"]),
            float(self.dt),
            float(self.scale[0]),
            str(self.bc),
            bool(self.periodic),
            scenario_signature(self._scenario),
        )

    # -- construction --------------------------------------------------------

    @classmethod
    def new_confined(cls, nx, ny, ra, pr, dt, aspect, bc, mesh=None) -> "Navier2D":
        """Chebyshev x Chebyshev (fully confined cell), with random IC as in
        the reference (/root/reference/src/navier_stokes/navier.rs:215-308)."""
        model = cls(nx, ny, ra, pr, dt, aspect, bc, periodic=False, mesh=mesh)
        model.init_random(0.1)
        return model

    @classmethod
    def new_periodic(cls, nx, ny, ra, pr, dt, aspect, bc, mesh=None) -> "Navier2D":
        """Fourier x Chebyshev (horizontally periodic)
        (/root/reference/src/navier_stokes/navier.rs:336-428)."""
        model = cls(nx, ny, ra, pr, dt, aspect, bc, periodic=True, mesh=mesh)
        model.init_random(0.1)
        return model

    @classmethod
    def from_config(cls, cfg, mesh=None) -> "Navier2D":
        """Construct from a :class:`~rustpde_mpi_tpu.config.NavierConfig`."""
        model = cls(
            *cfg.ctor_args(),
            periodic=cfg.periodic,
            mesh=mesh,
            scenario=getattr(cfg, "scenario", None),
        )
        if cfg.init_random_amp:
            model.init_random(cfg.init_random_amp)
        model.write_intervall = cfg.write_intervall
        model.params.update(cfg.params)
        if getattr(cfg, "stability", None) is not None:
            model.set_stability(cfg.stability)
        stats_cfg = getattr(cfg, "stats", None)
        if stats_cfg is None and config.env_get("RUSTPDE_STATS") == "1":
            stats_cfg = config.StatsConfig()
        if stats_cfg is not None:
            model.set_stats(stats_cfg)
        integ_cfg = getattr(cfg, "integrity", None)
        if integ_cfg is None and config.env_get("RUSTPDE_INTEGRITY") == "1":
            integ_cfg = config.IntegrityConfig()
        if integ_cfg is not None:
            model.set_integrity(integ_cfg)
        return model

    def _build_bc_fields(self, xs: np.ndarray, ys: np.ndarray) -> None:
        """Transform the BC lift profiles into ortho-space constants and
        precompute every derivative the step needs (the reference recomputes
        these each step from the stored lift field)."""
        sp = self.field_space
        scale = self.scale
        dt, ka = self.dt, self.params["ka"]
        if self.bc == "rbc":
            tempbc_v = bcs.bc_rbc_values(xs, ys)
        else:
            tempbc_v = bcs.bc_hc_values(xs, ys)
        rdt = config.real_dtype()
        that = sp.forward(jnp.asarray(tempbc_v, dtype=rdt))
        self.tempbc_ortho = that
        # physical gradients for the convection bc-contribution
        self._tempbc_dx = sp.backward_ortho(sp.gradient(that, (1, 0), scale))
        self._tempbc_dy = sp.backward_ortho(sp.gradient(that, (0, 1), scale))
        # diffusion source dt*ka*(d2/dx2 + d2/dy2) bc  (navier_eq.rs:214-218)
        self._tempbc_diff = dt * ka * (
            sp.gradient(that, (2, 0), scale) + sp.gradient(that, (0, 2), scale)
        )
        # NOTE: the reference also builds a presbc lift field but never
        # consumes it in the time loop or the snapshot writer
        # (/root/reference/src/navier_stokes/navier_io.rs:44-62); the profile
        # itself remains available as bcs.pres_bc_rbc_values.

    # -- solid obstacles (volume penalization) -------------------------------

    def set_solid(self, mask, value=None, eta: float | None = None) -> None:
        """Add a solid obstacle via Brinkman volume penalization.

        ``mask`` (nx, ny): 1 inside the solid, 0 in the fluid, smooth layer in
        between (models/solid_masks.py builders); ``value``: temperature the
        solid enforces (default 0); ``eta``: penalty time scale (default
        dt/10).  The reference stores the mask but never applies it
        (/root/reference/src/navier_stokes/navier.rs:86); here the step gains
        an *implicit pointwise* relaxation, solved exactly per sub-step:

            u    <- u / (1 + dt/eta * mask)
            temp <- (temp + dt/eta * mask * value) / (1 + dt/eta * mask)

        which is unconditionally stable for any eta.  Pass ``mask=None`` to
        remove the obstacle.

        The factor math lives in :func:`brinkman_factors` — shared verbatim
        with the vmapped geometry sweep
        (workloads/modifiers.geometry_sweep), whose bit-match-solo guarantee
        depends on the two paths never diverging."""
        # cached per-dt artifacts embed the penalization factors of the OLD
        # obstacle — changing the obstacle invalidates every rung
        self._dt_cache.clear()
        if mask is None:
            self._solid = None
            self._compile_entry_points()
            return
        mask = np.asarray(mask, dtype=np.float64)
        if value is None:
            value = np.zeros_like(mask)
        if eta is None:
            eta = self.dt / 10.0
        fac, temp_add = brinkman_factors(self, mask, value, eta)
        self._solid = {
            "mask": mask,
            "value": value,
            "eta": float(eta),  # retained so set_dt can rebuild the factors
            "fac": fac,
            "temp_add": temp_add,
        }
        self._compile_entry_points()

    @property
    def solid(self):
        """Reference-parity accessor: ``model.solid = (mask, value)``
        (navier.rs:86 ``navier.solid = Some(mask)``)."""
        if self._solid is None:
            return None
        return (self._solid["mask"], self._solid["value"])

    @solid.setter
    def solid(self, mask_value) -> None:
        if mask_value is None:
            self.set_solid(None)
        else:
            self.set_solid(mask_value[0], mask_value[1])

    # -- initial conditions --------------------------------------------------

    def init_random(self, amp: float, seed: int = 0) -> None:
        """Random uniform disturbance on temp/velx/vely
        (/root/reference/src/navier_stokes/navier.rs:173-182)."""
        rng = np.random.default_rng(seed)
        for name in ("temp", "velx", "vely"):
            space: Space2 = getattr(self, f"{name}_space")
            v = fns.random_values(space.shape_physical, amp, rng)
            self.set_field(name, v)

    def set_velocity(self, amp: float, m: float, n: float) -> None:
        """velx = amp sin(pi m x~) cos(pi n y~), vely = -amp cos sin
        (/root/reference/src/navier_stokes/navier.rs:161-164)."""
        xs, ys = (b.points for b in self.field_space.bases)
        self.set_field("velx", fns.sin_cos_values(xs, ys, amp, m, n))
        self.set_field("vely", fns.cos_sin_values(xs, ys, -amp, m, n))

    def set_temperature(self, amp: float, m: float, n: float) -> None:
        xs, ys = (b.points for b in self.field_space.bases)
        self.set_field("temp", fns.cos_sin_values(xs, ys, -amp, m, n))

    def set_field(self, name: str, values: np.ndarray) -> None:
        """Set one variable from physical values (host -> device forward)."""
        space: Space2 = getattr(self, f"{name}_space")
        with self._scope():
            vhat = space.forward(jnp.asarray(values, dtype=config.real_dtype()))
            self.state = self.state._replace(**{name: self._place(vhat)})

    def get_field(self, name: str) -> np.ndarray:
        """Physical values of one variable (device backward -> host)."""
        space: Space2 = getattr(self, f"{name}_space")
        with self._scope():
            return np.asarray(space.backward(getattr(self.state, name)))

    # -- the time step -------------------------------------------------------

    def _make_step(self, with_sentinels: bool = False):
        """The jitted step.  ``with_sentinels=True`` returns
        ``(state, (cfl, ke, div_norm))`` instead of just the state: pointwise
        advective CFL ``dt*max(|ux|/dx + |uy|/dy)`` and volume-averaged
        kinetic energy of the *consumed* state, plus the pre-projection
        divergence residual — all cheap reductions over arrays the step
        already materializes (the physical convection velocities and the
        projection RHS), so the state math is untouched and the overhead is
        a handful of elementwise ops per step."""
        dt = self.dt
        scale = self.scale
        nu = self.params["nu"]
        inv_dx, inv_dy = self._inv_dx, self._inv_dy
        w0s, w1s = self._w0, self._w1
        sp_t, sp_u, sp_v = self.temp_space, self.velx_space, self.vely_space
        sp_p, sp_q, sp_f = self.pres_space, self.pseu_space, self.field_space
        mask = self._dealias
        tb_ortho = self.tempbc_ortho
        tb_dx, tb_dy = self._tempbc_dx, self._tempbc_dy
        tb_diff = self._tempbc_diff
        sol_u, sol_v, sol_t, sol_p = (
            self.solver_velx,
            self.solver_vely,
            self.solver_temp,
            self.solver_pres,
        )
        solid = self._solid
        proj_grad = self._proj_grad
        # scenario step modifiers (operator constants — signed into
        # compat_key): rotating-frame Coriolis rate + passive scalar
        coriolis = self._coriolis()
        has_scal = self._scalar_active()
        sol_c = self.solver_scal
        kc_over_ka = (self._scalar_kappa() / self.params["ka"]) if has_scal else 1.0

        # RUSTPDE_SOLVE_PRECISION: experiment knob (default OFF) scoping a
        # matmul-precision override to the four implicit solves ONLY — the
        # remaining 6-pass GEMM family after the fast-synthesis work.  A
        # trace-time jax.default_matmul_precision context covers every GEMM
        # inside the solves (precond matvecs, dense inverses, modal maps)
        # without touching the shared impl classes.  f64 never downgrades.
        # Gates if ever defaulted: div-norm decay, Poisson MMS, shadow,
        # FAST_SYNTH-style long-horizon stats (the r2 NaN came from a GLOBAL
        # "high"; this is the scoped form).
        solve_prec = (
            config.env_get("RUSTPDE_SOLVE_PRECISION") or None
            if not config.X64
            else None
        )

        def solve_scope():
            if solve_prec:
                return jax.default_matmul_precision(solve_prec)
            import contextlib

            return contextlib.nullcontext()

        conv_impl = self._conv_impl
        step_impl = self._step_impl
        manual_synth = getattr(self, "_manual_synth", None)
        manual_poisson = getattr(self, "_manual_poisson", None)

        def conv(ux, uy, space, vhat, with_bc=False):
            """u . grad(v), dealiased, in scratch-ortho space
            (/root/reference/src/navier_stokes/functions.rs:56-69 +
            navier_eq.rs:60-101).

            Deliberately per-field, NOT stacked: batching the two derivative
            syntheses into one (2, n, n) transform was measured 18% SLOWER
            for the whole step at 1025^2 f32 (4.01 vs 3.41 ms) — inside one
            compiled program the extra stack/unstack HBM copies and the
            batched dot_generals cost more than the saved op count."""
            if conv_impl is not None:
                # the whole chain as one fused region: the Pallas VMEM
                # kernel (physical intermediates never touch HBM, dealias
                # row-drop in the epilogue) or the manually-partitioned
                # shard_map region on the split-sep mesh layout — both
                # exact to the chain below at fp reassociation
                fc = conv_impl[id(space)]
                if with_bc:
                    return fc.apply(ux, uy, vhat, tb_dx, tb_dy)
                return fc.apply(ux, uy, vhat)
            # fused synthesis-of-derivative: one GEMM per axis on sep spaces
            # (Space2.backward_gradient == backward_ortho(gradient(.)));
            # fast=True: 3-pass synthesis for the dealiased products
            dvdx = space.backward_gradient(vhat, (1, 0), scale, fast=True)
            dvdy = space.backward_gradient(vhat, (0, 1), scale, fast=True)
            total = ux * dvdx + uy * dvdy
            if with_bc:
                total = total + ux * tb_dx + uy * tb_dy
            if any(sp_f.sep):
                # dealias folded into the forward GEMMs (dead rows dropped
                # on sep axes, vector cut on the rest); fast=True
                # additionally honors RUSTPDE_FWD_PRECISION
                return sp_f.forward_dealiased(total, fast=True)
            return sp_f.forward(total) * mask

        def step(state: NavierState) -> NavierState:
            # pin the implicit-solve inputs to the spectral x-pencil layout
            # (no-op without a mesh, and on non-divisible extents — current
            # JAX rounds those constraints to replicated): asserts the pencil
            # discipline at the solve boundaries so GSPMD propagation cannot
            # drift the solve internals onto other layouts on real
            # (divisible) meshes.  NOTE it does NOT cure the fused split-sep
            # miscompile tracked in test_parallel.py::
            # test_sharded_split_periodic_mixed_sep_matches_serial (xfail).
            from ..parallel.mesh import SPEC, constrain

            def pin(a):
                return constrain(a, SPEC)

            temp, velx, vely, pres, pseu = (
                state.temp, state.velx, state.vely, state.pres, state.pseu
            )
            # buoyancy (full ortho space, includes the lift field)
            that = sp_t.to_ortho(temp) + tb_ortho
            # convection velocity in physical space (old time level; fast
            # 3-pass synthesis — feeds only the dealiased products); the
            # manual split-sep path runs these through their own shard_map
            # region (decomp.ShardedSynthesis)
            if manual_synth is not None:
                ux = manual_synth[id(sp_u)].apply(velx)
                uy = manual_synth[id(sp_v)].apply(vely)
            else:
                ux = sp_u.backward_fast(velx)
                uy = sp_v.backward_fast(vely)

            if with_sentinels:
                # sentinels of the consumed state, from the velocities the
                # convection terms need anyway (no extra transforms)
                cfl = dt * jnp.max(
                    jnp.abs(ux) * inv_dx[:, None] + jnp.abs(uy) * inv_dy[None, :]
                )
                ke = 0.5 * jnp.sum((ux**2 + uy**2) * w0s[:, None] * w1s[None, :])

            if step_impl is not None:
                # fused implicit half (ops/pallas_step.py): each stage ONE
                # Pallas kernel — rhs terms with the Helmholtz inverse
                # folded in for the velocities/temperature, divergence ->
                # fast-diag Poisson (singular pin in the epilogue mask) ->
                # pressure-gradient projection.  The convection chain feeds
                # the stages unchanged (dense or FusedConv per
                # RUSTPDE_CONV_KERNEL); the stage dots pin HIGHEST matmul
                # precision themselves, so no solve_scope here.  Mesh-free
                # by construction (_build_step_kernels), hence no pins.
                cx = conv(ux, uy, sp_u, velx)
                args = (velx, pres, cx) + ((vely,) if coriolis else ())
                velx_n = step_impl["velx"].apply(*args)
                cy = conv(ux, uy, sp_v, vely)
                args = (vely, pres, temp, cy) + ((velx,) if coriolis else ())
                vely_n = step_impl["vely"].apply(*args)
                div = step_impl["div"].apply(velx_n, vely_n)
                pseu_n = sp_q.pin_zero_mode(step_impl["poisson"].apply(div))
                velx_n = velx_n - step_impl["projx"].apply(pseu_n)
                vely_n = vely_n - step_impl["projy"].apply(pseu_n)
                pres_n = pres - nu * div + sp_q.to_ortho(pseu_n) / dt
                ct = conv(ux, uy, sp_t, temp, with_bc=True)
                temp_n = step_impl["temp"].apply(temp, ct)
                if has_scal:
                    cs = conv(ux, uy, sp_t, state.scal, with_bc=True)
                    scal_n = step_impl["scal"].apply(state.scal, cs)
            else:
                # horizontal momentum (navier_eq.rs:176-187)
                rhs = sp_u.to_ortho(velx)
                rhs = rhs - dt * sp_p.gradient(pres, (1, 0), scale)
                rhs = rhs - dt * conv(ux, uy, sp_u, velx)
                if coriolis:
                    # rotating-frame f-plane term +f*v (velx/vely share one
                    # space, so the cross-coupling is a plain ortho-space
                    # add); in exactly incompressible 2-D flow this force is
                    # irrotational and absorbed by the pressure — the
                    # scenario's analytic validation case
                    # (tests/test_workloads.py)
                    rhs = rhs + dt * coriolis * sp_v.to_ortho(vely)
                with solve_scope():
                    velx_n = sol_u.solve(pin(rhs))

                # vertical momentum + buoyancy (navier_eq.rs:190-203)
                rhs = sp_v.to_ortho(vely)
                rhs = rhs - dt * sp_p.gradient(pres, (0, 1), scale)
                rhs = rhs + dt * that
                rhs = rhs - dt * conv(ux, uy, sp_v, vely)
                if coriolis:
                    rhs = rhs - dt * coriolis * sp_u.to_ortho(velx)
                with solve_scope():
                    vely_n = sol_v.solve(pin(rhs))

                # pressure projection
                # (navier_eq.rs:19-25,117-125,137-143,158-162)
                div = sp_u.gradient(velx_n, (1, 0), scale) + sp_v.gradient(
                    vely_n, (0, 1), scale
                )
                with solve_scope():
                    if manual_poisson is not None:
                        # the manually-partitioned fast-diag region — the
                        # one stage whose GSPMD fusion miscompiles on the
                        # split-sep layout (parallel/decomp.ShardedPoisson
                        # bisection)
                        pseu_n = manual_poisson.solve(div)
                    else:
                        pseu_n = sol_p.solve(pin(div))
                pseu_n = sp_q.pin_zero_mode(pseu_n)  # remove singularity
                if proj_grad is not None:
                    gx0, gx1, gy0, gy1 = proj_grad
                    ax = pseu_n.ndim - 2
                    velx_n = velx_n - gx1.apply(gx0.apply(pseu_n, ax), ax + 1) / scale[0]
                    vely_n = vely_n - gy1.apply(gy0.apply(pseu_n, ax), ax + 1) / scale[1]
                else:
                    velx_n = velx_n - sp_u.from_ortho(
                        sp_q.gradient(pseu_n, (1, 0), scale)
                    )
                    vely_n = vely_n - sp_v.from_ortho(
                        sp_q.gradient(pseu_n, (0, 1), scale)
                    )
                pres_n = pres - nu * div + sp_q.to_ortho(pseu_n) / dt

                # temperature (navier_eq.rs:209-224)
                rhs = sp_t.to_ortho(temp)
                rhs = rhs + tb_diff
                rhs = rhs - dt * conv(ux, uy, sp_t, temp, with_bc=True)
                with solve_scope():
                    temp_n = sol_t.solve(pin(rhs))

                if has_scal:
                    # passive scalar (scenario modifier): the temperature's
                    # advection-diffusion at the scalar diffusivity, same BC
                    # lift — with matched diffusivity a scalar released
                    # equal to the temperature stays identically equal
                    # (exact validation case); the buoyancy never reads it
                    # (one-way coupling, hence "passive")
                    rhs = sp_t.to_ortho(state.scal)
                    rhs = rhs + kc_over_ka * tb_diff  # dt*kc*lap(bc lift)
                    rhs = rhs - dt * conv(ux, uy, sp_t, state.scal, with_bc=True)
                    with solve_scope():
                        scal_n = sol_c.solve(pin(rhs))

            if solid is not None:
                # implicit pointwise Brinkman penalization (set_solid):
                # elementwise in physical space, exact for the sub-step
                fac, temp_add = solid["fac"], solid["temp_add"]
                velx_n = sp_u.forward(sp_u.backward(velx_n) * fac)
                vely_n = sp_v.forward(sp_v.backward(vely_n) * fac)
                temp_n = sp_t.forward(sp_t.backward(temp_n) * fac + temp_add)
                if has_scal:
                    # the solid enforces the same target on the scalar
                    scal_n = sp_t.forward(
                        sp_t.backward(scal_n) * fac + temp_add
                    )

            # pin the step outputs too: the next step's transforms assume the
            # x-pencil layout, and XLA's sharding propagation is free to emit
            # replicated outputs otherwise — which silently serializes a
            # multi-chip run
            if has_scal:
                state_n = NavierScalarState(
                    pin(temp_n), pin(velx_n), pin(vely_n), pin(pres_n),
                    pin(pseu_n), pin(scal_n),
                )
            else:
                state_n = NavierState(
                    pin(temp_n), pin(velx_n), pin(vely_n), pin(pres_n),
                    pin(pseu_n),
                )
            if with_sentinels:
                # |div| of the uncorrected velocities — the residual the
                # projection removes this step; its blow-up tracks the flow's
                return state_n, (cfl, ke, norm_l2(div))
            return state_n

        return step

    def _make_div(self):
        sp_u, sp_v = self.velx_space, self.vely_space
        scale = self.scale

        def div(state: NavierState):
            return sp_u.gradient(state.velx, (1, 0), scale) + sp_v.gradient(
                state.vely, (0, 1), scale
            )

        return div

    def _make_observables(self):
        """One fused jitted function returning (Nu, Nuvol, Re, |div|).

        Formulas match /root/reference/src/navier_stokes/functions.rs:146-233.
        """
        sp_t, sp_u, sp_v = self.temp_space, self.velx_space, self.vely_space
        sp_f = self.field_space
        scale = self.scale
        nu, ka = self.params["nu"], self.params["ka"]
        tb = self.tempbc_ortho
        w0, w1 = self._w0, self._w1
        div_fn = self._make_div()
        scalar_active = self._scalar_active()

        def avg_x(v):
            return jnp.sum(v * w0[:, None], axis=0)

        def avg(v):
            return jnp.sum(v * w0[:, None] * w1[None, :])

        def observables(state: NavierState):
            that = sp_t.to_ortho(state.temp) + tb
            # physical dT/dy, computed ONCE via the fused synthesis-of-
            # derivative chain (backward_ortho(gradient(.)) collapsed to one
            # GEMM per axis on sep spaces) and shared by the plate-flux Nu
            # and the volume Nuvol — the unfused form ran the gradient and
            # two separate backward_orthos (VERDICT r4 next #7)
            dtdy_p = sp_f.backward_gradient(that, (0, 1), None)
            # Nu: plate heat flux <-2/sy * dT/dy>_x averaged over both plates
            x_avg = avg_x(dtdy_p) * (-2.0 / scale[1])
            nu_plate = 0.5 * (x_avg[0] + x_avg[-1])
            # Nuvol: <2 sy (uy T / ka - dT/dy / sy)>_V
            temp_p = sp_f.backward_ortho(that)
            uy = sp_v.backward(state.vely)
            nu_vol = avg(
                (dtdy_p / (-scale[1]) + uy * temp_p / ka) * 2.0 * scale[1]
            )
            # Re: <sqrt(ux^2+uy^2) * 2 sy / nu>_V
            ux = sp_u.backward(state.velx)
            re = avg(jnp.sqrt(ux**2 + uy**2) * 2.0 * scale[1] / nu)
            # divergence norm
            dnorm = norm_l2(div_fn(state))
            if scalar_active:
                # fold the scalar's finiteness into the NaN-detector
                # observable (a scal-only NaN is invisible to the flow —
                # exit()/state_healthy/serve isolation all watch dnorm)
                dnorm = dnorm + 0.0 * jnp.sum(jnp.abs(state.scal))
                # Sherwood number: the scalar-transfer analog of the
                # plate-flux Nu — the scalar shares the temperature's
                # composite space AND BC lift, so at matched diffusivity a
                # scalar released equal to T yields sherwood == nu exactly
                # (the scenario's validation identity).  Appended AFTER the
                # conventional four so |div| stays the index-3 NaN detector.
                shat = sp_t.to_ortho(state.scal) + tb
                dsdy_p = sp_f.backward_gradient(shat, (0, 1), None)
                s_avg = avg_x(dsdy_p) * (-2.0 / scale[1])
                sherwood = 0.5 * (s_avg[0] + s_avg[-1])
                return nu_plate, nu_vol, re, dnorm, sherwood
            return nu_plate, nu_vol, re, dnorm

        return observables

    # -- Integrate protocol / campaign machinery ------------------------------
    # update/update_n/update_n_pending, sentinels, set_stability, the dt rung
    # cache, observable futures and exit/exit_future live in
    # models/campaign.CampaignModelBase — this class only lists what a dt
    # change invalidates and how to rebuild it.

    # attributes a dt change swaps out, cached per rung so a governor
    # cycling a bounded dt ladder refactorizes/re-jits each rung ONCE
    # (solver_pres is dt-independent; tempbc_ortho/_tempbc_dx/_tempbc_dy are
    # cached alongside because _build_bc_fields rebuilds them together)
    _DT_ARTIFACTS = (
        "solver_velx",
        "solver_vely",
        "solver_temp",
        "solver_scal",
        "tempbc_ortho",
        "_tempbc_dx",
        "_tempbc_dy",
        "_tempbc_diff",
        "_step_impl",
        "_solid",
    ) + CampaignModelBase._DT_ARTIFACTS

    def _rebuild_dt_artifacts(self) -> None:
        """First visit to a dt rung: dt is baked deep into the pipeline —
        the implicit Helmholtz solvers factorize ``dt*nu`` / ``dt*ka``, the
        BC diffusion source scales with dt, and a solid mask's penalization
        factors use dt/eta — so rebuild solvers + lift-field derivatives and
        re-trace the jitted entry points (see CampaignModelBase.set_dt for
        the rung-cache contract)."""
        dt = self.dt
        nu, ka = self.params["nu"], self.params["ka"]
        sx2, sy2 = self.scale[0] ** 2, self.scale[1] ** 2
        self.solver_velx = HholtzAdi(self.velx_space, (dt * nu / sx2, dt * nu / sy2))
        self.solver_vely = self.solver_velx
        self.solver_temp = HholtzAdi(self.temp_space, (dt * ka / sx2, dt * ka / sy2))
        self.solver_scal = self._build_scalar_solver()
        # solver_pres is dt-independent (pure Poisson)
        xs, ys = (b.points for b in self.field_space.bases)
        with self._scope():
            self._build_bc_fields(xs, ys)
        # the fused stage kernels bake dt into every term matrix (and the
        # BC-lift constants above into the Helmholtz stages)
        self._step_impl = self._build_step_kernels()
        if self._solid is not None:
            # rebuilds the dt/eta factors AND recompiles the entry points;
            # the obstacle itself is unchanged, so the per-rung cache stays
            # valid (set_solid clears it — shield it across the call)
            cache, self._dt_cache = self._dt_cache, {}
            try:
                self.set_solid(
                    self._solid["mask"], self._solid["value"], self._solid["eta"]
                )
            finally:
                self._dt_cache = cache
        else:
            self._compile_entry_points()

    def eval_nu(self) -> float:
        return self.get_observables()[0]

    def eval_nuvol(self) -> float:
        return self.get_observables()[1]

    def eval_re(self) -> float:
        return self.get_observables()[2]

    def write(self, filename: str) -> None:
        """Write a flow snapshot in the reference HDF5 layout."""
        from ..utils import checkpoint

        checkpoint.write_snapshot(self, filename)

    def read(self, filename: str) -> None:
        """Restore from a snapshot (supports resolution change via spectral
        interpolation; sharded-checkpoint manifests restore topology-
        elastically, see utils/checkpoint.read_sharded_snapshot)."""
        from ..utils import checkpoint

        checkpoint.read_snapshot(self, filename)

    def read_unwrap(self, filename: str) -> None:
        from ..utils.checkpoint import CheckpointError

        try:
            self.read(filename)
        except (OSError, KeyError, CheckpointError) as exc:
            print(f"error while reading file {filename}: {exc}")

    def callback(self) -> None:
        from ..utils import navier_io

        navier_io.callback(self)
