"""Navier2D — 2-D Boussinesq Rayleigh–Bénard DNS, TPU-native.

Rebuild of the reference's physics layer
(/root/reference/src/navier_stokes/{navier,navier_eq}.rs) as a *functional*
JAX model: the simulation state is an immutable pytree of spectral
coefficients, one time step is a pure jitted function, and many steps run per
host round-trip through ``lax.scan``.  One model class covers both the
fully-confined (Chebyshev x Chebyshev) and horizontally-periodic
(Fourier x Chebyshev) configurations — the reference's serial/MPI module
duplication is intentionally not reproduced; sharding is layered on top in
``parallel/`` without touching the physics.

Numerical scheme (identical to the reference, navier_eq.rs):

* implicit Euler diffusion via ADI Helmholtz solves,
* explicit convection with 2/3-rule dealiasing,
* pressure projection: Poisson solve for a pseudo-pressure, velocity
  correction, pressure update ``pres += -nu*div + pseu/dt``,
* inhomogeneous BCs through constant lift fields (boundary_conditions.py).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import config
from ..bases import (
    Space2,
    cheb_dirichlet,
    cheb_dirichlet_neumann,
    cheb_neumann,
    chebyshev,
    fourier_r2c,
)
from ..field import average_weights, norm_l2
from ..solver import HholtzAdi, Poisson
from ..utils.integrate import Integrate
from . import boundary_conditions as bcs
from . import functions as fns


class NavierState(NamedTuple):
    """Spectral-coefficient pytree threaded through the jitted step."""

    temp: jax.Array
    velx: jax.Array
    vely: jax.Array
    pres: jax.Array
    pseu: jax.Array


class Navier2D(Integrate):
    """2-D Rayleigh–Bénard convection solver.

    Construct via :meth:`new_confined` (Chebyshev x Chebyshev) or
    :meth:`new_periodic` (Fourier x Chebyshev); parameter vocabulary matches
    the reference (nx, ny, ra, pr, dt, aspect, bc in {"rbc", "hc"}).
    """

    def __init__(
        self,
        nx: int,
        ny: int,
        ra: float,
        pr: float,
        dt: float,
        aspect: float,
        bc: str,
        periodic: bool,
        mesh=None,
    ):
        if bc not in ("rbc", "hc"):
            raise ValueError(f"boundary condition type {bc!r} not recognized")
        # pencil-sharding mesh (None = single device); one model serves both —
        # the reference's navier_stokes vs navier_stokes_mpi duplication is
        # deliberately not reproduced (SURVEY.md S1 note)
        self.mesh = mesh
        self.nx, self.ny = nx, ny
        self.dt = dt
        self.time = 0.0
        self.periodic = periodic
        self.bc = bc
        self.scale = (float(aspect), 1.0)
        nu = fns.get_nu(ra, pr, self.scale[1] * 2.0)
        ka = fns.get_ka(ra, pr, self.scale[1] * 2.0)
        self.params = {"ra": ra, "pr": pr, "nu": nu, "ka": ka}
        self.write_intervall: float | None = None
        self.statistics = None
        self._obs_cache: tuple | None = None
        self._solid = None  # (penalization factors) set via set_solid()
        # stability sentinels (utils/governor.py): None = plain stepping;
        # set_stability compiles the sentinel step variant into update_n
        self._stability = None
        self.last_chunk_status = None
        self._pre_div_latch = False
        # per-rung cache of dt-baked artifacts (solvers + compiled entry
        # points), so a governor cycling a bounded dt ladder re-jits each
        # rung at most once; recompile_count tracks actual rebuilds
        self._dt_cache: dict[float, dict] = {}
        self.recompile_count = 0
        # diagnostics history appended by the IO callback — the map the
        # reference allocates but never writes (navier.rs:81)
        self.diagnostics: dict[str, list[float]] = {}

        x_base = fourier_r2c if periodic else cheb_dirichlet
        x_full = fourier_r2c if periodic else chebyshev
        x_neumann = fourier_r2c if periodic else cheb_neumann

        # spaces per variable (/root/reference/src/navier_stokes/navier.rs:235-256,356-376);
        # velx/vely share one space object (identical bases -> shared operator
        # constants on device)
        self.velx_space = Space2(x_base(nx), cheb_dirichlet(ny))
        self.vely_space = self.velx_space
        temp_ybase = cheb_dirichlet(ny) if bc == "rbc" else cheb_dirichlet_neumann(ny)
        self.temp_space = Space2(x_neumann(nx), temp_ybase)
        self.pres_space = Space2(x_full(nx), chebyshev(ny))
        self.pseu_space = Space2(x_neumann(nx), cheb_neumann(ny))
        # scratch space for convection/observables (full ortho bases)
        self.field_space = Space2(x_full(nx), chebyshev(ny))

        # grid (unscaled master coords; physical coords = coords * scale)
        self.x = [b.points * s for b, s in zip(self.field_space.bases, self.scale)]
        xs, ys = (b.points for b in self.field_space.bases)
        # average weights dx/L as in the reference's average_axis
        # (/root/reference/src/field/average.rs:26-35), with this repo's
        # full-period normalization for periodic axes (field.average_weights)
        w0 = average_weights(xs, self.field_space.base_x.is_periodic)
        w1 = average_weights(ys, False)
        rdt = config.real_dtype()
        self._w0 = jnp.asarray(w0, dtype=rdt)
        self._w1 = jnp.asarray(w1, dtype=rdt)
        # per-point inverse grid spacing (physical, scaled) for the pointwise
        # advective CFL sentinel dt*max(|ux|/dx + |uy|/dy): cell widths from
        # the same midpoint rule the averages use — near a Chebyshev wall the
        # spacing is O(1/N^2) but the no-slip velocity vanishes linearly, so
        # the pointwise ratio self-limits to the local shear rate
        from ..field import grid_deltas

        dx0 = grid_deltas(xs, self.field_space.base_x.is_periodic) * self.scale[0]
        dy0 = grid_deltas(ys, False) * self.scale[1]
        self._inv_dx = jnp.asarray(1.0 / dx0, dtype=rdt)
        self._inv_dy = jnp.asarray(1.0 / dy0, dtype=rdt)

        # implicit solvers (/root/reference/src/navier_stokes/navier.rs:263-275)
        sx2, sy2 = self.scale[0] ** 2, self.scale[1] ** 2
        self.solver_velx = HholtzAdi(self.velx_space, (dt * nu / sx2, dt * nu / sy2))
        self.solver_vely = self.solver_velx  # identical operator, shared factors
        self.solver_temp = HholtzAdi(self.temp_space, (dt * ka / sx2, dt * ka / sy2))
        self.solver_pres = Poisson(self.pseu_space, (1.0 / sx2, 1.0 / sy2))

        # dealiasing mask over the scratch spectral shape (split-aware)
        self._dealias = jnp.asarray(self.field_space.dealias_mask(), dtype=rdt)

        # fused projection-gradient operators for the velocity correction
        # (confined only; the periodic x-axis gradient is diagonal logic):
        # velx -= P_u (D S_q) pseu / sx  per axis — one cross-space matrix
        # per axis instead of gradient + to_ortho + 2 projection applies
        from ..bases import fused_projection_gradient

        gx = fused_projection_gradient(self.velx_space, self.pseu_space, (1, 0))
        gy = fused_projection_gradient(self.vely_space, self.pseu_space, (0, 1))
        self._proj_grad = (*gx, *gy) if gx and gy else None

        # boundary-condition lift fields as device constants
        with self._scope():
            self._build_bc_fields(xs, ys)

        # jitted step + observables
        # jit with closure-converted constants: the dense transform / solver
        # matrices are hoisted out of the traced program and passed as
        # device-resident runtime arguments instead of being embedded in the
        # HLO — at 2049^2 the embedded-constant program exceeds what the TPU
        # compile service accepts (hundreds of MB), while the hoisted program
        # is a few hundred KB for any grid size.
        self._compile_entry_points()

        with self._scope():
            self.state = NavierState(
                temp=self._place(self.temp_space.ndarray_spectral()),
                velx=self._place(self.velx_space.ndarray_spectral()),
                vely=self._place(self.vely_space.ndarray_spectral()),
                pres=self._place(self.pres_space.ndarray_spectral()),
                pseu=self._place(self.pseu_space.ndarray_spectral()),
            )

    # one-time-warning latch for the GSPMD split-sep fallback (class-level:
    # one warning per process, not per model)
    _warned_split_sep_fallback = False

    # overlapped-IO hooks (utils/io_pipeline.py): an attached IOPipeline
    # routes callback IO (flow snapshots, diagnostics lines) through the
    # background writer / lag queue, and io_overlap opts the chunked driver
    # into lagged break checks (utils/integrate.py).  Class-level defaults
    # keep plain models fully synchronous.
    io_pipeline = None
    io_overlap = False

    def _gspmd_split_sep_fallback(self) -> bool:
        """True when the FUSED jitted step would be miscompiled: GSPMD
        miscompiles the fused split-sep periodic step under an active mesh
        (container jax 0.4.37 regression — every stage matches serial to
        ~1e-17 jitted separately and the eager per-op sharded step is exact,
        but the fused program yields wrong vely/pres from step 1; xfailed
        with bisection evidence in tests/test_parallel.py).  Until upstream
        is fixed, such models run the per-stage eager path: slow but right.
        ``RUSTPDE_FORCE_FUSED_GSPMD=1`` forces the fused path anyway (for
        upstream triage / once a fixed jax lands)."""
        import os

        if os.environ.get("RUSTPDE_FORCE_FUSED_GSPMD") == "1":
            return False
        if self.mesh is None or not self.periodic:
            return False
        sp = self.temp_space
        return sp.bases[0].kind.is_split and any(sp.sep)

    def _compile_entry_points(self) -> None:
        example = NavierState(
            temp=jax.ShapeDtypeStruct(
                self.temp_space.shape_spectral, self.temp_space.spectral_dtype()
            ),
            velx=jax.ShapeDtypeStruct(
                self.velx_space.shape_spectral, self.velx_space.spectral_dtype()
            ),
            vely=jax.ShapeDtypeStruct(
                self.vely_space.shape_spectral, self.vely_space.spectral_dtype()
            ),
            pres=jax.ShapeDtypeStruct(
                self.pres_space.shape_spectral, self.pres_space.spectral_dtype()
            ),
            pseu=jax.ShapeDtypeStruct(
                self.pseu_space.shape_spectral, self.pseu_space.spectral_dtype()
            ),
        )
        from ..utils.jit import hoist_constants

        self.recompile_count += 1
        self._sent_cc = None
        self._sent_consts = None
        self._step_n_sent = None
        with self._scope():
            step_cc, step_consts = hoist_constants(self._make_step(), example)
            obs_cc, obs_consts = hoist_constants(self._make_observables(), example)
        self._step_consts = step_consts
        self._obs_consts = obs_consts
        # retained for the ensemble engine (models/ensemble.py): the SAME
        # traced jaxpr is vmapped over a leading member axis there — one
        # physics code path, batch as a leading axis, no forked step
        self._step_cc = step_cc
        self._obs_cc = obs_cc

        if self._gspmd_split_sep_fallback():
            if not Navier2D._warned_split_sep_fallback:
                import warnings

                warnings.warn(
                    "the fused split-sep periodic step is miscompiled by "
                    "GSPMD under an active mesh (xfailed in "
                    "tests/test_parallel.py); falling back to per-stage "
                    "eager execution — multichip periodic runs are slower "
                    "but correct.  Set RUSTPDE_FORCE_FUSED_GSPMD=1 to force "
                    "the fused path.",
                    RuntimeWarning,
                    stacklevel=2,
                )
                Navier2D._warned_split_sep_fallback = True
            step_fn = self._make_step()
            obs_fn = self._make_observables()
            self._step = step_fn

            def step_n_eager(state, n):
                # same semantics as the scanned fast path: the state that
                # first went non-finite is kept, later steps are identity
                done = 0
                for _ in range(int(n)):
                    state = step_fn(state)
                    done += 1
                    if not bool(jnp.isfinite(jnp.sum(state.temp))):
                        break
                return state, jnp.asarray(done, jnp.int32)

            self._step_n = step_n_eager
            self._obs_fn = obs_fn
            return

        step_jit = jax.jit(step_cc)
        self._step = lambda s: step_jit(self._step_consts, s)

        def step_n(consts, state, n: int):
            """n scanned steps with in-chunk divergence early-exit: an
            is-finite flag rides the carry, and once the flow is NaN the
            remaining iterations take the identity branch of a ``lax.cond``
            — the device stops paying for GEMMs mid-chunk instead of burning
            the rest of a minutes-long chunk on NaNs (the reference checks
            ``pde.exit()`` every step, /root/reference/src/lib.rs:187-219).
            Returns ``(state, steps_done)``; a NaN temp field infects velx
            within one step (buoyancy) and vice versa (convection), so one
            reduction over temp per step is a complete detector."""

            def advance(carry):
                st, _, done = carry
                st2 = step_cc(consts, st)
                ok2 = jnp.isfinite(jnp.sum(st2.temp))
                return st2, ok2, done + 1

            def body(carry, _):
                carry2 = jax.lax.cond(carry[1], advance, lambda c: c, carry)
                return carry2, None

            init = (state, jnp.asarray(True), jnp.asarray(0, jnp.int32))
            (final, _, done), _ = jax.lax.scan(body, init, None, length=n)
            return final, done

        # donate the state: XLA aliases the five input coefficient buffers to
        # the scan carry's outputs, so a chunked dispatch updates the state
        # in place instead of holding a second resident copy in HBM.  Callers
        # must hand in buffers they no longer need — update_n dispatches a
        # fresh copy first, keeping references retained to ``self.state``
        # across the call valid (no use-after-donate on the public API).
        step_n_jit = jax.jit(
            step_n, static_argnames=("n",), donate_argnums=(1,)
        )
        self._step_n = lambda s, n: step_n_jit(self._step_consts, s, n=n)
        obs_jit = jax.jit(obs_cc)
        self._obs_fn = lambda s: obs_jit(self._obs_consts, s)

        if self._stability is not None:
            self._compile_sentinel_entry_points(example)

    def _compile_sentinel_entry_points(self, example) -> None:
        """Sentinel variant of the scanned chunk (set_stability): the carry
        additionally holds a CFL-ok flag and running sentinel reductions, and
        the early-exit fires on EITHER a non-finite state (the NaN path, as
        before) or a per-step CFL above ``max_cfl`` — the *pre-divergence*
        catch, taken while the state is still finite so the chunk can be
        recovered by an in-memory rollback instead of a checkpoint restore.
        One small scalar fetch per chunk; the buckets themselves stay
        asynchronous and donate their carry like the plain path."""
        from ..utils.jit import hoist_constants

        with self._scope():
            sent_cc, sent_consts = hoist_constants(
                self._make_step(with_sentinels=True), example
            )
        self._sent_cc = sent_cc
        self._sent_consts = sent_consts
        ceiling = float(self._stability.max_cfl)

        def step_n_sent(consts, carry, n: int):
            def advance(carry):
                st, fin, cok, done, cflm, gm, dvm, kep = carry
                st2, (cfl, ke, dv) = sent_cc(consts, st)
                fin2 = jnp.isfinite(jnp.sum(st2.temp))
                # NaN cfl must read as the NaN path, not a ceiling trip:
                # NaN > ceiling is False, so ~(cfl > ceiling) stays True
                cok2 = jnp.logical_not(cfl > ceiling)
                growth = jnp.where(kep > 0.0, ke / kep, 1.0)
                return (
                    st2,
                    fin2,
                    cok2,
                    done + 1,
                    jnp.maximum(cflm, cfl),
                    jnp.maximum(gm, growth),
                    jnp.maximum(dvm, dv),
                    ke,
                )

            def body(carry, _):
                carry2 = jax.lax.cond(
                    carry[1] & carry[2], advance, lambda c: c, carry
                )
                return carry2, None

            final, _ = jax.lax.scan(body, carry, None, length=n)
            return final

        sent_jit = jax.jit(
            step_n_sent, static_argnames=("n",), donate_argnums=(1,)
        )
        self._step_n_sent = lambda c, n: sent_jit(self._sent_consts, c, n=n)

    # -- sharding helpers ----------------------------------------------------

    def _scope(self):
        """Activate this model's mesh for the duration of a trace/dispatch."""
        from ..parallel.mesh import use_mesh

        if self.mesh is None:
            import contextlib

            return contextlib.nullcontext()
        return use_mesh(self.mesh)

    def _place(self, arr):
        """Put a spectral array into x-pencil layout under the mesh."""
        from ..parallel.mesh import SPEC, device_put

        return device_put(arr, SPEC)

    @property
    def compat_key(self) -> tuple:
        """Everything baked into the model's operator constants — grid,
        physics parameters, dt (the implicit solvers factorize ``dt*nu``),
        geometry and BC family.  Two requests with equal keys can share one
        compiled step jaxpr (and therefore one ensemble batch: the serve
        scheduler buckets by this key); anything differing forces a fresh
        model build + compile."""
        return (
            int(self.nx),
            int(self.ny),
            float(self.params["ra"]),
            float(self.params["pr"]),
            float(self.dt),
            float(self.scale[0]),
            str(self.bc),
            bool(self.periodic),
        )

    # -- construction --------------------------------------------------------

    @classmethod
    def new_confined(cls, nx, ny, ra, pr, dt, aspect, bc, mesh=None) -> "Navier2D":
        """Chebyshev x Chebyshev (fully confined cell), with random IC as in
        the reference (/root/reference/src/navier_stokes/navier.rs:215-308)."""
        model = cls(nx, ny, ra, pr, dt, aspect, bc, periodic=False, mesh=mesh)
        model.init_random(0.1)
        return model

    @classmethod
    def new_periodic(cls, nx, ny, ra, pr, dt, aspect, bc, mesh=None) -> "Navier2D":
        """Fourier x Chebyshev (horizontally periodic)
        (/root/reference/src/navier_stokes/navier.rs:336-428)."""
        model = cls(nx, ny, ra, pr, dt, aspect, bc, periodic=True, mesh=mesh)
        model.init_random(0.1)
        return model

    @classmethod
    def from_config(cls, cfg, mesh=None) -> "Navier2D":
        """Construct from a :class:`~rustpde_mpi_tpu.config.NavierConfig`."""
        model = cls(*cfg.ctor_args(), periodic=cfg.periodic, mesh=mesh)
        if cfg.init_random_amp:
            model.init_random(cfg.init_random_amp)
        model.write_intervall = cfg.write_intervall
        model.params.update(cfg.params)
        if getattr(cfg, "stability", None) is not None:
            model.set_stability(cfg.stability)
        return model

    def _build_bc_fields(self, xs: np.ndarray, ys: np.ndarray) -> None:
        """Transform the BC lift profiles into ortho-space constants and
        precompute every derivative the step needs (the reference recomputes
        these each step from the stored lift field)."""
        sp = self.field_space
        scale = self.scale
        dt, ka = self.dt, self.params["ka"]
        if self.bc == "rbc":
            tempbc_v = bcs.bc_rbc_values(xs, ys)
        else:
            tempbc_v = bcs.bc_hc_values(xs, ys)
        rdt = config.real_dtype()
        that = sp.forward(jnp.asarray(tempbc_v, dtype=rdt))
        self.tempbc_ortho = that
        # physical gradients for the convection bc-contribution
        self._tempbc_dx = sp.backward_ortho(sp.gradient(that, (1, 0), scale))
        self._tempbc_dy = sp.backward_ortho(sp.gradient(that, (0, 1), scale))
        # diffusion source dt*ka*(d2/dx2 + d2/dy2) bc  (navier_eq.rs:214-218)
        self._tempbc_diff = dt * ka * (
            sp.gradient(that, (2, 0), scale) + sp.gradient(that, (0, 2), scale)
        )
        # NOTE: the reference also builds a presbc lift field but never
        # consumes it in the time loop or the snapshot writer
        # (/root/reference/src/navier_stokes/navier_io.rs:44-62); the profile
        # itself remains available as bcs.pres_bc_rbc_values.

    # -- solid obstacles (volume penalization) -------------------------------

    def set_solid(self, mask, value=None, eta: float | None = None) -> None:
        """Add a solid obstacle via Brinkman volume penalization.

        ``mask`` (nx, ny): 1 inside the solid, 0 in the fluid, smooth layer in
        between (models/solid_masks.py builders); ``value``: temperature the
        solid enforces (default 0); ``eta``: penalty time scale (default
        dt/10).  The reference stores the mask but never applies it
        (/root/reference/src/navier_stokes/navier.rs:86); here the step gains
        an *implicit pointwise* relaxation, solved exactly per sub-step:

            u    <- u / (1 + dt/eta * mask)
            temp <- (temp + dt/eta * mask * value) / (1 + dt/eta * mask)

        which is unconditionally stable for any eta.  Pass ``mask=None`` to
        remove the obstacle."""
        rdt = config.real_dtype()
        # cached per-dt artifacts embed the penalization factors of the OLD
        # obstacle — changing the obstacle invalidates every rung
        self._dt_cache.clear()
        if mask is None:
            self._solid = None
            self._compile_entry_points()
            return
        mask = np.asarray(mask, dtype=np.float64)
        if value is None:
            value = np.zeros_like(mask)
        if eta is None:
            eta = self.dt / 10.0
        a = (self.dt / eta) * mask
        fac = 1.0 / (1.0 + a)
        # temp state excludes the BC lift field: target = value - tempbc
        sp = self.field_space
        with self._scope():
            tempbc_phys = np.asarray(sp.backward_ortho(self.tempbc_ortho))
        temp_add = a * (value - tempbc_phys) * fac
        self._solid = {
            "mask": mask,
            "value": value,
            "eta": float(eta),  # retained so set_dt can rebuild the factors
            "fac": jnp.asarray(fac, dtype=rdt),
            "temp_add": jnp.asarray(temp_add, dtype=rdt),
        }
        self._compile_entry_points()

    @property
    def solid(self):
        """Reference-parity accessor: ``model.solid = (mask, value)``
        (navier.rs:86 ``navier.solid = Some(mask)``)."""
        if self._solid is None:
            return None
        return (self._solid["mask"], self._solid["value"])

    @solid.setter
    def solid(self, mask_value) -> None:
        if mask_value is None:
            self.set_solid(None)
        else:
            self.set_solid(mask_value[0], mask_value[1])

    # -- initial conditions --------------------------------------------------

    def init_random(self, amp: float, seed: int = 0) -> None:
        """Random uniform disturbance on temp/velx/vely
        (/root/reference/src/navier_stokes/navier.rs:173-182)."""
        rng = np.random.default_rng(seed)
        for name in ("temp", "velx", "vely"):
            space: Space2 = getattr(self, f"{name}_space")
            v = fns.random_values(space.shape_physical, amp, rng)
            self.set_field(name, v)

    def set_velocity(self, amp: float, m: float, n: float) -> None:
        """velx = amp sin(pi m x~) cos(pi n y~), vely = -amp cos sin
        (/root/reference/src/navier_stokes/navier.rs:161-164)."""
        xs, ys = (b.points for b in self.field_space.bases)
        self.set_field("velx", fns.sin_cos_values(xs, ys, amp, m, n))
        self.set_field("vely", fns.cos_sin_values(xs, ys, -amp, m, n))

    def set_temperature(self, amp: float, m: float, n: float) -> None:
        xs, ys = (b.points for b in self.field_space.bases)
        self.set_field("temp", fns.cos_sin_values(xs, ys, -amp, m, n))

    def set_field(self, name: str, values: np.ndarray) -> None:
        """Set one variable from physical values (host -> device forward)."""
        space: Space2 = getattr(self, f"{name}_space")
        with self._scope():
            vhat = space.forward(jnp.asarray(values, dtype=config.real_dtype()))
            self.state = self.state._replace(**{name: self._place(vhat)})

    def get_field(self, name: str) -> np.ndarray:
        """Physical values of one variable (device backward -> host)."""
        space: Space2 = getattr(self, f"{name}_space")
        with self._scope():
            return np.asarray(space.backward(getattr(self.state, name)))

    # -- the time step -------------------------------------------------------

    def _make_step(self, with_sentinels: bool = False):
        """The jitted step.  ``with_sentinels=True`` returns
        ``(state, (cfl, ke, div_norm))`` instead of just the state: pointwise
        advective CFL ``dt*max(|ux|/dx + |uy|/dy)`` and volume-averaged
        kinetic energy of the *consumed* state, plus the pre-projection
        divergence residual — all cheap reductions over arrays the step
        already materializes (the physical convection velocities and the
        projection RHS), so the state math is untouched and the overhead is
        a handful of elementwise ops per step."""
        dt = self.dt
        scale = self.scale
        nu = self.params["nu"]
        inv_dx, inv_dy = self._inv_dx, self._inv_dy
        w0s, w1s = self._w0, self._w1
        sp_t, sp_u, sp_v = self.temp_space, self.velx_space, self.vely_space
        sp_p, sp_q, sp_f = self.pres_space, self.pseu_space, self.field_space
        mask = self._dealias
        tb_ortho = self.tempbc_ortho
        tb_dx, tb_dy = self._tempbc_dx, self._tempbc_dy
        tb_diff = self._tempbc_diff
        sol_u, sol_v, sol_t, sol_p = (
            self.solver_velx,
            self.solver_vely,
            self.solver_temp,
            self.solver_pres,
        )
        solid = self._solid
        proj_grad = self._proj_grad

        # RUSTPDE_SOLVE_PRECISION: experiment knob (default OFF) scoping a
        # matmul-precision override to the four implicit solves ONLY — the
        # remaining 6-pass GEMM family after the fast-synthesis work.  A
        # trace-time jax.default_matmul_precision context covers every GEMM
        # inside the solves (precond matvecs, dense inverses, modal maps)
        # without touching the shared impl classes.  f64 never downgrades.
        # Gates if ever defaulted: div-norm decay, Poisson MMS, shadow,
        # FAST_SYNTH-style long-horizon stats (the r2 NaN came from a GLOBAL
        # "high"; this is the scoped form).
        import os

        solve_prec = (
            os.environ.get("RUSTPDE_SOLVE_PRECISION") or None
            if not config.X64
            else None
        )

        def solve_scope():
            if solve_prec:
                return jax.default_matmul_precision(solve_prec)
            import contextlib

            return contextlib.nullcontext()

        def conv(ux, uy, space, vhat, with_bc=False):
            """u . grad(v), dealiased, in scratch-ortho space
            (/root/reference/src/navier_stokes/functions.rs:56-69 +
            navier_eq.rs:60-101).

            Deliberately per-field, NOT stacked: batching the two derivative
            syntheses into one (2, n, n) transform was measured 18% SLOWER
            for the whole step at 1025^2 f32 (4.01 vs 3.41 ms) — inside one
            compiled program the extra stack/unstack HBM copies and the
            batched dot_generals cost more than the saved op count."""
            # fused synthesis-of-derivative: one GEMM per axis on sep spaces
            # (Space2.backward_gradient == backward_ortho(gradient(.)));
            # fast=True: 3-pass synthesis for the dealiased products
            dvdx = space.backward_gradient(vhat, (1, 0), scale, fast=True)
            dvdy = space.backward_gradient(vhat, (0, 1), scale, fast=True)
            total = ux * dvdx + uy * dvdy
            if with_bc:
                total = total + ux * tb_dx + uy * tb_dy
            if any(sp_f.sep):
                # dealias folded into the forward GEMMs (dead rows dropped
                # on sep axes, vector cut on the rest); fast=True
                # additionally honors RUSTPDE_FWD_PRECISION
                return sp_f.forward_dealiased(total, fast=True)
            return sp_f.forward(total) * mask

        def step(state: NavierState) -> NavierState:
            # pin the implicit-solve inputs to the spectral x-pencil layout
            # (no-op without a mesh, and on non-divisible extents — current
            # JAX rounds those constraints to replicated): asserts the pencil
            # discipline at the solve boundaries so GSPMD propagation cannot
            # drift the solve internals onto other layouts on real
            # (divisible) meshes.  NOTE it does NOT cure the fused split-sep
            # miscompile tracked in test_parallel.py::
            # test_sharded_split_periodic_mixed_sep_matches_serial (xfail).
            from ..parallel.mesh import SPEC, constrain

            def pin(a):
                return constrain(a, SPEC)

            temp, velx, vely, pres, pseu = state
            # buoyancy (full ortho space, includes the lift field)
            that = sp_t.to_ortho(temp) + tb_ortho
            # convection velocity in physical space (old time level; fast
            # 3-pass synthesis — feeds only the dealiased products)
            ux = sp_u.backward_fast(velx)
            uy = sp_v.backward_fast(vely)

            if with_sentinels:
                # sentinels of the consumed state, from the velocities the
                # convection terms need anyway (no extra transforms)
                cfl = dt * jnp.max(
                    jnp.abs(ux) * inv_dx[:, None] + jnp.abs(uy) * inv_dy[None, :]
                )
                ke = 0.5 * jnp.sum((ux**2 + uy**2) * w0s[:, None] * w1s[None, :])

            # horizontal momentum (navier_eq.rs:176-187)
            rhs = sp_u.to_ortho(velx)
            rhs = rhs - dt * sp_p.gradient(pres, (1, 0), scale)
            rhs = rhs - dt * conv(ux, uy, sp_u, velx)
            with solve_scope():
                velx_n = sol_u.solve(pin(rhs))

            # vertical momentum + buoyancy (navier_eq.rs:190-203)
            rhs = sp_v.to_ortho(vely)
            rhs = rhs - dt * sp_p.gradient(pres, (0, 1), scale)
            rhs = rhs + dt * that
            rhs = rhs - dt * conv(ux, uy, sp_v, vely)
            with solve_scope():
                vely_n = sol_v.solve(pin(rhs))

            # pressure projection (navier_eq.rs:19-25,117-125,137-143,158-162)
            div = sp_u.gradient(velx_n, (1, 0), scale) + sp_v.gradient(
                vely_n, (0, 1), scale
            )
            with solve_scope():
                pseu_n = sol_p.solve(pin(div))
            pseu_n = sp_q.pin_zero_mode(pseu_n)  # remove singularity
            if proj_grad is not None:
                gx0, gx1, gy0, gy1 = proj_grad
                ax = pseu_n.ndim - 2
                velx_n = velx_n - gx1.apply(gx0.apply(pseu_n, ax), ax + 1) / scale[0]
                vely_n = vely_n - gy1.apply(gy0.apply(pseu_n, ax), ax + 1) / scale[1]
            else:
                velx_n = velx_n - sp_u.from_ortho(sp_q.gradient(pseu_n, (1, 0), scale))
                vely_n = vely_n - sp_v.from_ortho(sp_q.gradient(pseu_n, (0, 1), scale))
            pres_n = pres - nu * div + sp_q.to_ortho(pseu_n) / dt

            # temperature (navier_eq.rs:209-224)
            rhs = sp_t.to_ortho(temp)
            rhs = rhs + tb_diff
            rhs = rhs - dt * conv(ux, uy, sp_t, temp, with_bc=True)
            with solve_scope():
                temp_n = sol_t.solve(pin(rhs))

            if solid is not None:
                # implicit pointwise Brinkman penalization (set_solid):
                # elementwise in physical space, exact for the sub-step
                fac, temp_add = solid["fac"], solid["temp_add"]
                velx_n = sp_u.forward(sp_u.backward(velx_n) * fac)
                vely_n = sp_v.forward(sp_v.backward(vely_n) * fac)
                temp_n = sp_t.forward(sp_t.backward(temp_n) * fac + temp_add)

            # pin the step outputs too: the next step's transforms assume the
            # x-pencil layout, and XLA's sharding propagation is free to emit
            # replicated outputs otherwise — which silently serializes a
            # multi-chip run
            state_n = NavierState(
                pin(temp_n), pin(velx_n), pin(vely_n), pin(pres_n), pin(pseu_n)
            )
            if with_sentinels:
                # |div| of the uncorrected velocities — the residual the
                # projection removes this step; its blow-up tracks the flow's
                return state_n, (cfl, ke, norm_l2(div))
            return state_n

        return step

    def _make_div(self):
        sp_u, sp_v = self.velx_space, self.vely_space
        scale = self.scale

        def div(state: NavierState):
            return sp_u.gradient(state.velx, (1, 0), scale) + sp_v.gradient(
                state.vely, (0, 1), scale
            )

        return div

    def _make_observables(self):
        """One fused jitted function returning (Nu, Nuvol, Re, |div|).

        Formulas match /root/reference/src/navier_stokes/functions.rs:146-233.
        """
        sp_t, sp_u, sp_v = self.temp_space, self.velx_space, self.vely_space
        sp_f = self.field_space
        scale = self.scale
        nu, ka = self.params["nu"], self.params["ka"]
        tb = self.tempbc_ortho
        w0, w1 = self._w0, self._w1
        div_fn = self._make_div()

        def avg_x(v):
            return jnp.sum(v * w0[:, None], axis=0)

        def avg(v):
            return jnp.sum(v * w0[:, None] * w1[None, :])

        def observables(state: NavierState):
            that = sp_t.to_ortho(state.temp) + tb
            # physical dT/dy, computed ONCE via the fused synthesis-of-
            # derivative chain (backward_ortho(gradient(.)) collapsed to one
            # GEMM per axis on sep spaces) and shared by the plate-flux Nu
            # and the volume Nuvol — the unfused form ran the gradient and
            # two separate backward_orthos (VERDICT r4 next #7)
            dtdy_p = sp_f.backward_gradient(that, (0, 1), None)
            # Nu: plate heat flux <-2/sy * dT/dy>_x averaged over both plates
            x_avg = avg_x(dtdy_p) * (-2.0 / scale[1])
            nu_plate = 0.5 * (x_avg[0] + x_avg[-1])
            # Nuvol: <2 sy (uy T / ka - dT/dy / sy)>_V
            temp_p = sp_f.backward_ortho(that)
            uy = sp_v.backward(state.vely)
            nu_vol = avg(
                (dtdy_p / (-scale[1]) + uy * temp_p / ka) * 2.0 * scale[1]
            )
            # Re: <sqrt(ux^2+uy^2) * 2 sy / nu>_V
            ux = sp_u.backward(state.velx)
            re = avg(jnp.sqrt(ux**2 + uy**2) * 2.0 * scale[1] / nu)
            # divergence norm
            dnorm = norm_l2(div_fn(state))
            return nu_plate, nu_vol, re, dnorm

        return observables

    # -- Integrate protocol --------------------------------------------------

    def update(self) -> None:
        with self._scope():
            self.state = self._step(self.state)
        self.time += self.dt

    def update_n(self, n: int):
        """Advance n steps on the device via scanned power-of-two chunks
        (utils/jit.run_scanned).  Dispatches stay asynchronous (no per-bucket
        host sync — through the relay a sync costs ~110 ms) and donate their
        input state buffers (see _compile_entry_points); on divergence the
        in-scan early exit freezes the state, ``exit()`` reports it at the
        next chunk boundary, and ``self.time`` deliberately counts the
        scheduled steps (the post-NaN run is over either way).

        With stability sentinels armed (:meth:`set_stability`) the chunk
        additionally returns a :class:`~rustpde_mpi_tpu.utils.governor.ChunkStatus`
        (also stored as ``self.last_chunk_status``): a per-step CFL above the
        hard ceiling early-exits the scan with ``pre_divergence`` while the
        state is still finite, the chunk is rolled back in memory (state and
        time untouched — the chunk-start snapshot is exactly the un-donated
        ``self.state``) and ``exit()`` latches True until a governor
        acknowledges via :meth:`clear_pre_divergence`."""
        from ..utils.jit import run_scanned

        if self._step_n_sent is not None:
            return self._update_n_sentinel(n)
        with self._scope():
            # the chunked dispatch donates its input buffers; hand it a copy
            # so a state reference the caller retained stays readable, while
            # every inter-bucket hand-off inside the chain is donated
            state = jax.tree.map(jnp.copy, self.state)
            self.state = run_scanned(
                lambda s, k: self._step_n(s, k)[0], state, n
            )
        self.time += n * self.dt
        return None

    def _update_n_sentinel(self, n: int):
        """Sentinel-armed chunk: scan with CFL/KE/|div| reductions riding the
        carry, one scalar fetch at the end (the only extra host sync)."""
        return self.update_n_pending(n).resolve()

    def update_n_pending(self, n: int):
        """Sentinel-armed chunk with a DEFERRED commit decision (the lag=1
        contract of the overlapped driver, utils/io_pipeline.py): dispatch
        the scanned chunk, PROVISIONALLY advance ``state``/``time`` to its
        end, and return a
        :class:`~rustpde_mpi_tpu.utils.io_pipeline.PendingChunkStatus` whose
        ``resolve()`` fetches the sentinel scalars and either confirms the
        advance or restores the chunk-start snapshot (+ latches ``exit()``)
        — exactly the synchronous :meth:`update_n` outcome, decided one
        host round-trip later.  The governed runner dispatches chunk i+1
        from the provisional state before resolving chunk i, so the device
        queue never drains while the governor reads the sentinels; the
        on-device CFL ceiling guards the speculative chunk (it steps a
        frozen, finite state when chunk i tripped)."""
        from ..utils.governor import ChunkStatus
        from ..utils.io_pipeline import PendingChunkStatus
        from ..utils.jit import run_scanned

        if self._step_n_sent is None:
            raise RuntimeError(
                "update_n_pending requires armed stability sentinels "
                "(set_stability)"
            )
        self._pre_div_latch = False
        rdt = config.real_dtype()
        with self._scope():
            state = jax.tree.map(jnp.copy, self.state)
            carry = (
                state,
                jnp.asarray(True),
                jnp.asarray(True),
                jnp.asarray(0, jnp.int32),
                jnp.asarray(0.0, rdt),  # cfl max
                jnp.asarray(0.0, rdt),  # ke growth max
                jnp.asarray(0.0, rdt),  # |div| max
                jnp.asarray(0.0, rdt),  # previous-step ke
            )
            carry = run_scanned(lambda c, k: self._step_n_sent(c, k), carry, n)
        st, fin, cok, done, cflm, gm, dvm, ke = carry
        snapshot = (self.state, self.time)
        self.state = st  # provisional: resolve() confirms or restores
        self.time += n * self.dt
        dt = self.dt

        def finish(fetched):
            fin_h, cok_h, done_h, cflm_h, gm_h, dvm_h, ke_h = fetched
            fin_b, cok_b = bool(fin_h), bool(cok_h)
            pre_div = fin_b and not cok_b
            if pre_div:
                # in-memory rollback: the dispatch stepped a donated COPY,
                # so the snapshot still holds the chunk-start state — put it
                # back and latch exit() until a governor acts
                self.state, self.time = snapshot
                self._pre_div_latch = True
            status = ChunkStatus(
                requested=int(n),
                steps_done=int(done_h),
                finite=fin_b,
                cfl_ok=cok_b,
                pre_divergence=pre_div,
                cfl_max=float(cflm_h),
                ke=float(ke_h),
                ke_growth_max=float(gm_h),
                div_max=float(dvm_h),
                dt=dt,
            )
            self.last_chunk_status = status
            return status

        return PendingChunkStatus((fin, cok, done, cflm, gm, dvm, ke), finish)

    def set_stability(self, cfg) -> None:
        """Arm/disarm (``None``) the on-device stability sentinels
        (:class:`~rustpde_mpi_tpu.config.StabilityConfig`): compiles the
        sentinel variant of the scanned chunk into :meth:`update_n`.  Under
        the GSPMD split-sep fallback the sentinel path is unavailable and
        stepping stays plain (a one-time warning is emitted)."""
        self._stability = cfg
        self._dt_cache.clear()  # cached artifacts lack/stale sentinel entries
        self._compile_entry_points()
        if cfg is not None and self._step_n_sent is None:
            import warnings

            warnings.warn(
                "stability sentinels are not available on the per-stage "
                "eager GSPMD fallback path; stepping stays plain",
                RuntimeWarning,
                stacklevel=2,
            )
        self.last_chunk_status = None
        self._pre_div_latch = False

    def clear_pre_divergence(self) -> None:
        """Acknowledge a ``pre_divergence`` catch (the governor changed dt /
        killed members and wants the chunk retried): unlatch ``exit()``."""
        self._pre_div_latch = False

    def get_time(self) -> float:
        return self.time

    def get_dt(self) -> float:
        return self.dt

    # attributes a dt change swaps out, cached per rung so a governor
    # cycling a bounded dt ladder refactorizes/re-jits each rung ONCE
    # (solver_pres is dt-independent; tempbc_ortho/_tempbc_dx/_tempbc_dy are
    # cached alongside because _build_bc_fields rebuilds them together)
    _DT_ARTIFACTS = (
        "solver_velx",
        "solver_vely",
        "solver_temp",
        "tempbc_ortho",
        "_tempbc_dx",
        "_tempbc_dy",
        "_tempbc_diff",
        "_solid",
        "_step",
        "_step_n",
        "_obs_fn",
        "_step_cc",
        "_obs_cc",
        "_step_consts",
        "_obs_consts",
        "_sent_cc",
        "_sent_consts",
        "_step_n_sent",
    )

    def _dt_artifacts(self) -> dict:
        return {k: getattr(self, k, None) for k in self._DT_ARTIFACTS}

    def set_dt(self, dt: float) -> None:
        """Change the time-step size of a live model (the governor's dt
        ladder and the divergence-retry backoff, utils/resilience.py +
        utils/governor.py).

        dt is baked deep into the pipeline — the implicit Helmholtz solvers
        factorize ``dt*nu`` / ``dt*ka``, the BC diffusion source scales with
        dt, and a solid mask's penalization factors use dt/eta — so a FIRST
        visit to a dt rebuilds solvers + lift-field derivatives and
        re-traces the jitted entry points.  Every artifact is then cached
        per dt value, so revisiting a rung (the governor climbing back up
        its ladder) swaps the cached objects back in — the retained jit
        closures keep their identity, so XLA's executable cache hits and the
        total re-jit count over a long governed run is bounded by the ladder
        size.  State and time are untouched either way: the flow continues
        from the same fields at the new step size."""
        dt = float(dt)
        if dt <= 0.0:
            raise ValueError(f"dt must be positive, got {dt}")
        if dt == self.dt:
            return
        self._dt_cache[self.dt] = self._dt_artifacts()
        self.dt = dt
        cached = self._dt_cache.get(dt)
        if cached is not None:
            for key, value in cached.items():
                setattr(self, key, value)
            self._obs_cache = None
            return
        nu, ka = self.params["nu"], self.params["ka"]
        sx2, sy2 = self.scale[0] ** 2, self.scale[1] ** 2
        self.solver_velx = HholtzAdi(self.velx_space, (dt * nu / sx2, dt * nu / sy2))
        self.solver_vely = self.solver_velx
        self.solver_temp = HholtzAdi(self.temp_space, (dt * ka / sx2, dt * ka / sy2))
        # solver_pres is dt-independent (pure Poisson)
        xs, ys = (b.points for b in self.field_space.bases)
        with self._scope():
            self._build_bc_fields(xs, ys)
        if self._solid is not None:
            # rebuilds the dt/eta factors AND recompiles the entry points;
            # the obstacle itself is unchanged, so the per-rung cache stays
            # valid (set_solid clears it — shield it across the call)
            cache, self._dt_cache = self._dt_cache, {}
            try:
                self.set_solid(
                    self._solid["mask"], self._solid["value"], self._solid["eta"]
                )
            finally:
                self._dt_cache = cache
        else:
            self._compile_entry_points()
        self._obs_cache = None

    def get_observables_async(self):
        """Dispatch the fused ``(Nu, Nuvol, Re, |div|)`` computation and
        return an :class:`~rustpde_mpi_tpu.utils.io_pipeline.ObservableFuture`
        WITHOUT waiting for it — the device keeps working while the host
        decides when (if ever) to fetch.  Cached per state, shared with the
        synchronous accessors and :meth:`exit_future`, so diagnostics + break
        checks cost ONE dispatch and ONE host transfer per state."""
        from ..utils.io_pipeline import ObservableFuture

        if self._obs_cache is None or self._obs_cache[0] is not self.state:
            with self._scope():
                fut = ObservableFuture(
                    self._obs_fn(self.state),
                    convert=lambda vals: tuple(float(v) for v in vals),
                )
            self._obs_cache = (self.state, fut)
        return self._obs_cache[1]

    def get_observables(self) -> tuple[float, float, float, float]:
        """(Nu, Nuvol, Re, |div|) — one fused device dispatch, cached per
        state so callback printing + exit checks don't recompute.  The four
        scalars arrive in ONE host transfer (the future's ``device_get``),
        not four sequential blocking conversions — through the TPU relay
        each round-trip costs ~110 ms."""
        return self.get_observables_async().result()

    def eval_nu(self) -> float:
        return self.get_observables()[0]

    def eval_nuvol(self) -> float:
        return self.get_observables()[1]

    def eval_re(self) -> float:
        return self.get_observables()[2]

    def div_norm(self) -> float:
        return self.get_observables()[3]

    def write(self, filename: str) -> None:
        """Write a flow snapshot in the reference HDF5 layout."""
        from ..utils import checkpoint

        checkpoint.write_snapshot(self, filename)

    def read(self, filename: str) -> None:
        """Restore from a snapshot (supports resolution change via spectral
        interpolation; sharded-checkpoint manifests restore topology-
        elastically, see utils/checkpoint.read_sharded_snapshot)."""
        from ..utils import checkpoint

        checkpoint.read_snapshot(self, filename)

    # -- sharded (shard-wise) snapshot surface -------------------------------
    # utils/checkpoint's distributed two-phase writer/reader drives these:
    # each process fetches only its addressable shards, so checkpoints work
    # on multi-controller meshes where np.asarray(state) cannot.

    def snapshot_state_items(self) -> list:
        """``(name, device_array)`` for every state leaf the sharded
        checkpoint must carry — the full restart set (``pseu`` included, so
        a restore is bit-equal to the writer's state, not merely
        restart-equivalent)."""
        return [
            (f"state/{name}", getattr(self.state, name))
            for name in self.state._fields
        ]

    def snapshot_root_items(self) -> list:
        """Replicated host-side data for the sharded manifest root (the
        HostSnapshot ``datasets`` tuple convention)."""
        items = [("time", np.asarray(float(self.time), dtype=np.float64), "raw")]
        for key, value in self.params.items():
            items.append((key, np.asarray(float(value), dtype=np.float64), "raw"))
        return items

    def apply_restored_state(self, updates: dict, attrs: dict, root: dict) -> None:
        """Install state leaves assembled by the sharded reader (already
        placed in this model's target layout) + the manifest's time."""
        self.state = self.state._replace(**updates)
        self.time = float(np.asarray(root["time"]))
        self._obs_cache = None
        self._pre_div_latch = False

    def read_unwrap(self, filename: str) -> None:
        from ..utils.checkpoint import CheckpointError

        try:
            self.read(filename)
        except (OSError, KeyError, CheckpointError) as exc:
            print(f"error while reading file {filename}: {exc}")

    def callback(self) -> None:
        from ..utils import navier_io

        navier_io.callback(self)

    def exit(self) -> bool:
        """NaN-divergence break criterion
        (/root/reference/src/navier_stokes/navier.rs:482-489), extended by
        the pre-divergence latch: a CFL-ceiling catch (sentinels armed)
        reads as a break until a governor clears it — so an *ungoverned*
        ``integrate`` over a sentinel-armed model stops cleanly at the
        rolled-back (finite) state instead of looping forever."""
        if self._pre_div_latch:
            return True
        return bool(np.isnan(self.div_norm()))

    def exit_future(self):
        """Non-blocking form of :meth:`exit` for the overlapped driver
        (utils/integrate.py ``overlap``): a latched pre-divergence catch
        resolves immediately (host-side fact); otherwise the break flag
        rides the cached observables dispatch and is fetched when the
        driver gets around to it — typically one chunk later, after the
        next chunk is already in flight."""
        from ..utils.io_pipeline import MappedFuture, immediate

        if self._pre_div_latch:
            return immediate(True)
        return MappedFuture(
            self.get_observables_async(), lambda vals: bool(np.isnan(vals[3]))
        )

    def reset_time(self) -> None:
        self.time = 0.0
