"""Running-average flow statistics with HDF5 persistence.

TPU rebuild of /root/reference/src/navier_stokes/statistics.rs: spectral-space
running averages of temperature and velocities plus the pointwise Nusselt
field, updated with the reference's ``(avg*n + new) / (n+1)`` weighting
(statistics.rs:84-108) and persisted in the reference's layout — groups
``{temp,ux,uy,nusselt}/{x,dx,y,dy,v,vhat}`` plus scalars
``tot_time/avg_time/num_save`` and the physics params (statistics.rs:119-167).

Two deliberate fixes over the reference:

* the reference's ``update`` only running-averages ``t_avg`` and *overwrites*
  ``ux_avg``/``uy_avg``/``nusselt`` with the instantaneous fields
  (statistics.rs:98-104) despite their names; here all four carry the running
  average,
* the pointwise Nusselt field includes the temperature BC lift (the reference
  feeds the homogeneous part only, navier_io.rs:110-115, which drops the
  conduction contribution), so its volume average is consistent with
  ``eval_nuvol``.
"""

from __future__ import annotations

import os

import numpy as np



class Statistics:
    """Attach via ``model.statistics = Statistics(model, save_stat, write_stat)``;
    the integrate callback then updates every ``save_stat`` and writes
    ``data/statistics.h5`` every ``write_stat`` time units
    (utils/navier_io.py)."""

    def __init__(self, model, save_stat: float, write_stat: float):
        self.save_stat = save_stat
        self.write_stat = write_stat
        self.space = model.field_space
        self.scale = model.scale
        self.params = dict(model.params)
        shape = self.space.shape_spectral
        dtype = self.space.spectral_dtype()
        zeros = np.zeros(shape, dtype=dtype)
        self.t_avg = zeros.copy()
        self.ux_avg = zeros.copy()
        self.uy_avg = zeros.copy()
        self.nusselt = zeros.copy()
        self.avg_time = 0.0
        self.tot_time = float(model.time)
        self.num_save = 0
        self._nusselt_fn = self._make_nusselt(model)

    def _make_nusselt(self, model):
        """Pointwise-Nusselt field: 2*sy*(uy*T/ka - dT/dy/sy) in the
        scratch-ortho space, dealiased (statistics.rs:246-270).  Runs eagerly:
        updates are save-interval-rare, and jitting would re-embed the large
        transform constants the model deliberately hoists (utils/jit.py)."""
        sp = self.space
        scale = self.scale
        ka = self.params["ka"]
        mask = model._dealias

        def nusselt_field(that, uxhat, uyhat):
            del uxhat  # reference signature; only uy and T enter the flux
            temp_p = sp.backward_ortho(that)
            uy_p = sp.backward_ortho(uyhat)
            dtdz = sp.backward_ortho(sp.gradient(that, (0, 1), None)) / (-scale[1])
            nu_v = (dtdz + uy_p * temp_p / ka) * 2.0 * scale[1]
            return sp.forward(nu_v) * mask

        return nusselt_field

    def update(self, model) -> None:
        """Fold the model's current state into the running averages
        (statistics.rs:84-108)."""
        time = float(model.time)
        if time < self.tot_time:
            # typed, countable failure instead of only a swallowed print: a
            # mismatched restart silently NOT updating the averages is the
            # kind of loss a production run must be able to alert on
            from .stats import report_stats_event

            print(f"Statistics time mismatch (navier < stat): {time} < {self.tot_time}")
            report_stats_event(
                model,
                {
                    "event": "stats_mismatch",
                    "navier_time": time,
                    "stat_time": float(self.tot_time),
                },
            )
            return
        with model._scope():
            that_h = model.temp_space.to_ortho(model.state.temp)
            uxhat = model.velx_space.to_ortho(model.state.velx)
            uyhat = model.vely_space.to_ortho(model.state.vely)
            nu_hat = self._nusselt_fn(that_h + model.tempbc_ortho, uxhat, uyhat)
        w = float(self.num_save)
        for attr, new in (
            ("t_avg", that_h),
            ("ux_avg", uxhat),
            ("uy_avg", uyhat),
            ("nusselt", nu_hat),
        ):
            avg = getattr(self, attr)
            setattr(self, attr, (avg * w + np.asarray(new)) / (w + 1.0))
        self.num_save += 1
        self.avg_time += time - self.tot_time
        self.tot_time = time

    # -- IO ------------------------------------------------------------------

    _MEMBERS = (("temp", "t_avg"), ("ux", "ux_avg"), ("uy", "uy_avg"), ("nusselt", "nusselt"))

    def write(self, filename: str) -> None:
        """statistics.rs:140-158 layout."""
        import h5py

        from ..utils.checkpoint import write_field
        from ..field import grid_deltas

        os.makedirs(os.path.dirname(filename) or ".", exist_ok=True)
        xs = [b.points * s for b, s in zip(self.space.bases, self.scale)]
        dxs = [
            grid_deltas(b.points, b.is_periodic) * s
            for b, s in zip(self.space.bases, self.scale)
        ]
        with h5py.File(filename, "a") as h5:
            for varname, attr in self._MEMBERS:
                vhat = jax_asarray(getattr(self, attr), self.space)
                write_field(h5, varname, self.space, vhat, xs, dxs)
            for key, value in (
                ("tot_time", self.tot_time),
                ("avg_time", self.avg_time),
                ("num_save", float(self.num_save)),
            ):
                if key in h5:
                    del h5[key]
                h5.create_dataset(key, data=value)
            for key, value in self.params.items():
                if key in h5:
                    del h5[key]
                h5.create_dataset(key, data=float(value))

    def read(self, filename: str) -> None:
        """statistics.rs:119-134: restore averages + counters."""
        import h5py

        from ..utils.checkpoint import read_field_vhat

        with h5py.File(filename, "r") as h5:
            for varname, attr in self._MEMBERS:
                setattr(
                    self,
                    attr,
                    read_field_vhat(h5, varname, self.space).astype(
                        self.space.spectral_dtype()
                    ),
                )
            self.tot_time = float(np.asarray(h5["tot_time"]))
            self.avg_time = float(np.asarray(h5["avg_time"]))
            self.num_save = int(np.asarray(h5["num_save"]))
        print(f" <== {filename}")


def jax_asarray(arr, space):
    """Device array in the space's spectral dtype (host numpy accepted)."""
    import jax.numpy as jnp

    return jnp.asarray(arr, dtype=space.spectral_dtype())
